"""Member geometry preprocessing (host side, trace time).

Parses platform/tower member descriptions from the design dict, replicates
members over heading patterns, discretizes each into strip-theory nodes, and
packs every member's nodes into fixed-shape arrays (a ``HydroNodes`` pytree)
so the whole strip-theory pipeline runs as one XLA graph with a single padded
node axis — replacing the reference's per-member/per-node Python loops
(reference raft/raft_member.py:13-241, raft/raft_fowt.py:69-91).

Everything here is plain NumPy float64 and runs once per design; only the
packed arrays go to device.
"""

import dataclasses
from dataclasses import dataclass, field

import jax
import numpy as np

from raft_tpu.io.schema import get_from_dict


def _rotation_z(deg):
    c, s = np.cos(np.deg2rad(deg)), np.sin(np.deg2rad(deg))
    return np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]])


@dataclass
class Member:
    """One rigid cylindrical/rectangular member, preprocessed.

    Mirrors the reference Member's parsed state (reference
    raft/raft_member.py:13-200) plus its orientation products
    (raft/raft_member.py:204-241), computed eagerly.
    """

    name: str
    type: int
    shape: str              # 'circular' | 'rectangular'
    rA: np.ndarray          # end A position after heading rotation [3]
    rB: np.ndarray
    l: float                # member length
    stations: np.ndarray    # [n] normalized to 0..l
    d: np.ndarray           # [n] diameters (circular) — or None
    sl: np.ndarray          # [n, 2] side lengths (rectangular) — or None
    t: np.ndarray           # [n] shell thickness
    l_fill: np.ndarray      # scalar or [n-1] ballast fill lengths
    rho_fill: np.ndarray    # scalar or [n-1] ballast densities
    rho_shell: float
    gamma: float
    potMod: bool
    heading: float
    headings: np.ndarray    # the full headings entry (scalar or list)
    cap_stations: np.ndarray
    cap_t: np.ndarray
    cap_d_in: np.ndarray
    # hydro coefficients per station
    Cd_q: np.ndarray
    Cd_p1: np.ndarray
    Cd_p2: np.ndarray
    Cd_End: np.ndarray
    Ca_q: np.ndarray
    Ca_p1: np.ndarray
    Ca_p2: np.ndarray
    Ca_End: np.ndarray
    # orientation
    q: np.ndarray = field(default=None)
    p1: np.ndarray = field(default=None)
    p2: np.ndarray = field(default=None)
    R: np.ndarray = field(default=None)
    # strip discretization
    ns: int = 0
    ls: np.ndarray = field(default=None)    # [ns] node stations along axis
    dls: np.ndarray = field(default=None)   # [ns] strip lengths (0 = flat plate)
    ds: np.ndarray = field(default=None)    # [ns] (circ) or [ns,2] (rect) sizes
    drs: np.ndarray = field(default=None)   # [ns] (circ) or [ns,2] radius change
    r: np.ndarray = field(default=None)     # [ns, 3] node positions

    @property
    def circular(self):
        return self.shape == "circular"

    def dorsl(self):
        """Diameter (circ) or side-length-pair (rect) per station."""
        return self.d if self.circular else self.sl


def parse_member(mi, heading=0.0):
    """Build one Member from its design-dict entry with a given heading
    rotation (reference raft/raft_member.py:13-200)."""
    rA = np.array(mi["rA"], dtype=float)
    rB = np.array(mi["rB"], dtype=float)
    if heading != 0.0:
        rot = _rotation_z(heading)
        rA = rot @ rA
        rB = rot @ rB

    rAB = rB - rA
    l = float(np.linalg.norm(rAB))

    A = np.array(mi["stations"], dtype=float)
    n = len(A)
    if n < 2:
        raise ValueError("At least two stations entries must be provided")
    stations = (A - A[0]) / (A[-1] - A[0]) * l

    shape_str = str(mi["shape"])
    if shape_str[0].lower() == "c":
        shape = "circular"
        d = get_from_dict(mi, "d", shape=n)
        sl = None
        gamma = 0.0
    elif shape_str[0].lower() == "r":
        shape = "rectangular"
        d = None
        sl = get_from_dict(mi, "d", shape=[n, 2])
        gamma = get_from_dict(mi, "gamma", default=0.0)
    else:
        raise ValueError("Member shape must be circular or rectangular")

    t = get_from_dict(mi, "t", shape=n)
    l_fill = get_from_dict(mi, "l_fill", shape=-1, default=0.0)
    rho_fill = get_from_dict(mi, "rho_fill", shape=-1, default=0.0)
    if isinstance(l_fill, np.ndarray) and (
        len(l_fill) != n - 1 or len(np.atleast_1d(rho_fill)) != n - 1
    ):
        raise ValueError(
            f"Member '{mi.get('name','?')}': number of stations ({n}) must be one "
            f"more than the number of ballast sections"
        )
    rho_shell = get_from_dict(mi, "rho_shell", default=8500.0)

    cap_stations = get_from_dict(mi, "cap_stations", shape=-1, default=[])
    if isinstance(cap_stations, list) or np.size(cap_stations) == 0:
        cap_t = np.array([])
        cap_d_in = np.array([])
        cap_stations = np.array([])
    else:
        cap_stations = np.atleast_1d(cap_stations)
        cap_t = np.atleast_1d(get_from_dict(mi, "cap_t", shape=cap_stations.shape[0]))
        cap_d_in = np.atleast_1d(
            get_from_dict(mi, "cap_d_in", shape=cap_stations.shape[0])
        )
        cap_stations = (cap_stations - A[0]) / (A[-1] - A[0]) * l

    # drag/added-mass coefficients (reference defaults, raft_member.py:116-132)
    Cd_q = get_from_dict(mi, "Cd_q", shape=n, default=0.0)
    if "Cd" in mi and not np.isscalar(mi["Cd"]) and len(mi["Cd"]) == 2:
        Cd_p1 = np.tile(float(mi["Cd"][0]), n)
        Cd_p2 = np.tile(float(mi["Cd"][1]), n)
    else:
        Cd_p1 = get_from_dict(mi, "Cd", shape=n, default=0.6)
        Cd_p2 = get_from_dict(mi, "Cd", shape=n, default=0.6)
    Cd_End = get_from_dict(mi, "CdEnd", shape=n, default=0.6)
    Ca_q = get_from_dict(mi, "Ca_q", shape=n, default=0.0)
    if "Ca" in mi and not np.isscalar(mi["Ca"]) and len(mi["Ca"]) == 2:
        Ca_p1 = np.tile(float(mi["Ca"][0]), n)
        Ca_p2 = np.tile(float(mi["Ca"][1]), n)
    else:
        Ca_p1 = get_from_dict(mi, "Ca", shape=n, default=0.97)
        Ca_p2 = get_from_dict(mi, "Ca", shape=n, default=0.97)
    Ca_End = get_from_dict(mi, "CaEnd", shape=n, default=0.6)

    mem = Member(
        name=str(mi.get("name", "")),
        type=int(mi["type"]),
        shape=shape,
        rA=rA,
        rB=rB,
        l=l,
        stations=stations,
        d=d,
        sl=sl,
        t=t,
        l_fill=l_fill,
        rho_fill=rho_fill,
        rho_shell=float(rho_shell),
        gamma=float(gamma),
        potMod=bool(get_from_dict(mi, "potMod", dtype=bool, default=False)),
        heading=float(heading),
        headings=get_from_dict(mi, "headings", shape=-1, default=0.0),
        cap_stations=cap_stations,
        cap_t=cap_t,
        cap_d_in=cap_d_in,
        Cd_q=Cd_q,
        Cd_p1=Cd_p1,
        Cd_p2=Cd_p2,
        Cd_End=Cd_End,
        Ca_q=Ca_q,
        Ca_p1=Ca_p1,
        Ca_p2=Ca_p2,
        Ca_End=Ca_End,
    )
    _calc_orientation(mem)
    _discretize(mem, dlsMax=float(mi["dlsMax"]))
    return mem


def _calc_orientation(mem):
    """Direction vectors q, p1, p2 and rotation matrix R from end positions and
    twist gamma (reference raft/raft_member.py:204-241, Z1Y2Z3 Euler)."""
    rAB = mem.rB - mem.rA
    q = rAB / np.linalg.norm(rAB)
    beta = np.arctan2(q[1], q[0])
    phi = np.arctan2(np.sqrt(q[0] ** 2 + q[1] ** 2), q[2])
    s1, c1 = np.sin(beta), np.cos(beta)
    s2, c2 = np.sin(phi), np.cos(phi)
    s3, c3 = np.sin(np.deg2rad(mem.gamma)), np.cos(np.deg2rad(mem.gamma))
    R = np.array(
        [
            [c1 * c2 * c3 - s1 * s3, -c3 * s1 - c1 * c2 * s3, c1 * s2],
            [c1 * s3 + c2 * c3 * s1, c1 * c3 - c2 * s1 * s3, s1 * s2],
            [-c3 * s2, s2 * s3, c2],
        ]
    )
    p1 = R @ np.array([1.0, 0.0, 0.0])
    p2 = np.cross(q, p1)
    mem.R, mem.q, mem.p1, mem.p2 = R, q, p1, p2


def _discretize(mem, dlsMax):
    """Strip discretization with a node at each strip midpoint; flat surfaces
    (taper breaks and member ends) get zero-length strips.

    This reproduces the reference algorithm exactly — including its quirk of
    appending the end-B plate strip once per station segment rather than once
    per member (the block at reference raft/raft_member.py:165-170 is inside
    the segment loop), because the duplicated end strips contribute axial
    added mass / dynamic pressure terms for submerged member ends and the
    reference's validated behavior depends on them.
    """
    dorsl = list(mem.d) if mem.circular else [np.array(p) for p in mem.sl]
    n = len(mem.stations)

    ls = [0.0]
    dls = [0.0]
    ds = [0.5 * np.asarray(dorsl[0])]
    drs = [0.5 * np.asarray(dorsl[0])]

    for i in range(1, n):
        lstrip = mem.stations[i] - mem.stations[i - 1]
        if lstrip > 0.0:
            ns_seg = int(np.ceil(lstrip / dlsMax))
            dlstrip = lstrip / ns_seg
            m = 0.5 * (np.asarray(dorsl[i]) - np.asarray(dorsl[i - 1])) / lstrip
            ls += [mem.stations[i - 1] + dlstrip * (0.5 + j) for j in range(ns_seg)]
            dls += [dlstrip] * ns_seg
            ds += [
                np.asarray(dorsl[i - 1]) + dlstrip * 2 * m * (0.5 + j)
                for j in range(ns_seg)
            ]
            drs += [dlstrip * m] * ns_seg
        elif lstrip == 0.0:
            ls += [mem.stations[i - 1]]
            dls += [0.0]
            ds += [0.5 * (np.asarray(dorsl[i - 1]) + np.asarray(dorsl[i]))]
            drs += [0.5 * (np.asarray(dorsl[i]) - np.asarray(dorsl[i - 1]))]

        # end-B plate strip — appended per segment (see docstring)
        ls += [mem.stations[-1]]
        dls += [0.0]
        ds += [0.5 * np.asarray(dorsl[-1])]
        drs += [-0.5 * np.asarray(dorsl[-1])]

    mem.ns = len(ls)
    mem.ls = np.array(ls, dtype=float)
    mem.dls = np.array(dls, dtype=float)
    mem.ds = np.array(ds, dtype=float)
    mem.drs = np.array(drs, dtype=float)
    rAB = mem.rB - mem.rA
    mem.r = mem.rA[None, :] + (mem.ls[:, None] / mem.l) * rAB[None, :]


def process_members(design):
    """Expand the platform member list (with heading replication and
    potModMaster override) plus the tower into Member objects
    (reference raft/raft_fowt.py:54-91)."""
    potModMaster = get_from_dict(design["platform"], "potModMaster", dtype=int, default=0)
    dlsMax = get_from_dict(design["platform"], "dlsMax", default=5.0)

    members = []
    for mi in design["platform"]["members"]:
        mi = dict(mi)  # do not mutate the user's design dict
        if potModMaster == 1:
            mi["potMod"] = False
        elif potModMaster == 2:
            mi["potMod"] = True
        mi["dlsMax"] = dlsMax

        headings = get_from_dict(mi, "heading", shape=-1, default=0.0)
        mi["headings"] = headings
        if np.isscalar(headings):
            members.append(parse_member(mi, heading=float(headings)))
        else:
            for h in headings:
                members.append(parse_member(mi, heading=float(h)))

    tower = dict(design["turbine"]["tower"])
    tower["dlsMax"] = get_from_dict(
        design["turbine"]["tower"], "dlsMax", default=5.0
    )
    tower["headings"] = 0.0
    members.append(parse_member(tower, heading=0.0))
    return members


@dataclass
class HydroNodes:
    """All members' strip nodes packed into flat [N] / [N,3] / [N,3,3] arrays
    with precomputed static volumes/areas and interpolated coefficients, ready
    for einsum-style strip-theory integration on device.

    Masks encode the reference's per-node conditionals:
      submerged  — node center below the waterline (raft_fowt.py:513, :626)
      strip_mask — submerged AND not potential-flow modeled (inertia/added
                   mass terms, raft_fowt.py:520)
    Drag terms use ``submerged`` alone, matching the reference
    (raft_fowt.py:626 has no potMod gate).
    """

    r: np.ndarray        # [N, 3] node positions
    q: np.ndarray        # [N, 3] member axial unit vector at each node
    qMat: np.ndarray     # [N, 3, 3]
    p1Mat: np.ndarray    # [N, 3, 3]
    p2Mat: np.ndarray    # [N, 3, 3]
    v_side: np.ndarray   # [N] strip volume (waterline-clipped)
    v_end: np.ndarray    # [N] axial/end reference volume
    a_end: np.ndarray    # [N] signed end area (dynamic pressure)
    a_q: np.ndarray      # [N] axial drag area
    a_p1: np.ndarray     # [N] transverse drag area, p1 direction
    a_p2: np.ndarray     # [N] transverse drag area, p2 direction
    a_end_abs: np.ndarray  # [N] |end area| for end drag
    Ca_p1: np.ndarray    # [N] interpolated coefficients
    Ca_p2: np.ndarray
    Ca_End: np.ndarray
    Cd_q: np.ndarray
    Cd_p1: np.ndarray
    Cd_p2: np.ndarray
    Cd_End: np.ndarray
    submerged: np.ndarray   # [N] bool
    strip_mask: np.ndarray  # [N] bool

    def astype(self, dtype):
        """Copy with all float arrays cast to ``dtype`` (masks stay bool) —
        used to stage the node bundle into a f32 TPU graph or f64 CPU graph."""
        out = {}
        for f in dataclasses.fields(self):
            a = getattr(self, f.name)
            out[f.name] = a if a.dtype == bool else np.asarray(a, dtype)
        return HydroNodes(**out)


jax.tree_util.register_dataclass(
    HydroNodes,
    data_fields=[f.name for f in dataclasses.fields(HydroNodes)],
    meta_fields=[],
)


def pack_nodes(members):
    """Flatten all members' nodes into a HydroNodes bundle.

    Per-node static quantities follow reference raft/raft_fowt.py:466-695:
      side volume  v_i = pi/4 d^2 dl (circ) or sl0 sl1 dl (rect), scaled by the
                   submerged fraction when the strip pokes out of the water
                   (raft_fowt.py:532-537)
      end volume   v_i = pi/12 |(d+dr)^3 - (d-dr)^3|        (raft_fowt.py:562-566)
      end area     a_i = pi d dr (circ), signed              (raft_fowt.py:563)
      drag areas   a_q = pi d dl, a_p = d dl (circ)          (raft_fowt.py:638-640)
                   (rect: a_q = 2(sl0+sl0) dl — reference quirk kept, sl1 is
                   never used in the axial area — a_p1 = sl0 dl, a_p2 = sl1 dl)
    """
    rs, qs, qM, p1M, p2M = [], [], [], [], []
    v_side, v_end, a_end, a_q, a_p1, a_p2, a_end_abs = [], [], [], [], [], [], []
    Ca_p1l, Ca_p2l, Ca_Endl = [], [], []
    Cd_ql, Cd_p1l, Cd_p2l, Cd_Endl = [], [], [], []
    submerged, strip_mask = [], []

    for mem in members:
        circ = mem.circular
        for il in range(mem.ns):
            rs.append(mem.r[il])
            qs.append(mem.q)
            qM.append(np.outer(mem.q, mem.q))
            p1M.append(np.outer(mem.p1, mem.p1))
            p2M.append(np.outer(mem.p2, mem.p2))

            dl = mem.dls[il]
            if circ:
                d = mem.ds[il]
                dr = mem.drs[il]
                v = 0.25 * np.pi * d**2 * dl
                ve = np.pi / 12.0 * abs((d + dr) ** 3 - (d - dr) ** 3)
                ae = np.pi * d * dr
                aq = np.pi * d * dl
                ap1 = d * dl
                ap2 = d * dl
                ae_abs = abs(np.pi * d * dr)
            else:
                d0, d1 = mem.ds[il]
                dr0, dr1 = mem.drs[il]
                v = d0 * d1 * dl
                dmean = np.mean(mem.ds[il] + mem.drs[il])
                dmean2 = np.mean(mem.ds[il] - mem.drs[il])
                ve = np.pi / 12.0 * (dmean**3 - dmean2**3)
                ae = (d0 + dr0) * (d1 + dr1) - (d0 - dr0) * (d1 - dr1)
                aq = 2 * (d0 + d0) * dl  # reference quirk: uses ds[il,0] twice
                ap1 = d0 * dl
                ap2 = d1 * dl
                ae_abs = abs(ae)

            z = mem.r[il, 2]
            # waterline clipping of the side volume (raft_fowt.py:536-537);
            # only submerged nodes are ever used, so clip only those (an
            # above-water node would get a meaningless negative factor)
            if z < 0 and z + 0.5 * dl > 0 and dl > 0:
                v = v * (0.5 * dl - z) / dl
            v_side.append(v)
            v_end.append(ve)
            a_end.append(ae)
            a_q.append(aq)
            a_p1.append(ap1)
            a_p2.append(ap2)
            a_end_abs.append(ae_abs)

            # station-interpolated coefficients (raft_fowt.py:523-526, :629-632)
            st = mem.stations
            Ca_p1l.append(np.interp(mem.ls[il], st, mem.Ca_p1))
            Ca_p2l.append(np.interp(mem.ls[il], st, mem.Ca_p2))
            Ca_Endl.append(np.interp(mem.ls[il], st, mem.Ca_End))
            Cd_ql.append(np.interp(mem.ls[il], st, mem.Cd_q))
            Cd_p1l.append(np.interp(mem.ls[il], st, mem.Cd_p1))
            Cd_p2l.append(np.interp(mem.ls[il], st, mem.Cd_p2))
            Cd_Endl.append(np.interp(mem.ls[il], st, mem.Cd_End))

            sub = z < 0
            submerged.append(sub)
            strip_mask.append(sub and not mem.potMod)

    return HydroNodes(
        r=np.array(rs),
        q=np.array(qs),
        qMat=np.array(qM),
        p1Mat=np.array(p1M),
        p2Mat=np.array(p2M),
        v_side=np.array(v_side),
        v_end=np.array(v_end),
        a_end=np.array(a_end),
        a_q=np.array(a_q),
        a_p1=np.array(a_p1),
        a_p2=np.array(a_p2),
        a_end_abs=np.array(a_end_abs),
        Ca_p1=np.array(Ca_p1l),
        Ca_p2=np.array(Ca_p2l),
        Ca_End=np.array(Ca_Endl),
        Cd_q=np.array(Cd_ql),
        Cd_p1=np.array(Cd_p1l),
        Cd_p2=np.array(Cd_p2l),
        Cd_End=np.array(Cd_Endl),
        submerged=np.array(submerged),
        strip_mask=np.array(strip_mask),
    )
