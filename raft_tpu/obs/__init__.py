"""Observability layer for the serve stack (docs/observability.md).

Three pieces, each importable on its own:

* :mod:`raft_tpu.obs.metrics` — a process-local metrics registry
  (Counter / Gauge / Histogram with fixed log-spaced latency buckets)
  with streaming quantiles and a Prometheus text exposition; the
  engine's / router's legacy ``stats`` dicts are compatibility views
  over it (:class:`~raft_tpu.obs.metrics.StatsView`).
* :mod:`raft_tpu.obs.tracing` — cross-process request tracing: a
  :class:`~raft_tpu.obs.tracing.TraceContext` minted at ingress rides
  the wire schema, and per-stage spans land in a bounded
  :class:`~raft_tpu.obs.tracing.SpanRing` served by ``GET /tracez``.
* :mod:`raft_tpu.obs.profiler` — on-demand ``jax.profiler`` capture
  armed by ``POST /profilez`` (or ``RAFT_TPU_PROFILE_DIR`` for the
  non-serve sweep drivers), wrapping the next dispatch window and
  recording device memory stats + the waterfall flops ledger alongside.
"""

from raft_tpu.obs.metrics import (LATENCY_BUCKETS_S, Counter, Gauge,
                                  Histogram, MetricsRegistry, StatsView)
from raft_tpu.obs.tracing import (SpanRing, TraceContext, span,
                                  spans_enabled)
from raft_tpu.obs.profiler import ProfilerHook, profile_dir_from_env

__all__ = [
    "LATENCY_BUCKETS_S", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "StatsView", "SpanRing", "TraceContext", "span",
    "spans_enabled", "ProfilerHook", "profile_dir_from_env",
]
