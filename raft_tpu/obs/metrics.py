"""Metrics registry: Counter / Gauge / Histogram + Prometheus text.

The serve tier's telemetry was ad-hoc dict bumps surfaced as
point-in-time ``snapshot()`` JSON; every latency percentile in the repo
was computed after the fact by bench/loadgen.  This module gives the
service its own metrics:

* **Counter / Gauge** — monotonic / settable scalars;
* **Histogram** — fixed log-spaced buckets (100 µs → 100 s, four per
  decade) with streaming p50/p95/p99 computed from the bucket counts
  (linear interpolation within the landing bucket, the
  ``histogram_quantile`` convention), so a long-running server reports
  quantiles without retaining per-request samples;
* **MetricsRegistry** — the per-process (per-engine / per-router)
  name → metric table, rendered as Prometheus text exposition by
  ``GET /metricz`` (serve/transport.py) and as JSON inside ``/statz``;
* **StatsView** — a dict-compatible view that migrates a legacy
  ``self.stats`` dict onto the registry: integer-valued keys become
  registry counters named ``raft_tpu_<prefix>_<key>_total`` while
  list/other values stay local, so every existing
  ``stats["requests"] += 1`` call site and every legacy ``snapshot()``
  key keeps working unchanged.

Lock discipline: every mutable class below declares its ``_GUARDED_BY``
contract and graft-lint's lock rule (raft_tpu/analysis/rules/locks.py)
enforces it — recording is a lock-held bucket bump, reads are
GIL-atomic snapshots.  The metrics-hygiene rule
(raft_tpu/analysis/rules/metrics.py) cross-checks registered literal
metric names against docs/serving.md's metrics table.
"""

import bisect
import re
import threading

__all__ = ["LATENCY_BUCKETS_S", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "StatsView", "quantile_from_counts"]

#: fixed log-spaced latency bucket upper bounds (seconds): 100 µs to
#: 100 s, four buckets per decade — wide enough for a wire round-trip
#: and a cold 500 s compile to land in distinct, stable buckets
LATENCY_BUCKETS_S = (
    0.0001, 0.000178, 0.000316, 0.000562,
    0.001, 0.00178, 0.00316, 0.00562,
    0.01, 0.0178, 0.0316, 0.0562,
    0.1, 0.178, 0.316, 0.562,
    1.0, 1.78, 3.16, 5.62,
    10.0, 17.8, 31.6, 56.2, 100.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _check_name(name):
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def quantile_from_counts(counts, q, bounds=LATENCY_BUCKETS_S):
    """Streaming quantile from raw bucket counts (the ``to_doc``
    ``buckets`` list; ``counts[-1]`` is the +Inf bucket).  Merging
    histograms — e.g. one per replica — is a bucket-wise sum followed
    by this.  None when empty."""
    n = sum(counts)
    if n == 0:
        return None
    rank = max(float(q), 0.0) * n
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            frac = (rank - cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += c
    return bounds[-1]


class Counter:
    """Monotonic scalar.  ``inc`` is lock-held; ``value`` reads are
    GIL-atomic (int rebinds)."""

    _GUARDED_BY = {"value": "_lock"}

    kind = "counter"

    def __init__(self, name, help=""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n=1):
        with self._lock:
            self.value += n

    def set(self, v):
        """Compatibility setter for :class:`StatsView` (legacy call
        sites assign as well as bump)."""
        with self._lock:
            self.value = v

    def get(self):
        return self.value

    def render(self):
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} counter",
                f"{self.name} {self.value}"]

    def to_doc(self):
        return self.value


class Gauge:
    """Settable scalar (last write wins)."""

    _GUARDED_BY = {"value": "_lock"}

    kind = "gauge"

    def __init__(self, name, help=""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v):
        with self._lock:
            self.value = float(v)

    def get(self):
        return self.value

    def render(self):
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} gauge",
                f"{self.name} {self.value:g}"]

    def to_doc(self):
        return self.value


class Histogram:
    """Fixed-bucket histogram with streaming quantiles.

    ``counts[i]`` holds observations in ``(bounds[i-1], bounds[i]]``;
    the final slot is the +Inf bucket.  Quantiles interpolate linearly
    within the landing bucket (clamped to the top bound for the +Inf
    bucket), which is exactly what Prometheus' ``histogram_quantile``
    would compute from the exposition this renders."""

    _GUARDED_BY = {"counts": "_lock", "total": "_lock", "n": "_lock"}

    kind = "histogram"

    def __init__(self, name, help="", buckets=LATENCY_BUCKETS_S):
        self.name = _check_name(name)
        self.help = help
        self.bounds = tuple(float(b) for b in buckets)
        if list(self.bounds) != sorted(self.bounds):
            raise ValueError("histogram buckets must be ascending")
        self._lock = threading.Lock()
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.total += v
            self.n += 1

    def _snapshot(self):
        with self._lock:
            return list(self.counts), self.total, self.n

    def quantile(self, q):
        """Streaming quantile from the bucket counts; None when empty."""
        counts, _total, _n = self._snapshot()
        return quantile_from_counts(counts, q, bounds=self.bounds)

    def render(self):
        counts, total, n = self._snapshot()
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        cum = 0
        for b, c in zip(self.bounds, counts):
            cum += c
            lines.append(f'{self.name}_bucket{{le="{b:g}"}} {cum}')
        cum += counts[-1]
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{self.name}_sum {total:g}")
        lines.append(f"{self.name}_count {n}")
        return lines

    def to_doc(self):
        counts, total, n = self._snapshot()
        doc = {"count": n, "sum": round(total, 6)}
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            val = self.quantile(q)
            doc[key] = round(val, 6) if val is not None else None
        doc["buckets"] = counts
        return doc


class MetricsRegistry:
    """Per-process name → metric table (get-or-create semantics)."""

    _GUARDED_BY = {"_metrics": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get_or_create(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=LATENCY_BUCKETS_S):
        return self._get_or_create(Histogram, name, help,
                                   buckets=buckets)

    def stats_view(self, prefix, init):
        """Legacy-stats compatibility view (see :class:`StatsView`)."""
        return StatsView(self, prefix, init)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def render_prometheus(self):
        """The full registry as Prometheus text exposition format."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def to_doc(self):
        """JSON registry section for ``/statz``."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        return {m.name: {"kind": m.kind, "value": m.to_doc()}
                for m in metrics}


class StatsView:
    """dict-compatible stats whose integer counters live on a registry.

    Built from the class's legacy init dict: integer-valued keys become
    registry counters (``raft_tpu_<prefix>_<key>_total``); everything
    else (latency lists, floats, None placeholders) stays in a local
    dict.  All the legacy call-site idioms keep working —
    ``stats["requests"] += 1``, ``stats["latency_s"].append(x)``,
    ``dict(stats)``, ``stats.get(k)`` — while the counters become
    visible to ``/metricz`` for free.  Mutation of the view itself
    follows whatever lock guards the owning class's ``stats`` attribute
    (the counters add their own per-metric locks underneath)."""

    def __init__(self, registry, prefix, init):
        self._registry = registry
        self._prefix = prefix
        self._counters = {}
        self._local = {}
        self._order = []
        for key, val in dict(init).items():
            self._order.append(key)
            if isinstance(val, bool) or not isinstance(val, int):
                self._local[key] = val
            else:
                c = registry.counter(self._metric_name(key))
                if val:
                    c.set(val)
                self._counters[key] = c

    def _metric_name(self, key):
        return f"raft_tpu_{self._prefix}_{key}_total"

    def __getitem__(self, key):
        if key in self._counters:
            return self._counters[key].value
        return self._local[key]

    def __setitem__(self, key, val):
        if key in self._counters:
            self._counters[key].set(val)
            return
        if key not in self._local and not isinstance(val, bool) \
                and isinstance(val, int):
            c = self._registry.counter(self._metric_name(key))
            c.set(val)
            self._counters[key] = c
            self._order.append(key)
            return
        if key not in self._local:
            self._order.append(key)
        self._local[key] = val

    def __contains__(self, key):
        return key in self._counters or key in self._local

    def __iter__(self):
        return iter(self._order)

    def __len__(self):
        return len(self._order)

    def keys(self):
        return list(self._order)

    def items(self):
        return [(k, self[k]) for k in self._order]

    def values(self):
        return [self[k] for k in self._order]

    def get(self, key, default=None):
        return self[key] if key in self else default
