"""Cross-process request tracing: trace context + bounded span ring.

A :class:`TraceContext` (16-hex ``trace_id`` + 8-hex ``span_id``) is
minted once at ingress — ``Engine.submit`` or ``Router.submit`` — and
then RIDES the request everywhere: the wire schema carries it to
replica subprocesses (``{"trace": {...}}`` in the request document,
``trace_id`` in the terminal result line), retries/failover re-send the
SAME trace_id on the next replica, and a preempted sweep's resume keeps
the context in its parked state.  Each stage records a span (admission,
prep, queue-wait, dispatch, per-K-block waterfall, wire) into the
owning process's :class:`SpanRing` — a bounded buffer with a
dropped-span counter, exposed by ``GET /tracez?limit=N`` and stitched
across processes by ``Router.gather_trace`` into one chrome-trace
timeline (raft_tpu/trace.py renders it).

Span document shape (plain JSON types, wire-safe)::

    {"trace_id": "…16 hex…", "span_id": "…8 hex…",
     "parent_span_id": "…8 hex…" | None,
     "name": "dispatch", "proc": "engine",
     "t0": <unix seconds>, "dur_s": <float>, "meta": {...}}

Spans use wall-clock ``time.time()`` (same-host processes share it) so
router- and replica-side spans line up on one timeline without a clock
handshake; durations come from ``perf_counter`` pairs.

``RAFT_TPU_OBS_SPANS=0`` disables span recording entirely (the
instrumentation-overhead A/B knob in bench.py; metrics stay on).
"""

import os
import threading
import time
import uuid
from contextlib import contextmanager
from dataclasses import dataclass

__all__ = ["TraceContext", "SpanRing", "span", "spans_enabled",
           "DEFAULT_RING_SPANS"]

#: span-ring capacity: at ~6 spans per served request this holds the
#: last ~1300 requests — enough to stitch any request the load harness
#: can still name, bounded enough to never matter for memory
DEFAULT_RING_SPANS = 8192


def spans_enabled():
    """Span recording switch: ``RAFT_TPU_OBS_SPANS=0|off|false`` turns
    recording into a no-op (metrics and trace-context propagation stay
    on — only the ring stops filling)."""
    raw = os.environ.get("RAFT_TPU_OBS_SPANS", "").strip().lower()
    return raw not in ("0", "off", "false")


def _new_trace_id():
    return uuid.uuid4().hex[:16]


def _new_span_id():
    return uuid.uuid4().hex[:8]


@dataclass(frozen=True)
class TraceContext:
    """One request's identity on the trace timeline: the trace_id names
    the request end-to-end; span_id names the current span so children
    can point at their parent."""

    trace_id: str
    span_id: str

    @classmethod
    def new(cls):
        return cls(trace_id=_new_trace_id(), span_id=_new_span_id())

    def child(self):
        """Same trace, fresh span id (a new stage under this one)."""
        return TraceContext(trace_id=self.trace_id,
                            span_id=_new_span_id())

    def to_doc(self):
        """Wire form (request documents carry this verbatim)."""
        return {"trace_id": self.trace_id,
                "parent_span_id": self.span_id}

    @classmethod
    def from_doc(cls, doc):
        """Rebuild from a wire ``trace`` section; None when absent or
        malformed (a bad trace section must never fail a request)."""
        if not isinstance(doc, dict):
            return None
        tid = doc.get("trace_id")
        if not isinstance(tid, str) or not tid:
            return None
        sid = doc.get("parent_span_id")
        if not isinstance(sid, str) or not sid:
            sid = _new_span_id()
        return cls(trace_id=tid, span_id=sid)


class SpanRing:
    """Bounded per-process span buffer with a dropped-span counter."""

    _GUARDED_BY = {"_spans": "_lock", "dropped": "_lock",
                   "recorded": "_lock"}

    def __init__(self, capacity=DEFAULT_RING_SPANS):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._spans = []
        self.recorded = 0
        self.dropped = 0

    def record(self, name, trace, t0, dur_s, proc="engine", **meta):
        """Record one finished span; returns the span doc (or None when
        recording is disabled or the request is untraced)."""
        if trace is None or not spans_enabled():
            return None
        doc = {
            "trace_id": trace.trace_id,
            "span_id": _new_span_id(),
            "parent_span_id": trace.span_id,
            "name": name,
            "proc": proc,
            "t0": float(t0),
            "dur_s": float(dur_s),
            "meta": dict(meta),
        }
        with self._lock:
            self._spans.append(doc)
            self.recorded += 1
            if len(self._spans) > self.capacity:
                drop = len(self._spans) - self.capacity
                del self._spans[:drop]
                self.dropped += drop
        return doc

    def spans(self, limit=None, trace_id=None):
        """The most recent spans (ascending t0 order as recorded),
        optionally filtered by trace_id; ``limit`` keeps the newest N."""
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        if limit is not None and limit >= 0:
            out = out[-int(limit):]
        return out

    def snapshot(self):
        with self._lock:
            return {"capacity": self.capacity,
                    "held": len(self._spans),
                    "recorded": self.recorded,
                    "dropped": self.dropped}


@contextmanager
def span(ring, name, trace, proc="engine", **meta):
    """Context-managed stage span: times the body and records it into
    ``ring`` on exit (exceptions included — a failed stage still shows
    its span).  No-ops when ``trace`` is None."""
    t0 = time.time()
    p0 = time.perf_counter()
    try:
        yield
    finally:
        ring.record(name, trace, t0, time.perf_counter() - p0,
                    proc=proc, **meta)
