"""On-demand ``jax.profiler`` capture around one dispatch window.

Two arming paths, one capture:

* **Serve path** — ``POST /profilez`` (serve/transport.py) arms the
  engine's :class:`ProfilerHook`; the NEXT ``_dispatch_guarded`` device
  call runs under ``jax.profiler.start_trace``/``stop_trace`` and the
  hook records device memory stats plus the waterfall executed-flops
  ledger alongside (``capture.json`` in the log dir) — the MFU and
  fallback-attribution evidence the next TPU round needs, without
  re-running anything.
* **Non-serve path** — ``RAFT_TPU_PROFILE_DIR=<dir>`` makes the first
  ``waterfall_dispatch`` of the process capture itself the same way
  (:func:`env_capture`), so the sweep drivers and bench sections get
  the identical artifact with zero plumbing.

Both paths are one-shot (arm → one window → disarm): profiling every
window would turn a latency tool into a latency problem.  Capture
failures (no profiler on this backend, unwritable dir) are recorded in
the capture doc and never propagate into the dispatch — the solve wins
over the telemetry.

The ``RAFT_TPU_PROFILE_DIR`` env read lives HERE, not in waterfall.py:
waterfall is a compiled-code-roster module (serve/cache.py
``_CODE_VERSION_MODULES``) and this flag is bits-neutral — profiling a
dispatch must never invalidate a cached executable.
"""

import json
import os
import threading
import time

from raft_tpu.utils.profiling import logger

__all__ = ["ProfilerHook", "profile_dir_from_env", "env_capture"]

# nesting guard: the engine hook wrapping a sweep dispatch that itself
# reaches env_capture() must not start_trace twice (jax errors on
# nested traces); plain bool, flipped only under _ACTIVE_LOCK
_ACTIVE = [False]
_ACTIVE_LOCK = threading.Lock()

# env_capture is once-per-process: the flag captures THE next dispatch,
# not every dispatch of a 256-design sweep
_ENV_DONE = [False]


def profile_dir_from_env():
    """``RAFT_TPU_PROFILE_DIR`` or None."""
    return os.environ.get("RAFT_TPU_PROFILE_DIR") or None


def _device_memory_stats():
    """Per-device ``memory_stats()`` where the backend provides them
    (TPU/GPU do; CPU returns None) — plain JSON types only."""
    import jax

    out = {}
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception as exc:  # noqa: BLE001 — backend without the API
            logger.debug("memory_stats unavailable on %s: %s", dev, exc)
            stats = None
        out[str(dev)] = ({k: int(v) for k, v in stats.items()}
                         if stats else None)
    return out


def _waterfall_ledger():
    from raft_tpu.waterfall import last_dispatch_stats

    return last_dispatch_stats()


def _write_doc(log_dir, doc):
    path = os.path.join(log_dir, "capture.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
    os.replace(tmp, path)
    return path


def _capture(log_dir, fn, meta=None):
    """Run ``fn`` under a jax.profiler trace; returns (result, doc).
    Any capture failure lands in ``doc["error"]`` — never raised."""
    import jax

    doc = {"log_dir": log_dir, "t_unix": time.time(), "meta": meta or {}}
    started = False
    with _ACTIVE_LOCK:
        nested = _ACTIVE[0]
        _ACTIVE[0] = True
    t0 = time.perf_counter()
    try:
        if not nested:
            try:
                os.makedirs(log_dir, exist_ok=True)
                jax.profiler.start_trace(log_dir)
                started = True
            except Exception as exc:  # noqa: BLE001 — keep dispatching
                doc["error"] = f"{type(exc).__name__}: {exc}"
        else:
            doc["error"] = "nested capture: an outer window is active"
        result = fn()
    finally:
        doc["wall_s"] = round(time.perf_counter() - t0, 6)
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as exc:  # noqa: BLE001
                doc.setdefault("error",
                               f"{type(exc).__name__}: {exc}")
        if not nested:
            with _ACTIVE_LOCK:
                _ACTIVE[0] = False
    try:
        doc["device_memory"] = _device_memory_stats()
        doc["waterfall"] = _waterfall_ledger()
        if started:
            doc["path"] = _write_doc(log_dir, doc)
    except Exception as exc:  # noqa: BLE001 — telemetry never raises
        doc.setdefault("error", f"{type(exc).__name__}: {exc}")
    logger.info("profiler capture: dir=%s wall=%.3fs error=%s",
                log_dir, doc["wall_s"], doc.get("error"))
    return result, doc


class ProfilerHook:
    """One-shot dispatch-window profiler (see module docstring).

    ``run(fn)`` is the hot-path shim: a single GIL-atomic read when
    disarmed (the steady state), a full capture exactly once after
    ``arm``."""

    _GUARDED_BY = {"armed_dir": "_lock", "last": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self.armed_dir = None
        self.last = None

    @classmethod
    def from_env(cls):
        hook = cls()
        d = profile_dir_from_env()
        if d:
            hook.arm(d)
        return hook

    def arm(self, log_dir):
        """Arm capture of the next dispatch window into ``log_dir``.
        Non-reentrant: arming while a capture is already pending is
        refused (the ``POST /profilez`` 409)."""
        log_dir = str(log_dir)
        with self._lock:
            if self.armed_dir is not None:
                return {"armed": False, "log_dir": self.armed_dir,
                        "error": "already armed; capture pending"}
            self.armed_dir = log_dir
        return {"armed": True, "log_dir": log_dir}

    def run(self, fn, meta=None):
        if self.armed_dir is None:            # GIL-atomic fast path
            return fn()
        with self._lock:
            log_dir, self.armed_dir = self.armed_dir, None
        if log_dir is None:                   # lost the race: disarmed
            return fn()
        result, doc = _capture(log_dir, fn, meta=meta)
        with self._lock:
            self.last = doc
        return result

    def snapshot(self):
        with self._lock:
            return {"armed_dir": self.armed_dir, "last": self.last}


def env_capture(fn, meta=None):
    """The non-serve arming path: when ``RAFT_TPU_PROFILE_DIR`` is set,
    capture ``fn``'s window ONCE per process; otherwise (and on every
    later call) just run it."""
    log_dir = profile_dir_from_env()
    if not log_dir or _ENV_DONE[0]:
        return fn()
    _ENV_DONE[0] = True
    result, _doc = _capture(log_dir, fn, meta=meta)
    return result
