"""Quasi-static catenary mooring system in JAX.

Native replacement for the MoorPy subset the reference consumes
(reference raft/raft_model.py:58-77, :332-378; capability inventory in
SURVEY.md §2.2): YAML system parsing, per-line elastic catenary solves with
seabed contact, rigid-body equilibrium under external mean loads, and the
linearized outputs RAFT needs — the coupled stiffness matrix ``C_moor``, net
force ``F_moor``, line tensions, and the tension Jacobian ``J_moor``.

Where MoorPy linearizes by finite differences, everything here is
``jax.jacfwd`` through the actual solver, and the per-line catenary solves
are ``vmap``-batched; the whole system is differentiable and vmappable over
load cases (mean aero loads) and design parameters.

Catenary formulation: the standard quasi-static elastic catenary (as in
MoorPy/MAP; suspended + seabed-contact cases, frictionless seabed CB=0 which
is MoorPy's default for lines parsed from YAML), solved by damped Newton in
(log HF, VF).
"""

from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.utils.frames import rotation_matrix, translate_force_3to6


# ---------------- host-side parsing ----------------

@dataclass
class MooringSystem:
    """Static description of a body-coupled mooring system (arrays over
    composite anchor-to-fairlead lines; segment axis padded to the longest
    chain with inert entries L=0, EA=1, w=1, Wp=0)."""

    anchors: np.ndarray   # [nL, 3] fixed anchor positions
    rFair: np.ndarray     # [nL, 3] fairlead positions relative to the body
    L: np.ndarray         # [nL, S] unstretched segment lengths (anchor->fair)
    EA: np.ndarray        # [nL, S] axial stiffnesses
    w: np.ndarray         # [nL, S] submerged weights per length (N/m)
    Wp: np.ndarray        # [nL, S] clump weight at the TOP of each segment
    #                       (N; junction point mass - buoyancy; top row 0)
    depth: float
    names: list

    @property
    def n_lines(self):
        return len(self.L)

    def arrays(self, dtype=jnp.float64, device="cpu"):
        """Line property arrays for the solver functions.

        By default the arrays are committed to the host CPU backend: the
        mooring equilibrium is setup-time work wanting exact f64, and the TPU
        backend cannot compile f64 LU solves.  Committed placement makes every
        eager op downstream execute on CPU.  Pass ``device=None`` to leave
        placement to the caller (e.g. inside a jitted pipeline).
        """
        np_dtype = np.dtype(dtype.dtype if hasattr(dtype, "dtype") else dtype)
        src = (self.anchors, self.rFair, self.L, self.EA, self.w, self.Wp)
        if device == "cpu":
            from raft_tpu.utils.placement import put_cpu

            # place from the NumPy source: device_put of an existing jax
            # array goes through a ~100 ms/call path on plugin backends
            return tuple(put_cpu(np.asarray(a, np_dtype)) for a in src)
        return tuple(jnp.asarray(a, dtype) for a in src)


def parse_mooring(mooring, rho_water=1025.0, g=9.81):
    """Build a MooringSystem from the design dict's ``mooring`` section
    (schema per reference designs/*.yaml: points/lines/line_types).

    Lines chained through ``free`` intermediate points (the industry
    chain-rope-chain pattern; MoorPy capability surface, SURVEY.md §2.2)
    are composed into one composite anchor-to-fairlead line; a free
    point's optional ``mass``/``volume`` become a clump weight at the
    junction.  Free points must join exactly two lines (bridles are out
    of scope)."""
    types = {lt["name"]: lt for lt in mooring["line_types"]}
    points = {p["name"]: p for p in mooring["points"]}

    attach = {}          # point name -> [(line index, other point name)]
    for i, ln in enumerate(mooring["lines"]):
        attach.setdefault(ln["endA"], []).append((i, ln["endB"]))
        attach.setdefault(ln["endB"], []).append((i, ln["endA"]))

    def seg_props(ln):
        lt = types[ln["type"]]
        d_vol = float(lt["diameter"])  # volume-equivalent diameter
        mden = float(lt["mass_density"])
        return (float(ln["length"]), float(lt["stiffness"]),
                (mden - rho_water * np.pi / 4 * d_vol**2) * g)

    def point_weight(p):
        return (float(p.get("mass", 0.0))
                - rho_water * float(p.get("volume", 0.0))) * g

    anchors, rFair, segs, names, used = [], [], [], [], set()
    for name, p in points.items():
        if p["type"] != "fixed":
            continue
        for i0, nxt in attach.get(name, []):
            # walk the chain from this anchor through free points
            chain = [i0]
            cur = nxt
            while points[cur]["type"] == "free":
                at = attach[cur]
                if len(at) != 2:
                    raise ValueError(
                        f"free point '{cur}' joins {len(at)} lines; only "
                        "two-line chains are supported (no bridles)"
                    )
                (j,) = [j for j, _ in at if j != chain[-1]]
                chain.append(j)
                cur = [o for j, o in at if j == chain[-1]][0]
            if points[cur]["type"] != "vessel":
                raise ValueError(
                    f"line chain from anchor '{name}' ends at "
                    f"'{cur}' ({points[cur]['type']}); expected a vessel point"
                )
            seg = []
            node = name
            for j in chain:
                ln = mooring["lines"][j]
                node = ln["endB"] if ln["endA"] == node else ln["endA"]
                wp = point_weight(points[node]) if (
                    points[node]["type"] == "free") else 0.0
                seg.append(seg_props(ln) + (wp,))
                used.add(j)
            anchors.append(np.array(p["location"], float))
            rFair.append(np.array(points[cur]["location"], float))
            segs.append(seg)
            names.append("-".join(
                mooring["lines"][j].get("name", f"line{j+1}") for j in chain
            ))
    unused = set(range(len(mooring["lines"]))) - used
    if unused:
        bad = [mooring["lines"][j].get("name", f"line{j+1}") for j in unused]
        raise ValueError(
            f"lines {bad} are not part of any fixed-to-vessel chain"
        )

    S = max(len(s) for s in segs)
    nL = len(segs)
    L = np.zeros((nL, S))
    EA = np.ones((nL, S))
    w = np.ones((nL, S))
    Wp = np.zeros((nL, S))
    for i, seg in enumerate(segs):
        for k, (lk, ek, wk, wpk) in enumerate(seg):
            L[i, k], EA[i, k], w[i, k], Wp[i, k] = lk, ek, wk, wpk

    return MooringSystem(
        anchors=np.array(anchors),
        rFair=np.array(rFair),
        L=L, EA=EA, w=w, Wp=Wp,
        depth=float(mooring.get("water_depth", 0.0)),
        names=names,
    )


# ---------------- elastic catenary ----------------

def _profile(H, V, L, EA, w):
    """Fairlead excursion (x, z) produced by fairlead tension components
    (H horizontal, V vertical) for a line of length L, stiffness EA, unit
    submerged weight w.  Frictionless seabed.

    Suspended (V >= wL):
      x = H/w [asinh(V/H) - asinh((V-wL)/H)] + HL/EA
      z = H/w [sqrt(1+(V/H)^2) - sqrt(1+((V-wL)/H)^2)] + (VL - wL^2/2)/EA
    Touchdown (V < wL, length LB = L - V/w on the seabed):
      x = LB + H/w asinh(V/H) + HL/EA
      z = H/w (sqrt(1+(V/H)^2) - 1) + V^2/(2 EA w)
    The two meet continuously at V = wL.
    """
    W = w * L
    VA = V - W
    vh = V / H
    vah = VA / H
    xs = H / w * (jnp.arcsinh(vh) - jnp.arcsinh(vah)) + H * L / EA
    zs = (
        H / w * (jnp.sqrt(1 + vh**2) - jnp.sqrt(1 + vah**2))
        + (V * L - 0.5 * w * L**2) / EA
    )
    LB = jnp.clip(L - V / w, 0.0, L)
    xt = LB + H / w * jnp.arcsinh(vh) + H * L / EA
    zt = H / w * (jnp.sqrt(1 + vh**2) - 1.0) + V**2 / (2 * EA * w)
    suspended = VA >= 0
    return jnp.where(suspended, xs, xt), jnp.where(suspended, zs, zt)


def _profile_suspended(H, V, L, EA, w):
    """Suspended-segment spans (no seabed contact) — the analytic catenary
    expressions, valid for any bottom-end vertical tension VA = V - wL
    including VA < 0 (a segment sagging below its lower attachment).
    Vectorized over a trailing segment axis; inert padding (L=0) spans 0.
    """
    vh = V / H
    vah = (V - w * L) / H
    x = H / w * (jnp.arcsinh(vh) - jnp.arcsinh(vah)) + H * L / EA
    z = (
        H / w * (jnp.sqrt(1 + vh**2) - jnp.sqrt(1 + vah**2))
        + (V * L - 0.5 * w * L**2) / EA
    )
    return x, z


def _segment_top_tensions(V, L, w, Wp):
    """Vertical tension at the top of each segment of a composite line
    (segments ordered anchor(0) -> fairlead(S-1); fairlead vertical
    tension V; Wp = clump weight at each segment's top node)."""
    c = w * L
    above_seg = jnp.sum(c) - jnp.cumsum(c)            # sum_{j>i} w_j L_j
    above_pt = jnp.sum(Wp) - jnp.cumsum(Wp) + Wp      # sum_{j>=i} Wp_j
    return V - above_seg - above_pt


def _profile_composite(H, V, L, EA, w, Wp):
    """Fairlead excursion (x, z) of a composite line under fairlead tension
    (H, V): per-segment spans stacked anchor->fairlead.  The bottom segment
    may rest on the seabed (touchdown branch of :func:`_profile`); upper
    segments use the suspended expressions."""
    Vtop = _segment_top_tensions(V, L, w, Wp)
    x0, z0 = _profile(H, Vtop[0], L[0], EA[0], w[0])
    xu, zu = _profile_suspended(H, Vtop[1:], L[1:], EA[1:], w[1:])
    return x0 + jnp.sum(xu), z0 + jnp.sum(zu)


def catenary_solve(XF, ZF, L, EA, w, Wp=None, iters=60, tol=1e-11):
    """Solve one (possibly composite) line for fairlead tension components
    (HF, VF) such that the catenary spans horizontal distance XF and
    vertical distance ZF.  ``L``/``EA``/``w`` may be scalars (one segment)
    or [S] segment arrays ordered anchor->fairlead with clump weights
    ``Wp`` at segment tops.

    Damped Newton in (log HF, VF) — log keeps HF positive — from the
    MoorPy-style initial guess, iterated to a relative-residual tolerance
    inside a ``while_loop`` (cap ``iters``).

    Differentiation is *implicit* via ``lax.custom_root``: tangents come
    from one 2x2 linear solve of the profile equations at the converged
    point (implicit function theorem) rather than unrolling the Newton
    iterations.  That makes every consumer — the equilibrium Jacobian, the
    autodiff stiffness ``C_moor``, the tension Jacobian ``J_moor`` — both
    much cheaper to trace/compile and far better conditioned in float32,
    which is what lets the design-sweep driver run the whole mooring stage
    on the TPU.
    """
    L = jnp.atleast_1d(L)
    EA = jnp.atleast_1d(EA)
    w = jnp.atleast_1d(w)
    Wp = jnp.zeros_like(L) if Wp is None else jnp.atleast_1d(Wp)
    L_tot = jnp.sum(L)
    W = jnp.sum(w * L)                   # total suspended segment weight
    w_eff = W / L_tot
    # guard XF -> 0 (fairlead directly above anchor, e.g. a vertical tendon):
    # treat as a tiny horizontal span so the solve stays finite; HF then
    # correctly comes out ~0 and the force is purely vertical
    XF = jnp.maximum(XF, 1e-6 * L_tot)
    d = jnp.sqrt(XF**2 + ZF**2)
    slack = 3.0 * jnp.maximum((L_tot**2 - ZF**2) / XF**2 - 1.0, 1e-8)
    lam0 = jnp.where(L_tot <= d, 0.25, jnp.sqrt(slack))
    H0 = jnp.maximum(jnp.abs(0.5 * w_eff * XF / lam0), 10.0)
    V0 = 0.5 * w_eff * (ZF / jnp.tanh(lam0) + L_tot) + 0.5 * jnp.sum(Wp)
    scale = jnp.maximum(jnp.abs(XF), jnp.abs(ZF))
    tol = jnp.asarray(tol, XF.dtype) + 30 * jnp.finfo(XF.dtype).eps

    def resid(p):
        # residual as a function of the unknowns only; XF/ZF/L/EA/w enter
        # by closure, so custom_root's implicit derivative covers them
        H = jnp.exp(p[0])
        V = p[1]
        x, z = _profile_composite(H, V, L, EA, w, Wp)
        return jnp.stack([x - XF, z - ZF])

    def solve(f, p0):
        jac = jax.jacfwd(f)

        def step(p):
            r = f(p)
            J = jac(p)
            det = J[0, 0] * J[1, 1] - J[0, 1] * J[1, 0]
            det = jnp.where(jnp.abs(det) < 1e-30, 1e-30, det)
            du = (J[1, 1] * r[0] - J[0, 1] * r[1]) / det
            dv = (-J[1, 0] * r[0] + J[0, 0] * r[1]) / det
            du = jnp.clip(du, -1.5, 1.5)
            dv = jnp.clip(
                dv, -0.5 * (jnp.abs(p[1]) + W), 0.5 * (jnp.abs(p[1]) + W)
            )
            return p - jnp.stack([du, dv]), jnp.max(jnp.abs(r)) / scale

        def cond(state):
            i, p, err = state
            return (i < iters) & (err > tol)

        def body(state):
            i, p, _ = state
            p, err = step(p)
            return i + 1, p, err

        _, p, _ = jax.lax.while_loop(
            cond, body, (jnp.array(0), p0, jnp.asarray(jnp.inf, XF.dtype))
        )
        return p

    def tangent_solve(g, y):
        # g is the residual linearized at the solution; solve the 2x2 system
        J = jax.jacfwd(g)(jnp.zeros_like(y))
        det = J[0, 0] * J[1, 1] - J[0, 1] * J[1, 0]
        det = jnp.where(jnp.abs(det) < 1e-30, 1e-30, det)
        return jnp.stack([
            (J[1, 1] * y[0] - J[0, 1] * y[1]) / det,
            (-J[1, 0] * y[0] + J[0, 0] * y[1]) / det,
        ])

    p = jax.lax.custom_root(
        resid, jnp.stack([jnp.log(H0), V0]), solve, tangent_solve
    )
    return jnp.exp(p[0]), p[1]


# ---------------- system-level forces ----------------

def line_forces(r6, anchors, rFair, L, EA, w, Wp=None):
    """6-DOF mooring reaction on the body at pose r6, plus per-line fairlead
    force vectors and tension components.  Segment arrays are [nL, S]
    (anchor->fairlead; S=1 for simple lines).

    Returns (f6[6], HF[nL], VF[nL]).
    """
    if Wp is None:
        Wp = jnp.zeros_like(L)
    R = rotation_matrix(r6[3], r6[4], r6[5])
    arm = jnp.einsum("ij,lj->li", R, rFair)          # rotated fairlead offsets
    p = r6[:3] + arm                                  # fairlead world positions
    dxy = p[:, :2] - anchors[:, :2]
    XF = jnp.sqrt(jnp.sum(dxy**2, axis=1))
    ZF = p[:, 2] - anchors[:, 2]
    HF, VF = jax.vmap(catenary_solve)(XF, ZF, L, EA, w, Wp)
    # vertical-line guard: direction is irrelevant when XF ~ 0 since HF ~ 0
    u = dxy / jnp.maximum(XF, 1e-9)[:, None]
    F3 = jnp.stack([-HF * u[:, 0], -HF * u[:, 1], -VF], axis=1)  # [nL,3]
    f6 = jnp.sum(translate_force_3to6(F3, arm), axis=0)
    return f6, HF, VF


def line_tensions(r6, anchors, rFair, L, EA, w, Wp=None):
    """End tensions [TA..., TB...] (anchor ends first, then fairlead ends),
    matching MoorPy's getTensions ordering consumed at reference
    raft/raft_model.py:273-283."""
    if Wp is None:
        Wp = jnp.zeros_like(L)
    _, HF, VF = line_forces(r6, anchors, rFair, L, EA, w, Wp)
    # vertical tension at the anchor end of the composite line (1-D legacy
    # [nL] inputs are per-line scalars — summing axis -1 would total ALL
    # lines' weights)
    Lw = w * L
    W = (Lw if Lw.ndim == 1 else jnp.sum(Lw, axis=-1)) + (
        Wp if Wp.ndim == 1 else jnp.sum(Wp, axis=-1))
    VA = VF - W
    TB = jnp.sqrt(HF**2 + VF**2)
    TA = jnp.where(VA >= 0, jnp.sqrt(HF**2 + VA**2), HF)
    return jnp.concatenate([TA, TB])


def body_hydrostatic_force(r6, m, v, rCG, rM, AWP, rho=1025.0, g=9.81):
    """Weight + buoyancy + waterplane heave stiffness of the rigid body,
    with buoyancy applied at the metacenter rM (MoorPy Body convention —
    RAFT pushes m/v/rCG/AWP/rM into the body at raft/raft_fowt.py:309-313)."""
    R = rotation_matrix(r6[3], r6[4], r6[5])
    f6 = translate_force_3to6(
        jnp.array([0.0, 0.0, -m * g], r6.dtype), R @ rCG
    ) + translate_force_3to6(jnp.array([0.0, 0.0, rho * v * g], r6.dtype), R @ rM)
    return f6.at[2].add(-rho * g * AWP * r6[2])


def solve_equilibrium(
    f6_ext, body_props, anchors, rFair, L, EA, w, Wp=None, rho=1025.0, g=9.81,
    iters=40, r6_init=None, step_tol=1e-8,
):
    """Find the body pose r6 where mooring + hydrostatics + external mean
    loads balance (the reference's ms.solveEquilibrium3 call,
    raft/raft_model.py:347).  Damped Newton with the exact autodiff
    Jacobian, iterated inside a ``while_loop`` until the Newton step is
    below ``step_tol`` (translations: m, rotations: rad) or ``iters`` is
    reached — nothing differentiates *through* this loop
    (:func:`case_mooring` linearizes at the converged pose), so the
    data-dependent trip count is free.

    body_props : (m, v, rCG[3], rM[3], AWP)
    Returns r6[6].
    """
    m, v, rCG, rM, AWP = body_props
    if Wp is None:
        Wp = jnp.zeros_like(L)

    def total_force(r6):
        f_lines, _, _ = line_forces(r6, anchors, rFair, L, EA, w, Wp)
        f_body = body_hydrostatic_force(r6, m, v, rCG, rM, AWP, rho, g)
        return f_lines + f_body + f6_ext

    jac = jax.jacfwd(total_force)
    # derive constants from an operand so eager placement follows the system
    # arrays (committed to CPU by MooringSystem.arrays())
    step_cap = jnp.zeros_like(L, shape=(6,)) + jnp.asarray(
        [10.0, 10.0, 10.0, 0.1, 0.1, 0.1], L.dtype
    )
    tol = jnp.asarray(step_tol, L.dtype) + 100 * jnp.finfo(L.dtype).eps

    def cond(state):
        i, r6, err = state
        return (i < iters) & (err > tol)

    def body_fn(state):
        i, r6, _ = state
        F = total_force(r6)
        J = jac(r6)
        dx = jnp.linalg.solve(J, -F)
        dx = jnp.clip(dx, -step_cap, step_cap)
        return i + 1, r6 + dx, jnp.max(jnp.abs(dx))

    r0 = jnp.zeros_like(L, shape=(6,)) if r6_init is None else jnp.asarray(r6_init)
    _, r6, _ = jax.lax.while_loop(
        cond, body_fn, (jnp.array(0), r0, jnp.asarray(jnp.inf, L.dtype))
    )
    return r6


def coupled_stiffness(r6, anchors, rFair, L, EA, w, Wp=None):
    """Mooring-only 6x6 stiffness C = -d f6_lines / d r6 about pose r6
    (the reference's ms.getCoupledStiffness(lines_only=True),
    raft/raft_model.py:117, :366) — exact forward-mode autodiff through the
    catenary solves instead of MoorPy's finite differencing."""

    def f(r):
        f6, _, _ = line_forces(r, anchors, rFair, L, EA, w, Wp)
        return f6

    return -jax.jacfwd(f)(r6)


def tension_jacobian(r6, anchors, rFair, L, EA, w, Wp=None):
    """J_moor = d tensions / d r6  [2 nL, 6] (reference raft_model.py:366,
    consumed for tension FFTs at :273-283)."""
    return jax.jacfwd(
        lambda r: line_tensions(r, anchors, rFair, L, EA, w, Wp)
    )(r6)


def case_mooring(f6_ext, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w,
                 Wp=None, rho=1025.0, g=9.81, yawstiff=0.0):
    """One-shot per-case mooring analysis: equilibrium pose plus all the
    linearized quantities the dynamics solve consumes
    (reference raft/raft_model.py:332-392 calcMooringAndOffsets).

    Designed to be jitted once and vmapped over the case axis of ``f6_ext``
    (per-case mean aero loads) — every Model.analyze_cases call then reuses
    the same compiled executable instead of retracing the autodiff-through-
    catenary graphs per case.

    Returns (r6, C_moor, F_moor, T_moor, J_moor).
    """
    if Wp is None:
        Wp = jnp.zeros_like(L)
    r6 = solve_equilibrium(
        f6_ext, (m, v, rCG, rM, AWP), anchors, rFair, L, EA, w, Wp,
        rho=rho, g=g
    )
    C_moor = coupled_stiffness(r6, anchors, rFair, L, EA, w, Wp)
    C_moor = C_moor.at[5, 5].add(yawstiff)
    F_moor = line_forces(r6, anchors, rFair, L, EA, w, Wp)[0]
    T_moor = line_tensions(r6, anchors, rFair, L, EA, w, Wp)
    J_moor = tension_jacobian(r6, anchors, rFair, L, EA, w, Wp)
    return r6, C_moor, F_moor, T_moor, J_moor


# ---------------- cached jitted entry points ----------------
#
# jit caches executables on the *function object*, so a `jax.jit` taken on a
# fresh closure inside each Model instance recompiles the whole
# autodiff-through-catenary graph per model (~10 s on CPU).  Repeated model
# construction — the design-sweep inner loop — must instead reuse one
# compiled executable, so the jitted wrappers live here at module level,
# keyed only by the (hashable) physics scalars; array shapes are handled by
# jit's own cache.

def _case_mooring_flat(rho, g, yawstiff):
    """Positional-argument :func:`case_mooring` wrapper shared by the
    cached batch entry points below."""

    def one(f6, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w, Wp):
        return case_mooring(
            f6, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w, Wp,
            rho=rho, g=g, yawstiff=yawstiff,
        )

    return one


@lru_cache(maxsize=None)
def case_mooring_batch_fn(rho, g, yawstiff):
    """Jitted :func:`case_mooring`, vmapped over the case axis of ``f6_ext``
    (body properties and line arrays are shared across cases)."""
    one = _case_mooring_flat(rho, g, yawstiff)
    return jax.jit(jax.vmap(one, in_axes=(0,) + (None,) * 11))


@lru_cache(maxsize=None)
def case_mooring_design_batch_fn(rho, g, yawstiff):
    """Jitted :func:`case_mooring` vmapped over designs *and* cases:
    f6_ext[nd, nc, 6], body props [nd,...], line arrays [nd, nL, ...] —
    the sweep driver's batched mooring equilibrium (one compile serves the
    whole sweep)."""
    one = _case_mooring_flat(rho, g, yawstiff)
    per_design = jax.vmap(one, in_axes=(0,) + (None,) * 11)
    return jax.jit(jax.vmap(per_design))


@lru_cache(maxsize=None)
def unloaded_mooring_fn():
    """Jitted (C_moor0, F_moor0) at a given pose — the undisplaced
    linearization consumed by analyze_unloaded (reference
    raft/raft_model.py:117-118)."""

    def f(r6, anchors, rFair, L, EA, w, Wp):
        C0 = coupled_stiffness(r6, anchors, rFair, L, EA, w, Wp)
        F0 = line_forces(r6, anchors, rFair, L, EA, w, Wp)[0]
        return C0, F0

    return jax.jit(f)
