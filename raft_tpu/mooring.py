"""Quasi-static catenary mooring system in JAX.

Native replacement for the MoorPy subset the reference consumes
(reference raft/raft_model.py:58-77, :332-378; capability inventory in
SURVEY.md §2.2): YAML system parsing, per-line elastic catenary solves with
seabed contact, rigid-body equilibrium under external mean loads, and the
linearized outputs RAFT needs — the coupled stiffness matrix ``C_moor``, net
force ``F_moor``, line tensions, and the tension Jacobian ``J_moor``.

Where MoorPy linearizes by finite differences, everything here is
``jax.jacfwd`` through the actual solver, and the per-line catenary solves
are ``vmap``-batched; the whole system is differentiable and vmappable over
load cases (mean aero loads) and design parameters.

Catenary formulation: the standard quasi-static elastic catenary (as in
MoorPy/MAP; suspended + seabed-contact cases, with optional MoorPy-style
CB seabed friction via the line type's ``cb``/``seabed_friction`` key —
frictionless remains the default, matching MoorPy's YAML parsing), solved
by damped Newton in (log HF, log VF).  Free points joining three or more
lines form bridle groups whose junction positions are solved by an
adaptive Levenberg-Marquardt force balance under ``lax.custom_root``
(the equilibrium routinely sits at a leg's slack/taut stiffness kink).
"""

from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.utils.frames import rotation_matrix, translate_force_3to6


# ---------------- host-side parsing ----------------

@dataclass
class BridleSet:
    """Bridled line groups: free junction points joining three or more
    lines (MoorPy's general point-object capability; the classic crow's
    foot / delta connection).  Each bridle has up to K legs running
    bottom->top from the junction's perspective:

      kind 0 : anchor leg  — segments ordered anchor -> junction (the
               junction is the leg's top end; the anchor end may rest on
               the seabed),
      kind 1 : vessel leg  — segments ordered junction -> fairlead (the
               junction is the leg's bottom end; fully suspended),
      kind -1: inert padding.

    ``ends`` holds the leg's terminal point: anchor world position
    (kind 0) or fairlead position in the body frame (kind 1).
    """

    kind: np.ndarray    # [nB, K]
    ends: np.ndarray    # [nB, K, 3]
    L: np.ndarray       # [nB, K, S]
    EA: np.ndarray      # [nB, K, S]
    w: np.ndarray       # [nB, K, S]
    Wp: np.ndarray      # [nB, K, S]
    Wj: np.ndarray      # [nB] junction net weight (N; mass - buoyancy)
    p0: np.ndarray      # [nB, 3] junction position initial guess
    cb: np.ndarray = None  # [nB, K] seabed friction of each leg's
    #                        anchor-side segment (0 for vessel legs)

    def __post_init__(self):
        if self.cb is None:
            self.cb = np.zeros(self.kind.shape)

    @property
    def n(self):
        return len(self.Wj)

    def arrays(self, dtype=jnp.float64, device="cpu"):
        src = (self.kind.astype(float), self.ends, self.L, self.EA,
               self.w, self.Wp, self.cb, self.Wj, self.p0)
        if device == "cpu":
            from raft_tpu.utils.placement import put_cpu

            return tuple(put_cpu(np.asarray(a, float)) for a in src)
        return tuple(jnp.asarray(a, dtype) for a in src)


@dataclass
class MooringSystem:
    """Static description of a body-coupled mooring system (arrays over
    composite anchor-to-fairlead lines; segment axis padded to the longest
    chain with inert entries L=0, EA=1, w=1, Wp=0)."""

    anchors: np.ndarray   # [nL, 3] fixed anchor positions
    rFair: np.ndarray     # [nL, 3] fairlead positions relative to the body
    L: np.ndarray         # [nL, S] unstretched segment lengths (anchor->fair)
    EA: np.ndarray        # [nL, S] axial stiffnesses
    w: np.ndarray         # [nL, S] submerged weights per length (N/m)
    Wp: np.ndarray        # [nL, S] clump weight at the TOP of each segment
    #                       (N; junction point mass - buoyancy; top row 0)
    depth: float
    names: list
    cb: np.ndarray = None  # [nL] seabed friction coefficient (MoorPy CB;
    #                        bottom segment's line_type 'cb', default 0)
    bridles: BridleSet = None   # bridled groups, or None

    def __post_init__(self):
        if self.cb is None:
            self.cb = np.zeros(len(self.L))

    def bridle_arrays(self, dtype=jnp.float64, device="cpu"):
        """Bridle pytree for the solver functions (None if unbridled)."""
        if self.bridles is None:
            return None
        return self.bridles.arrays(dtype=dtype, device=device)

    @property
    def n_lines(self):
        return len(self.L)

    def arrays(self, dtype=jnp.float64, device="cpu"):
        """Line property arrays for the solver functions.

        By default the arrays are committed to the host CPU backend: the
        mooring equilibrium is setup-time work wanting exact f64, and the TPU
        backend cannot compile f64 LU solves.  Committed placement makes every
        eager op downstream execute on CPU.  Pass ``device=None`` to leave
        placement to the caller (e.g. inside a jitted pipeline).
        """
        np_dtype = np.dtype(dtype.dtype if hasattr(dtype, "dtype") else dtype)
        src = (self.anchors, self.rFair, self.L, self.EA, self.w, self.Wp,
               self.cb)
        if device == "cpu":
            from raft_tpu.utils.placement import put_cpu

            # place from the NumPy source: device_put of an existing jax
            # array goes through a ~100 ms/call path on plugin backends
            return tuple(put_cpu(np.asarray(a, np_dtype)) for a in src)
        return tuple(jnp.asarray(a, dtype) for a in src)


def parse_mooring(mooring, rho_water=1025.0, g=9.81):
    """Build a MooringSystem from the design dict's ``mooring`` section
    (schema per reference designs/*.yaml: points/lines/line_types).

    Lines chained through two-line ``free`` intermediate points (the
    industry chain-rope-chain pattern; MoorPy capability surface,
    SURVEY.md §2.2) are composed into one composite anchor-to-fairlead
    line; a free point's optional ``mass``/``volume`` become a clump
    weight at the junction.  Free points joining three or more lines
    become bridle junctions (``MooringSystem.bridles``): each attached
    chain is walked to its terminal fixed/vessel point and becomes a
    bridle leg, solved by a junction force-balance Newton at analysis
    time."""
    types = {lt["name"]: lt for lt in mooring["line_types"]}
    points = {p["name"]: p for p in mooring["points"]}

    attach = {}          # point name -> [(line index, other point name)]
    for i, ln in enumerate(mooring["lines"]):
        attach.setdefault(ln["endA"], []).append((i, ln["endB"]))
        attach.setdefault(ln["endB"], []).append((i, ln["endA"]))

    def seg_props(ln):
        lt = types[ln["type"]]
        d_vol = float(lt["diameter"])  # volume-equivalent diameter
        mden = float(lt["mass_density"])
        return (float(ln["length"]), float(lt["stiffness"]),
                (mden - rho_water * np.pi / 4 * d_vol**2) * g,
                float(lt.get("cb", lt.get("seabed_friction", 0.0))))

    def point_weight(p):
        return (float(p.get("mass", 0.0))
                - rho_water * float(p.get("volume", 0.0))) * g

    junctions = {
        name for name, p in points.items()
        if p["type"] == "free" and len(attach.get(name, [])) >= 3
    }

    def walk_chain(start_line, start_node):
        """Follow a chain from ``start_node`` (just crossed ``start_line``)
        through two-line free points; returns (line indices, terminal
        point name) — the terminal is fixed/vessel/junction."""
        chain = [start_line]
        cur = start_node
        while points[cur]["type"] == "free" and cur not in junctions:
            at = attach[cur]
            nxt = [j for j, _ in at if j != chain[-1]]
            if len(nxt) != 1:
                raise ValueError(
                    f"free point '{cur}' dead-ends the line chain (it "
                    f"joins {len(at)} line(s)); a free point must join "
                    "exactly two lines, or three-plus to form a bridle "
                    "junction"
                )
            chain.append(nxt[0])
            cur = [o for j, o in at if j == chain[-1]][0]
        return chain, cur

    def chain_segments(chain, start_node):
        """Segment property tuples for ``chain`` walked from
        ``start_node``, with intermediate free-point clump weights."""
        seg = []
        node = start_node
        for j in chain:
            ln = mooring["lines"][j]
            node = ln["endB"] if ln["endA"] == node else ln["endA"]
            wp = point_weight(points[node]) if (
                points[node]["type"] == "free" and node not in junctions
            ) else 0.0
            seg.append(seg_props(ln) + (wp,))
            used.add(j)
        return seg

    anchors, rFair, segs, names, used = [], [], [], [], set()
    for name, p in points.items():
        if p["type"] != "fixed":
            continue
        for i0, nxt in attach.get(name, []):
            chain, cur = walk_chain(i0, nxt)
            if cur in junctions:
                continue        # bridle anchor leg, claimed below
            if points[cur]["type"] != "vessel":
                raise ValueError(
                    f"line chain from anchor '{name}' ends at "
                    f"'{cur}' ({points[cur]['type']}); expected a vessel point"
                )
            seg = chain_segments(chain, name)
            anchors.append(np.array(p["location"], float))
            rFair.append(np.array(points[cur]["location"], float))
            segs.append(seg)
            names.append("-".join(
                mooring["lines"][j].get("name", f"line{j+1}") for j in chain
            ))

    # ---- bridle junctions: each attached chain becomes a leg ----
    bridle_legs, bridle_Wj, bridle_p0 = [], [], []
    for name in sorted(junctions):
        legs = []
        for i0, nxt in attach[name]:
            chain, cur = walk_chain(i0, nxt)
            term = points[cur]
            if cur in junctions or term["type"] == "free":
                raise ValueError(
                    f"bridle junction '{name}' connects to another "
                    f"junction/free terminal '{cur}'; chained junctions "
                    "are not supported"
                )
            # segments walked junction -> terminal; reorder bottom -> top:
            # anchor legs run anchor -> junction, vessel legs run
            # junction -> fairlead
            seg_out = chain_segments(chain, name)
            if term["type"] == "fixed":
                # reverse to anchor->junction order; clump weights attach
                # to the TOP node of each segment, so on reversal the Wp
                # column shifts by one (the weight walked after crossing
                # segment k sits at the junction-side end of the reversed
                # segment k+1): Wp_rev = reversed(Wp[:-1]) + [0]
                rev = [list(s) for s in seg_out[::-1]]
                wps = [s[-1] for s in seg_out]
                wps_rev = list(reversed(wps[:-1])) + [0.0]
                for s, wp2 in zip(rev, wps_rev):
                    s[-1] = wp2
                legs.append((0, np.array(term["location"], float),
                             [tuple(s) for s in rev]))
            else:
                legs.append((1, np.array(term["location"], float), seg_out))
        bridle_legs.append(legs)
        bridle_Wj.append(point_weight(points[name]))
        bridle_p0.append(np.array(points[name]["location"], float))

    unused = set(range(len(mooring["lines"]))) - used
    if unused:
        bad = [mooring["lines"][j].get("name", f"line{j+1}") for j in unused]
        raise ValueError(
            f"lines {bad} are not part of any fixed-to-vessel chain"
        )

    def seg_arrays(seg_lists, S):
        n = len(seg_lists)
        L = np.zeros((n, S))
        EA = np.ones((n, S))
        w = np.ones((n, S))
        Wp = np.zeros((n, S))
        cb = np.zeros(n)
        for i, seg in enumerate(seg_lists):
            # entries are seg_props(...) + (wp,) = (L, EA, w, cb, Wp)
            for k, (lk, ek, wk, cbk, wpk) in enumerate(seg):
                L[i, k], EA[i, k], w[i, k], Wp[i, k] = lk, ek, wk, wpk
                if k == 0:      # friction acts on the grounded bottom segment
                    cb[i] = cbk
        return L, EA, w, Wp, cb

    if segs:
        S = max(len(s) for s in segs)
        L, EA, w, Wp, cb = seg_arrays(segs, S)
        anchors = np.array(anchors)
        rFair = np.array(rFair)
    else:
        anchors = np.zeros((0, 3))
        rFair = np.zeros((0, 3))
        L = np.zeros((0, 1))
        EA = np.ones((0, 1))
        w = np.ones((0, 1))
        Wp = np.zeros((0, 1))
        cb = np.zeros(0)

    bridles = None
    if bridle_legs:
        K = max(len(legs) for legs in bridle_legs)
        Sb = max(len(seg) for legs in bridle_legs for _, _, seg in legs)
        nB = len(bridle_legs)
        kind = np.full((nB, K), -1.0)
        ends = np.zeros((nB, K, 3))
        bL = np.full((nB, K, Sb), 1.0)      # inert pad: L=1 (solved, masked)
        bEA = np.ones((nB, K, Sb)) * 1e9
        bw = np.ones((nB, K, Sb)) * 100.0
        bWp = np.zeros((nB, K, Sb))
        bcb = np.zeros((nB, K))
        for ib, legs in enumerate(bridle_legs):
            for ik, (kd, end, seg) in enumerate(legs):
                kind[ib, ik] = kd
                ends[ib, ik] = end
                if kd == 0:
                    # anchor leg (seg ordered anchor->junction): friction
                    # acts on the grounded anchor-side bottom segment
                    bcb[ib, ik] = seg[0][3]
                for ks, (lk, ek, wk, _cbk, wpk) in enumerate(seg):
                    bL[ib, ik, ks] = lk
                    bEA[ib, ik, ks] = ek
                    bw[ib, ik, ks] = wk
                    bWp[ib, ik, ks] = wpk
                # pad extra segment slots inertly (L=0 span)
                for ks in range(len(seg), Sb):
                    bL[ib, ik, ks] = 0.0
                    bEA[ib, ik, ks] = 1.0
                    bw[ib, ik, ks] = 1.0
            for ik in range(len(legs), K):
                # inert padded leg: parked far below, force masked out
                ends[ib, ik] = np.array([0.0, 0.0, -1.0])
        bridles = BridleSet(
            kind=kind, ends=ends, L=bL, EA=bEA, w=bw, Wp=bWp, cb=bcb,
            Wj=np.array(bridle_Wj), p0=np.array(bridle_p0),
        )

    return MooringSystem(
        anchors=anchors,
        rFair=rFair,
        L=L, EA=EA, w=w, Wp=Wp,
        depth=float(mooring.get("water_depth", 0.0)),
        names=names,
        cb=cb,
        bridles=bridles,
    )


# ---------------- elastic catenary ----------------

def _profile(H, V, L, EA, w, cb=0.0):
    """Fairlead excursion (x, z) produced by fairlead tension components
    (H horizontal, V vertical) for a line of length L, stiffness EA, unit
    submerged weight w, seabed friction coefficient ``cb`` (MoorPy's CB;
    0 = frictionless, MoorPy's default for YAML-parsed systems and what
    the reference consumes, raft/raft_model.py:58-59).

    Suspended (V >= wL):
      x = H/w [asinh(V/H) - asinh((V-wL)/H)] + HL/EA
      z = H/w [sqrt(1+(V/H)^2) - sqrt(1+((V-wL)/H)^2)] + (VL - wL^2/2)/EA
    Touchdown (V < wL, length LB = L - V/w on the seabed):
      x = LB + H/w asinh(V/H) + HL/EA
          + cb w/(2 EA) (lam max(lam, 0) - LB^2),  lam = LB - H/(cb w)
      z = H/w (sqrt(1+(V/H)^2) - 1) + V^2/(2 EA w)
    The friction term is MoorPy's catenary CB>0 branch: tension decays
    along the grounded length (zero beyond ``lam``), reducing the elastic
    stretch of the grounded portion; z is unchanged (friction acts
    horizontally).  The branches meet continuously at V = wL.
    """
    W = w * L
    VA = V - W
    vh = V / H
    vah = VA / H
    xs = H / w * (jnp.arcsinh(vh) - jnp.arcsinh(vah)) + H * L / EA
    zs = (
        H / w * (jnp.sqrt(1 + vh**2) - jnp.sqrt(1 + vah**2))
        + (V * L - 0.5 * w * L**2) / EA
    )
    LB = jnp.clip(L - V / w, 0.0, L)
    cb_s = jnp.maximum(cb, 1e-12)
    lam = LB - H / (cb_s * w)
    fric = jnp.where(
        cb > 0.0,
        cb_s * w / (2.0 * EA) * (lam * jnp.maximum(lam, 0.0) - LB**2),
        0.0,
    )
    xt = LB + H / w * jnp.arcsinh(vh) + H * L / EA + fric
    zt = H / w * (jnp.sqrt(1 + vh**2) - 1.0) + V**2 / (2 * EA * w)
    suspended = VA >= 0
    return jnp.where(suspended, xs, xt), jnp.where(suspended, zs, zt)


def _profile_suspended(H, V, L, EA, w):
    """Suspended-segment spans (no seabed contact) — the analytic catenary
    expressions, valid for any bottom-end vertical tension VA = V - wL
    including VA < 0 (a segment sagging below its lower attachment).
    Vectorized over a trailing segment axis; inert padding (L=0) spans 0.
    """
    vh = V / H
    vah = (V - w * L) / H
    x = H / w * (jnp.arcsinh(vh) - jnp.arcsinh(vah)) + H * L / EA
    z = (
        H / w * (jnp.sqrt(1 + vh**2) - jnp.sqrt(1 + vah**2))
        + (V * L - 0.5 * w * L**2) / EA
    )
    return x, z


def _segment_top_tensions(V, L, w, Wp):
    """Vertical tension at the top of each segment of a composite line
    (segments ordered anchor(0) -> fairlead(S-1); fairlead vertical
    tension V; Wp = clump weight at each segment's top node)."""
    c = w * L
    above_seg = jnp.sum(c) - jnp.cumsum(c)            # sum_{j>i} w_j L_j
    above_pt = jnp.sum(Wp) - jnp.cumsum(Wp) + Wp      # sum_{j>=i} Wp_j
    return V - above_seg - above_pt


def _profile_composite(H, V, L, EA, w, Wp, cb=0.0):
    """Fairlead excursion (x, z) of a composite line under fairlead tension
    (H, V): per-segment spans stacked anchor->fairlead.  The bottom segment
    may rest on the seabed (touchdown branch of :func:`_profile`, with
    seabed friction ``cb``); upper segments use the suspended
    expressions."""
    Vtop = _segment_top_tensions(V, L, w, Wp)
    x0, z0 = _profile(H, Vtop[0], L[0], EA[0], w[0], cb)
    xu, zu = _profile_suspended(H, Vtop[1:], L[1:], EA[1:], w[1:])
    return x0 + jnp.sum(xu), z0 + jnp.sum(zu)


def catenary_solve(XF, ZF, L, EA, w, Wp=None, cb=0.0, iters=60,
                   tol=1e-11, seabed=True):
    """Solve one (possibly composite) line for fairlead tension components
    (HF, VF) such that the catenary spans horizontal distance XF and
    vertical distance ZF.  ``L``/``EA``/``w`` may be scalars (one segment)
    or [S] segment arrays ordered anchor->fairlead with clump weights
    ``Wp`` at segment tops.

    Damped Newton in (log HF, log VF) — log keeps both tensions
    positive (a bottom->top oriented line always has positive fairlead
    tensions; solving V linearly admits spurious negative-V roots of the
    touchdown equations) — from the
    MoorPy-style initial guess, iterated to a relative-residual tolerance
    inside a ``while_loop`` (cap ``iters``).

    Differentiation is *implicit* via ``lax.custom_root``: tangents come
    from one 2x2 linear solve of the profile equations at the converged
    point (implicit function theorem) rather than unrolling the Newton
    iterations.  That makes every consumer — the equilibrium Jacobian, the
    autodiff stiffness ``C_moor``, the tension Jacobian ``J_moor`` — both
    much cheaper to trace/compile and far better conditioned in float32,
    which is what lets the design-sweep driver run the whole mooring stage
    on the TPU.
    """
    L = jnp.atleast_1d(L)
    EA = jnp.atleast_1d(EA)
    w = jnp.atleast_1d(w)
    Wp = jnp.zeros_like(L) if Wp is None else jnp.atleast_1d(Wp)
    L_tot = jnp.sum(L)
    W = jnp.sum(w * L)                   # total suspended segment weight
    w_eff = W / L_tot
    # guard XF -> 0 (fairlead directly above anchor, e.g. a vertical tendon):
    # treat as a tiny horizontal span so the solve stays finite; HF then
    # correctly comes out ~0 and the force is purely vertical
    XF = jnp.maximum(XF, 1e-6 * L_tot)
    d = jnp.sqrt(XF**2 + ZF**2)
    slack = 3.0 * jnp.maximum((L_tot**2 - ZF**2) / XF**2 - 1.0, 1e-8)
    lam0 = jnp.where(L_tot <= d, 0.25, jnp.sqrt(slack))
    H0 = jnp.maximum(jnp.abs(0.5 * w_eff * XF / lam0), 10.0)
    V0 = 0.5 * w_eff * (ZF / jnp.tanh(lam0) + L_tot) + 0.5 * jnp.sum(Wp)
    # taut (stretched) lines: the catenary-sag guess above is orders of
    # magnitude off and the Newton can stall — start from the elastic-bar
    # tension along the chord instead (bridle legs routinely go taut
    # while the junction Newton explores)
    EA_eff = L_tot / jnp.sum(L / EA)
    T_el = EA_eff * jnp.maximum(d - L_tot, 0.0) / L_tot + 0.5 * W
    taut = L_tot <= d
    H0 = jnp.where(taut, jnp.maximum(T_el * XF / d, 10.0), H0)
    V0 = jnp.where(taut, T_el * ZF / d + 0.5 * W + 0.5 * jnp.sum(Wp), V0)
    scale = jnp.maximum(jnp.abs(XF), jnp.abs(ZF))
    tol = jnp.asarray(tol, XF.dtype) + 30 * jnp.finfo(XF.dtype).eps

    def resid(p):
        # residual as a function of the unknowns only; XF/ZF/L/EA/w enter
        # by closure, so custom_root's implicit derivative covers them.
        # Both unknowns live in log space: H > 0 always, and the fairlead
        # (top-end) vertical tension V > 0 for every bottom->top oriented
        # line — solving V directly admits spurious negative-V roots of
        # the touchdown equations (found by the bridle junction Newton
        # exploring slack anchor-leg geometries)
        H = jnp.exp(p[0])
        V = jnp.exp(p[1])
        if seabed:
            x, z = _profile_composite(H, V, L, EA, w, Wp, cb)
        else:
            # fully-suspended composite (bridle vessel legs: the bottom
            # end hangs at the junction, clear of the seabed; VA < 0
            # sag-below-attachment is allowed)
            Vtop = _segment_top_tensions(V, L, w, Wp)
            xs, zs = _profile_suspended(H, Vtop, L, EA, w)
            x, z = jnp.sum(xs), jnp.sum(zs)
        return jnp.stack([x - XF, z - ZF])

    def solve(f, p0):
        jac = jax.jacfwd(f)

        def step(p):
            r = f(p)
            J = jac(p)
            det = J[0, 0] * J[1, 1] - J[0, 1] * J[1, 0]
            det = jnp.where(jnp.abs(det) < 1e-30, 1e-30, det)
            du = (J[1, 1] * r[0] - J[0, 1] * r[1]) / det
            dv = (-J[1, 0] * r[0] + J[0, 0] * r[1]) / det
            du = jnp.clip(du, -1.5, 1.5)
            dv = jnp.clip(dv, -1.5, 1.5)
            return p - jnp.stack([du, dv]), jnp.max(jnp.abs(r)) / scale

        def cond(state):
            i, p, err = state
            return (i < iters) & (err > tol)

        def body(state):
            i, p, _ = state
            p, err = step(p)
            return i + 1, p, err

        _, p, _ = jax.lax.while_loop(
            cond, body, (jnp.array(0), p0, jnp.asarray(jnp.inf, XF.dtype))
        )
        return p

    def tangent_solve(g, y):
        # g is the residual linearized at the solution; solve the 2x2 system
        J = jax.jacfwd(g)(jnp.zeros_like(y))
        det = J[0, 0] * J[1, 1] - J[0, 1] * J[1, 0]
        det = jnp.where(jnp.abs(det) < 1e-30, 1e-30, det)
        return jnp.stack([
            (J[1, 1] * y[0] - J[0, 1] * y[1]) / det,
            (-J[1, 0] * y[0] + J[0, 0] * y[1]) / det,
        ])

    p = jax.lax.custom_root(
        resid, jnp.stack([jnp.log(H0), jnp.log(jnp.maximum(V0, 1.0))]),
        solve, tangent_solve
    )
    HF, VF = jnp.exp(p[0]), jnp.exp(p[1])
    if seabed:
        # fully-slack regime: with more unstretched line than the vertical
        # drop plus the horizontal span (L > XF + ZF), the physical
        # profile is a vertical hang of length ZF with the excess lying
        # on the seabed — H = 0 exactly and V = the hanging weight
        # (MoorPy's zero-horizontal-tension profile).  The touchdown
        # equations have no positive-H root there; the Newton bottoms out
        # at H -> 0 with V indeterminate between the true hanging weight
        # and the full suspended weight, so the closed form replaces it.
        # The branches meet continuously at L = XF + ZF (both give
        # H -> 0, V -> hanging weight); elastic stretch of the hanging
        # part (~V/EA) is neglected, consistent with the quasi-static
        # seabed treatment.
        # relative margin 2e-4: just BELOW the boundary the log-H Newton
        # passes through a NaN-producing sliver (measured ~8e-5 wide in
        # relative L on the reference chain) before it converges to the
        # tiny-but-finite H regime; inside the margin the closed form's
        # H = 0 differs from the true H by < 1e-4 of V.  A residual
        # non-finite Newton escape (geometry-dependent sliver width)
        # falls back to the closed form as well — no NaN leaves the
        # touchdown solver for ZF >= 0 geometries.
        near = (ZF >= 0.0) & (L_tot >= (XF + ZF) * (1.0 - 2e-4))
        # the NaN escape only covers geometries within 1% of the fully-
        # slack boundary L = XF + ZF (where the Newton's measured NaN
        # sliver lives and the closed form is within ~1e-2 of truth): a
        # line whose Newton diverged anywhere else — taut, or slack but
        # far from the boundary where the true H is large (e.g.
        # XF=700/ZF=186/L=835 has H ~ 86 kN) — must keep its NaN
        # (detectable) rather than silently report zero tension
        bad = (ZF >= 0.0) & (L_tot >= d) & (
            L_tot >= (XF + ZF) * (1.0 - 1e-2)) & (
            ~jnp.isfinite(HF) | ~jnp.isfinite(VF))
        fully_slack = near | bad
        above = jnp.sum(L) - jnp.cumsum(L)   # line length above each seg
        hang = jnp.clip(ZF - above, 0.0, L)  # hanging part per segment
        V_hang = jnp.sum(w * hang) + jnp.sum(jnp.where(above < ZF, Wp, 0.0))
        HF = jnp.where(fully_slack, 0.0, HF)
        VF = jnp.where(fully_slack, V_hang, VF)
    return HF, VF


# ---------------- bridle junctions ----------------

def _bridle_leg_force(p, end_world, kind, L, EA, w, Wp, cb=0.0):
    """Force exerted ON the junction at ``p`` by one bridle leg, plus the
    leg's end tensions.  kind 0: anchor leg (junction on top, seabed
    catenary with friction coefficient ``cb`` on the grounded bottom
    segment); kind 1: vessel leg (junction on the bottom, fully
    suspended); kind < 0: inert padding (solved on a fixed benign
    geometry so no NaN can leak into the masked sum).

    Returns (F_on_junction[3], T_top, T_bot, HF, VF) — T_top at the leg's
    upper end (junction for anchor legs, fairlead for vessel legs), T_bot
    at its lower end (anchor / junction), both zero for padded legs."""
    active = kind >= 0.0
    is_anchor = kind == 0.0
    # low/high ends of the bottom->top catenary
    low = jnp.where(is_anchor, end_world, p)
    high = jnp.where(is_anchor, p, end_world)
    dxy = high[:2] - low[:2]
    XF = jnp.sqrt(jnp.sum(dxy**2))
    ZF = high[2] - low[2]
    # padded legs solve a fixed well-conditioned configuration
    XF = jnp.where(active, XF, 10.0)
    ZF = jnp.where(active, ZF, 5.0)
    H_a, V_a = catenary_solve(XF, ZF, L, EA, w, Wp, cb)        # seabed
    H_s, V_s = catenary_solve(XF, ZF, L, EA, w, Wp, seabed=False)
    HF = jnp.where(is_anchor, H_a, H_s)
    VF = jnp.where(is_anchor, V_a, V_s)
    u = dxy / jnp.maximum(XF, 1e-9)
    VA = VF - jnp.sum(w * L) - jnp.sum(Wp)
    # anchor leg: junction is the top (fairlead) end -> pulled down/toward
    # the anchor; vessel leg: junction is the bottom end -> the leg pulls
    # it up/toward the fairlead with the bottom-end tension (HF, VA)
    F = jnp.where(
        is_anchor,
        jnp.array([-HF * u[0], -HF * u[1], -VF]),
        jnp.array([HF * u[0], HF * u[1], VA]),
    )
    T_top = jnp.sqrt(HF**2 + VF**2)
    # bottom-end tension: suspended -> hypot(HF, VA); grounded anchor end
    # -> horizontal only, friction-decayed along the grounded length
    # (MoorPy's CB branch, same expression as line_tensions)
    w0 = w[0] if w.ndim else w
    L0 = L[0] if L.ndim else L
    Vb = VF - (jnp.sum(w * L) + jnp.sum(Wp) - w0 * L0)
    LB = jnp.clip(L0 - Vb / w0, 0.0, L0)
    HA = jnp.maximum(HF - cb * w0 * LB, 0.0)
    # vessel legs are fully suspended: VA < 0 is sag below the junction,
    # where the bottom tension is still hypot (only anchor legs ground)
    T_bot = jnp.where(is_anchor & (VA < 0), HA, jnp.sqrt(HF**2 + VA**2))
    return (jnp.where(active, F, 0.0), jnp.where(active, T_top, 0.0),
            jnp.where(active, T_bot, 0.0), HF, VF)


def _solve_bridle_junction(r6, bridle, iters=400):
    """Equilibrium position of one bridle junction: Newton on the 3-DOF
    force balance of its legs + junction weight.  The converged position
    is stop-gradient'ed and polished with one differentiable Newton step,
    so downstream jacfwd (stiffness, tension Jacobians) gets the
    implicit-function derivative without unrolling the loop.

    Returns (p[3], ends_world[K, 3], resid) where ``resid`` is the final
    force-balance residual relative to the legs' natural force scale —
    callers surface it so an iteration-capped exit cannot silently feed
    an unconverged junction into forces and stiffnesses."""
    kind, ends, L, EA, w, Wp, cb, Wj, p0 = bridle
    R = rotation_matrix(r6[3], r6[4], r6[5])
    ends_world = jnp.where(
        (kind == 1.0)[:, None],
        r6[:3] + jnp.einsum("ij,kj->ki", R, ends),
        ends,
    )

    def net(p):
        F = jax.vmap(
            lambda e, kd, Lk, EAk, wk, Wpk, cbk: _bridle_leg_force(
                p, e, kd, Lk, EAk, wk, Wpk, cbk)[0],
        )(ends_world, kind, L, EA, w, Wp, cb)
        return jnp.sum(F, axis=0) + jnp.array([0.0, 0.0, -Wj])

    jac = jax.jacfwd(net)
    # residual tolerance scaled by the legs' weight (the natural force
    # scale of the junction balance)
    f_scale = jnp.sum(jnp.sum(w * L, axis=-1) + jnp.sum(Wp, axis=-1)) + \
        jnp.abs(Wj) + 1.0
    tol = 1e-6 * f_scale

    def cond(state):
        i, p, lam, err = state
        return (i < iters) & (err > tol)

    def body(state):
        i, p, lam, _ = state
        F = net(p)
        n0 = jnp.max(jnp.abs(F))
        J = jac(p)
        # adaptive Levenberg-Marquardt: the equilibrium often sits within
        # centimetres of a leg's slack/taut stiffness kink (force slope
        # jumps ~EA/L there), where a plain Newton zigzags on the
        # ill-conditioned soft directions; rejected steps raise the
        # damping (gradient-descent-like, short steps), accepted steps
        # lower it back toward Newton
        JtJ = J.T @ J
        mu = lam * jnp.trace(JtJ) / 3.0
        dp = jnp.linalg.solve(
            JtJ + mu * jnp.eye(3, dtype=p.dtype), -J.T @ F)
        dp = jnp.clip(dp, -8.0, 8.0)
        n1 = jnp.max(jnp.abs(net(p + dp)))
        accept = n1 < n0
        p = jnp.where(accept, p + dp, p)
        lam = jnp.clip(jnp.where(accept, lam / 2.0, lam * 2.0),
                       1e-9, 30.0)
        return i + 1, p, lam, jnp.minimum(n1, n0)

    def solve(f, p_init):
        _, p_star, _, _ = jax.lax.while_loop(
            cond, body,
            (jnp.array(0), p_init, jnp.asarray(1e-4, p_init.dtype),
             jnp.asarray(jnp.inf, p_init.dtype)),
        )
        return p_star

    def tangent_solve(g, y):
        return jnp.linalg.solve(jax.jacfwd(g)(jnp.zeros_like(y)), y)

    # custom_root: the primal is the LM loop's converged point untouched
    # (an undamped Newton "polish" at a near-kink root can jump far along
    # the soft directions), with exact implicit-function tangents
    p = jax.lax.custom_root(net, p0, solve, tangent_solve)
    resid = jnp.max(jnp.abs(net(p))) / f_scale
    return p, ends_world, resid


def bridle_forces(r6, bridle):
    """6-DOF body reaction from every bridle group at pose r6, plus per-leg
    tension statistics and the junction convergence signal.

    Returns (f6[6], TA[nB, K], TB[nB, K], resid[nB]):
      TA — each leg's lower-end tension (anchor end for anchor legs,
           friction-decayed when grounded; junction end for vessel legs),
      TB — each leg's upper-end tension (junction end for anchor legs,
           fairlead end for vessel legs); both zero for padded legs,
      resid — each junction's relative force-balance residual (see
           :func:`_solve_bridle_junction`)."""
    kind, ends, L, EA, w, Wp, cb, Wj, p0 = bridle

    def one(kd, e, Lb, EAb, wb, Wpb, cbb, Wjb, p0b):
        p, ends_world, resid = _solve_bridle_junction(
            r6, (kd, e, Lb, EAb, wb, Wpb, cbb, Wjb, p0b))
        R = rotation_matrix(r6[3], r6[4], r6[5])

        def leg(e_w, e_body, kdk, Lk, EAk, wk, Wpk, cbk):
            _, T_top, T_bot, HF, VF = _bridle_leg_force(
                p, e_w, kdk, Lk, EAk, wk, Wpk, cbk)
            # vessel legs pull the body at their fairlead
            dxy = e_w[:2] - p[:2]
            u = dxy / jnp.maximum(jnp.sqrt(jnp.sum(dxy**2)), 1e-9)
            F3 = jnp.where(
                kdk == 1.0,
                jnp.array([-HF * u[0], -HF * u[1], -VF]),
                jnp.zeros(3),
            )
            arm = jnp.einsum("ij,j->i", R, e_body)
            f6 = translate_force_3to6(F3, arm)
            return f6, T_bot, T_top

        f6_legs, TA, TB = jax.vmap(leg)(
            ends_world, e, kd, Lb, EAb, wb, Wpb, cbb)
        return jnp.sum(f6_legs, axis=0), TA, TB, resid

    f6_all, TA_all, TB_all, resid = jax.vmap(one)(
        kind, ends, L, EA, w, Wp, cb, Wj, p0)
    return jnp.sum(f6_all, axis=0), TA_all, TB_all, resid


# ---------------- system-level forces ----------------

def line_forces(r6, anchors, rFair, L, EA, w, Wp=None, cb=None,
                bridles=None):
    """6-DOF mooring reaction on the body at pose r6, plus per-line fairlead
    force vectors and tension components.  Segment arrays are [nL, S]
    (anchor->fairlead; S=1 for simple lines).

    Returns (f6[6], HF[nL], VF[nL]).
    """
    if Wp is None:
        Wp = jnp.zeros_like(L)
    if cb is None:
        cb = jnp.zeros_like(L[..., 0] if L.ndim > 1 else L)
    R = rotation_matrix(r6[3], r6[4], r6[5])
    arm = jnp.einsum("ij,lj->li", R, rFair)          # rotated fairlead offsets
    p = r6[:3] + arm                                  # fairlead world positions
    dxy = p[:, :2] - anchors[:, :2]
    XF = jnp.sqrt(jnp.sum(dxy**2, axis=1))
    ZF = p[:, 2] - anchors[:, 2]
    HF, VF = jax.vmap(catenary_solve)(XF, ZF, L, EA, w, Wp, cb)
    # vertical-line guard: direction is irrelevant when XF ~ 0 since HF ~ 0
    u = dxy / jnp.maximum(XF, 1e-9)[:, None]
    F3 = jnp.stack([-HF * u[:, 0], -HF * u[:, 1], -VF], axis=1)  # [nL,3]
    f6 = jnp.sum(translate_force_3to6(F3, arm), axis=0)
    if bridles is not None:
        f6 = f6 + bridle_forces(r6, bridles)[0]
    return f6, HF, VF


def _line_tensions_resid(r6, anchors, rFair, L, EA, w, Wp=None, cb=None,
                         bridles=None):
    """:func:`line_tensions` plus the worst bridle-junction residual from
    the SAME bridle solve (so :func:`case_mooring` does not trace a second
    junction LM loop just to read the convergence signal)."""
    if Wp is None:
        Wp = jnp.zeros_like(L)
    _, HF, VF = line_forces(r6, anchors, rFair, L, EA, w, Wp, cb)
    # vertical tension at the anchor end of the composite line (1-D legacy
    # [nL] inputs are per-line scalars — summing axis -1 would total ALL
    # lines' weights)
    Lw = w * L
    W = (Lw if Lw.ndim == 1 else jnp.sum(Lw, axis=-1)) + (
        Wp if Wp.ndim == 1 else jnp.sum(Wp, axis=-1))
    VA = VF - W
    TB = jnp.sqrt(HF**2 + VF**2)
    # grounded case: seabed friction decays the horizontal tension along
    # the grounded length, HA = max(HF - cb w0 LB, 0) (MoorPy's CB branch)
    w0 = w if w.ndim == 1 else w[:, 0]
    L0 = L if L.ndim == 1 else L[:, 0]
    Vb = VF - (W - w0 * L0)    # vertical tension atop the bottom segment
    LB = jnp.clip(L0 - Vb / w0, 0.0, L0)
    cb_arr = jnp.zeros_like(HF) if cb is None else cb
    HA = jnp.maximum(HF - cb_arr * w0 * LB, 0.0)
    TA = jnp.where(VA >= 0, jnp.sqrt(HF**2 + VA**2), HA)
    resid = jnp.zeros((), L.dtype)
    if bridles is not None:
        _, TA_b, TB_b, resid_b = bridle_forces(r6, bridles)
        TA = jnp.concatenate([TA, TA_b.reshape(-1)])
        TB = jnp.concatenate([TB, TB_b.reshape(-1)])
        resid = jnp.max(resid_b)
    return jnp.concatenate([TA, TB]), resid


def line_tensions(r6, anchors, rFair, L, EA, w, Wp=None, cb=None,
                  bridles=None):
    """End tensions [TA..., TB...] (anchor ends first, then fairlead ends),
    matching MoorPy's getTensions ordering consumed at reference
    raft/raft_model.py:273-283.  When the system has bridles, each bridle
    leg contributes its own (TA, TB) pair after the trunk lines — the
    reference consumes MoorPy tensions for *every* line object, and the
    crow's-foot legs are routinely the tension-critical ones:

        [TA_line 0..nL, TA_leg (b,k) row-major ..., TB_line ..., TB_leg ...]

    Padded bridle slots report zero at both ends."""
    return _line_tensions_resid(r6, anchors, rFair, L, EA, w, Wp, cb,
                                bridles)[0]


def body_hydrostatic_force(r6, m, v, rCG, rM, AWP, rho=1025.0, g=9.81):
    """Weight + buoyancy + waterplane heave stiffness of the rigid body,
    with buoyancy applied at the metacenter rM (MoorPy Body convention —
    RAFT pushes m/v/rCG/AWP/rM into the body at raft/raft_fowt.py:309-313)."""
    R = rotation_matrix(r6[3], r6[4], r6[5])
    f6 = translate_force_3to6(
        jnp.array([0.0, 0.0, -m * g], r6.dtype), R @ rCG
    ) + translate_force_3to6(jnp.array([0.0, 0.0, rho * v * g], r6.dtype), R @ rM)
    return f6.at[2].add(-rho * g * AWP * r6[2])


def solve_equilibrium(
    f6_ext, body_props, anchors, rFair, L, EA, w, Wp=None, cb=None,
    bridles=None, rho=1025.0, g=9.81, iters=40, r6_init=None,
    step_tol=1e-8,
):
    """Find the body pose r6 where mooring + hydrostatics + external mean
    loads balance (the reference's ms.solveEquilibrium3 call,
    raft/raft_model.py:347).  Damped Newton with the exact autodiff
    Jacobian, iterated inside a ``while_loop`` until the Newton step is
    below ``step_tol`` (translations: m, rotations: rad) or ``iters`` is
    reached — nothing differentiates *through* this loop
    (:func:`case_mooring` linearizes at the converged pose), so the
    data-dependent trip count is free.

    body_props : (m, v, rCG[3], rM[3], AWP)
    Returns r6[6].
    """
    m, v, rCG, rM, AWP = body_props
    if Wp is None:
        Wp = jnp.zeros_like(L)

    def total_force(r6):
        f_lines, _, _ = line_forces(r6, anchors, rFair, L, EA, w, Wp, cb,
                                    bridles)
        f_body = body_hydrostatic_force(r6, m, v, rCG, rM, AWP, rho, g)
        return f_lines + f_body + f6_ext

    jac = jax.jacfwd(total_force)
    # derive constants from an operand so eager placement follows the system
    # arrays (committed to CPU by MooringSystem.arrays())
    step_cap = jnp.zeros_like(L, shape=(6,)) + jnp.asarray(
        [10.0, 10.0, 10.0, 0.1, 0.1, 0.1], L.dtype
    )
    tol = jnp.asarray(step_tol, L.dtype) + 100 * jnp.finfo(L.dtype).eps

    def cond(state):
        i, r6, err = state
        return (i < iters) & (err > tol)

    def body_fn(state):
        i, r6, err = state
        # freeze converged state: when this system's own step already met
        # the tolerance, stop moving it.  Unbatched this is a no-op (the
        # while_loop's cond has already exited), but under a vmap over
        # systems the loop runs until the SLOWEST lane converges and the
        # masked update keeps every fast lane's answer independent of its
        # batch mates — the property the batched design-prep path
        # (raft_tpu/batched_prep.py) relies on for solo == batched bits.
        active = err > tol
        F = total_force(r6)
        J = jac(r6)
        # tiny Tikhonov damping: an all-slack mooring (every line in the
        # H = 0 closed-form regime) has EXACTLY zero horizontal stiffness
        # — a physically neutral equilibrium whose Jacobian is singular.
        # The corresponding force components are also zero there, so the
        # damped solve correctly returns a zero step in the neutral
        # directions while perturbing well-conditioned systems at the
        # 1e-8 relative level.
        lam = 1e-8 * jnp.max(jnp.abs(jnp.diag(J))) + 1e-30
        dx = jnp.linalg.solve(J + lam * jnp.eye(6, dtype=J.dtype), -F)
        dx = jnp.clip(dx, -step_cap, step_cap)
        dx = jnp.where(active, dx, jnp.zeros_like(dx))
        return (i + 1, r6 + dx,
                jnp.where(active, jnp.max(jnp.abs(dx)), err))

    r0 = jnp.zeros_like(L, shape=(6,)) if r6_init is None else jnp.asarray(r6_init)
    _, r6, _ = jax.lax.while_loop(
        cond, body_fn, (jnp.array(0), r0, jnp.asarray(jnp.inf, L.dtype))
    )
    return r6


def coupled_stiffness(r6, anchors, rFair, L, EA, w, Wp=None, cb=None,
                      bridles=None):
    """Mooring-only 6x6 stiffness C = -d f6_lines / d r6 about pose r6
    (the reference's ms.getCoupledStiffness(lines_only=True),
    raft/raft_model.py:117, :366) — exact forward-mode autodiff through the
    catenary solves instead of MoorPy's finite differencing."""

    def f(r):
        f6, _, _ = line_forces(r, anchors, rFair, L, EA, w, Wp, cb, bridles)
        return f6

    return -jax.jacfwd(f)(r6)


def tension_jacobian(r6, anchors, rFair, L, EA, w, Wp=None, cb=None,
                     bridles=None):
    """J_moor = d tensions / d r6  [2 (nL + nB K), 6] (reference
    raft_model.py:366, consumed for tension FFTs at :273-283); bridle leg
    rows differentiate through the junction equilibrium via its
    custom_root implicit tangents."""
    return jax.jacfwd(
        lambda r: line_tensions(r, anchors, rFair, L, EA, w, Wp, cb,
                                bridles)
    )(r6)


def case_mooring(f6_ext, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w,
                 Wp=None, cb=None, bridles=None, rho=1025.0, g=9.81,
                 yawstiff=0.0, equilibrium_fn=None):
    """One-shot per-case mooring analysis: equilibrium pose plus all the
    linearized quantities the dynamics solve consumes
    (reference raft/raft_model.py:332-392 calcMooringAndOffsets).

    Designed to be jitted once and vmapped over the case axis of ``f6_ext``
    (per-case mean aero loads) — every Model.analyze_cases call then reuses
    the same compiled executable instead of retracing the autodiff-through-
    catenary graphs per case.

    Returns (r6, C_moor, F_moor, T_moor, J_moor, moor_resid) —
    ``moor_resid`` is the worst bridle-junction force-balance residual at
    the converged pose (0 when the system has no bridles), surfaced so an
    iteration-capped junction solve cannot feed forces silently (the
    dynamics path reports ``converged`` the same way).
    """
    if Wp is None:
        Wp = jnp.zeros_like(L)
    # equilibrium_fn: signature-compatible replacement for
    # solve_equilibrium — the reverse-mode path injects the IFT-adjoint
    # variant (raft_tpu/grad/fixed_point.py) here without touching the
    # forward arithmetic (its primal IS this default).
    solve = solve_equilibrium if equilibrium_fn is None else equilibrium_fn
    r6 = solve(
        f6_ext, (m, v, rCG, rM, AWP), anchors, rFair, L, EA, w, Wp, cb,
        bridles, rho=rho, g=g
    )
    C_moor = coupled_stiffness(r6, anchors, rFair, L, EA, w, Wp, cb, bridles)
    C_moor = C_moor.at[5, 5].add(yawstiff)
    F_moor = line_forces(r6, anchors, rFair, L, EA, w, Wp, cb, bridles)[0]
    T_moor, moor_resid = _line_tensions_resid(
        r6, anchors, rFair, L, EA, w, Wp, cb, bridles)
    J_moor = tension_jacobian(r6, anchors, rFair, L, EA, w, Wp, cb, bridles)
    return r6, C_moor, F_moor, T_moor, J_moor, moor_resid


# bridle-junction convergence reporting shared by every consumer (Model's
# per-case path and both fused sweeps): the junction solver iterates to
# 1e-6 x the legs' force scale, so a relative residual above this is an
# iteration-capped exit worth surfacing (warn-and-continue semantics,
# like the dynamics `converged` output)
BRIDLE_RESID_TOL = 1e-5


def warn_bridle_residual(moor_resid, label="case"):
    """Warn (via the package logger, the same diagnostic channel as the
    BEM panel-limit warning) for every leading-axis entry of
    ``moor_resid`` (scalars per case/design; trailing axes reduced by
    max) whose bridle force-balance residual exceeds
    :data:`BRIDLE_RESID_TOL`."""
    from raft_tpu.utils.profiling import logger

    r = np.asarray(moor_resid)
    if r.ndim == 0:
        r = r[None]
    r = r.reshape(len(r), -1).max(axis=1)
    for i in np.nonzero(r > BRIDLE_RESID_TOL)[0]:
        logger.warning(
            "%s %d: bridle junction solve residual %.2e exceeds "
            "tolerance; mooring linearization may be off.",
            label, i + 1, r[i],
        )


# ---------------- cached jitted entry points ----------------
#
# jit caches executables on the *function object*, so a `jax.jit` taken on a
# fresh closure inside each Model instance recompiles the whole
# autodiff-through-catenary graph per model (~10 s on CPU).  Repeated model
# construction — the design-sweep inner loop — must instead reuse one
# compiled executable, so the jitted wrappers live here at module level,
# keyed only by the (hashable) physics scalars; array shapes are handled by
# jit's own cache.

def _case_mooring_flat(rho, g, yawstiff):
    """Positional-argument :func:`case_mooring` wrapper shared by the
    cached batch entry points below."""

    def one(f6, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w, Wp, cb,
            bridles):
        return case_mooring(
            f6, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w, Wp, cb,
            bridles, rho=rho, g=g, yawstiff=yawstiff,
        )

    return one


@lru_cache(maxsize=None)
def case_mooring_batch_fn(rho, g, yawstiff):
    """Jitted :func:`case_mooring`, vmapped over the case axis of ``f6_ext``
    (body properties and line arrays are shared across cases)."""
    one = _case_mooring_flat(rho, g, yawstiff)
    return jax.jit(jax.vmap(one, in_axes=(0,) + (None,) * 13))


@lru_cache(maxsize=None)
def case_mooring_design_batch_fn(rho, g, yawstiff):
    """Jitted :func:`case_mooring` vmapped over designs *and* cases:
    f6_ext[nd, nc, 6], body props [nd,...], line arrays [nd, nL, ...] —
    the sweep driver's batched mooring equilibrium (one compile serves the
    whole sweep)."""
    one = _case_mooring_flat(rho, g, yawstiff)
    per_design = jax.vmap(one, in_axes=(0,) + (None,) * 13)
    return jax.jit(jax.vmap(per_design))


@lru_cache(maxsize=None)
def unloaded_mooring_fn():
    """Jitted (C_moor0, F_moor0) at a given pose — the undisplaced
    linearization consumed by analyze_unloaded (reference
    raft/raft_model.py:117-118)."""

    def f(r6, anchors, rFair, L, EA, w, Wp, cb, bridles=None):  # noqa: D401
        C0 = coupled_stiffness(r6, anchors, rFair, L, EA, w, Wp, cb, bridles)
        F0 = line_forces(r6, anchors, rFair, L, EA, w, Wp, cb, bridles)[0]
        return C0, F0

    return jax.jit(f)
