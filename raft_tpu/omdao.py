"""OpenMDAO-compatible wrapper for WEIS integration.

Re-provides the reference's ``RAFT_OMDAO`` component surface
(reference raft/omdao_raft.py:10-682): the same flat typed input/output
names, the same options dictionaries (modeling/turbine/members/mooring/
analysis), the same DLC spectral-wind filtering, and the same aggregate
outputs (``Max_Offset``, ``Max_PtfmPitch``, ``rotor_overspeed``,
``max_tower_base``, OpenFAST-handoff platform properties).

openmdao itself is an *optional* dependency: when installed, ``RAFT_OMDAO``
is a genuine ``om.ExplicitComponent``; when absent, a minimal in-package
shim provides the same ``add_input/add_output/compute`` contract so the
component remains constructible and testable (the dual-path equivalence
test pattern of reference tests/test_omdao_*.py) without the framework.

The I/O declaration is table-driven rather than a transliteration of the
reference's 250-line add_input sequence — the names and shapes are the
compatibility contract, the code is not.
"""

import os
import pickle

import numpy as np

try:
    import openmdao.api as om

    _HAVE_OM = True
    _ComponentBase = om.ExplicitComponent
except ImportError:  # pragma: no cover - exercised when openmdao installed
    _HAVE_OM = False

    class _ShimOptions(dict):
        def declare(self, name, default=None, **kw):
            self.setdefault(name, default)

    class _VarDict(dict):
        """Mimics OM's vector assignment: setting a declared array variable
        broadcasts into the existing storage (so scalar -> np.zeros(3)
        behaves as in openmdao); incompatible shapes fall back to replace."""

        def __setitem__(self, key, val):
            cur = self.get(key)
            if isinstance(cur, np.ndarray) and cur.shape:
                try:
                    cur[...] = val
                    return
                except (ValueError, TypeError):
                    pass
            super().__setitem__(key, val)

    class _ComponentBase:
        """Duck-typed stand-in for om.ExplicitComponent: holds declared
        variables in plain dicts and runs compute() directly."""

        def __init__(self):
            self.options = _ShimOptions()
            self._inputs = _VarDict()
            self._outputs = _VarDict()
            self._discrete_inputs = {}
            self._discrete_outputs = {}
            self._meta = {}
            self.initialize()

        def add_input(self, name, val=0.0, units=None, desc=""):
            self._inputs[name] = np.array(val, dtype=float)
            self._meta[name] = {"units": units, "desc": desc, "kind": "input"}

        def add_discrete_input(self, name, val=None, desc=""):
            self._discrete_inputs[name] = val
            self._meta[name] = {"desc": desc, "kind": "discrete_input"}

        def add_output(self, name, val=0.0, units=None, desc=""):
            self._outputs[name] = np.array(val, dtype=float)
            self._meta[name] = {"units": units, "desc": desc, "kind": "output"}

        def add_discrete_output(self, name, val=None, desc=""):
            self._discrete_outputs[name] = val
            self._meta[name] = {"desc": desc, "kind": "discrete_output"}

        def list_outputs(self, out_stream=None, all_procs=True):
            return [(k, {"val": v}) for k, v in self._outputs.items()]

        def set_val(self, name, val):
            if name in self._discrete_inputs:
                self._discrete_inputs[name] = val
            else:
                self._inputs[name] = np.array(val, dtype=float)

        def get_val(self, name):
            if name in self._outputs:
                return self._outputs[name]
            if name in self._discrete_outputs:
                return self._discrete_outputs[name]
            if name in self._inputs:
                return self._inputs[name]
            return self._discrete_inputs[name]

        def run(self):
            self.compute(
                self._inputs, self._outputs,
                self._discrete_inputs, self._discrete_outputs,
            )
            return self._outputs

        def declare_partials(self, of, wrt, method="exact"):
            pass

        def initialize(self):
            pass


NDIM = 3
NDOF = 6

_STAT_CHANNELS = [
    "surge", "sway", "heave", "roll", "pitch", "yaw",
    "AxRNA", "Mbase", "omega", "torque", "power", "bPitch", "Tmoor",
]
_STATS = ["avg", "std", "max", "PSD", "DEL"]

# differentiable design-scale inputs (modeling option ``derivatives``) and
# the aggregate outputs they carry exact partials for, mapped onto the
# traced parametric pipeline's parameter/metric names
_SCALE_INPUTS = {
    "design_scale_draft": "draft",
    "design_scale_ballast": "ballast",
    "design_scale_col_diam": "col_diam",
    "design_scale_line_length": "line_length",
}
_PARTIAL_OUTPUTS = {
    # the WEIS optimization constraints (omdao compute aggregates)
    "Max_PtfmPitch": "pitch_max_deg",
    "Max_Offset": "offset_max",
    "max_tower_base": "Mbase_max",
}


def _check_derivative_options(modeling_opt):
    """The traced parametric twin behind the exact partials models
    Morison-only hydro with no ballast trim (raft_tpu/parametric.py —
    see the restriction list next to its bridled-mooring
    NotImplementedError).  compute() honors run_native_BEM and
    trim_ballast, so combining either with ``derivatives`` would hand an
    optimizer a Jacobian of a DIFFERENT physics path than the outputs it
    constrains — refuse loudly instead of silently diverging
    (ADVICE r5 medium)."""
    if modeling_opt.get("run_native_BEM"):
        raise NotImplementedError(
            "modeling option 'derivatives' cannot be combined with "
            "'run_native_BEM': the traced parametric pipeline models "
            "Morison-only hydrodynamics, so the declared exact partials "
            "would be derivatives of a different physics path than "
            "compute()'s BEM-based outputs"
        )
    if modeling_opt.get("trim_ballast", 0):
        raise NotImplementedError(
            "modeling option 'derivatives' cannot be combined with "
            "trim_ballast != 0: the traced parametric pipeline has no "
            "ballast-trim step, so the declared exact partials would be "
            "derivatives of an untrimmed design while compute() reports "
            "the trimmed one"
        )

_PROPERTY_OUTPUTS = [
    # (name, shape factory, units)  — shapes use closures over option counts
    ("tower mass", lambda o: 0.0, "kg"),
    ("tower CG", lambda o: np.zeros(NDIM), "m"),
    ("substructure mass", lambda o: 0.0, "kg"),
    ("substructure CG", lambda o: np.zeros(NDIM), "m"),
    ("shell mass", lambda o: 0.0, "kg"),
    ("ballast mass", lambda o: np.zeros(o["n_ballast_type"]), "m"),
    ("ballast densities", lambda o: np.zeros(o["n_ballast_type"]), "kg"),
    ("total mass", lambda o: 0.0, "kg"),
    ("total CG", lambda o: np.zeros(NDIM), "m"),
    ("roll inertia at subCG", lambda o: np.zeros(NDIM), "kg*m**2"),
    ("pitch inertia at subCG", lambda o: np.zeros(NDIM), "kg*m**2"),
    ("yaw inertia at subCG", lambda o: np.zeros(NDIM), "kg*m**2"),
    ("Buoyancy (pgV)", lambda o: 0.0, "N"),
    ("Center of Buoyancy", lambda o: np.zeros(NDIM), "m"),
    ("C stiffness matrix", lambda o: np.zeros((NDOF, NDOF)), "Pa"),
    ("F_lines0", lambda o: np.zeros(o["nconnections"]), "N"),
    ("C_lines0", lambda o: np.zeros((NDOF, NDOF)), "Pa"),
    ("M support structure", lambda o: np.zeros((NDOF, NDOF)), "kg"),
    ("A support structure", lambda o: np.zeros((NDOF, NDOF)), None),
    ("C support structure", lambda o: np.zeros((NDOF, NDOF)), "Pa"),
]

_RESPONSE_OUTPUTS = [
    ("frequencies", "Hz"), ("wave elevation", "m"),
    ("surge RAO", "m"), ("sway RAO", "m"), ("heave RAO", "m"),
    ("pitch RAO", "rad"), ("roll RAO", "rad"), ("yaw RAO", "rad"),
    ("nacelle acceleration", "m/s**2"),
]


class RAFT_OMDAO(_ComponentBase):
    """RAFT OpenMDAO wrapper (TPU-native backend).

    Extra modeling options over the reference: ``device`` ('tpu' | 'cpu' |
    'gpu' — selects the backend the batched case solve runs on, with the
    precision default following that backend), ``precision``
    ('float32' | 'float64'), and ``run_native_BEM`` to use the in-package
    panel solver where the reference shells out to HAMS.

    Engine mode: modeling option ``engine`` (a live Engine/Router object)
    or ``engine_endpoint`` (a ``host:port`` string for a serve HTTP tier)
    routes the batched dynamics solve of every compute() through a
    RUNNING serve engine instead of compiling a pipeline in this process
    — an optimization driver then shares the engine's warmed executables
    (and its continuous-batching lane packing) with every other client.
    Statics, BEM and response metrics stay local; the served solve runs
    the engine's canonical fixed-shape bucket program — bit-identical to
    the same design served interactively (tests/test_serve_sweep.py) and
    equal to the in-process dispatch to float64 round-off.
    """

    def initialize(self):
        self.options.declare("modeling_options")
        self.options.declare("turbine_options")
        self.options.declare("mooring_options")
        self.options.declare("member_options")
        self.options.declare("analysis_options")

    # ------------------------------------------------------------- setup
    def setup(self):
        modeling_opt = self.options["modeling_options"]
        analysis_options = self.options["analysis_options"]
        nfreq = modeling_opt["nfreq"]
        n_cases = modeling_opt["n_cases"]

        turbine_opt = self.options["turbine_options"]
        tnpts = turbine_opt["npts"]
        n_gain = turbine_opt["PC_GS_n"]
        n_span = turbine_opt["n_span"]
        n_aoa = turbine_opt["n_aoa"]
        n_Re = turbine_opt["n_Re"]
        n_tab = turbine_opt["n_tab"]
        n_pc = turbine_opt["n_pc"]
        n_af = turbine_opt["n_af"]
        n_af_span = len(turbine_opt["af_used_names"])

        members_opt = self.options["member_options"]
        mooring_opt = self.options["mooring_options"]
        nlines = mooring_opt["nlines"]
        nline_types = mooring_opt["nline_types"]
        nconnections = mooring_opt["nconnections"]

        # ---- turbine & tower inputs
        for name, units, desc in [
            ("turbine_mRNA", "kg", "RNA mass"),
            ("turbine_IxRNA", "kg*m**2", "RNA inertia about shaft axis"),
            ("turbine_IrRNA", "kg*m**2", "RNA inertia about y/z axes"),
            ("turbine_xCG_RNA", "m", "x location of RNA center of mass"),
            ("turbine_hHub", "m", "hub height above water line"),
            ("turbine_overhang", "m", "rotor apex overhang"),
            ("turbine_Fthrust", "N", "temporary thrust force"),
            ("turbine_yaw_stiffness", "N*m", "additional yaw stiffness"),
        ]:
            self.add_input(name, val=0.0, units=units, desc=desc)

        self.add_input("turbine_tower_rA", val=np.zeros(NDIM), units="m")
        self.add_input("turbine_tower_rB", val=np.zeros(NDIM), units="m")
        self.add_input("turbine_tower_gamma", val=0.0, units="deg")
        self.add_input("turbine_tower_stations", val=np.zeros(tnpts))
        tower_d_shape = (
            0.0 if turbine_opt["scalar_diameters"]
            else np.zeros(2 * tnpts) if turbine_opt["shape"] == "rect"
            else np.zeros(tnpts)
        )
        self.add_input("turbine_tower_d", val=tower_d_shape, units="m")
        self.add_input(
            "turbine_tower_t",
            val=0.0 if turbine_opt["scalar_thicknesses"] else np.zeros(tnpts),
            units="m",
        )
        coeff_shape = 0.0 if turbine_opt["scalar_coefficients"] else np.zeros(tnpts)
        for c in ["Cd", "Ca", "CdEnd", "CaEnd"]:
            self.add_input(f"turbine_tower_{c}", val=coeff_shape)
        self.add_input("turbine_tower_rho_shell", val=0.0, units="kg/m**3")

        # ---- control inputs
        self.add_input("rotor_PC_GS_angles", val=np.zeros(n_gain), units="rad")
        self.add_input("rotor_PC_GS_Kp", val=np.zeros(n_gain), units="s")
        self.add_input("rotor_PC_GS_Ki", val=np.zeros(n_gain))
        self.add_input("Fl_Kp", val=0.0)
        self.add_input("rotor_inertia", val=0.0, units="kg*m**2")
        self.add_input("rotor_TC_VS_Kp", val=0.0, units="s")
        self.add_input("rotor_TC_VS_Ki", val=0.0)

        # ---- blade / rotor inputs
        self.add_discrete_input("nBlades", val=3)
        self.add_input("tilt", val=0.0, units="deg")
        self.add_input("precone", val=0.0, units="deg")
        self.add_input("wind_reference_height", val=0.0, units="m")
        self.add_input("hub_radius", val=0.0, units="m")
        self.add_input("gear_ratio", val=1.0)
        for name in ["blade_r", "blade_chord", "blade_theta",
                     "blade_precurve", "blade_presweep"]:
            units = "deg" if name == "blade_theta" else "m"
            self.add_input(name, val=np.zeros(n_span), units=units)
        self.add_input("blade_Rtip", val=0.0, units="m")
        self.add_input("blade_precurveTip", val=0.0, units="m")
        self.add_input("blade_presweepTip", val=0.0, units="m")

        # ---- airfoils
        self.add_discrete_input("airfoils_name", val=n_af * [""])
        self.add_input("airfoils_position", val=np.zeros(n_af_span))
        self.add_input("airfoils_r_thick", val=np.zeros(n_af))
        self.add_input("airfoils_aoa", val=np.zeros(n_aoa), units="rad")
        for c in ["cl", "cd", "cm"]:
            self.add_input(
                f"airfoils_{c}", val=np.zeros((n_af, n_aoa, n_Re, n_tab))
            )
        self.add_input("rotor_powercurve_v", val=np.zeros(n_pc), units="m/s")
        self.add_input(
            "rotor_powercurve_omega_rpm", val=np.zeros(n_pc), units="rpm"
        )
        self.add_input("rotor_powercurve_pitch", val=np.zeros(n_pc), units="deg")
        self.add_input("rho_air", val=1.225, units="kg/m**3")
        self.add_input("rho_water", val=1025.0, units="kg/m**3")
        self.add_input("mu_air", val=1.81e-5, units="kg/(m*s)")
        self.add_input("shear_exp", val=0.2)
        self.add_input("rated_rotor_speed", val=0.0, units="rpm")

        # ---- DLCs
        self.add_discrete_input("raft_dlcs", val=[[]] * n_cases)
        self.add_discrete_input(
            "raft_dlcs_keys",
            val=["wind_speed", "wind_heading", "turbulence", "turbine_status",
                 "yaw_misalign", "wave_spectrum", "wave_period", "wave_height",
                 "wave_heading"],
        )

        # ---- platform members
        for i in range(members_opt["nmembers"]):
            p = f"platform_member{i+1}_"
            npts = members_opt["npts"][i]
            shape = members_opt["shape"][i]
            self.add_input(p + "heading", val=np.zeros(members_opt["nreps"][i]),
                           units="deg")
            self.add_input(p + "rA", val=np.zeros(NDIM), units="m")
            self.add_input(p + "rB", val=np.zeros(NDIM), units="m")
            self.add_input(p + "s_ghostA", val=0.0)
            self.add_input(p + "s_ghostB", val=1.0)
            self.add_input(p + "gamma", val=0.0, units="deg")
            self.add_discrete_input(p + "potMod", val=False)
            self.add_input(p + "stations", val=np.zeros(npts))
            if members_opt["scalar_diameters"][i]:
                d_val = [0.0, 0.0] if shape == "rect" else 0.0
            else:
                d_val = np.zeros([npts, 2]) if shape == "rect" else np.zeros(npts)
            self.add_input(p + "d", val=d_val, units="m")
            self.add_input(
                p + "t",
                val=0.0 if members_opt["scalar_thicknesses"][i]
                else np.zeros(npts),
                units="m",
            )
            cshape = (
                0.0 if members_opt["scalar_coefficients"][i] else np.zeros(npts)
            )
            for c in ["Cd", "Ca", "CdEnd", "CaEnd"]:
                self.add_input(p + c, val=cshape)
            self.add_input(p + "rho_shell", val=0.0, units="kg/m**3")
            nlfill = members_opt["npts_lfill"][i]
            self.add_input(p + "l_fill", val=np.zeros(nlfill), units="m")
            self.add_input(p + "rho_fill", val=np.zeros(nlfill),
                           units="kg/m**3")
            ncaps = members_opt["ncaps"][i]
            self.add_input(p + "cap_stations", val=np.zeros(ncaps))
            self.add_input(p + "cap_t", val=np.zeros(ncaps), units="m")
            self.add_input(p + "cap_d_in", val=np.zeros(ncaps), units="m")
            self.add_input(p + "ring_spacing", val=0.0)
            self.add_input(p + "ring_t", val=0.0, units="m")
            self.add_input(p + "ring_h", val=0.0, units="m")

        # ---- mooring
        self.add_input("mooring_water_depth", val=0.0, units="m")
        for i in range(nconnections):
            p = f"mooring_point{i+1}_"
            self.add_discrete_input(p + "name", val=f"line{i+1}")
            self.add_discrete_input(p + "type", val="fixed")
            self.add_input(p + "location", val=np.zeros(NDIM), units="m")
        for i in range(nlines):
            p = f"mooring_line{i+1}_"
            self.add_discrete_input(p + "endA", val="default")
            self.add_discrete_input(p + "endB", val="default")
            self.add_discrete_input(p + "type", val="mooring_line_type1")
            self.add_input(p + "length", val=0.0, units="m")
        for i in range(nline_types):
            p = f"mooring_line_type{i+1}_"
            self.add_discrete_input(p + "name", val="default")
            self.add_input(p + "diameter", val=0.0, units="m")
            self.add_input(p + "mass_density", val=0.0, units="kg/m**3")
            for fld in ["stiffness", "breaking_load", "cost",
                        "transverse_added_mass", "tangential_added_mass",
                        "transverse_drag", "tangential_drag"]:
                self.add_input(p + fld, val=0.0)

        # ---- outputs
        opt_counts = {
            "n_ballast_type": members_opt["n_ballast_type"],
            "nconnections": nconnections,
        }
        for name, shape_fn, units in _PROPERTY_OUTPUTS:
            self.add_output(
                "properties_" + name, val=shape_fn(opt_counts), units=units
            )
        for name, units in _RESPONSE_OUTPUTS:
            self.add_output(
                "response_" + name, val=np.zeros(nfreq), units=units
            )
        for n in _STAT_CHANNELS:
            for s in _STATS:
                if s == "DEL" and n not in ("Tmoor", "Mbase"):
                    continue
                if n == "Tmoor":
                    val = (np.zeros((n_cases, 2 * nlines)) if s != "PSD"
                           else np.zeros((n_cases, 2 * nlines, nfreq)))
                else:
                    val = (np.zeros(n_cases) if s != "PSD"
                           else np.zeros((n_cases, nfreq)))
                units = {
                    "surge": "m", "sway": "m", "heave": "m",
                    "roll": "rad", "pitch": "rad", "yaw": "rad",
                    "AxRNA": "m/s/s", "Mbase": "N*m",
                }.get(n)
                self.add_output(f"stats_{n}_{s}", val=val, units=units)
        self.add_output("stats_wind_PSD", val=np.zeros((n_cases, nfreq)))
        self.add_output("stats_wave_PSD", val=np.zeros((n_cases, nfreq)))

        # ---- per-case solver health (raft_tpu/health.py SolveReport):
        # replaces the reference's print-only non-convergence WARNING with
        # real outputs an optimizer driver can gate on
        self.add_output("solver_converged", val=np.zeros(n_cases),
                        desc="1.0 where the case's dynamics fixed point "
                             "converged to the tolerance")
        self.add_output("solver_iters", val=np.zeros(n_cases),
                        desc="fixed-point iterations per case")
        self.add_output("solver_nonfinite", val=np.zeros(n_cases),
                        desc="1.0 where a non-finite iterate was "
                             "NaN-quarantined (response frozen at the "
                             "last finite state)")
        self.add_output("solver_recovery_tier", val=np.zeros(n_cases),
                        desc="conditioned-solve recovery tier taken "
                             "(0 baseline, 1 extra refinement, 2 flagged "
                             "Tikhonov)")
        self.add_output("solver_residual", val=np.zeros(n_cases),
                        desc="final relative residual of the 6x6 solves "
                             "(max over frequency)")
        self.add_output("solver_all_healthy", val=0.0,
                        desc="1.0 iff every case converged with no "
                             "NaN-quarantined lane")

        self.add_output("Max_Offset", val=0, units="m")
        self.add_output("heave_avg", val=0, units="m")
        self.add_output("Max_PtfmPitch", val=0, units="deg")
        self.add_output("Std_PtfmPitch", val=0, units="deg")
        self.add_output("max_nacelle_Ax", val=0, units="m/s**2")
        self.add_output("rotor_overspeed", val=0)
        self.add_output("max_tower_base", val=0, units="N*m")

        self.add_output("platform_total_center_of_mass", np.zeros(3), units="m")
        self.add_output("platform_displacement", 0.0, units="m**3")
        self.add_output("platform_mass", 0.0, units="kg")
        self.add_output("platform_I_total", np.zeros(6), units="kg*m**2")

        # ---- differentiable design-scale inputs (beyond the reference:
        # the reference component declares NO partials anywhere, so WEIS
        # finite-differences around it, reference raft/omdao_raft.py).
        # With modeling option ``derivatives`` on, four multiplicative
        # design-trim variables are exposed and the aggregate response
        # outputs get EXACT partials from the traced parametric pipeline
        # (raft_tpu/parametric.py, jax.jacfwd end to end).
        if modeling_opt.get("derivatives"):
            _check_derivative_options(modeling_opt)
            for p in _SCALE_INPUTS:
                self.add_input(p, val=1.0)
            self.declare_partials(
                list(_PARTIAL_OUTPUTS), list(_SCALE_INPUTS),
                method="exact")
        self._param_fn_cache = {}

        self.i_design = 0
        if modeling_opt.get("save_designs"):
            out = os.path.join(
                analysis_options["general"]["folder_output"], "raft_designs"
            )
            os.makedirs(out, exist_ok=True)

    # ------------------------------------------------------ design rebuild
    def _rebuild_design(self, inputs, discrete_inputs):
        """Flat OM inputs -> nested RAFT design dict
        (the inverse of the YAML schema; reference omdao_raft.py:349-599)."""
        modeling_opt = self.options["modeling_options"]
        turbine_opt = self.options["turbine_options"]
        members_opt = self.options["member_options"]
        mooring_opt = self.options["mooring_options"]

        def scal(name):
            return float(np.asarray(inputs[name]).reshape(-1)[0])

        design = {
            "type": ["input dictionary for RAFT"],
            "name": ["spiderfloat"],
            "comments": ["none"],
            "settings": {
                "XiStart": float(modeling_opt["xi_start"]),
                "min_freq": float(modeling_opt["min_freq"]),
                "max_freq": float(modeling_opt["max_freq"]),
                "nIter": int(modeling_opt["nIter"]),
            },
            "site": {
                "water_depth": scal("mooring_water_depth"),
                "rho_air": scal("rho_air"),
                "rho_water": scal("rho_water"),
                "mu_air": scal("mu_air"),
                "shearExp": scal("shear_exp"),
            },
        }

        tower = {
            "name": "tower", "type": 1,
            "rA": inputs["turbine_tower_rA"],
            "rB": inputs["turbine_tower_rB"],
            "shape": turbine_opt["shape"],
            "gamma": inputs["turbine_tower_gamma"],
            "stations": inputs["turbine_tower_stations"],
            "rho_shell": scal("turbine_tower_rho_shell"),
        }
        tower["d"] = (
            scal("turbine_tower_d") if turbine_opt["scalar_diameters"]
            else inputs["turbine_tower_d"]
        )
        tower["t"] = (
            scal("turbine_tower_t") if turbine_opt["scalar_thicknesses"]
            else inputs["turbine_tower_t"]
        )
        for c in ["Cd", "Ca", "CdEnd", "CaEnd"]:
            tower[c] = (
                scal(f"turbine_tower_{c}") if turbine_opt["scalar_coefficients"]
                else inputs[f"turbine_tower_{c}"]
            )

        design["turbine"] = {
            "mRNA": scal("turbine_mRNA"),
            "IxRNA": scal("turbine_IxRNA"),
            "IrRNA": scal("turbine_IrRNA"),
            "xCG_RNA": scal("turbine_xCG_RNA"),
            "hHub": scal("turbine_hHub"),
            "overhang": scal("turbine_overhang"),
            "Fthrust": scal("turbine_Fthrust"),
            "yaw_stiffness": scal("turbine_yaw_stiffness"),
            "gear_ratio": scal("gear_ratio"),
            "nBlades": int(discrete_inputs["nBlades"]),
            "shaft_tilt": scal("tilt"),
            "precone": scal("precone"),
            "Zhub": scal("wind_reference_height"),
            "Rhub": scal("hub_radius"),
            "I_drivetrain": scal("rotor_inertia"),
            "aeroServoMod": int(modeling_opt.get("aeroServoMod", 2)),
            "tower": tower,
            "blade": {
                "geometry": np.c_[
                    inputs["blade_r"], inputs["blade_chord"],
                    inputs["blade_theta"], inputs["blade_precurve"],
                    inputs["blade_presweep"],
                ],
                "Rtip": scal("blade_Rtip"),
                "precurveTip": scal("blade_precurveTip"),
                "presweepTip": scal("blade_presweepTip"),
                "airfoils": list(zip(
                    inputs["airfoils_position"], turbine_opt["af_used_names"]
                )),
            },
            "airfoils": [
                {
                    "name": discrete_inputs["airfoils_name"][i],
                    "relative_thickness": inputs["airfoils_r_thick"][i],
                    "data": np.c_[
                        np.rad2deg(inputs["airfoils_aoa"]),
                        inputs["airfoils_cl"][i, :, 0, 0],
                        inputs["airfoils_cd"][i, :, 0, 0],
                        inputs["airfoils_cm"][i, :, 0, 0],
                    ],
                }
                for i in range(turbine_opt["n_af"])
            ],
            "pitch_control": {
                "GS_Angles": inputs["rotor_PC_GS_angles"],
                "GS_Kp": inputs["rotor_PC_GS_Kp"],
                "GS_Ki": inputs["rotor_PC_GS_Ki"],
                "Fl_Kp": scal("Fl_Kp"),
            },
            "torque_control": {
                "VS_KP": scal("rotor_TC_VS_Kp"),
                "VS_KI": scal("rotor_TC_VS_Ki"),
            },
            "wt_ops": {
                "v": inputs["rotor_powercurve_v"],
                "omega_op": inputs["rotor_powercurve_omega_rpm"],
                "pitch_op": inputs["rotor_powercurve_pitch"],
            },
        }

        # platform members with ghost-segment trimming
        # (reference omdao_raft.py:471-560)
        min_freq_BEM = float(modeling_opt.get(
            "min_freq_BEM", modeling_opt["min_freq"] - 1e-7
        ))
        if min_freq_BEM >= modeling_opt["min_freq"]:
            min_freq_BEM = modeling_opt["min_freq"] - 1e-7
        design["platform"] = {
            "potModMaster": int(modeling_opt["potential_model_override"]),
            "dlsMax": float(modeling_opt["dls_max"]),
            "min_freq_BEM": min_freq_BEM,
            "members": [],
        }
        for i in range(members_opt["nmembers"]):
            p = f"platform_member{i+1}_"
            shape = members_opt["shape"][i]
            rA_0, rB_0 = inputs[p + "rA"], inputs[p + "rB"]
            sA, sB = float(inputs[p + "s_ghostA"]), float(inputs[p + "s_ghostB"])
            s_0 = np.asarray(inputs[p + "stations"], float)
            keep = (s_0 >= sA) & (s_0 <= sB)
            s_grid = np.unique(np.r_[sA, s_0[keep], sB])

            def interp(name):
                return np.interp(s_grid, s_0, np.asarray(inputs[name], float))

            mem = {
                "name": p, "type": i + 2,
                "rA": rA_0 + sA * (rB_0 - rA_0),
                "rB": rA_0 + sB * (rB_0 - rA_0),
                "shape": shape,
                "gamma": float(inputs[p + "gamma"]),
                "potMod": bool(discrete_inputs[p + "potMod"]),
                "stations": s_grid,
                "rho_shell": scal(p + "rho_shell"),
            }
            if members_opt["scalar_diameters"][i]:
                d = inputs[p + "d"]
                mem["d"] = (
                    [np.asarray(d, float)] * len(s_grid) if shape == "rect"
                    else [float(np.asarray(d).reshape(-1)[0])] * len(s_grid)
                )
            else:
                mem["d"] = interp(p + "d")
            mem["t"] = (
                scal(p + "t") if members_opt["scalar_thicknesses"][i]
                else interp(p + "t")
            )
            for c in ["Cd", "Ca", "CdEnd", "CaEnd"]:
                mem[c] = (
                    scal(p + c) if members_opt["scalar_coefficients"][i]
                    else interp(p + c)
                )
            if members_opt["nreps"][i] > 0:
                mem["heading"] = inputs[p + "heading"]
            if members_opt["npts_lfill"][i] > 0:
                mem["l_fill"] = inputs[p + "l_fill"]
                mem["rho_fill"] = inputs[p + "rho_fill"]

            ncaps = members_opt["ncaps"][i]
            ring_spacing = float(inputs[p + "ring_spacing"])
            if ncaps > 0 or ring_spacing > 0:
                height = s_grid[-1] - s_grid[0]
                n_stiff = 0 if ring_spacing == 0.0 else int(
                    np.floor(height / ring_spacing)
                )
                s_ring = (np.arange(1, n_stiff + 0.1) - 0.5) * (
                    ring_spacing / height
                )
                # rect members carry two side lengths per station; rings use
                # the first side as the effective diameter
                d_profile = np.asarray(mem["d"], float)
                if d_profile.ndim > 1:
                    d_profile = d_profile[:, 0]
                d_ring = np.interp(s_ring, s_grid, d_profile)
                t_in = np.asarray(inputs[p + "cap_t"], float)
                if ncaps > 0 and t_in.size > 0:
                    s_cap_0 = np.asarray(inputs[p + "cap_stations"], float)
                    keep_cap = (s_cap_0 >= sA) & (s_cap_0 <= sB)
                    s_cap, isort = np.unique(
                        np.r_[sA, s_cap_0[keep_cap], sB], return_index=True
                    )
                    t_cap = np.r_[t_in[0], t_in[keep_cap], t_in[-1]][isort]
                    di_cap = np.zeros(s_cap.shape)
                    if sA > 0.0:  # no end caps at member joints
                        s_cap, t_cap, di_cap = s_cap[1:], t_cap[1:], di_cap[1:]
                    if sB < 1.0:
                        s_cap, t_cap, di_cap = (s_cap[:-1], t_cap[:-1],
                                                di_cap[:-1])
                else:  # ring stiffeners only, no discrete caps declared
                    s_cap = np.zeros(0)
                    t_cap = np.zeros(0)
                    di_cap = np.zeros(0)
                s_cap = np.r_[s_ring, s_cap]
                t_cap = np.r_[float(inputs[p + "ring_t"]) * np.ones(n_stiff),
                              t_cap]
                di_cap = np.r_[d_ring - 2 * float(inputs[p + "ring_h"]),
                               di_cap]
                if len(s_cap) > 0:
                    order = np.argsort(s_cap)
                    mem["cap_stations"] = s_cap[order]
                    mem["cap_t"] = t_cap[order]
                    mem["cap_d_in"] = di_cap[order]
            design["platform"]["members"].append(mem)

        # mooring
        moor = {
            "water_depth": scal("mooring_water_depth"),
            "points": [], "lines": [], "line_types": [],
            "anchor_types": [{
                "name": "drag_embedment", "mass": 1e3, "cost": 1e4,
                "max_vertical_load": 0.0, "max_lateral_load": 1e5,
            }],
        }
        for i in range(mooring_opt["nconnections"]):
            p = f"mooring_point{i+1}_"
            pt = {
                "name": discrete_inputs[p + "name"],
                "type": discrete_inputs[p + "type"],
                "location": inputs[p + "location"],
            }
            if str(pt["type"]).lower() == "fixed":
                pt["anchor_type"] = "drag_embedment"
            moor["points"].append(pt)
        for i in range(mooring_opt["nlines"]):
            p = f"mooring_line{i+1}_"
            moor["lines"].append({
                "name": f"line{i+1}",
                "endA": discrete_inputs[p + "endA"],
                "endB": discrete_inputs[p + "endB"],
                "type": discrete_inputs[p + "type"],
                "length": inputs[p + "length"],
            })
        for i in range(mooring_opt["nline_types"]):
            p = f"mooring_line_type{i+1}_"
            lt = {"name": discrete_inputs[p + "name"]}
            for fld in ["diameter", "mass_density", "stiffness",
                        "breaking_load", "cost", "transverse_added_mass",
                        "tangential_added_mass", "transverse_drag",
                        "tangential_drag"]:
                lt[fld] = scal(p + fld)
            moor["line_types"].append(lt)
        design["mooring"] = moor

        # DLC filter: spectral-wind cases only (reference omdao_raft.py:601-611)
        keys = discrete_inputs["raft_dlcs_keys"]
        turb_ind = keys.index("turbulence")
        case_mask = [
            any(t in str(row[turb_ind]) for t in ("NTM", "ETM", "EWM"))
            for row in discrete_inputs["raft_dlcs"]
        ]
        design["cases"] = {
            "keys": keys,
            "data": [row for row, ok in
                     zip(discrete_inputs["raft_dlcs"], case_mask) if ok],
        }
        if not design["cases"]["data"]:
            raise ValueError(
                "RAFT_OMDAO: no spectral-wind (NTM/ETM/EWM) cases in "
                "raft_dlcs — the frequency-domain solve needs at least one; "
                "transient-only DLC sets belong to the time-domain tools."
            )
        return design, np.array(case_mask)

    # ----------------------------------------------------------- compute
    def _engine_solver(self, engine, endpoint, modeling_opt):
        """Dynamics-dispatch closure for ``Model.analyze_cases(solver=)``
        that submits the design to a running serve engine (``engine`` —
        any object with the Engine/Router ``evaluate`` surface) or to a
        serve HTTP tier (``endpoint`` — ``host:port``) instead of owning
        the dispatch in this process.

        With ``RAFT_TPU_BATCHED_PREP=1`` on the engine side, the
        driver-loop submissions this closure makes land in one design
        family (scale knobs never change branch signatures), so after
        the first iteration the engine preps each new scale point
        through the family's traced program instead of a full Model
        build — the serve-tier analogue of the sweep drivers' batched
        prep."""
        if modeling_opt.get("run_native_BEM"):
            raise NotImplementedError(
                "modeling options 'engine'/'engine_endpoint' cannot be "
                "combined with 'run_native_BEM': the serve engine preps "
                "designs without a potential-flow stage, so the served "
                "solve would not see the BEM coefficients"
            )
        if modeling_opt.get("trim_ballast", 0):
            raise NotImplementedError(
                "modeling options 'engine'/'engine_endpoint' cannot be "
                "combined with trim_ballast != 0: the serve engine preps "
                "the design exactly as submitted (no ballast trim), so "
                "the served solve would run an untrimmed design"
            )
        from raft_tpu.health import report_from_dict

        timeout = float(modeling_opt.get("engine_timeout_s", 600.0))

        def solve(model, args, aux):
            if engine is not None:
                res = engine.evaluate(model.design, timeout=timeout)
            else:
                from raft_tpu.serve import wire
                from raft_tpu.serve.transport import WireClient

                host, _, port = str(endpoint).rpartition(":")
                client = WireClient(host or "127.0.0.1", int(port))
                doc = client.solve({"design": model.design, "xi": True})
                res = wire.result_from_doc(doc)
            if res.status != "ok":
                raise RuntimeError(
                    f"RAFT_OMDAO engine solve failed "
                    f"(status={res.status}): {res.error}"
                )
            xr = np.ascontiguousarray(res.Xi.real)
            xi = np.ascontiguousarray(res.Xi.imag)
            return xr, xi, report_from_dict(res.solve_report)

        return solve

    def _scale_theta(self, inputs):
        """Current design-scale vector from the derivative inputs."""
        return np.array([
            float(np.asarray(inputs[p]).reshape(-1)[0])
            for p in _SCALE_INPUTS
        ])

    def compute(self, inputs, outputs, discrete_inputs, discrete_outputs):
        from raft_tpu.model import Model

        modeling_opt = self.options["modeling_options"]
        analysis_options = self.options["analysis_options"]
        design, case_mask = self._rebuild_design(inputs, discrete_inputs)
        if modeling_opt.get("derivatives"):
            from raft_tpu.parametric import apply_design_scales

            design = apply_design_scales(design, self._scale_theta(inputs))

        if modeling_opt.get("save_designs"):
            path = os.path.join(
                analysis_options["general"]["folder_output"], "raft_designs",
                f"raft_design_{self.i_design}.pkl",
            )
            with open(path, "wb") as fh:
                pickle.dump(design, fh, protocol=pickle.HIGHEST_PROTOCOL)
            self.i_design += 1

        model = Model(
            design,
            precision=modeling_opt.get("precision"),
            device=modeling_opt.get("device"),
        )
        model.analyze_unloaded(
            ballast=modeling_opt.get("trim_ballast", 0),
            heave_tol=modeling_opt.get("heave_tol", 1.0),
        )
        if modeling_opt.get("run_native_BEM"):
            model.run_bem()
        solver = None
        if (modeling_opt.get("engine") is not None
                or modeling_opt.get("engine_endpoint")):
            solver = self._engine_solver(
                modeling_opt.get("engine"),
                modeling_opt.get("engine_endpoint"), modeling_opt)
        model.analyze_cases(solver=solver)
        results = model.calc_outputs()

        for name, _ in self.list_outputs(out_stream=None, all_procs=True):
            if name.startswith("properties_"):
                outputs[name] = results["properties"][
                    name.split("properties_")[1]
                ]
            elif name.startswith("response_"):
                val = results["response"][name.split("response_")[1]]
                val = np.asarray(val)
                # flat component contract is single-case [nfreq]
                if np.iscomplexobj(val):
                    val = np.abs(val)
                outputs[name] = val[0] if val.ndim > 1 else val

        # solver-health outputs + warning (the reference only prints;
        # here a driver can constrain on solver_all_healthy and callers
        # capture the warning through the 'raft_tpu' logger)
        rep = model.solve_report
        outputs["solver_converged"][case_mask] = rep.converged.astype(float)
        outputs["solver_iters"][case_mask] = rep.iters.astype(float)
        outputs["solver_nonfinite"][case_mask] = rep.nonfinite.astype(float)
        outputs["solver_recovery_tier"][case_mask] = \
            rep.recovery_tier.astype(float)
        outputs["solver_residual"][case_mask] = rep.residual.astype(float)
        healthy = bool(rep.converged.all()) and not bool(rep.nonfinite.any())
        outputs["solver_all_healthy"] = float(healthy)
        if not healthy:
            from raft_tpu.utils.profiling import logger

            logger.warning(
                "RAFT_OMDAO: %d of %d case(s) unhealthy (non-converged or "
                "NaN-quarantined); see the solver_* outputs",
                int(np.sum(~rep.converged | rep.nonfinite)),
                len(rep.converged),
            )

        cm = results["case_metrics"]
        for n in _STAT_CHANNELS:
            for s in _STATS:
                if s == "DEL" and n not in ("Tmoor", "Mbase"):
                    continue
                outputs[f"stats_{n}_{s}"][case_mask] = cm[f"{n}_{s}"]
        for n in ["wind_PSD", "wave_PSD"]:
            outputs[f"stats_{n}"][case_mask, :] = cm[n]

        outputs["Max_Offset"] = np.sqrt(
            outputs["stats_surge_max"][case_mask] ** 2
            + outputs["stats_sway_max"][case_mask] ** 2
        ).max()
        outputs["heave_avg"] = outputs["stats_heave_avg"][case_mask].mean()
        outputs["Max_PtfmPitch"] = outputs["stats_pitch_max"][case_mask].max()
        outputs["Std_PtfmPitch"] = outputs["stats_pitch_std"][case_mask].mean()
        outputs["max_nacelle_Ax"] = outputs["stats_AxRNA_std"][case_mask].max()
        rated = float(np.asarray(inputs["rated_rotor_speed"]).reshape(-1)[0])
        if rated > 0:
            outputs["rotor_overspeed"] = (
                outputs["stats_omega_max"][case_mask].max() - rated
            ) / rated
        outputs["max_tower_base"] = outputs["stats_Mbase_max"][case_mask].max()

        outputs["platform_displacement"] = model.statics.V
        outputs["platform_total_center_of_mass"] = outputs[
            "properties_substructure CG"
        ]
        outputs["platform_mass"] = outputs["properties_substructure mass"]
        outputs["platform_I_total"][:3] = [
            outputs["properties_roll inertia at subCG"][0],
            outputs["properties_pitch inertia at subCG"][0],
            outputs["properties_yaw inertia at subCG"][0],
        ]
        self._last_model = model

    # --------------------------------------------------------- derivatives
    def compute_partials(self, inputs, partials, discrete_inputs=None):
        """Exact partials of the aggregate response outputs w.r.t. the
        design-scale inputs via the reverse-mode IFT adjoint
        (raft_tpu/grad, docs/differentiation.md) — no finite
        differencing anywhere.  One adjoint evaluation per output row
        prices ALL four design-scale columns at once (vs one forward
        pass per column under the old jacfwd route, or eight compute()
        evaluations under WEIS's FD wrapper around the reference
        component, which declares no partials at all).

        Engine mode: when modeling option ``engine`` (a live
        Engine/Router) or ``engine_endpoint`` (``host:port``) is set,
        each row is a served grad request (``Engine.submit_grad`` /
        ``POST /v1/grad``) — the driver shares the serve tier's warmed
        adjoint executables and its exact-answer grad cache, and the
        served bits are identical to the in-process adjoint (the wire
        schema round-trips f64 exactly; tests/test_grad.py).

        Fallback: if the adjoint path refuses the design (the implicit
        equilibrium rule rejects bridled moorings), the in-process mode
        falls back to the forward-mode jacfwd twin with a warning —
        same values to reverse/forward round-off, one pass per column.

        Requires modeling option ``derivatives``; only the
        (_PARTIAL_OUTPUTS x _SCALE_INPUTS) block is exact — every other
        partial remains undeclared, exactly like the reference.
        Incompatible with ``run_native_BEM`` and ``trim_ballast`` (the
        traced twin models neither; _check_derivative_options refuses
        the combination in setup() and here).

        All four design-scale columns (draft, ballast, col_diam,
        line_length) match in-cell central/one-sided FD of compute()
        itself (tests/test_parametric.py::test_omdao_scale_partials);
        the twin's waterline-clip and submergence masks follow the
        traced geometry, so the draft column is the derivative of
        compute()'s own smooth in-cell path (strip counts still jump
        at member-length multiples of dls_max — derivatives are exact
        within a topology cell).
        """
        from raft_tpu.parametric import PARAM_NAMES

        modeling_opt = self.options["modeling_options"]
        if not modeling_opt.get("derivatives"):
            raise RuntimeError(
                "compute_partials needs modeling option 'derivatives'")
        # guard again here: options dicts are mutable after setup()
        _check_derivative_options(modeling_opt)
        if discrete_inputs is None:
            discrete_inputs = self._discrete_inputs \
                if hasattr(self, "_discrete_inputs") else {}
        design, _mask = self._rebuild_design(inputs, discrete_inputs)
        theta = self._scale_theta(inputs)
        engine = modeling_opt.get("engine")
        endpoint = modeling_opt.get("engine_endpoint")
        if engine is not None or endpoint:
            rows = self._served_partials(engine, endpoint, design,
                                         theta, modeling_opt)
        else:
            rows = self._adjoint_partials(design, theta)
            if rows is None:
                rows = self._jacfwd_partials(design, theta)
        for out_name, metric in _PARTIAL_OUTPUTS.items():
            row = np.asarray(rows[metric])
            for in_name, pname in _SCALE_INPUTS.items():
                partials[out_name, in_name] = row[
                    PARAM_NAMES.index(pname)]

    def _design_key(self, design, family):
        import pickle as _pickle

        return (family, hash(_pickle.dumps(
            design, protocol=_pickle.HIGHEST_PROTOCOL)))

    def _adjoint_partials(self, design, theta):
        """{metric: grad row [4]} by one reverse-mode adjoint evaluation
        per output metric, programs cached per design topology.  Returns
        None when the adjoint pipeline refuses the design (jacfwd
        fallback)."""
        import jax

        from raft_tpu.grad.response import build_value_and_grad
        from raft_tpu.utils.profiling import logger

        key = self._design_key(design, "adjoint")
        fns = self._param_fn_cache.get(key)
        if fns is None:
            try:
                fns = {metric: build_value_and_grad(design, metric)[0]
                       for metric in _PARTIAL_OUTPUTS.values()}
            except NotImplementedError as e:
                logger.warning(
                    "RAFT_OMDAO: adjoint partials unavailable for this "
                    "design (%s); falling back to forward-mode jacfwd",
                    e)
                return None
            self._param_fn_cache = {key: fns}  # one design topology live
        th = jax.device_put(np.asarray(theta, np.float64),
                            jax.devices("cpu")[0])
        rows = {}
        for metric, fn in fns.items():
            _value, g = fn(th)
            rows[metric] = np.asarray(g)
        return rows

    def _jacfwd_partials(self, design, theta):
        """The pre-adjoint route: jax.jacfwd through the plain traced
        twin, one forward pass per design-scale column."""
        import jax

        from raft_tpu.parametric import build_design_response

        key = self._design_key(design, "jacfwd")
        hit = self._param_fn_cache.get(key)
        if hit is None:
            f, _theta0 = build_design_response(
                design, metrics=tuple(_PARTIAL_OUTPUTS.values()))
            hit = jax.jit(jax.jacfwd(f))
            self._param_fn_cache = {key: hit}   # one design topology live
        th = jax.device_put(np.asarray(theta, np.float64),
                            jax.devices("cpu")[0])
        J = hit(th)
        return {metric: np.asarray(J[metric])
                for metric in _PARTIAL_OUTPUTS.values()}

    def _served_partials(self, engine, endpoint, design, theta,
                         modeling_opt):
        """{metric: grad row [4]} through the served grad request type:
        one ``POST /v1/grad``-shaped objective per output row, answered
        by the serve tier's adjoint programs (and its exact-answer grad
        cache on repeat visits to a scale point)."""
        from raft_tpu.grad.response import GRAD_KNOBS
        from raft_tpu.parametric import PARAM_NAMES

        timeout = float(modeling_opt.get("engine_timeout_s", 600.0))
        rows = {}
        for metric in _PARTIAL_OUTPUTS.values():
            objective = {"metric": metric, "knobs": list(GRAD_KNOBS),
                         "theta": [float(t) for t in theta]}
            if engine is not None:
                res = engine.evaluate_grad(design, objective,
                                           timeout=timeout)
            else:
                from raft_tpu.serve import wire
                from raft_tpu.serve.transport import WireClient

                host, _, port = str(endpoint).rpartition(":")
                client = WireClient(host or "127.0.0.1", int(port))
                doc = client.grad({"design": design,
                                   "objective": objective})
                res = wire.grad_result_from_doc(doc)
            if res.status != "ok":
                raise RuntimeError(
                    f"RAFT_OMDAO served grad failed for {metric} "
                    f"(status={res.status}): {res.error}")
            rows[metric] = np.asarray(
                [res.gradient[p] for p in PARAM_NAMES], np.float64)
        return rows
