"""Strip-theory hydrodynamics as batched einsum pipelines.

Replaces the reference's hot per-member/per-node/per-frequency Python loops
(reference raft/raft_fowt.py:466-591 calcHydroConstants — HOT LOOP #1 — and
:595-703 calcLinearizedTerms — HOT LOOP #2) with masked reductions over a
flat node axis, so the entire hydro assembly lives inside one jitted XLA
graph and vmaps over load cases.

Conventions: frequency axis LAST in node-level arrays ([N, 3, nw]) and
LEADING in system-level arrays ([nw, 6, 6] / [nw, 6]) — the latter is the
natural layout for the batched per-frequency 6x6 solves.

All inputs are expected in a uniform dtype (f32/c64 on TPU, f64/c128 on
CPU); complex arrays never cross the jit boundary.
"""

import jax.numpy as jnp

from raft_tpu.precision import mp_masked_sum, mp_matmul
from raft_tpu.utils.frames import translate_matrix_3to6
from raft_tpu.waves import jonswap


def make_wave_spectrum(w, spectrum, height, period, dtype=None):
    """Wave elevation amplitude array zeta[nw] for a case
    (reference raft/raft_fowt.py:474-484).

    spectrum : 0 = still/none, 1 = unit, 2 = JONSWAP (encoded as an integer so
    cases batch as arrays; the Model maps the YAML strings).
    """
    w = jnp.asarray(w)
    dtype = dtype or w.dtype
    S = jonswap(w, height, period).astype(dtype)
    zeta_j = jnp.sqrt(S)
    return jnp.where(
        spectrum == 2, zeta_j,
        jnp.where(spectrum == 1, jnp.ones_like(w, dtype), jnp.zeros_like(w, dtype)),
    )


def _sum_matrix_3to6(Amat, r, mask, mp=False):
    """sum_n translate_matrix_3to6(Amat[n], r[n]) over masked nodes -> [6,6].

    mp=True: bf16 operands / f32 accumulation (raft_tpu/precision.py);
    the default is the exact baseline reduction."""
    A6 = translate_matrix_3to6(Amat, r)          # [N, 6, 6]
    if mp:
        return mp_masked_sum(A6, mask[:, None, None], axis=0)
    return jnp.sum(jnp.where(mask[:, None, None], A6, 0.0), axis=0)


def _sum_force_3to6(f3, r, mask):
    """sum_n [f3; cross(r, f3)] over masked nodes.

    f3 : [N, 3, nw] (complex), r : [N, 3] -> [nw, 6]
    """
    f3 = jnp.where(mask[:, None, None], f3, 0.0)
    fw = jnp.moveaxis(f3, -1, 1)                  # [N, nw, 3]
    m = jnp.cross(r[:, None, :], fw)              # [N, nw, 3]
    return jnp.concatenate(
        [jnp.sum(fw, axis=0), jnp.sum(m, axis=0)], axis=-1
    )                                              # [nw, 6]


def added_mass_morison(nodes, rho):
    """Constant Morison added-mass matrix A_hydro_morison[6,6]
    (reference raft/raft_fowt.py:541-545 side + :570-573 end terms).

    nodes: HydroNodes arrays already converted to jnp in the working dtype.
    """
    side = rho * nodes.v_side[:, None, None] * (
        nodes.Ca_p1[:, None, None] * nodes.p1Mat
        + nodes.Ca_p2[:, None, None] * nodes.p2Mat
    )
    end = rho * nodes.v_end[:, None, None] * nodes.Ca_End[:, None, None] * nodes.qMat
    return _sum_matrix_3to6(side + end, nodes.r, nodes.strip_mask)


def excitation_froude_krylov(nodes, u, ud, pDyn, rho, mp=False):
    """Wave inertial (Froude–Krylov + dynamic pressure) excitation
    F_hydro_iner[nw, 6] (reference raft/raft_fowt.py:548-591).

    u, ud : [N, 3, nw] wave kinematics at nodes; pDyn : [N, nw].
    mp : bf16-operand / f32-accumulate inertia contraction
        (raft_tpu/precision.py); default is the exact baseline einsum.
    """
    Imat = rho * nodes.v_side[:, None, None] * (
        (1.0 + nodes.Ca_p1)[:, None, None] * nodes.p1Mat
        + (1.0 + nodes.Ca_p2)[:, None, None] * nodes.p2Mat
    )
    ImatE = rho * nodes.v_end[:, None, None] * nodes.Ca_End[:, None, None] * nodes.qMat
    if mp:
        f3 = mp_matmul("nij,njw->niw", Imat + ImatE, ud)
    else:
        f3 = jnp.einsum("nij,njw->niw", (Imat + ImatE).astype(ud.dtype), ud)
    # dynamic pressure on end/taper areas, along the member axis
    f3 = f3 + pDyn[:, None, :] * (nodes.a_end[:, None] * nodes.q)[..., None]
    return _sum_force_3to6(f3, nodes.r, nodes.strip_mask)


def linearized_drag(nodes, Xi, u, w, dw, rho, mp=False):
    """Amplitude-dependent stochastic drag linearization
    (reference raft/raft_fowt.py:595-703, HOT LOOP #2).

    Xi : [6, nw] complex platform motion amplitudes
    u  : [N, 3, nw] wave velocity at nodes
    mp : bf16-operand / f32-accumulate contractions for the 3->6 matrix
        sum and the drag-excitation einsum (raft_tpu/precision.py);
        default is the exact baseline arithmetic.
    Returns (B_drag[6,6] real, F_drag[nw,6] complex).

    Reference quirks reproduced:
     - the 'directional RMS' sums |vrel_i * q_i|^2 over BOTH the component
       and frequency axes (helpers.getRMS applied to a [3,nw] array,
       raft_fowt.py:646-653) — not the magnitude of the projected component;
     - drag excitation uses B @ u (wave velocity), not relative velocity.
    """
    # node displacement/velocity from platform motion (helpers.getVelocity)
    r = nodes.r
    th = Xi[3:, :]                                     # [3, nw]
    # dr[n, i, w] = Xi[i, w] + cross(th, r_n)[i, w]
    cross = jnp.stack(
        [
            th[2][None, :] * (-r[:, 1][:, None]) + th[1][None, :] * r[:, 2][:, None],
            th[2][None, :] * r[:, 0][:, None] - th[0][None, :] * r[:, 2][:, None],
            -th[1][None, :] * r[:, 0][:, None] + th[0][None, :] * r[:, 1][:, None],
        ],
        axis=1,
    )                                                  # [N, 3, nw]
    dr = Xi[None, :3, :] + cross
    vnode = 1j * w * dr                                # [N, 3, nw]

    vrel = u - vnode
    vrel = jnp.where(nodes.submerged[:, None, None], vrel, 0.0)

    def dir_rms(pvec):
        # sqrt( dw * sum_{i,w} |vrel_iw * p_i|^2 )  per node
        comp = vrel * pvec[:, :, None]
        return jnp.sqrt(jnp.sum(jnp.abs(comp) ** 2, axis=(1, 2)) * dw)

    vRMS_q = dir_rms(nodes.q)
    # p1/p2 direction vectors are encoded in the projection matrices; recover
    # the vectors' squared components from the diagonals for the quirk-exact
    # elementwise product: |v_i p_i|^2 = |v_i|^2 p_i^2
    p1_sq = jnp.diagonal(nodes.p1Mat, axis1=-2, axis2=-1)   # [N, 3] = p1_i^2
    p2_sq = jnp.diagonal(nodes.p2Mat, axis1=-2, axis2=-1)

    def dir_rms_sq(p_sq):
        comp2 = jnp.abs(vrel) ** 2 * p_sq[:, :, None]
        return jnp.sqrt(jnp.sum(comp2, axis=(1, 2)) * dw)

    vRMS_p1 = dir_rms_sq(p1_sq)
    vRMS_p2 = dir_rms_sq(p2_sq)

    c = jnp.sqrt(8.0 / jnp.pi) * 0.5 * rho
    Bq = c * vRMS_q * nodes.a_q * nodes.Cd_q
    Bp1 = c * vRMS_p1 * nodes.a_p1 * nodes.Cd_p1
    Bp2 = c * vRMS_p2 * nodes.a_p2 * nodes.Cd_p2
    Bend = c * vRMS_q * nodes.a_end_abs * nodes.Cd_End

    Bmat = (
        (Bq + Bend)[:, None, None] * nodes.qMat
        + Bp1[:, None, None] * nodes.p1Mat
        + Bp2[:, None, None] * nodes.p2Mat
    )                                                   # [N, 3, 3]
    B_drag = _sum_matrix_3to6(Bmat, nodes.r, nodes.submerged, mp=mp)
    if mp:
        f3 = mp_matmul("nij,njw->niw", Bmat, u)
    else:
        f3 = jnp.einsum("nij,njw->niw", Bmat.astype(u.dtype), u)
    F_drag = _sum_force_3to6(f3, nodes.r, nodes.submerged)
    return B_drag, F_drag
