"""Fused draft x ballast design sweep — the whole 256-point parameter study
in a handful of device dispatches.

The reference's parameter sweep is a serial Python loop that rebuilds and
re-analyzes a full model per design point (reference
raft/parametersweep.py:56-100: nested loops, runRAFT per point, no
batching).  The generic sharded driver in :mod:`raft_tpu.sweep` already
vmaps the *dynamics* over designs, but it still pays host-side model
construction per point, which dominates a 256-point sweep.

This module exploits the sweep structure itself (BASELINE.json configs[3]:
a draft x ballast study of VolturnUS-S):

 - **geometry** only varies along the draft axis -> one strip-node bundle
   per draft value (16 bundles for a 16x16 grid), not per design;
 - **ballast density scaling is exactly linear in the statics**: every
   mass/CG/stiffness entry is affine in rho_fill (verified to float
   rounding), so two `compute_statics` evaluations per draft (fill scale 0
   and 1) give every ballast point by linear combination — 32 statics
   evaluations cover all 256 designs;
 - **aero-servo** (operating-wind cases, aeroServoMod 1/2): the zero-pitch
   first pass is design-independent -> one rotor evaluation per case; the
   second pass at each design's mean pitch is ONE vmapped compiled CPU
   call over (design x wind-case) lanes, and the hub a(w)/b(w) terms enter
   the device graph as rank-1 frequency profiles (a * P_hub);
 - **mooring**: all designs x distinct-mean-load cases solved in ONE
   vmapped f64 CPU call (implicit-diff catenary,
   mooring.case_mooring_design_batch_fn);
 - **dynamics**: all designs x cases x frequencies in ONE jitted TPU
   dispatch — `lax.map` over draft groups (bounds live memory) around
   `vmap` over (draft-in-group, ballast, case), with response statistics
   reduced in-graph so only [nd, nc, 6] statistics come back over the
   wire (the full Xi transfer is optional).

Result: the sweep costs seconds where the serial loop costs minutes — the
benchmark pairing this with the single-core NumPy baseline lives in
bench_sweep.py at the repo root.
"""

import copy
import dataclasses
import time
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.geometry import pack_nodes, process_members
from raft_tpu.hydro import added_mass_morison
from raft_tpu.io.schema import cases_as_dicts
from raft_tpu.model import Model, make_case_dynamics
from raft_tpu.mooring import case_mooring_design_batch_fn, parse_mooring
from raft_tpu.statics import compute_statics
from raft_tpu.sweep import pad_and_stack_nodes
from raft_tpu.utils.placement import put_cpu

_am_f64 = jax.jit(added_mass_morison)


def scale_draft(design, s):
    """Deep-copied design with every platform member's submerged endpoint
    depths scaled by ``s`` (the draft axis of the sweep: keels move from
    z to s*z, pontoons/heave plates track proportionally; above-water
    geometry and mooring fairleads stay fixed, like the reference sweep's
    draft loop, reference raft/parametersweep.py:71-76)."""
    d = copy.deepcopy(design)
    for mem in d["platform"]["members"]:
        for key in ("rA", "rB"):
            v = [float(x) for x in mem[key]]
            if v[2] < 0.0:
                v[2] = v[2] * float(s)
            mem[key] = v
    return d


def _scale_fill(member, s):
    """Member copy with ballast density scaled by ``s`` (shape-preserving)."""
    rf = member.rho_fill
    rf = rf * s if np.isscalar(rf) else np.asarray(rf) * s
    return dataclasses.replace(member, rho_fill=rf)


@dataclasses.dataclass
class _DraftVariant:
    """Host-side preprocessing of one draft value."""

    nodes: object            # HydroNodes (f64)
    moor: tuple              # mooring line arrays (numpy f64)
    A_morison: np.ndarray    # [6, 6] f64
    # statics at ballast scale 0 and 1 (everything else by linearity)
    m0: float
    m1: float
    mCG0: np.ndarray         # mass * rCG at scale 0 [3]
    mCG1: np.ndarray
    M0: np.ndarray           # M_struc at scale 0 [6, 6]
    M1: np.ndarray
    C0: np.ndarray           # C_struc at scale 0 [6, 6]
    C1: np.ndarray
    C_hydro: np.ndarray      # [6, 6] (ballast-independent)
    V: float
    AWP: float
    zMeta: float


def _prepare_draft(base_design, s, rho_water, g):
    d = scale_draft(base_design, s)
    members = process_members(d)
    nodes = pack_nodes(members)
    turbine = d["turbine"]
    S1 = compute_statics(members, turbine, rho_water, g)
    S0 = compute_statics(
        [_scale_fill(m, 0.0) for m in members], turbine, rho_water, g
    )
    ms = parse_mooring(d["mooring"], rho_water=rho_water, g=g)
    moor = (ms.anchors, ms.rFair, ms.L, ms.EA, ms.w, ms.Wp)
    A = np.asarray(_am_f64(put_cpu(nodes.astype(np.float64)), rho_water))
    return _DraftVariant(
        nodes=nodes, moor=moor, A_morison=A,
        m0=S0.mass, m1=S1.mass,
        mCG0=S0.mass * S0.rCG_TOT, mCG1=S1.mass * S1.rCG_TOT,
        M0=S0.M_struc, M1=S1.M_struc,
        C0=S0.C_struc, C1=S1.C_struc,
        C_hydro=S1.C_hydro, V=S1.V, AWP=S1.AWP, zMeta=S1.zMeta,
    )


def _aero_second_pass(model0, cases, wind, pitch_mean):
    """Second-pass rotor loads + aero-servo transfer terms at each design's
    mean platform pitch: ONE vmapped compiled CPU call over (design x
    wind-case) lanes plus broadcast transfer-function algebra (the
    reference re-runs CCBlade serially per sweep point,
    raft/raft_model.py:516-517 inside parametersweep.py:56-100's loop).

    pitch_mean : [nd, nc] mean platform pitch (rad) per design x case.
    Returns (a [nd, nc, nw], b [nd, nc, nw], F_aero0 [nd, nc, 6] at PRP).
    """
    from raft_tpu.aero import servo_transfer_terms
    from raft_tpu.utils.frames import transform_force

    rotor = model0.rotor
    nd, nc = pitch_mean.shape
    nw = model0.nw
    a = np.zeros((nd, nc, nw))
    b = np.zeros((nd, nc, nw))
    F0 = np.zeros((nd, nc, 6))
    widx = np.where(wind > 0.0)[0]
    if len(widx) == 0 or rotor is None:
        return a, b, F0
    nwind = len(widx)
    U = np.broadcast_to(wind[widx][None], (nd, nwind))
    yaw = np.array(
        [float(cases[i].get("yaw_misalign", 0.0)) for i in widx]
    )
    vals, J = rotor.run_bem_batch(
        U.ravel(), pitch_mean[:, widx].ravel(),
        np.broadcast_to(yaw[None], (nd, nwind)).ravel(),
    )
    vals = vals.reshape(nd, nwind, 10)
    J = J.reshape(nd, nwind, 10, 3)

    # mean hub loads with the reference's ordering quirk [T, Y, Z, My, Q, Mz]
    # (raft/raft_rotor.py:350-351), shifted to the PRP
    F_hub = np.stack(
        [vals[..., 0], vals[..., 6], vals[..., 7],
         vals[..., 8], vals[..., 1], vals[..., 9]], axis=-1,
    )
    rHub = np.array([0.0, 0.0, model0.hHub])
    F0[:, widx] = np.asarray(transform_force(F_hub, offset=rHub))

    dT_dU, dT_dOm, dT_dPi = J[..., 0, 0], J[..., 0, 1], J[..., 0, 2]
    dQ_dU, dQ_dOm, dQ_dPi = J[..., 1, 0], J[..., 1, 1], J[..., 1, 2]
    if model0.aeroServoMod == 1:
        b[:, widx] = dT_dU[..., None]
    else:
        kp_beta, ki_beta, kp_tau, ki_tau = rotor.case_gains(wind[widx])
        _, _, a_w, b_w = servo_transfer_terms(
            model0.w, dT_dU, dT_dOm, dT_dPi, dQ_dU, dQ_dOm, dQ_dPi,
            kp_beta, ki_beta, kp_tau, ki_tau,
            rotor.k_float, rotor.Ng, rotor.I_drivetrain, rotor.Zhub,
        )
        a[:, widx] = a_w
        b[:, widx] = b_w
    return a, b, F0


def _ballast_combine(v, b):
    """Statics for the full ballast axis of one draft variant by linear
    combination (b : [nB] ballast density scales).

    Returns dict of arrays with leading nB axis.
    """
    b = np.asarray(b, np.float64)
    mass = v.m0 + b * (v.m1 - v.m0)                       # [nB]
    mCG = v.mCG0[None] + b[:, None] * (v.mCG1 - v.mCG0)   # [nB, 3]
    rCG = mCG / mass[:, None]
    M_struc = v.M0[None] + b[:, None, None] * (v.M1 - v.M0)
    C_struc = v.C0[None] + b[:, None, None] * (v.C1 - v.C0)
    return dict(mass=mass, rCG=rCG, M_struc=M_struc, C_struc=C_struc)


def _dynamics_pipeline(model0, return_xi):
    """Jitted sweep dynamics for ``model0``'s configuration, cached so
    repeated sweeps (and the benchmark's hot re-run) reuse one executable."""
    return _dynamics_pipeline_cached(
        model0.w.tobytes(), np.asarray(model0.k).tobytes(), model0.nw,
        float(model0.depth), float(model0.rho_water), float(model0.g),
        float(model0.XiStart), int(model0.nIter),
        np.dtype(model0.dtype).name, np.dtype(model0.cdtype).name,
        float(model0.hHub), bool(return_xi),
    )


@lru_cache(maxsize=16)
def _dynamics_pipeline_cached(w_bytes, k_bytes, nw, depth, rho, g,
                              XiStart, nIter, dtype_name, cdtype_name,
                              hHub, return_xi):
    """Build the jitted sweep pipeline: lax.map over draft groups, vmap
    over (draft-in-group, ballast, case).

    The per-(design, case) aero-servo hub terms enter as rank-1 frequency
    profiles: M_lin(w) = M0 + a(w) * P_hub and B_lin(w) = b(w) * P_hub,
    where P_hub is the constant 6x6 pattern of a unit fore-aft hub added
    mass translated to the PRP (translate_matrix_3to6 is linear in its 3x3
    argument, so the full [nw,6,6] hub matrices never leave the device
    graph; the reference assembles them on host per case,
    raft/raft_model.py:552-555)."""
    from raft_tpu.utils.frames import translate_matrix_3to6

    dtype = np.dtype(dtype_name).type
    cdtype = np.dtype(cdtype_name).type
    w = np.frombuffer(w_bytes, np.float64, count=nw)
    k = np.frombuffer(k_bytes, np.float64, count=nw)
    dw = float(w[1] - w[0])
    one_case = make_case_dynamics(
        w, k, depth, rho, g, XiStart, nIter, dtype, cdtype,
    )
    E00 = np.zeros((1, 3, 3))
    E00[0, 0, 0] = 1.0
    P_hub = jnp.asarray(
        np.asarray(translate_matrix_3to6(E00, np.array([0.0, 0.0, hHub])))[0],
        dtype,
    )

    def per_design(nodes, zeta, beta, C_case, M0, a_c, b_c):
        Fz = jnp.zeros((nw, 6), dtype)

        def fn(z, b, C, a1, b1):
            M_lin = M0[None] + a1[:, None, None] * P_hub
            B_lin = b1[:, None, None] * P_hub
            return one_case(nodes, z, b, C, M_lin, B_lin, Fz, Fz)

        xr, xi, iters, conv = jax.vmap(fn)(
            zeta, beta, C_case, a_c, b_c
        )  # [nc, ...]
        std = jnp.sqrt(jnp.sum(xr * xr + xi * xi, axis=-1) * dw)  # [nc, 6]
        if return_xi:
            return std, iters, conv, xr, xi
        return std, iters, conv

    # [gd, nB] design axes inside a group; nodes shared along ballast
    per_draft = jax.vmap(per_design, in_axes=(None, None, None, 0, 0, 0, 0))
    per_group = jax.vmap(per_draft, in_axes=(0, None, None, 0, 0, 0, 0))

    def pipeline(nodes_g, zeta, beta, C_g, M0_g, a_g, b_g):
        def step(xs):
            nodes, C, M0, a_c, b_c = xs
            return per_group(nodes, zeta, beta, C, M0, a_c, b_c)

        return jax.lax.map(step, (nodes_g, C_g, M0_g, a_g, b_g))

    return jax.jit(pipeline)


def run_draft_ballast_sweep(
    base_design,
    draft_scales,
    ballast_scales,
    precision=None,
    draft_group=4,
    return_xi=False,
    verbose=True,
):
    """Run the fused draft x ballast sweep.

    Parameters
    ----------
    base_design : dict
        VolturnUS-S-style design (must have a cases table).  Operating-wind
        cases run the full aero-servo path (aeroServoMod 1/2): per-case
        mean rotor loads feed the mooring equilibria, and each design's
        mean-pitch rotor re-evaluation contributes hub added mass a(w) and
        damping b(w) to the dynamics — matching the reference sweep, which
        runs the complete model per point (raft/parametersweep.py:56-100).
    draft_scales : [nD] multipliers on submerged member depths.
    ballast_scales : [nB] multipliers on ballast fill density.
    draft_group : drafts per lax.map step (bounds device memory:
        gd * nB * nc wave-kinematics lanes live at once).
    return_xi : also return the full complex response amplitudes
        [nD, nB, nc, 6, nw] (extra device->host transfer).

    Returns dict with metrics [nD, nB, ...], timing breakdown, and the
    mooring/statics intermediates the benchmark asserts against.
    """
    t_start = time.perf_counter()
    model0 = Model(base_design, precision=precision)
    nD, nB = len(draft_scales), len(ballast_scales)
    nd = nD * nB
    if nD % draft_group:
        raise ValueError("len(draft_scales) must be divisible by draft_group")

    cases = cases_as_dicts(base_design)
    spec, height, period, beta, wind = model0._case_arrays(cases)
    zeta = model0._zeta(spec, height, period)              # [nc, nw] f64
    nc = zeta.shape[0]
    aero_on = (
        model0.rotor is not None
        and model0.aeroServoMod > 0
        and bool(np.any(wind > 0.0))
    )
    if np.any(wind > 0.0) and not aero_on:
        import warnings

        warnings.warn(
            "run_draft_ballast_sweep: cases specify operating wind but the "
            "design has aero off (aeroServoMod=0 or no rotor data); the "
            "sweep runs WITHOUT wind loading, like the reference's "
            "aeroServoMod gate (reference raft/raft_fowt.py:445)",
            stacklevel=2,
        )

    # ---- host prep: one variant per draft, ballast by linearity ----
    t0 = time.perf_counter()
    variants = [
        _prepare_draft(base_design, s, model0.rho_water, model0.g)
        for s in draft_scales
    ]
    b = np.asarray(ballast_scales, np.float64)
    comb = [_ballast_combine(v, b) for v in variants]
    t_host = time.perf_counter() - t0

    # ---- aero first pass: per-case mean loads at zero pitch ----
    # (design-independent, so one batched rotor evaluation serves the
    # whole sweep; the reference re-runs it per point).  Reuses the
    # second-pass machinery at a single zero-pitch "design" lane.
    t0 = time.perf_counter()
    F_prp = (
        _aero_second_pass(model0, cases, wind, np.zeros((1, nc)))[2][0]
        if aero_on else np.zeros((nc, 6))
    )
    t_aero1 = time.perf_counter() - t0

    # ---- mooring: all designs x distinct-mean-load cases in one f64 CPU
    # call.  Cases sharing the same mean load (all wind-free cases, and
    # repeated wind speeds) collapse to one equilibrium per design; the
    # NumPy baseline in bench_sweep.py applies the same collapse, so the
    # timed comparison stays symmetric. ----
    t0 = time.perf_counter()
    moor_fn = case_mooring_design_batch_fn(
        model0.rho_water, model0.g, model0.yawstiff
    )
    rep = lambda a: np.repeat(np.asarray(a, np.float64), nB, axis=0)  # noqa: E731
    mass_all = np.concatenate([c["mass"] for c in comb])              # [nd]
    rCG_all = np.concatenate([c["rCG"] for c in comb])                # [nd, 3]
    V_all = rep([v.V for v in variants])
    AWP_all = rep([v.AWP for v in variants])
    rM_all = np.stack(
        [np.array([0.0, 0.0, v.zMeta]) for v in variants for _ in range(nB)]
    )
    moor_all = tuple(
        rep(np.stack([v.moor[i] for v in variants])) for i in range(6)
    )
    groups = {}
    inv = np.zeros(nc, int)
    for i in range(nc):
        inv[i] = groups.setdefault(F_prp[i].tobytes(), len(groups))
    ng = len(groups)
    F0g = np.zeros((ng, 6))
    for i in range(nc):
        F0g[inv[i]] = F_prp[i]
    F0 = np.broadcast_to(F0g[None], (nd, ng, 6)).copy()
    out = moor_fn(*put_cpu((F0, mass_all, V_all, rCG_all, rM_all, AWP_all))
                  , *put_cpu(moor_all))
    expand = lambda a: np.asarray(a)[:, inv].copy()  # noqa: E731
    r6, C_moor, F_moor, T_moor, J_moor = (expand(o) for o in out)
    t_moor = time.perf_counter() - t0

    # ---- aero second pass at the mean platform pitch of every design ----
    t0 = time.perf_counter()
    if aero_on:
        a_hub, b_hub, F_aero2 = _aero_second_pass(
            model0, cases, wind, r6[:, :, 4]
        )
    else:
        a_hub = np.zeros((nd, nc, model0.nw))
        b_hub = np.zeros((nd, nc, model0.nw))
        F_aero2 = np.zeros((nd, nc, 6))
    t_aero2 = time.perf_counter() - t0

    # ---- dynamics: one jitted TPU dispatch ----
    dtype = model0.dtype
    G = nD // draft_group
    nodes_all = pad_and_stack_nodes([v.nodes.astype(dtype) for v in variants])
    shp = lambda a: a.reshape((G, draft_group) + a.shape[1:])  # noqa: E731
    nodes_g = jax.tree.map(shp, nodes_all)
    C_lin = (
        np.stack([c["C_struc"] for c in comb])[:, :, None]
        + np.stack([v.C_hydro for v in variants])[:, None, None]
        + C_moor.reshape(nD, nB, nc, 6, 6)
    )                                                          # [nD, nB, nc, 6, 6]
    M0_all = (
        np.stack([c["M_struc"] for c in comb])
        + np.stack([v.A_morison for v in variants])[:, None]
    )                                                          # [nD, nB, 6, 6]

    pipeline = _dynamics_pipeline(model0, return_xi)
    dev_args = (
        jax.device_put(nodes_g),
        jnp.asarray(zeta.astype(dtype)),
        jnp.asarray(np.asarray(beta, dtype)),
        jnp.asarray(shp(C_lin.astype(dtype))),
        jnp.asarray(shp(M0_all.astype(dtype))),
        jnp.asarray(shp(a_hub.reshape(nD, nB, nc, model0.nw).astype(dtype))),
        jnp.asarray(shp(b_hub.reshape(nD, nB, nc, model0.nw).astype(dtype))),
    )
    t0 = time.perf_counter()
    dyn = pipeline(*dev_args)
    jax.block_until_ready(dyn)
    t_dyn_first = time.perf_counter() - t0  # includes compile on first call
    std = np.asarray(dyn[0], np.float64).reshape(nd, nc, 6)
    iters = np.asarray(dyn[1]).reshape(nd, nc)
    conv = np.asarray(dyn[2]).reshape(nd, nc)

    # ---- metrics (reference parametersweep getOutputs semantics,
    # reference raft/parametersweep.py:9-21) ----
    offset = np.hypot(r6[:, 0, 0], r6[:, 0, 1])
    pitch = np.rad2deg(r6[:, 0, 4])
    # omdao-style aggregates (omdao.py:728-733): per-case mean + 3*std
    # maxima, incl. the reference's sway_max-from-heave_std quirk
    # (raft_fowt.py:716), then the max over cases
    surge_max = r6[:, :, 0] + 3.0 * std[:, :, 0]           # [nd, nc]
    sway_max = r6[:, :, 1] + 3.0 * std[:, :, 2]
    pitch_max = np.rad2deg(r6[:, :, 4] + 3.0 * std[:, :, 4])
    res = {
        "draft_scales": np.asarray(draft_scales, float),
        "ballast_scales": b,
        "mass": mass_all.reshape(nD, nB),
        "displacement": (model0.rho_water * V_all).reshape(nD, nB),
        "GMT": (rM_all[:, 2] - rCG_all[:, 2]).reshape(nD, nB),
        "offset": offset.reshape(nD, nB),
        "pitch_deg": pitch.reshape(nD, nB),
        "surge_std": std[:, :, 0].reshape(nD, nB, nc),
        "heave_std": std[:, :, 2].reshape(nD, nB, nc),
        "pitch_std_deg": np.rad2deg(std[:, :, 4]).reshape(nD, nB, nc),
        "std": std.reshape(nD, nB, nc, 6),
        "converged": conv.reshape(nD, nB, nc),
        "iters": iters.reshape(nD, nB, nc),
        "Xi0": r6.reshape(nD, nB, nc, 6),
        "T_moor": T_moor.reshape((nD, nB) + T_moor.shape[1:]),
        # per-case aggregates (the omdao Max_Offset / Max_PtfmPitch view)
        "offset_max": np.hypot(surge_max, sway_max).max(axis=1).reshape(nD, nB),
        "pitch_max_deg": pitch_max.max(axis=1).reshape(nD, nB),
        # second-pass mean aero loads at the PRP (zero for wind-free cases)
        "F_aero0": F_aero2.reshape(nD, nB, nc, 6),
        "timing": {
            "host_prep_s": t_host,
            "aero_first_s": t_aero1,
            "mooring_s": t_moor,
            "aero_second_s": t_aero2,
            "dynamics_first_s": t_dyn_first,
            "total_s": time.perf_counter() - t_start,
        },
    }
    if return_xi:
        xr = np.asarray(dyn[3], np.float64).reshape(nd, nc, 6, model0.nw)
        xi = np.asarray(dyn[4], np.float64).reshape(nd, nc, 6, model0.nw)
        res["Xi"] = (xr + 1j * xi).reshape(nD, nB, nc, 6, model0.nw)
    if verbose:
        tm = res["timing"]
        print(
            f"fused sweep {nD}x{nB}: host {tm['host_prep_s']:.2f}s, "
            f"aero {tm['aero_first_s'] + tm['aero_second_s']:.2f}s, "
            f"mooring {tm['mooring_s']:.2f}s, dynamics(first) "
            f"{tm['dynamics_first_s']:.2f}s, total {tm['total_s']:.2f}s"
        )
    return res


