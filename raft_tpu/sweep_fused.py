"""Fused draft x ballast design sweep — the whole 256-point parameter study
in a handful of device dispatches.

The reference's parameter sweep is a serial Python loop that rebuilds and
re-analyzes a full model per design point (reference
raft/parametersweep.py:56-100: nested loops, runRAFT per point, no
batching).  The generic sharded driver in :mod:`raft_tpu.sweep` already
vmaps the *dynamics* over designs, but it still pays host-side model
construction per point, which dominates a 256-point sweep.

This module exploits the sweep structure itself (BASELINE.json configs[3]:
a draft x ballast study of VolturnUS-S):

 - **geometry** only varies along the draft axis -> one strip-node bundle
   per draft value (16 bundles for a 16x16 grid), not per design;
 - **ballast density scaling is exactly linear in the statics**: every
   mass/CG/stiffness entry is affine in rho_fill (verified to float
   rounding), so two `compute_statics` evaluations per draft (fill scale 0
   and 1) give every ballast point by linear combination — 32 statics
   evaluations cover all 256 designs;
 - **aero-servo** (operating-wind cases, aeroServoMod 1/2): the zero-pitch
   first pass is design-independent -> one rotor evaluation per case; the
   second pass at each design's mean pitch is ONE vmapped compiled CPU
   call over (design x wind-case) lanes, and the hub a(w)/b(w) terms enter
   the device graph as rank-1 frequency profiles (a * P_hub);
 - **mooring**: all designs x distinct-mean-load cases solved in ONE
   vmapped f64 CPU call (implicit-diff catenary,
   mooring.case_mooring_design_batch_fn);
 - **dynamics**: all designs x cases x frequencies in ONE jitted TPU
   dispatch — `lax.map` over draft groups (bounds live memory) around
   `vmap` over (draft-in-group, ballast, case), with response statistics
   reduced in-graph so only [nd, nc, 6] statistics come back over the
   wire (the full Xi transfer is optional).

Result: the sweep costs seconds where the serial loop costs minutes — the
benchmark pairing this with the single-core NumPy baseline lives in
bench_sweep.py at the repo root.
"""

import copy
import dataclasses
import time
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.batched_prep import batched_prep_enabled
from raft_tpu.geometry import pack_nodes, process_members
from raft_tpu.hydro import added_mass_morison
from raft_tpu.io.schema import cases_as_dicts
from raft_tpu.model import Model, make_case_dynamics
from raft_tpu.mooring import (
    case_mooring_design_batch_fn,
    parse_mooring,
    warn_bridle_residual,
)
from raft_tpu.resilience import SolveRetryPolicy
from raft_tpu.sweep_buckets import sweep_buckets_enabled
from raft_tpu.statics import compute_statics
from raft_tpu.sweep import pad_and_stack_nodes
from raft_tpu.health import apply_debug_nans
from raft_tpu.utils.placement import put_cpu
from raft_tpu.utils.profiling import logger
from raft_tpu.waterfall import fixed_point_mode

_am_f64 = jax.jit(added_mass_morison)


def scale_draft(design, s):
    """Deep-copied design with every platform member's submerged endpoint
    depths scaled by ``s`` (the draft axis of the sweep: keels move from
    z to s*z, pontoons/heave plates track proportionally; above-water
    geometry and mooring fairleads stay fixed, like the reference sweep's
    draft loop, reference raft/parametersweep.py:71-76)."""
    d = copy.deepcopy(design)
    for mem in d["platform"]["members"]:
        for key in ("rA", "rB"):
            v = [float(x) for x in mem[key]]
            if v[2] < 0.0:
                v[2] = v[2] * float(s)
            mem[key] = v
    return d


def _scale_fill(member, s):
    """Member copy with ballast density scaled by ``s`` (shape-preserving)."""
    rf = member.rho_fill
    rf = rf * s if np.isscalar(rf) else np.asarray(rf) * s
    return dataclasses.replace(member, rho_fill=rf)


@dataclasses.dataclass
class _DraftVariant:
    """Host-side preprocessing of one draft value."""

    nodes: object            # HydroNodes (f64)
    moor: tuple              # mooring line arrays (numpy f64)
    bridles: object          # BridleSet or None
    A_morison: np.ndarray    # [6, 6] f64
    # statics at ballast scale 0 and 1 (everything else by linearity)
    m0: float
    m1: float
    mCG0: np.ndarray         # mass * rCG at scale 0 [3]
    mCG1: np.ndarray
    M0: np.ndarray           # M_struc at scale 0 [6, 6]
    M1: np.ndarray
    C0: np.ndarray           # C_struc at scale 0 [6, 6]
    C1: np.ndarray
    C_hydro: np.ndarray      # [6, 6] (ballast-independent)
    V: float
    AWP: float
    zMeta: float


def _prepare_draft(base_design, s, rho_water, g):
    key = ("draft", _design_key(base_design), float(s), float(rho_water),
           float(g))
    hit = _variant_cache.get(key)
    if hit is not None:
        return hit
    d = scale_draft(base_design, s)
    members = process_members(d)
    nodes = pack_nodes(members)
    turbine = d["turbine"]
    S1 = compute_statics(members, turbine, rho_water, g)
    S0 = compute_statics(
        [_scale_fill(m, 0.0) for m in members], turbine, rho_water, g
    )
    ms = parse_mooring(d["mooring"], rho_water=rho_water, g=g)
    moor = (ms.anchors, ms.rFair, ms.L, ms.EA, ms.w, ms.Wp, ms.cb)
    A = np.asarray(_am_f64(put_cpu(nodes.astype(np.float64)), rho_water))
    v = _DraftVariant(
        nodes=nodes, moor=moor, bridles=ms.bridles, A_morison=A,
        m0=S0.mass, m1=S1.mass,
        mCG0=S0.mass * S0.rCG_TOT, mCG1=S1.mass * S1.rCG_TOT,
        M0=S0.M_struc, M1=S1.M_struc,
        C0=S0.C_struc, C1=S1.C_struc,
        C_hydro=S1.C_hydro, V=S1.V, AWP=S1.AWP, zMeta=S1.zMeta,
    )
    _variant_cache_put(key, v)
    return v


_GUIDE_NODES = 8         # full-solve pitch samples per wind case
_GUIDE_PROBES = 2        # verification lanes per wind case
_GUIDE_RTOL = 1e-9       # probe tolerance; exceeded -> direct fallback
_GUIDE_PHI_TOL = 1e-2    # rad; max polish displacement of an in-basin lane


def _blank_rotor_telemetry():
    """Guided-rotor telemetry accumulator: lane counts, probe error, and
    stage costs (feeds sweep_timing_breakdown via res['rotor_telemetry']
    — how the docs/performance.md §9 warm-start claim is reconciled with
    what a given host actually measures)."""
    return {
        "guided_lanes": 0,           # lanes served by the warm-started path
        "direct_fallback_lanes": 0,  # lanes re-solved by the full path
        "bracketed_sample_lanes": 0,  # full-solve pitch samples + probes
        "small_batch_lanes": 0,      # tiny sweeps solved directly (no guide)
        "fallback_cases": 0,         # wind cases that tripped a guard
        "probe_rel_err_max": 0.0,
        "bracketed_sample_s": 0.0,
        "guided_batch_s": 0.0,
        "direct_fallback_s": 0.0,
        "rotor_host_devices": 0,     # host devices the lane axis sharded over
    }


def _guided_rotor_eval(rotor, U_case, yaw_case, pitch_dc, telemetry=None):
    """Rotor loads + derivatives over (design x wind-case) lanes, with the
    per-section inflow-angle solves warm-started across designs.

    On a single-core host the fully-bracketed BEM+jacfwd call costs
    ~2.4 ms/lane, so 256 designs x 6 wind cases = 3.8 s — the fused
    sweep's critical path.  Within one wind case only the platform pitch
    varies across designs and the solved inflow angles phi vary smoothly
    (piecewise-C1) with it, so a small number of pitch samples per case is
    solved with the full bracketing path, every design lane's phi is
    linearly interpolated from them, and the whole (design x case) batch
    then runs the GUIDED executable: Newton polish of the exact residual
    from the interpolated guess, skipping the ~34-evaluation bracketing/
    bisection (aero._solve_phi).  The physics is exact — the same
    residual converged to roundoff, the same jacfwd derivatives — only
    the root-finder's starting point is informed.  Probe lanes solved
    with BOTH paths verify the polish reconverges (loads and derivatives
    agree to ``_GUIDE_RTOL``); a failing case falls back to the full
    path for its lanes, so correctness is measured per run, not assumed.

    U_case, yaw_case : [nwind] per-case wind speed / yaw misalignment
    pitch_dc : [nd, nwind] platform pitch per design x case
    Returns (vals [nd, nwind, 10], J [nd, nwind, 10, 3]).
    """
    tel = telemetry if telemetry is not None else _blank_rotor_telemetry()
    nd, nwind = pitch_dc.shape
    K, P = _GUIDE_NODES, _GUIDE_PROBES
    if nd <= K + P + 1:
        t0 = time.perf_counter()
        vals, J = rotor.run_bem_batch(
            np.broadcast_to(U_case[None], (nd, nwind)).ravel(),
            pitch_dc.ravel(),
            np.broadcast_to(yaw_case[None], (nd, nwind)).ravel(),
        )
        tel["small_batch_lanes"] += nd * nwind
        tel["direct_fallback_s"] += time.perf_counter() - t0
        tel["rotor_host_devices"] = rotor.last_batch_info["n_devices"]
        return vals.reshape(nd, nwind, 10), J.reshape(nd, nwind, 10, 3)

    # full-solve pitch samples per case (probes off the node grid)
    lo = pitch_dc.min(axis=0)
    hi = np.maximum(pitch_dc.max(axis=0), lo + 1e-6)
    t_nodes = np.linspace(0.0, 1.0, K)
    t_probe = np.array([0.317, 0.683])[:P]
    t_all = np.concatenate([t_nodes, t_probe])           # [K+P]
    batch_pitch = lo[:, None] + (hi - lo)[:, None] * t_all[None]
    t0 = time.perf_counter()
    vals_n, J_n, phi_n = rotor.run_bem_batch(
        np.repeat(U_case, K + P), batch_pitch.ravel(),
        np.repeat(yaw_case, K + P), return_phi=True,
    )
    tel["bracketed_sample_s"] += time.perf_counter() - t0
    tel["bracketed_sample_lanes"] += (K + P) * nwind
    ns, nsp = phi_n.shape[-2:]
    vals_n = vals_n.reshape(nwind, K + P, 10)
    J_n = J_n.reshape(nwind, K + P, 10, 3)
    phi_n = phi_n.reshape(nwind, K + P, ns, nsp)

    # linear phi interpolation across the pitch axis, per case: guesses
    # land ~1e-4 rad from the root — well inside the Newton basin
    def interp_phi(x, j):
        t = (x - lo[j]) / (hi[j] - lo[j])
        i = np.clip((t * (K - 1)).astype(int), 0, K - 2)
        f = (t * (K - 1) - i)[:, None, None]
        return (1.0 - f) * phi_n[j, i] + f * phi_n[j, i + 1]

    # guided batch: all design lanes + the probe lanes for verification
    pitch_g = np.concatenate(
        [pitch_dc.T.ravel(), batch_pitch[:, K:].ravel()])
    U_g = np.concatenate(
        [np.repeat(U_case, nd), np.repeat(U_case, P)])
    yaw_g = np.concatenate(
        [np.repeat(yaw_case, nd), np.repeat(yaw_case, P)])
    phi0_g = np.concatenate([
        np.concatenate([interp_phi(pitch_dc[:, j], j)
                        for j in range(nwind)]),
        np.concatenate([interp_phi(batch_pitch[j, K:], j)
                        for j in range(nwind)]),
    ])
    t0 = time.perf_counter()
    vals_g, J_g, phi_g, resid_g = rotor.run_bem_batch(
        U_g, pitch_g, yaw_g, phi0=phi0_g, return_phi=True,
        return_resid=True)
    tel["guided_batch_s"] += time.perf_counter() - t0
    tel["rotor_host_devices"] = rotor.last_batch_info["n_devices"]
    # .copy(): np.asarray of a jax.Array is a READ-ONLY view, and the
    # fallback below assigns into these per failing case
    vals = vals_g[:nd * nwind].reshape(nwind, nd, 10).copy()
    J = J_g[:nd * nwind].reshape(nwind, nd, 10, 3).copy()
    pv = vals_g[nd * nwind:].reshape(nwind, P, 10)
    pj = J_g[nd * nwind:].reshape(nwind, P, 10, 3)
    resid_l = resid_g[:nd * nwind].reshape(nwind, nd)
    # per-lane polish displacement |phi_solved - phi0|: a lane whose
    # interpolated guess crossed a bracket switch between the K pitch
    # nodes can converge to a DIFFERENT valid root of the multi-root Ning
    # residual with a tiny residual (so the resid guard passes) at a
    # pitch the 2 probes never sample — but only by moving phi far
    # beyond the ~1e-4 rad interpolation error of an in-basin guess, so
    # the displacement itself is the detector
    dphi_l = np.abs(
        phi_g[:nd * nwind] - np.asarray(phi0_g[:nd * nwind])
    ).max(axis=(-2, -1)).reshape(nwind, nd)

    direct = []
    for j in range(nwind):
        sv = np.abs(vals_n[j]).max(axis=0) + 1e-30
        sj = np.abs(J_n[j]).max(axis=(0,)) + 1e-30
        err = max(
            (np.abs(pv[j] - vals_n[j, K:]) / sv).max(),
            (np.abs(pj[j] - J_n[j, K:]) / sj).max(),
        )
        # three guards, all failing CLOSED (a NaN comparison routes to
        # the direct fallback): the probe lanes measure interpolation-
        # guess quality at two pitches; the per-lane post-polish Ning
        # residual catches any single lane whose guess was trapped in
        # the wrong bracket between probes (the polish leaves |r| large
        # there, deterministically); and the per-lane phi displacement
        # catches the remaining hole — a lane that crossed a bracket
        # switch and converged cleanly to a DIFFERENT valid root, which
        # has small residual but moved phi far beyond interpolation
        # error (guesses land ~1e-4 rad from the intended root)
        lane_ok = np.all(resid_l[j] <= 1e-8)
        phi_ok = np.all(dphi_l[j] <= _GUIDE_PHI_TOL)
        tel["probe_rel_err_max"] = max(tel["probe_rel_err_max"],
                                       float(err))
        if not (err <= _GUIDE_RTOL and lane_ok and phi_ok):
            direct.append(j)
    tel["fallback_cases"] += len(direct)
    tel["guided_lanes"] += nd * (nwind - len(direct))
    tel["direct_fallback_lanes"] += nd * len(direct)
    if direct:
        dd = np.array(direct)
        t0 = time.perf_counter()
        v_d, J_d = rotor.run_bem_batch(
            np.broadcast_to(U_case[dd][None], (nd, len(dd))).ravel(),
            pitch_dc[:, dd].ravel(),
            np.broadcast_to(yaw_case[dd][None], (nd, len(dd))).ravel(),
        )
        tel["direct_fallback_s"] += time.perf_counter() - t0
        vals[dd] = v_d.reshape(nd, len(dd), 10).swapaxes(0, 1)
        J[dd] = J_d.reshape(nd, len(dd), 10, 3).swapaxes(0, 1)
    return vals.swapaxes(0, 1), J.swapaxes(0, 1)


def _aero_second_pass(model0, cases, wind, pitch_mean, telemetry=None):
    """Second-pass rotor loads + aero-servo transfer terms at each design's
    mean platform pitch: phi-warm-started batched rotor evaluation (see
    :func:`_guided_rotor_eval`) plus broadcast transfer-function algebra
    (the reference re-runs CCBlade serially per sweep point,
    raft/raft_model.py:516-517 inside parametersweep.py:56-100's loop).

    pitch_mean : [nd, nc] mean platform pitch (rad) per design x case.
    Returns (a [nd, nc, nw], b [nd, nc, nw], F_aero0 [nd, nc, 6] at PRP).
    """
    from raft_tpu.aero import servo_transfer_terms
    from raft_tpu.utils.frames import transform_force

    rotor = model0.rotor
    nd, nc = pitch_mean.shape
    nw = model0.nw
    a = np.zeros((nd, nc, nw))
    b = np.zeros((nd, nc, nw))
    F0 = np.zeros((nd, nc, 6))
    widx = np.where(wind > 0.0)[0]
    if len(widx) == 0 or rotor is None:
        return a, b, F0
    yaw = np.array(
        [float(cases[i].get("yaw_misalign", 0.0)) for i in widx]
    )
    vals, J = _guided_rotor_eval(
        rotor, wind[widx], yaw, pitch_mean[:, widx], telemetry=telemetry)

    # mean hub loads with the reference's ordering quirk [T, Y, Z, My, Q, Mz]
    # (raft/raft_rotor.py:350-351), shifted to the PRP
    F_hub = np.stack(
        [vals[..., 0], vals[..., 6], vals[..., 7],
         vals[..., 8], vals[..., 1], vals[..., 9]], axis=-1,
    )
    rHub = np.array([0.0, 0.0, model0.hHub])
    F0[:, widx] = np.asarray(transform_force(F_hub, offset=rHub))

    dT_dU, dT_dOm, dT_dPi = J[..., 0, 0], J[..., 0, 1], J[..., 0, 2]
    dQ_dU, dQ_dOm, dQ_dPi = J[..., 1, 0], J[..., 1, 1], J[..., 1, 2]
    if model0.aeroServoMod == 1:
        b[:, widx] = dT_dU[..., None]
    else:
        kp_beta, ki_beta, kp_tau, ki_tau = rotor.case_gains(wind[widx])
        _, _, a_w, b_w = servo_transfer_terms(
            model0.w, dT_dU, dT_dOm, dT_dPi, dQ_dU, dQ_dOm, dQ_dPi,
            kp_beta, ki_beta, kp_tau, ki_tau,
            rotor.k_float, rotor.Ng, rotor.I_drivetrain, rotor.Zhub,
        )
        a[:, widx] = a_w
        b[:, widx] = b_w
    return a, b, F0


def _ballast_combine(v, b):
    """Statics for the full ballast axis of one draft variant by linear
    combination (b : [nB] ballast density scales).

    Returns dict of arrays with leading nB axis.
    """
    b = np.asarray(b, np.float64)
    mass = v.m0 + b * (v.m1 - v.m0)                       # [nB]
    mCG = v.mCG0[None] + b[:, None] * (v.mCG1 - v.mCG0)   # [nB, 3]
    rCG = mCG / mass[:, None]
    M_struc = v.M0[None] + b[:, None, None] * (v.M1 - v.M0)
    C_struc = v.C0[None] + b[:, None, None] * (v.C1 - v.C0)
    return dict(mass=mass, rCG=rCG, M_struc=M_struc, C_struc=C_struc)


def _pipeline_placers(mesh):
    """(put_design, put_replicated) placement callables for the dynamics
    pipeline operands.  With a 1-D ``('design',)`` mesh, per-design
    operands shard along the within-group design axis (axis 1 — the
    lax.map group axis 0 stays serial on every device) and case/frequency
    operands replicate, so the jitted pipeline runs SPMD with zero
    communication (the design axis is embarrassingly parallel, SURVEY.md
    §2.4); without a mesh both are plain default-device placements."""
    if mesh is None:
        return jax.device_put, jnp.asarray
    from jax.sharding import NamedSharding, PartitionSpec as P

    s_d = NamedSharding(mesh, P(None, "design"))
    s_r = NamedSharding(mesh, P())
    return (lambda x: jax.device_put(x, s_d),
            lambda x: jax.device_put(x, s_r))


def _dynamics_pipeline(model0, return_xi, nIter=None, relax=0.8):
    """Jitted sweep dynamics for ``model0``'s configuration, cached so
    repeated sweeps (and the benchmark's hot re-run) reuse one executable.
    ``nIter``/``relax`` overrides serve the bounded non-convergence retry
    (doubled iteration budget, stronger under-relaxation)."""
    return _dynamics_pipeline_cached(
        model0.w.tobytes(), np.asarray(model0.k).tobytes(), model0.nw,
        float(model0.depth), float(model0.rho_water), float(model0.g),
        float(model0.XiStart), int(nIter or model0.nIter),
        np.dtype(model0.dtype).name, np.dtype(model0.cdtype).name,
        float(model0.hHub), bool(return_xi), float(relax),
    )


@lru_cache(maxsize=16)
def _dynamics_pipeline_cached(w_bytes, k_bytes, nw, depth, rho, g,
                              XiStart, nIter, dtype_name, cdtype_name,
                              hHub, return_xi, relax=0.8):
    """Build the jitted sweep pipeline: lax.map over draft groups, vmap
    over (draft-in-group, ballast, case).

    The per-(design, case) aero-servo hub terms enter as rank-1 frequency
    profiles: M_lin(w) = M0 + a(w) * P_hub and B_lin(w) = b(w) * P_hub,
    where P_hub is the constant 6x6 pattern of a unit fore-aft hub added
    mass translated to the PRP (translate_matrix_3to6 is linear in its 3x3
    argument, so the full [nw,6,6] hub matrices never leave the device
    graph; the reference assembles them on host per case,
    raft/raft_model.py:552-555)."""
    from raft_tpu.utils.frames import translate_matrix_3to6

    dtype = np.dtype(dtype_name).type
    cdtype = np.dtype(cdtype_name).type
    w = np.frombuffer(w_bytes, np.float64, count=nw)
    k = np.frombuffer(k_bytes, np.float64, count=nw)
    dw = float(w[1] - w[0])
    one_case = make_case_dynamics(
        w, k, depth, rho, g, XiStart, nIter, dtype, cdtype, relax=relax,
    )
    E00 = np.zeros((1, 3, 3))
    E00[0, 0, 0] = 1.0
    P_hub = jnp.asarray(
        np.asarray(translate_matrix_3to6(E00, np.array([0.0, 0.0, hHub])))[0],
        dtype,
    )

    def per_design(nodes, zeta, beta, C_case, M0, a_c, b_c):
        Fz = jnp.zeros((nw, 6), dtype)

        def fn(z, b, C, a1, b1):
            M_lin = M0[None] + a1[:, None, None] * P_hub
            B_lin = b1[:, None, None] * P_hub
            return one_case(nodes, z, b, C, M_lin, B_lin, Fz, Fz)

        xr, xi, rep = jax.vmap(fn)(
            zeta, beta, C_case, a_c, b_c
        )  # [nc, ...]; rep: SolveReport with [nc] fields
        std = jnp.sqrt(jnp.sum(xr * xr + xi * xi, axis=-1) * dw)  # [nc, 6]
        if return_xi:
            return std, rep, xr, xi
        return std, rep

    # [gd, nB] design axes inside a group; nodes shared along ballast
    per_draft = jax.vmap(per_design, in_axes=(None, None, None, 0, 0, 0, 0))
    per_group = jax.vmap(per_draft, in_axes=(0, None, None, 0, 0, 0, 0))

    def pipeline(nodes_g, zeta, beta, C_g, M0_g, a_g, b_g):
        def step(xs):
            nodes, C, M0, a_c, b_c = xs
            return per_group(nodes, zeta, beta, C, M0, a_c, b_c)

        return jax.lax.map(step, (nodes_g, C_g, M0_g, a_g, b_g))

    return jax.jit(pipeline)


def _unpack_dyn(dyn, nd_flat, ncc, return_xi, nw):
    """Pipeline output for one case chunk -> dict of host arrays with a
    flattened leading [nd_flat] design axis and a [ncc] case axis."""
    rep = dyn[1]
    out = {
        "std": np.asarray(dyn[0], np.float64).reshape(nd_flat, ncc, 6),
        "iters": np.asarray(rep.iters).reshape(nd_flat, ncc),
        "converged": np.asarray(rep.converged).reshape(nd_flat, ncc),
        "nonfinite": np.asarray(rep.nonfinite).reshape(nd_flat, ncc),
        "recovery_tier": np.asarray(
            rep.recovery_tier).reshape(nd_flat, ncc),
        "residual": np.asarray(
            rep.residual, np.float64).reshape(nd_flat, ncc),
        "cond": np.asarray(rep.cond, np.float64).reshape(nd_flat, ncc),
    }
    if return_xi:
        out["xr"] = np.asarray(dyn[2], np.float64).reshape(
            nd_flat, ncc, 6, nw)
        out["xi"] = np.asarray(dyn[3], np.float64).reshape(
            nd_flat, ncc, 6, nw)
    return out


def _overlap_case_chunks(wind, aero_on, overlap, nd_aero):
    """Case-axis chunks for the aero-second -> dynamics overlap, or None
    for the barrier-preserving single-dispatch path.

    The split is along the WIND-CASE axis: wind-free cases need no rotor
    second pass, so their dynamics dispatch goes out first (the device
    starts while the host begins rotor work), and the wind cases are cut
    into two double-buffered chunks — the dispatch for chunk k runs while
    the host computes rotor loads for chunk k+1.

    Barrier fallback when: overlap is False (or RAFT_TPU_NO_OVERLAP=1),
    a single case, aero off / no wind cases (nothing to overlap), or —
    under overlap='auto' — a sweep too small for the rotor stage to
    matter (each chunk shape is its own compiled executable; tiny test
    sweeps should not pay that).
    """
    import os

    nc = len(wind)
    if os.environ.get("RAFT_TPU_NO_OVERLAP") == "1" or overlap is False:
        return None
    widx = np.where(wind > 0.0)[0]
    if nc <= 1 or not aero_on or len(widx) == 0:
        return None
    if overlap == "auto" and nd_aero * len(widx) < 256:
        return None
    calm = np.where(~(wind > 0.0))[0]
    chunks = []
    if len(calm):
        chunks.append(calm)
    if len(widx) >= 2:
        half = (len(widx) + 1) // 2
        chunks.extend([widx[:half], widx[half:]])
    else:
        chunks.append(widx)
    return chunks


def _chunked_aero_dynamics(model0, cases, wind, aero_on, pitch_mean,
                           make_dev_args, nd_aero, nd_flat, return_xi,
                           retry_nonconverged, label, tracer,
                           overlap="auto", via_buckets=False):
    """The aero-second -> dynamics hand-off, split along the wind-case
    axis into double-buffered chunks: the jitted dynamics dispatch for
    chunk k is ASYNCHRONOUS (the old path blocked on one fused dispatch),
    so it runs on the device while the host computes rotor loads for
    chunk k+1; with one chunk this is exactly the old barrier path.

    make_dev_args(case_idx, a_sub, b_sub) builds the (sharded/placed)
    pipeline operands for that case subset; case-independent operands
    should be placed once by the caller and closed over.

    Returns (sol, a_hub, b_hub, F_aero2, telemetry, timing) where sol
    carries the merged [nd_flat, nc] solve results + the bounded
    non-convergence retry, and timing the stage spans/overlap metrics.
    """
    from raft_tpu.utils.profiling import compiled_flops

    nc = len(cases)
    nw = model0.nw
    waterfall_flops = None     # set when the waterfall pipeline is live
    chunks = _overlap_case_chunks(wind, aero_on, overlap, nd_aero)
    barrier = chunks is None
    if barrier:
        chunks = [np.arange(nc)]
    telemetry = _blank_rotor_telemetry()
    a_hub = np.zeros((nd_aero, nc, nw))
    b_hub = np.zeros((nd_aero, nc, nw))
    F_aero2 = np.zeros((nd_aero, nc, 6))
    if via_buckets:
        # canonical serving-bucket executables instead of the fused
        # sweep-shaped pipeline (raft_tpu/sweep_buckets.py): same lane
        # arithmetic contract, shared compiled programs with the serve
        # layer, every bucket recorded in the warm-up manifest.  The
        # bounded retry below intentionally stays on the legacy
        # pipeline (non-canonical nIter/relax overrides).
        from raft_tpu.sweep_buckets import fused_bucket_pipeline

        pipeline = fused_bucket_pipeline(model0, return_xi)
    elif (fixed_point_mode() != "legacy" and jax.process_count() == 1
          and not apply_debug_nans()):
        # convergence-aware iteration waterfall (raft_tpu/waterfall.py):
        # hop out converged lanes between fixed K-iteration blocks and
        # compact survivors down the canonical lane ladder.  The bounded
        # retry below stays on the legacy pipeline — escalated
        # (nIter, relax) re-solves are health-ladder reference paths.
        from raft_tpu.waterfall import fused_waterfall_pipeline

        pipeline = fused_waterfall_pipeline(model0, return_xi)
        waterfall_flops = 0.0
    else:
        pipeline = _dynamics_pipeline(model0, return_xi)
    backend = jax.default_backend()

    t_engine0 = time.perf_counter()
    t_rotor = 0.0
    inflight = []
    for k, ci in enumerate(chunks):
        ci = np.asarray(ci, int)
        wsub = wind[ci]
        if aero_on and np.any(wsub > 0.0):
            with tracer.span("aero_second", backend="cpu", chunk=k,
                             cases=len(ci)) as sp:
                a_c, b_c, F_c = _aero_second_pass(
                    model0, [cases[i] for i in ci], wsub,
                    pitch_mean[:, ci], telemetry=telemetry)
            t_rotor += sp["t1"] - sp["t0"]
            a_hub[:, ci] = a_c
            b_hub[:, ci] = b_c
            F_aero2[:, ci] = F_c
        dev_args = make_dev_args(ci, a_hub[:, ci], b_hub[:, ci])
        h = tracer.begin("dynamics", backend=backend, chunk=k,
                         cases=len(ci))
        dyn = pipeline(*dev_args)      # async dispatch: host continues
        if waterfall_flops is not None:
            # the waterfall pipeline is a synchronous host loop over
            # jitted phase programs: harvest its executed-flops ledger
            # per call (a single compiled cost model does not exist)
            from raft_tpu.waterfall import last_dispatch_stats
            waterfall_flops += float(
                last_dispatch_stats().get("flops_executed", 0.0))
        inflight.append((ci, dev_args, dyn, h))

    parts = []
    for ci, dev_args, dyn, h in inflight:
        jax.block_until_ready(dyn)
        tracer.end(h)
        parts.append((ci, _unpack_dyn(dyn, nd_flat, len(ci), return_xi,
                                      nw)))
    t_engine = time.perf_counter() - t_engine0
    dyn_flops = (waterfall_flops if waterfall_flops is not None else
                 sum(compiled_flops(pipeline, dev_args)
                     for _, dev_args, _, _ in inflight))

    # merge chunk columns back into [nd_flat, nc] order
    sol = {}
    for key, part0 in parts[0][1].items():
        full = np.empty((nd_flat, nc) + part0.shape[2:], part0.dtype)
        for ci, part in parts:
            full[:, ci] = part[key]
        sol[key] = full

    # bounded retry: re-solve only the chunks carrying non-converged
    # finite lanes (all retry dispatches issued async, then adopted per
    # lane only where the retry converges — first-pass-healthy lanes
    # stay bit-identical)
    retry_mask = ~sol["converged"] & ~sol["nonfinite"]
    sol["retried"] = np.zeros_like(retry_mask)
    retry_policy = SolveRetryPolicy.from_flag(retry_nonconverged)
    if retry_policy.enabled and retry_mask.any():
        nIter2, relax2 = retry_policy.escalate(model0.nIter)
        pipe2 = _dynamics_pipeline(
            model0, return_xi, nIter=nIter2, relax=relax2)
        redo = []
        for ci, dev_args, _, _ in inflight:
            if retry_mask[:, ci].any():
                h = tracer.begin("dynamics_retry", backend=backend,
                                 cases=len(ci))
                redo.append((ci, pipe2(*dev_args), h))
        n_rec = 0
        for ci, dyn2, h in redo:
            jax.block_until_ready(dyn2)
            tracer.end(h)
            part2 = _unpack_dyn(dyn2, nd_flat, len(ci), return_xi, nw)
            use = retry_mask[:, ci] & part2["converged"]
            n_rec += int(use.sum())
            sol["std"][:, ci] = np.where(
                use[:, :, None], part2["std"], sol["std"][:, ci])
            for key in ("iters", "converged", "nonfinite",
                        "recovery_tier", "residual", "cond"):
                sol[key][:, ci] = np.where(use, part2[key], sol[key][:, ci])
            if return_xi:
                for key in ("xr", "xi"):
                    sol[key][:, ci] = np.where(
                        use[:, :, None, None], part2[key],
                        sol[key][:, ci])
        sol["retried"] = retry_mask
        logger.warning(
            "%s: %d non-converged lane(s) retried with nIter=%d / "
            "relax=%.2g; %d recovered",
            label, int(retry_mask.sum()), nIter2, relax2, n_rec,
        )

    # overlap accounting: the union-vs-sum savings PLUS its per-backend
    # decomposition (cross_backend_s = seconds the CPU rotor stage and the
    # device dynamics were simultaneously busy; within_backend_s = extra
    # concurrency among same-backend spans, e.g. double-buffered async
    # dynamics chunks in flight together — the two used to be conflated
    # in overlap_saved_s, ROADMAP open item)
    decomp = tracer.overlap_backend_decomposition("aero_second", "dynamics")
    timing = {
        "aero_second_s": t_rotor,
        "dynamics_first_s": tracer.stage_wall("dynamics"),
        "overlap_chunks": len(chunks),
        "overlap_saved_s": tracer.overlap_saved_s(
            "aero_second", "dynamics"),
        "overlap_cross_backend_s": decomp["cross_backend_s"],
        "overlap_within_backend_s": sum(
            decomp["within_backend_s"].values()),
        "rotor_dyn_wall_s": t_engine,
    }
    return sol, a_hub, b_hub, F_aero2, telemetry, timing, dyn_flops


def _quarantine_design_rows(res, fmask, lead_shape):
    """Mask failed designs' rows across every per-design result array
    (floats -> NaN, bools -> False, ints -> 0) so a quarantined slot can
    never be mistaken for physics."""
    if not fmask.any():
        return
    nlead = len(lead_shape)
    for key, a in list(res.items()):
        if not isinstance(a, np.ndarray) or a.shape[:nlead] != lead_shape:
            continue
        a = np.array(a)  # some result arrays are read-only jax views
        if a.dtype == bool:
            a[fmask] = False
        elif np.issubdtype(a.dtype, np.integer):
            a[fmask] = 0
        else:
            a[fmask] = np.nan
        res[key] = a


def run_draft_ballast_sweep(
    base_design,
    draft_scales,
    ballast_scales,
    precision=None,
    draft_group=4,
    return_xi=False,
    verbose=True,
    mesh=None,
    retry_nonconverged=True,
    overlap="auto",
    tracer=None,
    via_buckets=None,
):
    """Run the fused draft x ballast sweep.

    Parameters
    ----------
    base_design : dict
        VolturnUS-S-style design (must have a cases table).  Operating-wind
        cases run the full aero-servo path (aeroServoMod 1/2): per-case
        mean rotor loads feed the mooring equilibria, and each design's
        mean-pitch rotor re-evaluation contributes hub added mass a(w) and
        damping b(w) to the dynamics — matching the reference sweep, which
        runs the complete model per point (raft/parametersweep.py:56-100).
    draft_scales : [nD] multipliers on submerged member depths.
    ballast_scales : [nB] multipliers on ballast fill density.
    draft_group : drafts per lax.map step (bounds device memory:
        gd * nB * nc wave-kinematics lanes live at once — per device when
        a mesh is given).
    return_xi : also return the full complex response amplitudes
        [nD, nB, nc, 6, nw] (extra device->host transfer).
    mesh : jax.sharding.Mesh | None
        Optional 1-D ``('design',)`` mesh: the dynamics dispatch shards
        the within-group draft axis across devices (``draft_group`` must
        be divisible by the mesh size); results are identical to the
        single-device path (asserted by the multichip dryrun).
    overlap : 'auto' | True | False
        Split the aero-second -> dynamics hand-off along the wind-case
        axis into double-buffered chunks so the async dynamics dispatch
        for chunk k runs while the host computes rotor loads for chunk
        k+1 (see :func:`_chunked_aero_dynamics`); 'auto' engages it only
        for sweeps large enough for the rotor stage to matter, False (or
        RAFT_TPU_NO_OVERLAP=1) forces the barrier-preserving single
        dispatch.
    tracer : raft_tpu.trace.Tracer | None
        Span recorder for the stage timeline (a fresh one is created per
        run when None); returned as ``res["tracer"]`` and dumped as a
        chrome://tracing JSON when RAFT_TPU_TRACE is set.

    Returns dict with metrics [nD, nB, ...], timing breakdown (including
    the measured overlap savings), per-run rotor telemetry, and the
    mooring/statics intermediates the benchmark asserts against.
    """
    from raft_tpu.trace import Tracer

    t_start = time.perf_counter()
    tracer = tracer or Tracer("fused_sweep")
    model0 = Model(base_design, precision=precision)
    nD, nB = len(draft_scales), len(ballast_scales)
    nd = nD * nB
    if nD % draft_group:
        raise ValueError("len(draft_scales) must be divisible by draft_group")

    cases = cases_as_dicts(base_design)
    spec, height, period, beta, wind = model0._case_arrays(cases)
    zeta = model0._zeta(spec, height, period)              # [nc, nw] f64
    nc = zeta.shape[0]
    aero_on = (
        model0.rotor is not None
        and model0.aeroServoMod > 0
        and bool(np.any(wind > 0.0))
    )
    if np.any(wind > 0.0) and not aero_on:
        import warnings

        warnings.warn(
            "run_draft_ballast_sweep: cases specify operating wind but the "
            "design has aero off (aeroServoMod=0 or no rotor data); the "
            "sweep runs WITHOUT wind loading, like the reference's "
            "aeroServoMod gate (reference raft/raft_fowt.py:445)",
            stacklevel=2,
        )

    # ---- host prep: one variant per draft, ballast by linearity
    # (threaded + variant-cached like the general design sweep).  Fault
    # isolation: a draft whose prep raises is quarantined — its slot is
    # filled with the first healthy draft to keep the batch shape, and
    # every (draft, ballast) row it covers is reported NaN + failed. ----
    t0 = time.perf_counter()
    from concurrent.futures import ThreadPoolExecutor

    def _safe_prep(s):
        try:
            return _prepare_draft(
                base_design, s, model0.rho_water, model0.g), None
        except Exception as e:  # noqa: BLE001 — quarantine any prep fault
            return None, f"{type(e).__name__}: {e}"

    with ThreadPoolExecutor(max_workers=8) as ex:
        prepped = list(ex.map(_safe_prep, draft_scales))
    failed_drafts = [(i, msg) for i, (v, msg) in enumerate(prepped)
                     if v is None]
    for i, msg in failed_drafts:
        logger.warning(
            "fused sweep draft %d (scale %g) quarantined: prep raised (%s)",
            i, float(draft_scales[i]), msg,
        )
    ok = [i for i, (v, _) in enumerate(prepped) if v is not None]
    if not ok:
        raise RuntimeError(
            "run_draft_ballast_sweep: every draft variant failed host-side "
            f"preparation; first error: {failed_drafts[0][1]}"
        )
    variants = [prepped[i][0] if prepped[i][0] is not None
                else prepped[ok[0]][0] for i in range(nD)]
    b = np.asarray(ballast_scales, np.float64)
    comb = [_ballast_combine(v, b) for v in variants]
    t_host = time.perf_counter() - t0
    tracer.add("host_prep", t_host, backend="cpu")

    # ---- aero first pass: per-case mean loads at zero pitch ----
    # (design-independent, so one batched rotor evaluation serves the
    # whole sweep; the reference re-runs it per point).  Reuses the
    # second-pass machinery at a single zero-pitch "design" lane.
    t0 = time.perf_counter()
    F_prp = (
        _aero_second_pass(model0, cases, wind, np.zeros((1, nc)))[2][0]
        if aero_on else np.zeros((nc, 6))
    )
    t_aero1 = time.perf_counter() - t0
    tracer.add("aero_first", t_aero1, backend="cpu")

    # ---- mooring: all designs x distinct-mean-load cases in one f64 CPU
    # call.  Cases sharing the same mean load (all wind-free cases, and
    # repeated wind speeds) collapse to one equilibrium per design; the
    # NumPy baseline in bench_sweep.py applies the same collapse, so the
    # timed comparison stays symmetric. ----
    t0 = time.perf_counter()
    moor_fn = case_mooring_design_batch_fn(
        model0.rho_water, model0.g, model0.yawstiff
    )
    rep = lambda a: np.repeat(np.asarray(a, np.float64), nB, axis=0)  # noqa: E731
    mass_all = np.concatenate([c["mass"] for c in comb])              # [nd]
    rCG_all = np.concatenate([c["rCG"] for c in comb])                # [nd, 3]
    V_all = rep([v.V for v in variants])
    AWP_all = rep([v.AWP for v in variants])
    rM_all = np.stack(
        [np.array([0.0, 0.0, v.zMeta]) for v in variants for _ in range(nB)]
    )
    moor_all = tuple(
        rep(np.stack([v.moor[i] for v in variants])) for i in range(7)
    )
    bridles_all = _stack_bridles(variants, rep)
    F0g, inv = _mean_load_case_groups(F_prp, nc)
    F0 = np.broadcast_to(F0g[None], (nd, len(F0g), 6)).copy()
    out = moor_fn(*put_cpu((F0, mass_all, V_all, rCG_all, rM_all, AWP_all))
                  , *put_cpu(moor_all),
                  put_cpu(bridles_all) if bridles_all is not None else None)
    expand = lambda a: np.asarray(a)[:, inv].copy()  # noqa: E731
    r6, C_moor, F_moor, T_moor, J_moor, moor_resid = (
        expand(o) for o in out)
    warn_bridle_residual(moor_resid, label="design")
    t_moor = time.perf_counter() - t0
    tracer.add("mooring", t_moor, backend="cpu")

    # ---- aero second pass + dynamics, overlapped along the case axis:
    # case-independent operands are placed once, then the chunk engine
    # interleaves host rotor work with async dynamics dispatches ----
    if mesh is not None and draft_group % mesh.size:
        raise ValueError(
            f"draft_group ({draft_group}) must be divisible by the "
            f"design-mesh size ({mesh.size})")
    dtype = model0.dtype
    G = nD // draft_group
    nodes_all = pad_and_stack_nodes([v.nodes.astype(dtype) for v in variants])
    shp = lambda a: a.reshape((G, draft_group) + a.shape[1:])  # noqa: E731
    nodes_g = jax.tree.map(shp, nodes_all)
    C_lin = (
        np.stack([c["C_struc"] for c in comb])[:, :, None]
        + np.stack([v.C_hydro for v in variants])[:, None, None]
        + C_moor.reshape(nD, nB, nc, 6, 6)
    )                                                          # [nD, nB, nc, 6, 6]
    M0_all = (
        np.stack([c["M_struc"] for c in comb])
        + np.stack([v.A_morison for v in variants])[:, None]
    )                                                          # [nD, nB, 6, 6]

    put_d, put_r = _pipeline_placers(mesh)
    nodes_dev = jax.tree.map(put_d, nodes_g) if mesh is not None \
        else jax.device_put(nodes_g)
    M0_dev = put_d(shp(M0_all.astype(dtype)))
    beta_f = np.asarray(beta, dtype)

    def make_dev_args(ci, a_sub, b_sub):
        ncc = len(ci)
        return (
            nodes_dev,
            put_r(zeta[ci].astype(dtype)),
            put_r(beta_f[ci]),
            put_d(shp(C_lin[:, :, ci].astype(dtype))),
            M0_dev,
            put_d(shp(a_sub.reshape(nD, nB, ncc, model0.nw)
                      .astype(dtype))),
            put_d(shp(b_sub.reshape(nD, nB, ncc, model0.nw)
                      .astype(dtype))),
        )

    sol, a_hub, b_hub, F_aero2, rotor_tel, eng_timing, dyn_flops = \
        _chunked_aero_dynamics(
            model0, cases, wind, aero_on, r6[:, :, 4], make_dev_args,
            nd, nd, return_xi, retry_nonconverged,
            f"fused sweep {nD}x{nB}", tracer, overlap=overlap,
            via_buckets=sweep_buckets_enabled(via_buckets),
        )  # dynamics_first_s includes compile on first call
    std = sol["std"]
    iters = sol["iters"]
    conv = sol["converged"]

    # ---- metrics (reference parametersweep getOutputs semantics,
    # reference raft/parametersweep.py:9-21) ----
    offset = np.hypot(r6[:, 0, 0], r6[:, 0, 1])
    pitch = np.rad2deg(r6[:, 0, 4])
    # omdao-style aggregates (omdao.py:728-733): per-case mean + 3*std
    # maxima, incl. the reference's sway_max-from-heave_std quirk
    # (raft_fowt.py:716), then the max over cases
    surge_max = r6[:, :, 0] + 3.0 * std[:, :, 0]           # [nd, nc]
    sway_max = r6[:, :, 1] + 3.0 * std[:, :, 2]
    pitch_max = np.rad2deg(r6[:, :, 4] + 3.0 * std[:, :, 4])
    res = {
        "draft_scales": np.asarray(draft_scales, float),
        "ballast_scales": b,
        "mass": mass_all.reshape(nD, nB),
        "displacement": (model0.rho_water * V_all).reshape(nD, nB),
        "GMT": (rM_all[:, 2] - rCG_all[:, 2]).reshape(nD, nB),
        "offset": offset.reshape(nD, nB),
        "pitch_deg": pitch.reshape(nD, nB),
        "surge_std": std[:, :, 0].reshape(nD, nB, nc),
        "heave_std": std[:, :, 2].reshape(nD, nB, nc),
        "pitch_std_deg": np.rad2deg(std[:, :, 4]).reshape(nD, nB, nc),
        "std": std.reshape(nD, nB, nc, 6),
        "converged": conv.reshape(nD, nB, nc),
        "iters": iters.reshape(nD, nB, nc),
        # per-point solver health (raft_tpu/health.py SolveReport fields)
        "nonfinite": sol["nonfinite"].reshape(nD, nB, nc),
        "recovery_tier": sol["recovery_tier"].reshape(nD, nB, nc),
        "residual": sol["residual"].reshape(nD, nB, nc),
        "cond": sol["cond"].reshape(nD, nB, nc),
        "retried": sol["retried"].reshape(nD, nB, nc),
        "Xi0": r6.reshape(nD, nB, nc, 6),
        "T_moor": T_moor.reshape((nD, nB) + T_moor.shape[1:]),
        "moor_resid": moor_resid.reshape(nD, nB, nc),
        # per-case aggregates (the omdao Max_Offset / Max_PtfmPitch view)
        "offset_max": np.hypot(surge_max, sway_max).max(axis=1).reshape(nD, nB),
        "pitch_max_deg": pitch_max.max(axis=1).reshape(nD, nB),
        # second-pass mean aero loads at the PRP (zero for wind-free cases)
        "F_aero0": F_aero2.reshape(nD, nB, nc, 6),
        "dynamics_flops": dyn_flops,
        "rotor_telemetry": rotor_tel,
        "tracer": tracer,
        "timing": {
            "host_prep_s": t_host,
            "aero_first_s": t_aero1,
            "mooring_s": t_moor,
            **eng_timing,
            "total_s": time.perf_counter() - t_start,
        },
    }
    if return_xi:
        res["Xi"] = (sol["xr"] + 1j * sol["xi"]).reshape(
            nD, nB, nc, 6, model0.nw)
    # quarantined drafts: NaN every row they cover + report them
    fmask = np.zeros((nD, nB), bool)
    for i, _ in failed_drafts:
        fmask[i] = True
    _quarantine_design_rows(res, fmask, (nD, nB))
    res["failed"] = [
        {"index": i, "point": {"draft_scale": float(draft_scales[i])},
         "error": msg}
        for i, msg in failed_drafts
    ]
    res["failed_mask"] = fmask
    tracer.maybe_dump_env()
    if verbose:
        tm = res["timing"]
        logger.info(
            "fused sweep %dx%d: host %.2fs, aero %.2fs, mooring %.2fs, "
            "dynamics(first) %.2fs, overlap saved %.2fs "
            "(%d chunk(s), %d host device(s)), total %.2fs",
            nD, nB, tm["host_prep_s"],
            tm["aero_first_s"] + tm["aero_second_s"], tm["mooring_s"],
            tm["dynamics_first_s"], tm["overlap_saved_s"],
            tm["overlap_chunks"], rotor_tel["rotor_host_devices"],
            tm["total_s"],
        )
    return res




# ------------------------------------------------------------------------
# general geometry sweeps (reference parametersweep.py's 5-parameter study)
# ------------------------------------------------------------------------

def apply_volturnus_point(design, ccD=1.0, ocD=1.0, draft=1.0,
                          spacing=1.0, pontoon=1.0):
    """Reference-style 5-parameter VolturnUS-S geometry variation: scale
    factors (1.0 = base design) on center-column diameter, outer-column
    diameter, draft, column spacing (outer-column radius), and pontoon
    height, with the dependent updates the reference's sweep applies —
    pontoon/support endpoints track the column faces, pontoon centerline
    tracks the keel + half height, and the vessel fairleads track the
    outer columns' outboard face (reference raft/parametersweep.py:56-100;
    the scales here compose cleanly where the reference's in-loop
    mutations are order-dependent).
    """
    d = copy.deepcopy(design)
    mem = d["platform"]["members"]
    cc = float(mem[0]["d"]) * ccD
    oc = float(mem[1]["d"]) * ocD
    T = float(mem[1]["rA"][2]) * draft
    R = float(mem[1]["rA"][0]) * spacing
    h = float(mem[2]["d"][1]) * pontoon
    mem[0]["d"] = cc
    mem[0]["rA"] = [0.0, 0.0, T]
    mem[1]["d"] = oc
    mem[1]["rA"] = [R, float(mem[1]["rA"][1]), T]
    mem[1]["rB"] = [R, float(mem[1]["rB"][1]), float(mem[1]["rB"][2])]
    z_p = T + h / 2.0
    mem[2]["d"] = [float(mem[2]["d"][0]), h]
    mem[2]["rA"] = [cc / 2.0, float(mem[2]["rA"][1]), z_p]
    mem[2]["rB"] = [R - oc / 2.0, float(mem[2]["rB"][1]), z_p]
    mem[3]["rA"][0] = cc / 2.0
    mem[3]["rB"][0] = R - oc / 2.0
    rF = R + oc / 2.0
    for p in d["mooring"]["points"]:
        if p.get("type") == "vessel":
            x, y = float(p["location"][0]), float(p["location"][1])
            r = max((x * x + y * y) ** 0.5, 1e-12)
            p["location"][0] = x / r * rF
            p["location"][1] = y / r * rF
    return d


def _unit_fill(member):
    """Member copy with unit ballast density where filled (the derivative
    direction of a uniform density shift, cf. Model.adjust_ballast_density)."""
    rf = np.asarray(member.rho_fill, float)
    unit = np.where(rf > 0.0, 1.0, 0.0)
    return dataclasses.replace(
        member, rho_fill=float(unit) if np.isscalar(member.rho_fill) else unit
    )


def _stack_bridles(variants, rep=None):
    """Stack per-variant BridleSet arrays along the design axis (order
    matching BridleSet.arrays()) for the batched mooring solve; None when
    the design family is unbridled.  ``rep`` optionally replicates each
    design's arrays along a ballast axis (the draft x ballast sweep)."""
    bs = [v.bridles for v in variants]
    if all(b is None for b in bs):
        return None
    if any(b is None for b in bs):
        raise ValueError(
            "mixed sweep: every design must have bridles or none must "
            "(the batched mooring solve shares one executable)"
        )
    fields = ("kind", "ends", "L", "EA", "w", "Wp", "cb", "Wj", "p0")
    out = tuple(
        np.stack([np.asarray(getattr(b, f), np.float64) for b in bs])
        for f in fields
    )
    if rep is not None:
        out = tuple(rep(a) for a in out)
    return out


@dataclasses.dataclass
class _GeomVariant:
    """Host-side preprocessing of one general design point."""

    nodes: object
    moor: tuple
    bridles: object            # BridleSet or None
    A_morison: np.ndarray
    S1: object                 # statics at the design's ballast densities
    S0: object = None          # fill scale 0 (for the density-trim algebra)
    Su: object = None          # unit fill density


# design-dict -> prepared-variant cache (VERDICT r2 #9: repeated sweeps —
# the benchmark's warm re-run, optimization loops revisiting points — skip
# the geometry/statics host prep entirely).  Keyed on the fields the prep
# actually consumes: platform + mooring + tower + the RNA lumped
# properties.  FIFO-evicted by an approximate byte budget (each entry's
# dominant cost is its HydroNodes bundle).
_variant_cache = {}
_VARIANT_CACHE_BYTES = 512 * 1024 * 1024
_variant_cache_held = [0]
_variant_cache_lock = __import__("threading").Lock()


def _variant_nbytes(v):
    import dataclasses as _dc

    n = 0
    for f in _dc.fields(type(v.nodes)):
        a = getattr(v.nodes, f.name)
        n += getattr(a, "nbytes", 0)
    return n + 4096  # statics + mooring arrays are small


def _variant_cache_put(key, v):
    nb = _variant_nbytes(v)
    if nb > _VARIANT_CACHE_BYTES:
        return
    with _variant_cache_lock:   # prep runs in a thread pool
        if key in _variant_cache:
            return
        while _variant_cache and (
                _variant_cache_held[0] + nb > _VARIANT_CACHE_BYTES):
            old = _variant_cache.pop(next(iter(_variant_cache)))
            _variant_cache_held[0] -= _variant_nbytes(old)
        _variant_cache[key] = v
        _variant_cache_held[0] += nb


def _design_key(design):
    import json

    t = design.get("turbine", {})
    rna = {k: t.get(k) for k in ("mRNA", "IxRNA", "IrRNA", "xCG_RNA",
                                 "hHub")}
    # the tower member is part of process_members' output, so it belongs
    # in the key alongside the platform members
    return json.dumps(
        [design.get("platform"), design.get("mooring"), rna,
         t.get("tower")],
        sort_keys=True, default=float,
    )


def _prepare_design_point(design, rho_water, g, need_trim):
    key = (_design_key(design), float(rho_water), float(g), bool(need_trim))
    hit = _variant_cache.get(key)
    if hit is not None:
        return hit
    members = process_members(design)
    nodes = pack_nodes(members)
    turbine = design["turbine"]
    S1 = compute_statics(members, turbine, rho_water, g)
    ms = parse_mooring(design["mooring"], rho_water=rho_water, g=g)
    A = np.asarray(_am_f64(put_cpu(nodes.astype(np.float64)), rho_water))
    v = _GeomVariant(
        nodes=nodes,
        moor=(ms.anchors, ms.rFair, ms.L, ms.EA, ms.w, ms.Wp, ms.cb),
        bridles=ms.bridles,
        A_morison=A, S1=S1,
    )
    if need_trim:
        v.S0 = compute_statics(
            [_scale_fill(m, 0.0) for m in members], turbine, rho_water, g)
        v.Su = compute_statics(
            [_unit_fill(m) for m in members], turbine, rho_water, g)
    _variant_cache_put(key, v)
    return v


def _batched_prep_points(designs, model0, precision, solo_prep):
    """Flag-gated batched twin of the threaded ``_safe_prep`` map: one
    traced geometry/statics/added-mass dispatch per fixed-size block
    instead of a host loop per design (RAFT_TPU_BATCHED_PREP).

    Designs that don't fit the family (branch-signature mismatch) or
    whose batched stage faults fall back to ``solo_prep`` one by one, so
    the quarantine contract is unchanged.  Returns ``(prepped,
    n_batched)`` with ``prepped`` shaped exactly like the threaded map's
    output, or ``None`` when no family can be built (caller runs the
    threaded path).
    """
    from raft_tpu.batched_prep import PrepFamily, PrepFamilyError

    try:
        family = PrepFamily(designs[0], precision=precision,
                            geometry_only=True)
    except Exception as e:  # noqa: BLE001 — any family fault → host path
        logger.warning(
            "batched design-prep family rejected (%s: %s); using the "
            "host prep path", type(e).__name__, e)
        return None
    rho_w, grav = float(model0.rho_water), float(model0.g)
    prepped = [None] * len(designs)
    lanes, lane_idx = [], []
    for i, d in enumerate(designs):
        key = (_design_key(d), rho_w, grav, False)
        hit = _variant_cache.get(key)
        if hit is not None:
            prepped[i] = (hit, None)
            continue
        try:
            lanes.append(family.extract(d))
            lane_idx.append(i)
        except PrepFamilyError:
            prepped[i] = solo_prep(d)
        except Exception as e:  # noqa: BLE001 — quarantine semantics
            logger.warning(     # live in solo_prep's own try/except
                "design %d: batched prep extract raised (%s: %s); "
                "solo fallback", i, type(e).__name__, e)
            prepped[i] = solo_prep(d)
    n_batched = 0
    if lanes:
        try:
            geoms = family.prepare_geometry(lanes)
        except Exception as e:  # noqa: BLE001 — block fault → solo all
            logger.warning(
                "batched design-prep block faulted (%s: %s); falling "
                "back to per-design host prep", type(e).__name__, e)
            geoms = None
        if geoms is None:
            for i in lane_idx:
                prepped[i] = solo_prep(designs[i])
        else:
            for i, lane, (nodes, S1, A) in zip(lane_idx, lanes, geoms):
                ms = lane["ms"]
                v = _GeomVariant(
                    nodes=nodes,
                    moor=(ms.anchors, ms.rFair, ms.L, ms.EA, ms.w,
                          ms.Wp, ms.cb),
                    bridles=ms.bridles,
                    A_morison=np.asarray(A), S1=S1,
                )
                _variant_cache_put(
                    (_design_key(designs[i]), rho_w, grav, False), v)
                prepped[i] = (v, None)
                n_batched += 1
    return prepped, n_batched


@lru_cache(maxsize=1)
def _unloaded_forces_batch_fn():
    """Jitted zero-pose line forces vmapped over the design axis (cached
    at module level like the other sweep executables)."""
    from raft_tpu.mooring import line_forces

    def f(anchors, rFair, L, EA, w, Wp, cb, bridles=None):
        z6 = jnp.zeros(6, dtype=jnp.float64)
        return line_forces(z6, anchors, rFair, L, EA, w, Wp, cb,
                           bridles)[0]

    return jax.jit(jax.vmap(f))




def _mean_load_case_groups(F_prp, nc):
    """Group cases sharing a mean-load vector (wind-free cases and repeated
    wind speeds collapse to one mooring equilibrium per design).  Returns
    (F0g [ng, 6], inv [nc] group index per case)."""
    groups = {}
    inv = np.zeros(nc, int)
    for i in range(nc):
        inv[i] = groups.setdefault(F_prp[i].tobytes(), len(groups))
    F0g = np.zeros((len(groups), 6))
    for i in range(nc):
        F0g[inv[i]] = F_prp[i]
    return F0g, inv


def run_design_sweep(
    designs,
    precision=None,
    group=16,
    return_xi=False,
    trim_ballast_density=False,
    verbose=True,
    mesh=None,
    retry_nonconverged=True,
    overlap="auto",
    tracer=None,
    via_buckets=None,
):
    """Fused sweep over an arbitrary list of design dicts — the general
    form of the reference's 5-parameter geometry study
    (raft/parametersweep.py:56-100, which rebuilds and re-analyzes a full
    model per point): one strip-node bundle + statics per design on host,
    then batched mooring equilibria, one vmapped rotor re-evaluation, and
    one jitted device dispatch for all designs x cases x frequencies
    (reusing the draft x ballast pipeline with a unit ballast axis).

    trim_ballast_density : closed-form uniform ballast-density trim per
        design (the affine equivalent of Model.adjust_ballast_density —
        the reference sweep runs its incremental adjustBallast per point;
        the closed form is applied symmetrically by the benchmark's
        serial baseline).
    mesh : optional 1-D ``('design',)`` mesh; the dynamics dispatch
        shards the within-group design axis across its devices
        (``group`` must be divisible by the mesh size), results
        identical to the single-device path.
    overlap, tracer : case-axis aero/dynamics overlap and stage-span
        recording, exactly as in :func:`run_draft_ballast_sweep`.

    All designs must share the cases table and frequency settings of
    ``designs[0]``.

    Returns dict of per-design arrays (mass, displacement, GMT, offset,
    pitch_deg, std, ...) shaped [nd, ...]; reshape to the study's axes
    grid for contour matrices.
    """
    from raft_tpu.trace import Tracer

    t_start = time.perf_counter()
    tracer = tracer or Tracer("design_sweep")
    model0 = Model(designs[0], precision=precision)
    nd = len(designs)

    cases = cases_as_dicts(designs[0])
    spec, height, period, beta, wind = model0._case_arrays(cases)
    zeta = model0._zeta(spec, height, period)
    nc = zeta.shape[0]
    aero_on = (
        model0.rotor is not None
        and model0.aeroServoMod > 0
        and bool(np.any(wind > 0.0))
    )

    # ---- host prep: geometry + statics per design (threaded: the numpy
    # work releases the GIL for much of its time, and repeated sweeps hit
    # the variant cache outright) ----
    t0 = time.perf_counter()
    from concurrent.futures import ThreadPoolExecutor

    def _safe_prep(d):
        try:
            return _prepare_design_point(
                d, model0.rho_water, model0.g, trim_ballast_density), None
        except Exception as e:  # noqa: BLE001 — quarantine any prep fault
            return None, f"{type(e).__name__}: {e}"

    n_prep_batched = 0
    prepped = None
    if batched_prep_enabled() and not trim_ballast_density:
        # trim needs S0/Su statics at 0-fill and unit-fill, which only
        # the host path stages — batched prep covers the no-trim sweep
        out = _batched_prep_points(designs, model0, precision, _safe_prep)
        if out is not None:
            prepped, n_prep_batched = out
    if prepped is None:
        with ThreadPoolExecutor(max_workers=8) as ex:
            prepped = list(ex.map(_safe_prep, designs))
    failed_pts = [(i, msg) for i, (v, msg) in enumerate(prepped)
                  if v is None]
    for i, msg in failed_pts:
        logger.warning(
            "design sweep point %d quarantined: prep raised (%s)", i, msg)
    ok = [i for i, (v, _) in enumerate(prepped) if v is not None]
    if not ok:
        raise RuntimeError(
            "run_design_sweep: every design failed host-side preparation; "
            f"first error: {failed_pts[0][1]}"
        )
    # failed designs' slots carry the first healthy design (batch shape
    # only); their result rows are NaN'd + reported below
    variants = [prepped[i][0] if prepped[i][0] is not None
                else prepped[ok[0]][0] for i in range(nd)]
    moor_all = tuple(
        np.stack([np.asarray(v.moor[i], np.float64) for v in variants])
        for i in range(7)
    )
    bridles_all = _stack_bridles(variants)
    t_host = time.perf_counter() - t0
    tracer.add("host_prep", t_host, backend="cpu",
               batched=n_prep_batched > 0,
               batched_designs=n_prep_batched)

    # ---- optional closed-form ballast-density trim ----
    rho_w, grav = model0.rho_water, model0.g
    if trim_ballast_density:
        f6 = _unloaded_forces_batch_fn()(
            *tuple(put_cpu(a) for a in moor_all),
            put_cpu(bridles_all) if bridles_all is not None else None)
        Fz0 = np.asarray(f6)[:, 2]                          # [nd]
        m1 = np.array([v.S1.mass for v in variants])
        Vf = np.array([v.Su.mass - v.S0.mass for v in variants])
        V = np.array([v.S1.V for v in variants])
        delta = (rho_w * V + Fz0 / grav - m1) / np.maximum(Vf, 1e-12)
        mass_all = m1 + delta * Vf
        mCG = np.stack([
            v.S1.mass * v.S1.rCG_TOT
            + dlt * (v.Su.mass * v.Su.rCG_TOT - v.S0.mass * v.S0.rCG_TOT)
            for v, dlt in zip(variants, delta)
        ])
        rCG_all = mCG / mass_all[:, None]
        M_struc = np.stack([
            v.S1.M_struc + dlt * (v.Su.M_struc - v.S0.M_struc)
            for v, dlt in zip(variants, delta)
        ])
        C_struc = np.stack([
            v.S1.C_struc + dlt * (v.Su.C_struc - v.S0.C_struc)
            for v, dlt in zip(variants, delta)
        ])
    else:
        delta = np.zeros(nd)
        mass_all = np.array([v.S1.mass for v in variants])
        rCG_all = np.stack([v.S1.rCG_TOT for v in variants])
        M_struc = np.stack([v.S1.M_struc for v in variants])
        C_struc = np.stack([v.S1.C_struc for v in variants])

    # ---- aero first pass (design-independent) ----
    t0 = time.perf_counter()
    F_prp = (
        _aero_second_pass(model0, cases, wind, np.zeros((1, nc)))[2][0]
        if aero_on else np.zeros((nc, 6))
    )
    t_aero1 = time.perf_counter() - t0
    tracer.add("aero_first", t_aero1, backend="cpu")

    # ---- mooring: designs x distinct-mean-load case groups ----
    t0 = time.perf_counter()
    moor_fn = case_mooring_design_batch_fn(
        model0.rho_water, model0.g, model0.yawstiff
    )
    V_all = np.array([v.S1.V for v in variants])
    AWP_all = np.array([v.S1.AWP for v in variants])
    rM_all = np.stack(
        [np.array([0.0, 0.0, v.S1.zMeta]) for v in variants]
    )
    F0g, inv = _mean_load_case_groups(F_prp, nc)
    F0 = np.broadcast_to(F0g[None], (nd, len(F0g), 6)).copy()
    out = moor_fn(*put_cpu((F0, mass_all, V_all, rCG_all, rM_all, AWP_all))
                  , *put_cpu(moor_all),
                  put_cpu(bridles_all) if bridles_all is not None else None)
    expand = lambda a: np.asarray(a)[:, inv].copy()  # noqa: E731
    r6, C_moor, F_moor, T_moor, J_moor, moor_resid = (
        expand(o) for o in out)
    warn_bridle_residual(moor_resid, label="design")
    t_moor = time.perf_counter() - t0
    tracer.add("mooring", t_moor, backend="cpu")

    # ---- aero second pass + dynamics, overlapped along the case axis:
    # pad the design axis to a group multiple and reuse the draft x
    # ballast pipeline with a unit ballast axis ----
    dtype = model0.dtype
    gd = min(group, nd)
    if mesh is not None and gd % mesh.size:
        raise ValueError(
            f"group ({gd}) must be divisible by the design-mesh "
            f"size ({mesh.size})")
    nd_pad = -(-nd // gd) * gd
    G = nd_pad // gd
    pad_idx = np.concatenate([np.arange(nd),
                              np.full(nd_pad - nd, nd - 1, int)])
    nodes_all = pad_and_stack_nodes(
        [variants[i].nodes.astype(dtype) for i in pad_idx])
    shp = lambda a: a.reshape((G, gd, 1) + a.shape[1:])  # noqa: E731
    nodes_g = jax.tree.map(
        lambda a: a.reshape((G, gd) + a.shape[1:]), nodes_all)
    C_lin = (
        C_struc[:, None]
        + np.stack([v.S1.C_hydro for v in variants])[:, None]
        + C_moor
    )[pad_idx]                                          # [nd_pad, nc, 6, 6]
    M0_all = (M_struc + np.stack([v.A_morison for v in variants]))[pad_idx]

    put_d, put_r = _pipeline_placers(mesh)
    nodes_dev = jax.tree.map(put_d, nodes_g) if mesh is not None \
        else jax.device_put(nodes_g)
    M0_dev = put_d(shp(M0_all.astype(dtype)))
    beta_f = np.asarray(beta, dtype)

    def make_dev_args(ci, a_sub, b_sub):
        return (
            nodes_dev,
            put_r(zeta[ci].astype(dtype)),
            put_r(beta_f[ci]),
            put_d(shp(C_lin[:, ci].astype(dtype))),
            M0_dev,
            put_d(shp(a_sub[pad_idx].astype(dtype))),
            put_d(shp(b_sub[pad_idx].astype(dtype))),
        )

    sol, a_hub, b_hub, F_aero2, rotor_tel, eng_timing, dyn_flops = \
        _chunked_aero_dynamics(
            model0, cases, wind, aero_on, r6[:, :, 4], make_dev_args,
            nd, nd_pad, return_xi, retry_nonconverged,
            f"design sweep x{nd}", tracer, overlap=overlap,
            via_buckets=sweep_buckets_enabled(via_buckets),
        )
    std = sol["std"][:nd]
    iters = sol["iters"][:nd]
    conv = sol["converged"][:nd]

    # ---- metrics (reference parametersweep getOutputs semantics) ----
    offset = np.hypot(r6[:, 0, 0], r6[:, 0, 1])
    pitch = np.rad2deg(r6[:, 0, 4])
    res = {
        "mass": mass_all,
        "displacement": rho_w * V_all,
        "GMT": rM_all[:, 2] - rCG_all[:, 2],
        "offset": offset,
        "pitch_deg": pitch,
        "delta_rho": delta,
        "std": std,
        "converged": conv,
        "iters": iters,
        # per-point solver health (raft_tpu/health.py SolveReport fields)
        "nonfinite": sol["nonfinite"][:nd],
        "recovery_tier": sol["recovery_tier"][:nd],
        "residual": sol["residual"][:nd],
        "cond": sol["cond"][:nd],
        "retried": sol["retried"][:nd],
        "Xi0": r6,
        "F_aero0": F_aero2,
        "T_moor": T_moor,
        "moor_resid": moor_resid,
        "dynamics_flops": dyn_flops,
        "rotor_telemetry": rotor_tel,
        "tracer": tracer,
        "timing": {
            "host_prep_s": t_host,
            "aero_first_s": t_aero1,
            "mooring_s": t_moor,
            **eng_timing,
            "total_s": time.perf_counter() - t_start,
        },
    }
    if return_xi:
        res["Xi"] = sol["xr"][:nd] + 1j * sol["xi"][:nd]
    # quarantined designs: NaN their rows + report them
    fmask = np.zeros(nd, bool)
    for i, _ in failed_pts:
        fmask[i] = True
    _quarantine_design_rows(res, fmask, (nd,))
    res["failed"] = [{"index": i, "error": msg} for i, msg in failed_pts]
    res["failed_mask"] = fmask
    tracer.maybe_dump_env()
    if verbose:
        tm = res["timing"]
        logger.info(
            "design sweep x%d: host %.2fs, aero %.2fs, mooring %.2fs, "
            "dynamics %.2fs, overlap saved %.2fs (%d chunk(s)), "
            "total %.2fs",
            nd, tm["host_prep_s"],
            tm["aero_first_s"] + tm["aero_second_s"], tm["mooring_s"],
            tm["dynamics_first_s"], tm["overlap_saved_s"],
            tm["overlap_chunks"], tm["total_s"],
        )
    return res
