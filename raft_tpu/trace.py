"""Stage-timeline span recorder for the heterogeneous (CPU + TPU) sweep
pipeline.

The fused sweep interleaves host rotor work with asynchronous device
dynamics dispatches (sweep_fused.py); whether the two actually overlap —
and by how much — must be *measured*, not asserted.  A :class:`Tracer`
records monotonic start/stop spans per stage, per chunk, per backend, and
can emit them as a chrome://tracing-compatible JSON (open in
``chrome://tracing`` or https://ui.perfetto.dev) as well as reduce them to
the flat per-stage seconds the benchmark's ``sweep_timing_breakdown``
reports.

Async device spans: a dispatch returns before the device finishes, so
device stages are recorded with :meth:`Tracer.begin` at dispatch and
:meth:`Tracer.end` when ``jax.block_until_ready`` returns — the span is
the dispatch-to-ready critical path as the host observes it (it includes
queueing, which is exactly what overlap is supposed to hide).

Set ``RAFT_TPU_TRACE=/path/to/trace.json`` to make the sweep drivers dump
their timeline automatically after every run (the file is overwritten
atomically per run, last run wins).
"""

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

__all__ = ["Tracer", "trace_path_from_env", "chrome_trace_from_spans",
           "DEFAULT_MAX_SPANS"]

#: span-buffer bound: a serve process with RAFT_TPU_TRACE set used to
#: grow ``spans`` without limit; past this the oldest spans roll off
#: and ``Tracer.dropped`` counts them
DEFAULT_MAX_SPANS = 65536


class _SpanBuffer(deque):
    """Bounded append-only span store with a dropped-span counter —
    list-compatible for every consumer in this module (append, iterate)
    and for the tests that inject spans directly."""

    def __init__(self, capacity):
        super().__init__(maxlen=max(int(capacity), 1))
        self.dropped = 0

    def append(self, item):
        if len(self) == self.maxlen:
            self.dropped += 1
        super().append(item)


class Tracer:
    """Monotonic span recorder.  Thread-safe; negligible overhead
    (one ``perf_counter`` pair and a dict per span).  The span store is
    BOUNDED (``max_spans``, default 65536): beyond it the oldest spans
    are dropped and counted in :attr:`dropped` — a long-running serve
    process with ``RAFT_TPU_TRACE`` set stays flat in memory."""

    def __init__(self, label="raft_tpu", max_spans=DEFAULT_MAX_SPANS):
        self.label = label
        self.spans = _SpanBuffer(max_spans)
        self._lock = threading.Lock()
        # wall-clock anchor so chrome traces from different processes
        # can be lined up if needed
        self.t0_unix = time.time()
        self.t0 = time.perf_counter()

    @property
    def dropped(self):
        """Spans lost to the bounded buffer (0 in any sane run)."""
        return self.spans.dropped

    # ------------------------------------------------------------ recording

    def begin(self, name, backend="host", chunk=None, **meta):
        """Open a span; returns the handle to pass to :meth:`end`.
        Use for async stages (device dispatch -> block_until_ready)."""
        return {
            "name": name, "backend": backend, "chunk": chunk,
            "t0": time.perf_counter() - self.t0, "meta": meta,
        }

    def end(self, handle, **meta):
        """Close a span opened by :meth:`begin` and record it."""
        handle["t1"] = time.perf_counter() - self.t0
        if meta:
            handle["meta"].update(meta)
        with self._lock:
            self.spans.append(handle)
        return handle["t1"] - handle["t0"]

    @contextmanager
    def span(self, name, backend="host", chunk=None, **meta):
        """Context-managed synchronous span."""
        h = self.begin(name, backend=backend, chunk=chunk, **meta)
        try:
            yield h
        finally:
            self.end(h)

    def add(self, name, seconds, backend="host", chunk=None, **meta):
        """Record a pre-measured duration ending now (for stages timed by
        existing perf_counter pairs)."""
        t1 = time.perf_counter() - self.t0
        with self._lock:
            self.spans.append({
                "name": name, "backend": backend, "chunk": chunk,
                "t0": t1 - float(seconds), "t1": t1, "meta": meta,
            })

    # ------------------------------------------------------------ reductions

    def _named(self, name):
        with self._lock:
            return [s for s in self.spans if s["name"] == name and "t1" in s]

    def stage_seconds(self):
        """{stage name: summed span seconds} — per-chunk spans of one
        stage accumulate (the 'how much work' view)."""
        out = {}
        with self._lock:
            for s in self.spans:
                if "t1" in s:
                    out[s["name"]] = out.get(s["name"], 0.0) \
                        + (s["t1"] - s["t0"])
        return out

    def stage_wall(self, *names):
        """Union wall-clock of the named stages (first start -> last end;
        the 'how long did the critical path take' view).  0.0 when no
        matching span exists."""
        spans = [s for n in names for s in self._named(n)]
        if not spans:
            return 0.0
        return max(s["t1"] for s in spans) - min(s["t0"] for s in spans)

    def overlap_saved_s(self, *names):
        """Seconds the named stages ran concurrently: sum of their span
        durations minus their union wall-clock.  0.0 on the barrier
        (non-overlapped) path by construction."""
        spans = [s for n in names for s in self._named(n)]
        if not spans:
            return 0.0
        total = sum(s["t1"] - s["t0"] for s in spans)
        return max(0.0, total - self.stage_wall(*names))

    @staticmethod
    def _union_s(spans):
        """Union wall-clock of a span list (merged-interval length)."""
        ivals = sorted((s["t0"], s["t1"]) for s in spans)
        total, end = 0.0, -float("inf")
        for t0, t1 in ivals:
            if t0 > end:
                total += t1 - t0
                end = t1
            elif t1 > end:
                total += t1 - end
                end = t1
        return total

    def backend_busy_s(self, *names):
        """{backend: union wall-clock seconds} of the named stages' spans,
        grouped by the spans' ``backend`` tag.  Unlike
        :meth:`stage_seconds`, concurrent spans on ONE backend (e.g. the
        two double-buffered async dynamics chunks both in flight) count
        their union once — this is the 'how long was that backend busy'
        view that the cross-backend overlap decomposition needs."""
        by_backend = {}
        for n in names:
            for s in self._named(n):
                by_backend.setdefault(s["backend"], []).append(s)
        return {b: self._union_s(sp) for b, sp in by_backend.items()}

    def overlap_backend_decomposition(self, *names):
        """Split :meth:`overlap_saved_s` into concurrency ACROSS backends
        vs concurrency WITHIN one backend.

        ``overlap_saved_s`` is (sum of span durations) − (union wall), so
        it also counts e.g. two async device chunks in flight at once —
        not only CPU-vs-device overlap.  The decomposition:

          within[b] = Σ durations on backend b − union wall on backend b
          cross     = Σ_b union[b] − union wall over all backends

        ``cross`` is the seconds at least two *different* backends were
        simultaneously busy (genuine heterogeneous overlap); Σ within +
        cross == overlap_saved_s up to float rounding.  Returns
        ``{"saved_s", "cross_backend_s", "within_backend_s": {b: ...}}``.
        """
        by_backend = {}
        for n in names:
            for s in self._named(n):
                by_backend.setdefault(s["backend"], []).append(s)
        if not by_backend:
            return {"saved_s": 0.0, "cross_backend_s": 0.0,
                    "within_backend_s": {}}
        union_b = {b: self._union_s(sp) for b, sp in by_backend.items()}
        union_all = self._union_s(
            [s for sp in by_backend.values() for s in sp])
        within = {
            b: max(0.0, sum(s["t1"] - s["t0"] for s in sp) - union_b[b])
            for b, sp in by_backend.items()
        }
        cross = max(0.0, sum(union_b.values()) - union_all)
        return {
            "saved_s": sum(within.values()) + cross,
            "cross_backend_s": cross,
            "within_backend_s": within,
        }

    # -------------------------------------------------------------- emission

    def chrome_trace(self):
        """chrome://tracing JSON object (ph="X" complete events; one pid
        per tracer label, one tid per backend so CPU and TPU stages render
        as parallel tracks)."""
        tids = {}
        events = []
        with self._lock:
            spans = list(self.spans)
        for s in spans:
            if "t1" not in s:
                continue
            tid = tids.setdefault(s["backend"], len(tids) + 1)
            args = {k: v for k, v in s.get("meta", {}).items()}
            if s.get("chunk") is not None:
                args["chunk"] = s["chunk"]
            events.append({
                "name": s["name"] if s.get("chunk") is None
                else f"{s['name']}[{s['chunk']}]",
                "cat": s["backend"], "ph": "X",
                "ts": s["t0"] * 1e6, "dur": (s["t1"] - s["t0"]) * 1e6,
                "pid": 1, "tid": tid, "args": args,
            })
        meta = [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": self.label}},
        ] + [
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
             "args": {"name": backend}}
            for backend, tid in tids.items()
        ]
        return {"traceEvents": meta + events,
                "displayTimeUnit": "ms",
                "otherData": {"t0_unix": self.t0_unix,
                              "dropped_spans": self.spans.dropped}}

    def dump(self, path):
        """Atomic (write-then-rename) chrome-trace dump."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self.chrome_trace(), fh)
        os.replace(tmp, path)
        return path

    def maybe_dump_env(self):
        """Dump to $RAFT_TPU_TRACE if set; returns the path or None."""
        path = trace_path_from_env()
        if path:
            return self.dump(path)
        return None


def trace_path_from_env():
    return os.environ.get("RAFT_TPU_TRACE") or None


def chrome_trace_from_spans(spans, label="raft_tpu_trace"):
    """Stitch cross-process span documents (raft_tpu/obs/tracing.py
    shape: absolute unix ``t0`` + ``dur_s``, a ``proc`` tag per
    process) into ONE chrome://tracing JSON object — one tid per proc,
    timeline re-anchored at the earliest span.  This is what
    ``Router.gather_trace`` emits: router-ingress, wire, and
    replica-side stage spans of one trace_id on a single timeline."""
    done = [s for s in spans if "t0" in s and "dur_s" in s]
    if not done:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "otherData": {"label": label}}
    anchor = min(s["t0"] for s in done)
    tids = {}
    events = []
    for s in sorted(done, key=lambda x: x["t0"]):
        proc = s.get("proc", "proc")
        tid = tids.setdefault(proc, len(tids) + 1)
        args = dict(s.get("meta") or {})
        for key in ("trace_id", "span_id", "parent_span_id"):
            if s.get(key):
                args[key] = s[key]
        events.append({
            "name": s.get("name", "span"), "cat": proc, "ph": "X",
            "ts": (s["t0"] - anchor) * 1e6, "dur": s["dur_s"] * 1e6,
            "pid": 1, "tid": tid, "args": args,
        })
    meta = [
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": label}},
    ] + [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
         "args": {"name": proc}}
        for proc, tid in tids.items()
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms",
            "otherData": {"label": label, "t0_unix": anchor}}
