"""Blade-element momentum rotor aerodynamics + aero-servo coupling.

Native replacement for the reference's CCBlade dependency (Fortran BEM with
hand-coded adjoints, consumed at reference raft/raft_rotor.py:182-307) and
for the Rotor class's aero-servo transfer functions (raft_rotor.py:327-489):

 - the induction solve uses Ning's guaranteed-convergence inflow-angle
   residual, solved by vectorized bisection over (span x azimuth), with
   gradients recovered by differentiable Newton polishing steps on top of a
   stop_gradient'ed bisection root (implicit-function derivatives without
   custom_root plumbing);
 - d{T,Q}/d{U, Omega, pitch} come from jax.jacfwd through the whole rotor
   evaluation — replacing CCBlade's hand-written derivative chain;
 - airfoil polars are pre-interpolated host-side exactly like the reference
   (200-point AoA grid, PCHIP spanwise blending on relative thickness,
   raft_rotor.py:81-166) and evaluated with linear interpolation in the
   solve.  The reference uses CCAirfoil's spline (raft_rotor.py:125-134);
   the divergence is QUANTIFIED by
   tests/test_aero.py::test_linear_vs_spline_polar_bound, which re-runs
   the identical evaluation on PCHIP-spline-resampled polars across the
   VolturnUS operating range: loads move <0.05%, the
   d{T,Q}/d{U,Omega,pitch} derivative rows <0.5% of their row
   magnitude, and the closed-loop aero damping b(w) <1% — an order
   below polar-data uncertainty;
 - the control branch reproduces the reference's transfer-function algebra
   (raft_rotor.py:367-432) including its quirks (ki_tau assigned from kp_tau,
   raft_rotor.py:375; mean-load moment ordering [T,Y,Z,My,Q,Mz],
   raft_rotor.py:350-351).

Runs on the CPU backend in f64 (per-case setup work, tiny arrays); the
outputs (scalars + [nw] arrays) feed the device dynamics graph.
"""

import numpy as np
from scipy.interpolate import PchipInterpolator

import jax
import jax.numpy as jnp

from raft_tpu.io.schema import get_from_dict
from raft_tpu.utils.placement import put_cpu
from raft_tpu.wind import kaimal_rotor_spectrum

_RAD2DEG = 57.29577951308232
_RPM2RADPS = 0.1047  # the reference's rounded conversion (raft_rotor.py:32)


# ---------------------------------------------------------------- airfoils

def build_airfoils(turbine, n_span=30, n_aoa=200):
    """Airfoil polar tables interpolated to the analysis grid
    (reference raft/raft_rotor.py:75-166).

    Returns (aoa_grid [n_aoa+2], cl, cd, cm [n_span, n_aoa+2]).
    """
    af_used = [b for a, b in turbine["blade"]["airfoils"]]
    af_position = [a for a, b in turbine["blade"]["airfoils"]]
    n_af = len(turbine["airfoils"])

    aoa = np.unique(
        np.hstack(
            [
                np.linspace(-180, -30, int(n_aoa / 4.0 + 1)),
                np.linspace(-30, 30, int(n_aoa / 2.0)),
                np.linspace(30, 180, int(n_aoa / 4.0 + 1)),
            ]
        )
    )

    af_name = [turbine["airfoils"][i]["name"] for i in range(n_af)]
    r_thick = np.array(
        [turbine["airfoils"][i]["relative_thickness"] for i in range(n_af)]
    )
    cl = np.zeros((n_af, len(aoa)))
    cd = np.zeros((n_af, len(aoa)))
    cm = np.zeros((n_af, len(aoa)))
    for i in range(n_af):
        tab = np.array(turbine["airfoils"][i]["data"])
        cl[i] = np.interp(aoa, tab[:, 0], tab[:, 1])
        cd[i] = np.interp(aoa, tab[:, 0], tab[:, 2])
        cm[i] = np.interp(aoa, tab[:, 0], tab[:, 3])
        # enforce +/-180 deg consistency (raft_rotor.py:125-133)
        for arr in (cl, cd, cm):
            if abs(arr[i, 0] - arr[i, -1]) > 1e-5:
                arr[i, 0] = arr[i, -1]

    r_thick_used = np.zeros(len(af_used))
    cl_used = np.zeros((len(af_used), len(aoa)))
    cd_used = np.zeros((len(af_used), len(aoa)))
    cm_used = np.zeros((len(af_used), len(aoa)))
    for i, name in enumerate(af_used):
        j = af_name.index(name)
        r_thick_used[i] = r_thick[j]
        cl_used[i] = cl[j]
        cd_used[i] = cd[j]
        cm_used[i] = cm[j]

    grid = np.linspace(0.0, 1.0, n_span)
    r_thick_interp = PchipInterpolator(af_position, r_thick_used)(grid)

    r_thick_unique, idx = np.unique(r_thick_used, return_index=True)
    flip = np.flip(r_thick_interp)
    cl_i = np.flip(PchipInterpolator(r_thick_unique, cl_used[idx])(flip), axis=0)
    cd_i = np.flip(PchipInterpolator(r_thick_unique, cd_used[idx])(flip), axis=0)
    cm_i = np.flip(PchipInterpolator(r_thick_unique, cm_used[idx])(flip), axis=0)
    return aoa, cl_i, cd_i, cm_i


# ---------------------------------------------------------------- BEM core

def _define_curvature(r, precurve, presweep, precone):
    """Azimuthal-frame blade coordinates, local cone angle, and path length
    (CCBlade's definecurvature; needed for curved IEA-15MW blades)."""
    x_az = -r * jnp.sin(precone) + precurve * jnp.cos(precone)
    z_az = r * jnp.cos(precone) + precurve * jnp.sin(precone)
    y_az = presweep
    # local cone angle from slopes (central differences, one-sided ends)
    dx = jnp.gradient(x_az)
    dz = jnp.gradient(z_az)
    cone = jnp.arctan2(-dx, dz)
    s = jnp.concatenate(
        [
            jnp.zeros(1, r.dtype),
            jnp.cumsum(
                jnp.sqrt(
                    jnp.diff(r) ** 2 + jnp.diff(precurve) ** 2 + jnp.diff(presweep) ** 2
                )
            ),
        ]
    )
    return x_az, y_az, z_az, cone, s


def _wind_components(Uinf, Omega, azimuth, r, precurve, presweep, precone,
                     yaw, tilt, hubHt, shearExp):
    """Per-section velocity components in the blade-aligned frame
    (CCBlade windcomponents)."""
    sy, cy = jnp.sin(yaw), jnp.cos(yaw)
    st, ct = jnp.sin(tilt), jnp.cos(tilt)
    sa, ca = jnp.sin(azimuth), jnp.cos(azimuth)
    sc, cc = jnp.sin(precone), jnp.cos(precone)

    x_az = -r * sc + precurve * cc
    z_az = r * cc + precurve * sc
    y_az = presweep

    height = (y_az * sa + z_az * ca) * ct - x_az * st
    V = Uinf * (1.0 + height / hubHt) ** shearExp

    Vwind_x = V * ((cy * st * ca + sy * sa) * sc + cy * ct * cc)
    Vwind_y = V * (cy * st * sa - sy * ca)
    Vrot_x = -Omega * y_az * sc
    Vrot_y = Omega * z_az
    return Vwind_x + Vrot_x, Vwind_y + Vrot_y


def _induction(phi, cl, cd, sigma_p, F_args, usecd=True):
    """Induction factors and the Ning residual for a given inflow angle.

    F_args = (B, r, Rhub, Rtip, Vx, Vy).
    Returns (R(phi), a, ap, F).
    """
    B, r, Rhub, Rtip, Vx, Vy = F_args
    sphi = jnp.sin(phi)
    cphi = jnp.cos(phi)
    abs_s = jnp.maximum(jnp.abs(sphi), 1e-9)

    # Prandtl tip/hub losses
    ftip = B / 2.0 * (Rtip / r - 1.0) / abs_s
    Ftip = 2.0 / jnp.pi * jnp.arccos(jnp.clip(jnp.exp(-ftip), 0.0, 1.0))
    fhub = B / 2.0 * (r / Rhub - 1.0) / abs_s
    Fhub = 2.0 / jnp.pi * jnp.arccos(jnp.clip(jnp.exp(-fhub), 0.0, 1.0))
    F = jnp.maximum(Ftip * Fhub, 1e-6)

    cn = cl * cphi + cd * sphi
    ct = cl * sphi - cd * cphi
    if not usecd:
        cn = cl * cphi
        ct = cl * sphi

    k = sigma_p * cn / (4.0 * F * sphi * sphi)
    kp = sigma_p * ct / (4.0 * F * sphi * cphi)

    # axial induction: momentum / Buhl-empirical / propeller-brake regions
    a_mom = k / (1.0 + k)
    g1 = 2.0 * F * k - (10.0 / 9.0 - F)
    g2 = jnp.maximum(2.0 * F * k - F * (4.0 / 3.0 - F), 1e-12)
    g3 = 2.0 * F * k - (25.0 / 9.0 - 2.0 * F)
    a_buhl = jnp.where(
        jnp.abs(g3) < 1e-6,
        1.0 - 1.0 / (2.0 * jnp.sqrt(g2)),
        (g1 - jnp.sqrt(g2)) / jnp.where(jnp.abs(g3) < 1e-6, 1.0, g3),
    )
    a_wind = jnp.where(k <= 2.0 / 3.0, a_mom, a_buhl)
    a_brake = jnp.where(k > 1.0, k / jnp.maximum(k - 1.0, 1e-9), 0.0)
    a = jnp.where(phi > 0, a_wind, a_brake)

    kp = jnp.where(jnp.abs(1.0 - kp) < 1e-9, kp + 1e-9, kp)
    ap = kp / (1.0 - kp)

    Vy_safe = jnp.where(jnp.abs(Vy) < 1e-6, jnp.sign(Vy) * 1e-6 + 1e-12, Vy)
    # NOTE: (1 - a) must keep its sign — near phi -> 0 the momentum branch
    # drives a through 1 and the residual's sign flip there is what the
    # bracketing relies on (Ning's method / CCBlade does not clamp here)
    one_minus_a = jnp.where(jnp.abs(1.0 - a) < 1e-12, 1e-12, 1.0 - a)
    resid = sphi / one_minus_a - Vx / Vy_safe * cphi * (1.0 - kp)
    return resid, a, ap, F


def _solve_phi(theta, cl_tab, cd_tab, aoa_grid, sigma_p, F_args,
               n_bisect=30, n_newton=2, phi0=None):
    """Inflow angle phi solving the BEM residual for one blade section.

    Bisection on Ning's primary bracket (eps, pi/2), with fallback brackets
    (-pi/4, -eps) and (pi/2, pi-eps) selected by sign tests — then
    differentiable Newton polishing so jacfwd recovers the implicit
    derivative through the solve.  30 halvings shrink the bracket to
    ~1.5e-9 rad, deep inside the Newton basin; the polish then reaches
    f64 roundoff (validated against scipy brentq at 1e-12 by
    tests/test_aero.py's NumPy-twin comparison).

    ``phi0`` (optional) supplies an externally-computed near-root initial
    guess: the bracketing and bisection are skipped entirely and a damped
    Newton polish runs from phi0 under ``lax.custom_root``, whose
    implicit-function tangent (one linearization at the root) replaces
    forward-mode propagation through the iterations — together ~6x
    cheaper per lane.  The sweep's guided second pass exploits this with
    guesses interpolated across neighbouring design lanes
    (raft_tpu/sweep_fused.py); guesses are clipped away from the phi=0
    branch discontinuity, and callers verify convergence against
    fully-solved probe lanes.
    """

    def resid(phi):
        alpha = phi - theta                                 # rad
        cl = jnp.interp(alpha * _RAD2DEG, aoa_grid, cl_tab)
        cd = jnp.interp(alpha * _RAD2DEG, aoa_grid, cd_tab)
        return _induction(phi, cl, cd, sigma_p, F_args)[0]

    eps = 1e-6
    if phi0 is not None:
        # guided path: Newton polish from the supplied guess under
        # custom_root — ONE implicit-function linearization at the root
        # (tangent = y / dR/dphi) instead of forward-mode propagation
        # through the polish iterations.  Measured ~6x cheaper per lane
        # than the bracketed path below.  (custom_root does NOT pay off
        # for the bracketed path: with the 30-iteration bisection in
        # scope its closure conversion compiled ~4x slower.)
        phi_init = jax.lax.stop_gradient(jnp.where(
            phi0 >= 0.0, jnp.maximum(phi0, eps), jnp.minimum(phi0, -eps)
        ))

        def solve(f, x0):
            df = jax.grad(f)
            phi = x0
            for _ in range(n_newton):
                # damped: an interpolated guess can sit a polar-kink away
                # from the root, where an undamped first step may overshoot
                phi = phi - jnp.clip(f(phi) / df(phi), -0.05, 0.05)
            return phi

        def tangent_solve(g, y):
            return y / jax.grad(g)(jnp.zeros_like(y))

        return jax.lax.custom_root(resid, phi_init, solve, tangent_solve)

    r_lo = resid(eps)
    r_hi = resid(jnp.pi / 2)
    primary = r_lo * r_hi <= 0
    # fallback selection (Ning's bracket logic): the residual is
    # discontinuous at phi=0 (momentum vs propeller-brake branch), so the
    # negative bracket is tested with resid(-eps), NOT resid(+eps)
    r_neg_lo = resid(-jnp.pi / 4)
    r_neg_hi = resid(-eps)
    use_neg = (~primary) & (r_neg_lo < 0) & (r_neg_hi > 0)
    lo = jnp.where(primary, eps, jnp.where(use_neg, -jnp.pi / 4, jnp.pi / 2))
    hi = jnp.where(primary, jnp.pi / 2, jnp.where(use_neg, -eps, jnp.pi - eps))
    rl0 = jnp.where(primary, r_lo, jnp.where(use_neg, r_neg_lo, r_hi))

    def bis_body(_, state):
        lo, hi, rl = state
        mid = 0.5 * (lo + hi)
        rm = resid(mid)
        same = rl * rm > 0
        return (
            jnp.where(same, mid, lo),
            jnp.where(same, hi, mid),
            jnp.where(same, rm, rl),
        )

    lo, hi, _ = jax.lax.fori_loop(0, n_bisect, bis_body, (lo, hi, rl0))
    phi = jax.lax.stop_gradient(0.5 * (lo + hi))

    dresid = jax.grad(resid)
    for _ in range(n_newton):
        phi = phi - resid(phi) / dresid(phi)
    return phi


def rotor_evaluate(Uinf, Omega, pitch, geom, polars, env, nSector=4,
                   phi0=None, n_newton=2):
    """Steady rotor loads (CCBlade.evaluate equivalent).

    Parameters
    ----------
    Uinf : hub wind speed [m/s]; Omega : rotor speed [rad/s];
    pitch : blade pitch [rad]
    geom : dict with r, chord, theta(rad), precurve, presweep, Rhub, Rtip,
        B, precone(rad), tilt(rad), yaw(rad), hubHt, shearExp
    polars : (aoa_grid_deg, cl[n_span,naoa], cd, cm)
    env : dict with rho, mu
    phi0 : optional [nSector, n_span] inflow-angle initial guesses — skips
        the bracketing/bisection per section (see :func:`_solve_phi`)
    n_newton : Newton polish steps (raised by guided callers)

    Returns dict with the hub loads T, Y, Z, Q, My, Mz, power P, their
    coefficients CT, CY, CZ, CQ, CMy, CMz, CP, and the solved inflow
    angles phi [nSector, n_span] (feedable back as ``phi0``).
    """
    aoa_grid, cl_tab, cd_tab, _ = polars
    r = geom["r"]
    chord = geom["chord"]
    theta = geom["theta"] + pitch
    B = geom["B"]
    sigma_p = B * chord / (2.0 * jnp.pi * r)

    azimuths = jnp.arange(nSector) * (2.0 * jnp.pi / nSector)
    phi0_all = (jnp.full((nSector, r.shape[0]), jnp.nan)
                if phi0 is None else phi0)

    def one_azimuth(az, phi0_row):
        Vx, Vy = _wind_components(
            Uinf, Omega, az, r, geom["precurve"], geom["presweep"],
            geom["precone"], geom["yaw"], geom["tilt"], geom["hubHt"],
            geom["shearExp"],
        )

        def one_section(th, clt, cdt, sp, ri, ci, vx, vy, p0):
            F_args = (B, ri, geom["Rhub"], geom["Rtip"], vx, vy)
            phi = _solve_phi(th, clt, cdt, aoa_grid, sp, F_args,
                             phi0=None if phi0 is None else p0,
                             n_newton=n_newton)
            alpha = phi - th
            cl = jnp.interp(alpha * _RAD2DEG, aoa_grid, clt)
            cd = jnp.interp(alpha * _RAD2DEG, aoa_grid, cdt)
            # r_fin: the Ning residual AT the returned root — free (this
            # _induction call is needed for the loads anyway) and the
            # deterministic per-section convergence signal for the guided
            # path (a guess trapped in the wrong bracket leaves |r| large)
            r_fin, a, ap, F = _induction(phi, cl, cd, sp, F_args)
            W2 = (vx * (1 - a)) ** 2 + (vy * (1 + ap)) ** 2
            Np = (cl * jnp.cos(phi) + cd * jnp.sin(phi)) * 0.5 * env["rho"] * W2 * ci
            Tp = (cl * jnp.sin(phi) - cd * jnp.cos(phi)) * 0.5 * env["rho"] * W2 * ci
            return Np, Tp, phi, jnp.abs(r_fin)

        Np, Tp, phi, rfin = jax.vmap(one_section)(
            theta, cl_tab, cd_tab, sigma_p, r, chord, Vx, Vy, phi0_row
        )
        return Np, Tp, phi, rfin

    Np_all, Tp_all, phi_all, rfin_all = jax.vmap(one_azimuth)(
        azimuths, phi0_all)

    # integrate distributed loads to the full hub force/moment vector with
    # zero-load extensions at hub and tip (CCBlade thrusttorque, extended
    # to the 6 components CCBlade.evaluate reports: the azimuth-frame
    # integrals are rotated into the hub frame per sector and averaged —
    # shear/tilt/yaw make the sectors asymmetric, producing the side
    # forces Y, Z and moments My, Mz the reference consumes into F_aero0,
    # reference raft/raft_rotor.py:237-252, :350-351)
    rfull = jnp.concatenate(
        [jnp.array([geom["Rhub"]]), r, jnp.array([geom["Rtip"]])]
    )
    pc = geom["precurve"]
    ps = geom["presweep"]
    pcfull = jnp.concatenate([pc[:1], pc, pc[-1:]])
    psfull = jnp.concatenate([ps[:1], ps, ps[-1:]])
    x_az, y_az, z_az, cone, s = _define_curvature(
        rfull, pcfull, psfull, geom["precone"]
    )
    ccone, scone = jnp.cos(cone), jnp.sin(cone)

    def hub_loads(Np, Tp, az):
        Npf = jnp.concatenate([jnp.zeros(1), Np, jnp.zeros(1)])
        Tpf = jnp.concatenate([jnp.zeros(1), Tp, jnp.zeros(1)])
        # azimuth-frame integrals: x shared with the hub frame, z along
        # the blade, tangential load along -y (blade motion direction;
        # Vrot_y = +Omega z_az in _wind_components)
        Fx = jnp.trapezoid(Npf * ccone, s)
        Fy_a = -jnp.trapezoid(Tpf, s)
        Fz_a = jnp.trapezoid(Npf * scone, s)
        Q = jnp.trapezoid(Tpf * z_az, s)    # CCBlade's torque integral
        My_a = jnp.trapezoid(Npf * (z_az * ccone - x_az * scone), s)
        Mz_a = -jnp.trapezoid(Tpf * x_az + Npf * y_az * ccone, s)
        # rotate azimuth frame -> hub frame (about the shared x axis;
        # blade height = z_az cos(az) + y_az sin(az), _wind_components)
        ca, sa = jnp.cos(az), jnp.sin(az)
        return (
            Fx,
            ca * Fy_a - sa * Fz_a,
            sa * Fy_a + ca * Fz_a,
            Q,
            ca * My_a - sa * Mz_a,
            sa * My_a + ca * Mz_a,
        )

    T_az, Y_az, Z_az, Q_az, My_az, Mz_az = jax.vmap(hub_loads)(
        Np_all, Tp_all, azimuths
    )
    T = B * jnp.mean(T_az)
    Y = B * jnp.mean(Y_az)
    Z = B * jnp.mean(Z_az)
    Q = B * jnp.mean(Q_az)
    My = B * jnp.mean(My_az)
    Mz = B * jnp.mean(Mz_az)
    P = Q * Omega

    q = 0.5 * env["rho"] * Uinf**2
    A = jnp.pi * geom["Rtip"] ** 2
    return {
        "T": T, "Q": Q, "P": P,
        "Y": Y, "Z": Z, "My": My, "Mz": Mz,
        "CT": T / (q * A), "CQ": Q / (q * geom["Rtip"] * A),
        "CP": P / (q * Uinf * A),
        "CY": Y / (q * A), "CZ": Z / (q * A),
        "CMy": My / (q * geom["Rtip"] * A),
        "CMz": Mz / (q * geom["Rtip"] * A),
        "phi": phi_all,
        "resid": jnp.max(rfin_all),
    }


# ------------------------------------------------------- servo transfer fns

def servo_transfer_terms(w, dT_dU, dT_dOm, dT_dPi, dQ_dU, dQ_dOm, dQ_dPi,
                         kp_beta, ki_beta, kp_tau, ki_tau,
                         k_float, Ng, I_drivetrain, Zhub):
    """Closed-loop aero-servo transfer functions (the reference's control
    branch, raft/raft_rotor.py:388-432), vectorized over arbitrary shared
    leading axes of the derivative/gain arguments — the design-sweep path
    evaluates all (design x case) operating points in one broadcast call.

    w : [nw]; every other argument broadcastable to a common leading shape.
    Returns (C, c_exc, a_aero, b_aero), each [..., nw]; the wind excitation
    is ``f_aero = c_exc * V_w`` with the case's rotor-averaged turbulence
    amplitude V_w.
    """
    e = lambda x: np.asarray(x, float)[..., None]  # noqa: E731
    dT_dU, dT_dOm, dT_dPi = e(dT_dU), e(dT_dOm), e(dT_dPi)
    dQ_dU, dQ_dOm, dQ_dPi = e(dQ_dU), e(dQ_dOm), e(dQ_dPi)
    kp_beta, ki_beta = e(kp_beta), e(ki_beta)
    kp_tau, ki_tau = e(kp_tau), e(ki_tau)

    D = (
        I_drivetrain * w**2
        + (dQ_dOm + kp_beta * dQ_dPi - Ng * kp_tau) * 1j * w
        + ki_beta * dQ_dPi
        - Ng * ki_tau
    )
    C = 1j * w * (dQ_dU - k_float * dQ_dPi / Zhub) / D
    H_QT = (
        (dT_dOm + kp_beta * dT_dPi) * 1j * w + ki_beta * dT_dPi
    ) / D
    c_exc = dT_dU - H_QT * dQ_dU
    resp = (
        dT_dU - k_float * dT_dPi - H_QT * (dQ_dU - k_float * dQ_dPi)
    )
    b_aero = np.real(resp)
    a_aero = np.real(resp / (1j * w))
    return C, c_exc, a_aero, b_aero


# ---------------------------------------------------------------- Rotor

# compiled loads+derivatives executables shared across Rotor instances with
# identical configuration (keyed by the raw geometry/polar bytes); each
# entry is a dict holding the single-point executable, the vmapped batch
# executables, the raw (unjitted) per-lane functions, and a lazily-filled
# map of host-mesh sharded variants keyed on the device tuple
_rotor_eval_cache = {}


# lanes per host device per dispatch.  The compiled per-device program is
# [_LANE_BLOCK]-shaped for EVERY mesh size (the lane batch is cut into
# super-blocks of _LANE_BLOCK * n_devices lanes, one async dispatch each),
# which is what makes the host-sharded and single-device paths
# bit-identical: XLA fuses differently at different batch shapes (measured
# ~5e-14 relative FMA-contraction drift between a [128]-lane and a
# 8x[16]-lane compile of the same per-lane chain), so equal bits require
# the SAME per-device partitioned module — enforced by fixing its shape.
_LANE_BLOCK = 64


def _host_mesh_devices(n_devices=None):
    """CPU devices the lane axis shards over (>1 only when the host
    platform was split, e.g. via RAFT_TPU_HOST_DEVICES in
    raft_tpu/__init__.py).  ``n_devices`` caps the count; 1 forces the
    single-device mesh (same per-device program, so results stay
    bit-identical — see _LANE_BLOCK)."""
    devs = list(jax.devices("cpu"))
    if n_devices is None:
        return devs
    return devs[: max(1, min(int(n_devices), len(devs)))]


def _sharded_batch_fns(cached, devices):
    """Jitted shard_map wrappers of the cached per-lane evaluations laying
    the lane axis across a 1-D ``('lane',)`` host mesh — the NamedSharding
    pattern bem_solver._sharded_solve_fn uses for the frequency batch.
    Lanes are independent scalar chains, so each device runs its
    [_LANE_BLOCK]-lane shard's vmap with zero communication; the
    single-device fallback is the same program on a 1-device mesh.
    Returns (plain_fn, guided_fn, lane_sharding)."""
    key = tuple(devices)
    hit = cached["sharded"].get(key)
    if hit is None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(devices), ("lane",))
        f, fg = cached["raw"]
        spec = P("lane")
        plain = shard_map(
            jax.vmap(f), mesh=mesh,
            in_specs=(spec,) * 5, out_specs=(spec,) * 3,
        )
        guided = shard_map(
            jax.vmap(fg), mesh=mesh,
            in_specs=(spec,) * 6, out_specs=(spec,) * 4,
        )
        hit = (jax.jit(plain), jax.jit(guided),
               NamedSharding(mesh, spec))
        cached["sharded"][key] = hit
    return hit


class Rotor:
    """Rotor aerodynamics + control for the frequency-domain model
    (reference raft/raft_rotor.py:35-489)."""

    def __init__(self, turbine, w):
        self.w = np.array(w)
        self.Zhub = float(turbine["Zhub"])
        self.shaft_tilt = float(turbine["shaft_tilt"])     # deg
        self.overhang = float(turbine.get("overhang", 0.0))
        self.R_rot = float(turbine["blade"]["Rtip"])
        self.I_drivetrain = float(turbine["I_drivetrain"])
        self.aeroServoMod = get_from_dict(turbine, "aeroServoMod", default=1)

        # operating schedule, extended with parked entries
        # (raft_rotor.py:51-61)
        self.Uhub = np.array(turbine["wt_ops"]["v"], float)
        self.Omega_rpm = np.array(turbine["wt_ops"]["omega_op"], float)
        self.pitch_deg = np.array(turbine["wt_ops"]["pitch_op"], float)
        self.Uhub = np.r_[self.Uhub, self.Uhub.max() * 1.4, 100]
        self.Omega_rpm = np.r_[self.Omega_rpm, 0, 0]
        self.pitch_deg = np.r_[self.pitch_deg, 90, 90]

        # geometry
        gt = np.array(turbine["blade"]["geometry"], float)
        self.geom = dict(
            r=jnp.asarray(gt[:, 0]),
            chord=jnp.asarray(gt[:, 1]),
            theta=jnp.asarray(np.deg2rad(gt[:, 2])),
            precurve=jnp.asarray(gt[:, 3]),
            presweep=jnp.asarray(gt[:, 4]),
            Rhub=float(turbine["Rhub"]),
            Rtip=float(turbine["blade"]["Rtip"]),
            B=int(turbine["nBlades"]),
            precone=float(np.deg2rad(turbine["precone"])),
            tilt=float(np.deg2rad(self.shaft_tilt)),
            yaw=0.0,
            hubHt=float(turbine["Zhub"]),
            shearExp=float(turbine["shearExp"]),
        )
        self.env = dict(rho=float(turbine["rho_air"]), mu=float(turbine["mu_air"]))

        aoa, cl, cd, cm = build_airfoils(turbine, n_span=gt.shape[0])
        self.polars = (
            jnp.asarray(aoa), jnp.asarray(cl), jnp.asarray(cd), jnp.asarray(cm),
        )

        self.set_control_gains(turbine)

        # jit the loads+derivatives evaluation once (CPU backend via input
        # placement; tiny arrays).  The compiled executable is shared across
        # Rotor instances with identical configuration through a module-level
        # cache — a design sweep constructs hundreds of Models with the same
        # turbine, and a per-instance jax.jit closure would recompile the
        # whole BEM+jacfwd graph each time.
        key = (
            gt.tobytes(),
            aoa.tobytes(), cl.tobytes(), cd.tobytes(),
            tuple(sorted(
                (k, v) for k, v in self.geom.items()
                if not isinstance(v, jnp.ndarray)
            )),
            tuple(sorted(self.env.items())),
        )
        cached = _rotor_eval_cache.get(key)
        if cached is None:
            # geometry/polars enter the executables as NUMPY closure
            # constants (tiny arrays baked into the graph as literals):
            # device-COMMITTED constants would pin the compiled graph to
            # cpu:0 and conflict with the host-mesh sharded dispatch
            # (_sharded_batch_fns), which replicates constants per device
            geom = {
                k: (np.asarray(v) if isinstance(v, jnp.ndarray) else v)
                for k, v in self.geom.items()
            }
            polars = tuple(np.asarray(p) for p in self.polars)
            env = self.env

            def loads_TQ(U, Om, pitch, tilt, yaw, phi0=None, n_newton=2):
                g = dict(geom)
                g["tilt"] = tilt
                g["yaw"] = yaw
                out = rotor_evaluate(U, Om, pitch, g, polars, env,
                                     phi0=phi0, n_newton=n_newton)
                return jnp.stack([out["T"], out["Q"], out["P"],
                                  out["CP"], out["CT"], out["CQ"],
                                  out["Y"], out["Z"], out["My"],
                                  out["Mz"]]), out["phi"], out["resid"]

            def loads_and_derivs(U, Om, pitch, tilt, yaw):
                vals, phi, _r = loads_TQ(U, Om, pitch, tilt, yaw)
                JT = jax.jacfwd(lambda a: loads_TQ(*a, tilt, yaw)[0])(
                    jnp.stack([U, Om, pitch])
                )  # [10 outputs, 3 inputs]
                return vals, JT, phi

            def loads_and_derivs_guided(U, Om, pitch, tilt, yaw, phi0):
                # phi0 skips bracketing/bisection; 3 damped Newton steps
                # re-converge the exact residual (guesses interpolated
                # across design lanes land ~1e-4 rad from the root).
                # resid = worst per-section |Ning residual| at the
                # returned roots — the caller's deterministic per-lane
                # guard against a guess trapped in the wrong bracket.
                vals, phi, resid = loads_TQ(U, Om, pitch, tilt, yaw,
                                            phi0, 3)
                JT = jax.jacfwd(
                    lambda a: loads_TQ(*a, tilt, yaw, phi0, 3)[0]
                )(jnp.stack([U, Om, pitch]))
                return vals, JT, phi, resid

            cached = {
                "eval": jax.jit(loads_and_derivs),
                "raw": (loads_and_derivs, loads_and_derivs_guided),
                "sharded": {},   # device tuple -> shard_map executables
            }
            _rotor_eval_cache[key] = cached
        self._cached = cached
        self._eval = cached["eval"]
        # telemetry of the last batched evaluation (lanes, padding, host
        # devices used) — read by the sweep's rotor-stage instrumentation
        self.last_batch_info = None

    # -------------------------------------------------------------- control

    def set_control_gains(self, turbine):
        """ROSCO-convention gain schedules (reference raft_rotor.py:309-323)."""
        pc = turbine.get("pitch_control", None)
        if pc is None:
            self.kp_0 = np.zeros_like(self.Uhub)
            self.ki_0 = np.zeros_like(self.Uhub)
            self.k_float = 0.0
            self.kp_tau = 0.0
            self.ki_tau = 0.0
            self.Ng = 1.0
            return
        pc_angles = np.array(pc["GS_Angles"]) * _RAD2DEG
        self.kp_0 = np.interp(self.pitch_deg, pc_angles, pc["GS_Kp"], left=0, right=0)
        self.ki_0 = np.interp(self.pitch_deg, pc_angles, pc["GS_Ki"], left=0, right=0)
        self.k_float = -pc["Fl_Kp"]
        self.kp_tau = -turbine["torque_control"]["VS_KP"]
        self.ki_tau = -turbine["torque_control"]["VS_KI"]
        self.Ng = turbine["gear_ratio"]

    def case_gains(self, Uinf):
        """Gain-schedule values at wind speed(s) ``Uinf``, including the
        reference's ki_tau-assigned-from-kp_tau quirk (raft_rotor.py:375).
        Broadcasts over array-valued Uinf.  Returns
        (kp_beta, ki_beta, kp_tau, ki_tau)."""
        kp_beta = -np.interp(Uinf, self.Uhub, self.kp_0)
        ki_beta = -np.interp(Uinf, self.Uhub, self.ki_0)
        kp_tau = self.kp_tau * (kp_beta == 0)
        ki_tau = self.kp_tau * (kp_beta == 0)
        return kp_beta, ki_beta, kp_tau, ki_tau

    # -------------------------------------------------------------- BEM

    def run_bem(self, Uhub, ptfm_pitch=0.0, yaw_misalign=0.0):
        """Steady loads and SI derivatives at the operating point for wind
        speed Uhub (reference raft_rotor.py:213-306 runCCBlade).

        Returns (loads dict, derivs dict) with derivatives already in SI
        (d/dU [m/s], d/dOmega [rad/s], d/dpitch [rad]).
        """
        Omega_rpm = np.interp(Uhub, self.Uhub, self.Omega_rpm)
        pitch_deg = np.interp(Uhub, self.Uhub, self.pitch_deg)
        tilt = np.deg2rad(self.shaft_tilt) + ptfm_pitch

        put = lambda x: put_cpu(np.float64(x))  # noqa: E731
        vals, J, _phi = self._eval(
            put(Uhub), put(Omega_rpm * np.pi / 30.0),
            put(np.deg2rad(pitch_deg)), put(tilt),
            put(np.deg2rad(yaw_misalign)),
        )
        vals = np.asarray(vals)
        J = np.asarray(J)

        self.U_case = Uhub
        self.Omega_case = Omega_rpm
        self.pitch_case = pitch_deg
        self.aero_torque = vals[1]
        self.aero_power = vals[2]

        loads = dict(
            T=vals[0], Q=vals[1], P=vals[2], CP=vals[3], CT=vals[4], CQ=vals[5],
            Y=vals[6], Z=vals[7], My=vals[8], Mz=vals[9],
        )
        derivs = dict(
            dT_dU=J[0, 0], dT_dOm=J[0, 1], dT_dPi=J[0, 2],
            dQ_dU=J[1, 0], dQ_dOm=J[1, 1], dQ_dPi=J[1, 2],
        )
        return loads, derivs

    def run_bem_batch(self, Uhub, ptfm_pitch, yaw_misalign=None,
                      phi0=None, return_phi=False, return_resid=False,
                      n_devices=None):
        """Batched steady loads + SI derivatives over a leading lane axis —
        the design sweep's second-pass rotor evaluation (one vmapped
        compiled CPU call instead of one serial :meth:`run_bem` per design
        x case; the reference re-runs CCBlade per sweep point,
        raft/parametersweep.py:56-100 via runRAFT -> raft_model.py:516-517).

        Uhub, ptfm_pitch, yaw_misalign : broadcastable arrays [nt]
        phi0 : optional [nt, nSector, n_span] inflow-angle guesses — lanes
            run the guided executable (no bracketing/bisection, ~6x
            cheaper; see :func:`_solve_phi`)
        return_phi : also return the solved phi [nt, nSector, n_span]
        return_resid : also return the worst per-section |Ning residual|
            at the returned roots per lane [nt] (guided path only; None
            for the bracketed path)
        n_devices : int | None — cap on the CPU host devices the lane
            axis shards over (None = all CPU devices; 1 forces the
            single-device mesh).  More than one host device exists only
            when the host platform was split (RAFT_TPU_HOST_DEVICES=N,
            wired in raft_tpu/__init__.py).  The lane batch is cut into
            super-blocks of ``_LANE_BLOCK * n_devices`` lanes, each laid
            across the 1-D host mesh with shard_map/NamedSharding and
            dispatched ASYNCHRONOUSLY (devices run concurrently, blocks
            queue); because the per-device partitioned program is
            [_LANE_BLOCK]-shaped at every mesh size, vals/J are
            bit-identical to the single-device path (asserted in
            tests/test_host_shard.py).
        Returns (vals [nt, 10], J [nt, 10, 3][, phi][, resid]) with the
        same layout as :meth:`run_bem`'s stacked outputs, derivatives
        already SI.

        The lane axis is padded (repeating the final lane) to fill the
        last super-block, so sweeps of every size and mesh share ONE
        compiled executable per mesh signature.
        """
        Uhub = np.atleast_1d(np.asarray(Uhub, np.float64))
        ptfm_pitch = np.broadcast_to(
            np.asarray(ptfm_pitch, np.float64), Uhub.shape
        )
        yaw = np.zeros_like(Uhub) if yaw_misalign is None else np.broadcast_to(
            np.asarray(yaw_misalign, np.float64), Uhub.shape
        )
        n = Uhub.size
        devices = _host_mesh_devices(n_devices)
        # never put more devices under the batch than it has 64-lane
        # blocks: a 6-lane call on an 8-device mesh would otherwise pad
        # to 512 lanes of work (the trimmed results stay bit-identical
        # across mesh sizes either way — fixed per-device block shape)
        devices = devices[: max(1, min(len(devices),
                                       -(-n // _LANE_BLOCK)))]
        n_dev = len(devices)
        G = _LANE_BLOCK * n_dev            # lanes per dispatch
        nb = -(-n // G) * G
        pad = lambda a: np.concatenate(  # noqa: E731
            [a, np.repeat(a[-1:], nb - n, axis=0)]
        ) if nb > n else a
        Uhub_p, pitch_p, yaw_p = pad(Uhub), pad(ptfm_pitch), pad(yaw)
        Omega_rpm = np.interp(Uhub_p, self.Uhub, self.Omega_rpm)
        pitch_deg = np.interp(Uhub_p, self.Uhub, self.pitch_deg)
        tilt = np.deg2rad(self.shaft_tilt) + pitch_p

        batch_fn, guided_fn, sharding = _sharded_batch_fns(
            self._cached, tuple(devices))
        put = lambda a: jax.device_put(  # noqa: E731
            np.asarray(a, np.float64), sharding)
        self.last_batch_info = {
            "lanes": int(n), "lanes_padded": int(nb),
            "n_devices": int(n_dev), "dispatches": int(nb // G),
            "guided": phi0 is not None,
        }

        args_np = [Uhub_p, Omega_rpm * np.pi / 30.0,
                   np.deg2rad(pitch_deg), tilt, np.deg2rad(yaw_p)]
        if phi0 is not None:
            args_np.append(pad(np.asarray(phi0, np.float64)))
        fn = batch_fn if phi0 is None else guided_fn
        outs = []
        for i in range(0, nb, G):          # async: blocks queue per device
            outs.append(fn(*(put(a[i:i + G]) for a in args_np)))
        jax.block_until_ready(outs)

        cat = lambda j: np.concatenate(  # noqa: E731
            [np.asarray(o[j]) for o in outs])[:n]
        out = [cat(0), cat(1)]
        if return_phi:
            out.append(cat(2))
        if return_resid:
            out.append(cat(3) if phi0 is not None else None)
        return tuple(out)

    # ---------------------------------------------------- aero-servo terms

    def calc_aero_servo_contributions(self, case, ptfm_pitch=0.0):
        """Mean loads + frequency-dependent aero-servo added mass a(w),
        damping b(w), and wind excitation f(w) about the hub
        (reference raft_rotor.py:327-489).

        Returns (F_aero0[6], f_aero[nw] complex, a_aero[nw], b_aero[nw]).
        """
        loads, d = self.run_bem(
            case["wind_speed"], ptfm_pitch=ptfm_pitch,
            yaw_misalign=case.get("yaw_misalign", 0.0),
        )
        Uinf = case["wind_speed"]
        w = self.w

        dT_dU, dT_dOm, dT_dPi = d["dT_dU"], d["dT_dOm"], d["dT_dPi"]
        dQ_dU, dQ_dOm, dQ_dPi = d["dQ_dU"], d["dQ_dOm"], d["dQ_dPi"]

        # mean load vector — moment ordering kept as the reference has it
        # ([T, Y, Z, My, Q, Mz], raft_rotor.py:350-351)
        F_aero0 = np.array(
            [loads["T"], loads["Y"], loads["Z"], loads["My"], loads["Q"],
             loads["Mz"]]
        )

        _, _, _, S_rot = kaimal_rotor_spectrum(
            w, Uinf, self.Zhub, self.R_rot, case["turbulence"]
        )
        self.V_w = np.sqrt(S_rot)

        if self.aeroServoMod == 1:
            a_aero = np.zeros_like(w)
            b_aero = np.zeros_like(w) + dT_dU
            f_aero = dT_dU * self.V_w
            self.C = np.zeros_like(w, dtype=complex)
        elif self.aeroServoMod == 2:
            self.kp_beta, self.ki_beta, kp_tau, ki_tau = self.case_gains(Uinf)

            self.C, self.c_exc, a_aero, b_aero = servo_transfer_terms(
                w, dT_dU, dT_dOm, dT_dPi, dQ_dU, dQ_dOm, dQ_dPi,
                self.kp_beta, self.ki_beta, kp_tau, ki_tau,
                self.k_float, self.Ng, self.I_drivetrain, self.Zhub,
            )
            f_aero = self.c_exc * self.V_w
        else:
            raise ValueError(f"aeroServoMod={self.aeroServoMod} not supported here")

        return F_aero0, f_aero, a_aero, b_aero
