"""Convergence-aware fixed-point engine: the iteration waterfall.

The dynamics fixed point (raft_tpu/dynamics.py) is vmapped over
(design x case) lanes, so the batched ``while_loop`` iterates until the
SLOWEST lane converges — already-converged lanes keep re-running the
full ``linearized_drag`` einsums, impedance assembly, and [nw]x6x6
solves as frozen ``where``-selects every iteration.  BENCH_FULL.json
measures the cost: ``dynamics_first_s`` is essentially the whole sweep
wall.  This module converts that waste directly into wall-clock:

 1. the monolithic loop is re-expressed as fixed **K-iteration blocks**
    (a scan of ``where(cond, body(s), s)`` trips — per-lane semantics
    identical to the batched while_loop, the equivalence tier-1 pins via
    the ``checkable=True`` scan path);
 2. after each block the engine hops out converged/frozen lanes on the
    host and **compacts the survivors** into the next smaller canonical
    lane-count rung (the serve layer's slot-ladder vocabulary —
    8/16/32/64/128, doubling above — so every block executes a
    pre-warmable fixed-shape program and jit's shape cache bounds the
    program family; no recompiles in steady state);
 3. the finished lanes' loop states are scattered back into original
    lane order and ONE vmapped finalize runs the refined recovery-ladder
    re-solve for every lane (the health ladder always takes the XLA
    reference path).

Bit-parity contract: a lane's per-iteration arithmetic is lane-local
(vmapped lanes are data-independent, and the phase closures are the SAME
``fixed_point_phases`` objects ``solve_dynamics`` composes), so a lane's
trajectory is bit-identical whether it rides a full or a compacted
block; gathers, host round-trips, and replicated-lane padding are exact.
``tests/test_waterfall.py`` pins ``np.array_equal`` against the legacy
dispatch on CPU, including NaN-quarantined and non-converged lanes
landing in compacted blocks.

Mode selection: ``RAFT_TPU_FIXED_POINT=waterfall|fused|legacy`` (default
``legacy`` — tier-1 bits unchanged).  ``fused`` rides the same waterfall
driver but executes each block through the fused per-iteration Pallas
megakernel (raft_tpu/pallas_kernels.py, ``fused_block_step``) instead of
the XLA scan — tolerance-level parity, interpret-mode tested on CPU.
The health-ladder retry tiers (sweep.SolveRetryPolicy) and the
``checkable`` debug pipelines always take the legacy XLA reference path.
The mode is part of the serve cache's executable flags
(raft_tpu/serve/cache.py), so executables compiled under a different
fixed-point mode are refused, never silently mixed.

**Preemption (PR 11):** the block boundaries double as preemption
points for the serve tier's two-level scheduler.  ``waterfall_dispatch``
accepts a ``should_yield`` callable, polled after every block: when it
returns True while lanes survive, the dispatch suspends — the survivors'
loop state (XiLast, iteration counters, done mask), lane ids, operands
and the per-lane retirement store are pulled to host NumPy and returned
as a :class:`SuspendedWaterfall`, and a later
``waterfall_dispatch(resume=...)`` re-injects them and continues.  The
host round-trip is exact (f64 copies, no arithmetic) and every resumed
block is the same canonical fixed-shape program the uninterrupted run
would have executed with the same scheduler state, so a
preempted-and-resumed dispatch is ``np.array_equal``-identical to an
uninterrupted one (pinned in tests/test_serve_sweep.py).
"""

import dataclasses
import os
import time
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.utils.profiling import logger

MODES = ("legacy", "waterfall", "fused")

#: lane-count rungs every block program is quantized to (the serve slot
#: ladder); above the top rung capacities double, so the program family
#: stays logarithmic in sweep size
LANE_LADDER = (8, 16, 32, 64, 128)

DEFAULT_BLOCK_ITERS = 4


def fixed_point_mode():
    """The requested fixed-point engine: ``RAFT_TPU_FIXED_POINT`` in
    {legacy, waterfall, fused}, default legacy (bit-for-bit the
    monolithic while_loop dispatch)."""
    raw = os.environ.get("RAFT_TPU_FIXED_POINT", "").strip().lower()
    if not raw:
        return "legacy"
    if raw in MODES:
        return raw
    logger.warning(
        "RAFT_TPU_FIXED_POINT=%r not in %s; using legacy", raw, MODES)
    return "legacy"


def block_iters():
    """Fixed-point iterations per waterfall block
    (``RAFT_TPU_FIXED_POINT_BLOCK``, default 4 — nIter=15 gives at most
    4 block dispatches per rung, enough hop-out granularity to harvest a
    p50<<max convergence spread without drowning in dispatch overhead)."""
    try:
        k = int(os.environ.get("RAFT_TPU_FIXED_POINT_BLOCK",
                               DEFAULT_BLOCK_ITERS))
    except ValueError:
        k = DEFAULT_BLOCK_ITERS
    return max(1, k)


def ladder_lanes(n):
    """Smallest canonical lane-count rung holding ``n`` lanes."""
    n = max(int(n), 1)
    for L in LANE_LADDER:
        if L >= n:
            return L
    L = LANE_LADDER[-1]
    while L < n:
        L *= 2
    return L


def _pad_rows(a, lanes):
    """Pad a leading lane axis to ``lanes`` by replicating row 0 —
    always-real inert work under the engine's packing contract (padding
    lanes are vmap-independent and their results are discarded)."""
    L0 = a.shape[0]
    if L0 == lanes:
        return a
    return jnp.concatenate(
        [a, jnp.repeat(a[:1], lanes - L0, axis=0)], axis=0)


@lru_cache(maxsize=32)
def _phase_pipelines(physics, relax, block, kernel, shared_nodes=False):
    """The jitted vmapped phase programs of one physics configuration:
    ``(prelude_fn, block_fn, finalize_fn)``.  Shapes bind at call time,
    so jit's shape cache holds one executable per lane-count rung; the
    persistent compilation cache makes them warm-restartable exactly like
    the serve bucket executables.  ``kernel=True`` swaps the block
    program's K-step scan for the fused Pallas megakernel.

    ``shared_nodes=True`` vmaps with the node bundle UNBATCHED
    (``in_axes`` None for nodes) — bit-identical to the Model's legacy
    closed-over-nodes case pipeline, which differs at the ulp level from
    a per-lane-broadcast node axis (XLA batches some node-only
    contractions differently); the single-Model case dispatch uses this
    so waterfall mode preserves the legacy bits exactly."""
    from raft_tpu.model import make_case_phases

    w = np.frombuffer(physics.w_bytes, np.float64, count=physics.nw)
    k = np.frombuffer(physics.k_bytes, np.float64, count=physics.nw)
    dtype = np.dtype(physics.dtype_name).type
    cdtype = np.dtype(physics.cdtype_name).type
    prelude, phases = make_case_phases(
        w, k, physics.depth, physics.rho, physics.g, physics.XiStart,
        physics.nIter, dtype, cdtype, relax=relax,
    )

    def prelude_one(nodes, zeta, beta, C_lin, M_lin, B_lin,
                    F_add_r, F_add_i):
        u, Fr, Fi = prelude(nodes, zeta, beta, F_add_r, F_add_i)
        ph = phases(nodes, u, C_lin, M_lin, B_lin, Fr, Fi)
        return u, Fr, Fi, ph.init

    def block_one(nodes, u, C_lin, M_lin, B_lin, Fr, Fi, state):
        with jax.default_matmul_precision("highest"):
            ph = phases(nodes, u, C_lin, M_lin, B_lin, Fr, Fi)

            def trip(s, _):
                return jax.lax.cond(ph.cond(s), ph.body,
                                    lambda x: x, s), None

            state, _ = jax.lax.scan(trip, state, None, length=block)
        return state

    def finalize_one(nodes, u, C_lin, M_lin, B_lin, Fr, Fi, state):
        with jax.default_matmul_precision("highest"):
            ph = phases(nodes, u, C_lin, M_lin, B_lin, Fr, Fi)
            return ph.finalize(state)

    nodes_ax = None if shared_nodes else 0
    vmap8 = lambda f: jax.vmap(f, in_axes=(nodes_ax,) + (0,) * 7)  # noqa: E731
    if kernel:
        from raft_tpu.pallas_kernels import HAVE_PALLAS, fused_block_fn
        from raft_tpu.precision import mixed_precision_enabled

        if not HAVE_PALLAS or mixed_precision_enabled():
            # the megakernel implements the full-precision baseline
            # arithmetic only — under RAFT_TPU_MIXED_PRECISION (or with
            # no Pallas) the fused mode degrades to the XLA waterfall
            # rather than silently changing the assembly precision
            logger.warning(
                "fused fixed-point mode unavailable (%s); using the XLA "
                "waterfall block",
                "mixed precision enabled" if HAVE_PALLAS
                else "Pallas not importable")
            block_fn = jax.jit(vmap8(block_one))
        else:
            block_fn = fused_block_fn(physics, relax, block)
    else:
        block_fn = jax.jit(vmap8(block_one))
    return (jax.jit(vmap8(prelude_one)), block_fn,
            jax.jit(vmap8(finalize_one)))


@dataclasses.dataclass
class SuspendedWaterfall:
    """A waterfall dispatch parked at a block boundary (``should_yield``
    fired with survivors remaining).  Everything is host NumPy — exact
    f64 copies of the device state, so resuming reproduces the
    uninterrupted run's bits.  Pass back to
    ``waterfall_dispatch(resume=...)``; a suspended object is consumed by
    that call (its retirement store is shared, not copied) and must not
    be resumed twice."""

    physics: object                 # SlotPhysics of the phase programs
    relax: float
    block: int                      # K iterations per block
    kernel: bool
    shared_nodes: bool
    L: int                          # real lane count
    Lq: int                         # original padded rung
    nodes_p: object                 # host node bundle at the original rung
    operands_full: tuple            # host operands at the original rung
    nodes_cur: object               # host node bundle at the current rung
    operands: tuple                 # host operands at the current rung
    state: tuple                    # host loop-state leaves, current rung
    ids: np.ndarray                 # row -> original lane id (-1 padding)
    state_store: list               # per-lane retired states (shared ref)
    trips: int
    blocks: int
    lane_iters: int
    rungs: list
    yields: int = 1
    flops: float = 0.0              # executed-flops ledger so far
    trace: object = None            # obs TraceContext — parked with the
    span_ring: object = None        # lane state so a resumed dispatch
                                    # keeps recording under ONE trace_id

    @property
    def survivors(self):
        """Lanes still iterating (what a resume pays for)."""
        return int((self.ids >= 0).sum())


# engine stats of the most recent dispatch (bench/test introspection):
# populated by waterfall_dispatch, read via last_dispatch_stats()
_LAST_STATS = {}

# XLA cost-model flops per (phase program, operand shapes) — the
# executed-flops ledger behind ``flops_executed`` in the dispatch stats.
# The waterfall is a host loop over jitted phase programs, so the
# monolithic pipeline's single compiled cost model does not exist here;
# summing the per-block program costs as blocks execute replaces it.
_FLOPS_CACHE = {}


def _fn_flops(fn, args):
    """Memoized cost-model flops of one jitted phase program at these
    operand shapes (0.0 when the backend reports no costs — an
    utilization estimate, same contract as ``compiled_flops``)."""
    from raft_tpu.utils.profiling import compiled_flops

    key = (id(fn),) + tuple(
        (tuple(np.shape(leaf)), str(getattr(leaf, "dtype", "")))
        for leaf in jax.tree.leaves(args))
    if key not in _FLOPS_CACHE:
        _FLOPS_CACHE[key] = compiled_flops(fn, args)
    return _FLOPS_CACHE[key]


def last_dispatch_stats():
    """Stats dict of the most recent waterfall dispatch in this process:
    ``n_lanes``, ``blocks``, ``lane_iters_executed`` (sum of per-rung
    lane-count x K over all blocks), ``lane_iters_monolithic`` (what the
    frozen-lane while_loop pays: max trips x padded lane count), and
    ``rungs`` (the lane-count sequence the waterfall descended)."""
    return dict(_LAST_STATS)


def waterfall_dispatch(physics, nodes_slots, args_slots, relax=0.8,
                       block=None, kernel=None, slab=None,
                       shared_nodes=False, should_yield=None,
                       resume=None, trace=None, span_ring=None):
    """Run flattened (design x case) lanes through the iteration
    waterfall.

    When ``RAFT_TPU_PROFILE_DIR`` is set, the FIRST dispatch of the
    process runs under ``jax.profiler`` capture (obs/profiler.py
    ``env_capture`` — the env read lives there, not here, so the flag
    never touches this module's code-version hash).
    """
    from raft_tpu.obs.profiler import env_capture

    return env_capture(lambda: _waterfall_entry(
        physics, nodes_slots, args_slots, relax, block, kernel, slab,
        shared_nodes, should_yield, resume, trace, span_ring))


def _waterfall_entry(physics, nodes_slots, args_slots, relax,
                     block, kernel, slab, shared_nodes, should_yield,
                     resume, trace, span_ring):
    """The dispatch body behind the profiler shim.

    physics : raft_tpu.serve.buckets.SlotPhysics (the scalars/frequency
        grid baked into the phase executables — same key the serve
        bucket pipelines use)
    nodes_slots : HydroNodes pytree with leading [L] lane axis (working
        dtype)
    args_slots : the 7-tuple from ``Model.prepare_case_inputs`` with
        leading [L]: (zeta, beta, C_lin, M_lin, B_lin, F_add_r, F_add_i)
    kernel : route blocks through the fused Pallas megakernel (default:
        ``fixed_point_mode() == "fused"``)
    slab : maximum lanes per waterfall descent (default: the top ladder
        rung) — megabatches beyond it run slab-by-slab, bounding operand
        memory and keeping every program inside the pre-warmable rung
        family
    shared_nodes : the node bundle has NO lane axis and is shared by all
        lanes (vmapped with in_axes None) — bit-identical to the Model's
        closed-over-nodes case pipeline; the default per-lane node axis
        matches the serve slot executables and the sweep pipelines
    should_yield : zero-arg callable polled after every K-iteration
        block; returning True while lanes survive suspends the dispatch
        and returns a :class:`SuspendedWaterfall` instead of results
        (the serve tier's preemption point).  Requires the megabatch to
        fit one slab (``L <= slab``).
    resume : a :class:`SuspendedWaterfall` to continue instead of
        starting fresh (``physics``/``nodes_slots``/``args_slots`` are
        ignored — the suspended object carries everything).

    Returns ``(xr [L, 6, nw], xi, report)`` numpy-backed outputs in the
    caller's lane order, per-lane bit-identical to the legacy monolithic
    dispatch of the same lanes — whether or not the dispatch was
    suspended and resumed along the way.
    """
    if resume is not None:
        return _waterfall_resume(resume, should_yield)
    if kernel is None:
        kernel = fixed_point_mode() == "fused"
    K = int(block) if block else block_iters()
    S = int(slab) if slab else LANE_LADDER[-1]
    L = int(args_slots[0].shape[0])
    if L > S:
        if should_yield is not None:
            raise ValueError(
                f"should_yield requires the megabatch to fit one slab "
                f"({L} lanes > slab {S}); size sweep chunks within a "
                "slab or raise `slab`")
        outs, agg = [], None
        for s0 in range(0, L, S):
            sl = slice(s0, min(s0 + S, L))
            nodes_s = nodes_slots if shared_nodes else jax.tree.map(
                lambda a: a[sl], nodes_slots)
            args_s = tuple(a[sl] for a in args_slots)
            outs.append(_waterfall_entry(
                physics, nodes_s, args_s, relax, block,
                kernel, S, shared_nodes, None, None, trace, span_ring))
            st = last_dispatch_stats()
            if agg is None:
                agg = st
                agg["rungs"] = list(st["rungs"])
            else:
                for key in ("n_lanes", "blocks", "lane_iters_executed",
                            "lane_iters_monolithic", "flops_executed"):
                    agg[key] += st[key]
                agg["rungs"] += st["rungs"]
        _LAST_STATS.clear()
        _LAST_STATS.update(agg)
        cat = lambda *xs: np.concatenate(xs, axis=0)  # noqa: E731
        return (cat(*[o[0] for o in outs]), cat(*[o[1] for o in outs]),
                jax.tree.map(cat, *[o[2] for o in outs]))
    prelude_fn, block_fn, finalize_fn = _phase_pipelines(
        physics, float(relax), K, bool(kernel), bool(shared_nodes))
    Lq = ladder_lanes(L)
    if shared_nodes:
        nodes_p = jax.tree.map(jnp.asarray, nodes_slots)
    else:
        nodes_p = jax.tree.map(
            lambda a: _pad_rows(jnp.asarray(a), Lq), nodes_slots)
    args_p = tuple(_pad_rows(jnp.asarray(a), Lq) for a in args_slots)

    u, Fr, Fi, state = prelude_fn(nodes_p, *args_p)
    flops = _fn_flops(prelude_fn, (nodes_p,) + args_p)
    C_p, M_p, B_p = args_p[2:5]
    operands = (u, C_p, M_p, B_p, Fr, Fi)

    # host-side waterfall bookkeeping: row -> original lane id (-1 = inert
    # padding), per-lane final-state store filled as lanes retire
    ids = np.concatenate(
        [np.arange(L), np.full(Lq - L, -1, np.int64)])
    return _waterfall_loop(
        physics, float(relax), K, bool(kernel), bool(shared_nodes),
        L, Lq, nodes_p, operands, nodes_p, operands, state, ids,
        None, 0, 0, 0, [], 0, block_fn, finalize_fn, should_yield,
        flops, trace=trace, span_ring=span_ring)


def _waterfall_resume(sus, should_yield=None):
    """Re-enter the waterfall loop from a :class:`SuspendedWaterfall`.
    The host -> device round-trip is exact, so the continued trajectory
    is bit-identical to never having suspended."""
    _prelude_fn, block_fn, finalize_fn = _phase_pipelines(
        sus.physics, sus.relax, sus.block, sus.kernel, sus.shared_nodes)
    nodes_p = jax.tree.map(jnp.asarray, sus.nodes_p)
    operands_full = tuple(jnp.asarray(a) for a in sus.operands_full)
    nodes_cur = nodes_p if sus.shared_nodes \
        else jax.tree.map(jnp.asarray, sus.nodes_cur)
    operands = tuple(jnp.asarray(a) for a in sus.operands)
    state = tuple(jnp.asarray(a) for a in sus.state)
    return _waterfall_loop(
        sus.physics, sus.relax, sus.block, sus.kernel, sus.shared_nodes,
        sus.L, sus.Lq, nodes_p, operands_full, nodes_cur, operands,
        state, np.array(sus.ids), sus.state_store, sus.trips,
        sus.blocks, sus.lane_iters, list(sus.rungs), sus.yields,
        block_fn, finalize_fn, should_yield, sus.flops,
        trace=sus.trace, span_ring=sus.span_ring)


def _waterfall_loop(physics, relax, K, kernel, shared_nodes, L, Lq,
                    nodes_p, operands_full, nodes_cur, operands, state,
                    ids, state_store, trips, blocks, lane_iters, rungs,
                    yields, block_fn, finalize_fn, should_yield,
                    flops=0.0, trace=None, span_ring=None):
    """The block/retire/compact loop shared by fresh and resumed
    dispatches — one code path, so suspension cannot change the
    scheduler's decisions (same rung sequence, same retire trips)."""
    max_trips = int(physics.nIter) + 1

    def _store(state_dev, rows, lanes):
        nonlocal state_store
        leaves = [np.asarray(leaf) for leaf in state_dev]
        if state_store is None:
            state_store = [
                np.zeros((L,) + leaf.shape[1:], leaf.dtype)
                for leaf in leaves]
        for buf, leaf in zip(state_store, leaves):
            buf[lanes] = leaf[rows]

    while True:
        rungs.append(len(ids))
        b_wall = time.time()
        b0 = time.perf_counter()
        state = block_fn(nodes_cur, *operands, state)
        flops += _fn_flops(block_fn, (nodes_cur,) + operands + (state,))
        blocks += 1
        trips += K
        lane_iters += len(ids) * K
        done = np.asarray(state[4])
        if span_ring is not None:
            # per-K-block span: the np.asarray above is the sync point,
            # so the span covers dispatch -> device-ready for this block
            span_ring.record(
                "wf_block", trace, b_wall,
                time.perf_counter() - b0,
                rung=len(ids), block=blocks, k=K)
        retire = done | (trips >= max_trips)
        real = ids >= 0
        retiring = retire & real
        if retiring.any():
            _store(state, np.where(retiring)[0], ids[retiring])
        survivors = np.where(~retire & real)[0]
        if survivors.size == 0:
            break
        Ln = ladder_lanes(survivors.size)
        if Ln < len(ids):
            rows = np.concatenate(
                [survivors,
                 np.full(Ln - survivors.size, survivors[0], np.int64)])
            idx = jnp.asarray(rows)
            take = lambda a: jnp.take(a, idx, axis=0)  # noqa: E731
            operands = tuple(jax.tree.map(take, op) for op in operands)
            if not shared_nodes:
                nodes_cur = jax.tree.map(take, nodes_cur)
            state = jax.tree.map(take, state)
            ids = np.concatenate(
                [ids[survivors],
                 np.full(Ln - survivors.size, -1, np.int64)])
        # else: no smaller rung to compact into — keep riding the current
        # fixed-shape program (converged lanes freeze via cond)
        if should_yield is not None and should_yield():
            # preemption point: park the survivors' state host-side
            # (exact copies; resuming continues the identical trajectory)
            return SuspendedWaterfall(
                physics=physics, relax=relax, block=K, kernel=kernel,
                shared_nodes=shared_nodes, L=L, Lq=Lq,
                nodes_p=jax.tree.map(np.asarray, nodes_p),
                operands_full=tuple(
                    np.asarray(a) for a in operands_full),
                nodes_cur=(None if shared_nodes
                           else jax.tree.map(np.asarray, nodes_cur)),
                operands=tuple(np.asarray(a) for a in operands),
                state=tuple(np.asarray(leaf) for leaf in state),
                ids=np.array(ids), state_store=state_store,
                trips=trips, blocks=blocks, lane_iters=lane_iters,
                rungs=list(rungs), yields=yields + 1, flops=flops,
                trace=trace, span_ring=span_ring)

    # scatter the retired per-lane loop states back into original lane
    # order (exact: no arithmetic touches a state after its lane's last
    # gated trip) and finalize every lane in ONE vmapped recovery-ladder
    # program at the original rung
    state_full = tuple(
        jnp.asarray(_pad_rows(jnp.asarray(buf), Lq))
        for buf in state_store)
    xr, xi, report = finalize_fn(nodes_p, *operands_full, state_full)
    flops += _fn_flops(finalize_fn,
                       (nodes_p,) + tuple(operands_full) + (state_full,))

    _LAST_STATS.clear()
    _LAST_STATS.update(
        n_lanes=L, blocks=blocks, rungs=rungs,
        lane_iters_executed=lane_iters,
        lane_iters_monolithic=trips * Lq,
        block_iters=K, kernel=bool(kernel), yields=yields,
        flops_executed=float(flops),
    )

    take = lambda a: np.asarray(a)[:L]  # noqa: E731
    return take(xr), take(xi), jax.tree.map(take, report)


def grouped_waterfall_pipeline(model0, relax=0.8):
    """Waterfall drop-in for ``sweep._sweep_pipeline``'s [design, case]
    executable: call signature ``(nodes_b, zeta, beta, C, M, B, Fr, Fi)``
    with leading [nd] (nodes) / [nd, nc] (args) axes, output
    ``(xr [nd, nc, 6, nw], xi, report)`` exactly like the vmapped
    pipeline — lanes flattened design-major/case-minor through the
    iteration waterfall.  The sweep's bounded non-convergence retry
    intentionally keeps the legacy pipeline (escalated (nIter, relax) is
    a reference-path re-solve, per the health-ladder contract)."""
    from raft_tpu.serve.buckets import SlotPhysics

    physics = SlotPhysics.from_model(model0)

    def pipeline(nodes_b, *args_b):
        nd, nc = args_b[0].shape[:2]
        L = int(nd) * int(nc)
        nodes_flat = jax.tree.map(
            lambda a: jnp.repeat(jnp.asarray(a), nc, axis=0), nodes_b)
        args_flat = tuple(
            jnp.reshape(jnp.asarray(a), (L,) + tuple(a.shape[2:]))
            for a in args_b)
        xr, xi, rep = waterfall_dispatch(
            physics, nodes_flat, args_flat, relax=relax)
        shape = lambda a: a.reshape((nd, nc) + a.shape[1:])  # noqa: E731
        return shape(xr), shape(xi), jax.tree.map(shape, rep)

    return pipeline


def fused_waterfall_pipeline(model0, return_xi, relax=0.8):
    """Waterfall drop-in for ``sweep_fused._dynamics_pipeline``'s
    executable: same call signature ``(nodes_g, zeta, beta, C_g, M0_g,
    a_g, b_g)`` (leading group axes [G, gd(, nB)]), same output tuple
    ``(std, report[, xr, xi])`` shaped flat [nd_flat * nc, ...] along
    the leading axis (design-major, case-minor — exactly what
    ``_unpack_dyn`` reshapes).  The rank-1 hub aero-servo profiles are
    materialized per lane (``M_lin = M0 + a(w) * P_hub``, elementwise
    identical to the fused pipeline's in-graph expression) because the
    waterfall phase programs take full [nw, 6, 6] matrices per lane;
    ``waterfall_dispatch`` then slabs the megabatch at the top ladder
    rung, so peak per-program operand memory stays bounded.  The
    sweep's bounded non-convergence retry keeps the legacy pipeline
    (health-ladder reference path)."""
    from raft_tpu.serve.buckets import SlotPhysics
    from raft_tpu.utils.frames import translate_matrix_3to6

    physics = SlotPhysics.from_model(model0)
    dtype = np.dtype(physics.dtype_name).type
    w = np.frombuffer(physics.w_bytes, np.float64, count=physics.nw)
    dw = float(w[1] - w[0])
    nw = physics.nw
    E00 = np.zeros((1, 3, 3))
    E00[0, 0, 0] = 1.0
    P_hub = jnp.asarray(
        np.asarray(
            translate_matrix_3to6(E00, np.array([0.0, 0.0,
                                                 float(model0.hHub)]))
        )[0],
        dtype,
    )

    def pipeline(nodes_g, zeta, beta, C_g, M0_g, a_g, b_g):
        lead = C_g.shape[:-3]          # (G, gd, nB) or (G, gd)
        ncc = C_g.shape[-3]
        n_designs = int(np.prod(lead[:2], dtype=np.int64))  # nodes axis
        n_rows = int(np.prod(lead, dtype=np.int64))         # C/a/b rows
        L = n_rows * ncc
        nB = n_rows // n_designs
        nodes_flat = jax.tree.map(
            lambda a: a.reshape((n_designs,) + a.shape[2:]), nodes_g)
        C_flat = C_g.reshape((n_rows, ncc, 6, 6))
        M0_flat = M0_g.reshape((n_rows, 6, 6))
        a_flat = a_g.reshape((n_rows, ncc, nw))
        b_flat = b_g.reshape((n_rows, ncc, nw))

        idx = jnp.arange(L)
        ri = idx // ncc                                  # design-row idx
        ci = idx % ncc                                   # case idx
        di = ri // nB                                    # node-bundle idx
        nodes_l = jax.tree.map(
            lambda a: jnp.take(a, di, axis=0), nodes_flat)
        M0_s = jnp.take(M0_flat, ri, axis=0)             # [L, 6, 6]
        a_s = a_flat[ri, ci]                             # [L, nw]
        b_s = b_flat[ri, ci]
        M_lin = M0_s[:, None] + a_s[:, :, None, None] * P_hub
        B_lin = b_s[:, :, None, None] * P_hub
        Fz = jnp.zeros((L, nw, 6), dtype)
        args = (jnp.take(zeta, ci, axis=0),
                jnp.take(beta, ci, axis=0),
                C_flat[ri, ci], M_lin, B_lin, Fz, Fz)
        xr, xi, rep = waterfall_dispatch(physics, nodes_l, args,
                                         relax=relax)
        std = np.sqrt(np.sum(xr * xr + xi * xi, axis=-1) * dw)
        if return_xi:
            return std, rep, xr, xi
        return std, rep

    return pipeline


def waterfall_case_dispatch(model, args):
    """The single-Model entry: route ``Model.analyze_cases``'s prepared
    case inputs through the iteration waterfall (what the non-slots
    dispatch does when ``RAFT_TPU_FIXED_POINT`` != legacy).  The node
    bundle is SHARED across lanes (vmapped in_axes None) and NEVER
    node-padded: the fixed point couples frequencies and nodes through
    the drag-RMS reductions, so only the pure vmap lane axis is
    quantized and per-lane arithmetic is bit-identical to the legacy
    closed-over-nodes pipeline's."""
    from raft_tpu.serve.buckets import SlotPhysics

    physics = SlotPhysics.from_model(model)
    nodes = model.nodes.astype(model.dtype)
    return waterfall_dispatch(physics, nodes, tuple(args),
                              relax=float(getattr(model, "relax", 0.8)),
                              shared_nodes=True)
