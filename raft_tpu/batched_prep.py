"""Batched, traced design preparation — the per-design host prep loop as
one ``jit(vmap)`` program (ISSUE 12 tentpole).

Solo prep (``sweep.py:_prepare_design``, the serve engine's prep workers)
builds a full :class:`~raft_tpu.model.Model` per design and walks
geometry/statics/mooring in Python — serial host work on the hot path
while the solve side is already vmapped and sharded.  This module maps a
``[n_designs]`` stacked design batch straight to the packed
:class:`~raft_tpu.geometry.HydroNodes` + case-args bundles the bucket
executables consume:

- **Host, per design** (cannot be traced — shapes depend on values): the
  *real* strip discretization via :func:`geometry.process_members` (dls_max
  spacing, per-segment ``ceil`` counts), mooring parsing, and knob
  extraction.  This is what "reproduce compute()'s actual node
  re-distribution" means: node positions/spacings come from each design's
  own discretization, not a proportional scaling of a frozen node set.
- **Device, per fixed lane block**: ONE traced program evaluates
  statics (:func:`parametric.compute_statics_t`), node packing
  (:func:`parametric.pack_nodes_t`, value-only waterline masks) and the
  Morison added-mass matrix for every lane, vmapped over designs; ONE
  design×case-batched mooring equilibrium
  (:func:`mooring.case_mooring_design_batch_fn`) linearizes all lanes'
  mooring at once.

Bit-identity (the PR 3/PR 8 house recipe): the program shape is a fixed
lane block (``RAFT_TPU_PREP_BLOCK``, default 8; short blocks are padded
with replicas of lane 0), every traced stage is elementwise in the lane
axis, and the mooring Newton freezes converged lanes
(``mooring.solve_equilibrium``), so a design's prepared bits are
independent of its batch mates — solo prep under the flag IS a batch of
one, and ``np.array_equal`` holds across compositions.  Legacy (flag-off)
prep is a *different* program (host NumPy); the two agree to roundoff,
which is why ``RAFT_TPU_BATCHED_PREP`` defaults off and tier-1 bits stay
untouched.

Family discipline: lanes share a template whose host branch decisions
(degenerate-frustum flags, cap classifications, waterplane-crossing
segments, strip counts — everything the traced twins read from
``tpl.*``) are frozen into the program.  :func:`PrepFamily.extract`
recomputes every one of those predicates for the candidate design and
raises :class:`PrepFamilyError` on any mismatch — the callers fall back
to solo prep for that design (a *fallback*, not a quarantine).
"""

import json
import os
from types import SimpleNamespace

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.geometry import HydroNodes, process_members
from raft_tpu.hydro import added_mass_morison
from raft_tpu.io.schema import cases_as_dicts, get_from_dict
from raft_tpu.mooring import case_mooring_design_batch_fn, parse_mooring
from raft_tpu.parametric import (
    _lateral_norm_zero,
    _segment_strip_counts,
    compute_statics_t,
    pack_nodes_t,
)
from raft_tpu.utils.placement import put_cpu


def batched_prep_enabled(flag=None):
    """Whether batched traced prep is on (``RAFT_TPU_BATCHED_PREP``,
    default off so tier-1 bits stay untouched).  ``flag`` overrides."""
    if flag is not None:
        return bool(flag)
    return os.environ.get("RAFT_TPU_BATCHED_PREP", "0").lower() in (
        "1", "true", "yes", "on")


def prep_block_size():
    """Fixed lane-block size of the traced prep program
    (``RAFT_TPU_PREP_BLOCK``, default 8)."""
    return max(1, int(os.environ.get("RAFT_TPU_PREP_BLOCK", "8")))


class PrepFamilyError(RuntimeError):
    """The design cannot join this prep family (branch signature, shape,
    or configuration mismatch) — callers fall back to solo prep."""


# knob leaves consumed as tm[...] by the traced twins
_TM_KEYS = (
    "rA", "q", "p1", "p2", "R", "stations", "dorsl", "t", "l_fill",
    "rho_fill", "cap_stations", "cap_t", "cap_d_in", "r", "ls", "dls",
    "ds", "drs",
)
# knob leaves the traced twins read off the template object — served
# traced through the _TplView overlay
_VIEW_KEYS = (
    "rho_shell", "Ca_p1", "Ca_p2", "Ca_End", "Cd_q", "Cd_p1", "Cd_p2",
    "Cd_End",
)


class _TplView:
    """Template proxy for the traced twins: attribute reads used in
    *arithmetic* (shell density, drag/added-mass coefficients) resolve to
    traced per-lane values, while every branch-deciding read falls
    through to the host template member."""

    __slots__ = ("_tpl", "_over")

    def __init__(self, tpl, over):
        object.__setattr__(self, "_tpl", tpl)
        object.__setattr__(self, "_over", over)

    def __getattr__(self, name):
        over = object.__getattribute__(self, "_over")
        if name in over:
            return over[name]
        return getattr(object.__getattribute__(self, "_tpl"), name)


def _scalarish(x):
    return np.isscalar(x) or np.ndim(x) == 0


def _member_signature(m):
    """Every host branch decision the traced twins freeze from the
    template, recomputed for member ``m`` — two members with equal
    signatures take identical branches through
    member_inertia_t / member_hydrostatics_t / pack_nodes_t.

    Includes the waterplane heading constant ``arctan2(q1, q0)`` (a host
    float embedded in crossing-segment hydrostatics) so program reuse
    across equal-signature families is value-safe.
    """
    st = np.asarray(m.stations, float)
    n = len(st)
    circ = bool(m.circular)
    cap_st = np.atleast_1d(np.asarray(m.cap_stations, float))
    ncap = len(cap_st)
    sig = [(
        "struct", circ, bool(m.potMod), int(m.ns), n, ncap,
        _scalarish(m.l_fill), _scalarish(m.rho_fill),
        tuple(_segment_strip_counts(m)), _lateral_norm_zero(m),
    )]

    # hydrostatics: per-segment crossing classification + the embedded
    # waterplane heading for crossing segments of non-vertical members
    for i in range(1, n):
        zA = float(m.rA[2] + m.q[2] * st[i - 1])
        zB = float(m.rA[2] + m.q[2] * st[i])
        crossing = zA * zB <= 0 and not (zA <= 0 and zB <= 0)
        sig.append(("hyd", i, crossing, zA <= 0 and zB <= 0))
        if crossing and not _lateral_norm_zero(m):
            sig.append(("beta", float(np.arctan2(m.q[1], m.q[0]))))

    def lf_at(i):
        return float(m.l_fill if _scalarish(m.l_fill)
                     else np.asarray(m.l_fill)[i - 1])

    # inertia: per-segment degenerate-frustum / uniform / fill flags
    for i in range(1, n):
        l_t = float(st[i] - st[i - 1])
        sig.append(("seg", i, l_t == 0.0))
        if l_t == 0.0:
            continue
        lf = lf_at(i)
        if circ:
            dA, dB = float(m.d[i - 1]), float(m.d[i])
            dAi = dA - 2 * float(m.t[i - 1])
            dBi = dB - 2 * float(m.t[i])
            dBf = (dBi - dAi) * (lf / l_t) + dAi
            sig.append((dA == 0 and dB == 0, dAi == 0 and dBi == 0,
                        dA == dB, dAi == dBi,
                        dAi == 0 and dBf == 0, dAi == dBf, lf == 0.0))
        else:
            def deg(a, b):
                A1, A2 = a[0] * a[1], b[0] * b[1]
                return (A1 + A2 + np.sqrt(max(A1 * A2, 0.0))) == 0

            slA, slB = np.asarray(m.sl[i - 1]), np.asarray(m.sl[i])
            slAi = slA - 2 * float(m.t[i - 1])
            slBi = slB - 2 * float(m.t[i])
            sig.append((deg(slA, slB), deg(slAi, slBi), lf == 0.0))

    # end caps (circular only — the traced path rejects rectangular caps)
    if ncap and not circ:
        sig.append(("rect-caps",))
        return tuple(sig)
    if ncap:
        d_in_t = np.asarray(m.d, float) - 2 * np.asarray(m.t, float)
        cap_d = np.atleast_1d(np.asarray(m.cap_d_in, float))
        cap_t = np.atleast_1d(np.asarray(m.cap_t, float))
        for i in range(ncap):
            L_t, h_t = float(cap_st[i]), float(cap_t[i])
            if L_t == st[0]:
                cls = 0
                dA_t = float(d_in_t[0])
                dB_t = float(np.interp(L_t + h_t, st, d_in_t))
                dAi_t = float(cap_d[i])
                dBi_t = dB_t * (dAi_t / dA_t)
            elif L_t == st[-1]:
                cls = 1
                dA_t = float(np.interp(L_t - h_t, st, d_in_t))
                dB_t = float(d_in_t[-1])
                dBi_t = float(cap_d[i])
                dAi_t = dA_t * (dBi_t / dB_t)
            elif i < ncap - 1 and L_t == float(cap_st[i + 1]):
                cls = 2
                dA_t = float(np.interp(L_t - h_t, st, d_in_t))
                dB_t = float(d_in_t[i])
                dBi_t = float(cap_d[i])
                dAi_t = dA_t * (dBi_t / dB_t)
            elif i > 0 and L_t == float(cap_st[i - 1]):
                cls = 3
                dA_t = float(d_in_t[i])
                dB_t = float(np.interp(L_t + h_t, st, d_in_t))
                dAi_t = float(cap_d[i])
                dBi_t = dB_t * (dAi_t / dA_t)
            else:
                cls = 4
                dA_t = float(np.interp(L_t - h_t / 2, st, d_in_t))
                dB_t = float(np.interp(L_t + h_t / 2, st, d_in_t))
                dM_t = float(np.interp(L_t, st, d_in_t))
                dAi_t = dA_t * (float(cap_d[i]) / dM_t)
                dBi_t = dB_t * (float(cap_d[i]) / dM_t)
            sig.append(("cap", i, cls,
                        dA_t == 0 and dB_t == 0, dAi_t == 0 and dBi_t == 0,
                        dA_t == dB_t, dAi_t == dBi_t, h_t == 0.0))
    return tuple(sig)


def _member_knobs(m):
    """The traced per-lane leaves for one host member — its own real
    discretization and geometry, as f64 NumPy (stacked over lanes by the
    caller)."""
    circ = m.circular
    ncap = len(np.atleast_1d(m.cap_stations))
    if circ:
        cap_d_in = (np.zeros(0) if ncap == 0
                    else np.atleast_1d(np.asarray(m.cap_d_in, float)))
    else:
        cap_d_in = (np.atleast_2d(np.asarray(m.cap_d_in, float))
                    if ncap else np.zeros((0, 2)))
    kn = dict(
        rA=np.asarray(m.rA, float),
        q=np.asarray(m.q, float),
        p1=np.asarray(m.p1, float),
        p2=np.asarray(m.p2, float),
        R=np.asarray(m.R, float),
        stations=np.asarray(m.stations, float),
        dorsl=np.asarray(m.dorsl(), float),
        t=np.asarray(m.t, float),
        l_fill=np.asarray(m.l_fill, float),
        rho_fill=np.asarray(m.rho_fill, float),
        cap_stations=np.atleast_1d(np.asarray(m.cap_stations, float)),
        cap_t=np.atleast_1d(np.asarray(m.cap_t, float)),
        cap_d_in=cap_d_in,
        r=np.asarray(m.r, float),
        ls=np.asarray(m.ls, float),
        dls=np.asarray(m.dls, float),
        ds=np.asarray(m.ds, float),
        drs=np.asarray(m.drs, float),
        rho_shell=np.asarray(float(m.rho_shell)),
    )
    for key in ("Ca_p1", "Ca_p2", "Ca_End", "Cd_q", "Cd_p1", "Cd_p2",
                "Cd_End"):
        kn[key] = np.asarray(getattr(m, key), float)
    return kn


def _turbine_vector(design):
    turb = design["turbine"]
    return np.array([float(turb["mRNA"]), float(turb["IxRNA"]),
                     float(turb["IrRNA"]), float(turb["xCG_RNA"]),
                     float(turb["hHub"])])


def _settings_key(design):
    """The scalars that define the shared frequency grid / solver config
    (must match across a family — they are baked into the template
    Model)."""
    settings = design.get("settings", {})
    site = design.get("site", {})
    return (
        get_from_dict(settings, "min_freq", default=0.01, dtype=float),
        get_from_dict(settings, "max_freq", default=1.00, dtype=float),
        get_from_dict(settings, "XiStart", default=0.1, dtype=float),
        get_from_dict(settings, "nIter", default=15, dtype=int),
        float(site["water_depth"]),
        float(site.get("rho_water", 1025.0)),
        float(site.get("g", 9.81)),
        float(design["platform"].get("yaw_stiffness", 0.0)),
    )


def family_key(design, cases=None, precision=None):
    """Grouping key: designs with equal keys are batchable in one
    :class:`PrepFamily` (equal branch signatures, frequency grid, site
    scalars, cases table, mooring shape, turbine mode)."""
    members = process_members(design)
    sigs = tuple(_member_signature(m) for m in members)
    if cases is None:
        cases = cases_as_dicts(design)
    ms = parse_mooring(design["mooring"], rho_water=_settings_key(design)[5],
                       g=_settings_key(design)[6])
    payload = (
        repr(sigs), _settings_key(design),
        json.dumps(cases, sort_keys=True, default=float),
        tuple(np.asarray(ms.L).shape), ms.bridles is None,
        int(get_from_dict(design["turbine"], "aeroServoMod", default=1)),
        str(precision),
    )
    return repr(payload)


# compiled geometry programs shared across equal-signature families (the
# signature pins every host constant the trace embeds, incl. the
# waterplane heading floats)
_GEOM_PROGRAM_CACHE = {}


class PreppedDesign:
    """Model-lite result of batched prep: exactly the attribute surface
    the sweep/serve consumers read off a prep Model (SlotPhysics.from_model,
    pipeline builders, default_collect, retry escalation) — no solver
    state, no per-design jitted executables."""

    def __init__(self, template_model, design, statics, nodes_f64):
        tm = template_model
        self.design = design
        self.w = tm.w
        self.k = tm.k
        self.nw = tm.nw
        self.dw = tm.dw
        self.depth = tm.depth
        self.rho_water = tm.rho_water
        self.g = tm.g
        self.XiStart = tm.XiStart
        self.nIter = tm.nIter
        self.dtype = tm.dtype
        self.cdtype = tm.cdtype
        self.precision = tm.precision
        self.hHub = float(design["turbine"]["hHub"])
        self.aeroServoMod = tm.aeroServoMod
        self.yawstiff = float(design["platform"].get("yaw_stiffness", 0.0))
        self.statics = statics
        self.nodes = nodes_f64


class PrepFamily:
    """A template design whose frozen branch decisions define one traced
    prep program; designs that :meth:`extract` cleanly run through
    :meth:`prepare` in fixed lane blocks."""

    def __init__(self, base_design, precision=None, cases=None,
                 geometry_only=False):
        from raft_tpu.model import Model

        self.geometry_only = bool(geometry_only)
        self.model = Model(base_design, precision=precision)
        self.precision = precision
        self.templates = self.model.members
        self.sigs = [_member_signature(m) for m in self.templates]
        if any(("rect-caps",) in s for s in self.sigs):
            raise PrepFamilyError(
                "rectangular members with end caps have no traced twin")
        self.rho_water = float(self.model.rho_water)
        self.g = float(self.model.g)
        self.yawstiff = float(self.model.yawstiff)
        self._settings = _settings_key(base_design)
        self.block = prep_block_size()
        self._cpu = jax.devices("cpu")[0]
        self._geom_b = self._build_geom_program()
        if self.geometry_only:
            # geometry/statics/added-mass only (sweep_fused stages its
            # own batched mooring + aero downstream)
            self.cases = None
            self.zeta = self.beta = None
            self.nc = 0
            self._wind = np.zeros(0)
            self._moor_shape = None
            self._moor_fn = None
        else:
            self.cases = (list(cases) if cases is not None
                          else cases_as_dicts(base_design))
            if not self.cases:
                raise PrepFamilyError("design has no cases table")
            spec, height, period, beta, wind = self.model._case_arrays(
                self.cases)
            if self.model.aeroServoMod > 0 and np.any(wind > 0.0):
                raise PrepFamilyError(
                    "aero-servo cases with wind need the rotor host pass "
                    "— solo prep only")
            self._wind = wind
            self.zeta = self.model._zeta(spec, height, period)  # [nc, nw]
            self.beta = beta
            self.nc = len(self.cases)
            ms = self.model.ms
            if ms.bridles is not None:
                raise PrepFamilyError(
                    "bridled mooring linearization is host-staged — solo "
                    "prep only")
            self._moor_shape = tuple(np.asarray(ms.L).shape)
            self._moor_fn = case_mooring_design_batch_fn(
                self.rho_water, self.g, self.yawstiff)
        # engine-facing counters (reset by callers as needed)
        self.n_batched = 0
        self.n_blocks = 0

    # -- traced program ------------------------------------------------

    def _build_geom_program(self):
        key = (repr(tuple(self.sigs)), self.rho_water, self.g, self.block)
        fn = _GEOM_PROGRAM_CACHE.get(key)
        if fn is not None:
            return fn
        templates = tuple(self.templates)
        rho, g = self.rho_water, self.g

        def one_lane(kns, turb):
            tms = []
            for tpl, kn in zip(templates, kns):
                tm = {k: kn[k] for k in _TM_KEYS}
                tm["tpl"] = _TplView(tpl, {k: kn[k] for k in _VIEW_KEYS})
                tms.append(tm)
            stt = compute_statics_t(
                tms, None, rho, g,
                turbine_t=(turb[0], turb[1], turb[2], turb[3], turb[4]))
            nodes = pack_nodes_t(tms)
            A = added_mass_morison(nodes, rho)
            return nodes, stt, A

        fn = jax.jit(jax.vmap(one_lane))
        _GEOM_PROGRAM_CACHE[key] = fn
        return fn

    # -- per-design host stage -----------------------------------------

    def extract(self, design):
        """Host stage for one design: REAL discretization + knob leaves,
        guarded by the full branch-signature comparison.  Raises
        :class:`PrepFamilyError` on any mismatch."""
        if _settings_key(design) != self._settings:
            raise PrepFamilyError("settings/site scalars differ from family")
        aero = get_from_dict(design["turbine"], "aeroServoMod", default=1)
        if aero != self.model.aeroServoMod:
            raise PrepFamilyError("aeroServoMod differs from family")
        if not self.geometry_only and aero > 0 \
                and np.any(self._wind > 0.0):
            raise PrepFamilyError("aero-servo cases with wind — solo only")
        members = process_members(design)
        if len(members) != len(self.templates):
            raise PrepFamilyError("member count differs from family")
        for m, sig in zip(members, self.sigs):
            if _member_signature(m) != sig:
                raise PrepFamilyError(
                    f"member '{m.name}' branch signature differs from "
                    "family template (topology cell boundary)")
        ms = parse_mooring(design["mooring"], rho_water=self.rho_water,
                           g=self.g)
        if not self.geometry_only:
            if ms.bridles is not None:
                raise PrepFamilyError("bridled mooring — solo prep only")
            if tuple(np.asarray(ms.L).shape) != self._moor_shape:
                raise PrepFamilyError("mooring line-array shape differs")
            if float(design["platform"].get("yaw_stiffness", 0.0)) \
                    != self.yawstiff:
                raise PrepFamilyError("yaw stiffness differs from family")
        return {
            "design": design,
            "knobs": tuple(_member_knobs(m) for m in members),
            "turb": _turbine_vector(design),
            "ms": ms,
            "moor": tuple(np.asarray(a, float) for a in (
                ms.anchors, ms.rFair, ms.L, ms.EA, ms.w, ms.Wp, ms.cb)),
        }

    # -- batched device stage ------------------------------------------

    def prepare(self, lanes):
        """Run extracted lanes through the traced prep in fixed blocks.

        lanes : list of :meth:`extract` results.
        Returns a list of ``(PreppedDesign, nodes, args)`` triples in
        order — the exact contract of ``sweep.py:_prepare_design``.
        """
        out = []
        B = self.block
        for k0 in range(0, len(lanes), B):
            out.extend(self._prepare_block(lanes[k0:k0 + B]))
        return out

    def _geom_block_host(self, padded):
        """Run one padded block through the traced geometry program and
        pull everything back to host NumPy."""
        knobs_b = tuple(
            {k: np.stack([ln["knobs"][mi][k] for ln in padded])
             for k in padded[0]["knobs"][mi]}
            for mi in range(len(self.templates))
        )
        turb_b = np.stack([ln["turb"] for ln in padded])
        with jax.default_device(self._cpu):
            nodes_b, st_b, A_b = self._geom_b(knobs_b, turb_b)
        nodes_host = {k: np.asarray(getattr(nodes_b, k))
                      for k in nodes_b.__dataclass_fields__}
        st_host = {k: np.asarray(v) for k, v in st_b.items()}
        return nodes_host, st_host, np.asarray(A_b)

    def _statics_ns(self, st_host, i):
        return SimpleNamespace(
            mass=float(st_host["mass"][i]),
            V=float(st_host["V"][i]),
            zMeta=float(st_host["zMeta"][i]),
            rCG_TOT=st_host["rCG"][i],
            AWP=float(st_host["AWP"][i]),
            M_struc=st_host["M_struc"][i],
            C_struc=st_host["C_struc"][i],
            C_hydro=st_host["C_hydro"][i],
        )

    def prepare_geometry(self, lanes):
        """Geometry/statics/added-mass only — no cases, no mooring
        linearization.  Returns a list of ``(nodes_f64, statics,
        A_morison)`` triples in lane order, where ``statics`` exposes
        the attrs ``sweep_fused`` reads off ``compute_statics`` output
        (mass, V, zMeta, rCG_TOT, AWP, M_struc, C_struc, C_hydro)."""
        out = []
        B = self.block
        for k0 in range(0, len(lanes), B):
            blk = lanes[k0:k0 + B]
            padded = list(blk) + [blk[0]] * (B - len(blk))
            nodes_host, st_host, A_host = self._geom_block_host(padded)
            for i in range(len(blk)):
                nodes = HydroNodes(
                    **{k: v[i] for k, v in nodes_host.items()})
                out.append((nodes, self._statics_ns(st_host, i),
                            A_host[i]))
            self.n_batched += len(blk)
            self.n_blocks += 1
        return out

    def _prepare_block(self, lanes):
        B = self.block
        n = len(lanes)
        padded = list(lanes) + [lanes[0]] * (B - n)

        nodes_host, st_host, A_host = self._geom_block_host(padded)
        moor_b = tuple(
            np.stack([ln["moor"][i] for ln in padded])
            for i in range(7)
        )

        with jax.default_device(self._cpu):
            # design×case-batched mooring linearization at the traced
            # statics (f6 = 0: aero-off / windless gate above), one
            # fixed-shape dispatch per block
            f6 = np.zeros((B, self.nc, 6))
            rM = np.stack(
                [np.zeros(B), np.zeros(B), st_host["zMeta"]], axis=1)
            moor_dev = tuple(put_cpu(a) for a in moor_b)
            _, C_moor_b, _, _, _, _ = self._moor_fn(
                put_cpu(f6), put_cpu(st_host["mass"]),
                put_cpu(st_host["V"]), put_cpu(st_host["rCG"]),
                put_cpu(rM), put_cpu(st_host["AWP"]), *moor_dev, None)
            C_moor_b = np.asarray(C_moor_b)

        dtype = self.model.dtype
        nw = self.model.nw
        zeta = self.zeta.astype(dtype)
        beta = self.beta.astype(dtype)
        out = []
        for i in range(len(lanes)):
            nodes = HydroNodes(**{k: v[i] for k, v in nodes_host.items()})
            st = self._statics_ns(st_host, i)
            # args assembly: prepare_case_inputs' aero-off/no-BEM branch
            M_lin = np.broadcast_to(
                (st.M_struc + A_host[i])[None, None],
                (self.nc, nw, 6, 6)).astype(dtype)
            B_lin = np.zeros((self.nc, nw, 6, 6), dtype)
            C_lin = (st.C_struc[None] + st.C_hydro[None]
                     + C_moor_b[i]).astype(dtype)
            F_add_r = np.zeros((self.nc, nw, 6), dtype)
            F_add_i = np.zeros((self.nc, nw, 6), dtype)
            args = (zeta, beta, C_lin, M_lin, B_lin, F_add_r, F_add_i)
            prepped = PreppedDesign(self.model, lanes[i]["design"], st,
                                    nodes)
            out.append((prepped, nodes.astype(dtype), args))
        self.n_batched += len(lanes)
        self.n_blocks += 1
        return out


def prepare_designs(designs, precision=None, cases=None, family=None):
    """Convenience: one family from ``designs[0]``, every design through
    batched prep.  Raises :class:`PrepFamilyError` if any design cannot
    join — callers needing per-design fallback should drive
    :meth:`PrepFamily.extract` themselves."""
    if not designs:
        return []
    if family is None:
        family = PrepFamily(designs[0], precision=precision, cases=cases)
    return family.prepare([family.extract(d) for d in designs])
