"""Spectral fatigue: damage-equivalent loads from response PSDs.

The reference allocates DEL channels but leaves them zero-filled
("Additional calculation of fatigue loads is planned for future work",
reference docs/usage.rst:475; placeholders at reference
raft/raft_model.py:199, :224, :284).  Here they are computed from the
frequency-domain response directly with Dirlik's rainflow-range
approximation (T. Dirlik, "Application of computers in fatigue analysis",
PhD thesis, Warwick 1985) — the standard spectral rainflow model for
Gaussian wide-band processes, which the frequency-domain responses are by
construction.

Everything is host-side NumPy post-processing on already-computed PSDs
(one closed-form evaluation per channel; nothing worth putting on the
accelerator).
"""

import math

import numpy as np


def spectral_moments(S, w, orders=(0, 1, 2, 4)):
    """Spectral moments m_n = int w^n S(w) dw of a one-sided response
    spectrum sampled on the (uniform or non-uniform) grid ``w`` [rad/s]."""
    S = np.asarray(S, float)
    w = np.asarray(w, float)
    return tuple(np.trapezoid(w**n * S, w) for n in orders)


def dirlik_del(S, w, m_wohler, f_ref=1.0):
    """Damage-equivalent load range of a zero-mean Gaussian process with
    one-sided spectrum ``S(w)`` for an S-N curve of slope ``m_wohler``,
    referenced to cycle frequency ``f_ref`` [Hz]:

        DEL = ( nu_p / f_ref * E[S_rf^m] )^(1/m)

    with nu_p the peak rate and E[S_rf^m] the m-th moment of Dirlik's
    rainflow-range density (closed form via gamma functions).  The
    exposure duration cancels, so the DEL is duration-independent at the
    reference frequency.  Returns 0 for an (effectively) empty spectrum.
    """
    m0, m1, m2, m4 = spectral_moments(S, w)
    if m0 <= 0.0 or m2 <= 0.0 or m4 <= 0.0:
        return 0.0
    nu_p = math.sqrt(m4 / m2) / (2.0 * math.pi)          # peaks per second

    xm = (m1 / m0) * math.sqrt(m2 / m4)
    a2 = m2 / math.sqrt(m0 * m4)                          # irregularity
    a2 = min(a2, 1.0 - 1e-12)
    D1 = 2.0 * (xm - a2 * a2) / (1.0 + a2 * a2)
    D1 = min(max(D1, 1e-12), 1.0 - 1e-12)
    R = (a2 - xm - D1 * D1) / (1.0 - a2 - D1 + D1 * D1)
    R = min(max(R, 1e-12), 1.0 - 1e-12)
    D2 = (1.0 - a2 - D1 + D1 * D1) / (1.0 - R)
    D3 = 1.0 - D1 - D2
    Q = 1.25 * (a2 - D3 - D2 * R) / D1
    Q = max(Q, 1e-12)

    m_ = float(m_wohler)
    ESm = (2.0 * math.sqrt(m0)) ** m_ * (
        D1 * Q**m_ * math.gamma(1.0 + m_)
        + math.sqrt(2.0) ** m_ * math.gamma(1.0 + m_ / 2.0)
        * (D2 * R**m_ + D3)
    )
    return float((nu_p / f_ref * ESm) ** (1.0 / m_))


def narrow_band_del(S, w, m_wohler, f_ref=1.0):
    """Rayleigh (narrow-band) rainflow DEL — the analytic upper-bound
    benchmark Dirlik reduces to for a narrow-band spectrum."""
    m0, _, m2, _ = spectral_moments(S, w)
    if m0 <= 0.0 or m2 <= 0.0:
        return 0.0
    nu_0 = math.sqrt(m2 / m0) / (2.0 * math.pi)          # upcrossing rate
    m_ = float(m_wohler)
    ESm = (2.0 * math.sqrt(2.0 * m0)) ** m_ * math.gamma(1.0 + m_ / 2.0)
    return float((nu_0 / f_ref * ESm) ** (1.0 / m_))
