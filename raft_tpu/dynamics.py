"""Batched frequency-domain response solve.

The reference's hot path (reference raft/raft_model.py:524-656 solveDynamics:
fixed-point drag-linearization loop around per-frequency 6x6 complex solves,
HOT LOOP #3) expressed as one XLA graph:

 - the per-frequency impedance assembly and solve are batched over the whole
   frequency axis (and, via vmap in the Model, over load cases);
 - the complex 6x6 solves are performed as real 12x12 block solves
   [[Zr, -Zi], [Zi, Zr]] — the TPU backend has no complex LU, and the block
   form runs in f32 on the MXU with an optional iterative-refinement step to
   recover accuracy;
 - the under-relaxed fixed point reproduces the reference's semantics
   exactly (start amplitudes XiStart, relaxation 0.2*old + 0.8*new,
   tolerance check |Xi - XiLast|/(|Xi|+tol) < tol, warn-and-continue on
   non-convergence) via a while_loop whose state freezes once converged —
   matching the reference's mid-loop `break` without data-dependent Python
   control flow.

Solver health (raft_tpu/health.py) is tracked in-graph:

 - NaN quarantine: a non-finite iterate freezes its lane at the last
   finite state and sets a flag instead of propagating through the
   batched [design, case] solve (the reference would print a warning and
   ship NaN statistics);
 - the final refined re-solve runs an escalating conditioned-solve
   recovery ladder (baseline Gauss-Jordan -> extra iterative refinement ->
   flagged Tikhonov regularization when the condition estimate of Z(w)
   blows up, e.g. at a zero-damping resonance);
 - every solve returns a :class:`raft_tpu.health.SolveReport` pytree
   (convergence flag, iteration count, residual, condition estimate,
   non-finite flag, recovery tier) that vmaps with the solve itself.
"""

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.health import (
    SolveReport,
    TIER_BASELINE,
    TIER_REFINE,
    TIER_TIKHONOV,
)
from raft_tpu.hydro import linearized_drag
from raft_tpu.precision import mixed_precision_enabled, mp_round


def _gj_step(i, M, idx):
    """One Gauss-Jordan elimination step on the augmented batch [..., n, m];
    returns the updated matrix and the |pivot| per batch element."""
    col = jnp.abs(jnp.take(M, i, axis=-1))          # column i magnitudes
    col = jnp.where(idx < i, -jnp.inf, col)         # rows above i are done
    p = jnp.argmax(col, axis=-1)                    # pivot row per batch
    rp = jnp.take_along_axis(M, p[..., None, None], axis=-2)[..., 0, :]
    ri = jnp.take(M, i, axis=-2)
    is_i = (idx == i)[:, None]
    is_p = (idx == p[..., None])[..., :, None]
    M = jnp.where(is_i, rp[..., None, :],
                  jnp.where(is_p, ri[..., None, :], M))
    piv = jnp.take(rp, i, axis=-1)[..., None]
    row = rp / piv                                  # normalized pivot row
    fac = jnp.take(M, i, axis=-1)[..., None]        # column i after swap
    M = jnp.where(is_i, row[..., None, :], M - fac * row[..., None, :])
    return M, jnp.abs(piv[..., 0])


def gauss_solve(A, b):
    """Batched dense solve by Gauss-Jordan elimination with partial
    pivoting, fully vectorized over the leading batch axes.

    A : [..., n, n];  b : [..., n, 1] -> x : [..., n, 1]

    XLA's batched LU (`jnp.linalg.solve`) runs ~13x slower than this on TPU
    for the tiny 12x12 systems in the RAO solve (measured 4.98 ms vs
    0.39 ms for 1536 systems on v5e): LU lowers to a column-by-column loop
    with dynamic-slice updates, while this formulation is n fori_loop steps
    of pure elementwise/where ops over the whole batch.  Pivot selection
    uses one argmax + gather per step; row swap and elimination are masked
    `where`s, so the graph has static shapes throughout.
    """
    n = A.shape[-1]
    M = jnp.concatenate([A, b], axis=-1)                # [..., n, n+1]
    idx = jnp.arange(n)
    M = jax.lax.fori_loop(0, n, lambda i, M: _gj_step(i, M, idx)[0], M)
    return M[..., -1:]


def gj_cond_estimate(A):
    """Cheap per-batch condition estimate of A: the max/min |pivot| ratio
    of a Gauss-Jordan elimination of the ROW-EQUILIBRATED matrix.

    Row equilibration (divide each row by its max magnitude) makes the
    estimate scale-invariant: the mixed translational/rotational DOFs of
    the impedance carry wildly different physical scales, and the raw
    pivot ratio would report that scaling disparity as ill-conditioning.
    A genuinely (near-)singular Z(w) — e.g. a zero-damping resonance where
    -w^2 M + C loses rank and Zi = 0 — drives the smallest equilibrated
    pivot toward 0 and the estimate toward +inf.  Non-finite inputs
    report +inf.  Estimate-only: the actual solves run on the
    un-equilibrated matrix so the baseline arithmetic is unchanged.
    """
    n = A.shape[-1]
    d = jnp.max(jnp.abs(A), axis=-1, keepdims=True)
    d = jnp.where(d > 0, d, jnp.ones_like(d))
    M = jnp.concatenate([A / d, jnp.zeros_like(A[..., :1])], axis=-1)
    idx = jnp.arange(n)
    shape = A.shape[:-2]
    init = (M,
            jnp.full(shape, jnp.inf, A.dtype),
            jnp.zeros(shape, A.dtype))

    def step(i, carry):
        M, pmin, pmax = carry
        M, pa = _gj_step(i, M, idx)
        return M, jnp.minimum(pmin, pa), jnp.maximum(pmax, pa)

    _, pmin, pmax = jax.lax.fori_loop(0, n, step, init)
    tiny = jnp.asarray(jnp.finfo(A.dtype).tiny, A.dtype)
    cond = pmax / jnp.maximum(pmin, tiny)
    return jnp.where(jnp.isfinite(cond), cond,
                     jnp.asarray(jnp.inf, A.dtype))


def _block_system(Zr, Zi, Fr, Fi):
    """(Zr + i Zi) x = Fr + i Fi as the equivalent real block system."""
    top = jnp.concatenate([Zr, -Zi], axis=-1)
    bot = jnp.concatenate([Zi, Zr], axis=-1)
    A = jnp.concatenate([top, bot], axis=-2)            # [..., 12, 12]
    b = jnp.concatenate([Fr, Fi], axis=-1)[..., None]   # [..., 12, 1]
    return A, b


def solve_complex_6x6(Zr, Zi, Fr, Fi, refine=1):
    """Solve (Zr + i Zi) x = (Fr + i Fi) batched over leading axes via the
    equivalent real block system.

    Zr, Zi : [..., 6, 6];  Fr, Fi : [..., 6]
    Returns (xr, xi) : [..., 6] each.
    refine : iterative-refinement steps (cheap; recovers ~2 digits in f32).
    """
    A, b = _block_system(Zr, Zi, Fr, Fi)
    solve = _solve_dispatch()
    x = solve(A, b)
    for _ in range(refine):
        r = b - A @ x
        x = x + solve(A, r)
    x = x[..., 0]
    return x[..., :6], x[..., 6:]


def _solve_dispatch():
    """The batched dense solve for the RAO hot loop: the hand-written
    Pallas elimination kernel when ``RAFT_TPU_PALLAS`` requests it
    (interpret mode off-TPU, so CPU tier-1 runs the kernel body), the
    generic XLA :func:`gauss_solve` otherwise.  Only this hot-loop
    entry dispatches — the recovery ladder
    (:func:`solve_complex_6x6_ladder`) always uses the baseline path,
    so tier selection never changes arithmetic under recovery."""
    from raft_tpu.pallas_kernels import gauss_solve_pallas, pallas_enabled

    return gauss_solve_pallas if pallas_enabled() else gauss_solve


def solve_complex_6x6_ladder(Zr, Zi, Fr, Fi, refine=1, resid_tol=None,
                             cond_max=None, tik_rel=1e-3, extra_refine=2):
    """The batched complex 6x6 solve with the escalating conditioned-solve
    recovery ladder, per batch element (per frequency bin in the RAO
    solve):

     tier 0 (baseline)  : Gauss-Jordan block solve + ``refine`` standard
                          iterative-refinement steps — bit-identical to
                          :func:`solve_complex_6x6` (the extra tiers are
                          computed in-graph but only *selected* where
                          needed, so healthy bins keep the exact baseline
                          arithmetic);
     tier 1 (refine)    : ``extra_refine`` additional refinement steps
                          where the relative residual exceeds
                          ``resid_tol`` or the baseline went non-finite;
     tier 2 (tikhonov)  : flagged Tikhonov-regularized solve
                          (A^T A + lam^2 I) x = A^T b with
                          lam = tik_rel * max|A|, where the
                          row-equilibrated condition estimate exceeds
                          ``cond_max`` or the refined solve is still bad —
                          a numerically singular Z(w) (zero-damping
                          resonance) then yields a finite regularized
                          response instead of Inf/NaN poisoning the batch.

    Defaults scale with the working dtype: resid_tol = 1e3*eps (f32
    ~1.2e-4, f64 ~2.2e-13 — two orders above a healthy refined solve),
    cond_max = 0.02/eps (f32 ~1.7e5, f64 ~9e13).

    Returns (xr, xi, residual, cond, tier):
      xr, xi   : [..., 6] solution parts (finite whenever any tier is)
      residual : [...] final relative residual max|b - A x| / max|b|
      cond     : [...] condition estimate (see :func:`gj_cond_estimate`)
      tier     : [...] int recovery tier taken (TIER_*)
    """
    A, b = _block_system(Zr, Zi, Fr, Fi)
    dtype = A.dtype
    eps = float(np.finfo(dtype).eps)
    if resid_tol is None:
        resid_tol = 1e3 * eps
    if cond_max is None:
        cond_max = 0.02 / eps
    tiny = jnp.asarray(np.finfo(dtype).tiny, dtype)
    bnorm = jnp.max(jnp.abs(b), axis=(-2, -1))

    def rel_resid(x):
        r = jnp.max(jnp.abs(b - A @ x), axis=(-2, -1)) / (bnorm + tiny)
        return jnp.where(jnp.isfinite(r), r, jnp.asarray(jnp.inf, dtype))

    def finite(x):
        return jnp.all(jnp.isfinite(x), axis=(-2, -1))

    # tier 0: the exact baseline path of solve_complex_6x6
    x0 = gauss_solve(A, b)
    for _ in range(refine):
        x0 = x0 + gauss_solve(A, b - A @ x0)
    r0 = rel_resid(x0)
    need1 = (r0 > resid_tol) | ~finite(x0)

    # tier 1: extra refinement (always computed, selected where needed —
    # the 12x12 systems are tiny, so unconditional compute + select keeps
    # the graph free of data-dependent control flow under vmap)
    x1 = x0
    for _ in range(extra_refine):
        x1 = x1 + gauss_solve(A, b - A @ x1)
    xa = jnp.where(need1[..., None, None], x1, x0)
    ra = rel_resid(xa)

    # tier 2: flagged Tikhonov regularization on the normal equations
    cond = gj_cond_estimate(A)
    need2 = (ra > resid_tol) | ~finite(xa) | (cond > cond_max)
    anorm = jnp.max(jnp.abs(A), axis=(-2, -1))
    lam2 = (tik_rel * anorm) ** 2 + tiny
    At = jnp.swapaxes(A, -1, -2)
    n = A.shape[-1]
    G = At @ A + lam2[..., None, None] * jnp.eye(n, dtype=dtype)
    x2 = gauss_solve(G, At @ b)
    x = jnp.where(need2[..., None, None], x2, xa)

    tier = jnp.where(
        need2, TIER_TIKHONOV, jnp.where(need1, TIER_REFINE, TIER_BASELINE)
    )
    residual = rel_resid(x)
    x = x[..., 0]
    return x[..., :6], x[..., 6:], residual, cond, tier


def assemble_impedance(w, M, B, C, mp=False):
    """Z(w) = -w^2 M + i w B + C as (real, imag) parts.

    w : [nw]; M, B : [nw, 6, 6]; C : [6, 6] or [nw, 6, 6]
    mp : mixed-precision operand rounding (bf16 matrix operands, full-
        precision arithmetic — see raft_tpu/precision.py); ``False`` is
        the exact baseline expression.
    """
    w2 = (w * w)[:, None, None]
    if mp:
        Zr = -w2 * mp_round(M) + mp_round(C)
        Zi = w[:, None, None] * mp_round(B)
        return Zr, Zi
    Zr = -w2 * M + C
    Zi = w[:, None, None] * B
    return Zr, Zi


def solve_dynamics(
    nodes,
    u,
    w,
    dw,
    rho,
    M_lin,
    B_lin,
    C_lin,
    F_lin_r,
    F_lin_i,
    XiStart,
    nIter=15,
    tol=0.01,
    refine=1,
    checkable=False,
    relax=0.8,
):
    """Fixed-point dynamics solve for one case (vmap over cases in the Model).

    Parameters
    ----------
    nodes : HydroNodes (jnp arrays, working dtype)
    u     : [N, 3, nw] complex wave velocity at nodes
    M_lin, B_lin : [nw, 6, 6] frequency-dependent mass/damping (struct + BEM
        + morison + aero already summed; reference raft_model.py:552-555)
    C_lin : [6, 6] total stiffness
    F_lin_r/i : [nw, 6] linear excitation force (real/imag parts)
    XiStart : initial amplitude guess (reference raft_model.py:50, :535)
    relax : weight of the NEW iterate in the under-relaxed update
        (reference: 0.8, i.e. Xi <- 0.2*old + 0.8*new); the sweep drivers'
        bounded non-convergence retry re-solves with a smaller value
        (stronger under-relaxation).

    Returns (Xi_r, Xi_i, report) : [nw, 6] response amplitude parts plus a
    :class:`raft_tpu.health.SolveReport`.  A non-finite iterate freezes the
    lane at its last finite state (NaN quarantine) instead of propagating
    through a batched solve; the returned amplitudes are always finite
    unless every recovery tier failed AND no finite iterate ever existed
    (then they are zero with ``nonfinite`` set).
    """
    ph = fixed_point_phases(
        nodes, u, w, dw, rho, M_lin, B_lin, C_lin, F_lin_r, F_lin_i,
        XiStart, nIter=nIter, tol=tol, refine=refine, relax=relax,
    )
    if checkable:
        # scan-based fixed-trip-count variant with the same freeze
        # semantics: jax.experimental.checkify supports scan but not this
        # while_loop, so the NaN-checking debug pipeline
        # (raft_tpu.validate.checked_pipeline) requests this path
        def scan_body(state, _):
            state = jax.lax.cond(ph.cond(state), ph.body,
                                 lambda s: s, state)
            return state, None
        state, _ = jax.lax.scan(scan_body, ph.init, None, length=nIter + 1)
    else:
        state = jax.lax.while_loop(ph.cond, ph.body, ph.init)
    return ph.finalize(state)


class FixedPointPhases:
    """The dynamics fixed point decomposed into reusable phases.

    ``init`` is the loop-carried state pytree
    ``(i, XiNext, XiPoint, Xi_lastfinite, done, froze)``; ``cond``/
    ``body`` are the while_loop pieces; ``finalize(state)`` performs the
    refined re-solve through the recovery ladder and builds the
    SolveReport.  :func:`solve_dynamics` composes them back into the
    legacy monolithic solve (bit-for-bit the pre-refactor graph), and the
    convergence-aware engine (raft_tpu/waterfall.py) drives the SAME
    phase closures in fixed K-iteration blocks with active-lane
    compaction between blocks — per-lane arithmetic is shared by
    construction, which is what makes the waterfall's bit-parity contract
    a property of batching alone.
    """

    def __init__(self, init, cond, body, finalize):
        self.init = init
        self.cond = cond
        self.body = body
        self.finalize = finalize


def fixed_point_phases(
    nodes,
    u,
    w,
    dw,
    rho,
    M_lin,
    B_lin,
    C_lin,
    F_lin_r,
    F_lin_i,
    XiStart,
    nIter=15,
    tol=0.01,
    refine=1,
    relax=0.8,
):
    """Build the fixed-point phase closures for one case (see
    :class:`FixedPointPhases`).  Same operands and semantics as
    :func:`solve_dynamics`, which delegates here."""
    nw = w.shape[0]
    cdtype = u.dtype
    relax = float(relax)
    # round so the default relax=0.8 reproduces the reference's literal
    # 0.2 weight exactly (1.0 - 0.8 = 0.19999999999999996 in binary)
    w_old = round(1.0 - relax, 12)
    XiLast = jnp.full((6, nw), XiStart, dtype=cdtype)
    Xi0 = jnp.zeros((6, nw), dtype=cdtype)

    # mixed precision (RAFT_TPU_MIXED_PRECISION, default off — read at
    # trace time): bf16-operand assembly inside the fixed point; the
    # final re-solve below shadows it with a full-precision assembly and
    # degraded lanes fall back to it (raft_tpu/precision.py)
    mp = mixed_precision_enabled()

    def assemble(XiL, full_precision=False):
        use_mp = mp and not full_precision
        B_drag, F_drag = linearized_drag(nodes, XiL, u, w, dw, rho,
                                         mp=use_mp)
        B_tot = B_lin + B_drag[None, :, :]
        Zr, Zi = assemble_impedance(w, M_lin, B_tot, C_lin, mp=use_mp)
        F = F_drag + (F_lin_r + 1j * F_lin_i).astype(cdtype)  # [nw, 6]
        return Zr, Zi, F

    def step(XiL, n_refine):
        Zr, Zi, F = assemble(XiL)
        xr, xi = solve_complex_6x6(
            Zr, Zi, jnp.real(F), jnp.imag(F), refine=n_refine
        )
        return (xr + 1j * xi).T                                # [6, nw]

    def cond(state):
        i, XiLast, XiPoint, Xi, done, froze = state
        return (i < nIter + 1) & (~done)

    def body(state):
        i, XiLast, XiPoint, Xi_prev, done, froze = state
        # no refinement inside the loop: the fixed point only needs the
        # solution to well within the 1% convergence tolerance, and the
        # unrefined f32 block solve already sits at ~1e-4 relative
        Xi = step(XiLast, 0)
        # NaN quarantine: a non-finite iterate freezes this lane at its
        # last finite state (XiLast stays finite by construction) and
        # raises the flag, instead of propagating through the batch
        finite = jnp.all(jnp.isfinite(Xi))
        tolCheck = jnp.abs(Xi - XiLast) / (jnp.abs(Xi) + tol)
        conv = jnp.all(tolCheck < tol)                 # NaN compares False
        XiNext = jnp.where(conv | ~finite, XiLast,
                           w_old * XiLast + relax * Xi)
        # XiPoint records the linearization point of the last solve, so the
        # refined re-solve below reproduces exactly that solve
        return (i + 1, XiNext, XiLast,
                jnp.where(finite, Xi, Xi_prev),        # last finite iterate
                conv | ~finite, froze | ~finite)

    init = (jnp.array(0), XiLast, XiLast, Xi0,
            jnp.array(False), jnp.array(False))

    def finalize(state):
        i, _, XiPoint, Xi, done, froze = state
        converged = done & ~froze
        # one re-solve at the final drag-linearization point recovers the
        # full f32+refinement accuracy for the returned amplitudes without
        # paying the refinement inside every fixed-point iteration — now
        # through the conditioned-solve recovery ladder, which also yields
        # the per-case residual / condition-estimate / recovery-tier
        # health record
        Zr, Zi, F = assemble(XiPoint)
        xr_c, xi_c, resid, cond_est, tier = solve_complex_6x6_ladder(
            Zr, Zi, jnp.real(F), jnp.imag(F), refine=refine
        )
        if mp:
            # automatic fall-back-to-full-precision: any frequency lane
            # the ladder escalated past baseline, or whose condition
            # estimate exceeds the f32 ladder threshold, takes the answer
            # from a full-precision shadow assembly+ladder at the same
            # linearization point (one extra assembly — the fixed point
            # already amortized the mixed-precision speedup)
            Zr_f, Zi_f, F_f = assemble(XiPoint, full_precision=True)
            xr_f, xi_f, resid_f, cond_f, tier_f = solve_complex_6x6_ladder(
                Zr_f, Zi_f, jnp.real(F_f), jnp.imag(F_f), refine=refine
            )
            eps32 = float(np.finfo(np.float32).eps)
            degraded = (tier != TIER_BASELINE) | (cond_est > 0.02 / eps32)
            xr_c = jnp.where(degraded[..., None], xr_f, xr_c)
            xi_c = jnp.where(degraded[..., None], xi_f, xi_c)
            resid = jnp.where(degraded, resid_f, resid)
            cond_est = jnp.where(degraded, cond_f, cond_est)
            tier = jnp.where(degraded, tier_f, tier)
        Xi_cand = (xr_c + 1j * xi_c).T                         # [6, nw]
        cand_ok = jnp.all(jnp.isfinite(Xi_cand))
        # if even the ladder's last tier is non-finite (e.g. NaN node
        # inputs), fall back to the loop's last finite iterate (zeros if
        # none existed)
        Xi_out = jnp.where(cand_ok, Xi_cand, Xi)
        report = SolveReport(
            converged=converged,
            iters=i,
            nonfinite=froze | ~cand_ok,
            recovery_tier=jnp.max(tier),
            residual=jnp.max(resid),
            cond=jnp.max(cond_est),
        )
        return jnp.real(Xi_out), jnp.imag(Xi_out), report

    return FixedPointPhases(init, cond, body, finalize)
