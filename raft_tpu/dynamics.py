"""Batched frequency-domain response solve.

The reference's hot path (reference raft/raft_model.py:524-656 solveDynamics:
fixed-point drag-linearization loop around per-frequency 6x6 complex solves,
HOT LOOP #3) expressed as one XLA graph:

 - the per-frequency impedance assembly and solve are batched over the whole
   frequency axis (and, via vmap in the Model, over load cases);
 - the complex 6x6 solves are performed as real 12x12 block solves
   [[Zr, -Zi], [Zi, Zr]] — the TPU backend has no complex LU, and the block
   form runs in f32 on the MXU with an optional iterative-refinement step to
   recover accuracy;
 - the under-relaxed fixed point reproduces the reference's semantics
   exactly (start amplitudes XiStart, relaxation 0.2*old + 0.8*new,
   tolerance check |Xi - XiLast|/(|Xi|+tol) < tol, warn-and-continue on
   non-convergence) via a while_loop whose state freezes once converged —
   matching the reference's mid-loop `break` without data-dependent Python
   control flow.
"""

import jax
import jax.numpy as jnp

from raft_tpu.hydro import linearized_drag


def solve_complex_6x6(Zr, Zi, Fr, Fi, refine=1):
    """Solve (Zr + i Zi) x = (Fr + i Fi) batched over leading axes via the
    equivalent real block system.

    Zr, Zi : [..., 6, 6];  Fr, Fi : [..., 6]
    Returns (xr, xi) : [..., 6] each.
    refine : iterative-refinement steps (cheap; recovers ~2 digits in f32).
    """
    top = jnp.concatenate([Zr, -Zi], axis=-1)
    bot = jnp.concatenate([Zi, Zr], axis=-1)
    A = jnp.concatenate([top, bot], axis=-2)            # [..., 12, 12]
    b = jnp.concatenate([Fr, Fi], axis=-1)[..., None]   # [..., 12, 1]
    x = jnp.linalg.solve(A, b)
    for _ in range(refine):
        r = b - A @ x
        x = x + jnp.linalg.solve(A, r)
    x = x[..., 0]
    return x[..., :6], x[..., 6:]


def assemble_impedance(w, M, B, C):
    """Z(w) = -w^2 M + i w B + C as (real, imag) parts.

    w : [nw]; M, B : [nw, 6, 6]; C : [6, 6] or [nw, 6, 6]
    """
    w2 = (w * w)[:, None, None]
    Zr = -w2 * M + C
    Zi = w[:, None, None] * B
    return Zr, Zi


def solve_dynamics(
    nodes,
    u,
    w,
    dw,
    rho,
    M_lin,
    B_lin,
    C_lin,
    F_lin_r,
    F_lin_i,
    XiStart,
    nIter=15,
    tol=0.01,
    refine=1,
):
    """Fixed-point dynamics solve for one case (vmap over cases in the Model).

    Parameters
    ----------
    nodes : HydroNodes (jnp arrays, working dtype)
    u     : [N, 3, nw] complex wave velocity at nodes
    M_lin, B_lin : [nw, 6, 6] frequency-dependent mass/damping (struct + BEM
        + morison + aero already summed; reference raft_model.py:552-555)
    C_lin : [6, 6] total stiffness
    F_lin_r/i : [nw, 6] linear excitation force (real/imag parts)
    XiStart : initial amplitude guess (reference raft_model.py:50, :535)

    Returns (Xi_r, Xi_i) : [nw, 6] response amplitudes, plus iteration count
    and final convergence flag.
    """
    nw = w.shape[0]
    cdtype = u.dtype
    XiLast = jnp.full((6, nw), XiStart, dtype=cdtype)
    Xi0 = jnp.zeros((6, nw), dtype=cdtype)

    def step(XiLast):
        B_drag, F_drag = linearized_drag(nodes, XiLast, u, w, dw, rho)
        B_tot = B_lin + B_drag[None, :, :]
        Zr, Zi = assemble_impedance(w, M_lin, B_tot, C_lin)
        F = F_drag + (F_lin_r + 1j * F_lin_i).astype(cdtype)  # [nw, 6]
        xr, xi = solve_complex_6x6(Zr, Zi, jnp.real(F), jnp.imag(F), refine=refine)
        return (xr + 1j * xi).T                                # [6, nw]

    def cond(state):
        i, XiLast, Xi, done = state
        return (i < nIter + 1) & (~done)

    def body(state):
        i, XiLast, Xi_prev, done = state
        Xi = step(XiLast)
        tolCheck = jnp.abs(Xi - XiLast) / (jnp.abs(Xi) + tol)
        conv = jnp.all(tolCheck < tol)
        XiNext = jnp.where(conv, XiLast, 0.2 * XiLast + 0.8 * Xi)
        return (i + 1, XiNext, Xi, conv)

    i, _, Xi, converged = jax.lax.while_loop(
        cond, body, (jnp.array(0), XiLast, Xi0, jnp.array(False))
    )
    return jnp.real(Xi), jnp.imag(Xi), i, converged
