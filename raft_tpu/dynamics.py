"""Batched frequency-domain response solve.

The reference's hot path (reference raft/raft_model.py:524-656 solveDynamics:
fixed-point drag-linearization loop around per-frequency 6x6 complex solves,
HOT LOOP #3) expressed as one XLA graph:

 - the per-frequency impedance assembly and solve are batched over the whole
   frequency axis (and, via vmap in the Model, over load cases);
 - the complex 6x6 solves are performed as real 12x12 block solves
   [[Zr, -Zi], [Zi, Zr]] — the TPU backend has no complex LU, and the block
   form runs in f32 on the MXU with an optional iterative-refinement step to
   recover accuracy;
 - the under-relaxed fixed point reproduces the reference's semantics
   exactly (start amplitudes XiStart, relaxation 0.2*old + 0.8*new,
   tolerance check |Xi - XiLast|/(|Xi|+tol) < tol, warn-and-continue on
   non-convergence) via a while_loop whose state freezes once converged —
   matching the reference's mid-loop `break` without data-dependent Python
   control flow.
"""

import jax
import jax.numpy as jnp

from raft_tpu.hydro import linearized_drag


def gauss_solve(A, b):
    """Batched dense solve by Gauss-Jordan elimination with partial
    pivoting, fully vectorized over the leading batch axes.

    A : [..., n, n];  b : [..., n, 1] -> x : [..., n, 1]

    XLA's batched LU (`jnp.linalg.solve`) runs ~13x slower than this on TPU
    for the tiny 12x12 systems in the RAO solve (measured 4.98 ms vs
    0.39 ms for 1536 systems on v5e): LU lowers to a column-by-column loop
    with dynamic-slice updates, while this formulation is n fori_loop steps
    of pure elementwise/where ops over the whole batch.  Pivot selection
    uses one argmax + gather per step; row swap and elimination are masked
    `where`s, so the graph has static shapes throughout.
    """
    n = A.shape[-1]
    M = jnp.concatenate([A, b], axis=-1)                # [..., n, n+1]
    idx = jnp.arange(n)

    def step(i, M):
        col = jnp.abs(jnp.take(M, i, axis=-1))          # column i magnitudes
        col = jnp.where(idx < i, -jnp.inf, col)         # rows above i are done
        p = jnp.argmax(col, axis=-1)                    # pivot row per batch
        rp = jnp.take_along_axis(M, p[..., None, None], axis=-2)[..., 0, :]
        ri = jnp.take(M, i, axis=-2)
        is_i = (idx == i)[:, None]
        is_p = (idx == p[..., None])[..., :, None]
        M = jnp.where(is_i, rp[..., None, :],
                      jnp.where(is_p, ri[..., None, :], M))
        piv = jnp.take(rp, i, axis=-1)[..., None]
        row = rp / piv                                  # normalized pivot row
        fac = jnp.take(M, i, axis=-1)[..., None]        # column i after swap
        M = jnp.where(is_i, row[..., None, :], M - fac * row[..., None, :])
        return M

    M = jax.lax.fori_loop(0, n, step, M)
    return M[..., -1:]


def solve_complex_6x6(Zr, Zi, Fr, Fi, refine=1):
    """Solve (Zr + i Zi) x = (Fr + i Fi) batched over leading axes via the
    equivalent real block system.

    Zr, Zi : [..., 6, 6];  Fr, Fi : [..., 6]
    Returns (xr, xi) : [..., 6] each.
    refine : iterative-refinement steps (cheap; recovers ~2 digits in f32).
    """
    top = jnp.concatenate([Zr, -Zi], axis=-1)
    bot = jnp.concatenate([Zi, Zr], axis=-1)
    A = jnp.concatenate([top, bot], axis=-2)            # [..., 12, 12]
    b = jnp.concatenate([Fr, Fi], axis=-1)[..., None]   # [..., 12, 1]
    x = gauss_solve(A, b)
    for _ in range(refine):
        r = b - A @ x
        x = x + gauss_solve(A, r)
    x = x[..., 0]
    return x[..., :6], x[..., 6:]


def assemble_impedance(w, M, B, C):
    """Z(w) = -w^2 M + i w B + C as (real, imag) parts.

    w : [nw]; M, B : [nw, 6, 6]; C : [6, 6] or [nw, 6, 6]
    """
    w2 = (w * w)[:, None, None]
    Zr = -w2 * M + C
    Zi = w[:, None, None] * B
    return Zr, Zi


def solve_dynamics(
    nodes,
    u,
    w,
    dw,
    rho,
    M_lin,
    B_lin,
    C_lin,
    F_lin_r,
    F_lin_i,
    XiStart,
    nIter=15,
    tol=0.01,
    refine=1,
    checkable=False,
):
    """Fixed-point dynamics solve for one case (vmap over cases in the Model).

    Parameters
    ----------
    nodes : HydroNodes (jnp arrays, working dtype)
    u     : [N, 3, nw] complex wave velocity at nodes
    M_lin, B_lin : [nw, 6, 6] frequency-dependent mass/damping (struct + BEM
        + morison + aero already summed; reference raft_model.py:552-555)
    C_lin : [6, 6] total stiffness
    F_lin_r/i : [nw, 6] linear excitation force (real/imag parts)
    XiStart : initial amplitude guess (reference raft_model.py:50, :535)

    Returns (Xi_r, Xi_i) : [nw, 6] response amplitudes, plus iteration count
    and final convergence flag.
    """
    nw = w.shape[0]
    cdtype = u.dtype
    XiLast = jnp.full((6, nw), XiStart, dtype=cdtype)
    Xi0 = jnp.zeros((6, nw), dtype=cdtype)

    def step(XiLast, n_refine):
        B_drag, F_drag = linearized_drag(nodes, XiLast, u, w, dw, rho)
        B_tot = B_lin + B_drag[None, :, :]
        Zr, Zi = assemble_impedance(w, M_lin, B_tot, C_lin)
        F = F_drag + (F_lin_r + 1j * F_lin_i).astype(cdtype)  # [nw, 6]
        xr, xi = solve_complex_6x6(
            Zr, Zi, jnp.real(F), jnp.imag(F), refine=n_refine
        )
        return (xr + 1j * xi).T                                # [6, nw]

    def cond(state):
        i, XiLast, XiPoint, Xi, done = state
        return (i < nIter + 1) & (~done)

    def body(state):
        i, XiLast, XiPoint, Xi_prev, done = state
        # no refinement inside the loop: the fixed point only needs the
        # solution to well within the 1% convergence tolerance, and the
        # unrefined f32 block solve already sits at ~1e-4 relative
        Xi = step(XiLast, 0)
        tolCheck = jnp.abs(Xi - XiLast) / (jnp.abs(Xi) + tol)
        conv = jnp.all(tolCheck < tol)
        XiNext = jnp.where(conv, XiLast, 0.2 * XiLast + 0.8 * Xi)
        # XiPoint records the linearization point of the last solve, so the
        # refined re-solve below reproduces exactly that solve
        return (i + 1, XiNext, XiLast, Xi, conv)

    init = (jnp.array(0), XiLast, XiLast, Xi0, jnp.array(False))
    if checkable:
        # scan-based fixed-trip-count variant with the same freeze
        # semantics: jax.experimental.checkify supports scan but not this
        # while_loop, so the NaN-checking debug pipeline
        # (raft_tpu.validate.checked_pipeline) requests this path
        def scan_body(state, _):
            state = jax.lax.cond(cond(state), body, lambda s: s, state)
            return state, None
        state, _ = jax.lax.scan(scan_body, init, None, length=nIter + 1)
        i, _, XiPoint, Xi, converged = state
    else:
        i, _, XiPoint, Xi, converged = jax.lax.while_loop(cond, body, init)
    # one refined re-solve at the final drag-linearization point recovers
    # the full f32+refinement accuracy for the returned amplitudes without
    # paying the refinement inside every fixed-point iteration
    if refine > 0:
        Xi = step(XiPoint, refine)
    return jnp.real(Xi), jnp.imag(Xi), i, converged
