"""Visualization: 3-D system geometry and response-spectrum plots.

Re-provides the reference's plotting surface (reference
raft/raft_model.py:730-765 plotResponses, :792-823 plot;
raft/raft_member.py:801-873 member wireframes; mooring-line profiles drawn
by MoorPy's ms.plot) on top of matplotlib.  All functions are host-side and
optional — nothing in the numeric path imports this module.
"""

import numpy as np


def _require_mpl():
    import os

    import matplotlib

    # only force the headless backend when there is no display to attach to
    # (leave interactive sessions on whatever backend the user has)
    if not os.environ.get("DISPLAY") and not os.environ.get("MPLBACKEND"):
        matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt  # noqa: F401

    return plt


# ------------------------------------------------------------------ members

def member_wireframe(mem, n_az=12):
    """Line segments ([n, 2, 3] arrays) tracing one member: longitudinal
    edges at n_az azimuths plus a ring/rectangle at each station
    (the reference draws the same station-ring + edge wireframe,
    raft_member.py:801-873)."""
    lines = []
    stations = np.asarray(mem.stations, float)
    if mem.circular:
        radii = 0.5 * np.asarray(mem.d, float)
        az = np.linspace(0, 2 * np.pi, n_az, endpoint=False)
        # longitudinal edges
        for a in az[:: max(1, n_az // 6)]:
            pts = [
                mem.rA + mem.q * s
                + r * (np.cos(a) * mem.p1 + np.sin(a) * mem.p2)
                for s, r in zip(stations, radii)
            ]
            lines.extend(
                np.stack([p0, p1]) for p0, p1 in zip(pts[:-1], pts[1:])
            )
        # station rings
        ring_az = np.linspace(0, 2 * np.pi, 24)
        for s, r in zip(stations, radii):
            ring = np.stack(
                [
                    mem.rA + mem.q * s
                    + r * (np.cos(a) * mem.p1 + np.sin(a) * mem.p2)
                    for a in ring_az
                ]
            )
            lines.extend(
                np.stack([p0, p1]) for p0, p1 in zip(ring[:-1], ring[1:])
            )
    else:
        sl = np.asarray(mem.sl, float)  # [n, 2]
        corners = np.array([[1, 1], [1, -1], [-1, -1], [-1, 1]]) * 0.5
        ringpts = []
        for s, (s1, s2) in zip(stations, sl):
            ring = np.stack(
                [
                    mem.rA + mem.q * s + c1 * s1 * mem.p1 + c2 * s2 * mem.p2
                    for c1, c2 in corners
                ]
            )
            ringpts.append(ring)
            closed = np.vstack([ring, ring[:1]])
            lines.extend(
                np.stack([p0, p1]) for p0, p1 in zip(closed[:-1], closed[1:])
            )
        for r0, r1 in zip(ringpts[:-1], ringpts[1:]):
            lines.extend(np.stack([p0, p1]) for p0, p1 in zip(r0, r1))
    return lines


# ------------------------------------------------------------- mooring lines

def line_profile(anchor, fairlead, HF, VF, L, EA, w, n=40, touchdown=True):
    """Sampled 3-D shape of one catenary mooring line from the converged
    fairlead tension components (the same elastic-catenary branches as
    mooring._profile, evaluated at n arc-length stations from the anchor).

    touchdown=False forces the suspended expressions even for VA < 0 —
    an upper segment of a composite line sagging below its junction,
    which must not be drawn as seabed contact."""
    anchor = np.asarray(anchor, float)
    fairlead = np.asarray(fairlead, float)
    dxy = fairlead[:2] - anchor[:2]
    XF = max(float(np.hypot(*dxy)), 1e-9)
    u = dxy / XF
    s = np.linspace(0.0, L, n)
    VA = VF - w * L
    if HF <= 0.0 and touchdown:
        # fully-slack closed form (catenary_solve's H = 0 regime): the
        # line runs along the seabed then hangs vertically below the
        # fairlead — the catenary expressions divide by HF
        ZF = fairlead[2] - anchor[2]
        LB = max(L - max(ZF, 0.0), 0.0)
        x = np.minimum(s, LB) / max(LB, 1e-9) * XF
        z = np.maximum(s - LB, 0.0)
        pts = np.zeros((n, 3))
        pts[:, 0] = anchor[0] + u[0] * x
        pts[:, 1] = anchor[1] + u[1] * x
        pts[:, 2] = anchor[2] + z
        return pts
    if VA >= 0 or not touchdown:  # suspended (incl. sagging segments)
        Vs = VA + w * s
        x = HF / w * (np.arcsinh(Vs / HF) - np.arcsinh(VA / HF)) + HF * s / EA
        z = (
            HF / w * (np.sqrt(1 + (Vs / HF) ** 2) - np.sqrt(1 + (VA / HF) ** 2))
            + (VA * s + 0.5 * w * s**2) / EA
        )
    else:  # touchdown: seabed segment of length LB, then catenary
        LB = np.clip(L - VF / w, 0.0, L)
        sp = np.maximum(s - LB, 0.0)
        x = np.where(
            s <= LB,
            s + HF * s / EA,
            LB + HF / w * np.arcsinh(w * sp / HF) + HF * s / EA,
        )
        z = np.where(
            s <= LB,
            0.0,
            HF / w * (np.sqrt(1 + (w * sp / HF) ** 2) - 1.0)
            + w * sp**2 / (2 * EA),
        )
    pts = np.zeros((n, 3))
    pts[:, 0] = anchor[0] + u[0] * x
    pts[:, 1] = anchor[1] + u[1] * x
    pts[:, 2] = anchor[2] + z
    return pts


def composite_line_profile(anchor, fairlead, HF, VF, L, EA, w, Wp=None,
                           n=40):
    """Sampled 3-D shape of a composite (multi-segment) line: per-segment
    catenary profiles stacked anchor->fairlead, each drawn with its own
    top tension (mooring._segment_top_tensions)."""
    from raft_tpu.mooring_numpy import segment_top_tensions_np

    L = np.atleast_1d(np.asarray(L, float))
    EA = np.atleast_1d(np.asarray(EA, float))
    w = np.atleast_1d(np.asarray(w, float))
    Wp = np.zeros_like(L) if Wp is None else np.atleast_1d(np.asarray(Wp))
    Vtop = segment_top_tensions_np(VF, L, w, Wp)
    start = np.asarray(anchor, float)
    out = []
    for k in range(len(L)):
        if L[k] == 0.0:
            continue
        pts = line_profile(start, fairlead, HF, float(Vtop[k]),
                           float(L[k]), float(EA[k]), float(w[k]), n=n,
                           touchdown=(k == 0))
        out.append(pts)
        start = pts[-1]
    return np.concatenate(out) if out else np.asarray([anchor, fairlead])


# --------------------------------------------------------------------- rotor

def rotor_wireframe(rotor, hub_pos, azimuth0=0.0):
    """Blade outline segments for the rotor at ``hub_pos``
    (the reference draws blade surfaces at raft_rotor.py:492-548; here each
    blade is its pitch axis plus leading/trailing edge chord outline)."""
    g = rotor.geom
    r = np.asarray(g["r"], float)
    chord = np.asarray(g["chord"], float)
    precurve = np.asarray(g["precurve"], float)
    presweep = np.asarray(g["presweep"], float)
    cone, tilt = g["precone"], g["tilt"]
    lines = []
    for ib in range(g["B"]):
        az = azimuth0 + 2 * np.pi * ib / g["B"]
        # blade-frame coordinates: x downwind (precurve), z spanwise
        xb = precurve * np.cos(cone) - r * np.sin(cone)
        zb = r * np.cos(cone) + precurve * np.sin(cone)
        yb = presweep
        for off in (-0.25, 0.75):  # leading/trailing edge at quarter chord
            ye = yb + off * chord
            # rotate about the shaft (x) axis by azimuth, then tilt about y
            Y = ye * np.cos(az) - zb * np.sin(az)
            Z = ye * np.sin(az) + zb * np.cos(az)
            X = xb * np.cos(tilt) + Z * np.sin(tilt)
            Zt = -xb * np.sin(tilt) + Z * np.cos(tilt)
            pts = np.stack(
                [hub_pos[0] + X, hub_pos[1] + Y, hub_pos[2] + Zt], axis=1
            )
            lines.extend(
                np.stack([p0, p1]) for p0, p1 in zip(pts[:-1], pts[1:])
            )
    return lines


# ------------------------------------------------------------------- figures

def plot_model(model, ax=None, color="k", nodes=False, station_plot=None):
    """3-D wireframe of platform + tower members and mooring lines
    (reference raft/raft_model.py:792-823)."""
    plt = _require_mpl()
    from mpl_toolkits.mplot3d.art3d import Line3DCollection

    if ax is None:
        fig = plt.figure(figsize=(8, 8))
        ax = fig.add_subplot(projection="3d")
    else:
        fig = ax.get_figure()

    segs = []
    for mem in model.members:
        segs.extend(member_wireframe(mem))
    if getattr(model, "rotor", None) is not None:
        hub = np.array([-model.rotor.overhang, 0.0, model.hHub])
        segs.extend(rotor_wireframe(model.rotor, hub))
    ax.add_collection3d(
        Line3DCollection(segs, colors=color, linewidths=0.5, alpha=0.8)
    )
    if nodes:
        r = model.nodes.r
        ax.scatter(r[:, 0], r[:, 1], r[:, 2], s=4, c="r")

    # mooring lines at the unloaded mean position
    import jax.numpy as jnp

    from raft_tpu.mooring import line_forces

    arr = model._moor_arrays
    r6 = getattr(model, "Xi0_unloaded", np.zeros(6))
    _, HF, VF = line_forces(jnp.asarray(r6, jnp.float64), *arr)
    ms = model.ms
    for i in range(ms.n_lines):
        fair = np.asarray(ms.rFair[i]) + np.asarray(r6[:3])
        pts = composite_line_profile(
            ms.anchors[i], fair, float(HF[i]), float(VF[i]),
            ms.L[i], ms.EA[i], ms.w[i], ms.Wp[i],
        )
        ax.plot(pts[:, 0], pts[:, 1], pts[:, 2], color="b", lw=1.0)

    # bridle groups: draw straight chords junction-terminal per leg
    if ms.bridles is not None:
        for ib in range(ms.bridles.n):
            p0 = np.asarray(ms.bridles.p0[ib])
            for ik in range(ms.bridles.kind.shape[1]):
                kd = ms.bridles.kind[ib, ik]
                if kd < 0:
                    continue
                end = np.asarray(ms.bridles.ends[ib, ik], float)
                if kd == 1:
                    end = end + np.asarray(r6[:3])
                seg = np.stack([p0, end])
                ax.plot(seg[:, 0], seg[:, 1], seg[:, 2], color="b",
                        lw=1.0, ls="--")

    # free surface
    ext = [20.0]
    if ms.n_lines:
        ext.append(float(np.abs(ms.anchors[:, :2]).max()))
    if ms.bridles is not None:
        ext.append(float(np.abs(ms.bridles.ends[..., :2]).max()))
    lim = max(ext)
    xs = np.linspace(-lim, lim, 2)
    X, Y = np.meshgrid(xs, xs)
    ax.plot_surface(X, Y, 0 * X, alpha=0.1, color="c")

    ax.set_xlabel("x (m)")
    ax.set_ylabel("y (m)")
    ax.set_zlabel("z (m)")
    zs = []
    if ms.n_lines:
        zs.append(float(ms.anchors[:, 2].min()))
    if ms.bridles is not None:
        zs.append(float(ms.bridles.ends[..., 2].min()))
    zmin = min(zs) if zs else -1.0
    ax.set_zlim(min(zmin, -1.0), max(float(model.hHub) + 10.0, 10.0))
    return fig, ax


_PSD_CHANNELS = [
    ("wave_PSD", "wave elevation (m²/(rad/s))"),
    ("surge_PSD", "surge (m²/(rad/s))"),
    ("heave_PSD", "heave (m²/(rad/s))"),
    ("pitch_PSD", "pitch (deg²/(rad/s))"),
    ("AxRNA_PSD", "nacelle accel. ((m/s²)²/(rad/s))"),
    ("Mbase_PSD", "tower base moment ((Nm)²/(rad/s))"),
]


def plot_responses(model, channels=None):
    """Response power-spectral-density subplot grid, one line per case
    (reference raft/raft_model.py:730-765)."""
    plt = _require_mpl()
    metrics = model.results.get("case_metrics")
    if metrics is None:
        raise RuntimeError("run analyze_cases() before plot_responses()")
    channels = channels or _PSD_CHANNELS
    freqs = model.w / (2 * np.pi)

    fig, axes = plt.subplots(
        len(channels), 1, sharex=True, figsize=(8, 2.2 * len(channels))
    )
    axes = np.atleast_1d(axes)
    ncase = metrics[channels[0][0]].shape[0]
    for ax, (key, label) in zip(axes, channels):
        for i in range(ncase):
            ax.plot(freqs, metrics[key][i], label=f"case {i+1}")
        ax.set_ylabel(label, fontsize=8)
        ax.grid(alpha=0.3)
    axes[0].legend(fontsize=8)
    axes[-1].set_xlabel("frequency (Hz)")
    fig.tight_layout()
    return fig, axes


def plot_sweep_contours(results, axes_dict, keys, case_index=0):
    """Contour-plot matrix over a 2-D design sweep — the reference's
    parametersweep figure style (reference raft/parametersweep.py:122-561
    draws 4x4 matrices of contour plots over pairs of design variables).

    results : dict from sweep.run_sweep (flat leading design axis)
    axes_dict : {param_name: values} with exactly two parameters (the grid
        the points were built from, as passed to sweep.grid_points)
    keys : list of scalar result keys to draw, one contour panel each
        (extra trailing axes, e.g. a case axis, are selected with
        ``case_index``)

    Returns (fig, axes array).
    """
    from raft_tpu.sweep import results_to_grid

    plt = _require_mpl()
    if len(axes_dict) != 2:
        raise ValueError(
            f"plot_sweep_contours needs exactly two swept parameters, "
            f"got {list(axes_dict)}"
        )
    (nx_name, xs), (ny_name, ys) = axes_dict.items()
    n = len(keys)
    ncols = int(np.ceil(np.sqrt(n)))
    nrows = int(np.ceil(n / ncols))
    fig, axs = plt.subplots(
        nrows, ncols, figsize=(4.2 * ncols, 3.4 * nrows), squeeze=False
    )
    X, Y = np.meshgrid(xs, ys, indexing="ij")
    for k, key in enumerate(keys):
        ax = axs[k // ncols][k % ncols]
        Z = np.asarray(results_to_grid(results, axes_dict, key))
        if Z.ndim > 2:
            # select case_index on the LAST extra axis (the case axis by
            # results layout), index 0 on any others; out-of-range raises
            # rather than silently plotting a different slice
            if case_index >= Z.shape[-1]:
                raise IndexError(
                    f"case_index {case_index} out of range for '{key}' "
                    f"(last axis has {Z.shape[-1]} entries)"
                )
            Z = Z[..., case_index]
            while Z.ndim > 2:
                Z = Z[..., 0]
        cs = ax.contourf(X, Y, Z, levels=12)
        fig.colorbar(cs, ax=ax, shrink=0.9)
        ax.set_title(key, fontsize=9)
        ax.set_xlabel(nx_name, fontsize=8)
        ax.set_ylabel(ny_name, fontsize=8)
    for k in range(n, nrows * ncols):
        axs[k // ncols][k % ncols].axis("off")
    fig.tight_layout()
    return fig, axs
