"""HAMS interop: the input-file tree the Fortran HAMS BEM solver consumes.

The reference shells out to HAMS through pyHAMS (reference
raft/raft_fowt.py:363-391: create_hams_dirs, write_hydrostatic_file,
write_control_file, run_hams), and its ``preprocess_HAMS`` path exists to
produce WAMIT-format `.1`/`.3`/`.hst` files for OpenFAST.  Here the same
file surface is generated natively so that

 - an external HAMS/WAMIT run can still be used as the hydrodynamics source
   (drop-in directory layout, then ``Model.import_bem`` on its output), and
 - ``Model.preprocess_hams`` produces the OpenFAST-handoff files from the
   in-package panel solver with no Fortran dependency.

Formats follow the published HAMS v3 input conventions (ControlFile.in,
Hydrostatic.in, Input/HullMesh.pnl).
"""

import os

import numpy as np


def create_hams_dirs(mesh_dir):
    """Create the HAMS working tree (Input/, Output/{Wamit,Hams}_format)."""
    for sub in ("Input", os.path.join("Output", "Wamit_format"),
                os.path.join("Output", "Hams_format")):
        os.makedirs(os.path.join(mesh_dir, sub), exist_ok=True)
    return mesh_dir


def _mat6(f, M):
    for row in np.asarray(M, float):
        f.write("   " + "  ".join(f"{v: .6E}" for v in row) + "\n")


def write_hydrostatic_file(mesh_dir, k_hydro=None, center=(0.0, 0.0, 0.0),
                           mass=None, damping_lin=None, damping_quad=None,
                           k_ext=None):
    """Write Hydrostatic.in: body center + the stacked 6x6 matrices HAMS
    expects (only the restoring matrix matters for the .1/.3 path; the rest
    default to zero, matching the reference's usage where the file is
    'unused for .1 and .3' — raft/raft_fowt.py:371-373)."""
    z6 = np.zeros((6, 6))
    path = os.path.join(mesh_dir, "Hydrostatic.in")
    with open(path, "w") as f:
        f.write(" Center of Gravity:\n")
        f.write("   " + "  ".join(f"{v: .6E}" for v in center) + "\n")
        f.write(" Body Mass Matrix:\n")
        _mat6(f, mass if mass is not None else z6)
        f.write(" External Linear Damping Matrix:\n")
        _mat6(f, damping_lin if damping_lin is not None else z6)
        f.write(" External Quadratic Damping Matrix:\n")
        _mat6(f, damping_quad if damping_quad is not None else z6)
        f.write(" Hydrostatic Restoring Matrix:\n")
        _mat6(f, k_hydro if k_hydro is not None else z6)
        f.write(" External Restoring Matrix:\n")
        _mat6(f, k_ext if k_ext is not None else z6)
    return path


def write_control_file(mesh_dir, water_depth=50.0, inc_f_lim=1, i_f_type=3,
                       o_f_type=4, num_freqs=-100, min_freq=0.01,
                       d_freq=0.01, num_headings=1, min_heading=0.0,
                       d_heading=0.0, ref_center=(0.0, 0.0, 0.0),
                       n_threads=4, note=None):
    """Write ControlFile.in (frequency/heading schedule; negative
    Number_of_frequencies means an evenly spaced grid, HAMS convention —
    the reference passes numFreqs=-nw, raft/raft_fowt.py:381-382).

    ``note``, when given, is appended after the end-of-file marker (so
    the fixed line layout an external HAMS parser expects is untouched) —
    used to flag when the emitted Buoy.1/.3 deviate from this schedule
    (e.g. mesh-resolution frequency clamping)."""
    path = os.path.join(mesh_dir, "ControlFile.in")
    with open(path, "w") as f:
        f.write("   --------------HAMS Control file---------------\n\n")
        f.write(f"   Waterdepth  {float(water_depth):.4f}\n\n")
        f.write("   #Start Definition of Wave Frequencies\n")
        f.write(f"    0_inf_frequency_limits  {inc_f_lim}\n")
        f.write(f"    Input_frequency_type    {i_f_type}\n")
        f.write(f"    Output_frequency_type   {o_f_type}\n")
        f.write(f"    Number_of_frequencies  {num_freqs}\n")
        f.write(f"    Minimum_frequency_Wmin  {min_freq:.6f}\n")
        f.write(f"    Frequency_step          {d_freq:.6f}\n")
        f.write("   #End Definition of Wave Frequencies\n\n")
        f.write("   #Start Definition of Wave Headings\n")
        f.write(f"    Number_of_headings      {num_headings}\n")
        f.write(f"    Minimum_heading         {min_heading:.4f}\n")
        f.write(f"    Heading_step            {d_heading:.4f}\n")
        f.write("   #End Definition of Wave Headings\n\n")
        f.write("    Reference_body_center   "
                + "  ".join(f"{v:.4f}" for v in ref_center) + "\n")
        f.write("    Reference_body_length   1.0\n")
        f.write("    Wave-diffrac-solution   2\n")
        f.write("    If_remove_irr_freq      0\n")
        f.write(f"    Number of threads       {n_threads}\n\n")
        f.write("    ----------End HAMS Control file---------------\n")
        if note:
            f.write(f"    NOTE: {note}\n")
    return path


def read_control_file(path):
    """Parse the frequency/heading schedule back out of a ControlFile.in
    (round-trip check + interop with externally prepared HAMS cases)."""
    out = {}
    key_map = {
        "Waterdepth": ("water_depth", float),
        "Number_of_frequencies": ("num_freqs", int),
        "Minimum_frequency_Wmin": ("min_freq", float),
        "Frequency_step": ("d_freq", float),
        "Number_of_headings": ("num_headings", int),
        "Minimum_heading": ("min_heading", float),
        "Heading_step": ("d_heading", float),
    }
    with open(path) as f:
        for ln in f:
            parts = ln.split()
            if len(parts) >= 2 and parts[0] in key_map:
                name, cast = key_map[parts[0]]
                out[name] = cast(float(parts[1]))
    return out
