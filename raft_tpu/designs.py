"""Built-in example designs, constructed programmatically.

These are self-contained design dictionaries in the same schema the YAML
loader produces (reference schema documented by
examples/VolturnUS-S_example.yaml; see SURVEY.md §2.1 row 11), so the
framework, its tests, the benchmark, and the driver entry points work even
without any external design files.

`deep_spar()` is a generic ballasted deep-draft spar (inspired by the public
OC3-Hywind configuration but with round-number parameters of our own
choosing); `demo_semi()` is a small three-column semisubmersible exercising
heading replication, rectangular pontoons, and multi-section ballast.
"""

import numpy as np


def _case_table(rows):
    keys = [
        "wind_speed", "wind_heading", "turbulence", "turbine_status",
        "yaw_misalign", "wave_spectrum", "wave_period", "wave_height",
        "wave_heading",
    ]
    return {"keys": keys, "data": [list(r) for r in rows]}


def deep_spar(n_cases=1, nw_settings=(0.02, 0.8)):
    """A moored deep-draft spar floating wind platform (no aero)."""
    min_freq, max_freq = nw_settings
    cases = _case_table(
        [
            [0.0, 0.0, "IB_NTM", "operating", 0.0, "JONSWAP", 9.0 + 0.5 * i,
             5.0 + 0.5 * i, 0.0]
            for i in range(n_cases)
        ]
    )
    return {
        "settings": {"min_freq": min_freq, "max_freq": max_freq,
                     "XiStart": 0.1, "nIter": 15},
        "site": {"water_depth": 300.0, "rho_water": 1025.0, "rho_air": 1.225,
                 "mu_air": 1.81e-5, "shearExp": 0.12},
        "cases": cases,
        "turbine": {
            "mRNA": 3.5e5, "IxRNA": 4.0e7, "IrRNA": 2.5e7,
            "xCG_RNA": -0.2, "hHub": 90.0, "Fthrust": 8.0e5,
            "aeroServoMod": 0,
            "tower": {
                "name": "tower", "type": 1,
                "rA": [0.0, 0.0, 10.0], "rB": [0.0, 0.0, 87.0],
                "shape": "circ", "gamma": 0.0,
                "stations": [10.0, 87.0],
                "d": [6.5, 3.9],
                "t": [0.030, 0.020],
                "Cd": 0.0, "Ca": 0.0, "CdEnd": 0.0, "CaEnd": 0.0,
                "rho_shell": 8500.0,
            },
        },
        "platform": {
            "potModMaster": 0,
            "dlsMax": 5.0,
            "members": [
                {
                    "name": "spar", "type": 2,
                    "rA": [0.0, 0.0, -120.0], "rB": [0.0, 0.0, 10.0],
                    "shape": "circ", "gamma": 0.0, "potMod": False,
                    "stations": [0.0, 108.0, 116.0, 130.0],
                    "d": [9.4, 9.4, 6.5, 6.5],
                    "t": [0.027, 0.027, 0.027, 0.027],
                    "l_fill": [52.0, 0.0, 0.0],
                    "rho_fill": [1800.0, 0.0, 0.0],
                    "Cd": 0.6, "Ca": 0.97, "CdEnd": 0.6, "CaEnd": 0.0,
                    "rho_shell": 7850.0,
                },
            ],
        },
        "mooring": {
            "water_depth": 300.0,
            "points": (
                [
                    {"name": f"anchor{i+1}", "type": "fixed",
                     "location": [850.0 * np.cos(th), 850.0 * np.sin(th), -300.0],
                     "anchor_type": "drag_embedment"}
                    for i, th in enumerate(np.deg2rad([60.0, 180.0, 300.0]))
                ]
                + [
                    {"name": f"fair{i+1}", "type": "vessel",
                     "location": [5.2 * np.cos(th), 5.2 * np.sin(th), -70.0]}
                    for i, th in enumerate(np.deg2rad([60.0, 180.0, 300.0]))
                ]
            ),
            "lines": [
                {"name": f"line{i+1}", "endA": f"anchor{i+1}",
                 "endB": f"fair{i+1}", "type": "chain", "length": 900.0}
                for i in range(3)
            ],
            "line_types": [
                {"name": "chain", "diameter": 0.09, "mass_density": 77.7,
                 "stiffness": 3.84e8, "breaking_load": 1e8, "cost": 100.0,
                 "transverse_added_mass": 1.0, "tangential_added_mass": 0.0,
                 "transverse_drag": 1.6, "tangential_drag": 0.1}
            ],
            "anchor_types": [
                {"name": "drag_embedment", "mass": 1e4, "cost": 1e4}
            ],
        },
    }


def demo_rotor_turbine(n_span=10, aeroServoMod=2):
    """A self-contained synthetic rotor configuration (blade geometry,
    smooth analytic airfoil polars, operating schedule, and ROSCO-style
    control gains) with every key :class:`raft_tpu.aero.Rotor` consumes —
    so rotor/aero-servo paths run in tests and benchmarks without the
    read-only reference mount.  The numbers are round inventions in the
    15-MW class, NOT the IEA-15MW: physics realism is not the point;
    exercising the BEM solve, its derivatives, and the control branch is.

    Returns a ready-to-use Rotor config dict (rho_air/mu_air/shearExp
    included); merge into a design's ``turbine`` dict to enable aero in a
    full Model (see :func:`demo_semi_aero`).
    """
    Rhub, Rtip = 2.5, 60.0
    r = np.linspace(Rhub + 1.5, Rtip - 0.8, n_span)
    mu = (r - Rhub) / (Rtip - Rhub)
    chord = 5.2 - 2.8 * mu
    twist_deg = 14.0 * (1.0 - mu) ** 1.5
    geometry = [
        [float(ri), float(ci), float(ti), 0.0, 0.0]
        for ri, ci, ti in zip(r, chord, twist_deg)
    ]

    # smooth analytic polars over the full +-180 deg range: thin-airfoil
    # behavior near zero AoA blending into a flat-plate-like deep stall —
    # single-root-friendly for the Ning residual at every station
    aoa = np.linspace(-180.0, 180.0, 73)
    a_rad = np.deg2rad(aoa)

    def polar(cl_scale, cd0):
        cl = cl_scale * np.sin(2.0 * a_rad) / 2.0 + 0.9 * np.sin(a_rad) \
            * np.cos(a_rad) ** 2
        cd = cd0 + 1.3 * np.sin(a_rad) ** 2
        cm = -0.08 * np.sin(a_rad)
        # +-180 deg consistency (build_airfoils enforces it anyway)
        cl[0] = cl[-1]
        cd[0] = cd[-1]
        cm[0] = cm[-1]
        return np.stack([aoa, cl, cd, cm], axis=1).tolist()

    airfoils = [
        {"name": "root_thick", "relative_thickness": 0.45,
         "data": polar(1.2, 0.030)},
        {"name": "tip_thin", "relative_thickness": 0.21,
         "data": polar(2.0, 0.012)},
    ]

    v = np.arange(3.0, 26.0, 1.0)
    rated = 10.5
    omega = np.where(v < rated, 7.5 * v / rated, 7.5)       # rpm
    pitch = np.where(v < rated, 0.0, 0.9 * (v - rated))     # deg

    return {
        "mRNA": 9.5e5, "IxRNA": 3.0e8, "IrRNA": 1.6e8, "xCG_RNA": -5.0,
        "hHub": 140.0, "Zhub": 140.0,
        "aeroServoMod": int(aeroServoMod),
        "nBlades": 3, "Rhub": Rhub,
        "precone": 3.0, "shaft_tilt": 5.0, "overhang": -11.0,
        "I_drivetrain": 2.8e8, "gear_ratio": 1.0,
        "blade": {
            "Rtip": Rtip,
            "geometry": geometry,
            "airfoils": [[0.0, "root_thick"], [0.35, "tip_thin"],
                         [1.0, "tip_thin"]],
        },
        "airfoils": airfoils,
        "wt_ops": {
            "v": v.tolist(),
            "omega_op": omega.tolist(),
            "pitch_op": pitch.tolist(),
        },
        "pitch_control": {
            "GS_Angles": np.deg2rad(np.linspace(1.0, 24.0, 8)).tolist(),
            "GS_Kp": np.linspace(-1.2, -0.3, 8).tolist(),
            "GS_Ki": np.linspace(-0.14, -0.04, 8).tolist(),
            "Fl_Kp": -9.0,
        },
        "torque_control": {"VS_KP": -3.8e7, "VS_KI": -4.6e6},
        "rho_air": 1.225, "mu_air": 1.81e-5, "shearExp": 0.12,
    }


def demo_semi_aero(n_cases=4, n_wind=2, nw_settings=(0.02, 0.6),
                   aeroServoMod=2):
    """:func:`demo_semi` with the synthetic rotor attached and the last
    ``n_wind`` cases given operating wind — the smallest design that runs
    the full aero-servo sweep path (zero-pitch first pass, guided
    mean-pitch second pass, hub a(w)/b(w) terms) without the reference
    mount."""
    d = demo_semi(n_cases=n_cases, nw_settings=nw_settings)
    turb = demo_rotor_turbine(aeroServoMod=aeroServoMod)
    hub = d["turbine"]["hHub"]
    turb["hHub"] = hub
    turb["Zhub"] = hub
    tower = d["turbine"]["tower"]
    d["turbine"] = dict(turb)
    d["turbine"]["tower"] = tower
    keys = d["cases"]["keys"]
    rows = [dict(zip(keys, row)) for row in d["cases"]["data"]]
    for j in range(max(0, n_cases - n_wind), n_cases):
        rows[j]["wind_speed"] = 8.0 + 2.0 * (j - (n_cases - n_wind))
    d["cases"]["data"] = [[row[k] for k in keys] for row in rows]
    return d


def demo_semi(n_cases=2, nw_settings=(0.02, 0.8)):
    """A three-column semisubmersible with a center column and rectangular
    pontoons, exercising heading replication and mixed member shapes."""
    d = deep_spar(n_cases=n_cases, nw_settings=nw_settings)
    r_col = 30.0
    d["platform"]["members"] = [
        {
            "name": "center", "type": 2,
            "rA": [0.0, 0.0, -20.0], "rB": [0.0, 0.0, 15.0],
            "shape": "circ", "gamma": 0.0, "potMod": False,
            "stations": [0.0, 35.0],
            "d": [10.0, 10.0], "t": [0.05, 0.05],
            "l_fill": 2.0, "rho_fill": 2500.0,
            "Cd": 0.6, "Ca": 0.97, "CdEnd": 0.6, "CaEnd": 0.6,
            "rho_shell": 7850.0,
        },
        {
            "name": "outer", "type": 2,
            "rA": [r_col, 0.0, -20.0], "rB": [r_col, 0.0, 15.0],
            "shape": "circ", "gamma": 0.0, "potMod": False,
            "heading": [60.0, 180.0, 300.0],
            "stations": [0.0, 35.0],
            "d": [12.5, 12.5], "t": [0.045, 0.045],
            "l_fill": 7.0, "rho_fill": 1025.0,
            "Cd": 0.6, "Ca": 0.97, "CdEnd": 0.6, "CaEnd": 0.6,
            "rho_shell": 7850.0,
        },
        {
            "name": "pontoon", "type": 2,
            "rA": [5.0, 0.0, -16.5], "rB": [r_col - 6.0, 0.0, -16.5],
            "shape": "rect", "gamma": 0.0, "potMod": False,
            "heading": [60.0, 180.0, 300.0],
            "stations": [0.0, 1.0],
            "d": [[12.4, 7.0], [12.4, 7.0]],
            "t": [0.04, 0.04],
            "l_fill": 19.0, "rho_fill": 1025.0,
            "Cd": [2.0, 1.0], "Ca": [1.0, 1.0], "CdEnd": 0.6, "CaEnd": 0.6,
            "rho_shell": 7850.0,
        },
    ]
    d["turbine"]["hHub"] = 110.0
    d["turbine"]["tower"]["rA"] = [0.0, 0.0, 15.0]
    d["turbine"]["tower"]["rB"] = [0.0, 0.0, 105.0]
    d["turbine"]["tower"]["stations"] = [15.0, 105.0]
    d["mooring"]["water_depth"] = 200.0
    d["site"]["water_depth"] = 200.0
    for p in d["mooring"]["points"]:
        if p["type"] == "fixed":
            p["location"][2] = -200.0
        else:
            p["location"][0] *= 8.0
            p["location"][1] *= 8.0
            p["location"][2] = -14.0
    return d
