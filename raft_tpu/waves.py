"""Linear (Airy) wave theory kernels: dispersion, kinematics, spectra.

Replaces the reference's per-frequency / per-node Python loops
(reference raft/helpers.py:85-154 getWaveKin/waveNumber, :397-443 JONSWAP)
with fully vectorized jnp ops over (node, frequency) so they fuse into the
case-dynamics XLA graph.
"""

import jax
import jax.numpy as jnp

_G = 9.81


def wave_number(w, h, g=_G, iters=30):
    """Wave number k solving the dispersion relation w^2 = g k tanh(k h).

    Newton iteration from the deep-water guess, fixed ``iters`` steps
    (converges to machine precision in < 10; reference raft/helpers.py:139-154
    stops at 0.1% relative which this strictly improves on).

    w : [...] rad/s (positive), h : scalar depth -> k : [...]
    """
    w = jnp.asarray(w, float)
    w2 = w * w
    k0 = jnp.maximum(w2 / g, 1e-12)

    def body(_, k):
        t = jnp.tanh(jnp.clip(k * h, 1e-12, 50.0))
        f = w2 - g * k * t
        df = -g * (t + k * h * (1 - t * t))   # d/dk of g k tanh(kh), sign flipped
        knew = k - f / df
        return jnp.maximum(knew, 1e-12)

    return jax.lax.fori_loop(0, iters, body, k0)


def depth_ratios(k, z, h):
    """Numerically stable hyperbolic depth-attenuation ratios.

    Returns (sinh(k(z+h))/sinh(kh), cosh(k(z+h))/sinh(kh), cosh(k(z+h))/cosh(kh))
    computed via exponentials so nothing overflows for large kh
    (replaces the reference's explicit deep/shallow branching,
    raft/helpers.py:106-120; the formulas are analytically identical to both
    branches).

    k : [nw], z : [...] (<= 0 expected) -> each ratio [..., nw]
    """
    k = jnp.asarray(k)
    z = jnp.asarray(z).astype(k.dtype)[..., None]
    h = jnp.asarray(h).astype(k.dtype)
    ekz = jnp.exp(k * z)                       # e^{k z},      z<=0 so <= 1
    emk = jnp.exp(-k * (z + 2.0 * h))          # e^{-k(z+2h)}, z>=-h so <= 1
    e2h = jnp.exp(-2.0 * k * h)
    denom_s = 1.0 - e2h
    denom_s = jnp.where(denom_s <= 0, 1e-30, denom_s)
    s = (ekz - emk) / denom_s
    c = (ekz + emk) / denom_s
    cc = (ekz + emk) / (1.0 + e2h)
    return s, c, cc


def wave_kinematics(zeta0, beta, w, k, h, r, rho=1025.0, g=_G, dtype=None):
    """Complex wave kinematics amplitude spectra at point(s) r.

    Vectorized over both nodes and frequencies (reference raft/helpers.py:85-134
    loops over frequencies per node).  Nodes above the free surface get zeros,
    matching the reference's ``if z < 0`` gate, via ``where`` masking.

    Parameters
    ----------
    zeta0 : [nw] complex wave elevation amplitudes at the origin
    beta  : scalar wave heading [rad]
    w, k  : [nw] frequencies / wave numbers
    h     : depth
    r     : [..., 3] node positions
    dtype : complex dtype for the outputs.  Defaults to the promotion of the
        inputs.  Pass ``jnp.complex64`` on TPU — the hardware has no c128
        support, so the f32 pair type is the native choice there.

    Returns
    -------
    u    : [..., 3, nw] velocity amplitudes
    ud   : [..., 3, nw] acceleration amplitudes
    pDyn : [..., nw] dynamic pressure amplitudes
    """
    zeta0 = jnp.asarray(zeta0)
    if dtype is None:
        dtype = jnp.result_type(zeta0.dtype, jnp.complex64)
    real = jnp.finfo(dtype).dtype  # matching real dtype (f32 for c64, ...)
    zeta0 = zeta0.astype(dtype)
    w = jnp.asarray(w).astype(real)
    k = jnp.asarray(k).astype(real)
    r = jnp.asarray(r).astype(real)
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    cb, sb = jnp.cos(jnp.asarray(beta, real)), jnp.sin(jnp.asarray(beta, real))
    phase = k * (cb * x + sb * y)[..., None]           # [..., nw]
    # complex exp built from real cos/sin so the complex width follows `dtype`
    zeta = zeta0 * (jnp.cos(phase) - 1j * jnp.sin(phase)).astype(dtype)

    s, c, cc = depth_ratios(k, z, h)                   # [..., nw]
    sub = (z < 0)[..., None]                           # submergence mask

    ux = w * zeta * c * cb
    uy = w * zeta * c * sb
    uz = 1j * w * zeta * s
    u = jnp.stack([ux, uy, uz], axis=-2)               # [..., 3, nw]
    u = jnp.where(sub[..., None, :], u, 0.0)
    ud = 1j * w * u
    pDyn = jnp.where(sub, rho * g * zeta * cc, 0.0)
    return u, ud, pDyn


def jonswap(ws, Hs, Tp, Gamma=1.0):
    """One-sided JONSWAP wave PSD [m^2/(rad/s)] per IEC 61400-3
    (reference raft/helpers.py:397-443; Gamma=1 gives Pierson-Moskowitz).

    Broadcasts over all inputs (so (case, freq) grids evaluate in one call).
    """
    ws = jnp.asarray(ws, float)
    f = 0.5 / jnp.pi * ws
    fpOvrf4 = (Tp * f) ** -4.0
    C = 1.0 - 0.287 * jnp.log(Gamma)
    Sigma = jnp.where(f <= 1.0 / Tp, 0.07, 0.09)
    Alpha = jnp.exp(-0.5 * ((f * Tp - 1.0) / Sigma) ** 2)
    return (
        0.5 / jnp.pi * C * 0.3125 * Hs * Hs * fpOvrf4 / f
        * jnp.exp(-1.25 * fpOvrf4) * Gamma**Alpha
    )


def get_rms(xi, dw):
    """RMS of a complex amplitude spectrum: sqrt(sum |xi|^2 dw) over the last
    axis (reference raft/helpers.py:385-388)."""
    return jnp.sqrt(jnp.sum(jnp.abs(xi) ** 2, axis=-1) * dw)


def get_psd(xi):
    """Power spectral density |xi|^2 (reference raft/helpers.py:391-394)."""
    return jnp.abs(xi) ** 2
