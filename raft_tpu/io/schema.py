"""Design-dictionary schema handling: YAML loading and defaulted, shape-checked
value extraction (the reference's de-facto config system,
raft/helpers.py:456-516 getFromDict; YAML surface documented by
examples/VolturnUS-S_example.yaml and designs/*.yaml).

Host-side, plain Python/NumPy — this runs once per design at trace time.
"""

import numpy as np
import yaml


_NO_DEFAULT = object()


def get_from_dict(d, key, shape=0, dtype=float, default=_NO_DEFAULT):
    """Fetch ``d[key]`` with scalar/array shape coercion and defaults.

    Semantics match the reference helper (raft/helpers.py:456-516):

    - shape == 0: scalar expected, returned as ``dtype``
    - shape == -1: any shape accepted (scalar stays scalar)
    - shape == n (int): 1-D array of length n; scalars are tiled
    - shape == [m, n]: 2-D; a length-n 1-D input is tiled m times
    - missing key: return (possibly tiled) default, or raise if no default
    """
    if key in d and d[key] is not None:
        val = d[key]
        if shape == 0:
            if np.isscalar(val):
                return dtype(val)
            raise ValueError(f"Value for key '{key}' should be scalar but is: {val}")
        if shape == -1:
            if np.isscalar(val):
                return dtype(val)
            return np.array(val, dtype=dtype)
        if np.isscalar(val):
            return np.tile(dtype(val), shape)
        if np.isscalar(shape):
            if len(val) == shape:
                return np.array([dtype(v) for v in val])
            raise ValueError(
                f"Value for key '{key}' is not the expected size {shape}: {val}"
            )
        vala = np.array(val, dtype=dtype)
        if list(vala.shape) == list(shape):
            return vala
        if len(shape) > 2:
            raise ValueError("get_from_dict supports at most 2-D shapes")
        if vala.ndim == 1 and len(vala) == shape[1]:
            return np.tile(vala, [shape[0], 1])
        raise ValueError(
            f"Value for key '{key}' is not compatible with shape {shape}: {val}"
        )
    if default is _NO_DEFAULT or default is None:
        # (the reference treats default=None as "no default"; we keep that)
        raise ValueError(f"Key '{key}' not found in input file...")
    if shape == 0 or shape == -1:
        return default
    return np.tile(default, shape)


def load_design(source):
    """Load a design dict from a YAML path, pickle path, or pass a dict through
    (reference raft/raft_model.py:1098-1108)."""
    if isinstance(source, dict):
        return source
    s = str(source)
    if s.endswith(".pkl") or s.endswith(".pickle"):
        import pickle

        with open(s, "rb") as f:
            return pickle.load(f)
    with open(s) as f:
        return yaml.load(f, Loader=yaml.FullLoader)


def cases_as_dicts(design):
    """Expand the DLC table (keys + data rows, reference
    examples/VolturnUS-S_example.yaml:21-24) into per-case dicts
    (reference raft/raft_model.py:245)."""
    if "cases" not in design or design["cases"] is None:
        return []
    keys = design["cases"]["keys"]
    return [dict(zip(keys, row)) for row in design["cases"]["data"]]
