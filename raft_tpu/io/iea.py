"""IEA Wind Task 37 ontology ("windIO") turbine YAML -> RAFT design schema.

Re-provides the reference's converter (reference
raft/helpers.py:518-663 convertIEAturbineYAML2RAFT) without the WISDEM
dependency: the ontology file is parsed directly with PyYAML and the
blade reference-axis arc length is computed in-line.

The returned dict plugs straight into a design's ``turbine`` section
(the format consumed by raft_tpu.aero.Rotor: ``blade.geometry`` columns
[r, chord, theta, precurve, presweep], ``blade.airfoils`` as
(position, name) pairs, ``airfoils`` as name/relative_thickness/data
polar tables in degrees).
"""

import numpy as np
import yaml


def _interp_axis(grid, entry):
    return np.interp(grid, entry["grid"], entry["values"])


def _arc_length(points):
    """Cumulative arc length along a polyline [n,3]
    (WISDEM's commonse.utilities.arc_length equivalent)."""
    seg = np.linalg.norm(np.diff(points, axis=0), axis=1)
    return np.concatenate([[0.0], np.cumsum(seg)])


def convert_iea_turbine(source, n_span=30, out_path=None):
    """Convert an IEA-ontology turbine description (YAML path or parsed
    dict) to the RAFT ``turbine`` schema.

    Parameters
    ----------
    source : str | dict
        Path to a windIO geometry YAML (e.g. IEA-15-240-RWT.yaml) or the
        already-parsed dict.
    n_span : int
        Number of equally spaced blade stations (interior stations carry
        the distributed geometry; the tip sets Rtip/precurveTip).
    out_path : str | None
        Optionally also write the result as a RAFT-style YAML file.
    """
    if isinstance(source, dict):
        wt = source
    else:
        with open(source) as f:
            wt = yaml.safe_load(f)

    hub = wt["components"]["hub"]
    drivetrain = wt["components"]["nacelle"]["drivetrain"]
    assembly = wt["assembly"]
    Rhub = 0.5 * hub["diameter"]

    out = {
        "nBlades": int(assembly["number_of_blades"]),
        "precone": float(np.rad2deg(hub["cone_angle"])),
        "shaft_tilt": float(np.rad2deg(drivetrain["uptilt"])),
        "overhang": float(drivetrain["overhang"]),
        "Rhub": float(Rhub),
    }

    grid = np.linspace(0.0, 1.0, n_span)
    blade = wt["components"]["blade"]["outer_shape_bem"]
    axis = np.column_stack(
        [_interp_axis(grid, blade["reference_axis"][c]) for c in "xyz"]
    )
    # rescale the z axis so the swept radius matches the stated rotor
    # diameter (the ontology's reference axis is along the curved blade)
    rotor_diameter = assembly.get("rotor_diameter", 0.0)
    if rotor_diameter:
        axis[:, 2] *= rotor_diameter / (2.0 * (_arc_length(axis)[-1] + Rhub))

    r = axis[1:-1, 2] + Rhub
    chord = _interp_axis(grid[1:-1], blade["chord"])
    theta = np.rad2deg(_interp_axis(grid[1:-1], blade["twist"]))
    geometry = np.column_stack(
        [r, chord, theta, axis[1:-1, 0], axis[1:-1, 1]]
    )
    out["blade"] = {
        "geometry": geometry,
        "Rtip": float(axis[-1, 2] + Rhub),
        "precurveTip": float(axis[-1, 0]),
        "presweepTip": float(axis[-1, 1]),
        "airfoils": list(zip(
            blade["airfoil_position"]["grid"],
            blade["airfoil_position"]["labels"],
        )),
    }

    if assembly.get("hub_height", 0.0):
        out["Zhub"] = float(assembly["hub_height"])
    else:
        tower_z = wt["components"]["tower"]["outer_shape_bem"][
            "reference_axis"]["z"]["values"]
        out["Zhub"] = float(tower_z[-1] + drivetrain["distance_tt_hub"])

    env = wt.get("environment", {})
    out["env"] = {
        "rho": env.get("air_density", 1.225),
        "mu": env.get("air_dyn_viscosity", 1.81e-5),
        "shearExp": env.get("shear_exp", 0.12),
    }

    out["airfoils"] = []
    for af in wt["airfoils"]:
        polar = af["polars"][0]
        if len(af["polars"]) > 1:
            print(f"Warning for airfoil {af['name']}, only the first polar "
                  "entry is used.")
        aoa = np.asarray(polar["c_l"]["grid"], float)
        for coeff in ("c_d", "c_m"):
            if not np.array_equal(aoa, np.asarray(polar[coeff]["grid"], float)):
                raise ValueError(
                    f"AOA grids for airfoil {af['name']} are not consistent "
                    f"between c_l and {coeff}."
                )
        out["airfoils"].append({
            "name": af["name"],
            "relative_thickness": af["relative_thickness"],
            "data": np.column_stack([
                np.rad2deg(aoa),
                polar["c_l"]["values"],
                polar["c_d"]["values"],
                polar["c_m"]["values"],
            ]),
        })

    if out_path:
        write_raft_turbine_yaml(out_path, out)
    return out


def write_raft_turbine_yaml(path, turbine):
    """Write the converted turbine as a RAFT-style YAML file (the reference
    hand-formats this output, helpers.py:616-663)."""
    d = dict(turbine)
    blade = dict(d["blade"])
    blade["geometry"] = [[round(float(v), 4) for v in row]
                         for row in np.asarray(blade["geometry"])]
    blade["airfoils"] = [[float(p), str(n)] for p, n in blade["airfoils"]]
    d["blade"] = blade
    d["airfoils"] = [
        {
            "name": af["name"],
            "relative_thickness": af["relative_thickness"],
            "key": ["alpha", "c_l", "c_d", "c_m"],
            "data": [[round(float(v), 6) for v in row]
                     for row in np.asarray(af["data"])],
        }
        for af in d["airfoils"]
    ]
    with open(path, "w") as f:
        f.write("# RAFT-style YAML inputs for turbine\n")
        yaml.safe_dump({"turbine": d}, f, sort_keys=False,
                       default_flow_style=None)
    return path
