from raft_tpu.io.schema import get_from_dict, load_design, cases_as_dicts
