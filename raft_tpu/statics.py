"""Static mass, inertia, and hydrostatic properties of the floating system.

Host-side NumPy float64 (runs once per design; several outputs like the
hydrostatic C44 ~ -5e9 N·m arise from large cancellations and warrant exact
f64, which the TPU backend does not provide).  Mirrors the physics of
reference raft/raft_member.py:245-798 (getInertia/getHydrostatics) and
raft/raft_fowt.py:127-313 (calcStatics), with the quirks either reproduced or
documented below.

Deliberate divergences from the reference (all in unreachable/broken paths):
 - zero-length submembers contribute nothing (the reference would add a stale
   rotated MoI block from the previous loop iteration, raft_member.py:350-356
   leaves Ixx/Iyy/Izz undefined/stale when l == 0);
 - rectangular top-end caps use the corrected assignment order (the reference
   reads slBi before assigning it, raft_member.py:570);
 - the tapered rectangular MoI uses the exact closed form (the reference's
   general branch contains a TypeError, raft_member.py:294).
Reproduced quirks (reachable but questionable, kept for output parity):
 - waterplane diameter interpolated with swapped endpoints
   (raft_member.py:697: yA=d[i], yB=d[i-1]);
 - rectangular waterplane IyWP = sl0^3*sl0/12 instead of sl0^3*sl1/12
   (raft_member.py:706).
Additional divergences in the rectangular waterplane-crossing path (which
the reference cannot actually execute — it would NameError on dWP at
raft_member.py:741): dWP is taken as the area-equivalent diameter for the
incline moment term, and the member's IWP is reported as the rotated IxWP
(the reference reports 0 for rectangular members since only the circular
branch sets IWP).

Note on duplication: the frustum/frame formulas here intentionally mirror
the jnp versions in raft_tpu/utils (tested against each other) — this module
is a per-design host loop where plain NumPy avoids per-op JAX dispatch
overhead and any risk of eager ops landing on the reduced-precision TPU
backend.
"""

from dataclasses import dataclass, field

import numpy as np

from raft_tpu.geometry import Member


# ---------------- numpy frustum helpers (exact host math) ----------------

def _vcv_circ(dA, dB, H):
    if dA == 0 and dB == 0:
        return 0.0, 0.0
    A1 = np.pi / 4 * dA**2
    A2 = np.pi / 4 * dB**2
    Am = np.pi / 4 * dA * dB
    V = (A1 + A2 + Am) * H / 3
    hc = (A1 + 2 * Am + 3 * A2) / (A1 + Am + A2) * H / 4
    return V, hc


def _vcv_rect(slA, slB, H):
    A1 = slA[0] * slA[1]
    A2 = slB[0] * slB[1]
    if A1 == 0 and A2 == 0 and np.sum(np.abs(slA)) == 0 and np.sum(np.abs(slB)) == 0:
        return 0.0, 0.0
    Am = np.sqrt(A1 * A2)
    denom = A1 + Am + A2
    if denom == 0:
        return 0.0, 0.0
    V = denom * H / 3
    hc = (A1 + 2 * Am + 3 * A2) / denom * H / 4
    return V, hc


def _moi_circ(dA, dB, H, p):
    """(I_rad about end, I_ax) of a solid circular frustum
    (reference raft/raft_member.py:250-268)."""
    if H == 0:
        return 0.0, 0.0
    r1, r2 = dA / 2, dB / 2
    if dA == dB:
        I_rad = (1 / 12) * (p * H * np.pi * r1**2) * (3 * r1**2 + 4 * H**2)
        I_ax = 0.5 * p * np.pi * H * r1**4
    else:
        ratio = (r2**5 - r1**5) / (r2 - r1)
        I_rad = (1 / 20) * p * np.pi * H * ratio + (1 / 30) * p * np.pi * H**3 * (
            r1**2 + 3 * r1 * r2 + 6 * r2**2
        )
        I_ax = (1 / 10) * p * np.pi * H * ratio
    return I_rad, I_ax


def _moi_rect(slA, slB, H, p):
    """(Ixx, Iyy, Izz) about the end node of a tapered cuboid — exact closed
    form (see raft_tpu/utils/frustum.py rect_frustum_moi)."""
    if H == 0:
        return 0.0, 0.0, 0.0
    La, Wa = slA
    Lb, Wb = slB
    dL, dW = Lb - La, Wb - Wa

    def poly_int(c):
        return sum(ck / (k + 1) for k, ck in enumerate(c))

    l3 = [La**3, 3 * La**2 * dL, 3 * La * dL**2, dL**3]
    w3 = [Wa**3, 3 * Wa**2 * dW, 3 * Wa * dW**2, dW**3]
    x2 = p * H / 12 * poly_int([
        l3[0] * Wa, l3[0] * dW + l3[1] * Wa, l3[1] * dW + l3[2] * Wa,
        l3[2] * dW + l3[3] * Wa, l3[3] * dW,
    ])
    y2 = p * H / 12 * poly_int([
        w3[0] * La, w3[0] * dL + w3[1] * La, w3[1] * dL + w3[2] * La,
        w3[2] * dL + w3[3] * La, w3[3] * dL,
    ])
    z2 = p * H**3 * poly_int([0.0, 0.0, La * Wa, La * dW + Wa * dL, dL * dW])
    return y2 + z2, x2 + z2, x2 + y2


def _getH(r):
    return np.array([[0, r[2], -r[1]], [-r[2], 0, r[0]], [r[1], -r[0], 0]], float)


def _translate_force_3to6(F, r):
    out = np.zeros(6, dtype=F.dtype)
    out[:3] = F
    out[3:] = np.cross(r, F)
    return out


def _translate_matrix_6to6(M, r):
    H = _getH(r)
    out = np.zeros((6, 6))
    out[:3, :3] = M[:3, :3]
    out[:3, 3:] = M[:3, :3] @ H + M[:3, 3:]
    out[3:, :3] = out[:3, 3:].T
    out[3:, 3:] = H @ M[:3, :3] @ H.T + M[3:, :3] @ H + H.T @ M[:3, 3:] + M[3:, 3:]
    return out


# ---------------- member inertia ----------------

def member_inertia(mem: Member):
    """Mass/inertia 6x6 about the PRP plus totals for one member
    (reference raft/raft_member.py:245-643).

    Returns (M_struc[6,6], mass, center[3], mshell, mfill list, pfill list,
    vfill list).
    """
    n = len(mem.stations)
    mass_center = np.zeros(3)
    mshell = 0.0
    vfill, mfill, pfill = [], [], []
    M_struc = np.zeros((6, 6))

    for i in range(1, n):
        rA = mem.rA + mem.q * mem.stations[i - 1]
        l = mem.stations[i] - mem.stations[i - 1]
        if l == 0.0:
            vfill.append(0.0)
            mfill.append(0.0)
            pfill.append(0.0)
            continue

        l_fill = mem.l_fill if np.isscalar(mem.l_fill) else mem.l_fill[i - 1]
        rho_fill = mem.rho_fill if np.isscalar(mem.rho_fill) else mem.rho_fill[i - 1]
        rho_shell = mem.rho_shell

        if mem.circular:
            dA, dB = mem.d[i - 1], mem.d[i]
            dAi = mem.d[i - 1] - 2 * mem.t[i - 1]
            dBi = mem.d[i] - 2 * mem.t[i]
            V_outer, hco = _vcv_circ(dA, dB, l)
            V_inner, hci = _vcv_circ(dAi, dBi, l)
            v_shell = V_outer - V_inner
            m_shell = v_shell * rho_shell
            hc_shell = (hco * V_outer - hci * V_inner) / (V_outer - V_inner)
            dBi_fill = (dBi - dAi) * (l_fill / l) + dAi
            v_fill, hc_fill = _vcv_circ(dAi, dBi_fill, l_fill)
            m_fill = v_fill * rho_fill
            mass = m_shell + m_fill
            hc = (hc_fill * m_fill + hc_shell * m_shell) / mass
            center = rA + mem.q * hc

            I_rad_o, I_ax_o = _moi_circ(dA, dB, l, rho_shell)
            I_rad_i, I_ax_i = _moi_circ(dAi, dBi, l, rho_shell)
            I_rad_f, I_ax_f = _moi_circ(dAi, dBi_fill, l_fill, rho_fill)
            I_rad = (I_rad_o - I_rad_i) + I_rad_f - mass * hc**2
            I_ax = (I_ax_o - I_ax_i) + I_ax_f
            Ixx = Iyy = I_rad
            Izz = I_ax
        else:
            slA, slB = mem.sl[i - 1], mem.sl[i]
            slAi = mem.sl[i - 1] - 2 * mem.t[i - 1]
            slBi = mem.sl[i] - 2 * mem.t[i]
            V_outer, hco = _vcv_rect(slA, slB, l)
            V_inner, hci = _vcv_rect(slAi, slBi, l)
            v_shell = V_outer - V_inner
            m_shell = v_shell * rho_shell
            hc_shell = (hco * V_outer - hci * V_inner) / (V_outer - V_inner)
            slBi_fill = (slBi - slAi) * (l_fill / l) + slAi
            v_fill, hc_fill = _vcv_rect(slAi, slBi_fill, l_fill)
            m_fill = v_fill * rho_fill
            mass = m_shell + m_fill
            hc = (hc_fill * m_fill + hc_shell * m_shell) / mass
            center = rA + mem.q * hc

            Ixx_o, Iyy_o, Izz_o = _moi_rect(slA, slB, l, rho_shell)
            Ixx_i, Iyy_i, Izz_i = _moi_rect(slAi, slBi, l, rho_shell)
            Ixx_f, Iyy_f, Izz_f = _moi_rect(slAi, slBi_fill, l_fill, rho_fill)
            Ixx = (Ixx_o - Ixx_i) + Ixx_f - mass * hc**2
            Iyy = (Iyy_o - Iyy_i) + Iyy_f - mass * hc**2
            Izz = (Izz_o - Izz_i) + Izz_f

        mass_center += mass * center
        mshell += m_shell
        vfill.append(v_fill)
        mfill.append(m_fill)
        pfill.append(rho_fill)

        Mmat = np.diag([mass, mass, mass, 0.0, 0.0, 0.0])
        I = np.diag([Ixx, Iyy, Izz])
        # I_rot = R I R^T (reference raft_member.py:472-473 via T = R.T)
        Mmat[3:, 3:] = mem.R @ I @ mem.R.T
        M_struc += _translate_matrix_6to6(Mmat, center)

    # ----- end caps / bulkheads (reference raft_member.py:484-637) -----
    m_cap_list = []
    for i in range(len(mem.cap_stations)):
        L = mem.cap_stations[i]
        h = mem.cap_t[i]
        rho_cap = mem.rho_shell

        if mem.circular:
            d_hole = mem.cap_d_in[i]
            d_in = mem.d - 2 * mem.t
            if L == mem.stations[0]:
                dA = d_in[0]
                dB = np.interp(L + h, mem.stations, d_in)
                dAi = d_hole
                dBi = dB * (dAi / dA)
            elif L == mem.stations[-1]:
                dA = np.interp(L - h, mem.stations, d_in)
                dB = d_in[-1]
                dBi = d_hole
                dAi = dA * (dBi / dB)
            elif (mem.stations[0] < L < mem.stations[0] + h) or (
                mem.stations[-1] - h < L < mem.stations[-1]
            ):
                raise ValueError("Cap too close to member end; unsupported")
            elif i < len(mem.cap_stations) - 1 and L == mem.cap_stations[i + 1]:
                dA = np.interp(L - h, mem.stations, d_in)
                dB = d_in[i]
                dBi = d_hole
                dAi = dA * (dBi / dB)
            elif i > 0 and L == mem.cap_stations[i - 1]:
                dA = d_in[i]
                dB = np.interp(L + h, mem.stations, d_in)
                dAi = d_hole
                dBi = dB * (dAi / dA)
            else:
                dA = np.interp(L - h / 2, mem.stations, d_in)
                dB = np.interp(L + h / 2, mem.stations, d_in)
                dM = np.interp(L, mem.stations, d_in)
                dMi = d_hole
                dAi = dA * (dMi / dM)
                dBi = dB * (dMi / dM)

            V_outer, hco = _vcv_circ(dA, dB, h)
            V_inner, hci = _vcv_circ(dAi, dBi, h)
            v_cap = V_outer - V_inner
            m_cap = v_cap * rho_cap
            hc_cap = (hco * V_outer - hci * V_inner) / (V_outer - V_inner)

            I_rad_o, I_ax_o = _moi_circ(dA, dB, h, rho_cap)
            I_rad_i, I_ax_i = _moi_circ(dAi, dBi, h, rho_cap)
            I_rad = (I_rad_o - I_rad_i) - m_cap * hc_cap**2
            I_ax = I_ax_o - I_ax_i
            Ixx = Iyy = I_rad
            Izz = I_ax
        else:
            sl_hole = np.atleast_1d(mem.cap_d_in[i])
            sl_in = mem.sl - 2 * mem.t[:, None]
            if L == mem.stations[0]:
                slA = sl_in[0]
                slB = np.array(
                    [np.interp(L + h, mem.stations, sl_in[:, j]) for j in range(2)]
                )
                slAi = sl_hole
                slBi = slB * (slAi / slA)
            elif L == mem.stations[-1]:
                slA = np.array(
                    [np.interp(L - h, mem.stations, sl_in[:, j]) for j in range(2)]
                )
                slB = sl_in[-1]
                slBi = sl_hole
                slAi = slA * (slBi / slB)
            elif (mem.stations[0] < L < mem.stations[0] + h) or (
                mem.stations[-1] - h < L < mem.stations[-1]
            ):
                raise ValueError("Cap too close to member end; unsupported")
            elif i < len(mem.cap_stations) - 1 and L == mem.cap_stations[i + 1]:
                slA = np.array(
                    [np.interp(L - h, mem.stations, sl_in[:, j]) for j in range(2)]
                )
                slB = sl_in[i]
                slBi = sl_hole
                slAi = slA * (slBi / slB)
            elif i > 0 and L == mem.cap_stations[i - 1]:
                slA = sl_in[i]
                slB = np.array(
                    [np.interp(L + h, mem.stations, sl_in[:, j]) for j in range(2)]
                )
                slAi = sl_hole
                slBi = slB * (slAi / slA)
            else:
                slA = np.array(
                    [np.interp(L - h / 2, mem.stations, sl_in[:, j]) for j in range(2)]
                )
                slB = np.array(
                    [np.interp(L + h / 2, mem.stations, sl_in[:, j]) for j in range(2)]
                )
                slM = np.array(
                    [np.interp(L, mem.stations, sl_in[:, j]) for j in range(2)]
                )
                slAi = slA * (sl_hole / slM)
                slBi = slB * (sl_hole / slM)

            V_outer, hco = _vcv_rect(slA, slB, h)
            V_inner, hci = _vcv_rect(slAi, slBi, h)
            v_cap = V_outer - V_inner
            m_cap = v_cap * rho_cap
            hc_cap = (hco * V_outer - hci * V_inner) / (V_outer - V_inner)
            Ixx_o, Iyy_o, Izz_o = _moi_rect(slA, slB, h, rho_cap)
            Ixx_i, Iyy_i, Izz_i = _moi_rect(slAi, slBi, h, rho_cap)
            Ixx = (Ixx_o - Ixx_i) - m_cap * hc_cap**2
            Iyy = (Iyy_o - Iyy_i) - m_cap * hc_cap**2
            Izz = Izz_o - Izz_i

        pos_cap = mem.rA + mem.q * L
        if L == mem.stations[0]:
            center_cap = pos_cap + mem.q * hc_cap
        elif L == mem.stations[-1]:
            center_cap = pos_cap - mem.q * (h - hc_cap)
        else:
            center_cap = pos_cap - mem.q * (h / 2 - hc_cap)

        mass_center += m_cap * center_cap
        mshell += m_cap
        m_cap_list.append(m_cap)

        Mmat = np.diag([m_cap, m_cap, m_cap, 0.0, 0.0, 0.0])
        I = np.diag([Ixx, Iyy, Izz])
        Mmat[3:, 3:] = mem.R @ I @ mem.R.T
        M_struc += _translate_matrix_6to6(Mmat, center_cap)

    mass = M_struc[0, 0]
    center = mass_center / mass if mass > 0 else np.zeros(3)
    return M_struc, mass, center, mshell, mfill, pfill, vfill


# ---------------- member hydrostatics ----------------

def member_hydrostatics(mem: Member, rho, g):
    """Buoyancy force vector, hydrostatic stiffness, underwater volume,
    center of buoyancy, and waterplane properties of one member
    (reference raft/raft_member.py:648-798)."""
    Fvec = np.zeros(6)
    Cmat = np.zeros((6, 6))
    V_UW = 0.0
    r_centerV = np.zeros(3)
    AWP = IWP = xWP = yWP = 0.0

    n = len(mem.stations)
    for i in range(1, n):
        rA = mem.rA + mem.q * mem.stations[i - 1]
        rB = mem.rA + mem.q * mem.stations[i]

        if rA[2] * rB[2] <= 0:  # crosses (or touches) the waterplane
            beta = np.arctan2(mem.q[1], mem.q[0])
            phi = np.arctan2(np.sqrt(mem.q[0] ** 2 + mem.q[1] ** 2), mem.q[2])
            cosPhi, sinPhi, tanPhi = np.cos(phi), np.sin(phi), np.tan(phi)

            def intrp(x, xA, xB, yA, yB):
                return yA + (x - xA) * (yB - yA) / (xB - xA)

            xWP = intrp(0, rA[2], rB[2], rA[0], rB[0])
            yWP = intrp(0, rA[2], rB[2], rA[1], rB[1])
            if mem.circular:
                # endpoint order kept as the reference has it (see module doc)
                dWP = intrp(0, rA[2], rB[2], mem.d[i], mem.d[i - 1])
                AWP = (np.pi / 4) * dWP**2
                IWP = (np.pi / 64) * dWP**4
                IxWP = IyWP = IWP
            else:
                slWP = intrp(0, rA[2], rB[2], mem.sl[i], mem.sl[i - 1])
                dWP = np.sqrt(4 * slWP[0] * slWP[1] / np.pi)  # equivalent diameter
                AWP = slWP[0] * slWP[1]
                IxWP = (1 / 12) * slWP[0] * slWP[1] ** 3
                IyWP = (1 / 12) * slWP[0] ** 3 * slWP[0]  # reference quirk kept
                I = np.diag([IxWP, IyWP, 0.0])
                I_rot = mem.R @ I @ mem.R.T
                IxWP = I_rot[0, 0]
                IyWP = I_rot[1, 1]
                IWP = IxWP

            LWP = abs(rA[2]) / cosPhi

            if mem.circular:
                V_UWi, hc = _vcv_circ(mem.d[i - 1], dWP, LWP)
            else:
                V_UWi, hc = _vcv_rect(mem.sl[i - 1], slWP, LWP)
            r_center = rA + mem.q * hc

            dPhi_dThx = -np.sin(beta)
            dPhi_dThy = np.cos(beta)
            dFz_dz = -rho * g * AWP / cosPhi

            Fz = rho * g * V_UWi
            M = (
                -rho * g * np.pi
                * (dWP**2 / 32 * (2.0 + tanPhi**2) + 0.5 * (rA[2] / cosPhi) ** 2)
                * sinPhi
            )
            Fvec[2] += Fz
            Fvec[3] += M * dPhi_dThx + Fz * rA[1]
            Fvec[4] += M * dPhi_dThy - Fz * rA[0]

            Cmat[2, 2] += -dFz_dz
            Cmat[2, 3] += rho * g * (-AWP * yWP)
            Cmat[2, 4] += rho * g * (AWP * xWP)
            Cmat[3, 2] += rho * g * (-AWP * yWP)
            Cmat[3, 3] += rho * g * (IxWP + AWP * yWP**2)
            Cmat[3, 4] += rho * g * (AWP * xWP * yWP)
            Cmat[4, 2] += rho * g * (AWP * xWP)
            Cmat[4, 3] += rho * g * (AWP * xWP * yWP)
            Cmat[4, 4] += rho * g * (IyWP + AWP * xWP**2)
            Cmat[3, 3] += rho * g * V_UWi * r_center[2]
            Cmat[4, 4] += rho * g * V_UWi * r_center[2]

            V_UW += V_UWi
            r_centerV += r_center * V_UWi

        elif rA[2] <= 0 and rB[2] <= 0:  # fully submerged
            if mem.circular:
                V_UWi, hc = _vcv_circ(
                    mem.d[i - 1], mem.d[i], mem.stations[i] - mem.stations[i - 1]
                )
            else:
                V_UWi, hc = _vcv_rect(
                    mem.sl[i - 1], mem.sl[i], mem.stations[i] - mem.stations[i - 1]
                )
            r_center = rA + mem.q * hc
            Fvec += _translate_force_3to6(np.array([0, 0, rho * g * V_UWi]), r_center)
            Cmat[3, 3] += rho * g * V_UWi * r_center[2]
            Cmat[4, 4] += rho * g * V_UWi * r_center[2]
            V_UW += V_UWi
            r_centerV += r_center * V_UWi
        # else: fully above water — nothing

    r_center = r_centerV / V_UW if V_UW > 0 else np.zeros(3)
    return Fvec, Cmat, V_UW, r_center, AWP, IWP, xWP, yWP


# ---------------- FOWT-level aggregation ----------------

@dataclass
class Statics:
    """All static system properties (reference FOWT attributes set by
    raft/raft_fowt.py:127-313)."""

    M_struc: np.ndarray
    B_struc: np.ndarray
    C_struc: np.ndarray
    W_struc: np.ndarray
    C_struc_sub: np.ndarray
    C_hydro: np.ndarray
    W_hydro: np.ndarray
    V: float
    rCB: np.ndarray
    AWP: float
    zMeta: float
    mtower: float
    rCG_tow: np.ndarray
    msubstruc: float
    rCG_sub: np.ndarray
    M_struc_subPRP: np.ndarray
    M_struc_subCM: np.ndarray
    mshell: float
    mballast: np.ndarray
    pb: list
    rCG_TOT: np.ndarray
    mass: float
    # per-member ballast volumes, for ballast adjustment
    member_vfill: list = field(default_factory=list)


def compute_statics(members, turbine, rho_water=1025.0, g=9.81):
    """Aggregate member inertia + hydrostatics + lumped RNA into system
    matrices (reference raft/raft_fowt.py:127-313).

    turbine : dict with mRNA, IxRNA, IrRNA, xCG_RNA, hHub.
    """
    M_struc = np.zeros((6, 6))
    B_struc = np.zeros((6, 6))
    C_struc = np.zeros((6, 6))
    W_struc = np.zeros(6)
    C_struc_sub = np.zeros((6, 6))
    C_hydro = np.zeros((6, 6))
    W_hydro = np.zeros(6)

    VTOT = 0.0
    AWP_TOT = 0.0
    IWPx_TOT = 0.0
    IWPy_TOT = 0.0
    Sum_V_rCB = np.zeros(3)
    Sum_M_center = np.zeros(3)

    mtower = 0.0
    rCG_tow = np.zeros(3)
    msubstruc = 0.0
    M_struc_subPRP = np.zeros((6, 6))
    msubstruc_sum = np.zeros(3)
    mshell_tot = 0.0
    mballast = []
    pballast = []
    member_vfill = []

    for mem in members:
        Mm, mass, center, mshell, mfill, pfill, vfill = member_inertia(mem)
        member_vfill.append(vfill)
        W_struc += _translate_force_3to6(np.array([0, 0, -g * mass]), center)
        M_struc += Mm
        Sum_M_center += center * mass

        if mem.type <= 1:  # tower
            mtower = mass
            rCG_tow = center
        if mem.type > 1:  # substructure
            msubstruc += mass
            M_struc_subPRP += Mm
            msubstruc_sum += center * mass
            mshell_tot += mshell
            mballast.extend(mfill)
            pballast.extend(pfill)

        Fvec, Cmat, V_UW, r_CB, AWP, IWP, xWP, yWP = member_hydrostatics(
            mem, rho_water, g
        )
        W_hydro += Fvec
        C_hydro += Cmat
        VTOT += V_UW
        AWP_TOT += AWP
        IWPx_TOT += IWP + AWP * yWP**2
        IWPy_TOT += IWP + AWP * xWP**2
        Sum_V_rCB += r_CB * V_UW

    # lumped RNA (reference raft_fowt.py:236-242)
    mRNA = float(turbine["mRNA"])
    Mmat = np.diag(
        [mRNA, mRNA, mRNA, float(turbine["IxRNA"]), float(turbine["IrRNA"]),
         float(turbine["IrRNA"])]
    )
    center = np.array([float(turbine["xCG_RNA"]), 0.0, float(turbine["hHub"])])
    W_struc += _translate_force_3to6(np.array([0, 0, -g * mRNA]), center)
    M_struc += _translate_matrix_6to6(Mmat, center)
    Sum_M_center += center * mRNA

    mTOT = M_struc[0, 0]
    rCG_TOT = Sum_M_center / mTOT
    rCG_sub = msubstruc_sum / msubstruc
    M_struc_subCM = _translate_matrix_6to6(M_struc_subPRP, -rCG_sub)

    # unique ballast densities and their total masses (raft_fowt.py:276-286)
    pb = []
    for p in pballast:
        if p != 0 and p not in pb:
            pb.append(p)
    mball = np.zeros(len(pb))
    for i, p in enumerate(pb):
        for j, mb in enumerate(mballast):
            if float(pballast[j]) == float(p):
                mball[i] += mb

    rCB_TOT = Sum_V_rCB / VTOT if VTOT > 0 else np.zeros(3)
    zMeta = 0.0 if VTOT == 0 else rCB_TOT[2] + IWPx_TOT / VTOT

    C_struc[3, 3] = -mTOT * g * rCG_TOT[2]
    C_struc[4, 4] = -mTOT * g * rCG_TOT[2]
    C_struc_sub[3, 3] = -msubstruc * g * rCG_sub[2]
    C_struc_sub[4, 4] = -msubstruc * g * rCG_sub[2]

    return Statics(
        M_struc=M_struc,
        B_struc=B_struc,
        C_struc=C_struc,
        W_struc=W_struc,
        C_struc_sub=C_struc_sub,
        C_hydro=C_hydro,
        W_hydro=W_hydro,
        V=VTOT,
        rCB=rCB_TOT,
        AWP=AWP_TOT,
        zMeta=zMeta,
        mtower=mtower,
        rCG_tow=rCG_tow,
        msubstruc=msubstruc,
        rCG_sub=rCG_sub,
        M_struc_subPRP=M_struc_subPRP,
        M_struc_subCM=M_struc_subCM,
        mshell=mshell_tot,
        mballast=mball,
        pb=pb,
        rCG_TOT=rCG_TOT,
        mass=mTOT,
        member_vfill=member_vfill,
    )
