"""Model facade (placeholder — full implementation lands with the dynamics
pipeline)."""


class Model:  # pragma: no cover - placeholder
    def __init__(self, design, **kwargs):
        raise NotImplementedError("raft_tpu.Model is under construction")


def run_raft(input_file, **kwargs):  # pragma: no cover - placeholder
    raise NotImplementedError
