"""Model — the user-facing orchestration facade.

Mirrors the reference Model API surface (reference raft/raft_model.py:23-1147:
``Model(design)``, ``analyzeUnloaded``, ``analyzeCases``, ``solveEigen``,
``calcOutputs``, module-level ``runRAFT``) with snake_case names plus
camelCase aliases, and the same ``results`` dictionary keys, so reference
users can switch directly.

Architecture (TPU-first, not a port):
 - host/CPU f64 setup: geometry packing, statics, mooring equilibrium
   (per-case mean offsets via vmap over cases);
 - ONE jitted device graph for the entire case dynamics: wave kinematics at
   all strip nodes, Froude-Krylov excitation, drag-linearization fixed point
   and the per-frequency 6x6 solves, batched [case, freq] — replacing the
   reference's triple Python loops (raft_model.py:239/:558/:585);
 - complex arrays never cross the device boundary (TPU constraint), so the
   pipeline returns (real, imag) pairs;
 - dtype policy: f32/c64 graph on TPU, f64/c128 on CPU (selectable via
   ``precision=``).
"""

import os
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.geometry import pack_nodes, process_members
from raft_tpu.hydro import (
    added_mass_morison,
    excitation_froude_krylov,
    make_wave_spectrum,
)
from raft_tpu.dynamics import fixed_point_phases, solve_dynamics
from raft_tpu.precision import mixed_precision_enabled
from raft_tpu.health import (
    apply_debug_nans,
    log_report,
    report_dict,
    report_to_numpy,
)
from raft_tpu.io.schema import cases_as_dicts, get_from_dict, load_design
from raft_tpu.mooring import (
    case_mooring_batch_fn,
    line_forces,
    parse_mooring,
    unloaded_mooring_fn,
    warn_bridle_residual,
)
from raft_tpu.statics import compute_statics, member_inertia
from raft_tpu.utils.placement import backend_sharding, put_cpu
from raft_tpu.utils.profiling import logger, timer
from raft_tpu.utils.frames import (
    transform_force,
    translate_matrix_3to6,
    translate_matrix_6to6,
)
from raft_tpu.waves import wave_kinematics, wave_number

_RAD2DEG = 57.29577951308232

_SPECTRUM_CODES = {"still": 0, "none": 0, "unit": 1, "JONSWAP": 2}


@lru_cache(maxsize=32)
def _wave_numbers_cached(w_bytes, nw, depth, g):
    """Dispersion solve for a frequency grid, cached across Model instances
    (a design sweep re-solves the identical grid hundreds of times)."""
    w = np.frombuffer(w_bytes, np.float64, count=nw)
    k = np.asarray(wave_number(put_cpu(w), depth, g=g))
    k.setflags(write=False)  # the cached array is shared across Models
    return k


def _uniform_heading_grid(headings, resolution=1e-3, max_grid=73):
    """Smallest uniform grid (in degrees) containing every requested
    heading — the representation the HAMS control-file schedule can
    describe (min/step/count).  {0, 30, 90} -> (0, 30, 60, 90).

    Headings are snapped to ``resolution`` degrees first (float noise
    like 22.500001 must not set the gcd step), and if the uniform grid
    would still exceed ``max_grid`` entries (headings with a tiny common
    step would otherwise multiply the diffraction RHS count without
    bound), the exact requested set is returned instead — only the HAMS
    control-file writer needs the min/step/count form, and it falls back
    to a degenerate schedule for non-uniform sets."""
    import math

    hs = sorted({round(float(h) / resolution) for h in headings})
    if len(hs) <= 1:
        return (hs[0] * resolution,) if hs else (0.0,)
    step = 0
    for d in np.diff(hs):
        step = math.gcd(step, int(d))
    n = (hs[-1] - hs[0]) // step + 1
    if n > max_grid:
        return tuple(h * resolution for h in hs)
    return tuple((hs[0] + i * step) * resolution for i in range(n))


def _fixed_point_engine_requested():
    """Whether the convergence-aware fixed-point engine handles the
    non-slots case dispatch: RAFT_TPU_FIXED_POINT != legacy AND the
    checkable debug pipeline is not requested (the debug path always
    runs the legacy reference dispatch)."""
    from raft_tpu.waterfall import fixed_point_mode

    return fixed_point_mode() != "legacy" and not apply_debug_nans()


def make_case_dynamics(w, k, depth, rho, g, XiStart, nIter, dtype, cdtype,
                       checkable=False, relax=0.8):
    """Build the single-case device function
    ``fn(nodes, zeta[nw], beta, C_lin[6,6], M_lin[nw,6,6], B_lin[nw,6,6],
    F_add_r[nw,6], F_add_i[nw,6]) -> (Xi_r[6,nw], Xi_i[6,nw], report)``
    where ``report`` is a :class:`raft_tpu.health.SolveReport` pytree
    (convergence flag, iteration count, NaN-quarantine flag, recovery
    tier, residual, condition estimate — all batched by the callers'
    vmaps alongside the amplitudes).

    ``nodes`` is an explicit argument (a HydroNodes pytree in the working
    dtype) so callers can vmap over *designs* as well as cases — the sweep
    driver (raft_tpu/sweep.py) batches padded node bundles over a device
    mesh, while :meth:`Model.case_pipeline_fn` closes over one design's
    nodes and vmaps over cases only.  ``relax`` is the new-iterate weight
    of the under-relaxed fixed point (reference: 0.8); the sweep drivers'
    non-convergence retry passes a smaller value.
    """
    w = np.asarray(w).astype(dtype)
    k = np.asarray(k).astype(dtype)
    dw = float(w[1] - w[0])
    rho = float(rho)
    depth = float(depth)
    g = float(g)
    nIter = int(nIter)
    XiStart = float(XiStart)

    def one_case(nodes, zeta, beta, C_lin, M_lin, B_lin, F_add_r, F_add_i):
        # full-f32 matmul precision: the TPU's default bf16 matmul passes
        # cost ~3 decimal digits on the RAO (measured 4e-3 L_inf vs 2e-6
        # with this), and the matmuls here are tiny (6x6 solves, [N,3,3]
        # einsums) so the highest-precision path is essentially free
        with jax.default_matmul_precision("highest"):
            u, ud, pD = wave_kinematics(
                zeta.astype(cdtype), beta, w, k, depth, nodes.r,
                rho=rho, g=g, dtype=cdtype,
            )
            F_iner = excitation_froude_krylov(
                nodes, u, ud, pD, rho, mp=mixed_precision_enabled()
            )  # [nw,6]
            Fr = jnp.real(F_iner) + F_add_r
            Fi = jnp.imag(F_iner) + F_add_i
            xr, xi, report = solve_dynamics(
                nodes, u, w, dw, rho, M_lin, B_lin, C_lin, Fr, Fi,
                XiStart, nIter=nIter, checkable=checkable, relax=relax,
            )
        return xr, xi, report

    return one_case


def make_case_phases(w, k, depth, rho, g, XiStart, nIter, dtype, cdtype,
                     relax=0.8):
    """The single-case dynamics split at the fixed-point phase boundaries
    for the convergence-aware engine (raft_tpu/waterfall.py): the SAME
    arithmetic as :func:`make_case_dynamics`'s ``one_case``, factored into

    ``prelude(nodes, zeta, beta, F_add_r, F_add_i) -> (u, Fr, Fi)``
        wave kinematics + Froude-Krylov excitation (loop-invariant), and
    ``phases(nodes, u, C_lin, M_lin, B_lin, Fr, Fi)``
        the :class:`raft_tpu.dynamics.FixedPointPhases` closures over the
        prelude outputs.

    Both run under the same full-f32 matmul-precision context as
    ``one_case`` (the context sets per-op precision at trace time, so
    splitting the trace does not change any op's parameters).
    """
    w = np.asarray(w).astype(dtype)
    k = np.asarray(k).astype(dtype)
    dw = float(w[1] - w[0])
    rho = float(rho)
    depth = float(depth)
    g = float(g)
    nIter = int(nIter)
    XiStart = float(XiStart)

    def prelude(nodes, zeta, beta, F_add_r, F_add_i):
        with jax.default_matmul_precision("highest"):
            u, ud, pD = wave_kinematics(
                zeta.astype(cdtype), beta, w, k, depth, nodes.r,
                rho=rho, g=g, dtype=cdtype,
            )
            F_iner = excitation_froude_krylov(
                nodes, u, ud, pD, rho, mp=mixed_precision_enabled()
            )  # [nw,6]
            Fr = jnp.real(F_iner) + F_add_r
            Fi = jnp.imag(F_iner) + F_add_i
        return u, Fr, Fi

    def phases(nodes, u, C_lin, M_lin, B_lin, Fr, Fi):
        return fixed_point_phases(
            nodes, u, w, dw, rho, M_lin, B_lin, C_lin, Fr, Fi,
            XiStart, nIter=nIter, relax=relax,
        )

    return prelude, phases


class Model:
    """Frequency-domain model of a moored floating wind turbine.

    Parameters
    ----------
    design : dict | path
        RAFT-schema design description (YAML path or parsed dict).
    precision : 'float32' | 'float64' | None
        Working dtype of the device dynamics graph.  Default: f32 on TPU
        (no f64 solver support there), f64 elsewhere.
    device : 'tpu' | 'cpu' | 'gpu' | None
        Backend the batched case dynamics runs on (the north-star
        ``device='tpu'`` switch).  None = JAX's default backend.  The
        precision default follows the *selected* backend, so
        ``Model(design, device='cpu')`` on a TPU host runs an f64 CPU
        solve and ``device='tpu'`` runs the f32 TPU graph.  Host-side
        stages (statics, mooring, rotor BEM) always run f64 on CPU.
    slots : raft_tpu.serve.buckets.BucketSpec | None
        Canonical serving bucket: when given, ``analyze_cases`` pads its
        dynamics dispatch (nodes zero-padded, cases packed into lanes) and
        runs the serving engine's fixed-shape slot executable for that
        bucket instead of compiling a per-design-shape pipeline.  Results
        are then bit-identical to the same request served by
        ``raft_tpu.serve.Engine`` in any megabatch of the bucket (same
        compiled program, per-lane-independent arithmetic — see
        docs/serving.md).  None (default) keeps the exact-shape pipeline,
        whose differently-shaped program may differ from the served path
        by float-reassociation noise.
    """

    def __init__(self, design, nTurbines=1, precision=None, device=None,
                 slots=None):
        if not isinstance(design, dict):
            design = load_design(design)
        self.design = design
        self.nDOF = 6

        settings = design.get("settings") or {}
        min_freq = get_from_dict(settings, "min_freq", default=0.01, dtype=float)
        max_freq = get_from_dict(settings, "max_freq", default=1.00, dtype=float)
        self.XiStart = get_from_dict(settings, "XiStart", default=0.1, dtype=float)
        self.nIter = get_from_dict(settings, "nIter", default=15, dtype=int)

        self.w = np.arange(min_freq, max_freq + 0.5 * min_freq, min_freq) * 2 * np.pi
        self.nw = len(self.w)
        self.dw = self.w[1] - self.w[0]

        site = design["site"]
        self.depth = get_from_dict(site, "water_depth", dtype=float)
        self.rho_water = get_from_dict(site, "rho_water", default=1025.0)
        self.g = get_from_dict(site, "g", default=9.81)

        self.k = _wave_numbers_cached(
            self.w.tobytes(), self.nw, self.depth, self.g
        )

        # members + packed strip nodes
        self.members = process_members(design)
        self.nodes = pack_nodes(self.members)

        # mooring
        self.ms = parse_mooring(design["mooring"], rho_water=self.rho_water, g=self.g)
        self._moor_arrays = self.ms.arrays()
        self._bridle_arrays = self.ms.bridle_arrays()
        self.yawstiff = design["platform"].get("yaw_stiffness", 0.0)

        # turbine lumped properties
        turb = design["turbine"]
        self.mRNA = float(turb["mRNA"])
        self.IrRNA = float(turb["IrRNA"])
        self.hHub = float(turb["hHub"])
        self.aeroServoMod = get_from_dict(turb, "aeroServoMod", default=1)
        self.rotor = None
        if self.aeroServoMod > 0:
            from raft_tpu.aero import Rotor

            rot_cfg = dict(turb)
            rot_cfg["rho_air"] = site["rho_air"]
            rot_cfg["mu_air"] = site["mu_air"]
            rot_cfg["shearExp"] = site["shearExp"]
            self.rotor = Rotor(rot_cfg, self.w)

        # device + precision policy
        if device is not None:
            device = str(device).lower()
            self._sharding = backend_sharding(device)  # raises if absent
        else:
            self._sharding = None
        self.device = device
        backend = device or jax.default_backend()
        if precision is None:
            precision = "float32" if backend == "tpu" else "float64"
        self.precision = precision
        self.dtype = np.float32 if precision == "float32" else np.float64
        self.cdtype = np.complex64 if precision == "float32" else np.complex128

        self.slots = slots
        self.statics = None
        self._ICG_turbine = None
        self.results = {}
        self._pipeline = None
        self.bem_coeffs = None

    # ------------------------------------------------------------------
    # statics / unloaded analysis
    # ------------------------------------------------------------------

    def analyze_unloaded(self, ballast=0, heave_tol=1.0):
        """Unloaded-state properties: statics, undisplaced mooring stiffness,
        equilibrium offsets (reference raft/raft_model.py:109-146)."""
        z6 = jnp.zeros(6, dtype=jnp.float64)
        arr = self._moor_arrays
        C0, F0 = unloaded_mooring_fn()(z6, *arr, self._bridle_arrays)
        self.C_moor0 = np.asarray(C0)
        self.F_moor0 = np.asarray(F0)

        if ballast == 1:
            self.adjust_ballast(heave_tol=heave_tol)
        elif ballast == 2:
            self.adjust_ballast_density()

        with timer("statics"):
            self.statics = compute_statics(
                self.members, self.design["turbine"], self.rho_water, self.g
            )
            self._A_morison = np.asarray(self._added_mass_f64())

        self.results["properties"] = {}
        Xi0 = self._mooring_and_offsets(np.zeros((1, 6)))[0][0]
        self.Xi0_unloaded = Xi0
        self.results["properties"]["offset_unloaded"] = Xi0
        return self.results

    def import_bem(self, file1, file3=None):
        """Load potential-flow radiation/diffraction coefficients from
        WAMIT-format `.1`/`.3` files (the reference's pyHAMS output-reading
        path, raft/raft_fowt.py:394-406; also the WAMIT/Capytaine interop
        route shown by tests/verification.py:240-254), or from a Capytaine
        NetCDF dataset when ``file1`` ends in ``.nc``.  Members flagged
        ``potMod`` are already excluded from strip-theory inertial terms via
        the packed ``strip_mask``."""
        if str(file1).endswith(".nc"):
            from raft_tpu.bem import read_capytaine_nc

            if file3 is not None:
                raise ValueError(
                    "import_bem: a Capytaine .nc dataset carries both "
                    "radiation and excitation data; no second file expected"
                )
            self.bem_coeffs = read_capytaine_nc(file1)
            return self.bem_coeffs
        from raft_tpu.bem import read_coeffs

        self.bem_coeffs = read_coeffs(
            file1, file3, rho=self.rho_water, g=self.g
        )
        return self.bem_coeffs

    def run_bem(self, headings=(0.0,), nw_bem=24, dz_max=None, da_max=None,
                panels=None, quad="gauss", w_grid=None, irr_removal=True,
                n_devices=None):
        """Run the NATIVE radiation/diffraction panel solver on all potMod
        members (the reference's calcBEM path, raft/raft_fowt.py:318-423,
        with the external Fortran HAMS subprocess replaced by the TPU-native
        solver in raft_tpu/bem_solver.py).

        Coefficients are solved on a coarse grid spanning the model band
        (min_freq_BEM .. max model frequency, reference raft_fowt.py:59-62)
        and interpolated onto the model grid inside the case pipeline exactly
        like imported WAMIT data.  Panel sizes default to the design's
        dz_BEM/da_BEM.

        The device policy follows the Model: the solve runs on
        ``Model(device=...)``'s backend and, when that backend has
        multiple local devices, the frequency batch is sharded across
        all of them (``n_devices`` caps the count; 1 forces the
        single-device path — see solve_bem).
        """
        from raft_tpu.bem_solver import coeffs_from_members

        platform = self.design["platform"]
        dz = dz_max if dz_max is not None else get_from_dict(
            platform, "dz_BEM", default=3.0)
        da = da_max if da_max is not None else get_from_dict(
            platform, "da_BEM", default=2.0)
        if w_grid is not None:
            w_bem = np.asarray(w_grid, float)
        else:
            w_min = 2 * np.pi * get_from_dict(
                platform, "min_freq_BEM", default=self.w[0] / 2 / np.pi)
            w_bem = np.linspace(max(w_min, self.w[0]), self.w[-1], nw_bem)
        self.bem_coeffs = coeffs_from_members(
            [m for m in self.members if m.potMod], w_bem,
            headings_deg=headings, rho=self.rho_water, g=self.g,
            dz_max=dz, da_max=da, panels=panels, quad=quad,
            backend=self.device, depth=self.depth,
            irr_removal=irr_removal, n_devices=n_devices,
        )
        return self.bem_coeffs

    def _added_mass_f64(self):
        nodes64 = put_cpu(self.nodes.astype(np.float64))
        return added_mass_morison(nodes64, self.rho_water)

    def _body_props(self):
        st = self.statics
        return (
            np.float64(st.mass),
            np.float64(st.V),
            np.asarray(st.rCG_TOT, np.float64),
            np.array([0.0, 0.0, st.zMeta]),
            np.float64(st.AWP),
        )

    def _mooring_and_offsets(self, F_aero0):
        """Mean offsets + linearized mooring for a batch of mean-load
        vectors [ncase, 6] (reference raft/raft_model.py:332-392), through
        the module-level cached jitted executable (mooring.
        case_mooring_batch_fn — one compile serves every Model with the
        same physics scalars and array shapes)."""
        F_aero0 = np.atleast_2d(F_aero0)
        fn = case_mooring_batch_fn(self.rho_water, self.g, self.yawstiff)
        args = put_cpu(
            (np.asarray(F_aero0, np.float64),) + self._body_props()
        ) + self._moor_arrays + (self._bridle_arrays,)
        out = fn(*args)
        return tuple(np.asarray(o) for o in out)

    # ------------------------------------------------------------------
    # eigen analysis
    # ------------------------------------------------------------------

    def solve_eigen(self, display=1):
        """Rigid-body natural frequencies and modes
        (reference raft/raft_model.py:396-501)."""
        st = self.statics
        M_tot = st.M_struc + self._A_morison
        C_tot = (st.C_struc + st.C_hydro + self.C_moor0).copy()
        C_tot[5, 5] += self.yawstiff

        for i in range(6):
            if M_tot[i, i] < 1.0 or C_tot[i, i] < 1.0:
                raise RuntimeError(
                    f"System matrices have small/negative diagonal at DOF {i}: "
                    f"M={M_tot[i, i]:.3g} C={C_tot[i, i]:.3g}"
                )

        eigenvals, eigenvectors = np.linalg.eig(np.linalg.solve(M_tot, C_tot))
        if np.any(eigenvals <= 0.0):
            raise RuntimeError("zero or negative system eigenvalues detected")

        # greedy DOF-dominance sorting, rotational DOFs claimed first
        # (reference raft_model.py:434-449)
        ind_list = []
        for i in range(5, -1, -1):
            vec = np.abs(eigenvectors[i, :]).copy()
            for _ in range(6):
                ind = int(np.argmax(vec))
                if ind in ind_list:
                    vec[ind] = 0.0
                else:
                    ind_list.append(ind)
                    break
        ind_list.reverse()

        fns = np.sqrt(np.real(eigenvals[ind_list])) / 2.0 / np.pi
        modes = np.real(eigenvectors[:, ind_list])

        if display:
            print("\n--------- Natural frequencies and mode shapes -------------")
            print("Mode        1         2         3         4         5         6")
            print("Fn (Hz)" + "".join(f"{fn:10.4f}" for fn in fns))
            for i in range(6):
                print(f"DOF {i+1}  " + "".join(f"{modes[i, j]:10.4f}" for j in range(6)))
            print("-----------------------------------------------------------")

        self.results["eigen"] = {"frequencies": fns, "modes": modes}
        return fns, modes

    # ------------------------------------------------------------------
    # case analysis (the hot path)
    # ------------------------------------------------------------------

    def _case_arrays(self, cases):
        """Extract batched case parameters."""
        ncase = len(cases)
        spec = np.zeros(ncase, int)
        height = np.zeros(ncase)
        period = np.ones(ncase)
        beta = np.zeros(ncase)
        wind = np.zeros(ncase)
        for i, c in enumerate(cases):
            s = str(c.get("wave_spectrum", "unit"))
            if s not in _SPECTRUM_CODES:
                raise ValueError(f"Wave spectrum input '{s}' not recognized.")
            spec[i] = _SPECTRUM_CODES[s]
            height[i] = float(c.get("wave_height", 0.0))
            period[i] = float(c.get("wave_period", 1.0))
            # wave heading is given in degrees in the design schema
            beta[i] = np.deg2rad(float(c.get("wave_heading", 0.0)))
            wind[i] = float(c.get("wind_speed", 0.0))
        return spec, height, period, beta, wind

    def _zeta(self, spec, height, period):
        """Wave amplitude spectra per case [ncase, nw] (f64 host)."""
        return np.asarray(
            make_wave_spectrum(
                self.w[None, :], spec[:, None], height[:, None], period[:, None]
            )
        )

    def aero_case_means(self, cases, wind, ptfm_pitch=0.0):
        """Per-case mean rotor loads at the PRP at a given platform pitch
        (the reference's first calcTurbineConstants pass,
        raft/raft_model.py:504-513); zero rows for wind-free cases or aero
        off.  Shared by prepare_case_inputs and the fused sweep's
        design-independent first pass (sweep_fused.py)."""
        rHub = np.array([0.0, 0.0, self.hHub])
        F = np.zeros((len(cases), 6))
        if self.rotor is None or self.aeroServoMod <= 0:
            return F
        for i, case in enumerate(cases):
            if wind[i] > 0.0:
                F0_hub, _, _, _ = self.rotor.calc_aero_servo_contributions(
                    case, ptfm_pitch=ptfm_pitch
                )
                F[i] = np.asarray(transform_force(F0_hub, offset=rHub))
        return F

    def case_pipeline_fn(self, checkable=False, wrap=None):
        """The (un-jitted) batched device function for the case dynamics:
        (zeta[nc,nw], beta[nc], C_lin[nc,6,6], M_lin[nc,nw,6,6],
        B_lin[nc,nw,6,6], F_add_r[nc,nw,6], F_add_i[nc,nw,6])
        -> (Xi_r[nc,6,nw], Xi_i[nc,6,nw], SolveReport with [nc] fields).

        Exposed separately so the driver entry point and the multichip dryrun
        can jit it with explicit shardings.  ``wrap`` is applied to the
        single-case closure before the vmap (the checkify hook used by
        raft_tpu.validate.checked_pipeline, which also sets ``checkable``
        for the scan-based fixed point)."""
        one_case = make_case_dynamics(
            self.w, self.k, self.depth, self.rho_water, self.g,
            self.XiStart, self.nIter, self.dtype, self.cdtype,
            checkable=checkable,
        )
        nodes = self.nodes.astype(self.dtype)
        fn = lambda *a: one_case(nodes, *a)  # noqa: E731
        if wrap is not None:
            fn = wrap(fn)
        return jax.vmap(fn)

    def _build_pipeline(self):
        """The single jitted device graph: [case] -> Xi, SolveReport.

        The RAFT_TPU_DEBUG_NANS=1 environment switch enables
        ``jax_debug_nans`` and selects the scan-based checkable fixed
        point (the variant jax.experimental.checkify supports — see
        raft_tpu.validate.checked_pipeline)."""
        return jax.jit(self.case_pipeline_fn(checkable=apply_debug_nans()))

    def prepare_case_inputs(self, cases=None, verbose=True):
        """Host-side setup for the batched case solve: per-case aero means,
        mooring equilibrium/linearization, and assembly of the linear-term
        arrays (reference solveStatics + the pre-sums at
        raft/raft_model.py:504-555).

        Returns (args, aux): ``args`` is the input tuple for
        :meth:`case_pipeline_fn` (all NumPy, working dtype); ``aux`` carries
        the per-case quantities the output stage needs.
        """
        if cases is None:
            cases = cases_as_dicts(self.design)
        ncase = len(cases)
        if ncase == 0:
            raise ValueError("design has no cases table")
        if self.statics is None:
            self.analyze_unloaded()

        st = self.statics

        spec, height, period, beta, wind = self._case_arrays(cases)
        zeta = self._zeta(spec, height, period)

        # ---- per-case aero means at zero platform pitch
        # (reference solveStatics first pass, raft_model.py:504-513) ----
        rHub = np.array([0.0, 0.0, self.hHub])
        aero_on = (
            self.rotor is not None
            and self.aeroServoMod > 0
        )
        F_aero0 = self.aero_case_means(cases, wind)

        # ---- mean offsets & linearized mooring, all cases in one jitted
        # vmapped CPU f64 call ----
        with timer("mooring_offsets"):
            Xi0, C_moor, _, T_moor, J_moor, moor_resid = (
                self._mooring_and_offsets(F_aero0))
        warn_bridle_residual(moor_resid, label="case")
        if verbose:
            for i in range(ncase):
                print(
                    f"Case {i+1}: mean offsets surge={Xi0[i,0]:.2f} m, "
                    f"pitch={Xi0[i,4]*_RAD2DEG:.2f} deg"
                )

        # ---- re-run aero at the mean platform pitch (reference
        # solveStatics second pass, raft_model.py:516-517) and build the
        # frequency-dependent hub added mass / damping matrices ----
        M_hub = np.zeros((ncase, self.nw, 6, 6))
        B_hub = np.zeros((ncase, self.nw, 6, 6))
        self._rotor_case = [None] * ncase
        for i, case in enumerate(cases):
            if aero_on and wind[i] > 0.0:
                F0_hub, f_a, a_a, b_a = self.rotor.calc_aero_servo_contributions(
                    case, ptfm_pitch=Xi0[i, 4]
                )
                F_aero0[i] = np.asarray(transform_force(F0_hub, offset=rHub))
                diag_a = np.zeros((self.nw, 3, 3))
                diag_a[:, 0, 0] = a_a
                diag_b = np.zeros((self.nw, 3, 3))
                diag_b[:, 0, 0] = b_a
                M_hub[i] = np.asarray(translate_matrix_3to6(diag_a, rHub))
                B_hub[i] = np.asarray(translate_matrix_3to6(diag_b, rHub))
                self._rotor_case[i] = dict(
                    C=np.array(self.rotor.C),
                    V_w=np.array(self.rotor.V_w),
                    kp_beta=getattr(self.rotor, "kp_beta", 0.0),
                    ki_beta=getattr(self.rotor, "ki_beta", 0.0),
                    Omega_case=self.rotor.Omega_case,
                    pitch_case=self.rotor.pitch_case,
                    aero_torque=self.rotor.aero_torque,
                    aero_power=self.rotor.aero_power,
                    A00=M_hub[i, :, 0, 0].copy(),
                    B00=B_hub[i, :, 0, 0].copy(),
                    F_aero0=F_aero0[i].copy(),
                )
        # NOTE: turbulent wind excitation f_a is computed but, like the
        # reference (raft_model.py:547-549), NOT applied in the wave-response
        # solve; it feeds only the rotor output spectra.

        M_lin = (
            st.M_struc[None, None, :, :] + self._A_morison[None, None, :, :] + M_hub
        ).astype(self.dtype)
        B_lin = B_hub.astype(self.dtype)
        C_lin = (
            st.C_struc[None, :, :] + st.C_hydro[None, :, :] + C_moor
        ).astype(self.dtype)
        F_add_r = np.zeros((ncase, self.nw, 6), self.dtype)  # BEM excitation slot
        F_add_i = np.zeros((ncase, self.nw, 6), self.dtype)

        # ---- potential-flow coefficients (reference raft_fowt.py:486-495:
        # A_BEM/B_BEM join the frequency-dependent linear terms and
        # F_BEM = X_BEM * zeta joins the excitation) ----
        if self.bem_coeffs is not None:
            from raft_tpu.bem import interp_to_grid

            # A/B are case-independent; only the excitation heading varies
            A_bem, B_bem, _ = interp_to_grid(self.bem_coeffs, self.w)
            M_lin += A_bem.astype(self.dtype)[None]
            B_lin += B_bem.astype(self.dtype)[None]
            for i in range(ncase):
                _, _, X_bem = interp_to_grid(
                    self.bem_coeffs, self.w, beta=np.rad2deg(beta[i])
                )
                F_bem = X_bem * zeta[i][:, None]
                F_add_r[i] = np.real(F_bem).astype(self.dtype)
                F_add_i[i] = np.imag(F_bem).astype(self.dtype)

        args = (
            zeta.astype(self.dtype),
            beta.astype(self.dtype),
            C_lin,
            M_lin,
            B_lin,
            F_add_r,
            F_add_i,
        )
        aux = dict(
            cases=cases, ncase=ncase, zeta=zeta, Xi0=Xi0,
            T_moor=T_moor, J_moor=J_moor, F_aero0=F_aero0,
        )
        return args, aux

    def analyze_cases(self, display=0, runPyHAMS=False, meshDir=None,
                      tracer=None, solver=None):
        """Run all load cases: per-case statics (aero means + mooring
        equilibrium), batched dynamics solve, and response metrics
        (reference raft/raft_model.py:149-309).

        ``solver``: optional replacement for the batched dynamics
        dispatch — a callable ``(model, args, aux) -> (xr, xi, report)``
        returning host arrays ([ncase,6,nw] response halves and a
        ``SolveReport`` over [ncase]).  Used by the OpenMDAO component's
        engine mode to route the solve through a running serve engine
        (local or HTTP) while keeping every host-side metric stage here;
        the served solve is bit-identical to the same design dispatched
        through ``Model(..., slots=bucket)`` (the engine's canonical
        fixed-shape program) and agrees with the unslotted in-process
        dispatch to float64 round-off.

        runPyHAMS=True triggers the potential-flow solve on potMod members
        before the case batch, like the reference's calcBEM call
        (raft_model.py:235-236) — here via the native panel solver; an
        external HAMS/WAMIT output can be loaded with import_bem instead.

        ``tracer`` (raft_tpu.trace.Tracer, created per call when None)
        records the stage timeline — host prep vs the device dispatch —
        surfaced as ``results["stage_spans"]`` and dumped as a
        chrome://tracing JSON when RAFT_TPU_TRACE is set (the same
        instrumentation the sweep drivers use for the CPU/TPU overlap).
        """
        from raft_tpu.trace import Tracer

        tracer = tracer or Tracer("analyze_cases")
        if runPyHAMS and any(m.potMod for m in self.members):
            if self.bem_coeffs is None:
                # solve at every distinct case wave heading so off-axis
                # cases get their own excitation column (interp_to_grid
                # interpolates between tabulated headings per case); the
                # set is expanded to a uniform grid because the HAMS
                # control file format (and preprocess_hams) describes
                # headings as min/step/count
                headings = _uniform_heading_grid(
                    float(c.get("wave_heading", 0.0))
                    for c in cases_as_dicts(self.design)
                )
                if meshDir:  # also write the HAMS/WAMIT tree there
                    self.preprocess_hams(mesh_dir=meshDir, headings=headings)
                else:
                    self.run_bem(headings=headings)
            elif meshDir:
                logger.warning(
                    "analyze_cases: BEM coefficients already loaded; "
                    "meshDir ignored — call preprocess_hams() directly to "
                    "write the HAMS/WAMIT tree"
                )
        with tracer.span("case_prep", backend="cpu"):
            args, aux = self.prepare_case_inputs()
        cases = aux["cases"]
        ncase = aux["ncase"]
        zeta = aux["zeta"]
        Xi0 = aux["Xi0"]
        T_moor = aux["T_moor"]
        J_moor = aux["J_moor"]
        F_aero0 = aux["F_aero0"]
        # tension channels: trunk lines plus bridle legs (padded slots
        # report zeros) — T_moor is [ncase, 2 (nL + nB K)]
        nLines = T_moor.shape[-1] // 2

        # ---- the batched device solve ----
        if solver is not None:
            # delegated solve (e.g. through a serve engine): the caller
            # owns dispatch; statics above and metrics below stay local
            with timer("rao_solve"), tracer.span(
                    "dynamics", backend="engine"):
                xr, xi, report = solver(self, args, aux)
        elif self.slots is not None:
            # serving-bucket mode: the dispatch runs the canonical
            # fixed-shape slot executable of this bucket, shared with the
            # raft_tpu.serve engine — results bit-identical to the same
            # request served in any megabatch of the bucket
            from raft_tpu.serve.buckets import slotted_case_dispatch

            with timer("rao_solve"), tracer.span(
                    "dynamics", backend=jax.default_backend()):
                xr, xi, report = slotted_case_dispatch(
                    self, self.slots, args)
        elif _fixed_point_engine_requested():
            # convergence-aware engine (RAFT_TPU_FIXED_POINT=waterfall|
            # fused): fixed K-iteration blocks with active-lane
            # compaction, per-lane bit-identical to the legacy pipeline
            # (raft_tpu/waterfall.py); the checkable debug pipeline
            # always keeps the legacy reference dispatch
            from raft_tpu.waterfall import waterfall_case_dispatch

            with timer("rao_solve"), tracer.span(
                    "dynamics", backend=jax.default_backend()):
                xr, xi, report = waterfall_case_dispatch(self, args)
        else:
            if self._pipeline is None:
                with timer("pipeline_compile"):
                    self._pipeline = self._build_pipeline()
            with timer("rao_solve"), tracer.span(
                    "dynamics", backend=jax.default_backend()):
                if self._sharding is not None:
                    # committed inputs pin the jitted graph to the
                    # requested backend (jit follows input placement)
                    dev_args = tuple(
                        jax.device_put(np.asarray(a), self._sharding)
                        for a in args
                    )
                else:
                    dev_args = tuple(jnp.asarray(a) for a in args)
                xr, xi, report = self._pipeline(*dev_args)
                jax.block_until_ready(xr)
        Xi = np.asarray(xr, np.float64) + 1j * np.asarray(xi, np.float64)  # [case,6,nw]
        self.Xi = Xi
        self.zeta = zeta
        # solver health: per-case report surfaced in the results dict and
        # routed through the package logger (callers can silence/capture
        # it; the reference's equivalent is a bare print,
        # raft/raft_model.py:603-611)
        report = report_to_numpy(report)
        self.solve_report = report
        self.results["solve_report"] = report_dict(report)
        log_report(report, label="case", log=logger)

        # ---- response metrics (reference raft_fowt.py:706-833 and
        # raft_model.py:158-309) ----
        self._init_case_metrics(ncase, nLines)
        m = self.results["case_metrics"]
        from raft_tpu.fatigue import dirlik_del

        # S-N slopes for the fatigue channels (settings overridable):
        # welded steel tower m=4, mooring chain m=3 (DNV-OS-E301 defaults)
        settings = self.design.get("settings") or {}
        m_tower = get_from_dict(settings, "wohler_exp_tower", default=4.0)
        m_chain = get_from_dict(settings, "wohler_exp_mooring", default=3.0)
        for i in range(ncase):
            self._save_case_outputs(m, i, Xi0[i], Xi[i], zeta[i], cases[i])
            # the reference zero-fills the DEL channels (raft_model.py:199);
            # here they are real: Dirlik spectral rainflow on the response
            # PSDs, 1 Hz reference cycle rate
            m["Mbase_DEL"][i] = dirlik_del(m["Mbase_PSD"][i], self.w, m_tower)
            # mooring tension spectra: T_amps = J_moor @ Xi
            T_amps = J_moor[i] @ Xi[i]  # [2nL, nw]
            m["Tmoor_avg"][i] = T_moor[i]
            for iT in range(2 * nLines):
                TRMS = float(np.sqrt(np.sum(np.abs(T_amps[iT]) ** 2) * self.w[0]))
                m["Tmoor_std"][i, iT] = TRMS
                m["Tmoor_max"][i, iT] = T_moor[i, iT] + 3 * TRMS
                m["Tmoor_PSD"][i, iT] = np.abs(T_amps[iT]) ** 2
                m["Tmoor_DEL"][i, iT] = dirlik_del(
                    m["Tmoor_PSD"][i, iT], self.w, m_chain
                )
            if display:
                self._print_case_stats(i, nLines)

        self.results["means"] = {
            "aero force": F_aero0,
            "platform offset": Xi0,
        }
        self.results["response"] = {}
        return self.results

    def _init_case_metrics(self, ncase, nLines):
        m = {}
        for ch in ["surge", "sway", "heave", "roll", "pitch", "yaw", "AxRNA",
                   "Mbase", "omega", "torque", "power", "bPitch"]:
            m[f"{ch}_avg"] = np.zeros(ncase)
            m[f"{ch}_std"] = np.zeros(ncase)
            m[f"{ch}_max"] = np.zeros(ncase)
            m[f"{ch}_PSD"] = np.zeros((ncase, self.nw))
        m["Mbase_DEL"] = np.zeros(ncase)
        for ch in ["Tmoor_avg", "Tmoor_std", "Tmoor_max", "Tmoor_DEL"]:
            m[ch] = np.zeros((ncase, 2 * nLines))
        m["Tmoor_PSD"] = np.zeros((ncase, 2 * nLines, self.nw))
        m["wind_PSD"] = np.zeros((ncase, self.nw))
        m["wave_PSD"] = np.zeros((ncase, self.nw))
        self.results["case_metrics"] = m

    def _save_case_outputs(self, m, iCase, Xi0, Xi, zeta, case):
        """Platform/turbine response statistics for one case
        (reference raft/raft_fowt.py:706-833)."""
        st = self.statics
        dw = self.dw
        w = self.w

        def rms(x):
            # plain NumPy: host post-processing must not dispatch eager ops
            # to the TPU backend (no complex support there)
            return float(np.sqrt(np.sum(np.abs(np.asarray(x)) ** 2) * dw))

        for j, ch in enumerate(["surge", "sway", "heave"]):
            m[f"{ch}_avg"][iCase] = Xi0[j]
            m[f"{ch}_std"][iCase] = rms(Xi[j])
            m[f"{ch}_PSD"][iCase] = np.abs(Xi[j]) ** 2
        m["surge_max"][iCase] = Xi0[0] + 3 * m["surge_std"][iCase]
        # reference quirk: sway_max built from heave_std (raft_fowt.py:716)
        m["sway_max"][iCase] = Xi0[1] + 3 * m["heave_std"][iCase]
        m["heave_max"][iCase] = Xi0[2] + 3 * m["heave_std"][iCase]

        for j, ch in zip([3, 4, 5], ["roll", "pitch", "yaw"]):
            deg = Xi[j] * _RAD2DEG
            m[f"{ch}_avg"][iCase] = Xi0[j] * _RAD2DEG
            m[f"{ch}_std"][iCase] = rms(deg)
            m[f"{ch}_max"][iCase] = Xi0[j] * _RAD2DEG + 3 * m[f"{ch}_std"][iCase]
            m[f"{ch}_PSD"][iCase] = np.abs(deg) ** 2

        XiHub = Xi[0] + self.hHub * Xi[4]
        m["AxRNA_std"][iCase] = rms(XiHub * w**2)
        m["AxRNA_PSD"][iCase] = np.abs(XiHub * w**2) ** 2

        # tower-base bending moment (reference raft_fowt.py:748-769);
        # the case-invariant tower inertia terms are cached across cases
        m_turbine = st.mtower + self.mRNA
        zCG_turbine = (st.rCG_tow[2] * st.mtower + self.hHub * self.mRNA) / m_turbine
        tower = self.members[-1]
        zBase = tower.rA[2]
        hArm = zCG_turbine - zBase
        aCG = -(w**2) * (Xi[0] + zCG_turbine * Xi[4])
        if getattr(self, "_ICG_turbine", None) is None:
            M_tower = member_inertia(tower)[0]
            self._ICG_turbine = (
                np.asarray(
                    translate_matrix_6to6(M_tower, np.array([0.0, 0.0, -zCG_turbine]))
                )[4, 4]
                + self.mRNA * (self.hHub - zCG_turbine) ** 2
                + self.IrRNA
            )
        ICG_turbine = self._ICG_turbine
        rc = self._rotor_case[iCase] if hasattr(self, "_rotor_case") else None
        M_I = -m_turbine * aCG * hArm - ICG_turbine * (-(w**2) * Xi[4])
        M_w = m_turbine * self.g * hArm * Xi[4]
        # M_F_aero is zeroed like the reference (raft_fowt.py:760); the aero
        # reaction moment uses the hub fore-aft a(w)/b(w)
        M_X_aero = 0.0
        F_aero0_case = np.zeros(6)
        if rc is not None:
            M_X_aero = (
                -(-(w**2) * rc["A00"] + 1j * w * rc["B00"])
                * (self.hHub - zBase) ** 2 * Xi[4]
            )
            F_aero0_case = rc["F_aero0"]
        dynamic_moment = M_I + M_w + M_X_aero
        m["Mbase_avg"][iCase] = m_turbine * self.g * hArm * np.sin(Xi0[4]) + np.asarray(
            transform_force(F_aero0_case, offset=np.array([0.0, 0.0, -hArm]))
        )[4]
        m["Mbase_std"][iCase] = rms(dynamic_moment)
        m["Mbase_max"][iCase] = m["Mbase_avg"][iCase] + 3 * m["Mbase_std"][iCase]
        m["Mbase_PSD"][iCase] = np.abs(dynamic_moment) ** 2

        m["wave_PSD"][iCase] = np.abs(zeta) ** 2

        # rotor/control output spectra (reference raft_fowt.py:797-833)
        if rc is not None and self.aeroServoMod > 1 and case.get("wind_speed", 0) > 0:
            from raft_tpu.aero import _RPM2RADPS

            radps2rpm = 1.0 / _RPM2RADPS
            phi_w = rc["C"] * (XiHub - rc["V_w"] / (1j * w))
            omega_w = 1j * w * phi_w
            m["omega_avg"][iCase] = rc["Omega_case"]
            m["omega_std"][iCase] = radps2rpm * rms(omega_w)
            m["omega_max"][iCase] = m["omega_avg"][iCase] + 2 * m["omega_std"][iCase]
            m["omega_PSD"][iCase] = radps2rpm**2 * np.abs(omega_w) ** 2
            torque_w = (
                1j * w * self.rotor.kp_tau + self.rotor.ki_tau
            ) * phi_w
            m["torque_avg"][iCase] = rc["aero_torque"] / self.rotor.Ng
            m["torque_std"][iCase] = rms(torque_w)
            m["torque_PSD"][iCase] = np.abs(torque_w) ** 2
            m["power_avg"][iCase] = rc["aero_power"]
            bPitch_w = (1j * w * rc["kp_beta"] + rc["ki_beta"]) * phi_w
            m["bPitch_avg"][iCase] = rc["pitch_case"]
            m["bPitch_std"][iCase] = _RAD2DEG * rms(bPitch_w)
            m["bPitch_PSD"][iCase] = _RAD2DEG**2 * np.abs(bPitch_w) ** 2
            m["wind_PSD"][iCase] = np.abs(rc["V_w"]) ** 2

    def _print_case_stats(self, i, nLines):
        m = self.results["case_metrics"]
        print(f"-------------------- Case {i+1} Statistics --------------------")
        print("Response channel     Average     RMS         Maximum")
        for ch, unit in [("surge", "m"), ("sway", "m"), ("heave", "m"),
                         ("roll", "deg"), ("pitch", "deg"), ("yaw", "deg")]:
            print(
                f"{ch+' ('+unit+')':19s}{m[ch+'_avg'][i]:10.2e}  "
                f"{m[ch+'_std'][i]:10.2e}  {m[ch+'_max'][i]:10.2e}"
            )
        print(
            f"{'nacelle acc. (m/s)':19s}{m['AxRNA_avg'][i]:10.2e}  "
            f"{m['AxRNA_std'][i]:10.2e}  {m['AxRNA_max'][i]:10.2e}"
        )
        print(
            f"{'tower bending (Nm)':19s}{m['Mbase_avg'][i]:10.2e}  "
            f"{m['Mbase_std'][i]:10.2e}  {m['Mbase_max'][i]:10.2e}"
        )
        for j in range(nLines):
            jj = j + nLines
            print(
                f"line {j+1} tension (N) {m['Tmoor_avg'][i, jj]:10.2e}  "
                f"{m['Tmoor_std'][i, jj]:10.2e}  {m['Tmoor_max'][i, jj]:10.2e}"
            )
        print("-----------------------------------------------------------")

    # ------------------------------------------------------------------
    # outputs
    # ------------------------------------------------------------------

    def calc_outputs(self):
        """Populate results['properties'] and results['response']
        (reference raft/raft_model.py:660-725)."""
        st = self.statics
        if "properties" in self.results:
            p = self.results["properties"]
            p["tower mass"] = st.mtower
            p["tower CG"] = st.rCG_tow
            p["substructure mass"] = st.msubstruc
            p["substructure CG"] = st.rCG_sub
            p["shell mass"] = st.mshell
            p["ballast mass"] = st.mballast
            p["ballast densities"] = st.pb
            p["total mass"] = st.mass
            p["total CG"] = st.rCG_TOT
            p["roll inertia at subCG"] = st.M_struc_subCM[3, 3]
            p["pitch inertia at subCG"] = st.M_struc_subCM[4, 4]
            p["yaw inertia at subCG"] = st.M_struc_subCM[5, 5]
            p["Buoyancy (pgV)"] = self.rho_water * self.g * st.V
            p["Center of Buoyancy"] = st.rCB
            p["C stiffness matrix"] = st.C_hydro
            p["F_lines0"] = self.F_moor0
            p["C_lines0"] = self.C_moor0
            p["M support structure"] = st.M_struc_subCM
            A_support = self._A_morison.copy()
            if self.bem_coeffs is not None:
                # reference adds the highest-frequency BEM added mass
                # (raft_model.py:697: A_BEM[:,:,-1])
                from raft_tpu.bem import interp_to_grid

                A_bem, _, _ = interp_to_grid(self.bem_coeffs, self.w)
                A_support = A_support + A_bem[-1]
            p["A support structure"] = A_support
            p["C support structure"] = st.C_struc_sub + st.C_hydro + self.C_moor0

        if hasattr(self, "Xi"):
            r = self.results.setdefault("response", {})
            with np.errstate(divide="ignore", invalid="ignore"):
                # bins where the wave spectrum underflows to exactly zero
                # (far tails of JONSWAP) carry zero response too; report a
                # zero RAO there instead of the reference's 0/0 NaN
                # (raft_model.py:707)
                zeta = np.where(np.abs(self.zeta) > 0, self.zeta, np.nan)
                RAOmag = np.abs(self.Xi / zeta[:, None, :])  # [case, 6, nw]
                RAOmag = np.where(np.isfinite(RAOmag), RAOmag, 0.0)
            r["frequencies"] = self.w / 2 / np.pi
            r["wave elevation"] = self.zeta
            r["Xi"] = self.Xi
            r["surge RAO"] = RAOmag[:, 0]
            r["sway RAO"] = RAOmag[:, 1]
            r["heave RAO"] = RAOmag[:, 2]
            # reference key/index mismatch kept: 'pitch RAO' holds DOF 3 and
            # 'roll RAO' holds DOF 4 (raft_model.py:715-716)
            r["pitch RAO"] = RAOmag[:, 3]
            r["roll RAO"] = RAOmag[:, 4]
            r["yaw RAO"] = RAOmag[:, 5]
            r["nacelle acceleration"] = (
                self.w**2 * (self.Xi[:, 0] + self.Xi[:, 4] * self.hHub)
            )
        return self.results

    # ------------------------------------------------------------------
    # ballast adjustment
    # ------------------------------------------------------------------

    def adjust_ballast(self, heave_tol=1.0):
        """Adjust member ballast fill levels to trim unloaded heave within
        heave_tol (reference raft/raft_model.py:827-979 adjustBallast).

        Divergence from the reference: each candidate section's fill length
        is found by exact inversion of the frustum volume (bisection to
        machine precision) instead of the reference's 0.01 m incremental
        crawl; the member/section iteration order and the replication across
        heading copies follow the reference.
        """
        z6 = jnp.zeros(6, dtype=jnp.float64)
        F_moor0 = np.asarray(
            line_forces(z6, *self._moor_arrays, self._bridle_arrays)[0])

        def heave_imbalance():
            st = compute_statics(
                self.members, self.design["turbine"], self.rho_water, self.g
            )
            sumFz = -st.mass * self.g + st.V * self.rho_water * self.g + F_moor0[2]
            return sumFz / (self.rho_water * self.g * st.AWP), st

        heave, st = heave_imbalance()
        i = 0
        while i < len(self.members) and abs(heave) > heave_tol:
            mem = self.members[i]
            headings = np.atleast_1d(mem.headings)
            n_copies = len(headings)
            if mem.heading != headings[0]:
                i += 1
                continue
            rho_fills = np.atleast_1d(mem.rho_fill).astype(float)
            l_fills = np.atleast_1d(np.asarray(mem.l_fill, float) * np.ones_like(rho_fills))
            for j, rho_b in enumerate(rho_fills):
                if rho_b <= 0:
                    continue
                dmass = (
                    st.V * self.rho_water * self.g + F_moor0[2]
                ) / self.g - st.mass
                mdvol = dmass / rho_b / n_copies
                # exact l_fill giving current volume + mdvol in this section
                if mem.circular:
                    dAi = mem.d[j] - 2 * mem.t[j]
                    dBi = mem.d[j + 1] - 2 * mem.t[j + 1]
                else:
                    dAi = mem.sl[j] - 2 * mem.t[j]
                    dBi = mem.sl[j + 1] - 2 * mem.t[j + 1]
                l = mem.l
                from raft_tpu.statics import _vcv_circ, _vcv_rect

                def vol(lf):
                    if mem.circular:
                        dBf = (dBi - dAi) * (lf / l) + dAi
                        return _vcv_circ(dAi, dBf, lf)[0]
                    dBf = (dBi - dAi) * (lf / l) + dAi
                    return _vcv_rect(dAi, dBf, lf)[0]

                target = vol(l_fills[j]) + mdvol
                lo, hi = 0.0, l
                if target <= 0:
                    lf = 0.0
                elif target >= vol(l):
                    lf = l
                else:
                    for _ in range(60):
                        mid = 0.5 * (lo + hi)
                        if vol(mid) < target:
                            lo = mid
                        else:
                            hi = mid
                    lf = round(0.5 * (lo + hi), 2)
                for kcopy in range(n_copies):
                    other = self.members[i + kcopy]
                    if np.isscalar(other.l_fill):
                        other.l_fill = lf
                    else:
                        other.l_fill = np.asarray(other.l_fill, float)
                        other.l_fill[j] = lf
                heave, st = heave_imbalance()
                if abs(heave) < heave_tol:
                    break
            i += 1
        print(f"Ballast adjustment done; residual heave imbalance {heave:.3f} m")
        return heave

    def adjust_ballast_density(self):
        """Uniformly adjust ballast densities to zero the unloaded heave
        (reference raft/raft_model.py:982-1037)."""
        z6 = jnp.zeros(6, dtype=jnp.float64)
        F_moor0 = np.asarray(
            line_forces(z6, *self._moor_arrays, self._bridle_arrays)[0])

        for mem in self.members:
            if np.isscalar(mem.l_fill):
                if mem.rho_fill == 0.0:
                    mem.l_fill = 0.0
            else:
                mem.l_fill = np.where(
                    np.atleast_1d(mem.rho_fill) == 0.0, 0.0, mem.l_fill
                )

        st = compute_statics(
            self.members, self.design["turbine"], self.rho_water, self.g
        )
        sumFz = -st.mass * self.g + st.V * self.rho_water * self.g + F_moor0[2]
        ballast_volume = sum(sum(v) for v in st.member_vfill)
        if ballast_volume <= 0:
            raise RuntimeError("adjust_ballast_density needs nonzero ballast volume")
        delta_rho = sumFz / self.g / ballast_volume
        print(f"Adjusting ballast density by {delta_rho:.3f} kg/m^3")
        for mem in self.members:
            if np.isscalar(mem.l_fill):
                if mem.l_fill > 0.0:
                    mem.rho_fill = mem.rho_fill + delta_rho
            else:
                lf = np.atleast_1d(mem.l_fill)
                rf = np.atleast_1d(np.asarray(mem.rho_fill, float) * np.ones_like(lf))
                mem.rho_fill = np.where(lf > 0.0, rf + delta_rho, rf)
        return delta_rho

    # ------------------------------------------------------------------
    # HAMS/OpenFAST interop
    # ------------------------------------------------------------------

    def preprocess_hams(self, dw=0, wMax=0, dz=0, da=0, mesh_dir="BEM",
                        headings=(0.0,), nw_bem=24):
        """Generate the HAMS working tree (Input/HullMesh.pnl,
        ControlFile.in, Hydrostatic.in) and WAMIT-format ``.1``/``.3``
        output files for OpenFAST handoff (reference
        raft/raft_model.py:769-790 preprocess_HAMS + raft_fowt.py:349-391),
        with the Fortran HAMS run replaced by the native panel solver.

        The tree is drop-in compatible: point an external HAMS build at
        ``mesh_dir`` to recompute with higher fidelity, then load its
        output with :meth:`import_bem`.
        """
        from raft_tpu.bem import write_wamit_1, write_wamit_3
        from raft_tpu.hams_io import (
            create_hams_dirs,
            write_control_file,
            write_hydrostatic_file,
        )
        from raft_tpu.mesh import dedupe_nodes, mesh_platform, write_pnl

        platform = self.design["platform"]
        dz = dz or get_from_dict(platform, "dz_BEM", default=3.0)
        da = da or get_from_dict(platform, "da_BEM", default=2.0)

        panels = mesh_platform(self.members, dz_max=dz, da_max=da)
        if len(panels) == 0:
            raise RuntimeError(
                "preprocess_hams: no members have potMod=True"
            )
        create_hams_dirs(mesh_dir)
        nodes, conn = dedupe_nodes(panels)
        write_pnl(
            os.path.join(mesh_dir, "Input", "HullMesh.pnl"), nodes, conn
        )
        if self.statics is None:
            self.analyze_unloaded()
        write_hydrostatic_file(mesh_dir, k_hydro=self.statics.C_hydro)
        # solve, then write a control file describing the grid actually
        # solved and emitted into Buoy.1/.3 (they used to advertise
        # different schedules).  Default: the same run_bem grid the
        # analyze_cases(runPyHAMS=True) path uses (min_freq_BEM-bounded
        # nw_bem linspace) so adding meshDir never changes the physics;
        # an explicit dw requests the reference's dw-spaced HAMS schedule
        # (reference raft/raft_fowt.py:381-382).
        if dw:
            dw_hams = float(dw)
            w_max = max(float(wMax), float(self.w[-1]))
            n_sched = int(np.ceil(w_max / dw_hams))
            w_sched = dw_hams * np.arange(1, n_sched + 1)
            coeffs = self.run_bem(
                headings=headings, dz_max=dz, da_max=da,
                panels=panels, w_grid=w_sched,
            )
        else:
            coeffs = self.run_bem(
                headings=headings, nw_bem=nw_bem, dz_max=dz, da_max=da,
                panels=panels,
            )
        wb = np.asarray(coeffs.w)
        dwb = np.diff(wb)
        note = None
        if len(wb) > 1 and not np.allclose(dwb, dwb[0], rtol=1e-6):
            # the solver clamped bins above the mesh-resolution cap, so
            # the emitted grid is not uniform; the schedule below covers
            # the uniform part and the note flags the deviation
            note = (
                f"frequencies above the mesh-resolution cap were clamped:"
                f" Buoy.1/.3 contain {len(wb)} bins ending at"
                f" {wb[-1]:.4f} rad/s"
            )
        dh = np.diff(np.asarray(headings, float))
        if len(dh) > 1 and not np.allclose(dh, dh[0], atol=1e-9):
            hnote = "heading set is non-uniform; see Buoy.3 for exact values"
            note = f"{note}; {hnote}" if note else hnote
        write_control_file(
            mesh_dir, water_depth=self.depth,
            num_freqs=-len(wb),
            min_freq=float(wb[0]),
            d_freq=float(dwb[0]) if len(wb) > 1 else 0.0,
            num_headings=len(headings),
            min_heading=float(headings[0]),
            d_heading=(float(headings[1] - headings[0])
                       if len(headings) > 1 else 0.0),
            note=note,
        )
        out = os.path.join(mesh_dir, "Output", "Wamit_format")
        write_wamit_1(os.path.join(out, "Buoy.1"), coeffs,
                      rho=self.rho_water)
        write_wamit_3(os.path.join(out, "Buoy.3"), coeffs,
                      rho=self.rho_water, g=self.g)
        from raft_tpu.bem import write_wamit_hst

        write_wamit_hst(os.path.join(out, "Buoy.hst"),
                        self.statics.C_hydro, rho=self.rho_water, g=self.g)
        return mesh_dir

    preprocess_HAMS = preprocess_hams

    def adjust_wisdem(self, old_wisdem_file, new_wisdem_file):
        """Write a copy of a WISDEM geometry YAML with each floating-member
        ballast volume updated from this model's trimmed fill levels
        (reference raft/raft_model.py:1040-1090 adjustWISDEM; the WEIS
        ballast handoff after adjust_ballast).

        Members are matched like the reference: same bottom-joint z (to 5
        printed characters) and same first outer diameter; only the first
        ballast entry's volume is updated, assuming a constant diameter
        over the fill (the reference's stated assumption)."""
        import yaml as _yaml

        with open(old_wisdem_file, "r", encoding="utf-8") as f:
            wisdem_design = _yaml.safe_load(f)

        platform = wisdem_design["components"]["floating_platform"]
        joints = {j["name"]: j for j in platform["joints"]}
        for wmem in platform["members"]:
            if "ballasts" not in wmem.get("internal_structure", {}):
                continue
            joint = joints.get(wmem.get("joint1"))
            if joint is None:
                continue
            wd0 = float(np.atleast_1d(
                wmem["outer_shape"]["outer_diameter"]["values"])[0])
            for mem in self.members:
                d0 = float(np.atleast_1d(mem.d)[0])
                if (str(joint["location"][2])[0:5]
                        == str(float(mem.rA[2]))[0:5] and wd0 == d0):
                    t0 = float(np.atleast_1d(mem.t)[0])
                    area = np.pi * ((d0 - 2 * t0) / 2) ** 2
                    lf0 = float(np.atleast_1d(mem.l_fill)[0])
                    wmem["internal_structure"]["ballasts"][0]["volume"] = (
                        float(area * lf0)
                    )
                    break

        with open(new_wisdem_file, "w", encoding="utf-8") as f:
            _yaml.safe_dump(wisdem_design, f, default_flow_style=None,
                            sort_keys=False, allow_unicode=False)
        return wisdem_design

    adjustWISDEM = adjust_wisdem

    # ------------------------------------------------------------------
    # plotting (host-side, optional; raft_tpu/viz.py)
    # ------------------------------------------------------------------

    def plot(self, ax=None, color="k", nodes=False, **kwargs):
        """3-D wireframe of the full system
        (reference raft/raft_model.py:792-823).  Reference-only keyword
        arguments (hideGrid, draw_body, ...) are accepted and ignored so
        ported call sites keep working."""
        import inspect

        from raft_tpu.viz import plot_model

        accepted = inspect.signature(plot_model).parameters
        ignored = [k for k in kwargs if k not in accepted]
        if ignored:
            print(f"Model.plot: ignoring unsupported options {ignored}")
        kwargs = {k: v for k, v in kwargs.items() if k in accepted}
        return plot_model(self, ax=ax, color=color, nodes=nodes, **kwargs)

    def plot_responses(self, channels=None):
        """Response PSD subplot grid
        (reference raft/raft_model.py:730-765)."""
        from raft_tpu.viz import plot_responses

        return plot_responses(self, channels=channels)

    # camelCase aliases for reference-API compatibility
    analyzeUnloaded = analyze_unloaded
    plotResponses = plot_responses
    adjustBallast = adjust_ballast
    analyzeCases = analyze_cases
    solveEigen = solve_eigen
    calcOutputs = calc_outputs
    adjustBallastDensity = adjust_ballast_density


def run_raft(input_file, plot=0, ballast=0, run_native_bem=False, **kwargs):
    """Set up and run the full analysis from a YAML/pickle design
    (reference raft/raft_model.py:1092-1135)."""
    design = load_design(input_file)
    print(" --- making model ---")
    model = Model(design, **kwargs)
    print(" --- analyzing unloaded ---")
    model.analyze_unloaded(ballast=ballast)
    if run_native_bem:
        print(" --- running native BEM solver ---")
        model.run_bem()
    print(" --- analyzing cases ---")
    model.analyze_cases()
    model.solve_eigen()
    model.calc_outputs()
    if plot:
        import matplotlib.pyplot as plt

        fig, _ = model.plot()
        fig.savefig("raft_tpu_geometry.png", dpi=120)
        plt.close(fig)
        fig, _ = model.plot_responses()
        fig.savefig("raft_tpu_responses.png", dpi=120)
        plt.close(fig)
        print("saved raft_tpu_geometry.png, raft_tpu_responses.png")
    return model


runRAFT = run_raft
