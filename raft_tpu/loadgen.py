"""Open-loop load generator for the serve tier (SLO measurement).

Closed-loop benchmarks (submit, wait, submit) hide overload: the
generator slows down with the server, so the measured latency stays
flat exactly when a real client population would be piling up.  This
module drives the engine/router **open-loop** — request arrival times
are a Poisson process drawn up-front from a seeded RNG, and the
submitter fires each request at its scheduled instant regardless of
how the previous ones are doing.  Offered load is therefore an input,
not an emergent property, which is what makes goodput (terminal-ok ÷
offered) and the rejection breakdown meaningful SLO figures under
sustained overload and chaos.

Pieces:

* ``poisson_arrivals(rate_hz, duration_s, seed)`` — arrival offsets in
  seconds, a pure function of its arguments (tests replay it);
* ``request_mix(n, config)`` — per-arrival kind tags from the same
  seeded stream: ``solo`` (single design evaluation), ``sweep`` (a
  small ``submit_sweep`` batch of ballast variants — exercises the
  chunk path and, under chaos, the mid-stream failover), ``tight``
  (solo with a deadline that clears warm-path latency but not an
  overloaded queue — under overload these MUST become
  ``rejected_deadline``, not slow answers);
* ``run_phase(backend, config, design, ...)`` — submit the whole
  schedule open-loop, then collect every handle and report offered,
  terminal-status breakdown, goodput, p50/p95/p99 latency, and lost
  (never-terminal) requests.  Every ``canary_every``-th solo request
  reuses the byte-identical base design; the report's
  ``bits_identical`` asserts all their ok answers are
  ``np.array_equal`` — retries/failover under chaos must not change
  numbers.

The backend just needs the engine surface (``submit``,
``submit_sweep``); the Router satisfies it, and tests drive a fake.
Chaos mid-run: ``chaos=(spec, at_frac)`` arms a timer that sets
``RAFT_TPU_CHAOS`` at ``at_frac`` of the phase duration (env saved and
restored), so the fault lands while requests are in flight instead of
at a quiet boundary.

Env knobs (``LoadgenConfig.from_env``):

Request bodies cycle through a BOUNDED variant pool
(``distinct`` ballast variants for solos, another ``distinct`` for
sweeps; ``warm_pool(config, design)`` enumerates it) so the harness
measures the warm serving envelope — steady-state traffic is repeat
requests over a working set, and the cold-prep cost is a separate
figure, not a tax on every arrival.

==============================  ======  =============================
``RAFT_TPU_LOADGEN_RATE``       4.0     offered arrivals per second
``RAFT_TPU_LOADGEN_DURATION_S`` 5.0     phase length (seconds)
``RAFT_TPU_LOADGEN_SEED``       0       arrival/mix RNG seed
``RAFT_TPU_LOADGEN_SWEEP_N``    3       designs per sweep request
``RAFT_TPU_LOADGEN_TIGHT_S``    2.0     deadline of ``tight`` requests
``RAFT_TPU_LOADGEN_DISTINCT``   8       variant-pool size per class
``RAFT_TPU_LOADGEN_ZIPF``       0.0     Zipf exponent for variant
                                        popularity (0 = round-robin)
==============================  ======  =============================

Zipfian popularity (``zipf`` > 0): instead of cycling the variant
pool round-robin, each request draws its variant index from a seeded
Zipf(s) distribution over the SAME bounded pool (rank-k weight
``k**-s``), so repeat-heavy real-world traffic — and therefore a
result cache's achievable hit-rate — can be measured.  The index
streams are a pure function of ``config.seed`` (``zipf_indices``),
the pool stays bounded (``warm_pool`` is unchanged), and canaries
still reuse the byte-identical base design so ``bits_identical``
keeps asserting across cached and uncached serves.
"""

import copy
import dataclasses
import os
import threading
import time

import numpy as np

from raft_tpu.utils.profiling import logger


def _env_float(name, default):
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name, default):
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


@dataclasses.dataclass
class LoadgenConfig:
    """One load phase: offered rate, duration and request mix."""

    rate_hz: float = 4.0
    duration_s: float = 5.0
    seed: int = 0
    sweep_n: int = 3
    tight_deadline_s: float = 2.0
    p_sweep: float = 0.15          # fraction of arrivals that are sweeps
    p_tight: float = 0.15          # fraction with the tight deadline
    canary_every: int = 4          # every k-th solo reuses the base design
    distinct: int = 8              # variant-pool size (see warm_pool)
    zipf: float = 0.0              # variant popularity skew (0 = cycle)
    max_requests: int = 0          # 0 = unbounded; else truncate the
    # arrival schedule after this many requests — measuring a "first N
    # requests" window (e.g. a freshly scaled replica's warm-handoff
    # hit-rate) needs an exact request count, not a duration guess
    collect_timeout_s: float = 120.0

    @classmethod
    def from_env(cls, **overrides):
        cfg = cls(
            rate_hz=_env_float("RAFT_TPU_LOADGEN_RATE", 4.0),
            duration_s=_env_float("RAFT_TPU_LOADGEN_DURATION_S", 5.0),
            seed=_env_int("RAFT_TPU_LOADGEN_SEED", 0),
            sweep_n=_env_int("RAFT_TPU_LOADGEN_SWEEP_N", 3),
            tight_deadline_s=_env_float("RAFT_TPU_LOADGEN_TIGHT_S", 2.0),
            distinct=_env_int("RAFT_TPU_LOADGEN_DISTINCT", 8),
            zipf=_env_float("RAFT_TPU_LOADGEN_ZIPF", 0.0),
        )
        return dataclasses.replace(cfg, **overrides)


def poisson_arrivals(rate_hz, duration_s, seed):
    """Arrival offsets (seconds, ascending) of a Poisson process at
    ``rate_hz`` over ``duration_s`` — a pure function of its arguments,
    so a phase's offered schedule replays exactly per seed."""
    rng = np.random.default_rng(int(seed))
    arrivals = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / float(rate_hz)))
        if t >= float(duration_s):
            return np.asarray(arrivals, dtype=float)
        arrivals.append(t)


def request_mix(n, config):
    """Kind tag per arrival (``solo`` / ``sweep`` / ``tight``), drawn
    from a stream seeded independently of the arrival times so changing
    the mix never reshuffles the schedule."""
    rng = np.random.default_rng(int(config.seed) + 0x5EED)
    u = rng.random(int(n))
    kinds = []
    for x in u:
        if x < config.p_sweep:
            kinds.append("sweep")
        elif x < config.p_sweep + config.p_tight:
            kinds.append("tight")
        else:
            kinds.append("solo")
    return kinds


def zipf_indices(n, config, stream):
    """``n`` variant-pool indices drawn Zipf(``config.zipf``) over
    ``config.distinct`` ranks — a pure function of ``(config.seed,
    config.zipf, config.distinct, stream)``, so a phase's popularity
    schedule replays exactly per seed.  ``stream`` decorrelates the
    solo and sweep draws from each other and from the arrival/mix
    streams.  Rank k (0-based index k-1) gets weight ``k**-zipf``:
    higher exponents concentrate traffic on the head of the pool,
    which is what makes a result cache's hit-rate measurable."""
    distinct = max(1, int(config.distinct))
    ranks = np.arange(1, distinct + 1, dtype=float)
    w = ranks ** -float(config.zipf)
    rng = np.random.default_rng(int(config.seed) + int(stream))
    return rng.choice(distinct, size=int(n), p=w / w.sum())


def _ballast_variant(design, i):
    """The i-th distinct request body: bump the first member's ballast
    density (a knob ``routing_key`` deliberately ignores, so variants
    stay one replica family).  Falls back to a tag key when the design
    lacks the member structure (fake-backend tests)."""
    d = copy.deepcopy(design)
    try:
        mem = d["platform"]["members"][0]
        fill = list(mem.get("rho_fill") or [1000.0, 0.0, 0.0])
        fill[0] = float(fill[0]) + 10.0 * (int(i) + 1)
        mem["rho_fill"] = fill
    except (KeyError, IndexError, TypeError):
        d["_loadgen_variant"] = int(i) + 1
    return d


def warm_pool(config, design):
    """Every distinct request body a phase with this config can submit:
    the base design (canaries) plus the solo and sweep variant pools.
    The harness cycles variants through a BOUNDED pool (``distinct``)
    so it measures the warm serving envelope — a serving tier's steady
    state is repeat traffic over a working set, not a cold host prep
    per arrival.  Benches submit this pool once before the measured
    phases (cold-path cost is the ``serve`` section's own figure)."""
    pool = [copy.deepcopy(design)]
    pool += [_ballast_variant(design, i) for i in range(config.distinct)]
    pool += [_ballast_variant(design, 1000 + i)
             for i in range(config.distinct)]
    return pool


@dataclasses.dataclass
class _Flight:
    kind: str
    handle: object
    canary: bool = False
    t_submit: float = 0.0


def run_phase(backend, config, design, name="load", chaos=None,
              clock=time.perf_counter, sleep=time.sleep):
    """Drive one open-loop phase against ``backend`` and report SLOs.

    ``chaos``: optional ``(spec_text, at_frac)`` — arm RAFT_TPU_CHAOS
    with ``spec_text`` at ``at_frac`` of the phase duration so the
    fault fires mid-run, restoring the previous env value afterwards.
    A 3-tuple ``(spec_text, at_frac, heal_frac)`` additionally HEALS
    the fault at ``heal_frac`` of the duration (restores the previous
    env mid-run), so one phase spans inject + heal — e.g. a network
    partition that opens and closes while traffic flows.
    Returns the phase report dict (see module docstring)."""
    arrivals = poisson_arrivals(config.rate_hz, config.duration_s,
                                config.seed)
    kinds = request_mix(len(arrivals), config)
    if config.max_requests and len(arrivals) > int(config.max_requests):
        # truncate AFTER drawing both streams so a bounded phase offers
        # the exact prefix of the unbounded schedule (same seed, same
        # first-N requests)
        arrivals = arrivals[:int(config.max_requests)]
        kinds = kinds[:int(config.max_requests)]
    flights = []
    chaos_timer = None
    chaos_prev = os.environ.get("RAFT_TPU_CHAOS")
    chaos_fires = None

    heal_timer = None
    healed = {}          # snapshot of fires taken at heal time

    def _arm_chaos(spec):
        os.environ["RAFT_TPU_CHAOS"] = spec
        logger.warning("loadgen %s: chaos armed mid-run: %s", name, spec)

    def _heal_chaos():
        from raft_tpu.chaos import get_injector

        inj = get_injector()
        if inj is not None:
            healed["fires"] = inj.snapshot()
        if chaos_prev is None:
            os.environ.pop("RAFT_TPU_CHAOS", None)
        else:
            os.environ["RAFT_TPU_CHAOS"] = chaos_prev
        logger.warning("loadgen %s: chaos healed mid-run", name)

    if chaos is not None:
        spec, at_frac = chaos[0], chaos[1]
        chaos_timer = threading.Timer(
            float(at_frac) * config.duration_s, _arm_chaos, (spec,))
        chaos_timer.daemon = True
        chaos_timer.start()
        if len(chaos) > 2 and chaos[2] is not None:
            heal_timer = threading.Timer(
                float(chaos[2]) * config.duration_s, _heal_chaos)
            heal_timer.daemon = True
            heal_timer.start()
    solo_pick = sweep_pick = None
    if config.zipf > 0.0:
        solo_pick = zipf_indices(len(arrivals), config, 0x21BF)
        sweep_pick = zipf_indices(
            len(arrivals) * max(1, int(config.sweep_n)), config, 0x5EE9)
    t_start = clock()
    solo_seq = 0
    sweep_seq = 0
    try:
        for arr, kind in zip(arrivals, kinds):
            lag = t_start + float(arr) - clock()
            if lag > 0:
                sleep(lag)
            try:
                if kind == "sweep":
                    h = backend.submit_sweep(
                        [_ballast_variant(
                            design,
                            1000 + int(sweep_pick[sweep_seq
                                                  * config.sweep_n + j])
                            if sweep_pick is not None
                            else 1000 + (sweep_seq + j) % config.distinct)
                         for j in range(config.sweep_n)])
                    sweep_seq += 1
                    flights.append(_Flight("sweep", h,
                                           t_submit=clock() - t_start))
                else:
                    canary = (kind == "solo"
                              and solo_seq % config.canary_every == 0)
                    body = design if canary \
                        else _ballast_variant(
                            design,
                            int(solo_pick[solo_seq])
                            if solo_pick is not None
                            else solo_seq % config.distinct)
                    if kind == "solo":
                        solo_seq += 1
                    deadline = config.tight_deadline_s \
                        if kind == "tight" else None
                    h = backend.submit(body, deadline_s=deadline)
                    flights.append(_Flight(kind, h, canary=canary,
                                           t_submit=clock() - t_start))
            except RuntimeError as exc:       # backend refused at the door
                flights.append(_Flight(kind, None))
                logger.warning("loadgen %s: submit refused: %s", name, exc)
    finally:
        if chaos_timer is not None:
            chaos_timer.cancel()
            chaos_timer.join(timeout=1.0)
        if heal_timer is not None:
            heal_timer.join(timeout=max(
                1.0, float(config.collect_timeout_s)))
    # ---- collect: every accepted request must reach a terminal status
    statuses = {}
    lost = 0
    ok_lat = []
    canary_bits = []
    slowest = None       # (latency_s, trace_id) of the slowest ok req
    for fl in flights:
        if fl.handle is None:
            statuses["refused"] = statuses.get("refused", 0) + 1
            continue
        try:
            res = fl.handle.result(timeout=config.collect_timeout_s)
        except Exception as exc:               # noqa: BLE001 — timeout =
            lost += 1                          # lost request, the SLO sin
            logger.warning("loadgen %s: %s request never reached a "
                           "terminal status (%s)", name, fl.kind, exc)
            continue
        status = getattr(res, "status", None) or "unknown"
        statuses[status] = statuses.get(status, 0) + 1
        if status == "ok":
            lat = float(getattr(res, "latency_s", 0.0))
            ok_lat.append(lat)
            # keep the slowest ok request's trace_id so the operator
            # can gather_trace the phase's tail latency straight off
            # the report (docs/observability.md)
            if slowest is None or lat > slowest[0]:
                slowest = (lat, getattr(res, "trace_id", None))
            if fl.canary and getattr(res, "Xi", None) is not None:
                canary_bits.append(np.asarray(res.Xi))
    if chaos is not None:
        from raft_tpu.chaos import get_injector

        inj = get_injector()
        chaos_fires = inj.snapshot() if inj is not None else None
        if chaos_fires is None:
            # healed mid-run: the fire accounting was captured then
            chaos_fires = healed.get("fires")
        if chaos_prev is None:
            os.environ.pop("RAFT_TPU_CHAOS", None)
        else:
            os.environ["RAFT_TPU_CHAOS"] = chaos_prev
    offered = len(flights)
    ok = statuses.get("ok", 0)
    lat_ms = np.asarray(sorted(ok_lat)) * 1e3
    bits = None
    if len(canary_bits) >= 2:
        bits = all(np.array_equal(canary_bits[0], b)
                   for b in canary_bits[1:])
    report = {
        "name": name,
        "offered": offered,
        "rate_hz": round(config.rate_hz, 3),
        "duration_s": round(config.duration_s, 3),
        "wall_s": round(clock() - t_start, 3),
        "statuses": statuses,
        "ok": ok,
        "goodput": round(ok / offered, 4) if offered else 1.0,
        "lost": lost,
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 2)
        if len(lat_ms) else None,
        "p95_ms": round(float(np.percentile(lat_ms, 95)), 2)
        if len(lat_ms) else None,
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 2)
        if len(lat_ms) else None,
        "canaries_ok": len(canary_bits),
        "bits_identical": bits,
        "slowest_latency_s": round(slowest[0], 6) if slowest else None,
        "slowest_trace_id": slowest[1] if slowest else None,
    }
    if chaos_fires is not None:
        report["chaos"] = chaos_fires
    logger.info("loadgen %s: offered=%d goodput=%.3f lost=%d p95=%s",
                name, offered, report["goodput"], lost,
                report["p95_ms"])
    return report
