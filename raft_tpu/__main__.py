"""Command-line entry point: ``python -m raft_tpu design.yaml [options]``
(the reference's ``python raft_model.py`` __main__ path,
reference raft/raft_model.py:1140-1147, as a proper CLI)."""

import argparse
import sys


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="raft_tpu",
        description="Frequency-domain FOWT analysis (TPU-native RAFT)",
    )
    p.add_argument("design", help="design YAML/pickle path")
    p.add_argument("--plot", action="store_true",
                   help="save geometry + response-PSD figures")
    p.add_argument("--ballast", type=int, default=0, choices=[0, 1, 2],
                   help="ballast trim mode (1=fill levels, 2=densities)")
    p.add_argument("--precision", choices=["float32", "float64"],
                   default=None, help="device working precision")
    p.add_argument("--device", choices=["tpu", "cpu", "gpu"], default=None,
                   help="backend for the batched case solve "
                        "(default: JAX default backend)")
    p.add_argument("--bem", action="store_true",
                   help="run the native BEM solver on potMod members")
    args = p.parse_args(argv)

    from raft_tpu.model import run_raft

    run_raft(
        args.design, plot=int(args.plot), ballast=args.ballast,
        precision=args.precision, run_native_bem=args.bem,
        device=args.device,
    )


if __name__ == "__main__":
    main()
    sys.exit(0)
