"""Command-line entry point.

``python -m raft_tpu design.yaml [options]`` — one-shot full analysis
(the reference's ``python raft_model.py`` __main__ path, reference
raft/raft_model.py:1140-1147, as a proper CLI).

``python -m raft_tpu warmup [design.yaml ...]`` — ahead-of-time compile
warm-up of the serving buckets (manifest-driven; see docs/serving.md).

``python -m raft_tpu serve [design.yaml ...]`` — long-lived serving
engine reading JSON-line requests from stdin and writing JSON-line
results to stdout (the default legacy path), or with
``--http PORT [--replicas N]`` an HTTP/1.1 JSON server over one
engine or an N-replica consistent-hash router (docs/serving.md,
"Network transport & replicas").
"""

import argparse
import json
import sys


def _analyze_main(argv):
    p = argparse.ArgumentParser(
        prog="raft_tpu",
        description="Frequency-domain FOWT analysis (TPU-native RAFT)",
    )
    p.add_argument("design", help="design YAML/pickle path")
    p.add_argument("--plot", action="store_true",
                   help="save geometry + response-PSD figures")
    p.add_argument("--ballast", type=int, default=0, choices=[0, 1, 2],
                   help="ballast trim mode (1=fill levels, 2=densities)")
    p.add_argument("--precision", choices=["float32", "float64"],
                   default=None, help="device working precision")
    p.add_argument("--device", choices=["tpu", "cpu", "gpu"], default=None,
                   help="backend for the batched case solve "
                        "(default: JAX default backend)")
    p.add_argument("--bem", action="store_true",
                   help="run the native BEM solver on potMod members")
    args = p.parse_args(argv)

    from raft_tpu.model import run_raft

    run_raft(
        args.design, plot=int(args.plot), ballast=args.ballast,
        precision=args.precision, run_native_bem=args.bem,
        device=args.device,
    )


def _serve_parser(prog, description):
    p = argparse.ArgumentParser(prog=prog, description=description)
    p.add_argument("designs", nargs="*",
                   help="design YAML paths to seed/warm buckets from")
    p.add_argument("--precision", choices=["float32", "float64"],
                   default=None)
    p.add_argument("--device", choices=["tpu", "cpu", "gpu"], default=None)
    p.add_argument("--cache-dir", default=None,
                   help="serve cache base (default: RAFT_TPU_CACHE_DIR / "
                        "the persistent XLA cache dir)")
    return p


def _warmup_main(argv):
    p = _serve_parser(
        "raft_tpu warmup",
        "AOT-compile the serving buckets recorded in the warm-up "
        "manifest (plus any designs given), through the persistent "
        "XLA compilation cache.")
    args = p.parse_args(argv)

    from raft_tpu.io.schema import load_design
    from raft_tpu.serve import warmup

    designs = [load_design(path) for path in args.designs]
    report = warmup(designs=designs or None, precision=args.precision,
                    cache_dir=args.cache_dir)
    print(json.dumps(report))


class _SignalShutdown(BaseException):
    """Raised by the SIGTERM/SIGINT handlers to unblock the stdin read
    so the serve loop can drain gracefully.  A BaseException so the
    request loop's per-line ``except Exception`` can never swallow a
    signal that lands mid-body."""

    def __init__(self, signum):
        super().__init__(f"signal {signum}")
        self.signum = signum


def _serve_main(argv):
    import os

    p = _serve_parser(
        "raft_tpu serve",
        "Long-lived serving engine: JSON-line requests on stdin "
        '({"design": "path.yaml", "cases": [...], "deadline_s": 10}, '
        'or {"sweep": {"designs": [...], "chunk": N}} for a chunked '
        "design sweep streamed as per-chunk result lines), "
        "JSON-line results on stdout.  With --http, an HTTP/1.1 JSON "
        "server (and optionally an N-replica router) instead of the "
        "stdin loop.  SIGTERM/SIGINT shut down gracefully: in-flight "
        "batches drain and every outstanding handle resolves with a "
        'terminal status ("shutdown" at worst).')
    p.add_argument("--window-ms", type=float, default=None,
                   help="micro-batching window (default "
                        "RAFT_TPU_SERVE_WINDOW_MS or 5 ms)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip the manifest warm-up at startup")
    p.add_argument("--xi", action="store_true",
                   help="include the full complex response amplitudes "
                        "in each result line")
    p.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="serve the wire protocol over HTTP on PORT "
                        "(0 = OS-assigned, read back from the ready "
                        "line; default RAFT_TPU_SERVE_HTTP_PORT; "
                        "omitted entirely = legacy stdin JSONL loop)")
    p.add_argument("--replicas", type=int, default=None, metavar="N",
                   help="with --http: front N spawned engine replica "
                        "processes with the consistent-hash router "
                        "(default RAFT_TPU_SERVE_REPLICAS or 0 = serve "
                        "one in-process engine)")
    p.add_argument("--autoscale", action="store_true",
                   help="with --http --replicas: let the router grow/"
                        "shrink the fleet against per-replica pressure "
                        "(default RAFT_TPU_AUTOSCALE; thresholds via "
                        "RAFT_TPU_AUTOSCALE_* — see docs/serving.md)")
    args = p.parse_args(argv)

    http_port = args.http
    if http_port is None and os.environ.get("RAFT_TPU_SERVE_HTTP_PORT"):
        http_port = int(os.environ["RAFT_TPU_SERVE_HTTP_PORT"])
    if args.cache_dir is None and os.environ.get(
            "RAFT_TPU_SERVE_SHARED_CACHE"):
        args.cache_dir = os.environ["RAFT_TPU_SERVE_SHARED_CACHE"]
    if http_port is not None:
        return _serve_http_main(args, http_port)

    import signal

    from raft_tpu.io.schema import load_design
    from raft_tpu.serve import Engine, EngineConfig, warmup

    cfg = EngineConfig(precision=args.precision, device=args.device,
                       cache_dir=args.cache_dir)
    if args.window_ms is not None:
        cfg.window_ms = args.window_ms
    designs = [load_design(path) for path in args.designs]
    if not args.no_warmup:
        warmup(designs=designs or None, precision=args.precision,
               cache_dir=args.cache_dir)

    def _on_signal(signum, frame):
        raise _SignalShutdown(signum)

    old_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)
    }
    eng = Engine(cfg)
    sig = None
    pending = []
    try:
        print(json.dumps({"event": "ready",
                          **{k: v for k, v in eng.snapshot().items()
                             if not isinstance(v, (list, dict))}}),
              flush=True)
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
                if "sweep" in req:
                    # inline blocking emission: chunk lines stream as
                    # they finish, then the terminal sweep_result line
                    _emit_sweep(eng, req["sweep"], load_design, pending,
                                args.xi)
                    continue
                design = req["design"]
                if isinstance(design, str):
                    design = load_design(design)
                pending.append(eng.submit(
                    design, cases=req.get("cases"),
                    deadline_s=req.get("deadline_s")))
            except Exception as e:  # noqa: BLE001 — bad line, keep serving
                print(json.dumps({"event": "error",
                                  "error": f"{type(e).__name__}: {e}"}),
                      flush=True)
                continue
            # drain results in submission order as they complete
            while pending and pending[0].done():
                _emit_result(pending.pop(0).result(0), args.xi)
    except _SignalShutdown as e:
        sig = e.signum
    finally:
        for s, h in old_handlers.items():
            signal.signal(s, h)
        # graceful shutdown: EOF drains the queue fully; a signal
        # finishes the in-flight dispatch and resolves everything still
        # queued with status="shutdown".  Either way the engine
        # guarantees every handle a terminal status, so the emits below
        # can never block past the shutdown timeout.
        eng.shutdown(wait=True, drain=(sig is None))
        for h in pending:
            try:
                _emit_result(h.result(timeout=30), args.xi)
            except TimeoutError:  # pragma: no cover — belt and braces
                print(json.dumps({"event": "result", "rid": h.rid,
                                  "status": "shutdown",
                                  "error": "unresolved at shutdown"}),
                      flush=True)
        print(json.dumps({"event": "shutdown", "signal": sig, **{
            k: v for k, v in eng.snapshot().items()
            if not isinstance(v, (list, dict))}}), flush=True)


def _serve_http_main(args, http_port):
    """The --http serve path: one in-process engine (replicas=0) or an
    N-replica router, fronted by serve/transport.py.  stdout carries
    only the ready/shutdown lines; requests ride the wire."""
    import os
    import signal
    import threading

    from raft_tpu.io.schema import load_design
    from raft_tpu.serve import Engine, EngineConfig, serve_http, warmup

    n_replicas = args.replicas
    if n_replicas is None:
        n_replicas = int(os.environ.get("RAFT_TPU_SERVE_REPLICAS", "0"))

    if n_replicas > 0:
        from raft_tpu.serve import Router

        backend = Router(
            n_replicas=n_replicas, cache_dir=args.cache_dir,
            precision=args.precision, device=args.device,
            window_ms=args.window_ms, warmup=not args.no_warmup,
            autoscale=True if args.autoscale else None)
    else:
        cfg = EngineConfig(precision=args.precision, device=args.device,
                           cache_dir=args.cache_dir)
        if args.window_ms is not None:
            cfg.window_ms = args.window_ms
        designs = [load_design(path) for path in args.designs]
        if not args.no_warmup:
            warmup(designs=designs or None, precision=args.precision,
                   cache_dir=args.cache_dir)
        backend = Engine(cfg)

    stop = threading.Event()
    sig_caught = []

    def _on_signal(signum, frame):
        sig_caught.append(signum)
        stop.set()

    old_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)
    }
    transport = serve_http(backend, port=http_port)
    try:
        print(json.dumps({"event": "ready", "port": transport.port,
                          "replicas": n_replicas}), flush=True)
        stop.wait()
    finally:
        for s, h in old_handlers.items():
            signal.signal(s, h)
        report = transport.drain(drain_queue=not sig_caught)
        print(json.dumps({"event": "shutdown",
                          "signal": sig_caught[0] if sig_caught else None,
                          **report}), flush=True)


def _emit_result(res, include_xi=False):
    from raft_tpu.serve import wire

    print(json.dumps(wire.result_doc(res, include_xi=include_xi)),
          flush=True)


def _emit_sweep(eng, doc, load_design, pending, include_xi):
    """Inline sweep emission for the stdin JSONL loop: an accepted line,
    one line per finished chunk (the PR 2 checkpoint schema as wire
    format), then the terminal ``sweep_result`` line (meta only — the
    arrays ride the chunk lines).  Interactive results that finish while
    the sweep streams (preemption keeps them flowing) are drained
    between chunk lines so they are not held to the end."""
    from raft_tpu.serve import wire

    designs, cases, chunk = wire.parse_sweep_request(doc)
    designs = [load_design(d) if isinstance(d, str) else d
               for d in designs]
    handle = eng.submit_sweep(designs, cases=cases, chunk=chunk)
    print(json.dumps({"event": "sweep_accepted", "rid": handle.rid,
                      "n_designs": handle.n_designs,
                      "n_chunks": handle.n_chunks}), flush=True)
    for ch in handle.chunks():
        print(json.dumps(wire.sweep_chunk_doc(ch)), flush=True)
        while pending and pending[0].done():
            _emit_result(pending.pop(0).result(0), include_xi)
    print(json.dumps(wire.sweep_result_doc(handle.result(600))),
          flush=True)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "warmup":
        return _warmup_main(argv[1:])
    if argv and argv[0] == "serve":
        return _serve_main(argv[1:])
    return _analyze_main(argv)


if __name__ == "__main__":
    main()
    sys.exit(0)
