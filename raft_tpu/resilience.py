"""Unified resilience policies: retry, backoff, timeout, circuit breaker.

Before this module every layer re-implemented its own fault handling:
``sweep.py`` hard-coded the bounded non-convergence retry (doubled nIter,
relax 0.4), ``sweep_fused.py`` duplicated the same constants per chunk,
and the serving engine had no retry/timeout story at all — a hung XLA
dispatch stalled the batcher thread forever.  The WaterLily.jl and
TPU-CFD serving papers (PAPERS.md) both stress that heterogeneous
frameworks live or die on graceful degradation when one backend
misbehaves; this module is the single vocabulary for that degradation:

 - :class:`BackoffPolicy` — exponential backoff with *deterministic*
   jitter (seeded hash of (attempt, key), never wall-clock entropy), so
   a replayed fault schedule produces the same delays;
 - :class:`RetryPolicy` — bounded attempts over a backoff schedule with
   an optional per-attempt timeout, retrying only :class:`TransientError`
   (or caller-chosen) classes;
 - :class:`CircuitBreaker` / :class:`BreakerBoard` — the classic
   closed -> open -> half-open automaton, keyed per (backend, bucket) by
   the serving engine so one wedged executable family degrades to
   fast-fail (or the CPU backend) instead of queueing work behind a
   corpse;
 - :class:`SolveRetryPolicy` — the sweep drivers' non-convergence
   escalation schedule (iteration multiplier + stronger
   under-relaxation), now defined once and imported by ``sweep.py``,
   ``sweep_fused.py``, and the engine instead of three copies of the
   magic numbers.

Everything here is host-side control flow: no policy ever changes the
arithmetic of a healthy solve (the sweep retry is adopted per lane only
where it converges, and the engine re-dispatches the *same* packed
operands), preserving the bit-identity contracts of docs/serving.md.
"""

import dataclasses
import hashlib
import threading
import time

from raft_tpu.utils.profiling import logger


class TransientError(RuntimeError):
    """A fault worth retrying: the operation may succeed unchanged on a
    later attempt (backend hiccup, transient allocation failure).  Chaos
    injection raises a subclass (raft_tpu/chaos.py)."""


class WatchdogTimeout(RuntimeError):
    """A dispatch exceeded its wall-clock watchdog budget.  Deliberately
    NOT a TransientError: the stuck executable may never return, so
    retrying into it is unsafe — the serving engine trips the circuit
    breaker instead."""


def _hash_unit(*parts):
    """Deterministic float in [0, 1) from the given parts (no RNG state,
    no wall clock — replays identically)."""
    h = hashlib.sha256("|".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "big") / 2.0 ** 64


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff with deterministic jitter.

    delay(attempt) = min(max_s, base_s * mult**(attempt-1)) * jitter_factor
    where jitter_factor is 1 - jitter * u and u = hash(seed, key, attempt)
    in [0, 1) — the same (seed, key, attempt) always backs off the same.
    """

    base_s: float = 0.05
    mult: float = 2.0
    max_s: float = 2.0
    jitter: float = 0.5
    seed: int = 0

    def delay(self, attempt, key=""):
        raw = min(self.max_s, self.base_s * self.mult ** max(attempt - 1, 0))
        u = _hash_unit(self.seed, key, attempt)
        return raw * (1.0 - self.jitter * u)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry over a backoff schedule.

    max_attempts counts the first try: max_attempts=1 means no retry.
    retry_on is the tuple of exception classes worth a second attempt —
    anything else propagates immediately.  timeout_s is the per-attempt
    wall-clock budget enforced by the caller's watchdog (the policy just
    carries the number so every layer reads one knob).
    """

    max_attempts: int = 2
    backoff: BackoffPolicy = dataclasses.field(default_factory=BackoffPolicy)
    retry_on: tuple = (TransientError,)
    timeout_s: float = None
    name: str = ""

    def run(self, fn, key="", on_retry=None, sleep=time.sleep):
        """Call ``fn()`` under this policy.  ``on_retry(attempt, exc)``
        is invoked before each re-attempt's backoff sleep.  The last
        failure propagates."""
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except self.retry_on as e:
                if attempt >= self.max_attempts:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                delay = self.backoff.delay(attempt, key=key)
                logger.warning(
                    "%s: attempt %d/%d failed (%s: %s); retrying in %.3fs",
                    self.name or "retry", attempt, self.max_attempts,
                    type(e).__name__, e, delay)
                sleep(delay)


@dataclasses.dataclass(frozen=True)
class SolveRetryPolicy:
    """The sweep drivers' bounded non-convergence escalation: one extra
    solve of the affected chunk with ``iter_mult x nIter`` iterations and
    under-relaxation ``relax`` (0.4 vs the reference's 0.8), adopted per
    lane only where the retry converges — first-pass-healthy lanes stay
    bit-identical.  Previously three hard-coded copies of (2x, 0.4); now
    the one place those constants live."""

    max_retries: int = 1
    iter_mult: float = 2.0
    relax: float = 0.4

    @property
    def enabled(self):
        return self.max_retries > 0

    @classmethod
    def from_flag(cls, retry_nonconverged):
        """Legacy bool/policy coercion for the sweep drivers' public
        ``retry_nonconverged=`` argument."""
        if isinstance(retry_nonconverged, cls):
            return retry_nonconverged
        return cls(max_retries=1 if retry_nonconverged else 0)

    def escalate(self, nIter):
        """(nIter, relax) of the retry solve."""
        return int(round(self.iter_mult * nIter)), self.relax


# ---------------------------------------------------------------- breaker

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"


class CircuitBreaker:
    """closed -> open -> half-open automaton, thread-safe.

    ``failure_threshold`` consecutive failures (or one ``trip()``) open
    the breaker; while open, ``allow()`` is False until ``cooldown_s``
    has elapsed, after which exactly one caller is admitted as the
    half-open probe.  The probe's ``record_success`` closes the breaker;
    its ``record_failure`` re-opens it (cooldown restarts).  Every state
    change is appended to ``transitions`` as ``(t, from, to, reason)``
    for the stats snapshot.
    """

    # shared-state contract enforced by the lock-discipline analyzer
    # (docs/robustness.md 'Lock discipline')
    _GUARDED_BY = {
        "_state": "_lock",
        "_failures": "_lock",
        "_opened_at": "_lock",
        "transitions": "_lock",
    }

    def __init__(self, failure_threshold=3, cooldown_s=30.0,
                 clock=time.monotonic, name=""):
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._failures = 0
        self._opened_at = None
        self.transitions = []

    def _move_locked(self, state, reason):
        if state != self._state:
            self.transitions.append(
                (self._clock(), self._state, state, reason))
            logger.warning("circuit breaker %s: %s -> %s (%s)",
                           self.name or "?", self._state, state, reason)
        self._state = state

    @property
    def state(self):
        with self._lock:
            return self._state

    def allow(self):
        """Whether a call may proceed now.  The transition open ->
        half-open happens here, and only one caller wins the probe."""
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            if self._state == STATE_OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._move_locked(STATE_HALF_OPEN, "cooldown elapsed")
                    return True      # this caller is the probe
                return False
            return False             # half-open: probe already in flight

    def record_success(self):
        with self._lock:
            self._failures = 0
            if self._state != STATE_CLOSED:
                self._move_locked(STATE_CLOSED, "probe succeeded")

    def record_failure(self, reason="failure"):
        with self._lock:
            self._failures += 1
            if self._state == STATE_HALF_OPEN \
                    or self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._move_locked(STATE_OPEN, reason)

    def trip(self, reason="tripped"):
        """Force-open regardless of the failure count (the watchdog's
        verdict: the executable is a corpse, stop feeding it)."""
        with self._lock:
            self._failures = max(self._failures, self.failure_threshold)
            self._opened_at = self._clock()
            self._move_locked(STATE_OPEN, reason)

    def snapshot(self):
        with self._lock:
            return {
                "state": self._state,
                "failures": self._failures,
                "transitions": [
                    {"t": round(t, 3), "from": a, "to": b, "reason": r}
                    for t, a, b, r in self.transitions
                ],
            }


class BreakerBoard:
    """Keyed registry of circuit breakers — the engine keys on
    (backend, bucket spec) so one sick executable family never blocks
    the others."""

    _GUARDED_BY = {"_breakers": "_lock"}

    def __init__(self, failure_threshold=3, cooldown_s=30.0,
                 clock=time.monotonic):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers = {}

    def get(self, key):
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = CircuitBreaker(
                    self.failure_threshold, self.cooldown_s,
                    clock=self._clock, name=str(key))
                self._breakers[key] = br
            return br

    def snapshot(self):
        with self._lock:
            items = list(self._breakers.items())
        return {str(k): br.snapshot() for k, br in items}

    def transition_count(self):
        with self._lock:
            return sum(len(br.transitions)
                       for br in self._breakers.values())

    def states(self):
        """{key: state} without per-breaker snapshots — cheap enough for
        a readiness probe polled every few seconds."""
        with self._lock:
            items = list(self._breakers.items())
        return {str(k): br.state for k, br in items}

    def open_count(self):
        return sum(1 for s in self.states().values() if s != STATE_CLOSED)
