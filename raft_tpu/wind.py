"""IEC 61400-1 wind turbulence models and the rotor-averaged Kaimal spectrum.

Provides the subset of the reference's pyIECWind + Rotor.IECKaimal that the
spectral-domain path consumes (reference raft/pyIECWind.py:25-77 setup/NTM/
ETM/EWM; raft/raft_rotor.py:551-643 IECKaimal).  Host-side (runs once per
load case); the rotor-averaging needs modified Struve / Bessel functions,
taken from scipy.special here — a JAX implementation is only needed if the
whole aero path moves on-device for design sweeps.
"""

import numpy as np
from scipy.special import iv, modstruve

_TURBINE_CLASS_VREF = {"I": 50.0, "II": 42.5, "III": 37.5, "IV": 30.0}
_TURBULENCE_CLASS_IREF = {"A+": 0.18, "A": 0.16, "B": 0.14, "C": 0.12}


class IECWind:
    """IEC extreme/normal turbulence parameters
    (reference raft/pyIECWind.py:8-77)."""

    def __init__(self, turbine_class="I", turbulence_class="B", z_hub=90.0):
        self.turbine_class = turbine_class
        self.turbulence_class = turbulence_class
        self.z_hub = z_hub
        self.V_ref = _TURBINE_CLASS_VREF[turbine_class]
        self.V_ave = 0.2 * self.V_ref
        self.I_ref = _TURBULENCE_CLASS_IREF[turbulence_class]
        self.Sigma_1 = 42.0 if z_hub > 60 else 0.7 * z_hub

    def NTM(self, V_hub):
        """Normal turbulence model sigma_1 (IEC 6.3.1.3)."""
        return self.I_ref * (0.75 * V_hub + 5.6)

    def ETM(self, V_hub):
        """Extreme turbulence model sigma_1 (IEC 6.3.2.3)."""
        c = 2.0
        return c * self.I_ref * (0.072 * (self.V_ave / c + 3) * (V_hub / c - 4) + 10)

    def EWM(self, V_hub):
        """Extreme wind model sigma_1 (IEC 6.3.2.1)."""
        return 0.11 * V_hub


def parse_turbulence(turbulence):
    """Decode the case 'turbulence' entry: either a float turbulence
    intensity (NTM assumed) or a class string like 'IB_NTM'
    (reference raft/raft_rotor.py:566-596).

    Returns (I_ref or None, turbine_class, turbulence_class, model).
    """
    if isinstance(turbulence, (int, float)):
        return float(turbulence), "I", "B", "NTM"
    s = str(turbulence)
    cls = ""
    for ch in s:
        if ch in ("I", "V"):
            cls += ch
        else:
            break
    if not cls:
        raise ValueError(
            f"Turbulence class must start with I, II, III, or IV: {turbulence}"
        )
    categ = s[len(cls)]
    try:
        model = s.split("_")[1]
    except IndexError:
        raise ValueError(f"Error reading the turbulence model: {turbulence}")
    return None, cls, categ, model


def kaimal_rotor_spectrum(w, V_ref, HH, R, turbulence):
    """Rotor-averaged Kaimal turbulence spectra (U, V, W, Rot) at frequencies
    w [rad/s] (reference raft/raft_rotor.py:551-643).

    V_ref : hub wind speed; HH : hub height; R : rotor radius;
    turbulence : case turbulence entry (intensity float or 'IB_NTM' style).
    Returns (U, V, W, Rot) PSDs [(m/s)^2 / (rad/s)] — Rot is the
    rotor-averaged longitudinal spectrum used for thrust excitation.
    """
    f = np.asarray(w) / 2 / np.pi

    I_ref_override, cls, categ, model = parse_turbulence(turbulence)
    iec = IECWind(cls, categ, z_hub=HH)
    if I_ref_override is not None:
        iec.I_ref = I_ref_override
        model = "NTM"

    if model == "NTM":
        sigma_1 = iec.NTM(V_ref)
    elif model == "ETM":
        sigma_1 = iec.ETM(V_ref)
    elif model == "EWM":
        sigma_1 = iec.EWM(V_ref)
    else:
        raise ValueError(f"Wind model must be NTM, ETM, or EWM, not {model}")

    # turbulence scale parameters, IEC 61400-1-2019 Annex C3
    L_1 = 0.7 * HH if HH <= 60 else 42.0
    sigma_u, L_u = sigma_1, 8.1 * L_1
    sigma_v, L_v = 0.8 * sigma_1, 2.7 * L_1
    sigma_w, L_w = 0.5 * sigma_1, 0.66 * L_1

    U = (4 * L_u / V_ref) * sigma_u**2 / (1 + 6 * f * L_u / V_ref) ** (5.0 / 3.0)
    V = (4 * L_v / V_ref) * sigma_v**2 / (1 + 6 * f * L_v / V_ref) ** (5.0 / 3.0)
    W = (4 * L_w / V_ref) * sigma_w**2 / (1 + 6 * f * L_w / V_ref) ** (5.0 / 3.0)

    kappa = 12 * np.sqrt((f / V_ref) ** 2 + (0.12 / L_u) ** 2)

    with np.errstate(over="ignore", invalid="ignore"):
        Rot = (2 * U / (R * kappa) ** 3) * (
            modstruve(1, 2 * R * kappa) - iv(1, 2 * R * kappa) - 2 / np.pi
            + R * kappa
            * (-2 * modstruve(-2, 2 * R * kappa) + 2 * iv(2, 2 * R * kappa) + 1)
        )
    Rot = np.nan_to_num(Rot, nan=0.0, posinf=0.0, neginf=0.0)
    return U, V, W, Rot
