"""IEC 61400-1 wind turbulence models and the rotor-averaged Kaimal spectrum.

Provides the subset of the reference's pyIECWind + Rotor.IECKaimal that the
spectral-domain path consumes (reference raft/pyIECWind.py:25-77 setup/NTM/
ETM/EWM; raft/raft_rotor.py:551-643 IECKaimal).  Host-side (runs once per
load case); the rotor-averaging needs modified Struve / Bessel functions,
taken from scipy.special here — a JAX implementation is only needed if the
whole aero path moves on-device for design sweeps.
"""

import os

import numpy as np
from scipy.special import iv, modstruve

_TURBINE_CLASS_VREF = {"I": 50.0, "II": 42.5, "III": 37.5, "IV": 30.0}
_TURBULENCE_CLASS_IREF = {"A+": 0.18, "A": 0.16, "B": 0.14, "C": 0.12}


class IECWind:
    """IEC extreme/normal turbulence parameters
    (reference raft/pyIECWind.py:8-77)."""

    def __init__(self, turbine_class="I", turbulence_class="B", z_hub=90.0):
        self.turbine_class = turbine_class
        self.turbulence_class = turbulence_class
        self.z_hub = z_hub
        self.V_ref = _TURBINE_CLASS_VREF[turbine_class]
        self.V_ave = 0.2 * self.V_ref
        self.I_ref = _TURBULENCE_CLASS_IREF[turbulence_class]
        self.Sigma_1 = 42.0 if z_hub > 60 else 0.7 * z_hub

    def NTM(self, V_hub):
        """Normal turbulence model sigma_1 (IEC 6.3.1.3)."""
        return self.I_ref * (0.75 * V_hub + 5.6)

    def ETM(self, V_hub):
        """Extreme turbulence model sigma_1 (IEC 6.3.2.3)."""
        c = 2.0
        return c * self.I_ref * (0.072 * (self.V_ave / c + 3) * (V_hub / c - 4) + 10)

    def EWM(self, V_hub):
        """Extreme wind model sigma_1 (IEC 6.3.2.1)."""
        return 0.11 * V_hub

    def EWM_speeds(self):
        """Extreme wind speeds (steady 50-yr/1-yr, turbulent 50-yr/1-yr)
        (IEC 6.3.2.1; reference raft/pyIECWind.py:66-77)."""
        V_e50 = 1.4 * self.V_ref
        return V_e50, 0.8 * V_e50, self.V_ref, 0.8 * self.V_ref


def parse_turbulence(turbulence):
    """Decode the case 'turbulence' entry: either a float turbulence
    intensity (NTM assumed) or a class string like 'IB_NTM'
    (reference raft/raft_rotor.py:566-596).

    Returns (I_ref or None, turbine_class, turbulence_class, model).
    """
    if isinstance(turbulence, (int, float)):
        return float(turbulence), "I", "B", "NTM"
    s = str(turbulence)
    cls = ""
    for ch in s:
        if ch in ("I", "V"):
            cls += ch
        else:
            break
    if not cls:
        raise ValueError(
            f"Turbulence class must start with I, II, III, or IV: {turbulence}"
        )
    categ = s[len(cls)]
    try:
        model = s.split("_")[1]
    except IndexError:
        raise ValueError(f"Error reading the turbulence model: {turbulence}")
    return None, cls, categ, model


def kaimal_rotor_spectrum(w, V_ref, HH, R, turbulence):
    """Rotor-averaged Kaimal turbulence spectra (U, V, W, Rot) at frequencies
    w [rad/s] (reference raft/raft_rotor.py:551-643).

    V_ref : hub wind speed; HH : hub height; R : rotor radius;
    turbulence : case turbulence entry (intensity float or 'IB_NTM' style).
    Returns (U, V, W, Rot) PSDs [(m/s)^2 / (rad/s)] — Rot is the
    rotor-averaged longitudinal spectrum used for thrust excitation.
    """
    f = np.asarray(w) / 2 / np.pi

    I_ref_override, cls, categ, model = parse_turbulence(turbulence)
    iec = IECWind(cls, categ, z_hub=HH)
    if I_ref_override is not None:
        iec.I_ref = I_ref_override
        model = "NTM"

    if model == "NTM":
        sigma_1 = iec.NTM(V_ref)
    elif model == "ETM":
        sigma_1 = iec.ETM(V_ref)
    elif model == "EWM":
        sigma_1 = iec.EWM(V_ref)
    else:
        raise ValueError(f"Wind model must be NTM, ETM, or EWM, not {model}")

    # turbulence scale parameters, IEC 61400-1-2019 Annex C3
    L_1 = 0.7 * HH if HH <= 60 else 42.0
    sigma_u, L_u = sigma_1, 8.1 * L_1
    sigma_v, L_v = 0.8 * sigma_1, 2.7 * L_1
    sigma_w, L_w = 0.5 * sigma_1, 0.66 * L_1

    U = (4 * L_u / V_ref) * sigma_u**2 / (1 + 6 * f * L_u / V_ref) ** (5.0 / 3.0)
    V = (4 * L_v / V_ref) * sigma_v**2 / (1 + 6 * f * L_v / V_ref) ** (5.0 / 3.0)
    W = (4 * L_w / V_ref) * sigma_w**2 / (1 + 6 * f * L_w / V_ref) ** (5.0 / 3.0)

    kappa = 12 * np.sqrt((f / V_ref) ** 2 + (0.12 / L_u) ** 2)

    with np.errstate(over="ignore", invalid="ignore"):
        Rot = (2 * U / (R * kappa) ** 3) * (
            modstruve(1, 2 * R * kappa) - iv(1, 2 * R * kappa) - 2 / np.pi
            + R * kappa
            * (-2 * modstruve(-2, 2 * R * kappa) + 2 * iv(2, 2 * R * kappa) + 1)
        )
    Rot = np.nan_to_num(Rot, nan=0.0, posinf=0.0, neginf=0.0)
    return U, V, W, Rot


# --------------------------------------------------------------------------
# IEC 61400-1 transient (deterministic extreme) events — OpenFAST support
# (reference raft/pyIECWind.py:79-416).  Each event method returns a list of
# (label, table) pairs where ``table`` is an [nt, 9] array in OpenFAST
# uniform-wind column order:
#   time, V, direction, V_vert, shear_horz, shear_vert(power-law),
#   shear_vert_lin, gust speed, upflow
# --------------------------------------------------------------------------

_WND_COLUMNS = [
    ("Time", "", "(s)"), ("Wind", "Speed", "(m/s)"), ("Wind", "Dir", "(deg)"),
    ("Vertical", "Speed", "(m/s)"), ("Horiz.", "Shear", "(-)"),
    ("Pwr. Law", "Vert. Shr", "(-)"), ("Lin. Vert.", "Shear", "(-)"),
    ("Gust", "Speed", "(m/s)"), ("Upflow", "Angle", "(deg)"),
]

_ALPHA = 0.2  # normal wind-profile power-law exponent (IEC 6.3.1.2)


class IECTransients:
    """Deterministic IEC 61400-1 ed.3 extreme events as time tables, plus
    the OpenFAST `.wnd` uniform-wind writer.

    Parameters mirror the reference's pyIECWind_extreme attributes
    (reference raft/pyIECWind.py:10-23): hub height ``z_hub``, rotor
    diameter ``D``, transient start time ``T_start``, time step ``dt``,
    total file span ``T0..TF``, and which signed variants to emit
    (``dir_change`` in '+'/'-'/'both', ``shear_orient`` in 'v'/'h'/'both').
    """

    def __init__(self, turbine_class="I", turbulence_class="B", z_hub=90.0,
                 D=126.0, vert_slope=0.0, dt=0.05, T_start=30.0,
                 T0=0.0, TF=630.0, dir_change="both", shear_orient="both"):
        self.iec = IECWind(turbine_class, turbulence_class, z_hub=z_hub)
        self.z_hub = z_hub
        self.D = D
        self.vert_slope = vert_slope
        self.dt = dt
        self.T_start = T_start
        self.T0 = T0
        self.TF = TF
        self.dir_change = dir_change
        self.shear_orient = shear_orient

    def _flow_angles(self, V_hub_in):
        """Split the inflow into horizontal/vertical components for a sloped
        site (reference pyIECWind.py:91-92)."""
        s = np.deg2rad(self.vert_slope)
        return V_hub_in * np.cos(s), V_hub_in * np.sin(s)

    def _table(self, t, **cols):
        """Assemble the 9-column table; unspecified columns default to the
        steady baseline (V=V_hub, power-law shear alpha)."""
        base = {
            "V": cols.pop("V_hub", 0.0) * np.ones_like(t),
            "dir": np.zeros_like(t),
            "V_vert": cols.pop("V_vert", 0.0) * np.ones_like(t),
            "shear_horz": np.zeros_like(t),
            "shear_vert": _ALPHA * np.ones_like(t),
            "shear_vert_lin": np.zeros_like(t),
            "gust": np.zeros_like(t),
            "upflow": np.zeros_like(t),
        }
        for key, val in cols.items():
            base[key] = np.broadcast_to(val, t.shape).astype(float)
        return np.column_stack([t] + [base[key] for key in
                                      ["V", "dir", "V_vert", "shear_horz",
                                       "shear_vert", "shear_vert_lin",
                                       "gust", "upflow"]])

    def _signs(self):
        out = []
        if self.dir_change.lower() in ("both", "+"):
            out.append(+1.0)
        if self.dir_change.lower() in ("both", "-"):
            out.append(-1.0)
        return out

    def EOG(self, V_hub_in):
        """Extreme operating gust (IEC 6.3.2.2): Mexican-hat gust of
        amplitude min(1.35(V_e1 − V_hub), 3.3 σ1/(1+0.1 D/Σ1)) over 10.5 s."""
        T = 10.5
        t = np.arange(0.0, T + 0.5 * self.dt, self.dt)
        V_hub, V_vert = self._flow_angles(V_hub_in)
        sigma_1 = self.iec.NTM(V_hub)
        _, V_e1, _, _ = self.iec.EWM_speeds()
        V_gust = min(
            1.35 * (V_e1 - V_hub),
            3.3 * sigma_1 / (1 + 0.1 * self.D / self.iec.Sigma_1),
        )
        gust_t = np.where(
            t < T,
            -0.37 * V_gust * np.sin(3 * np.pi * t / T)
            * (1 - np.cos(2 * np.pi * t / T)),
            0.0,
        )
        return [("EOG", self._table(t, V_hub=V_hub, V_vert=V_vert,
                                    gust=gust_t))], sigma_1

    def EDC(self, V_hub_in):
        """Extreme direction change (IEC 6.3.2.4): half-cosine direction ramp
        to ±Theta_e over 6 s."""
        T = 6.0
        t = np.arange(0.0, T + 0.5 * self.dt, self.dt)
        V_hub, V_vert = self._flow_angles(V_hub_in)
        sigma_1 = self.iec.NTM(V_hub)
        theta_e = np.rad2deg(
            4.0 * np.arctan(
                sigma_1 / (V_hub * (1 + 0.01 * self.D / self.iec.Sigma_1))
            )
        )
        theta_e = min(theta_e, 180.0)
        ramp = 0.5 * theta_e * (1 - np.cos(np.pi * np.minimum(t, T) / T))
        return [
            (f"EDC_{'P' if s > 0 else 'N'}",
             self._table(t, V_hub=V_hub, V_vert=V_vert, dir=s * ramp))
            for s in self._signs()
        ], sigma_1

    def ECD(self, V_hub_in):
        """Extreme coherent gust with direction change (IEC 6.3.2.5):
        +15 m/s speed rise with simultaneous ±Theta_cg rotation over 10 s."""
        T, V_cg = 10.0, 15.0
        t = np.arange(0.0, T + 0.5 * self.dt, self.dt)
        V_hub, V_vert = self._flow_angles(V_hub_in)
        sigma_1 = self.iec.NTM(V_hub)
        theta_cg = 180.0 if V_hub < 4.0 else 720.0 / V_hub
        rise = 0.5 * (1 - np.cos(np.pi * np.minimum(t, T) / T))
        return [
            (f"ECD_{'P' if s > 0 else 'N'}",
             self._table(t, V_hub=0.0, V=V_hub + V_cg * rise,
                         V_vert=V_vert, dir=s * theta_cg * rise))
            for s in self._signs()
        ], sigma_1

    def EWS(self, V_hub_in):
        """Extreme wind shear (IEC 6.3.2.6): transient linear vertical or
        horizontal shear pulse over 12 s."""
        T, beta = 12.0, 6.4
        t = np.arange(0.0, T + 0.5 * self.dt, self.dt)
        V_hub, V_vert = self._flow_angles(V_hub_in)
        sigma_1 = self.iec.NTM(V_hub)
        pulse = (
            (2.5 + 0.2 * beta * sigma_1 * (self.D / self.iec.Sigma_1) ** 0.25)
            * (1 - np.cos(2 * np.pi * t / T)) / V_hub
        )
        out = []
        for s in self._signs():
            tag = "P" if s > 0 else "N"
            if self.shear_orient.lower() in ("both", "v"):
                out.append((f"EWS_V_{tag}",
                            self._table(t, V_hub=V_hub, V_vert=V_vert,
                                        shear_vert_lin=s * pulse)))
            if self.shear_orient.lower() in ("both", "h"):
                out.append((f"EWS_H_{tag}",
                            self._table(t, V_hub=V_hub, V_vert=V_vert,
                                        shear_horz=s * pulse)))
        return out, sigma_1

    def write_wnd(self, path, table, comments=()):
        """Write one OpenFAST uniform-wind file: shift the transient to
        T_start and pad steady rows out to [T0, TF]
        (reference raft/pyIECWind.py:373-403)."""
        data = np.asarray(table, float).copy()
        data[:, 0] += self.T_start
        data = np.vstack([data[0], data, data[-1]])
        data[0, 0] = self.T0
        data[-1, 0] = self.TF

        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            f.write("! Wind file generated by raft_tpu.wind "
                    "- IEC 61400-1 3rd Edition\n")
            for c in comments:
                f.write(f"! {c}\n")
            f.write("! " + "-" * 63 + "\n")
            for irow in range(3):
                f.write("! " + "".join(
                    c[irow].center(12) for c in _WND_COLUMNS) + "\n")
            for row in data:
                f.write("  " + "".join(
                    f"{val:.6f}".center(12) for val in row) + "\n")
        return os.path.abspath(path)

    def execute(self, Vtype, V_hub, outdir=".", case_name="case"):
        """Generate every requested event's .wnd files
        (reference raft/pyIECWind.py:405-416).  Returns the file paths."""
        events = []
        if "EOG" in Vtype:
            events += self.EOG(V_hub)[0]
        if "EDC" in Vtype:
            events += self.EDC(V_hub)[0]
        if "ECD" in Vtype:
            events += self.ECD(V_hub)[0]
        if "EWS" in Vtype:
            events += self.EWS(V_hub)[0]
        paths = []
        comments = [
            f"IEC Turbine Class {self.iec.turbine_class}, "
            f"IEC Turbulence Category {self.iec.turbulence_class}",
            f"{self.D:.2f} m rotor diameter, {self.z_hub:.2f} m hub height",
            f"V_hub = {V_hub:.2f} m/s",
        ]
        for label, table in events:
            fname = f"{case_name}_{label}_U{V_hub:2.1f}.wnd"
            paths.append(
                self.write_wnd(os.path.join(outdir, fname), table, comments)
            )
        return paths
