"""Deterministic fault injection for the serving fault envelope.

Production robustness claims are worthless untested, and real faults
(backend hangs, corrupted cache files, NaN storms) are rare and
unreproducible.  This module injects them ON DEMAND and DETERMINISTICALLY
so the chaos matrix (tests/test_chaos.py) can assert the envelope's
contracts: healthy batch-mates bit-unaffected, breaker state machine
correct, shedding engages/recovers, shutdown resolves every handle.

Enabled ONLY via the environment::

    RAFT_TPU_CHAOS="<fault>[;<fault>...]:<seed>"
    fault = name[=value][@rid[,rid...]][*times][%pct]

 - ``name``   one of the FAULTS table below;
 - ``value``  fault parameter (stall seconds for the stall faults);
 - ``@rids``  restrict to these engine request ids (1-based submit
   order); absent = any request;
 - ``*times`` fire at most this many times (process-wide); absent =
   unlimited;
 - ``%pct``   fire with this probability — decided by a seeded hash of
   (seed, name, rid, occurrence), NOT an RNG stream, so the decision for
   a given request is independent of call order and replays identically;
 - ``seed``   required integer; the whole schedule is a pure function of
   (spec, seed, request ids).

Examples::

    RAFT_TPU_CHAOS="prep_raise@2:7"              # rid 2's prep raises
    RAFT_TPU_CHAOS="dispatch_stall=2.0*1:42"     # first dispatch hangs 2s
    RAFT_TPU_CHAOS="nan_lane@3;backend_error*1:1"

Fault classes and their hook points:

==================  ======================================================
``prep_raise``      host-side prep raises ChaosError (Engine._prepare)
``prep_slow``       host-side prep stalls ``value`` seconds (default 1.0)
``nan_lane``        the request's wave-excitation lanes are set to NaN at
                    pack time — the IN-GRAPH fault: the dynamics NaN
                    quarantine (raft_tpu/health.py) must freeze exactly
                    these lanes and no others
``dispatch_stall``  the bucket dispatch stalls ``value`` seconds (default
                    5.0) — what the engine watchdog must catch
``backend_error``   the dispatch raises ChaosBackendError, a
                    TransientError the retry policy may re-attempt
``corrupt_cache``   a just-written prep-cache entry is overwritten with
                    garbage — the load path must refuse + delete it
``conn_drop``       the HTTP transport closes the client socket after the
                    accepted chunk but before the terminal result line
                    (serve/transport.py) — the client sees a dropped
                    stream while the engine handle still resolves
``replica_kill``    the router SIGKILLs the replica it just forwarded the
                    request to (serve/router.py) — the in-flight request
                    must be retried on another replica, bit-identically.
                    On the sweep path the kill fires after the FIRST
                    streamed chunk, forcing the mid-stream chunk-failover
                    path (completed chunks checkpointed, only the
                    remaining designs resubmitted)
``replica_slow``    the router's wire client stalls ``value`` seconds
                    (default 0.5) after putting the request on the wire,
                    then gives up on the reply as a too-slow replica
                    (serve/transport.py, ``WireClient.solve``) — the
                    router must retry on the next ring replica,
                    bit-identically
``corrupt_result_cache``  a just-written solve-RESULT cache entry
                    (serve/result_cache.py) is overwritten with garbage
                    — ``get`` must refuse + delete it and the engine
                    recompute bit-identical answers, never serve the
                    corrupt bytes
``dup_inflight``    a single-flight COALESCING LEADER (serve/router.py)
                    stalls ``value`` seconds (default 0.25, the window
                    followers pile in during) and then fails WITHOUT
                    forwarding — its coalesced followers must NOT
                    inherit the failure: each retries with a fresh
                    dispatch under its own rid, bit-identically
``corrupt_manifest``  a just-persisted popularity ledger / warm-handoff
                    manifest (serve/result_cache.py) is overwritten with
                    garbage — the refusing loader must log, delete and
                    rebuild it empty; a replica spawn handed a corrupt
                    manifest must come up clean, never crash
``stale_handoff``   the warm-handoff manifest shipped to a freshly
                    spawned replica names ``value`` (default 3) entries
                    that no longer exist on disk (evicted / bogus keys)
                    — the replica's preload must count them as plain
                    misses and keep going
``net_partition``   the router's wire client drops /v1/* POST traffic
                    (serve/transport.py, ``WireClient``) while GET
                    probes (/healthz, /statz, /versionz) still answer —
                    the gray failure a partitioned host produces.  The
                    rid slot targets a replica PORT
                    (``net_partition@PORT``); without ``@`` every
                    endpoint is partitioned.  Forwards surface
                    ConnectionDropped and the router must fail over to
                    surviving replicas, bit-identically
``wire_corrupt``    a decoded response payload (serve/transport.py,
                    ``WireClient``) has one value flipped in flight
                    before checksum verification — the embedded payload
                    checksum (serve/wire.py) must refuse it as
                    ConnectionDropped so the router retries; corrupt Xi
                    bits are never decoded into a result.  ``@PORT``
                    targets one endpoint
``handshake_skew``  the /versionz flag surface a peer reports during
                    ``Router.attach_remote`` (serve/router.py) is
                    mutated to a bogus code_version — the handshake must
                    REFUSE the peer with a logged reason and never add
                    it to the ring
==================  ======================================================

Per-rid targeting caveat: the engine deduplicates prep per design key,
so ``prep_raise``/``prep_slow`` intercept the rid that OWNS the prep
(the first request to submit that design) — a request coalescing onto
an in-flight prep is not intercepted, and if the shared prep raises the
follower retries once with a fresh prep under its own rid rather than
inheriting the owner's failure.  To target a specific rid, give it a
design key of its own (the chaos matrix does).

The injector NEVER activates without the env var; ``get_injector()``
re-parses only when the env string changes, so one process-wide instance
accounts all fires (``snapshot()`` feeds the engine stats).
"""

import dataclasses
import os
import threading
import time

from raft_tpu.resilience import TransientError, _hash_unit
from raft_tpu.utils.profiling import logger

CHAOS_ENV = "RAFT_TPU_CHAOS"

FAULTS = ("prep_raise", "prep_slow", "nan_lane", "dispatch_stall",
          "backend_error", "corrupt_cache", "conn_drop", "replica_kill",
          "replica_slow", "corrupt_result_cache", "dup_inflight",
          "corrupt_manifest", "stale_handoff", "net_partition",
          "wire_corrupt", "handshake_skew")

_DEFAULT_VALUES = {"prep_slow": 1.0, "dispatch_stall": 5.0,
                   "replica_slow": 0.5, "dup_inflight": 0.25,
                   "stale_handoff": 3.0}


class ChaosError(RuntimeError):
    """An injected non-transient fault (quarantined, never retried)."""


class ChaosBackendError(TransientError):
    """An injected transient backend fault (retry-eligible)."""


@dataclasses.dataclass
class _Rule:
    name: str
    value: float = None
    rids: frozenset = None     # None = any request
    times: int = None          # None = unlimited
    pct: float = 100.0
    fired: int = 0
    seen: int = 0              # occurrence counter for the pct hash


def parse_spec(text):
    """``"fault[;fault...]:seed"`` -> (rules, seed).  Raises ValueError
    with the offending token on any malformed spec — a typo'd chaos spec
    must fail loudly, not silently inject nothing."""
    text = text.strip()
    if ":" not in text:
        raise ValueError(
            f"chaos spec {text!r} lacks the required ':<seed>' suffix")
    spec, seed_s = text.rsplit(":", 1)
    try:
        seed = int(seed_s)
    except ValueError:
        raise ValueError(f"chaos seed {seed_s!r} is not an integer")
    rules = []
    for tok in filter(None, (t.strip() for t in spec.split(";"))):
        rule = _Rule(name=tok)
        for marker, field, conv in (("%", "pct", float),
                                    ("*", "times", int),
                                    ("@", "rids", None)):
            if marker in rule.name:
                rule.name, _, raw = rule.name.partition(marker)
                if conv is None:
                    try:
                        rule.rids = frozenset(
                            int(r) for r in raw.split(","))
                    except ValueError:
                        raise ValueError(
                            f"chaos rids {raw!r} must be integers")
                else:
                    try:
                        setattr(rule, field, conv(raw))
                    except ValueError:
                        raise ValueError(
                            f"chaos {field} {raw!r} is not a number")
        if "=" in rule.name:
            rule.name, _, raw = rule.name.partition("=")
            try:
                rule.value = float(raw)
            except ValueError:
                raise ValueError(f"chaos value {raw!r} is not a number")
        if rule.name not in FAULTS:
            raise ValueError(
                f"unknown chaos fault {rule.name!r} (choose from "
                f"{', '.join(FAULTS)})")
        if rule.value is None:
            rule.value = _DEFAULT_VALUES.get(rule.name)
        rules.append(rule)
    if not rules:
        raise ValueError(f"chaos spec {text!r} names no faults")
    return rules, seed


class ChaosInjector:
    """One parsed chaos schedule; thread-safe fire accounting."""

    def __init__(self, rules, seed, spec_text=""):
        self.rules = rules
        self.seed = seed
        self.spec_text = spec_text
        self._lock = threading.Lock()
        self.fires = []                      # [(name, rid)]

    @classmethod
    def from_spec(cls, text):
        rules, seed = parse_spec(text)
        return cls(rules, seed, spec_text=text)

    def should(self, name, rid=None):
        """Whether fault ``name`` fires for request ``rid`` now.
        Deterministic: the pct decision hashes (seed, name, rid,
        occurrence) — no RNG state, no clock."""
        with self._lock:
            for rule in self.rules:
                if rule.name != name:
                    continue
                if rule.rids is not None and rid not in rule.rids:
                    continue
                rule.seen += 1
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.pct < 100.0:
                    u = _hash_unit(self.seed, name, rid, rule.seen)
                    if u >= rule.pct / 100.0:
                        continue
                rule.fired += 1
                self.fires.append((name, rid))
                logger.warning("chaos: injecting %s (rid=%s, fire #%d)",
                               name, rid, rule.fired)
                return rule
        return None

    # ------------------------------------------------------ hook helpers

    def raise_if(self, name, rid=None, exc=ChaosError):
        rule = self.should(name, rid)
        if rule is not None:
            raise exc(f"chaos-injected {name} (rid={rid}, "
                      f"seed={self.seed})")

    def stall_if(self, name, rid=None, sleep=time.sleep):
        """Sleep the rule's value seconds if the fault fires; returns the
        stall duration (0.0 when it did not fire)."""
        rule = self.should(name, rid)
        if rule is None:
            return 0.0
        dur = float(rule.value if rule.value is not None else 1.0)
        sleep(dur)
        return dur

    def poison_if(self, name, rid, args):
        """Replace the request's wave-excitation lanes with NaN if the
        fault fires (the in-graph NaN-quarantine fault).  Returns a NEW
        args tuple — cached _Prepped objects are never mutated."""
        from raft_tpu.health import inject_nonfinite_excitation

        if self.should(name, rid) is None:
            return args
        return inject_nonfinite_excitation(args)

    def corrupt_if(self, name, path):
        """Overwrite ``path`` with garbage bytes if the fault fires (the
        corrupt-cache-entry fault: loaders must refuse + delete)."""
        if self.should(name) is None:
            return False
        with open(path, "wb") as fh:
            fh.write(b"\x00chaos-corrupted\x00" * 4)
        return True

    def snapshot(self):
        with self._lock:
            counts = {}
            for name, _rid in self.fires:
                counts[name] = counts.get(name, 0) + 1
            return {"spec": self.spec_text, "seed": self.seed,
                    "fires": counts, "total_fires": len(self.fires)}


# one cached injector per env-string value, so every layer (engine, prep
# cache) shares fire accounting within a process, and tests that
# monkeypatch the env get a fresh schedule
_cached = {"text": None, "injector": None}
_cached_lock = threading.Lock()


def get_injector(environ=None):
    """The process's active injector, or None when RAFT_TPU_CHAOS is
    unset.  Re-parses only when the env string changes."""
    env = os.environ if environ is None else environ
    text = env.get(CHAOS_ENV, "").strip()
    with _cached_lock:
        if not text:
            _cached["text"], _cached["injector"] = None, None
            return None
        if text != _cached["text"]:
            _cached["injector"] = ChaosInjector.from_spec(text)
            _cached["text"] = text
        return _cached["injector"]


