// Native mesher core for raft_tpu: adaptive azimuthal revolve of a member
// radius profile into surface panels.  This is the one host-side component
// whose data-dependent control flow (azimuth-count hysteresis, 2:1 transition
// rings) is XLA-hostile (SURVEY.md §2.3), so it is implemented natively; the
// Python fallback in raft_tpu/mesh.py::revolve_profile produces identical
// output (asserted by tests/test_mesh.py).
//
// Build: make -C raft_tpu/native   (g++ -O2 -shared -fPIC)
// ABI: raft_revolve_profile(r, z, n, da_max, out, cap) -> npanels written,
//      or -1 if more than `cap` panels would be required.

#include <cmath>
#include <cstdint>

namespace {

constexpr double kTwoPi = 6.283185307179586476925286766559;

struct Writer {
  double* out;      // [cap][4][3]
  int cap;
  int n = 0;
  bool overflow = false;

  void quad(const double* a, const double* b, const double* c,
            const double* d) {
    if (n >= cap) {
      overflow = true;
      return;
    }
    double* p = out + static_cast<int64_t>(n) * 12;
    for (int i = 0; i < 3; ++i) p[i] = a[i];
    for (int i = 0; i < 3; ++i) p[3 + i] = b[i];
    for (int i = 0; i < 3; ++i) p[6 + i] = c[i];
    for (int i = 0; i < 3; ++i) p[9 + i] = d[i];
    ++n;
  }
};

// One full ring of naz quads between profile points (r1,z1) and (r2,z2).
// Winding matches mesh.py::_ring_quads (normals out of the body).
void ring(Writer& w, double r1, double z1, double r2, double z2, int naz) {
  for (int ia = 0; ia < naz; ++ia) {
    double th0 = kTwoPi * ia / naz;
    double th1 = kTwoPi * (ia + 1) / naz;
    double c0 = std::cos(th0), s0 = std::sin(th0);
    double c1 = std::cos(th1), s1 = std::sin(th1);
    double a[3] = {r1 * c1, r1 * s1, z1};
    double b[3] = {r2 * c1, r2 * s1, z2};
    double c[3] = {r2 * c0, r2 * s0, z2};
    double d[3] = {r1 * c0, r1 * s0, z1};
    w.quad(a, b, c, d);
  }
}

// 2:1 transition ring; refine_bottom == true means the (r2,z2) edge carries
// the finer discretization.  Mirrors mesh.py::_transition_ring.
void transition(Writer& w, double r1, double z1, double r2, double z2,
                int naz, bool refine_bottom) {
  for (int ia = 1; ia <= naz / 2; ++ia) {
    double th1 = (ia - 1.0) * kTwoPi / naz * 2.0;
    double th2 = (ia - 0.5) * kTwoPi / naz * 2.0;
    double th3 = (ia - 0.0) * kTwoPi / naz * 2.0;
    double c1 = std::cos(th1), s1 = std::sin(th1);
    double c2 = std::cos(th2), s2 = std::sin(th2);
    double c3 = std::cos(th3), s3 = std::sin(th3);
    if (refine_bottom) {
      double mx = (r1 * c1 + r1 * c3) / 2.0, my = (r1 * s1 + r1 * s3) / 2.0;
      double a0[3] = {mx, my, z1};
      double b0[3] = {r2 * c2, r2 * s2, z2};
      double c0[3] = {r2 * c1, r2 * s1, z2};
      double d0[3] = {r1 * c1, r1 * s1, z1};
      w.quad(a0, b0, c0, d0);
      double a1[3] = {r1 * c3, r1 * s3, z1};
      double b1[3] = {r2 * c3, r2 * s3, z2};
      double c1v[3] = {r2 * c2, r2 * s2, z2};
      double d1[3] = {mx, my, z1};
      w.quad(a1, b1, c1v, d1);
    } else {
      double mx = (r2 * c1 + r2 * c3) / 2.0, my = (r2 * s1 + r2 * s3) / 2.0;
      double a0[3] = {r1 * c2, r1 * s2, z1};
      double b0[3] = {mx, my, z2};
      double c0[3] = {r2 * c1, r2 * s1, z2};
      double d0[3] = {r1 * c1, r1 * s1, z1};
      w.quad(a0, b0, c0, d0);
      double a1[3] = {r1 * c3, r1 * s3, z1};
      double b1[3] = {r2 * c3, r2 * s3, z2};
      double c1v[3] = {mx, my, z2};
      double d1[3] = {r1 * c2, r1 * s2, z1};
      w.quad(a1, b1, c1v, d1);
    }
  }
}

}  // namespace

extern "C" int raft_revolve_profile(const double* r_rp, const double* z_rp,
                                    int n, double da_max, double* out,
                                    int cap) {
  Writer w{out, cap};
  int naz = 8;
  for (int i = 0; i + 1 < n; ++i) {
    double r1 = r_rp[i], z1 = z_rp[i];
    double r2 = r_rp[i + 1], z2 = z_rp[i + 1];
    while (r1 * kTwoPi / naz >= da_max / 2.0 &&
           r2 * kTwoPi / naz >= da_max / 2.0)
      naz *= 2;
    while (naz > 2 && r1 * kTwoPi / naz < da_max / 2.0 &&
           r2 * kTwoPi / naz < da_max / 2.0)
      naz /= 2;
    double w1 = r1 * kTwoPi / naz;
    double w2 = r2 * kTwoPi / naz;
    if (w1 < da_max / 2.0 && w2 >= da_max / 2.0)
      transition(w, r1, z1, r2, z2, naz, /*refine_bottom=*/true);
    else if (w2 < da_max / 2.0 && w1 >= da_max / 2.0)
      transition(w, r1, z1, r2, z2, naz, /*refine_bottom=*/false);
    else
      ring(w, r1, z1, r2, z2, naz);
  }
  return w.overflow ? -1 : w.n;
}
