"""Solver-health subsystem: structured per-case solve reports, the
recovery-tier vocabulary, and host-side quarantine/reporting helpers.

The reference's solver health accounting is a single print statement
("WARNING - Iteration of dynamics solve unsuccessful...", reference
raft/raft_model.py:603-611) and nothing else: a NaN'd case propagates
silently into the response statistics, and a design point that throws
during setup kills a parameter sweep outright.  At production-sweep scale
(ROADMAP north star: design sweeps sharded over a device mesh) one bad
lane must not poison a batched solve, so health is tracked *in-graph*:

 - :class:`SolveReport` is a pytree produced inside the traced
   fixed-point loop (raft_tpu/dynamics.py), batched by the same vmaps
   that batch the solve itself — per (design, case) lane it records the
   convergence flag, iteration count, final relative residual, a
   condition estimate of Z(w), a non-finite flag (the NaN quarantine:
   a non-finite iterate freezes the lane at its last finite state), and
   the recovery tier the conditioned-solve ladder escalated to;
 - the host-side helpers below convert the report to NumPy, fan it into
   result dictionaries, and route warnings through the package logger
   (``logging.getLogger("raft_tpu")``) so callers can silence or capture
   solver-health output instead of scraping stdout;
 - :class:`FailedPoint` is the sweep drivers' quarantine record for a
   design point whose *host-side* preparation raised (the CPU mooring
   equilibrium is the usual thrower): the point is reported in the
   result's ``failed`` list with its batch slot masked, and the sweep
   completes.
"""

import dataclasses
import os
from typing import NamedTuple

import numpy as np

from raft_tpu.utils.profiling import logger

# recovery tiers of the conditioned-solve ladder (dynamics.solve_complex_
# 6x6_ladder), escalating per frequency bin:
TIER_BASELINE = 0    # Gauss-Jordan block solve + standard refinement
TIER_REFINE = 1      # extra iterative-refinement steps (residual too large)
TIER_TIKHONOV = 2    # flagged Tikhonov-regularized solve (condition estimate
#                      blew up / solve non-finite, e.g. a zero-damping
#                      resonance making Z(w) numerically singular)
TIER_NAMES = {
    TIER_BASELINE: "baseline",
    TIER_REFINE: "extra-refinement",
    TIER_TIKHONOV: "tikhonov",
}


class SolveReport(NamedTuple):
    """Per-case solver-health record (a JAX pytree: every field is an
    array with the lane batch shape — scalar for one case, [ncase] after
    the case vmap, [ndesign, ncase] in the sweep drivers).

    converged     : bool  — fixed point met the reference's tolerance
    iters         : int   — fixed-point iterations taken (freeze included)
    nonfinite     : bool  — a non-finite iterate was quarantined: the lane
                            froze at its last finite state instead of
                            propagating NaN/Inf through the batch
    recovery_tier : int   — max ladder tier over frequency (TIER_*)
    residual      : float — max over frequency of the final solve's
                            relative residual |b - A x| / |b|
    cond          : float — max over frequency of the row-equilibrated
                            pivot-ratio condition estimate of Z(w)
    """

    converged: object
    iters: object
    nonfinite: object
    recovery_tier: object
    residual: object
    cond: object


@dataclasses.dataclass
class FailedPoint:
    """A sweep design point quarantined on the host side: its
    ``_prepare_design`` (geometry packing / statics / mooring equilibrium)
    raised, so its batch slot was masked and its result rows are NaN."""

    index: int          # position in the sweep's ``points`` list
    point: dict         # the parameter dict of the failed design point
    error: str          # "ExceptionType: message" of what prep raised

    def as_dict(self):
        return {"index": self.index, "point": self.point,
                "error": self.error}


def report_to_numpy(rep):
    """Device SolveReport -> SolveReport of host NumPy arrays."""
    return SolveReport(*(np.asarray(f) for f in rep))


def report_dict(rep, prefix=""):
    """SolveReport -> plain dict of NumPy arrays (for results dicts and
    .npz checkpoints, which cannot hold pytrees)."""
    rep = report_to_numpy(rep)
    return {prefix + name: getattr(rep, name) for name in rep._fields}


def report_from_dict(d, prefix=""):
    """Inverse of :func:`report_dict` (checkpoint reload)."""
    return SolveReport(
        **{name: np.asarray(d[prefix + name]) for name in SolveReport._fields}
    )


def log_report(rep, label="case", log=None, limit=10):
    """Route per-lane solver-health warnings through the package logger.

    Replaces the reference's print-only non-convergence WARNING
    (reference raft/raft_model.py:603-611): callers silence or capture
    these with standard ``logging`` configuration on the ``raft_tpu``
    logger.  Returns the number of unhealthy (non-converged or
    NaN-quarantined) lanes.
    """
    log = log or logger
    rep = report_to_numpy(rep)
    conv = np.atleast_1d(rep.converged)
    nonfin = np.atleast_1d(rep.nonfinite)
    tier = np.atleast_1d(rep.recovery_tier)
    resid = np.atleast_1d(rep.residual)
    bad = np.argwhere(~conv | nonfin)
    for n, idx in enumerate(bad):
        if n >= limit:
            log.warning(
                "%s solver health: ... and %d more unhealthy lanes",
                label, len(bad) - limit,
            )
            break
        i = tuple(int(v) for v in idx)
        tag = f"{label} {i[0] + 1}" if len(i) == 1 else f"{label} {i}"
        if nonfin[tuple(idx)]:
            log.warning(
                "%s produced non-finite iterates; lane quarantined at its "
                "last finite state (NaN frozen, response reported as zero "
                "where no finite iterate exists)", tag,
            )
        else:
            log.warning(
                "%s dynamics iteration did not converge to the tolerance "
                "(residual %.3g, recovery tier %s)",
                tag, float(resid[tuple(idx)]),
                TIER_NAMES.get(int(tier[tuple(idx)]), "?"),
            )
    n_tik = int(np.sum(tier >= TIER_TIKHONOV))
    if n_tik:
        log.warning(
            "%s solver health: %d lane(s) fell back to the flagged "
            "Tikhonov-regularized solve (ill-conditioned Z(w)); their "
            "responses are regularized approximations", label, n_tik,
        )
    return int(len(bad))


def quarantine_cotangents(cts, nonfinite):
    """Adjoint mirror of the NaN-quarantine freeze contract.

    Forward contract: a non-finite iterate freezes its lane at the last
    finite state and raises ``SolveReport.nonfinite`` instead of
    propagating NaN through the batched solve.  The reverse-mode analogue
    (raft_tpu/grad/fixed_point.py) must uphold the same isolation: a
    quarantined lane's adjoint is *flagged zeros* — every cotangent
    flowing out of that lane's solve is scaled to exactly 0.0 where
    ``nonfinite`` is set, so one bad lane cannot poison a batched
    gradient.  Callers detect the quarantine the same way they do in the
    forward pass: by checking the report's ``nonfinite`` flag.

    cts : pytree of cotangent arrays (lane-shaped leading axes broadcast
        against ``nonfinite``); ``nonfinite`` is the scalar-per-lane flag.
    Returns the same pytree with quarantined lanes zeroed.
    """
    import jax
    import jax.numpy as jnp

    def zero_lane(c):
        dt = getattr(c, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.inexact):
            # integer-input cotangents arrive as float0 symbolic zeros —
            # already zero, and no ufunc can scale them
            return c
        # where, not multiply: a quarantined lane's cotangent may be NaN
        # (non-differentiable point of the frozen state), and NaN * 0 is
        # NaN — select() drops it exactly
        return jnp.where(nonfinite, jnp.zeros_like(c), c)

    return jax.tree_util.tree_map(zero_lane, cts)


# ---------------------------------------------------------------------------
# Fault-injection surface: how the chaos harness (raft_tpu/chaos.py)
# produces an in-graph non-finite lane.  Lives HERE, next to the
# quarantine contract it exercises: a NaN'd wave-excitation spectrum
# makes the first dynamics iterate non-finite, the traced fixed point
# freezes that lane at its last finite state, and batch-mates are
# bit-unaffected (vmap lanes are data-independent; docs/robustness.md).
# ---------------------------------------------------------------------------

def inject_nonfinite_excitation(args, value=float("nan")):
    """Return a COPY of the prepared case-input 7-tuple
    (``Model.prepare_case_inputs`` order) with the wave-excitation
    spectrum ``zeta`` (args[0]) replaced by ``value`` in every lane.
    Never mutates its input — cached prep artifacts stay pristine."""
    z0 = np.asarray(args[0])
    return (np.full(z0.shape, value, z0.dtype),) + tuple(args[1:])


# ---------------------------------------------------------------------------
# RAFT_TPU_DEBUG_NANS: opt-in debugging switch.  When set, jax_debug_nans is
# enabled (XLA re-runs the offending primitive un-jitted and raises at the
# first NaN) and Model builds the scan-based "checkable" fixed point that
# jax.experimental.checkify supports (raft_tpu.validate.checked_pipeline).
# ---------------------------------------------------------------------------

DEBUG_NANS_ENV = "RAFT_TPU_DEBUG_NANS"
_TRUTHY = ("1", "true", "yes", "on")


def debug_nans_requested(environ=None):
    """Whether the RAFT_TPU_DEBUG_NANS environment switch is on."""
    env = os.environ if environ is None else environ
    return str(env.get(DEBUG_NANS_ENV, "")).strip().lower() in _TRUTHY


def apply_debug_nans(environ=None):
    """Apply the RAFT_TPU_DEBUG_NANS switch and return its state.

    When the switch is on, enables ``jax_debug_nans``; when off, jax
    config is left untouched (so a user's manual
    ``jax.config.update("jax_debug_nans", True)`` is never clobbered).
    The returned bool doubles as the ``checkable`` pipeline selector.
    """
    on = debug_nans_requested(environ)
    if on:
        import jax

        jax.config.update("jax_debug_nans", True)
    return on
