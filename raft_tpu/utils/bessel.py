"""JAX Bessel functions J0, J1, Y0, Y1 via Abramowitz & Stegun rational
approximations (A&S 9.4.1-9.4.6, |error| < 1e-7 absolute).

Needed on-device by the BEM solver's wave-term evaluation
(raft_tpu/greens.py): jax.scipy.special has no Y0/Y1, and the rotor-averaged
Kaimal spectrum host path uses scipy — these are the TPU-side equivalents.
All functions accept x >= 0 (Y0/Y1 require x > 0).
"""

import jax.numpy as jnp


def _poly(x, coeffs):
    r = coeffs[0]
    for c in coeffs[1:]:
        r = r * x + c
    return r


def j0(x):
    x = jnp.asarray(x)
    ax = jnp.abs(x)
    # |x| <= 3 : A&S 9.4.1
    y = (ax / 3.0) ** 2
    small = _poly(y, [0.00021, -0.0039444, 0.0444479, -0.3163866,
                      1.2656208, -2.2499997, 1.0])
    # |x| > 3 : A&S 9.4.3 modulus/phase
    z = 3.0 / jnp.where(ax > 1e-30, ax, 1.0)
    f0 = _poly(z, [0.00014476, -0.00072805, 0.00137237, -0.00009512,
                   -0.00552740, -0.00000077, 0.79788456])
    t0 = ax + _poly(z, [0.00013558, -0.00029333, -0.00054125, 0.00262573,
                        -0.00003954, -0.04166397, -0.78539816])
    big = f0 * jnp.cos(t0) / jnp.sqrt(jnp.where(ax > 1e-30, ax, 1.0))
    return jnp.where(ax <= 3.0, small, big)


def j1(x):
    x = jnp.asarray(x)
    ax = jnp.abs(x)
    # |x| <= 3 : A&S 9.4.4  (J1/x form)
    y = (ax / 3.0) ** 2
    small = ax * _poly(y, [0.00001109, -0.00031761, 0.00443319, -0.03954289,
                           0.21093573, -0.56249985, 0.5])
    # |x| > 3 : A&S 9.4.6
    z = 3.0 / jnp.where(ax > 1e-30, ax, 1.0)
    f1 = _poly(z, [-0.00020033, 0.00113653, -0.00249511, 0.00017105,
                   0.01659667, 0.00000156, 0.79788456])
    t1 = ax + _poly(z, [-0.00029166, 0.00079824, 0.00074348, -0.00637879,
                        0.00005650, 0.12499612, -2.35619449])
    big = f1 * jnp.cos(t1) / jnp.sqrt(jnp.where(ax > 1e-30, ax, 1.0))
    return jnp.sign(x) * jnp.where(ax <= 3.0, small, big)


def y0(x):
    x = jnp.asarray(x)
    xs = jnp.where(x > 1e-30, x, 1e-30)
    # x <= 3 : A&S 9.4.2
    y = (xs / 3.0) ** 2
    small = (2.0 / jnp.pi) * jnp.log(xs / 2.0) * j0(xs) + _poly(
        y, [-0.00024846, 0.00427916, -0.04261214, 0.25300117, -0.74350384,
            0.60559366, 0.36746691]
    )
    z = 3.0 / xs
    f0 = _poly(z, [0.00014476, -0.00072805, 0.00137237, -0.00009512,
                   -0.00552740, -0.00000077, 0.79788456])
    t0 = xs + _poly(z, [0.00013558, -0.00029333, -0.00054125, 0.00262573,
                        -0.00003954, -0.04166397, -0.78539816])
    big = f0 * jnp.sin(t0) / jnp.sqrt(xs)
    return jnp.where(x <= 3.0, small, big)


def y1(x):
    x = jnp.asarray(x)
    xs = jnp.where(x > 1e-30, x, 1e-30)
    # x <= 3 : A&S 9.4.5  (x*Y1 = (2/pi) x ln(x/2) J1(x) + poly((x/3)^2))
    y = (xs / 3.0) ** 2
    small = (
        (2.0 / jnp.pi) * xs * jnp.log(xs / 2.0) * j1(xs)
        + _poly(y, [0.0027873, -0.0400976, 0.3123951, -1.3164827,
                    2.1682709, 0.2212091, -0.6366198])
    ) / xs
    z = 3.0 / xs
    f1 = _poly(z, [-0.00020033, 0.00113653, -0.00249511, 0.00017105,
                   0.01659667, 0.00000156, 0.79788456])
    t1 = xs + _poly(z, [-0.00029166, 0.00079824, 0.00074348, -0.00637879,
                        0.00005650, 0.12499612, -2.35619449])
    big = f1 * jnp.sin(t1) / jnp.sqrt(xs)
    return jnp.where(x <= 3.0, small, big)


# ---- Struve functions and smooth Bessel parts for the BEM wave kernel ----
# (raft_tpu/greens.py's gather-free Chebyshev evaluation reconstructs the
# kernel from its exact oscillatory part, which involves H0, H1 and the
# entire "smooth" remainders of Y0, Y1 after their log/pole terms)

_EULER = 0.5772156649015329

# H0/H1 power series sum c_k z^{2k+1} (resp z^{2k+2}), z < 6
_H0S = [0.63661977237, -0.070735530263, 0.0028294212105, -5.7743290011e-05,
        7.1288012359e-07, -5.8915712693e-09, 3.4861368458e-11,
        -1.5493941537e-13, 5.3612254452e-16, -1.4851040014e-18,
        3.3675827697e-21, -6.3659409636e-24, 1.0185505542e-26]
_H1S = [0.21220659079, -0.014147106053, 0.00040420303007, -6.4159211123e-06,
        6.4807283963e-08, -4.5319778995e-10, 2.3240912305e-12,
        -9.1140832569e-15, 2.8216976027e-17, -7.0719238164e-20,
        1.4641664216e-22, -2.5463763854e-25, 3.7724094599e-28]
# Chebyshev fits of H0-Y0 and H1-Y1 on z in [6, 16] (abs err ~1e-10)
_HY0C = [0.064149213671, -0.030257562249, 0.0070858627048, -0.0016497512559,
         0.00038230060055, -8.8267921171e-05, 2.0324099412e-05,
         -4.6706257848e-06, 1.0719780769e-06, -2.4585893034e-07,
         5.6373547261e-08, -1.29290359e-08, 2.9737881889e-09,
         -7.1666062767e-10, 1.5637068624e-10]
_HY1C = [0.64375641524, -0.006332819952, 0.0021702608419, -0.00066412978813,
         0.00019055933486, -5.2448827252e-05, 1.4023492519e-05,
         -3.6709886766e-06, 9.4574038273e-07, -2.4065909308e-07,
         6.0649370294e-08, -1.5169827809e-08, 3.7829627243e-09,
         -9.8831808511e-10, 2.2950079932e-10]
# entire series: Y0sm = sum c_k a^{2k} (k>=1), Y1sm = sum c_k a^{2k+1}
_Y0SM = [0.15915494309, -0.014920775915, 0.00050656955267, -8.9944877959e-06,
         9.8579586243e-08, -7.3454983667e-10, 3.966228219e-12,
         -1.6239990502e-14]
_Y1SM = [-0.15915494309, 0.049735919716, -0.0027631066509, 6.7638548095e-05,
         -9.4262211519e-07, 8.5146090341e-09, -5.3920957663e-11,
         2.3818587261e-13]


def _cheb1d(coeffs, x):
    """Clenshaw evaluation of a 1D Chebyshev series at x in [-1, 1]."""
    b1 = b2 = 0.0
    for c in coeffs[:0:-1]:
        b1, b2 = 2.0 * x * b1 - b2 + c, b1
    return x * b1 - b2 + coeffs[0]


def _evenpoly(coeffs, x2, x_pow):
    r = 0.0
    for c in coeffs[::-1]:
        r = r * x2 + c
    return r * x_pow


def struve_h0_minus_y0(x):
    """H0(x) - Y0(x), x >= 0: smooth, monotone ~2/(pi x) decay.  Branches:
    power series minus y0 (x<6), Chebyshev fit ([6,16]), asymptotic
    2/pi (1/x - 1/x^3 + 9/x^5 - 225/x^7) beyond (abs err <~1e-7)."""
    xs = jnp.maximum(jnp.asarray(x), 1e-30)
    x2 = xs * xs
    small = _evenpoly(_H0S, x2, xs) - y0(xs)
    mid = _cheb1d(_HY0C, (xs - 6.0) / 5.0 - 1.0)
    xi = 1.0 / jnp.maximum(xs, 6.0)
    big = (2.0 / jnp.pi) * xi * (1.0 + xi * xi * (-1.0 + xi * xi * (
        9.0 - 225.0 * xi * xi)))
    return jnp.where(xs < 6.0, small, jnp.where(xs <= 16.0, mid, big))


def struve_h1_minus_y1(x):
    """H1(x) - Y1(x), x >= 0 (tends to 2/pi at infinity)."""
    xs = jnp.maximum(jnp.asarray(x), 1e-30)
    x2 = xs * xs
    small = _evenpoly(_H1S, x2, x2) - y1(xs)
    mid = _cheb1d(_HY1C, (xs - 6.0) / 5.0 - 1.0)
    xi2 = 1.0 / jnp.maximum(x2, 36.0)
    big = (2.0 / jnp.pi) * (1.0 + xi2 * (1.0 + xi2 * (
        -2.99179121 + 38.81817939 * xi2)))
    return jnp.where(xs < 6.0, small, jnp.where(xs <= 16.0, mid, big))


def struve_h0(x):
    """Struve H0 (series below 6, (H0-Y0)+Y0 above)."""
    xs = jnp.maximum(jnp.asarray(x), 1e-30)
    small = _evenpoly(_H0S, xs * xs, xs)
    return jnp.where(xs < 6.0, small, struve_h0_minus_y0(xs) + y0(xs))


def struve_h1(x):
    """Struve H1 (series below 6, (H1-Y1)+Y1 above)."""
    xs = jnp.maximum(jnp.asarray(x), 1e-30)
    small = _evenpoly(_H1S, xs * xs, xs * xs)
    return jnp.where(xs < 6.0, small, struve_h1_minus_y1(xs) + y1(xs))


def y0_smooth(x):
    """Y0(x) - (2/pi)(ln(x/2)+gamma) J0(x) — the entire remainder of Y0
    (series below 1.2 where the direct subtraction cancels, direct form
    above)."""
    xs = jnp.maximum(jnp.asarray(x), 1e-30)
    ser = _evenpoly(_Y0SM, xs * xs, xs * xs)
    direct = y0(xs) - (2.0 / jnp.pi) * (jnp.log(xs / 2.0) + _EULER) * j0(xs)
    return jnp.where(xs < 1.2, ser, direct)


def y1_smooth(x):
    """Y1(x) + (2/pi)/x - (2/pi)(ln(x/2)+gamma) J1(x) — the entire
    remainder of Y1 (the 1/x pole subtraction is catastrophic in f32 below
    ~0.1, hence the series branch)."""
    xs = jnp.maximum(jnp.asarray(x), 1e-30)
    ser = _evenpoly(_Y1SM, xs * xs, xs)
    direct = (y1(xs) + (2.0 / jnp.pi) / xs
              - (2.0 / jnp.pi) * (jnp.log(xs / 2.0) + _EULER) * j1(xs))
    return jnp.where(xs < 1.2, ser, direct)
