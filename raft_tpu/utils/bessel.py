"""JAX Bessel functions J0, J1, Y0, Y1 via Abramowitz & Stegun rational
approximations (A&S 9.4.1-9.4.6, |error| < 1e-7 absolute).

Needed on-device by the BEM solver's wave-term evaluation
(raft_tpu/greens.py): jax.scipy.special has no Y0/Y1, and the rotor-averaged
Kaimal spectrum host path uses scipy — these are the TPU-side equivalents.
All functions accept x >= 0 (Y0/Y1 require x > 0).
"""

import jax.numpy as jnp


def _poly(x, coeffs):
    r = coeffs[0]
    for c in coeffs[1:]:
        r = r * x + c
    return r


def j0(x):
    x = jnp.asarray(x)
    ax = jnp.abs(x)
    # |x| <= 3 : A&S 9.4.1
    y = (ax / 3.0) ** 2
    small = _poly(y, [0.00021, -0.0039444, 0.0444479, -0.3163866,
                      1.2656208, -2.2499997, 1.0])
    # |x| > 3 : A&S 9.4.3 modulus/phase
    z = 3.0 / jnp.where(ax > 1e-30, ax, 1.0)
    f0 = _poly(z, [0.00014476, -0.00072805, 0.00137237, -0.00009512,
                   -0.00552740, -0.00000077, 0.79788456])
    t0 = ax + _poly(z, [0.00013558, -0.00029333, -0.00054125, 0.00262573,
                        -0.00003954, -0.04166397, -0.78539816])
    big = f0 * jnp.cos(t0) / jnp.sqrt(jnp.where(ax > 1e-30, ax, 1.0))
    return jnp.where(ax <= 3.0, small, big)


def j1(x):
    x = jnp.asarray(x)
    ax = jnp.abs(x)
    # |x| <= 3 : A&S 9.4.4  (J1/x form)
    y = (ax / 3.0) ** 2
    small = ax * _poly(y, [0.00001109, -0.00031761, 0.00443319, -0.03954289,
                           0.21093573, -0.56249985, 0.5])
    # |x| > 3 : A&S 9.4.6
    z = 3.0 / jnp.where(ax > 1e-30, ax, 1.0)
    f1 = _poly(z, [-0.00020033, 0.00113653, -0.00249511, 0.00017105,
                   0.01659667, 0.00000156, 0.79788456])
    t1 = ax + _poly(z, [-0.00029166, 0.00079824, 0.00074348, -0.00637879,
                        0.00005650, 0.12499612, -2.35619449])
    big = f1 * jnp.cos(t1) / jnp.sqrt(jnp.where(ax > 1e-30, ax, 1.0))
    return jnp.sign(x) * jnp.where(ax <= 3.0, small, big)


def y0(x):
    x = jnp.asarray(x)
    xs = jnp.where(x > 1e-30, x, 1e-30)
    # x <= 3 : A&S 9.4.2
    y = (xs / 3.0) ** 2
    small = (2.0 / jnp.pi) * jnp.log(xs / 2.0) * j0(xs) + _poly(
        y, [-0.00024846, 0.00427916, -0.04261214, 0.25300117, -0.74350384,
            0.60559366, 0.36746691]
    )
    z = 3.0 / xs
    f0 = _poly(z, [0.00014476, -0.00072805, 0.00137237, -0.00009512,
                   -0.00552740, -0.00000077, 0.79788456])
    t0 = xs + _poly(z, [0.00013558, -0.00029333, -0.00054125, 0.00262573,
                        -0.00003954, -0.04166397, -0.78539816])
    big = f0 * jnp.sin(t0) / jnp.sqrt(xs)
    return jnp.where(x <= 3.0, small, big)


def y1(x):
    x = jnp.asarray(x)
    xs = jnp.where(x > 1e-30, x, 1e-30)
    # x <= 3 : A&S 9.4.5  (x*Y1 = (2/pi) x ln(x/2) J1(x) + poly((x/3)^2))
    y = (xs / 3.0) ** 2
    small = (
        (2.0 / jnp.pi) * xs * jnp.log(xs / 2.0) * j1(xs)
        + _poly(y, [0.0027873, -0.0400976, 0.3123951, -1.3164827,
                    2.1682709, 0.2212091, -0.6366198])
    ) / xs
    z = 3.0 / xs
    f1 = _poly(z, [-0.00020033, 0.00113653, -0.00249511, 0.00017105,
                   0.01659667, 0.00000156, 0.79788456])
    t1 = xs + _poly(z, [-0.00029166, 0.00079824, 0.00074348, -0.00637879,
                        0.00005650, 0.12499612, -2.35619449])
    big = f1 * jnp.sin(t1) / jnp.sqrt(xs)
    return jnp.where(x <= 3.0, small, big)
