"""Closed-form frustum volume / centroid / moment-of-inertia formulas,
vectorized for batched use (reference raft/helpers.py:35-62 FrustumVCV,
raft/raft_member.py:250-331 FrustumMOI / RectangularFrustumMOI).

All functions broadcast elementwise over array inputs, so an entire member's
submember stack (or all members of all sweep designs) evaluates in one call.
Degenerate inputs (H == 0 or zero cross-section) return zeros, matching the
reference's guard branches, but via ``where`` masking instead of ``if``.
"""

import jax.numpy as jnp


def frustum_vcv_circ(dA, dB, H):
    """Volume and centroid height (from the dA end) of a conical frustum.

    Returns (V, hc); zero-size inputs give (0, 0).
    Reference raft/helpers.py:35-62.
    """
    dA, dB, H = jnp.broadcast_arrays(
        jnp.asarray(dA, float), jnp.asarray(dB, float), jnp.asarray(H, float)
    )
    A1 = (jnp.pi / 4) * dA**2
    A2 = (jnp.pi / 4) * dB**2
    Amid = (jnp.pi / 4) * dA * dB
    denom = A1 + Amid + A2
    V = denom * H / 3
    safe = jnp.where(denom > 0, denom, 1.0)  # NaN-free in fwd AND grad passes
    hc = jnp.where(denom > 0, (A1 + 2 * Amid + 3 * A2) / safe * H / 4, 0.0)
    zero = (dA == 0) & (dB == 0)
    return jnp.where(zero, 0.0, V), jnp.where(zero, 0.0, hc)


def frustum_vcv_rect(slA, slB, H):
    """Volume and centroid height of a rectangular (pyramidal) frustum.

    slA, slB : [..., 2] side-length pairs.  Returns (V, hc).
    Reference raft/helpers.py:47-55 (length-2 branch).
    """
    slA = jnp.asarray(slA, float)
    slB = jnp.asarray(slB, float)
    H = jnp.asarray(H, float)
    A1 = slA[..., 0] * slA[..., 1]
    A2 = slB[..., 0] * slB[..., 1]
    Amid = jnp.sqrt(A1 * A2)
    denom = A1 + Amid + A2
    V = denom * H / 3
    safe = jnp.where(denom > 0, denom, 1.0)
    hc = jnp.where(denom > 0, (A1 + 2 * Amid + 3 * A2) / safe * H / 4, 0.0)
    zero = (jnp.sum(jnp.abs(slA), axis=-1) == 0) & (jnp.sum(jnp.abs(slB), axis=-1) == 0)
    return jnp.where(zero, 0.0, V), jnp.where(zero, 0.0, hc)


def frustum_moi(dA, dB, H, rho):
    """Radial (about the dA end node) and axial moments of inertia of a solid
    circular frustum of density rho.  Returns (I_rad_end, I_ax).

    Uses the cylinder formula when dA == dB and the tapered formula otherwise,
    selected by ``where`` (reference raft/raft_member.py:250-268).
    """
    dA, dB, H, rho = jnp.broadcast_arrays(
        jnp.asarray(dA, float), jnp.asarray(dB, float),
        jnp.asarray(H, float), jnp.asarray(rho, float),
    )
    r1 = dA / 2
    r2 = dB / 2
    # cylinder branch
    I_rad_cyl = (1 / 12) * (rho * H * jnp.pi * r1**2) * (3 * r1**2 + 4 * H**2)
    I_ax_cyl = 0.5 * rho * jnp.pi * H * r1**4
    # tapered branch; (r2^5 - r1^5)/(r2 - r1) is regular but guard the division
    dr = r2 - r1
    ratio = (r2**5 - r1**5) / jnp.where(dr == 0, 1.0, dr)
    I_rad_tap = (1 / 20) * rho * jnp.pi * H * ratio + (1 / 30) * rho * jnp.pi * H**3 * (
        r1**2 + 3 * r1 * r2 + 6 * r2**2
    )
    I_ax_tap = (1 / 10) * rho * jnp.pi * H * ratio
    same = dA == dB
    I_rad = jnp.where(same, I_rad_cyl, I_rad_tap)
    I_ax = jnp.where(same, I_ax_cyl, I_ax_tap)
    zero = H == 0
    return jnp.where(zero, 0.0, I_rad), jnp.where(zero, 0.0, I_ax)


def rect_frustum_moi(slA, slB, H, rho):
    """Moments of inertia about the end node of a (possibly tapered) cuboid.

    slA, slB : [..., 2] (L, W) side pairs.  Returns (Ixx, Iyy, Izz) about the
    bottom end node (x/y radial, z axial).

    The reference (raft/raft_member.py:270-331) provides four special-case
    branches; the general taper branch there is unreachable (it contains a
    ``H(...)`` call typo that would raise TypeError).  Here we use the single
    exact closed form for a linearly tapered rectangular frustum — side
    lengths L(t), W(t) vary linearly over t in [0, 1] — via exact polynomial
    integration of

      x2  = rho*H/12 * int L(t)^3 W(t) dt     (spread about the local y axis)
      y2  = rho*H/12 * int W(t)^3 L(t) dt     (spread about the local x axis)
      z2  = rho*H^3  * int t^2 L(t) W(t) dt   (height spread about the end)

      Ixx = y2 + z2,  Iyy = x2 + z2,  Izz = x2 + y2

    which reduces to each of the reference's three working branches
    (verified in tests/test_kernels.py against numerical integration).
    """
    slA = jnp.asarray(slA, float)
    slB = jnp.asarray(slB, float)
    H = jnp.asarray(H, float)
    rho = jnp.asarray(rho, float)
    La, Wa = slA[..., 0], slA[..., 1]
    Lb, Wb = slB[..., 0], slB[..., 1]

    # Side lengths vary linearly: L(t) = La + (Lb-La) t, W(t) similarly, t in [0,1].
    dL = Lb - La
    dW = Wb - Wa

    def poly_int(coeffs):
        # integral over t in [0,1] of sum_k coeffs[k] t^k
        return sum(c / (k + 1) for k, c in enumerate(coeffs))

    # products as polynomials in t
    # L(t)*W(t) = La*Wa + (La*dW + Wa*dL) t + dL*dW t^2
    lw0, lw1, lw2 = La * Wa, La * dW + Wa * dL, dL * dW

    # x2 = rho * H/12 * int L(t)^3 W(t) dt   (second moment about local y from x-extent)
    # L^3 coefficients
    l3_0 = La**3
    l3_1 = 3 * La**2 * dL
    l3_2 = 3 * La * dL**2
    l3_3 = dL**3
    # L^3 * W coefficients
    x2 = rho * H / 12 * poly_int([
        l3_0 * Wa,
        l3_0 * dW + l3_1 * Wa,
        l3_1 * dW + l3_2 * Wa,
        l3_2 * dW + l3_3 * Wa,
        l3_3 * dW,
    ])
    w3_0 = Wa**3
    w3_1 = 3 * Wa**2 * dW
    w3_2 = 3 * Wa * dW**2
    w3_3 = dW**3
    y2 = rho * H / 12 * poly_int([
        w3_0 * La,
        w3_0 * dL + w3_1 * La,
        w3_1 * dL + w3_2 * La,
        w3_2 * dL + w3_3 * La,
        w3_3 * dL,
    ])
    # z2 = rho * H^3 * int t^2 L(t) W(t) dt  (second moment about end from height)
    z2 = rho * H**3 * poly_int([0.0, 0.0, lw0, lw1, lw2])

    Ixx = y2 + z2
    Iyy = x2 + z2
    Izz = x2 + y2
    zero = H == 0
    return (
        jnp.where(zero, 0.0, Ixx),
        jnp.where(zero, 0.0, Iyy),
        jnp.where(zero, 0.0, Izz),
    )
