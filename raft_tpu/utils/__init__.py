from raft_tpu.utils.frames import (
    small_rotate, get_h, rotation_matrix, translate_force_3to6,
    transform_force, translate_matrix_3to6, translate_matrix_6to6,
    rotate_matrix3, rotate_matrix6, vec_vec_trans,
)
from raft_tpu.utils.frustum import (
    frustum_vcv_circ, frustum_vcv_rect, frustum_moi, rect_frustum_moi,
)
