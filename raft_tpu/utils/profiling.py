"""Structured logging, solve timers, and profiler hooks.

The reference's observability is bare ``print()`` statements
(reference raft/raft_model.py:241-242,363,603-611; SURVEY.md §5 'Tracing /
profiling: None').  Here the framework gets a real instrumentation layer:

 - a package logger (``raft_tpu``) with an opt-in structured formatter;
 - ``timer`` / ``Timers``: wall-clock counters around the expensive stages
   (geometry packing, mooring equilibrium, BEM solve, the batched RAO
   pipeline) with per-stage call counts and totals;
 - ``trace`` : context manager wrapping ``jax.profiler.trace`` so a TPU
   trace of the case pipeline is one ``with`` statement
   (view with TensorBoard or xprof).

Everything is no-overhead-by-default: timers are only active inside an
explicit ``Timers()`` context, and the logger follows standard logging
levels.
"""

import contextlib
import logging
import time

logger = logging.getLogger("raft_tpu")


def configure_logging(level=logging.INFO, structured=False):
    """Attach a stream handler to the package logger.

    structured=True emits ``key=value`` lines (machine-parseable);
    otherwise a plain human format is used.
    """
    fmt = (
        "ts=%(created).3f level=%(levelname)s module=%(module)s msg=%(message)s"
        if structured
        else "[raft_tpu %(levelname)s] %(message)s"
    )
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(fmt))
    logger.handlers = [handler]
    logger.setLevel(level)
    return logger


class Timers:
    """Accumulating named wall-clock counters.

    >>> tm = Timers()
    >>> with tm.time("rao_solve"):
    ...     run()
    >>> tm.report()
    {'rao_solve': {'calls': 1, 'total_s': ..., 'mean_s': ...}}
    """

    _active = None  # innermost active Timers (for the module-level timer())

    def __init__(self):
        self.counters = {}

    def __enter__(self):
        self._prev = Timers._active
        Timers._active = self
        return self

    def __exit__(self, *exc):
        Timers._active = self._prev
        return False

    @contextlib.contextmanager
    def time(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            c = self.counters.setdefault(name, {"calls": 0, "total_s": 0.0})
            c["calls"] += 1
            c["total_s"] += dt

    def report(self, log=False):
        out = {
            k: {**v, "mean_s": v["total_s"] / max(v["calls"], 1)}
            for k, v in self.counters.items()
        }
        if log:
            for k, v in sorted(out.items(), key=lambda kv: -kv[1]["total_s"]):
                logger.info(
                    "timer %s: calls=%d total=%.4fs mean=%.4fs",
                    k, v["calls"], v["total_s"], v["mean_s"],
                )
        return out


@contextlib.contextmanager
def timer(name):
    """Time a block against the innermost active ``Timers`` context;
    a silent no-op when none is active (so library code can instrument
    unconditionally)."""
    tm = Timers._active
    if tm is None:
        yield
    else:
        with tm.time(name):
            yield


@contextlib.contextmanager
def trace(log_dir="/tmp/raft_tpu_trace"):
    """Capture a JAX/XLA profiler trace of the enclosed block
    (open in TensorBoard: `tensorboard --logdir <log_dir>`)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def compiled_flops(jitted_fn, args):
    """XLA cost-model flop count of a jitted function at the given
    arguments (compiled.cost_analysis; the lower+compile hits the jit and
    persistent caches, so this is cheap on a warm executable).  Returns
    0.0 when the backend does not report costs — callers should treat the
    value as an estimate for utilization reporting, not a guarantee."""
    try:
        cost = jitted_fn.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return float(cost.get("flops", 0.0)) if cost else 0.0
    except Exception:  # pragma: no cover - cost model availability varies
        return 0.0
