"""Fast committed-device placement helpers.

``jax.device_put(x, device)`` with a bare ``Device`` goes through a slow
per-call path on plugin backends (~90 ms per call measured under the axon
TPU plugin, even for a 3x3 array); passing a ``SingleDeviceSharding``
instead hits the fast path (<0.1 ms).  Host-side setup code (mooring
arrays, rotor polars, f64 statics inputs) places small arrays on the CPU
backend constantly, so this difference dominates per-design cost in sweeps.
"""

from functools import lru_cache

import jax


@lru_cache(maxsize=None)
def cpu_sharding():
    return jax.sharding.SingleDeviceSharding(jax.devices("cpu")[0])


def backend_devices(platform=None):
    """Local devices of ``platform`` ('tpu' | 'cpu' | 'gpu'; None = default
    backend), with the same clear error :func:`backend_sharding` raises
    when the requested platform is absent."""
    try:
        if platform is None:
            return jax.local_devices()
        return jax.local_devices(backend=platform)
    except RuntimeError as e:
        avail = sorted({d.platform for d in jax.devices()})
        raise RuntimeError(
            f"device='{platform}' requested but no such backend is "
            f"available (have: {avail})"
        ) from e


def batch_mesh(platform=None, axis="batch", devices=None):
    """1-D device mesh over the local devices of ``platform`` (or the
    explicit ``devices`` list) for embarrassingly-parallel batch axes —
    the same shape :func:`raft_tpu.sweep.make_sweep_mesh` uses for the
    design axis, reused by the BEM frequency sharding."""
    import numpy as np

    devs = list(devices) if devices is not None else backend_devices(platform)
    return jax.sharding.Mesh(np.array(devs), (axis,))


def batch_sharding(mesh, axis="batch"):
    """NamedSharding laying an array's leading axis across ``mesh``."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(axis))


def replicated_sharding(mesh):
    """NamedSharding replicating an array on every device of ``mesh``."""
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def put_cpu(x):
    """Commit array/pytree ``x`` to the host CPU backend (fast path)."""
    return jax.device_put(x, cpu_sharding())


@lru_cache(maxsize=None)
def backend_sharding(platform):
    """SingleDeviceSharding for the first device of ``platform``
    ('tpu' | 'cpu' | 'gpu'); raises with the available platforms listed
    when the requested one is absent."""
    try:
        dev = jax.devices(platform)[0]
    except RuntimeError as e:
        avail = sorted({d.platform for d in jax.devices()})
        raise RuntimeError(
            f"device='{platform}' requested but no such backend is "
            f"available (have: {avail})"
        ) from e
    return jax.sharding.SingleDeviceSharding(dev)
