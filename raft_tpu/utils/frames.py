"""6-DOF rigid-body frame transforms as batched JAX primitives.

Provides the math of the reference's helpers (reference raft/helpers.py:158-382
— SmallRotate, getH, rotationMatrix, translateForce3to6DOF,
translateMatrix3to6DOF, translateMatrix6to6DOF, rotateMatrix3/6) but written
as pure functions that broadcast over arbitrary leading batch dimensions, so
they can be used inside vmapped/jitted pipelines instead of per-node Python
loops.
"""

import jax.numpy as jnp


def small_rotate(r, th):
    """First-order displacement of point(s) ``r`` under small rotations ``th``.

    Equals ``cross(th, r)`` (reference raft/helpers.py:158-170).  Broadcasts;
    supports complex rotation amplitudes.

    r : [..., 3], th : [..., 3] -> [..., 3]
    """
    return jnp.cross(th, r)


def get_h(r):
    """Alternator matrix H(r) with H @ v = cross(v, r) = -cross(r, v).

    Matches the reference's sign convention (reference raft/helpers.py:187-195).

    r : [..., 3] -> [..., 3, 3]
    """
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    zero = jnp.zeros_like(x)
    return jnp.stack(
        [
            jnp.stack([zero, z, -y], axis=-1),
            jnp.stack([-z, zero, x], axis=-1),
            jnp.stack([y, -x, zero], axis=-1),
        ],
        axis=-2,
    )


def rotation_matrix(x3, x2, x1):
    """Rotation matrix from intrinsic z-y-x (yaw-pitch-roll applied z,y,x order)
    Tait-Bryan angles; column convention matches reference raft/helpers.py:197-224.

    x3, x2, x1 : broadcastable scalars/arrays (roll, pitch, yaw) -> [..., 3, 3]
    """
    x3, x2, x1 = jnp.broadcast_arrays(
        jnp.asarray(x3), jnp.asarray(x2), jnp.asarray(x1)
    )
    s1, c1 = jnp.sin(x1), jnp.cos(x1)
    s2, c2 = jnp.sin(x2), jnp.cos(x2)
    s3, c3 = jnp.sin(x3), jnp.cos(x3)
    return jnp.stack(
        [
            jnp.stack([c1 * c2, c1 * s2 * s3 - c3 * s1, s1 * s3 + c1 * c3 * s2], axis=-1),
            jnp.stack([c2 * s1, c1 * c3 + s1 * s2 * s3, c3 * s1 * s2 - c1 * s3], axis=-1),
            jnp.stack([-s2, c2 * s3, c2 * c3], axis=-1),
        ],
        axis=-2,
    )


def translate_force_3to6(F, r):
    """Force at position r -> 6-DOF force/moment about the origin
    (reference raft/helpers.py:226-241).

    F : [..., 3], r : [..., 3] -> [..., 6]
    """
    return jnp.concatenate(
        jnp.broadcast_arrays(F, jnp.cross(r, F)), axis=-1
    )


def transform_force(f_in, offset=None, rot=None):
    """Transform a 6-DOF force/moment between frames: optional rotation ``rot``
    ([..., 3, 3]) then moment shift by ``offset`` (reference raft/helpers.py:244-291).

    f_in : [..., 6] -> [..., 6]
    """
    F = f_in[..., :3]
    M = f_in[..., 3:]
    if rot is not None:
        F = jnp.einsum("...ij,...j->...i", rot, F)
        M = jnp.einsum("...ij,...j->...i", rot, M)
    if offset is not None:
        M = M + jnp.cross(offset, F)
    return jnp.concatenate([F, M], axis=-1)


def translate_matrix_3to6(Min, r):
    """3x3 mass/damping-like matrix at point r -> 6x6 about origin via the
    Sadeghi & Incecik parallel-axis transform (reference raft/helpers.py:295-318).

    Min : [..., 3, 3], r : [..., 3] -> [..., 6, 6]
    """
    H = get_h(r)
    MH = Min @ H
    top = jnp.concatenate([Min, MH], axis=-1)
    bottom = jnp.concatenate(
        [jnp.swapaxes(MH, -1, -2), H @ Min @ jnp.swapaxes(H, -1, -2)], axis=-1
    )
    return jnp.concatenate([top, bottom], axis=-2)


def translate_matrix_6to6(Min, r):
    """6x6 matrix about a point at -r -> about origin (r points from the new
    reference point to the current one; reference raft/helpers.py:321-343).

    Min : [..., 6, 6], r : [..., 3] -> [..., 6, 6]
    """
    H = get_h(r)
    m = Min[..., :3, :3]
    J = Min[..., :3, 3:]
    I = Min[..., 3:, 3:]
    mH = m @ H
    Jp = mH + J
    Ip = (
        H @ m @ jnp.swapaxes(H, -1, -2)
        + jnp.swapaxes(J, -1, -2) @ H
        + jnp.swapaxes(H, -1, -2) @ J
        + I
    )
    top = jnp.concatenate([m, Jp], axis=-1)
    bottom = jnp.concatenate([jnp.swapaxes(Jp, -1, -2), Ip], axis=-1)
    return jnp.concatenate([top, bottom], axis=-2)


def rotate_matrix3(Min, rotMat):
    """[m'] = [R][m][R]^T (reference raft/helpers.py:371-382)."""
    return rotMat @ Min @ jnp.swapaxes(rotMat, -1, -2)


def rotate_matrix6(Min, rotMat):
    """Rotate a 6x6 mass/inertia tensor (reference raft/helpers.py:347-368)."""
    Rt = jnp.swapaxes(rotMat, -1, -2)
    m = rotMat @ Min[..., :3, :3] @ Rt
    J = rotMat @ Min[..., :3, 3:] @ Rt
    I = rotMat @ Min[..., 3:, 3:] @ Rt
    top = jnp.concatenate([m, J], axis=-1)
    bottom = jnp.concatenate([jnp.swapaxes(J, -1, -2), I], axis=-1)
    return jnp.concatenate([top, bottom], axis=-2)


def vec_vec_trans(v):
    """Outer product v v^T (reference raft/helpers.py:174-182).

    v : [..., 3] -> [..., 3, 3]
    """
    return v[..., :, None] * v[..., None, :]
