"""Sharded design-space sweep driver.

Replaces the reference's serial nested-loop parameter sweep
(reference raft/parametersweep.py:56-100: 3^5 VolturnUS-S geometry variants,
one full Model run each, no checkpointing) with a TPU-first batch pipeline:

 - host side, each design point is preprocessed independently (geometry
   packing, statics, per-case mooring equilibrium — all NumPy f64);
 - the packed strip-node bundles are padded to a common node count and
   stacked, so the whole sweep chunk is ONE pytree with a leading
   [design] axis;
 - the case-dynamics graph (wave kinematics -> Froude-Krylov -> drag
   linearization fixed point -> per-frequency 6x6 solves) is vmapped over
   cases AND designs and jitted with an explicit NamedSharding that lays the
   design axis across the device mesh — XLA runs each shard's designs on its
   own chip with zero communication (the sweep is embarrassingly parallel;
   the only collective is the implicit all-gather when results are fetched);
 - chunks of `mesh size` designs are processed at a time, and every chunk's
   results are checkpointed to an .npz so a crashed 243-point sweep resumes
   instead of restarting (the reference has no checkpoint/restart —
   SURVEY.md §5);
 - the sweep is fault-isolated: a design point whose host-side prep
   raises (the CPU mooring equilibrium is the usual thrower) is
   quarantined into the result's ``failed`` list with its batch slot
   masked, device-side NaN lanes freeze in-graph and surface through the
   per-point SolveReport fields, and non-converged lanes get one bounded
   retry re-solve with doubled nIter and stronger under-relaxation — the
   sweep always completes (raft_tpu/health.py).

Typical use::

    points = grid_points({"d_col": [9, 10, 11], "draft": [18, 20, 22]})
    res = run_sweep(base_design, points, apply_point, out_dir="sweep_ckpt")
"""

import copy
import dataclasses
import itertools
import os
import time
import zipfile

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.batched_prep import (
    PrepFamily,
    PrepFamilyError,
    batched_prep_enabled,
)
from raft_tpu.geometry import HydroNodes
from raft_tpu.health import FailedPoint
from raft_tpu.model import Model, make_case_dynamics
from raft_tpu.resilience import SolveRetryPolicy
from raft_tpu.sweep_buckets import grouped_sweep_pipeline, sweep_buckets_enabled
from raft_tpu.waterfall import fixed_point_mode, grouped_waterfall_pipeline
from raft_tpu.utils.profiling import logger


def grid_points(axes):
    """Cartesian product of named parameter axes -> list of dicts
    (the reference's nested loops, parametersweep.py:56-84)."""
    names = list(axes)
    return [
        dict(zip(names, vals))
        for vals in itertools.product(*(axes[n] for n in names))
    ]


def pad_and_stack_nodes(nodes_list):
    """Stack a list of HydroNodes into one bundle with a leading [design]
    axis, zero-padding the node axis to the largest design.

    Zero padding is inert by construction: padded nodes have zero strip
    volumes/areas and False submerged/strip masks, so every hydro term they
    touch (added mass, Froude-Krylov, drag linearization) contributes 0.
    """
    N = max(n.r.shape[0] for n in nodes_list)
    out = {}
    for f in dataclasses.fields(HydroNodes):
        arrs = []
        for n in nodes_list:
            a = getattr(n, f.name)
            pad = N - a.shape[0]
            if pad:
                a = np.concatenate(
                    [a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0
                )
            arrs.append(a)
        out[f.name] = np.stack(arrs)
    return HydroNodes(**out)


def _prepare_design(base_design, point, apply_point, precision):
    """One design point -> (model, nodes, args) on host."""
    design = copy.deepcopy(base_design)
    design = apply_point(design, point) or design
    model = Model(design, precision=precision)
    model.analyze_unloaded()
    args, _ = model.prepare_case_inputs(verbose=False)
    return model, model.nodes.astype(model.dtype), args


def _prepare_chunk(base_design, chunk_pts, apply_point, precision, k0,
                   family):
    """Host prep for one chunk: batched traced prep through ``family``
    when available (RAFT_TPU_BATCHED_PREP — raft_tpu/batched_prep.py),
    per-design solo fallback on family mismatch, quarantine on hard
    failure.  Returns (preps, failed, n_batched)."""
    n_real = len(chunk_pts)
    preps = [None] * n_real
    failed = []
    n_batched = 0
    solo = list(range(n_real))
    if family is not None:
        lanes, lane_idx, solo = [], [], []
        for j, pt in enumerate(chunk_pts):
            try:
                design = copy.deepcopy(base_design)
                design = apply_point(design, pt) or design
                lanes.append(family.extract(design))
                lane_idx.append(j)
            except Exception as e:  # family mismatch or bad design dict;
                # solo prep below decides between fallback and quarantine
                if not isinstance(e, PrepFamilyError):
                    logger.warning(
                        "sweep point %d: batched prep extract raised "
                        "(%s: %s); solo fallback", k0 + j,
                        type(e).__name__, e,
                    )
                solo.append(j)
        if lanes:
            try:
                for j, triple in zip(lane_idx, family.prepare(lanes)):
                    preps[j] = triple
                n_batched = len(lane_idx)
            except Exception as e:  # noqa: BLE001 — family-level fault:
                # every batched lane falls back to solo prep
                logger.warning(
                    "sweep chunk at %d: batched prep raised (%s: %s); "
                    "solo fallback for %d design(s)", k0,
                    type(e).__name__, e, len(lane_idx),
                )
                solo = sorted(solo + lane_idx)
    for j in solo:
        pt = chunk_pts[j]
        try:
            preps[j] = _prepare_design(base_design, pt, apply_point,
                                       precision)
        except Exception as e:  # noqa: BLE001 — quarantine any prep fault
            msg = f"{type(e).__name__}: {e}"
            failed.append((k0 + j, pt, msg))
            logger.warning(
                "sweep point %d quarantined: design prep raised (%s)",
                k0 + j, msg,
            )
    return preps, failed, n_batched


def default_collect(model, point, Xi):
    """Per-design summary metrics (the reference sweep's getOutputs,
    parametersweep.py:9-21, plus response statistics).

    Xi : [ncase, 6, nw] complex response amplitudes.
    """
    st = model.statics
    dw = model.dw
    std = np.sqrt(np.sum(np.abs(Xi) ** 2, axis=-1) * dw)  # [ncase, 6]
    return {
        "mass": st.mass,
        "displacement": st.V,
        "GMT": st.zMeta - st.rCG_TOT[2],
        "surge_std": std[:, 0],
        "heave_std": std[:, 2],
        "pitch_std_deg": np.rad2deg(std[:, 4]),
    }


def make_sweep_mesh(devices=None):
    """1-D 'design' mesh over all (or the given) devices — after
    :func:`initialize_distributed` on every host, this spans the whole
    multi-host pool (DCN between hosts, ICI within a slice)."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), ("design",))


def initialize_distributed(coordinator=None, num_processes=None,
                           process_id=None):
    """Join a multi-host JAX pool so sweeps span all hosts' devices.

    Call once per host process before any other JAX use; afterwards
    ``jax.devices()`` lists every chip in the pool and
    :func:`make_sweep_mesh` shards the design axis across all of them.
    Parameters default to the cloud-TPU/SLURM auto-detection built into
    ``jax.distributed.initialize``; pass them explicitly on bare clusters
    (coordinator = "host0:port").

    The reference has no distributed path at all (SURVEY.md §2.4) — its
    243-point sweep is a serial Python loop (parametersweep.py:56-100).
    """
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return jax.process_index(), jax.process_count()


def _load_checkpoint(ck_path):
    """Load a chunk checkpoint if it exists; returns None to recompute.

    A corrupt, truncated, or incomplete checkpoint (a crash mid-write in
    a pre-atomic-write run, disk trouble, a stray file) is *deleted* with
    a logged reason and the chunk recomputed — never silently trusted and
    never allowed to poison the restart.

    Multi-process coherent: the exists/recompute decision is taken on
    process 0 and broadcast, so every host makes the same choice
    (recomputing a chunk runs global collectives that need all processes).
    Multi-host checkpointing requires ``out_dir`` on a filesystem shared
    by all hosts — a host that cannot see a checkpoint process 0 decided
    to load gets a clear error instead of a collective hang.
    """
    if ck_path is None:
        return None

    def _discard(reason):
        logger.warning(
            "sweep checkpoint %s %s; deleting it and recomputing the chunk",
            ck_path, reason,
        )
        if jax.process_index() == 0:
            try:
                os.remove(ck_path)
            except OSError:
                pass
        return None

    def _try_load():
        if not os.path.exists(ck_path):
            return None
        try:
            with np.load(ck_path, allow_pickle=False) as zf:
                data = {key: zf[key] for key in zf.files}
        except (OSError, ValueError, EOFError, KeyError,
                zipfile.BadZipFile) as e:
            return _discard(
                f"is corrupt or truncated ({type(e).__name__}: {e})"
            )
        if "_all_failed" not in data and "Xi_r" not in data:
            return _discard(
                "is missing the required result arrays (incomplete write "
                "or foreign file)"
            )
        return data

    if jax.process_count() == 1:
        return _try_load()

    from jax.experimental import multihost_utils

    data = _try_load() if jax.process_index() == 0 else None
    ok = data is not None if jax.process_index() == 0 else False
    ok = bool(multihost_utils.broadcast_one_to_all(np.array(ok)))
    if not ok:
        return None
    if jax.process_index() == 0:
        return data
    data = _try_load()
    if data is None:
        raise RuntimeError(
            f"sweep checkpoint {ck_path} loads on process 0 but not on "
            f"process {jax.process_index()}: multi-host sweeps need "
            "out_dir on a shared filesystem"
        )
    return data


def _fetch(x):
    """Device array -> host NumPy, valid in multi-process runs too: a
    globally sharded result is not fully addressable on one host, so it is
    allgathered first (every host then holds the full sweep results)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


# jitted sweep executables cached at module level (keyed on the physics
# scalars, grid, dtype, fixed-point parameters, and sharding) so repeated
# sweeps — and the bounded non-convergence retry, which needs a second
# executable with doubled nIter — never recompile per run_sweep call
_PIPELINE_CACHE = {}

# SolveReport fields as flat result/checkpoint keys, with the fill value
# used for masked rows (quarantined prep failures and ragged padding)
_REPORT_FILLS = {
    "converged": False, "iters": 0, "nonfinite": False,
    "recovery_tier": 0, "residual": np.nan, "cond": np.nan,
}


def _sweep_pipeline(model0, sharding, nIter, relax):
    """The jitted [design, case] dynamics executable for ``model0``'s
    configuration, design axis laid out by ``sharding``."""
    key = (
        model0.w.tobytes(), np.asarray(model0.k).tobytes(), model0.nw,
        float(model0.depth), float(model0.rho_water), float(model0.g),
        float(model0.XiStart), int(nIter), float(relax),
        np.dtype(model0.dtype).name, np.dtype(model0.cdtype).name,
        sharding,
    )
    fn = _PIPELINE_CACHE.get(key)
    if fn is None:
        one_case = make_case_dynamics(
            model0.w, model0.k, model0.depth, model0.rho_water, model0.g,
            model0.XiStart, nIter, model0.dtype, model0.cdtype, relax=relax,
        )
        per_design = jax.vmap(one_case, in_axes=(None, 0, 0, 0, 0, 0, 0, 0))
        fn = jax.jit(
            jax.vmap(per_design),
            in_shardings=(sharding,) * 8,
            out_shardings=sharding,
        )
        _PIPELINE_CACHE[key] = fn
    return fn


def _fetch_solve(xr, xi, rep):
    """Pipeline output -> dict of host NumPy arrays (allgathered)."""
    out = {"Xi_r": _fetch(xr).astype(np.float64),
           "Xi_i": _fetch(xi).astype(np.float64)}
    for name in rep._fields:
        out[name] = _fetch(getattr(rep, name))
    return out


def _masked_row_fill(template, fill):
    """NaN/zero row shaped like one entry of ``template``."""
    t = np.asarray(template)
    if isinstance(fill, float) and np.isnan(fill) \
            and not np.issubdtype(t.dtype, np.floating) \
            and not np.issubdtype(t.dtype, np.complexfloating):
        fill = 0
    return np.full(t.shape, fill, t.dtype)


def run_sweep(
    base_design,
    points,
    apply_point,
    mesh=None,
    precision=None,
    out_dir=None,
    collect=default_collect,
    verbose=True,
    retry_nonconverged=True,
    overlap=True,
    via_buckets=None,
    tracer=None,
):
    """Run the analysis over all design ``points`` with the design axis
    sharded across ``mesh`` and per-chunk checkpointing under ``out_dir``.

    Parameters
    ----------
    base_design : dict
        The template design (all points share its cases table + settings,
        so every point solves the same [case, freq] batch shape).
    points : list[dict]
        Parameter values per design point (see :func:`grid_points`).
    apply_point : callable(design, point) -> design | None
        Mutates/returns a deep copy of the base design for one point —
        the equivalent of the reference's dependent-geometry update block
        (parametersweep.py:60-100).
    mesh : jax.sharding.Mesh | None
        1-D mesh with axis "design"; default spans all local devices.
    out_dir : str | None
        Checkpoint directory. Chunk k's results live in ``chunk_{k:04d}.npz``
        and are loaded instead of recomputed on restart.
    retry_nonconverged : bool | resilience.SolveRetryPolicy
        Give non-converged (but finite) lanes one bounded retry re-solve
        under the unified escalation policy (raft_tpu/resilience.py:
        default doubled nIter, relax 0.4 instead of the reference's 0.8);
        the retry result is adopted only where it converges, so
        first-pass-healthy lanes stay bit-identical.  Pass a
        ``SolveRetryPolicy`` to customize the schedule.
    overlap : bool
        Software-pipeline the chunk loop: chunk k's device solve is
        dispatched asynchronously and stays in flight while the host
        preps chunk k+1 (geometry/statics/mooring — the CPU-bound half
        of the sweep), its results fetched only when the next chunk has
        been dispatched.  Results are identical to the serial loop (the
        fetch/retry/checkpoint tail runs unchanged, just later).
        Automatically disabled in multi-process runs, where collective
        ordering must follow the chunk order on every host.
    tracer : raft_tpu.trace.Tracer | None
        Records per-chunk ``prep`` spans (meta: batched, designs,
        batched_designs) alongside the existing stage accounting.

    Returns
    -------
    dict of stacked result arrays, leading axis = len(points): ``Xi``
    [npoints, ncase, 6, nw] complex response amplitudes, the per-point
    SolveReport fields (``converged``, ``iters``, ``nonfinite``,
    ``recovery_tier``, ``residual``, ``cond`` — see raft_tpu/health.py)
    plus ``retried``, the ``collect`` metrics and ``param_*`` columns,
    and the fault-isolation record: ``failed`` (list of
    {index, point, error} dicts for points whose host-side prep raised)
    with the matching ``failed_mask``.  Failed points' result rows are
    NaN (flag fields False/0) — they can never be mistaken for physics.
    """
    if mesh is None:
        mesh = make_sweep_mesh()
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    # sweep-through-buckets (RAFT_TPU_SWEEP_BUCKETS / via_buckets=True):
    # the chunk dynamics dispatch runs on the serving layer's canonical
    # bucket executables (raft_tpu/sweep_buckets.py) instead of the
    # sweep-shaped vmapped pipeline; single-process only (the bucket
    # slab dispatch has no multi-host collective ordering)
    use_buckets = sweep_buckets_enabled(via_buckets) \
        and jax.process_count() == 1
    if sweep_buckets_enabled(via_buckets) and not use_buckets:
        logger.warning(
            "run_sweep: via_buckets requested but multi-process run — "
            "falling back to the fused per-shape pipeline")
    # convergence-aware fixed-point engine (RAFT_TPU_FIXED_POINT):
    # single-process only, like the bucket routing (the waterfall's
    # host-side compaction has no multi-host collective ordering)
    use_waterfall = (not use_buckets) and jax.process_count() == 1 \
        and fixed_point_mode() != "legacy"
    retry_policy = SolveRetryPolicy.from_flag(retry_nonconverged)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)

    # batched traced prep (RAFT_TPU_BATCHED_PREP): one family from the
    # base design serves every chunk; designs that cannot join fall back
    # to solo prep per point inside _prepare_chunk
    prep_family = None
    if batched_prep_enabled():
        try:
            prep_family = PrepFamily(base_design, precision=precision)
        except Exception as e:  # noqa: BLE001 — batched prep is optional
            logger.warning(
                "run_sweep: batched prep unavailable (%s: %s); solo prep",
                type(e).__name__, e,
            )
    prep_wall_s = 0.0
    prep_batched = 0

    sharding = NamedSharding(mesh, P("design"))

    npoints = len(points)
    overlap_ok = bool(overlap) and jax.process_count() == 1
    records = {}  # chunk index -> dict(res | None, failed, n_real, k0)

    def _write_ck(ck_path, res, failed):
        if ck_path and jax.process_index() == 0:
            # one writer in multi-process runs (every host holds the full
            # allgathered results, so checkpoints stay restartable
            # anywhere); write-then-rename so a crash mid-write never
            # leaves a truncated chunk that would poison the restart
            save = {} if res is None else dict(res)
            if res is None:
                save["_all_failed"] = np.array(True)
            if failed:
                save["_failed_idx"] = np.array([f[0] for f in failed], int)
                save["_failed_msg"] = np.array([f[2] for f in failed])
            tmp_path = ck_path + ".tmp.npz"
            np.savez(tmp_path, **save)
            os.replace(tmp_path, ck_path)

    def _finalize(ctx):
        """Blocking tail of one dispatched chunk: fetch, bounded retry,
        quarantine masking, metric collection, checkpoint, record."""
        k, k0 = ctx["k"], ctx["k0"]
        chunk_pts, n_real = ctx["chunk_pts"], len(ctx["chunk_pts"])
        preps, failed, valid = ctx["preps"], ctx["failed"], ctx["valid"]
        ok, m0, dev_in = ctx["ok"], ctx["m0"], ctx["dev_in"]
        sol = _fetch_solve(*ctx["raw"])

        # bounded retry: one re-solve of the chunk with doubled nIter
        # and stronger under-relaxation; adopted per lane only where
        # the retry actually converges (NaN-quarantined lanes are
        # excluded — more iterations cannot fix non-finite inputs)
        retry_mask = valid[:, None] & ~sol["converged"] \
            & ~sol["nonfinite"]
        sol["retried"] = np.zeros_like(retry_mask)
        if retry_policy.enabled and retry_mask.any():
            nIter2, relax2 = retry_policy.escalate(m0.nIter)
            pipe2 = _sweep_pipeline(m0, sharding, nIter2, relax2)
            sol2 = _fetch_solve(*pipe2(*dev_in))
            use = retry_mask & sol2["converged"]
            for key in ("Xi_r", "Xi_i"):
                sol[key] = np.where(
                    use[:, :, None, None], sol2[key], sol[key]
                )
            for key in _REPORT_FILLS:
                sol[key] = np.where(use, sol2[key], sol[key])
            sol["retried"] = retry_mask
            logger.warning(
                "sweep chunk %d: %d non-converged lane(s) retried with "
                "nIter=%d / relax=%.2g; %d recovered",
                k, int(retry_mask.sum()), nIter2, relax2,
                int(use.sum()),
            )

        # mask quarantined rows before anything downstream sees them
        inv = ~valid[:n_real]
        res = {}
        for key in ("Xi_r", "Xi_i"):
            a = sol[key][:n_real].copy()
            a[inv] = np.nan
            res[key] = a
        for key, fillval in _REPORT_FILLS.items():
            # fill values are dtype-matched (bool->False, int->0,
            # float->NaN), so masked rows assign directly
            a = sol[key][:n_real].copy()
            a[inv] = fillval
            res[key] = a
        res["retried"] = sol["retried"][:n_real].copy()
        res["retried"][inv] = False

        Xi = res["Xi_r"] + 1j * res["Xi_i"]  # [n_real, ncase, 6, nw]
        per_metrics = [
            collect(preps[j][0], chunk_pts[j], Xi[j]) if valid[j]
            else None
            for j in range(n_real)
        ]
        template = per_metrics[ok[0]]
        for key in template:
            res[key] = np.stack([
                np.asarray(per_metrics[j][key])
                if per_metrics[j] is not None
                else _masked_row_fill(template[key], np.nan)
                for j in range(n_real)
            ])
        for name in chunk_pts[0]:
            res[f"param_{name}"] = np.array(
                [pt[name] for pt in chunk_pts]
            )

        _write_ck(ctx["ck_path"], res, failed)
        if verbose:
            logger.info(
                "sweep chunk %d: solved %d designs on %d devices"
                "%s", k, n_real - len(failed), n_dev,
                f" ({len(failed)} quarantined)" if failed else "",
            )
        records[k] = {"res": res, "failed": failed, "n_real": n_real,
                      "k0": k0}

    inflight = None
    for k0 in range(0, npoints, n_dev):
        k = k0 // n_dev
        ck_path = os.path.join(out_dir, f"chunk_{k:04d}.npz") if out_dir else None
        chunk_pts = points[k0 : k0 + n_dev]
        n_real = len(chunk_pts)

        loaded = _load_checkpoint(ck_path)
        if loaded is not None:
            fidx = loaded.pop("_failed_idx", None)
            fmsg = loaded.pop("_failed_msg", None)
            failed = [
                (int(i), chunk_pts[int(i) - k0], str(m))
                for i, m in zip(
                    np.atleast_1d(fidx) if fidx is not None else [],
                    np.atleast_1d(fmsg) if fmsg is not None else [],
                )
            ]
            res = None if loaded.pop("_all_failed", None) is not None \
                else loaded
            records[k] = {"res": res, "failed": failed, "n_real": n_real,
                          "k0": k0}
            if verbose:
                logger.info(
                    "sweep chunk %d: loaded checkpoint (%d designs)",
                    k, n_real,
                )
            continue

        # host prep below overlaps the previous chunk's in-flight device
        # solve (dispatches are async; the fetch happens in _finalize)

        # host prep (the expensive part is the mooring equilibrium +
        # NumPy statics; RAFT_TPU_BATCHED_PREP runs the whole chunk
        # through one traced lane-block program instead of the per-point
        # loop).  Fault isolation: a raising design point is quarantined
        # — its batch slot is masked with a healthy design and its
        # result rows reported as NaN + failed, so one bad design dict
        # cannot kill the whole sweep.
        t_prep = time.perf_counter()
        span = tracer.begin(
            "prep", chunk=k, batched=prep_family is not None
        ) if tracer is not None else None
        preps, failed, n_batched = _prepare_chunk(
            base_design, chunk_pts, apply_point, precision, k0,
            prep_family,
        )
        if span is not None:
            tracer.end(span, designs=n_real, batched_designs=n_batched)
        prep_wall_s += time.perf_counter() - t_prep
        prep_batched += n_batched

        ok = [j for j in range(n_real) if preps[j] is not None]
        if not ok:
            # whole chunk failed host-side; no device solve
            _write_ck(ck_path, None, failed)
            if verbose:
                logger.info(
                    "sweep chunk %d: solved 0 designs on %d devices "
                    "(%d quarantined)", k, n_dev, len(failed),
                )
            records[k] = {"res": None, "failed": failed,
                          "n_real": n_real, "k0": k0}
            continue

        # explicit slot map: every device slot names the prep it
        # carries and ``valid`` marks the slots whose results are
        # real.  Failed-prep slots and the ragged-tail padding slots
        # are filled with the chunk's first healthy design purely to
        # keep the batch shape — the mask guarantees those copies can
        # never leak into collected metrics.
        fill = ok[0]
        slot = [j if (j < n_real and preps[j] is not None) else fill
                for j in range(n_dev)]
        valid = np.array(
            [j < n_real and preps[j] is not None for j in range(n_dev)]
        )
        nodes_list = [preps[s][1] for s in slot]
        args_list = [preps[s][2] for s in slot]

        nodes_b = pad_and_stack_nodes(nodes_list)
        args_b = tuple(
            np.stack([a[i] for a in args_list])
            for i in range(len(args_list[0]))
        )

        m0 = preps[fill][0]
        if use_buckets:
            # retry dispatches below keep the legacy pipeline: the
            # escalated (nIter, relax) is not a canonical serving
            # configuration (see raft_tpu/sweep_buckets.py)
            pipeline = grouped_sweep_pipeline(m0)
        elif use_waterfall:
            # convergence-aware engine (RAFT_TPU_FIXED_POINT): flattened
            # lanes through fixed K-iteration blocks with active-lane
            # compaction, per-lane bit-identical to the legacy pipeline;
            # the retry dispatch below stays on the legacy reference path
            pipeline = grouped_waterfall_pipeline(m0)
        else:
            pipeline = _sweep_pipeline(m0, sharding, m0.nIter, 0.8)
        dev_in = jax.device_put((nodes_b,) + args_b, sharding)
        raw = pipeline(*dev_in)        # ASYNC dispatch: fetch in _finalize
        ctx = dict(k=k, k0=k0, ck_path=ck_path, chunk_pts=chunk_pts,
                   preps=preps, failed=failed, valid=valid, ok=ok,
                   m0=m0, dev_in=dev_in, raw=raw)
        if inflight is not None:
            _finalize(inflight)        # blocks on the PREVIOUS chunk
        inflight = ctx
        if not overlap_ok:
            _finalize(inflight)
            inflight = None
    if inflight is not None:
        _finalize(inflight)
    chunk_records = [records[k] for k in sorted(records)]

    proto = next(
        (r["res"] for r in chunk_records if r["res"] is not None), None
    )
    if proto is None:
        first = chunk_records[0]["failed"][0]
        raise RuntimeError(
            f"run_sweep: every design point failed host-side preparation; "
            f"first error at point {first[0]}: {first[2]}"
        )
    out = {}
    for key in proto:
        parts = []
        for rec in chunk_records:
            if rec["res"] is not None and key in rec["res"]:
                parts.append(rec["res"][key])
            elif rec["res"] is not None:
                # checkpoint written by an older schema (missing a newer
                # result column): fill masked rows rather than crash
                parts.append(np.stack(
                    [_masked_row_fill(proto[key][0],
                                      _REPORT_FILLS.get(key, np.nan))]
                    * rec["n_real"]
                ))
            elif key.startswith("param_"):
                name = key[len("param_"):]
                parts.append(np.array([
                    pt[name]
                    for pt in points[rec["k0"]: rec["k0"] + rec["n_real"]]
                ]))
            else:
                parts.append(np.stack(
                    [_masked_row_fill(proto[key][0],
                                      _REPORT_FILLS.get(key, np.nan))]
                    * rec["n_real"]
                ))
        out[key] = np.concatenate(parts, axis=0)
    out["Xi"] = out.pop("Xi_r") + 1j * out.pop("Xi_i")
    failed_all = [f for rec in chunk_records for f in rec["failed"]]
    out["failed"] = [
        FailedPoint(i, pt, msg).as_dict() for i, pt, msg in failed_all
    ]
    mask = np.zeros(npoints, bool)
    for i, _, _ in failed_all:
        mask[i] = True
    out["failed_mask"] = mask
    # prep-stage telemetry (checkpoint-loaded chunks pay no prep):
    # wall seconds over all freshly-prepped chunks and how many designs
    # went through the batched traced program (0 = all solo)
    out["prep_wall_s"] = float(prep_wall_s)
    out["prep_batched"] = int(prep_batched)
    return out


def results_to_grid(results, axes, key):
    """Reshape a flat sweep result array back onto the named parameter grid
    (for the reference's contour-matrix plots, parametersweep.py:122-561)."""
    shape = tuple(len(v) for v in axes.values())
    return np.asarray(results[key]).reshape(shape + results[key].shape[1:])
