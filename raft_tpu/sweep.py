"""Sharded design-space sweep driver.

Replaces the reference's serial nested-loop parameter sweep
(reference raft/parametersweep.py:56-100: 3^5 VolturnUS-S geometry variants,
one full Model run each, no checkpointing) with a TPU-first batch pipeline:

 - host side, each design point is preprocessed independently (geometry
   packing, statics, per-case mooring equilibrium — all NumPy f64);
 - the packed strip-node bundles are padded to a common node count and
   stacked, so the whole sweep chunk is ONE pytree with a leading
   [design] axis;
 - the case-dynamics graph (wave kinematics -> Froude-Krylov -> drag
   linearization fixed point -> per-frequency 6x6 solves) is vmapped over
   cases AND designs and jitted with an explicit NamedSharding that lays the
   design axis across the device mesh — XLA runs each shard's designs on its
   own chip with zero communication (the sweep is embarrassingly parallel;
   the only collective is the implicit all-gather when results are fetched);
 - chunks of `mesh size` designs are processed at a time, and every chunk's
   results are checkpointed to an .npz so a crashed 243-point sweep resumes
   instead of restarting (the reference has no checkpoint/restart —
   SURVEY.md §5).

Typical use::

    points = grid_points({"d_col": [9, 10, 11], "draft": [18, 20, 22]})
    res = run_sweep(base_design, points, apply_point, out_dir="sweep_ckpt")
"""

import copy
import dataclasses
import itertools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.geometry import HydroNodes
from raft_tpu.model import Model, make_case_dynamics


def grid_points(axes):
    """Cartesian product of named parameter axes -> list of dicts
    (the reference's nested loops, parametersweep.py:56-84)."""
    names = list(axes)
    return [
        dict(zip(names, vals))
        for vals in itertools.product(*(axes[n] for n in names))
    ]


def pad_and_stack_nodes(nodes_list):
    """Stack a list of HydroNodes into one bundle with a leading [design]
    axis, zero-padding the node axis to the largest design.

    Zero padding is inert by construction: padded nodes have zero strip
    volumes/areas and False submerged/strip masks, so every hydro term they
    touch (added mass, Froude-Krylov, drag linearization) contributes 0.
    """
    N = max(n.r.shape[0] for n in nodes_list)
    out = {}
    for f in dataclasses.fields(HydroNodes):
        arrs = []
        for n in nodes_list:
            a = getattr(n, f.name)
            pad = N - a.shape[0]
            if pad:
                a = np.concatenate(
                    [a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0
                )
            arrs.append(a)
        out[f.name] = np.stack(arrs)
    return HydroNodes(**out)


def _prepare_design(base_design, point, apply_point, precision):
    """One design point -> (model, nodes, args) on host."""
    design = copy.deepcopy(base_design)
    design = apply_point(design, point) or design
    model = Model(design, precision=precision)
    model.analyze_unloaded()
    args, _ = model.prepare_case_inputs(verbose=False)
    return model, model.nodes.astype(model.dtype), args


def default_collect(model, point, Xi):
    """Per-design summary metrics (the reference sweep's getOutputs,
    parametersweep.py:9-21, plus response statistics).

    Xi : [ncase, 6, nw] complex response amplitudes.
    """
    st = model.statics
    dw = model.dw
    std = np.sqrt(np.sum(np.abs(Xi) ** 2, axis=-1) * dw)  # [ncase, 6]
    return {
        "mass": st.mass,
        "displacement": st.V,
        "GMT": st.zMeta - st.rCG_TOT[2],
        "surge_std": std[:, 0],
        "heave_std": std[:, 2],
        "pitch_std_deg": np.rad2deg(std[:, 4]),
    }


def make_sweep_mesh(devices=None):
    """1-D 'design' mesh over all (or the given) devices — after
    :func:`initialize_distributed` on every host, this spans the whole
    multi-host pool (DCN between hosts, ICI within a slice)."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), ("design",))


def initialize_distributed(coordinator=None, num_processes=None,
                           process_id=None):
    """Join a multi-host JAX pool so sweeps span all hosts' devices.

    Call once per host process before any other JAX use; afterwards
    ``jax.devices()`` lists every chip in the pool and
    :func:`make_sweep_mesh` shards the design axis across all of them.
    Parameters default to the cloud-TPU/SLURM auto-detection built into
    ``jax.distributed.initialize``; pass them explicitly on bare clusters
    (coordinator = "host0:port").

    The reference has no distributed path at all (SURVEY.md §2.4) — its
    243-point sweep is a serial Python loop (parametersweep.py:56-100).
    """
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return jax.process_index(), jax.process_count()


def _load_checkpoint(ck_path):
    """Load a chunk checkpoint if it exists; returns None to recompute.

    Multi-process coherent: the exists/recompute decision is taken on
    process 0 and broadcast, so every host makes the same choice
    (recomputing a chunk runs global collectives that need all processes).
    Multi-host checkpointing requires ``out_dir`` on a filesystem shared
    by all hosts — a host that cannot see a checkpoint process 0 decided
    to load gets a clear error instead of a collective hang.
    """
    if ck_path is None:
        return None

    def _try_load():
        # a checkpoint from an older (pre-atomic-write) run can be
        # truncated; treat an unreadable file as absent
        if not os.path.exists(ck_path):
            return None
        try:
            with np.load(ck_path, allow_pickle=False) as zf:
                return {key: zf[key] for key in zf.files}
        except Exception:
            return None

    if jax.process_count() == 1:
        return _try_load()

    from jax.experimental import multihost_utils

    data = _try_load() if jax.process_index() == 0 else None
    ok = data is not None if jax.process_index() == 0 else False
    ok = bool(multihost_utils.broadcast_one_to_all(np.array(ok)))
    if not ok:
        return None
    if jax.process_index() == 0:
        return data
    data = _try_load()
    if data is None:
        raise RuntimeError(
            f"sweep checkpoint {ck_path} loads on process 0 but not on "
            f"process {jax.process_index()}: multi-host sweeps need "
            "out_dir on a shared filesystem"
        )
    return data


def _fetch(x):
    """Device array -> host NumPy, valid in multi-process runs too: a
    globally sharded result is not fully addressable on one host, so it is
    allgathered first (every host then holds the full sweep results)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def run_sweep(
    base_design,
    points,
    apply_point,
    mesh=None,
    precision=None,
    out_dir=None,
    collect=default_collect,
    verbose=True,
):
    """Run the analysis over all design ``points`` with the design axis
    sharded across ``mesh`` and per-chunk checkpointing under ``out_dir``.

    Parameters
    ----------
    base_design : dict
        The template design (all points share its cases table + settings,
        so every point solves the same [case, freq] batch shape).
    points : list[dict]
        Parameter values per design point (see :func:`grid_points`).
    apply_point : callable(design, point) -> design | None
        Mutates/returns a deep copy of the base design for one point —
        the equivalent of the reference's dependent-geometry update block
        (parametersweep.py:60-100).
    mesh : jax.sharding.Mesh | None
        1-D mesh with axis "design"; default spans all local devices.
    out_dir : str | None
        Checkpoint directory. Chunk k's results live in ``chunk_{k:04d}.npz``
        and are loaded instead of recomputed on restart.

    Returns
    -------
    dict of stacked result arrays, leading axis = len(points), plus
    ``Xi`` [npoints, ncase, 6, nw] complex response amplitudes.
    """
    if mesh is None:
        mesh = make_sweep_mesh()
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)

    sharding = NamedSharding(mesh, P("design"))
    pipeline = None  # built after the first chunk is prepped (needs w grid)

    npoints = len(points)
    chunk_results = []
    for k0 in range(0, npoints, n_dev):
        k = k0 // n_dev
        ck_path = os.path.join(out_dir, f"chunk_{k:04d}.npz") if out_dir else None
        chunk_pts = points[k0 : k0 + n_dev]
        n_real = len(chunk_pts)

        loaded = _load_checkpoint(ck_path)
        if loaded is not None:
            chunk_results.append(loaded)
            if verbose:
                print(f"sweep chunk {k}: loaded checkpoint ({n_real} designs)")
            continue

        # host prep (independent per design; the expensive part is the
        # vmapped CPU mooring equilibrium inside prepare_case_inputs)
        models, nodes_list, args_list = [], [], []
        for pt in chunk_pts:
            m, nd, ar = _prepare_design(base_design, pt, apply_point, precision)
            models.append(m)
            nodes_list.append(nd)
            args_list.append(ar)
        # pad the ragged trailing chunk by repeating the last design so the
        # batch still fills the mesh; the copies are dropped on collect
        while len(nodes_list) < n_dev:
            nodes_list.append(nodes_list[-1])
            args_list.append(args_list[-1])

        nodes_b = pad_and_stack_nodes(nodes_list)
        args_b = tuple(
            np.stack([a[i] for a in args_list]) for i in range(len(args_list[0]))
        )

        if pipeline is None:
            m0 = models[0]
            one_case = make_case_dynamics(
                m0.w, m0.k, m0.depth, m0.rho_water, m0.g,
                m0.XiStart, m0.nIter, m0.dtype, m0.cdtype,
            )
            per_design = jax.vmap(one_case, in_axes=(None, 0, 0, 0, 0, 0, 0, 0))
            pipeline = jax.jit(
                jax.vmap(per_design),
                in_shardings=(sharding,) * 8,
                out_shardings=sharding,
            )

        dev_in = jax.device_put((nodes_b,) + args_b, sharding)
        xr, xi, iters, conv = pipeline(*dev_in)
        xr = _fetch(xr).astype(np.float64)
        xi = _fetch(xi).astype(np.float64)
        Xi = xr + 1j * xi  # [n_dev, ncase, 6, nw]

        res = {"Xi_r": xr[:n_real], "Xi_i": xi[:n_real],
               "converged": _fetch(conv)[:n_real]}
        per_design_metrics = [
            collect(models[i], chunk_pts[i], Xi[i]) for i in range(n_real)
        ]
        for key in per_design_metrics[0]:
            res[key] = np.stack([d[key] for d in per_design_metrics])
        for name in chunk_pts[0]:
            res[f"param_{name}"] = np.array([pt[name] for pt in chunk_pts])

        if ck_path and jax.process_index() == 0:
            # one writer in multi-process runs (every host holds the full
            # allgathered results, so checkpoints stay restartable anywhere);
            # write-then-rename so a crash mid-write never leaves a
            # truncated chunk that would poison the restart
            tmp_path = ck_path + ".tmp.npz"
            np.savez(tmp_path, **res)
            os.replace(tmp_path, ck_path)
        if verbose:
            print(f"sweep chunk {k}: solved {n_real} designs on {n_dev} devices")
        chunk_results.append(res)

    out = {}
    for key in chunk_results[0]:
        out[key] = np.concatenate([c[key] for c in chunk_results], axis=0)
    out["Xi"] = out.pop("Xi_r") + 1j * out.pop("Xi_i")
    return out


def results_to_grid(results, axes, key):
    """Reshape a flat sweep result array back onto the named parameter grid
    (for the reference's contour-matrix plots, parametersweep.py:122-561)."""
    shape = tuple(len(v) for v in axes.values())
    return np.asarray(results[key]).reshape(shape + results[key].shape[1:])
