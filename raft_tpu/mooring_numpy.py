"""Serial single-core NumPy mooring solver — the performance-baseline twin
of :mod:`raft_tpu.mooring`.

This reproduces, in plain NumPy with Python loops over lines, the MoorPy
call pattern the reference consumes (reference raft/raft_model.py:332-378:
``ms.solveEquilibrium3`` then ``ms.getCoupledStiffness(..., tensions=True)``),
the same way :mod:`raft_tpu.reference_numpy` reproduces the reference's
dynamics loops.  It exists so the design-sweep benchmark can measure an
honest end-to-end serial-NumPy baseline (statics + mooring + dynamics per
design) without any JAX machinery in the timed path, and it doubles as an
independent f64 oracle for the JAX mooring solver (tests/test_mooring.py).

Formulation identical to raft_tpu.mooring (elastic catenary, frictionless
seabed, damped Newton in (log HF, log VF) — log space in BOTH unknowns so
the spurious negative-V roots of the touchdown equations are unreachable);
the body stiffness is obtained by
central finite differencing of the net line force like MoorPy does
(MoorPy getCoupledStiffness is FD-based — SURVEY.md §2.2 row 1).
"""

import numpy as np


def _profile_np(H, V, L, EA, w):
    """Fairlead excursion (x, z) for tension components (H, V) — NumPy twin
    of mooring._profile."""
    W = w * L
    VA = V - W
    vh = V / H
    vah = VA / H
    if VA >= 0.0:  # fully suspended
        x = H / w * (np.arcsinh(vh) - np.arcsinh(vah)) + H * L / EA
        z = (
            H / w * (np.sqrt(1 + vh**2) - np.sqrt(1 + vah**2))
            + (V * L - 0.5 * w * L**2) / EA
        )
    else:  # seabed contact
        LB = min(max(L - V / w, 0.0), L)
        x = LB + H / w * np.arcsinh(vh) + H * L / EA
        z = H / w * (np.sqrt(1 + vh**2) - 1.0) + V**2 / (2 * EA * w)
    return x, z


def segment_top_tensions_np(V, L, w, Wp):
    """Vertical tension at the top of each segment (anchor(0)->fairlead;
    NumPy twin of mooring._segment_top_tensions, shared with the
    visualization so the junction accounting lives in one place)."""
    c = np.asarray(w, float) * np.asarray(L, float)
    Wp = np.asarray(Wp, float)
    return V - (np.sum(c) - np.cumsum(c)) - (np.sum(Wp) - np.cumsum(Wp) + Wp)


def _profile_comp_np(H, V, L, EA, w, Wp, seabed=True):
    """Composite-line spans (segments anchor->fairlead; NumPy twin of
    mooring._profile_composite).  Upper segments use the suspended
    expressions (valid for sagging VA < 0 too); only the bottom segment
    can rest on the seabed."""
    L = np.atleast_1d(np.asarray(L, float))
    EA = np.atleast_1d(np.asarray(EA, float))
    w = np.atleast_1d(np.asarray(w, float))
    Wp = np.atleast_1d(np.asarray(Wp, float))
    c = w * L
    Vtop = segment_top_tensions_np(V, L, w, Wp)
    if seabed:
        x, z = _profile_np(H, Vtop[0], L[0], EA[0], w[0])
    else:
        # fully-suspended bottom segment (bridle vessel legs)
        vh = Vtop[0] / H
        vah = (Vtop[0] - c[0]) / H
        x = H / w[0] * (np.arcsinh(vh) - np.arcsinh(vah)) + H * L[0] / EA[0]
        z = (H / w[0] * (np.sqrt(1 + vh**2) - np.sqrt(1 + vah**2))
             + (Vtop[0] * L[0] - 0.5 * w[0] * L[0]**2) / EA[0])
    for i in range(1, len(L)):
        if L[i] == 0.0:
            continue
        vh = Vtop[i] / H
        vah = (Vtop[i] - c[i]) / H
        x += H / w[i] * (np.arcsinh(vh) - np.arcsinh(vah)) + H * L[i] / EA[i]
        z += (H / w[i] * (np.sqrt(1 + vh**2) - np.sqrt(1 + vah**2))
              + (Vtop[i] * L[i] - 0.5 * w[i] * L[i]**2) / EA[i])
    return x, z


def catenary_solve_np(XF, ZF, L, EA, w, Wp=None, tol=1e-10, max_iter=60,
                      seabed=True):
    """Newton solve for one (possibly composite) line's fairlead tensions
    (HF, VF); L/EA/w/Wp may be scalars or [S] segment arrays."""
    L = np.atleast_1d(np.asarray(L, float))
    EA = np.atleast_1d(np.asarray(EA, float))
    w = np.atleast_1d(np.asarray(w, float))
    Wp = np.zeros_like(L) if Wp is None else np.atleast_1d(np.asarray(Wp, float))
    L_tot = np.sum(L)
    W = float(np.sum(w * L))
    w_eff = W / L_tot
    XF = max(XF, 1e-6 * L_tot)
    d = np.hypot(XF, ZF)
    slack = 3.0 * max((L_tot**2 - ZF**2) / XF**2 - 1.0, 1e-8)
    lam0 = 0.25 if L_tot <= d else np.sqrt(slack)
    H = max(abs(0.5 * w_eff * XF / lam0), 10.0)
    V = 0.5 * w_eff * (ZF / np.tanh(lam0) + L_tot) + 0.5 * float(np.sum(Wp))
    if L_tot <= d:
        # taut line: elastic-bar tension along the chord (matches the JAX
        # solver's taut initial guess; the catenary-sag guess stalls here)
        EA_eff = L_tot / float(np.sum(L / EA))
        T_el = EA_eff * max(d - L_tot, 0.0) / L_tot + 0.5 * W
        H = max(T_el * XF / d, 10.0)
        V = T_el * ZF / d + 0.5 * W + 0.5 * float(np.sum(Wp))
    scale = max(abs(XF), abs(ZF))
    # Both unknowns in log space — H > 0 always, and the fairlead (top-end)
    # vertical tension V > 0 for every bottom->top oriented line.  Solving V
    # linearly admits spurious negative-V roots of the touchdown equations
    # (residual ~1e-10 but unphysical); same treatment as the JAX
    # mooring.catenary_solve.
    u = np.log(H)
    s = np.log(max(V, 1.0))
    for _ in range(max_iter):
        H, V = np.exp(u), np.exp(s)
        x, z = _profile_comp_np(H, V, L, EA, w, Wp, seabed)
        r = np.array([x - XF, z - ZF])
        if np.max(np.abs(r)) < tol * scale:
            break
        # Jacobian wrt (log H, log V) by central differences of the profile
        eps = 1e-7
        xp, zp = _profile_comp_np(np.exp(u + eps), V, L, EA, w, Wp, seabed)
        xm, zm = _profile_comp_np(np.exp(u - eps), V, L, EA, w, Wp, seabed)
        J00, J10 = (xp - xm) / (2 * eps), (zp - zm) / (2 * eps)
        xp, zp = _profile_comp_np(H, np.exp(s + eps), L, EA, w, Wp, seabed)
        xm, zm = _profile_comp_np(H, np.exp(s - eps), L, EA, w, Wp, seabed)
        J01, J11 = (xp - xm) / (2 * eps), (zp - zm) / (2 * eps)
        det = J00 * J11 - J01 * J10
        if abs(det) < 1e-30:
            det = 1e-30
        du = (J11 * r[0] - J01 * r[1]) / det
        dv = (-J10 * r[0] + J00 * r[1]) / det
        du = np.clip(du, -1.5, 1.5)
        dv = np.clip(dv, -1.5, 1.5)
        u -= du
        s -= dv
    H, V = np.exp(u), np.exp(s)
    if seabed and ZF >= 0.0 and (
            L_tot >= (XF + ZF) * (1.0 - 2e-4)
            or (L_tot >= d
                and not (np.isfinite(H) and np.isfinite(V)))):
        # fully-slack regime (twin of mooring.catenary_solve): vertical
        # hang of length ZF, excess line on the seabed — H = 0 exactly,
        # V = hanging weight (the touchdown equations have no positive-H
        # root here and the Newton bottoms out with V indeterminate)
        above = np.sum(L) - np.cumsum(L)
        hang = np.clip(ZF - above, 0.0, L)
        H = 0.0
        V = float(np.sum(w * hang) + np.sum(Wp[above < ZF]))
    return H, V


def _rotmat(r4, r5, r6):
    c4, s4 = np.cos(r4), np.sin(r4)
    c5, s5 = np.cos(r5), np.sin(r5)
    c6, s6 = np.cos(r6), np.sin(r6)
    Rx = np.array([[1, 0, 0], [0, c4, -s4], [0, s4, c4]])
    Ry = np.array([[c5, 0, s5], [0, 1, 0], [-s5, 0, c5]])
    Rz = np.array([[c6, -s6, 0], [s6, c6, 0], [0, 0, 1]])
    return Rz @ Ry @ Rx


def line_forces_np(r6, anchors, rFair, L, EA, w, Wp=None):
    """Net 6-DOF mooring reaction at body pose r6 plus per-line (HF, VF) —
    serial loop over lines.  L/EA/w/Wp are [nL] or [nL, S]."""
    if Wp is None:
        Wp = np.zeros_like(np.asarray(L, float))
    R = _rotmat(r6[3], r6[4], r6[5])
    f6 = np.zeros(6)
    HFs = np.zeros(len(L))
    VFs = np.zeros(len(L))
    for i in range(len(L)):
        arm = R @ rFair[i]
        p = r6[:3] + arm
        dxy = p[:2] - anchors[i, :2]
        XF = np.hypot(dxy[0], dxy[1])
        ZF = p[2] - anchors[i, 2]
        HF, VF = catenary_solve_np(XF, ZF, L[i], EA[i], w[i], Wp[i])
        u = dxy / max(XF, 1e-9)
        F3 = np.array([-HF * u[0], -HF * u[1], -VF])
        f6[:3] += F3
        f6[3:] += np.cross(arm, F3)
        HFs[i], VFs[i] = HF, VF
    return f6, HFs, VFs


def line_tensions_np(r6, anchors, rFair, L, EA, w, Wp=None):
    if Wp is None:
        Wp = np.zeros_like(np.asarray(L, float))
    _, HF, VF = line_forces_np(r6, anchors, rFair, L, EA, w, Wp)
    # 1-D legacy [nL] inputs are per-line scalars, not a segment axis
    Lw = np.asarray(w, float) * np.asarray(L, float)
    Wp_ = np.asarray(Wp, float)
    W = (Lw if Lw.ndim == 1 else np.sum(Lw, axis=-1)) + (
        Wp_ if Wp_.ndim == 1 else np.sum(Wp_, axis=-1))
    VA = VF - W
    TB = np.hypot(HF, VF)
    TA = np.where(VA >= 0, np.hypot(HF, VA), HF)
    return np.concatenate([TA, TB])


def body_force_np(r6, m, v, rCG, rM, AWP, rho, g):
    R = _rotmat(r6[3], r6[4], r6[5])
    f6 = np.zeros(6)
    aG = R @ np.asarray(rCG)
    aB = R @ np.asarray(rM)
    Fg = np.array([0.0, 0.0, -m * g])
    Fb = np.array([0.0, 0.0, rho * v * g])
    f6[:3] = Fg + Fb
    f6[3:] = np.cross(aG, Fg) + np.cross(aB, Fb)
    f6[2] -= rho * g * AWP * r6[2]
    return f6


def solve_equilibrium_np(
    f6_ext, body_props, anchors, rFair, L, EA, w, Wp=None, rho=1025.0,
    g=9.81, tol=1e-8, max_iter=40,
):
    """Damped-Newton rigid-body equilibrium (ms.solveEquilibrium3 twin)."""
    m, v, rCG, rM, AWP = body_props

    def total(r6):
        f = line_forces_np(r6, anchors, rFair, L, EA, w, Wp)[0]
        return f + body_force_np(r6, m, v, rCG, rM, AWP, rho, g) + f6_ext

    r6 = np.zeros(6)
    step_cap = np.array([10.0, 10.0, 10.0, 0.1, 0.1, 0.1])
    h = np.array([1e-4, 1e-4, 1e-4, 1e-6, 1e-6, 1e-6])
    for _ in range(max_iter):
        F = total(r6)
        J = np.zeros((6, 6))
        for j in range(6):
            e = np.zeros(6)
            e[j] = h[j]
            J[:, j] = (total(r6 + e) - total(r6 - e)) / (2 * h[j])
        # tiny Tikhonov damping (twin of mooring.solve_equilibrium): an
        # all-slack mooring has exactly zero horizontal stiffness AND
        # zero horizontal force — the damped solve returns a zero step
        # in the neutral directions instead of raising on singularity
        lam = 1e-8 * np.max(np.abs(np.diag(J))) + 1e-30
        dx = np.linalg.solve(J + lam * np.eye(6), -F)
        dx = np.clip(dx, -step_cap, step_cap)
        r6 = r6 + dx
        if np.max(np.abs(dx)) < tol:
            break
    return r6


def coupled_stiffness_np(r6, anchors, rFair, L, EA, w, Wp=None):
    """C = -d f6_lines / d r6 by central differences (MoorPy-style)."""
    h = np.array([1e-4, 1e-4, 1e-4, 1e-6, 1e-6, 1e-6])
    C = np.zeros((6, 6))
    for j in range(6):
        e = np.zeros(6)
        e[j] = h[j]
        fp = line_forces_np(r6 + e, anchors, rFair, L, EA, w, Wp)[0]
        fm = line_forces_np(r6 - e, anchors, rFair, L, EA, w, Wp)[0]
        C[:, j] = -(fp - fm) / (2 * h[j])
    return C


def tension_jacobian_np(r6, anchors, rFair, L, EA, w, Wp=None):
    h = np.array([1e-4, 1e-4, 1e-4, 1e-6, 1e-6, 1e-6])
    nL = len(L)
    J = np.zeros((2 * nL, 6))
    for j in range(6):
        e = np.zeros(6)
        e[j] = h[j]
        tp = line_tensions_np(r6 + e, anchors, rFair, L, EA, w, Wp)
        tm = line_tensions_np(r6 - e, anchors, rFair, L, EA, w, Wp)
        J[:, j] = (tp - tm) / (2 * h[j])
    return J


def case_mooring_np(f6_ext, body_props, anchors, rFair, L, EA, w,
                    Wp=None, rho=1025.0, g=9.81, yawstiff=0.0):
    """Serial twin of mooring.case_mooring: equilibrium + linearization
    (reference calcMooringAndOffsets, raft/raft_model.py:332-392)."""
    r6 = solve_equilibrium_np(
        f6_ext, body_props, anchors, rFair, L, EA, w, Wp, rho=rho, g=g
    )
    C = coupled_stiffness_np(r6, anchors, rFair, L, EA, w, Wp)
    C[5, 5] += yawstiff
    F = line_forces_np(r6, anchors, rFair, L, EA, w, Wp)[0]
    T = line_tensions_np(r6, anchors, rFair, L, EA, w, Wp)
    J = tension_jacobian_np(r6, anchors, rFair, L, EA, w, Wp)
    return r6, C, F, T, J
