"""Reverse-mode design→response composition.

Chains the PR 12 traced prep family (knobs → traced members → packed
nodes → statics → mooring → case args, :mod:`raft_tpu.parametric`) into
the dynamics solve with the implicit-adjoint fixed points from
:mod:`raft_tpu.grad.fixed_point` injected at the two while_loop
boundaries, so ``jax.grad`` of any response/fatigue/RAO scalar w.r.t.
the design knobs works end-to-end.  Forward values are bit-identical to
the forward-mode twin: the injected rules' primals ARE the legacy
solves.

The objective-spec surface consumed by the served grad request type
(Engine.submit_grad / POST /v1/grad, docs/differentiation.md) and the
OpenMDAO ``derivatives`` mode:

 - ``metric``: one of :data:`GRAD_METRICS` (the traced twin's scalar
   response metrics);
 - ``knobs``: non-empty subset of :data:`GRAD_KNOBS` (the design scale
   parameters, raft_tpu/parametric.py PARAM_NAMES);
 - ``theta``: optional evaluation point (4 scale factors, default all
   ones = the base design).
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.grad.fixed_point import (
    implicit_solve_dynamics,
    implicit_solve_equilibrium,
)
from raft_tpu.hydro import excitation_froude_krylov
from raft_tpu.mooring import case_mooring
from raft_tpu.parametric import (
    METRIC_NAMES,
    PARAM_NAMES,
    build_design_response,
)
from raft_tpu.precision import mixed_precision_enabled
from raft_tpu.waves import wave_kinematics

GRAD_METRICS = METRIC_NAMES
GRAD_KNOBS = PARAM_NAMES


def make_implicit_case_dynamics(w, k, depth, rho, g, XiStart, nIter,
                                dtype, cdtype, checkable=False,
                                relax=0.8):
    """:func:`raft_tpu.model.make_case_dynamics` with the IFT adjoint
    attached to the fixed-point solve: same signature, same forward
    values (the implicit rule's primal is the legacy
    :func:`raft_tpu.dynamics.solve_dynamics`), reverse-differentiable.
    ``checkable`` is refused — the checkify debug pipeline and the
    adjoint path are mutually exclusive by construction."""
    if checkable:
        raise NotImplementedError(
            "the implicit-adjoint dynamics path does not support the "
            "checkable debug pipeline")
    w = np.asarray(w).astype(dtype)
    k = np.asarray(k).astype(dtype)
    dw = float(w[1] - w[0])
    rho = float(rho)
    depth = float(depth)
    g = float(g)
    nIter = int(nIter)
    XiStart = float(XiStart)

    def one_case(nodes, zeta, beta, C_lin, M_lin, B_lin, F_add_r,
                 F_add_i):
        with jax.default_matmul_precision("highest"):
            u, ud, pD = wave_kinematics(
                zeta.astype(cdtype), beta, w, k, depth, nodes.r,
                rho=rho, g=g, dtype=cdtype,
            )
            F_iner = excitation_froude_krylov(
                nodes, u, ud, pD, rho, mp=mixed_precision_enabled()
            )
            Fr = jnp.real(F_iner) + F_add_r
            Fi = jnp.imag(F_iner) + F_add_i
            xr, xi, report = implicit_solve_dynamics(
                nodes, u, w, dw, rho, M_lin, B_lin, C_lin, Fr, Fi,
                XiStart, nIter=nIter, relax=relax,
            )
        return xr, xi, report

    return one_case


# :func:`raft_tpu.mooring.case_mooring` with the IFT adjoint attached to
# the equilibrium Newton (same signature, same forward pose; the
# linearized stiffness/tension quantities already differentiate — they
# are jacfwd evaluations AT the converged pose)
implicit_case_mooring = partial(
    case_mooring, equilibrium_fn=implicit_solve_equilibrium)


def parse_objective(doc):
    """Validate a grad objective spec (wire document or plain dict).

    ``{"metric": <GRAD_METRICS>, "knobs": [<GRAD_KNOBS>...],
    "theta": [4 floats]?}`` → (metric, knobs tuple, theta tuple | None).
    Raises ValueError with a client-actionable message on any mismatch —
    the wire layer maps this to a 400.
    """
    if not isinstance(doc, dict):
        raise ValueError("objective must be a JSON object")
    metric = doc.get("metric")
    if metric not in GRAD_METRICS:
        raise ValueError(
            "objective.metric must be one of %s (got %r)"
            % (list(GRAD_METRICS), metric))
    knobs = doc.get("knobs", list(GRAD_KNOBS))
    if (not isinstance(knobs, (list, tuple)) or not knobs
            or any(kn not in GRAD_KNOBS for kn in knobs)):
        raise ValueError(
            "objective.knobs must be a non-empty subset of %s (got %r)"
            % (list(GRAD_KNOBS), knobs))
    theta = doc.get("theta")
    if theta is not None:
        if (not isinstance(theta, (list, tuple))
                or len(theta) != len(GRAD_KNOBS)):
            raise ValueError(
                "objective.theta must list %d scale factors"
                % len(GRAD_KNOBS))
        theta = tuple(float(t) for t in theta)
    return metric, tuple(knobs), theta


def build_design_objective(base_design, metric, m_wohler=4.0):
    """(objective, theta0): ``objective(theta) -> scalar`` is the traced
    design-response metric with the implicit-adjoint solves injected, so
    both ``jax.jacfwd`` and ``jax.grad`` work; theta0 = ones(4)."""
    if metric not in GRAD_METRICS:
        raise ValueError(
            "metric must be one of %s (got %r)"
            % (list(GRAD_METRICS), metric))
    f, theta0 = build_design_response(
        base_design, metrics=(metric,), m_wohler=m_wohler,
        dynamics_factory=make_implicit_case_dynamics,
        mooring_fn=implicit_case_mooring,
    )

    def objective(theta):
        return f(theta)[metric]

    return objective, theta0


def build_value_and_grad(base_design, metric, m_wohler=4.0):
    """(fn, theta0): jitted ``fn(theta) -> (value, grad[4])`` — the
    reverse-mode program the engine memoizes per (design, metric).  The
    pipeline is f64 (statics cancellations), so callers commit theta to
    CPU; one adjoint evaluation prices all knobs at once."""
    objective, theta0 = build_design_objective(
        base_design, metric, m_wohler=m_wohler)
    return jax.jit(jax.value_and_grad(objective)), theta0


def design_value_and_grad(base_design, metric, knobs=GRAD_KNOBS,
                          theta=None, m_wohler=4.0):
    """In-process served-grad semantics: evaluate one objective and its
    exact adjoint gradient restricted to ``knobs``.

    Returns ``(value, {knob: d value / d scale})`` as Python floats —
    the same payload the wire schema carries, so the served answer can
    be checked bit-identical against this function.
    """
    fn, theta0 = build_value_and_grad(base_design, metric,
                                      m_wohler=m_wohler)
    if theta is not None:
        theta0 = jnp.asarray(theta, jnp.float64)
    theta0 = jax.device_put(theta0, jax.devices("cpu")[0])
    value, g = fn(theta0)
    grad = {p: float(g[i]) for i, p in enumerate(GRAD_KNOBS)
            if p in knobs}
    return float(value), grad
