"""Exact adjoints for the solve stack (`raft_tpu/grad`).

The forward stack iterates two data-dependent fixed points — the
drag-linearization loop (raft_tpu/dynamics.py) and the mooring
equilibrium Newton (raft_tpu/mooring.py) — both expressed as
``lax.while_loop``, which JAX can forward-differentiate (the traced
parametric twin's ``jacfwd`` path, PR 12) but not reverse-differentiate.
This package supplies the implicit-function-theorem ``custom_vjp`` rules
that make ``jax.grad`` of any response/fatigue/RAO scalar w.r.t. design
knobs work end-to-end:

 - :mod:`raft_tpu.grad.fixed_point` — the two IFT rules.  Primals call
   the unmodified legacy solves (bit-identical forward), and the adjoint
   is one extra linear solve against the converged state instead of
   backprop-through-iterations;
 - :mod:`raft_tpu.grad.response` — the differentiable design→response
   composition: implicit variants of the case-dynamics /
   case-mooring builders injected into
   :func:`raft_tpu.parametric.build_design_response`, plus the
   objective-spec surface (`metric` × `knobs`) that the served grad
   request type (Engine.submit_grad / POST /v1/grad) and the OpenMDAO
   ``derivatives`` mode consume.

See docs/differentiation.md for the rule derivations, the supported
objective list, the fixed-point mode matrix, and the wire schema.
"""

from raft_tpu.grad.fixed_point import (
    ADJOINT_ITERS_ENV,
    adjoint_iters,
    grad_axis,
    implicit_solve_dynamics,
    implicit_solve_equilibrium,
)
from raft_tpu.grad.response import (
    GRAD_KNOBS,
    GRAD_METRICS,
    build_design_objective,
    build_value_and_grad,
    design_value_and_grad,
    make_implicit_case_dynamics,
    implicit_case_mooring,
    parse_objective,
)

__all__ = [
    "ADJOINT_ITERS_ENV",
    "adjoint_iters",
    "grad_axis",
    "implicit_solve_dynamics",
    "implicit_solve_equilibrium",
    "GRAD_KNOBS",
    "GRAD_METRICS",
    "build_design_objective",
    "build_value_and_grad",
    "design_value_and_grad",
    "make_implicit_case_dynamics",
    "implicit_case_mooring",
    "parse_objective",
]
