"""Implicit-function-theorem adjoints for the stack's two fixed points.

Both iterative solves in the forward stack are ``lax.while_loop``s, which
JAX forward-differentiates but cannot reverse-differentiate.  The rules
here make them reverse-differentiable *without* touching their forward
arithmetic:

:func:`implicit_solve_dynamics`
    ``custom_vjp`` around the drag-linearization fixed point.  The
    primal calls the unmodified :func:`raft_tpu.dynamics.solve_dynamics`
    (legacy traced while_loop), so forward bits are untouched; because
    the waterfall engine drives the SAME per-lane phase closures (its
    bit-parity contract) and the fused sweep agrees to solver tolerance,
    legacy, waterfall, and fused forward modes all route through this
    one adjoint rule.  The backward pass applies the implicit function
    theorem at the converged state: with the per-frequency solve map
    ``T(X) = Z(X)^-1 F(X)`` (assemble drag linearization at X -> complex
    6x6 solves), the response satisfies ``X* = T(X*)`` and the adjoint
    is ``ct_theta = (dT/dtheta)^T q`` where ``(I - A^T) q = v`` with
    ``A = dT/dX`` — one extra *linear* solve against the converged
    state, not backprop-through-iterations.  The transposed solve runs
    the same under-relaxed damped iteration as the forward loop
    (``p <- v + ((1-r) I + r A^T) p``, ``q = r p``), so it converges
    whenever the forward fixed point does, and each step is one
    ``jax.vjp`` of ``T`` (cost of a single forward iteration).

:func:`implicit_solve_equilibrium`
    ``custom_vjp`` around the mooring-equilibrium damped Newton: the
    pose solves ``F(r6*, theta) = 0``, so
    ``ct_theta = -(dF/dtheta)^T J^-T v`` with ``J = dF/dr6`` at the
    converged pose — a single transposed 6x6 solve with the same tiny
    Tikhonov damping as the forward Newton.

NaN-quarantine contract (adjoint mirror of the forward freeze,
:func:`raft_tpu.health.quarantine_cotangents`): a lane whose forward
solve quarantined (``SolveReport.nonfinite``) returns *flagged zeros*
as its adjoint — incoming cotangents are scaled to exactly 0.0 before
the transposed solve, so one bad lane cannot poison a batched gradient
and callers detect it by the same ``nonfinite`` flag as the forward.

Accuracy note: the forward loop stops at its 1% amplitude tolerance,
but the IFT linearization wants the *exact* fixed point, so the forward
rule polishes the converged iterate (residual-only extra iterations of
``T``; the returned primal bits are the legacy solve's, untouched)
before linearizing.  The polish/adjoint iteration cap is
``RAFT_TPU_GRAD_ADJOINT_ITERS`` (default 200) — part of the cached-flag
surface (the ``grad`` axis, raft_tpu/serve/cache.py) because it bounds
gradient accuracy.
"""

import os
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.dynamics import assemble_impedance, solve_dynamics
from raft_tpu.health import quarantine_cotangents
from raft_tpu.hydro import linearized_drag
from raft_tpu.mooring import (
    body_hydrostatic_force,
    line_forces,
    solve_equilibrium,
)

ADJOINT_ITERS_ENV = "RAFT_TPU_GRAD_ADJOINT_ITERS"
_DEFAULT_ADJOINT_ITERS = 200


def adjoint_iters():
    """Iteration cap of the transposed fixed-point solve and of the
    residual-only polish (``RAFT_TPU_GRAD_ADJOINT_ITERS``, default 200).
    Read at trace time, like the other solver-mode env switches."""
    raw = os.environ.get("RAFT_TPU_GRAD_ADJOINT_ITERS", "").strip()
    return int(raw) if raw else _DEFAULT_ADJOINT_ITERS


def grad_axis():
    """The grad axis of the serving flag surface: a string identifying
    the adjoint rule revision and its accuracy-bounding configuration.
    Two executables/results with different grad axes never alias in the
    serving caches (raft_tpu/serve/cache.py folds this into
    ``current_flags()``)."""
    return "ift1;adjoint_iters=%d" % adjoint_iters()


# =====================================================================
# dynamics: the drag-linearization fixed point
# =====================================================================

def _dynamics_T(w, dw, rho):
    """The per-case fixed-point solve map over (real, imag) amplitude
    parts: ``T(x) = Z(x)^-1 F(x)`` with the drag linearization assembled
    at x.  Same operand flow as one body iteration of
    :func:`raft_tpu.dynamics.fixed_point_phases` (baseline precision; the
    adjoint runs f64 on CPU, where the exact complex LU is available and
    the mixed-precision/Pallas forward tiers don't apply)."""

    def T(xr, xi, nodes, u, M_lin, B_lin, C_lin, Fr, Fi):
        with jax.default_matmul_precision("highest"):
            XiL = (xr + 1j * xi).astype(u.dtype)            # [6, nw]
            B_drag, F_drag = linearized_drag(nodes, XiL, u, w, dw, rho)
            Zr, Zi = assemble_impedance(w, M_lin, B_lin + B_drag[None],
                                        C_lin)
            F = F_drag + (Fr + 1j * Fi).astype(u.dtype)     # [nw, 6]
            Z = (Zr + 1j * Zi).astype(u.dtype)
            X = jnp.linalg.solve(Z, F[..., None])[..., 0].T  # [6, nw]
        return jnp.real(X), jnp.imag(X)

    return T


@lru_cache(maxsize=64)
def _dynamics_rule(w_bytes, nw, w_dtype, dw, rho, XiStart, nIter, tol,
                   refine, relax, cap):
    """Build (and cache) the custom_vjp rule for one frequency-grid /
    solver-scalar configuration.  ``w`` travels as bytes so the rule is
    hashable-keyed; everything else is a float/int literal."""
    w = np.frombuffer(w_bytes, dtype=w_dtype, count=nw)
    T = _dynamics_T(w, dw, rho)
    relax_f = float(relax)
    w_old = round(1.0 - relax_f, 12)

    @jax.custom_vjp
    def solve(nodes, u, M_lin, B_lin, C_lin, Fr, Fi):
        return solve_dynamics(
            nodes, u, w, dw, rho, M_lin, B_lin, C_lin, Fr, Fi,
            XiStart, nIter=nIter, tol=tol, refine=refine, relax=relax,
        )

    def fwd(nodes, u, M_lin, B_lin, C_lin, Fr, Fi):
        out = solve_dynamics(
            nodes, u, w, dw, rho, M_lin, B_lin, C_lin, Fr, Fi,
            XiStart, nIter=nIter, tol=tol, refine=refine, relax=relax,
        )
        xr, xi, report = out
        ops = (nodes, u, M_lin, B_lin, C_lin, Fr, Fi)

        # residual-only polish: drive the converged iterate to the exact
        # fixed point of T before the bwd linearizes there.  The primal
        # outputs above are returned untouched (forward bits identical to
        # the legacy solve); only the adjoint linearization state tightens.
        eps = float(np.finfo(jnp.result_type(xr)).eps)
        ptol = 1e3 * eps

        def cond(state):
            i, _, _, delta = state
            return (i < cap) & (delta > ptol)

        def body(state):
            i, pr, pi, _ = state
            tr, ti = T(pr, pi, *ops)
            nr = w_old * pr + relax_f * tr
            ni = w_old * pi + relax_f * ti
            fin = jnp.all(jnp.isfinite(nr)) & jnp.all(jnp.isfinite(ni))
            scale = jnp.maximum(
                jnp.maximum(jnp.max(jnp.abs(pr)), jnp.max(jnp.abs(pi))),
                1e-30)
            delta = jnp.maximum(jnp.max(jnp.abs(nr - pr)),
                                jnp.max(jnp.abs(ni - pi))) / scale
            nr = jnp.where(fin, nr, pr)
            ni = jnp.where(fin, ni, pi)
            return (i + 1, nr, ni, jnp.where(fin, delta, 0.0))

        _, xr_s, xi_s, _ = jax.lax.while_loop(
            cond, body,
            (jnp.array(0), xr, xi, jnp.asarray(jnp.inf, xr.dtype)),
        )
        return out, (ops, xr_s, xi_s, report.nonfinite)

    def bwd(res, cts):
        ops, xr_s, xi_s, nonfinite = res
        ct_xr, ct_xi = cts[0], cts[1]   # report cotangents are symbolic
        #                                 zeros (non-diff health record)
        # adjoint quarantine: flagged zeros in, flagged zeros out
        ct_xr, ct_xi = quarantine_cotangents((ct_xr, ct_xi), nonfinite)

        # A quarantined solve's saved iterate/operands can hold NaN, and
        # NaN * 0 = NaN would re-poison the zeroed cotangents through the
        # vjp arithmetic below.  Finite placeholders are safe here: they
        # only alter the linearization point of lanes whose cotangents
        # are already exact zeros (healthy entries pass through
        # bit-untouched by the where).
        def _fin_leaf(x):
            x = jnp.asarray(x)
            if not jnp.issubdtype(x.dtype, jnp.inexact):
                return x
            return jnp.where(jnp.isfinite(x), x, jnp.zeros_like(x))

        def _fin(tree):
            return jax.tree_util.tree_map(_fin_leaf, tree)

        xr_s, xi_s = _fin(xr_s), _fin(xi_s)
        ops = tuple(_fin(o) for o in ops)

        _, vjp_x = jax.vjp(lambda a, b: T(a, b, *ops), xr_s, xi_s)

        # damped transposed Neumann solve of (I - A^T) q = v via
        # p <- v + ((1-r) I + r A^T) p,  q = r p: same contraction factor
        # as the forward under-relaxed loop, so it converges whenever the
        # forward did.
        eps = float(np.finfo(jnp.result_type(xr_s)).eps)
        vmax = jnp.maximum(jnp.max(jnp.abs(ct_xr)), jnp.max(jnp.abs(ct_xi)))
        atol = jnp.maximum(vmax, 1e-30) * (1e2 * eps)

        def cond(state):
            i, _, _, delta = state
            return (i < cap) & (delta > atol)

        def body(state):
            i, pr, pi, _ = state
            ar, ai = vjp_x((pr, pi))
            # a frozen lane can sit at a non-differentiable point of T
            # (e.g. the drag sigma sqrt at zero response), where even a
            # zero cotangent turns NaN through the linearization — pin
            # the quarantined lane's update so its state stays exact 0
            ar, ai = quarantine_cotangents((ar, ai), nonfinite)
            nr = ct_xr + w_old * pr + relax_f * ar
            ni = ct_xi + w_old * pi + relax_f * ai
            delta = jnp.maximum(jnp.max(jnp.abs(nr - pr)),
                                jnp.max(jnp.abs(ni - pi)))
            return (i + 1, nr, ni, delta)

        _, pr, pi, _ = jax.lax.while_loop(
            cond, body,
            (jnp.array(0), ct_xr, ct_xi,
             jnp.asarray(jnp.inf, ct_xr.dtype)),
        )
        qr, qi = relax_f * pr, relax_f * pi

        _, vjp_th = jax.vjp(lambda o: T(xr_s, xi_s, *o), ops)
        (ct_ops,) = vjp_th((qr, qi))
        # pin the quarantined lane's operand cotangents to exact zeros —
        # the flag, not the value, is the signal (same contract as fwd)
        return quarantine_cotangents(ct_ops, nonfinite)

    solve.defvjp(fwd, bwd)
    return solve


def implicit_solve_dynamics(nodes, u, w, dw, rho, M_lin, B_lin, C_lin,
                            F_lin_r, F_lin_i, XiStart, nIter=15, tol=0.01,
                            refine=1, relax=0.8):
    """:func:`raft_tpu.dynamics.solve_dynamics` with the IFT adjoint
    attached: identical signature, identical forward values (the primal
    IS the legacy solve), plus reverse-mode differentiability w.r.t.
    ``nodes, u, M_lin, B_lin, C_lin, F_lin_r, F_lin_i``.

    ``w`` must be a concrete frequency grid (numpy array) — it is a
    solver constant, not a design variable, and it keys the cached rule.
    The health report output is non-differentiable (its cotangents are
    discarded); quarantined lanes return flagged-zero adjoints.
    """
    w = np.asarray(w)
    rule = _dynamics_rule(
        w.tobytes(), w.shape[0], str(w.dtype), float(dw), float(rho),
        float(XiStart), int(nIter), float(tol), int(refine), float(relax),
        int(adjoint_iters()),
    )
    return rule(nodes, u, M_lin, B_lin, C_lin, F_lin_r, F_lin_i)


# =====================================================================
# mooring: the equilibrium Newton
# =====================================================================

@lru_cache(maxsize=16)
def _equilibrium_rule(rho, g, iters, step_tol):
    """custom_vjp rule for the mooring-equilibrium pose at one
    (rho, g, solver-scalar) configuration."""

    def F(r6, f6_ext, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w, Wp,
          cb):
        f_lines, _, _ = line_forces(r6, anchors, rFair, L, EA, w, Wp, cb,
                                    None)
        f_body = body_hydrostatic_force(r6, m, v, rCG, rM, AWP, rho, g)
        return f_lines + f_body + f6_ext

    @jax.custom_vjp
    def solve(f6_ext, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w, Wp,
              cb):
        return solve_equilibrium(
            f6_ext, (m, v, rCG, rM, AWP), anchors, rFair, L, EA, w, Wp,
            cb, None, rho=rho, g=g, iters=iters, step_tol=step_tol,
        )

    def fwd(f6_ext, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w, Wp,
            cb):
        r6 = solve_equilibrium(
            f6_ext, (m, v, rCG, rM, AWP), anchors, rFair, L, EA, w, Wp,
            cb, None, rho=rho, g=g, iters=iters, step_tol=step_tol,
        )
        return r6, (r6, f6_ext, m, v, rCG, rM, AWP, anchors, rFair, L,
                    EA, w, Wp, cb)

    def bwd(res, ct_r6):
        r6, *ops = res
        ops = tuple(ops)
        # IFT at the root F(r6*, theta) = 0:
        #   ct_theta = -(dF/dtheta)^T J^-T ct_r6,  J = dF/dr6
        # with the forward Newton's tiny Tikhonov damping so the all-slack
        # neutral-equilibrium case (exactly singular J) stays finite.
        J = jax.jacfwd(lambda r: F(r, *ops))(r6)
        lam = 1e-8 * jnp.max(jnp.abs(jnp.diag(J))) + 1e-30
        Jd = J + lam * jnp.eye(6, dtype=J.dtype)
        q = jnp.linalg.solve(Jd.T, ct_r6)
        _, vjp_th = jax.vjp(lambda *o: F(r6, *o), *ops)
        return vjp_th(-q)

    solve.defvjp(fwd, bwd)
    return solve


def implicit_solve_equilibrium(f6_ext, body_props, anchors, rFair, L, EA,
                               w, Wp=None, cb=None, bridles=None,
                               rho=1025.0, g=9.81, iters=40, r6_init=None,
                               step_tol=1e-8):
    """:func:`raft_tpu.mooring.solve_equilibrium` with the IFT adjoint
    attached: same signature, same forward pose (the primal IS the
    legacy damped Newton), reverse-differentiable w.r.t. every array
    operand.  Bridled systems are out of scope (the traced parametric
    twin already refuses them); ``r6_init`` warm starts are likewise
    unsupported here because the adjoint linearizes at the converged
    pose only."""
    if bridles is not None:
        raise NotImplementedError(
            "implicit mooring adjoints support simple (non-bridled) "
            "moorings")
    if r6_init is not None:
        raise NotImplementedError(
            "implicit mooring adjoints do not take r6_init warm starts")
    m, v, rCG, rM, AWP = body_props
    if Wp is None:
        Wp = jnp.zeros_like(L)
    rule = _equilibrium_rule(float(rho), float(g), int(iters),
                             float(step_tol))
    return rule(f6_ext, m, v, rCG, rM, AWP, anchors, rFair, L, EA, w, Wp,
                cb)
