"""Reference-style single-core NumPy RAO solve.

This is the performance *baseline* implementation: it reproduces the
reference's loop structure — an outer Python loop over load cases
(reference raft/raft_model.py:239), a drag-linearization fixed-point loop
(raft_model.py:558-608), inner Python loops over strip nodes for wave
kinematics and drag linearization (raft_fowt.py:503-591, :613-695 —
vectorized only over the frequency axis within a node, exactly like the
reference), and a per-frequency Python loop of dense complex 6x6 solves
(raft_model.py:585-590).

It computes the *same math* as the JAX pipeline (same quirks), so it doubles
as the parity oracle: `tests/test_parity.py` asserts the batched XLA graph
matches this path to tight tolerance, and `bench.py` times the two against
each other for the driver metric (VolturnUS-S RAO solve, 128 w x 12 cases).

Pure NumPy; no JAX imports.
"""

import numpy as np


def _wave_kin_node(zeta0, beta, w, k, h, r):
    """Airy kinematics at ONE node, vectorized over frequency only
    (the reference's helpers.getWaveKin call pattern, raft_fowt.py:517)."""
    x, y, z = r
    cb, sb = np.cos(beta), np.sin(beta)
    zeta = zeta0 * np.exp(-1j * k * (cb * x + sb * y))
    if z >= 0:
        nw = len(w)
        return np.zeros((3, nw), complex), np.zeros((3, nw), complex), np.zeros(nw, complex)
    ekz = np.exp(k * z)
    emk = np.exp(-k * (z + 2.0 * h))
    e2h = np.exp(-2.0 * k * h)
    denom = np.maximum(1.0 - e2h, 1e-30)
    s = (ekz - emk) / denom
    c = (ekz + emk) / denom
    cc = (ekz + emk) / (1.0 + e2h)
    u = np.stack([w * zeta * c * cb, w * zeta * c * sb, 1j * w * zeta * s])
    return u, 1j * w * u, zeta * cc  # pDyn: rho*g applied by the caller


def _translate_matrix_3to6(Mat, r):
    """Sadeghi & Incecik 3x3 -> 6x6 (reference raft/helpers.py:295-318)."""
    out = np.zeros((6, 6))
    H = np.array([[0.0, -r[2], r[1]], [r[2], 0.0, -r[0]], [-r[1], r[0], 0.0]])
    out[:3, :3] = Mat
    out[:3, 3:] = Mat @ H.T
    out[3:, :3] = H @ Mat
    out[3:, 3:] = H @ Mat @ H.T
    return out


def rao_solve_numpy(
    nodes, w, k, depth, rho, g, zeta, beta, C_lin, M_lin, B_lin,
    F_add_r, F_add_i, XiStart=0.1, nIter=15, tol=0.01,
):
    """Solve the case batch with reference-style Python loops.

    Same signature data as Model.case_pipeline_fn's args (NumPy f64).
    Returns Xi [ncase, 6, nw] complex.
    """
    ncase, nw = zeta.shape
    N = nodes.r.shape[0]
    Xi_all = np.zeros((ncase, 6, nw), complex)

    for iCase in range(ncase):  # outer case loop (raft_model.py:239)
        # --- per-node wave kinematics + Froude-Krylov excitation ---
        u = np.zeros((N, 3, nw), complex)
        F_iner = np.zeros((6, nw), complex)
        for n in range(N):  # HOT LOOP #1 (raft_fowt.py:503-591)
            un, udn, ccn = _wave_kin_node(
                zeta[iCase], beta[iCase], w, k, depth, nodes.r[n]
            )
            u[n] = un
            pDyn = rho * g * ccn
            if nodes.strip_mask[n]:
                Imat = rho * nodes.v_side[n] * (
                    (1.0 + nodes.Ca_p1[n]) * nodes.p1Mat[n]
                    + (1.0 + nodes.Ca_p2[n]) * nodes.p2Mat[n]
                ) + rho * nodes.v_end[n] * nodes.Ca_End[n] * nodes.qMat[n]
                f3 = Imat @ udn + pDyn[None, :] * (nodes.a_end[n] * nodes.q[n])[:, None]
                F_iner[:3] += f3
                F_iner[3:] += np.cross(nodes.r[n], f3.T).T

        F_lin = F_iner + F_add_r[iCase].T + 1j * F_add_i[iCase].T  # [6, nw]

        # --- drag-linearization fixed point (raft_model.py:558-608) ---
        XiLast = np.full((6, nw), XiStart, complex)
        Xi = np.zeros((6, nw), complex)
        dw = w[1] - w[0]
        for _ in range(nIter + 1):
            B_drag = np.zeros((6, 6))
            F_drag = np.zeros((6, nw), complex)
            for n in range(N):  # HOT LOOP #2 (raft_fowt.py:613-695)
                if not nodes.submerged[n]:
                    continue
                r = nodes.r[n]
                drdt = np.cross(XiLast[3:].T, r).T
                vnode = 1j * w * (XiLast[:3] + drdt)
                vrel = u[n] - vnode
                p1_sq = np.diag(nodes.p1Mat[n])
                p2_sq = np.diag(nodes.p2Mat[n])
                vRMS_q = np.sqrt(
                    np.sum(np.abs(vrel * nodes.q[n][:, None]) ** 2) * dw
                )
                vRMS_p1 = np.sqrt(np.sum(np.abs(vrel) ** 2 * p1_sq[:, None]) * dw)
                vRMS_p2 = np.sqrt(np.sum(np.abs(vrel) ** 2 * p2_sq[:, None]) * dw)
                cdrag = np.sqrt(8.0 / np.pi) * 0.5 * rho
                Bq = cdrag * vRMS_q * nodes.a_q[n] * nodes.Cd_q[n]
                Bp1 = cdrag * vRMS_p1 * nodes.a_p1[n] * nodes.Cd_p1[n]
                Bp2 = cdrag * vRMS_p2 * nodes.a_p2[n] * nodes.Cd_p2[n]
                Bend = cdrag * vRMS_q * nodes.a_end_abs[n] * nodes.Cd_End[n]
                Bmat = (
                    (Bq + Bend) * nodes.qMat[n]
                    + Bp1 * nodes.p1Mat[n]
                    + Bp2 * nodes.p2Mat[n]
                )
                B_drag += _translate_matrix_3to6(Bmat, r)
                f3 = Bmat @ u[n]
                F_drag[:3] += f3
                F_drag[3:] += np.cross(r, f3.T).T

            F = F_lin + F_drag
            for ii in range(nw):  # HOT LOOP #3 (raft_model.py:585-590)
                Z = (
                    -w[ii] ** 2 * M_lin[iCase, ii]
                    + 1j * w[ii] * (B_lin[iCase, ii] + B_drag)
                    + C_lin[iCase]
                )
                Xi[:, ii] = np.linalg.solve(Z, F[:, ii])

            tolCheck = np.abs(Xi - XiLast) / (np.abs(Xi) + tol)
            if (tolCheck < tol).all():
                break
            XiLast = 0.2 * XiLast + 0.8 * Xi  # under-relaxation (raft_model.py:606)
        Xi_all[iCase] = Xi

    return Xi_all


def added_mass_numpy(nodes, rho):
    """Constant Morison added-mass matrix A[6,6] with a reference-style
    per-node Python loop (raft/raft_fowt.py:541-545, :570-573) — the NumPy
    baseline twin of raft_tpu.hydro.added_mass_morison."""
    A = np.zeros((6, 6))
    N = nodes.r.shape[0]
    for n in range(N):
        if nodes.strip_mask[n]:
            Am = rho * nodes.v_side[n] * (
                nodes.Ca_p1[n] * nodes.p1Mat[n]
                + nodes.Ca_p2[n] * nodes.p2Mat[n]
            ) + rho * nodes.v_end[n] * nodes.Ca_End[n] * nodes.qMat[n]
            A += _translate_matrix_3to6(Am, nodes.r[n])
    return A
