"""Serial NumPy rotor BEM — the baseline twin of raft_tpu.aero.

Reproduces the reference's CCBlade usage pattern with plain NumPy/SciPy
loops (reference raft/raft_rotor.py:213-306 runCCBlade consuming
CCBlade.evaluate): a Python loop over azimuthal sectors and blade sections,
Ning's guaranteed-bracket inflow-angle residual solved per section with
scipy.optimize.brentq, trapezoidal integration to the 6-component hub
loads, and d{T,Q}/d{U, Omega, pitch} by central finite differences.

The reference consumes analytic Fortran adjoints from CCBlade; central
differences are the plain-NumPy equivalent, and their 6 extra evaluations
are counted in the baseline's wall-clock (stated in bench_sweep.py).  This
module doubles as the aero oracle: tests assert the vectorized JAX rotor
(raft_tpu/aero.py) matches these loops.

Pure NumPy/SciPy in the evaluation path; no JAX.
"""

import numpy as np
from scipy.optimize import brentq

_RAD2DEG = 57.29577951308232


def _wind_components_np(Uinf, Omega, azimuth, r, precurve, presweep, precone,
                        yaw, tilt, hubHt, shearExp):
    """Velocity components in the blade-aligned frame at every section
    (CCBlade windcomponents; twin of aero._wind_components)."""
    sy, cy = np.sin(yaw), np.cos(yaw)
    st, ct = np.sin(tilt), np.cos(tilt)
    sa, ca = np.sin(azimuth), np.cos(azimuth)
    sc, cc = np.sin(precone), np.cos(precone)

    x_az = -r * sc + precurve * cc
    z_az = r * cc + precurve * sc
    y_az = presweep

    height = (y_az * sa + z_az * ca) * ct - x_az * st
    V = Uinf * (1.0 + height / hubHt) ** shearExp

    Vwind_x = V * ((cy * st * ca + sy * sa) * sc + cy * ct * cc)
    Vwind_y = V * (cy * st * sa - sy * ca)
    Vrot_x = -Omega * y_az * sc
    Vrot_y = Omega * z_az
    return Vwind_x + Vrot_x, Vwind_y + Vrot_y


def _induction_np(phi, cl, cd, sigma_p, B, r, Rhub, Rtip, Vx, Vy):
    """Scalar induction factors + Ning residual (twin of aero._induction)."""
    sphi = np.sin(phi)
    cphi = np.cos(phi)
    abs_s = max(abs(sphi), 1e-9)

    ftip = B / 2.0 * (Rtip / r - 1.0) / abs_s
    Ftip = 2.0 / np.pi * np.arccos(min(max(np.exp(-ftip), 0.0), 1.0))
    fhub = B / 2.0 * (r / Rhub - 1.0) / abs_s
    Fhub = 2.0 / np.pi * np.arccos(min(max(np.exp(-fhub), 0.0), 1.0))
    F = max(Ftip * Fhub, 1e-6)

    cn = cl * cphi + cd * sphi
    ct = cl * sphi - cd * cphi

    k = sigma_p * cn / (4.0 * F * sphi * sphi)
    kp = sigma_p * ct / (4.0 * F * sphi * cphi)

    if phi > 0:
        if k <= 2.0 / 3.0:
            a = k / (1.0 + k)
        else:
            g1 = 2.0 * F * k - (10.0 / 9.0 - F)
            g2 = max(2.0 * F * k - F * (4.0 / 3.0 - F), 1e-12)
            g3 = 2.0 * F * k - (25.0 / 9.0 - 2.0 * F)
            if abs(g3) < 1e-6:
                a = 1.0 - 1.0 / (2.0 * np.sqrt(g2))
            else:
                a = (g1 - np.sqrt(g2)) / g3
    else:
        a = k / max(k - 1.0, 1e-9) if k > 1.0 else 0.0

    if abs(1.0 - kp) < 1e-9:
        kp += 1e-9
    ap = kp / (1.0 - kp)

    Vy_safe = Vy if abs(Vy) >= 1e-6 else np.sign(Vy) * 1e-6 + 1e-12
    one_minus_a = 1.0 - a
    if abs(one_minus_a) < 1e-12:
        one_minus_a = 1e-12
    resid = sphi / one_minus_a - Vx / Vy_safe * cphi * (1.0 - kp)
    return resid, a, ap, F


def _solve_phi_np(theta, cl_tab, cd_tab, aoa_grid, sigma_p,
                  B, r, Rhub, Rtip, Vx, Vy):
    """Inflow angle for one section: brentq on Ning's brackets (twin of
    aero._solve_phi, which uses bisection + Newton polish)."""

    def resid(phi):
        alpha = phi - theta
        cl = np.interp(alpha * _RAD2DEG, aoa_grid, cl_tab)
        cd = np.interp(alpha * _RAD2DEG, aoa_grid, cd_tab)
        return _induction_np(phi, cl, cd, sigma_p, B, r, Rhub, Rtip, Vx, Vy)[0]

    eps = 1e-6
    r_lo = resid(eps)
    r_hi = resid(np.pi / 2)
    if r_lo * r_hi <= 0:
        lo, hi = eps, np.pi / 2
    elif resid(-np.pi / 4) < 0 and resid(-eps) > 0:
        lo, hi = -np.pi / 4, -eps
    else:
        lo, hi = np.pi / 2, np.pi - eps
    return brentq(resid, lo, hi, xtol=1e-12, rtol=1e-14), resid


def rotor_loads_np(Uinf, Omega, pitch, geom, polars, env, nSector=4):
    """Steady 6-component hub loads with reference-style serial loops
    (twin of aero.rotor_evaluate; same math, per-section Python loop).

    Returns dict with T, Y, Z, Q, My, Mz, P.
    """
    aoa_grid, cl_tabs, cd_tabs, _ = polars
    r = np.asarray(geom["r"], float)
    chord = np.asarray(geom["chord"], float)
    theta_all = np.asarray(geom["theta"], float) + pitch
    precurve = np.asarray(geom["precurve"], float)
    presweep = np.asarray(geom["presweep"], float)
    B = geom["B"]
    Rhub, Rtip = geom["Rhub"], geom["Rtip"]
    precone = geom["precone"]
    sigma_p = B * chord / (2.0 * np.pi * r)
    n = len(r)

    azimuths = np.arange(nSector) * (2.0 * np.pi / nSector)

    # curvature of the extended (hub/tip zero-load) radial stations
    rfull = np.concatenate([[Rhub], r, [Rtip]])
    pcfull = np.concatenate([precurve[:1], precurve, precurve[-1:]])
    psfull = np.concatenate([presweep[:1], presweep, presweep[-1:]])
    x_az = -rfull * np.sin(precone) + pcfull * np.cos(precone)
    z_az = rfull * np.cos(precone) + pcfull * np.sin(precone)
    y_az = psfull
    cone = np.arctan2(-np.gradient(x_az), np.gradient(z_az))
    s = np.concatenate([
        [0.0],
        np.cumsum(np.sqrt(np.diff(rfull) ** 2 + np.diff(pcfull) ** 2
                          + np.diff(psfull) ** 2)),
    ])
    ccone, scone = np.cos(cone), np.sin(cone)

    T = Y = Z = Q = My = Mz = 0.0
    for az in azimuths:  # serial sector loop (CCBlade's evaluate pattern)
        Vx_all, Vy_all = _wind_components_np(
            Uinf, Omega, az, r, precurve, presweep, precone,
            geom["yaw"], geom["tilt"], geom["hubHt"], geom["shearExp"],
        )
        Np = np.zeros(n)
        Tp = np.zeros(n)
        for i in range(n):  # serial section loop
            phi, resid = _solve_phi_np(
                theta_all[i], cl_tabs[i], cd_tabs[i], aoa_grid, sigma_p[i],
                B, r[i], Rhub, Rtip, Vx_all[i], Vy_all[i],
            )
            alpha = phi - theta_all[i]
            cl = np.interp(alpha * _RAD2DEG, aoa_grid, cl_tabs[i])
            cd = np.interp(alpha * _RAD2DEG, aoa_grid, cd_tabs[i])
            _, a, ap, F = _induction_np(
                phi, cl, cd, sigma_p[i], B, r[i], Rhub, Rtip,
                Vx_all[i], Vy_all[i],
            )
            W2 = (Vx_all[i] * (1 - a)) ** 2 + (Vy_all[i] * (1 + ap)) ** 2
            Np[i] = (cl * np.cos(phi) + cd * np.sin(phi)) * 0.5 * env["rho"] * W2 * chord[i]
            Tp[i] = (cl * np.sin(phi) - cd * np.cos(phi)) * 0.5 * env["rho"] * W2 * chord[i]

        Npf = np.concatenate([[0.0], Np, [0.0]])
        Tpf = np.concatenate([[0.0], Tp, [0.0]])
        Fx = np.trapezoid(Npf * ccone, s)
        Fy_a = -np.trapezoid(Tpf, s)
        Fz_a = np.trapezoid(Npf * scone, s)
        Qa = np.trapezoid(Tpf * z_az, s)
        My_a = np.trapezoid(Npf * (z_az * ccone - x_az * scone), s)
        Mz_a = -np.trapezoid(Tpf * x_az + Npf * y_az * ccone, s)
        ca, sa = np.cos(az), np.sin(az)
        T += Fx
        Y += ca * Fy_a - sa * Fz_a
        Z += sa * Fy_a + ca * Fz_a
        Q += Qa
        My += ca * My_a - sa * Mz_a
        Mz += sa * My_a + ca * Mz_a

    scale = B / nSector
    out = dict(T=T * scale, Y=Y * scale, Z=Z * scale, Q=Q * scale,
               My=My * scale, Mz=Mz * scale)
    out["P"] = out["Q"] * Omega
    return out


def run_bem_np(rotor_cfg, Uhub, ptfm_pitch=0.0, yaw_misalign=0.0,
               rel_step=1e-4):
    """Loads + SI derivatives at the operating point (serial twin of
    Rotor.run_bem).  Derivatives by central finite differences — 6 extra
    full evaluations, the plain-NumPy stand-in for CCBlade's analytic
    adjoints.

    rotor_cfg : dict with 'geom' (numpy arrays), 'polars', 'env',
        'Uhub_sched', 'Omega_rpm_sched', 'pitch_deg_sched' — see
        rotor_numpy_config().
    """
    Omega = np.interp(Uhub, rotor_cfg["Uhub_sched"],
                      rotor_cfg["Omega_rpm_sched"]) * np.pi / 30.0
    pitch = np.deg2rad(np.interp(Uhub, rotor_cfg["Uhub_sched"],
                                 rotor_cfg["pitch_deg_sched"]))
    geom = dict(rotor_cfg["geom"])
    geom["tilt"] = np.deg2rad(rotor_cfg["shaft_tilt"]) + ptfm_pitch
    geom["yaw"] = np.deg2rad(yaw_misalign)
    polars, env = rotor_cfg["polars"], rotor_cfg["env"]

    def ev(U, Om, pi):
        return rotor_loads_np(U, Om, pi, geom, polars, env)

    loads = ev(Uhub, Omega, pitch)
    hU = max(abs(Uhub), 1.0) * rel_step
    hOm = max(abs(Omega), 0.1) * rel_step
    hPi = max(abs(pitch), 0.01) * rel_step
    d = {}
    for name, h, args in (
        ("dU", hU, lambda s: (Uhub + s, Omega, pitch)),
        ("dOm", hOm, lambda s: (Uhub, Omega + s, pitch)),
        ("dPi", hPi, lambda s: (Uhub, Omega, pitch + s)),
    ):
        p = ev(*args(h))
        m = ev(*args(-h))
        d[f"dT_{name}"] = (p["T"] - m["T"]) / (2 * h)
        d[f"dQ_{name}"] = (p["Q"] - m["Q"]) / (2 * h)
    return loads, d


def rotor_numpy_config(turbine, site):
    """Host-side rotor configuration for the serial path, from the same
    design dict fields Rotor.__init__ consumes (geometry, operating
    schedule with parked extension, interpolated polars)."""
    from raft_tpu.aero import build_airfoils

    gt = np.array(turbine["blade"]["geometry"], float)
    Uhub = np.array(turbine["wt_ops"]["v"], float)
    Omega_rpm = np.array(turbine["wt_ops"]["omega_op"], float)
    pitch_deg = np.array(turbine["wt_ops"]["pitch_op"], float)
    Uhub = np.r_[Uhub, Uhub.max() * 1.4, 100]
    Omega_rpm = np.r_[Omega_rpm, 0, 0]
    pitch_deg = np.r_[pitch_deg, 90, 90]
    aoa, cl, cd, cm = build_airfoils(turbine, n_span=gt.shape[0])
    geom = dict(
        r=gt[:, 0], chord=gt[:, 1], theta=np.deg2rad(gt[:, 2]),
        precurve=gt[:, 3], presweep=gt[:, 4],
        Rhub=float(turbine["Rhub"]), Rtip=float(turbine["blade"]["Rtip"]),
        B=int(turbine["nBlades"]),
        precone=float(np.deg2rad(turbine["precone"])),
        hubHt=float(turbine["Zhub"]),
        shearExp=float(site["shearExp"]),
    )
    cfg = dict(
        geom=geom,
        polars=(aoa, np.asarray(cl), np.asarray(cd), np.asarray(cm)),
        env=dict(rho=float(site["rho_air"]), mu=float(site["mu_air"])),
        Uhub_sched=Uhub, Omega_rpm_sched=Omega_rpm,
        pitch_deg_sched=pitch_deg,
        shaft_tilt=float(turbine["shaft_tilt"]),
        Zhub=float(turbine["Zhub"]),
        R_rot=float(turbine["blade"]["Rtip"]),
        I_drivetrain=float(turbine["I_drivetrain"]),
    )
    # ROSCO gain schedules over the extended operating schedule
    # (twin of Rotor.set_control_gains, reference raft_rotor.py:309-323)
    pc = turbine.get("pitch_control")
    if pc is None:
        cfg.update(kp_0=np.zeros_like(Uhub), ki_0=np.zeros_like(Uhub),
                   k_float=0.0, kp_tau=0.0, ki_tau=0.0, Ng=1.0)
    else:
        pc_angles = np.array(pc["GS_Angles"]) * _RAD2DEG
        cfg.update(
            kp_0=np.interp(pitch_deg, pc_angles, pc["GS_Kp"],
                           left=0, right=0),
            ki_0=np.interp(pitch_deg, pc_angles, pc["GS_Ki"],
                           left=0, right=0),
            k_float=-pc["Fl_Kp"],
            kp_tau=-turbine["torque_control"]["VS_KP"],
            ki_tau=-turbine["torque_control"]["VS_KI"],
            Ng=turbine["gear_ratio"],
        )
    return cfg


def case_gains_np(cfg, Uinf):
    """Gain-schedule values at wind speed Uinf with the reference's
    ki_tau-from-kp_tau quirk (raft_rotor.py:375) — serial twin of
    Rotor.case_gains, packed for aero_servo_np."""
    kp_beta = -np.interp(Uinf, cfg["Uhub_sched"], cfg["kp_0"])
    ki_beta = -np.interp(Uinf, cfg["Uhub_sched"], cfg["ki_0"])
    kp_tau = cfg["kp_tau"] * (kp_beta == 0)
    ki_tau = cfg["kp_tau"] * (kp_beta == 0)
    return kp_beta, ki_beta, kp_tau, ki_tau, cfg["Ng"], cfg["k_float"]


def aero_servo_np(rotor_cfg, gains, w, case, ptfm_pitch=0.0):
    """Serial twin of Rotor.calc_aero_servo_contributions for
    aeroServoMod=2: mean hub loads (reference ordering quirk
    [T, Y, Z, My, Q, Mz], raft_rotor.py:350-351) and the closed-loop
    a(w)/b(w) from the same transfer-function algebra
    (raft_rotor.py:388-432), with ``gains`` =
    (kp_beta, ki_beta, kp_tau, ki_tau, Ng, k_float) at this wind speed.

    Returns (F_aero0_hub[6], a_aero[nw], b_aero[nw]).
    """
    loads, d = run_bem_np(
        rotor_cfg, case["wind_speed"], ptfm_pitch=ptfm_pitch,
        yaw_misalign=case.get("yaw_misalign", 0.0),
    )
    F_aero0 = np.array([loads["T"], loads["Y"], loads["Z"],
                        loads["My"], loads["Q"], loads["Mz"]])
    kp_beta, ki_beta, kp_tau, ki_tau, Ng, k_float = gains
    I_dt = rotor_cfg["I_drivetrain"]
    D = (
        I_dt * w**2
        + (d["dQ_dOm"] + kp_beta * d["dQ_dPi"] - Ng * kp_tau) * 1j * w
        + ki_beta * d["dQ_dPi"]
        - Ng * ki_tau
    )
    H_QT = ((d["dT_dOm"] + kp_beta * d["dT_dPi"]) * 1j * w
            + ki_beta * d["dT_dPi"]) / D
    resp = (
        d["dT_dU"] - k_float * d["dT_dPi"]
        - H_QT * (d["dQ_dU"] - k_float * d["dQ_dPi"])
    )
    b_aero = np.real(resp)
    a_aero = np.real(resp / (1j * w))
    return F_aero0, a_aero, b_aero
