"""Potential-flow (BEM) coefficient interop: WAMIT-format readers, writers,
and interpolation onto the model frequency grid.

Replaces the pyHAMS reader path the reference consumes
(reference raft/raft_fowt.py:394-420 calcBEM reading WAMIT `.1`/`.3` output
and interpolating onto the RAFT grid; tests/verification.py:240-254 reading
the OC3/OC4 golden files) so externally computed radiation/diffraction
coefficients — from WAMIT, HAMS, Capytaine, or our native solver — flow into
the batched dynamics pipeline as frequency-dependent A(w), B(w) and
excitation X(w).

File conventions (WAMIT v6+ numeric output, ULEN = 1):
  `.1` rows:  PER  I  J  Abar(I,J)  [Bbar(I,J)]
      PER > 0: A = rho * Abar,  B = rho * omega * Bbar
      PER = 0 (omega = inf) and PER < 0 (omega = 0): added mass only.
  `.3` rows:  PER  BETA  I  MOD  PHA  RE  IM  ->  X = rho * g * (RE + i IM)

Pure NumPy, host side; the outputs are plain arrays fed into
Model.prepare_case_inputs.
"""

from dataclasses import dataclass

import numpy as np


@dataclass
class HydroCoeffs:
    """Radiation/diffraction coefficient set on its native frequency grid.

    A [nw, 6, 6]  : added mass (dimensional, kg / kg m / kg m^2)
    B [nw, 6, 6]  : radiation damping
    w [nw]        : rad/s, ascending
    A0, Ainf      : zero-/infinite-frequency added mass if present, else None
    headings [nh] : wave headings (deg) of the excitation data
    X [nw, nh, 6] : complex excitation force per unit amplitude
    """

    w: np.ndarray
    A: np.ndarray
    B: np.ndarray
    headings: np.ndarray = None
    X: np.ndarray = None
    A0: np.ndarray = None
    Ainf: np.ndarray = None
    # native-solver provenance (None for imported WAMIT/Capytaine data):
    # panel counts plus the execution route the coefficients took —
    # {"npanels", "npanels_solved", "sharded", "n_devices", "streamed"}
    solver_info: dict = None


def read_wamit_1(path, rho=1025.0):
    """Read a WAMIT `.1` added-mass/damping file -> (w, A, B, A0, Ainf).

    Accepts both 4-column (A only, zero/infinite frequency) and 5-column
    rows; damping is dimensionalized with the rho*omega WAMIT convention.
    """
    per, ij, vals = [], [], []
    with open(path) as f:
        rows = [ln.split() for ln in f if ln.strip()]
    A0 = np.zeros((6, 6))
    Ainf = np.zeros((6, 6))
    has_A0 = has_Ainf = False
    finite = {}
    for row in rows:
        T = float(row[0])
        i, j = int(row[1]) - 1, int(row[2]) - 1
        a = float(row[3])
        if T == 0.0:            # omega = infinity
            Ainf[i, j] = rho * a
            has_Ainf = True
        elif T < 0.0:           # omega = 0
            A0[i, j] = rho * a
            has_A0 = True
        else:
            b = float(row[4]) if len(row) > 4 else 0.0
            finite.setdefault(T, []).append((i, j, a, b))
    periods = sorted(finite.keys(), reverse=True)      # ascending omega
    w = 2.0 * np.pi / np.array(periods)
    nw = len(w)
    A = np.zeros((nw, 6, 6))
    B = np.zeros((nw, 6, 6))
    for iw, T in enumerate(periods):
        for i, j, a, b in finite[T]:
            A[iw, i, j] = rho * a
            B[iw, i, j] = rho * w[iw] * b
    return w, A, B, (A0 if has_A0 else None), (Ainf if has_Ainf else None)


def read_wamit_3(path, rho=1025.0, g=9.81):
    """Read a WAMIT `.3` excitation file -> (w, headings_deg, X[nw, nh, 6])."""
    data = {}
    heads = set()
    with open(path) as f:
        for ln in f:
            row = ln.split()
            if not row:
                continue
            T = float(row[0])
            beta = float(row[1])
            i = int(row[2]) - 1
            re, im = float(row[5]), float(row[6])
            data[(T, beta, i)] = re + 1j * im
            heads.add(beta)
    periods = sorted({k[0] for k in data}, reverse=True)
    headings = np.array(sorted(heads))
    w = 2.0 * np.pi / np.array(periods)
    X = np.zeros((len(w), len(headings), 6), complex)
    for iw, T in enumerate(periods):
        for ih, beta in enumerate(headings):
            for i in range(6):
                X[iw, ih, i] = rho * g * data.get((T, beta, i), 0.0)
    return w, headings, X


def read_coeffs(file1, file3=None, rho=1025.0, g=9.81):
    """Load a coefficient set from WAMIT-format files."""
    w, A, B, A0, Ainf = read_wamit_1(file1, rho=rho)
    headings = X = None
    if file3 is not None:
        w3, headings, X3 = read_wamit_3(file3, rho=rho, g=g)
        if len(w3) != len(w) or not np.allclose(w3, w, rtol=1e-6):
            # re-interpolate excitation onto the .1 grid
            X = np.empty((len(w), len(headings), 6), complex)
            for ih in range(len(headings)):
                for i in range(6):
                    X[:, ih, i] = np.interp(w, w3, X3[:, ih, i].real) + 1j * np.interp(
                        w, w3, X3[:, ih, i].imag
                    )
        else:
            X = X3
    return HydroCoeffs(w=w, A=A, B=B, headings=headings, X=X, A0=A0, Ainf=Ainf)


def write_wamit_1(path, coeffs, rho=1025.0):
    """Write the `.1` format (round-trip/interop; inverse of read_wamit_1)."""
    with open(path, "w") as f:
        if coeffs.A0 is not None:
            for i in range(6):
                for j in range(6):
                    if coeffs.A0[i, j] != 0.0:
                        f.write(
                            f"{-1.0:14.6E} {i+1:5d} {j+1:5d} "
                            f"{coeffs.A0[i, j] / rho:13.6E}\n"
                        )
        if coeffs.Ainf is not None:
            for i in range(6):
                for j in range(6):
                    if coeffs.Ainf[i, j] != 0.0:
                        f.write(
                            f"{0.0:14.6E} {i+1:5d} {j+1:5d} "
                            f"{coeffs.Ainf[i, j] / rho:13.6E}\n"
                        )
        for iw, wi in enumerate(coeffs.w):
            T = 2.0 * np.pi / wi
            for i in range(6):
                for j in range(6):
                    a = coeffs.A[iw, i, j] / rho
                    b = coeffs.B[iw, i, j] / (rho * wi)
                    if a != 0.0 or b != 0.0:
                        f.write(
                            f"{T:14.6E} {i+1:5d} {j+1:5d} {a:13.6E} {b:13.6E}\n"
                        )


def write_wamit_3(path, coeffs, rho=1025.0, g=9.81):
    """Write the `.3` excitation format (inverse of read_wamit_3)."""
    if coeffs.X is None:
        raise ValueError("coefficient set has no excitation data to write")
    if coeffs.headings is None:
        if coeffs.X.ndim == 3 and coeffs.X.shape[1] == 1:
            import warnings

            warnings.warn(
                "write_wamit_3: coefficient set has a single-heading "
                "excitation column but no headings array; labeling it "
                "0.0 deg — set coeffs.headings explicitly if the data "
                "was solved at a different heading",
                stacklevel=2,
            )
            headings = np.array([0.0])
        else:
            raise ValueError(
                "coefficient set has excitation data but no headings; "
                "set coeffs.headings to the wave-heading array (deg)"
            )
    else:
        headings = np.atleast_1d(coeffs.headings)
    with open(path, "w") as f:
        for iw, wi in enumerate(coeffs.w):
            T = 2.0 * np.pi / wi
            for ih, beta in enumerate(headings):
                for i in range(6):
                    x = coeffs.X[iw, ih, i] / (rho * g)
                    f.write(
                        f"{T:14.6E} {beta:10.3f} {i+1:5d} "
                        f"{abs(x):13.6E} {np.degrees(np.angle(x)):10.3f} "
                        f"{x.real:13.6E} {x.imag:13.6E}\n"
                    )


def write_wamit_hst(path, C_hydro, rho=1025.0, g=9.81, ulen=1.0):
    """Write the WAMIT `.hst` hydrostatic-stiffness format (the third file
    of the reference's OpenFAST-handoff tree, e.g.
    reference raft/data/cylinder/Output/Wamit_format/Buoy.hst): rows
    ``i j C(i,j)`` with the standard nondimensionalization
    C(i,j) / (rho g ULEN^k), k = 2 for i,j <= 3, 3 for mixed, 4 for
    rotation-rotation."""
    C = np.asarray(C_hydro, float)
    with open(path, "w") as f:
        for i in range(6):
            for j in range(6):
                k = 2 + (i >= 3) + (j >= 3)
                val = C[i, j] / (rho * g * ulen**k)
                f.write(f"{i+1:6d}{j+1:6d}    {val:.6E}\n")
    return path


def read_wamit_hst(path, rho=1025.0, g=9.81, ulen=1.0):
    """Read a WAMIT `.hst` file back into a dimensional 6x6 matrix."""
    C = np.zeros((6, 6))
    for line in open(path):
        parts = line.split()
        if len(parts) != 3:
            continue
        i, j = int(parts[0]) - 1, int(parts[1]) - 1
        k = 2 + (i >= 3) + (j >= 3)
        C[i, j] = float(parts[2]) * rho * g * ulen**k
    return C


def read_capytaine_nc(path, w_des=None, excitation="total"):
    """Read a Capytaine radiation/diffraction NetCDF dataset into a
    HydroCoeffs set (the BEM-import route the reference validated before
    moving to HAMS — reference tests/test_capytaine_integration.py).

    The classic-NetCDF3 files Capytaine writes are read with
    scipy.io.netcdf_file (no netCDF4/xarray dependency).

    w_des : optional target grid [rad/s]; coefficients are linearly
        interpolated onto it, raising ValueError if it extends outside
        the tabulated range (the reference integration's contract,
        reference tests/test_capytaine_integration.py:31-34).
    excitation : 'total' (Froude-Krylov + diffraction, the physical
        excitation in current Capytaine datasets — **conjugated on
        import** from Capytaine's e^{-i w t} time convention to this
        package's e^{+i w t} convention so phases feed the complex
        impedance solve Z = -w^2 M + i w B + C correctly) or
        'diffraction' (the raw diffraction_force field alone, passed
        through unconjugated — reference-compat ONLY: what the
        reference's removed integration consumed as fEx; its golden
        arrays match this raw field bit-exactly, so this path exists to
        reproduce them, not to drive response solves).
    """
    from scipy.io import netcdf_file

    with netcdf_file(path, "r", mmap=False) as f:
        w = np.asarray(f.variables["omega"][:], float)
        # dims (omega, radiating_dof, influenced_dof) -> A[w, i, j] with
        # i the force DOF (influenced) and j the motion DOF (radiating)
        A = np.transpose(np.asarray(f.variables["added_mass"][:], float),
                         (0, 2, 1))
        B = np.transpose(
            np.asarray(f.variables["radiation_damping"][:], float), (0, 2, 1)
        )
        diff = np.asarray(f.variables["diffraction_force"][:], float)
        fk = np.asarray(f.variables["Froude_Krylov_force"][:], float)
        if excitation == "total":
            # conjugate: Capytaine e^{-iwt} -> package e^{+iwt}
            X = (diff[0] + fk[0]) - 1j * (diff[1] + fk[1])  # [w, ndir, 6]
        elif excitation == "diffraction":
            X = diff[0] + 1j * diff[1]
        else:
            raise ValueError(
                f"excitation must be 'total' or 'diffraction', "
                f"got {excitation!r}"
            )
        headings = np.degrees(
            np.asarray(f.variables["wave_direction"][:], float)
        )

    order = np.argsort(w)
    w, A, B, X = w[order], A[order], B[order], X[order]
    if w_des is not None:
        w_des = np.asarray(w_des, float)
        if w_des.min() < w.min() - 1e-12 or w_des.max() > w.max() + 1e-12:
            raise ValueError(
                f"requested frequency range [{w_des.min():.3f}, "
                f"{w_des.max():.3f}] rad/s extends outside the Capytaine "
                f"data range [{w.min():.3f}, {w.max():.3f}]"
            )
        interp = lambda col: np.interp(w_des, w, col)   # noqa: E731
        A = np.stack([
            np.stack([interp(A[:, i, j]) for j in range(6)], -1)
            for i in range(6)
        ], -2)
        B = np.stack([
            np.stack([interp(B[:, i, j]) for j in range(6)], -1)
            for i in range(6)
        ], -2)
        X = np.stack([
            np.stack([
                interp(X[:, h, i].real) + 1j * interp(X[:, h, i].imag)
                for i in range(6)
            ], -1)
            for h in range(X.shape[1])
        ], -2)
        w = w_des
    return HydroCoeffs(w=w, A=A, B=B, headings=headings, X=X)


def interp_to_grid(coeffs, w, beta=0.0):
    """Interpolate a HydroCoeffs set onto the model grid `w` [rad/s].

    Mirrors the reference's semantics (raft/raft_fowt.py:398-406): added
    mass is extended toward omega=0 with the zero-frequency value when
    available (else the lowest-frequency value), damping tends to zero at
    omega=0, excitation is linearly interpolated; out-of-range frequencies
    clamp to the nearest data (np.interp semantics).  NaNs raise, matching
    the reference's guards (raft_fowt.py:409-420).

    beta : wave heading (deg) — the excitation is linearly interpolated
    between the two bracketing tabulated headings (clamped outside the
    tabulated range; the reference supports only one heading,
    per-case selection + interpolation are extensions here).

    Returns (A[nw,6,6], B[nw,6,6], X[nw,6] complex).
    """
    wB = coeffs.w
    nw = len(w)
    A = np.empty((nw, 6, 6))
    B = np.empty((nw, 6, 6))
    A_lo = coeffs.A0 if coeffs.A0 is not None else coeffs.A[0]
    wA = np.concatenate([[0.0], wB])
    if coeffs.Ainf is not None:
        # anchor the high-frequency end at the tabulated omega=inf limit
        # (placed just past the model grid so in-range data is untouched)
        w_hi = max(wB[-1], np.max(w)) * 2.0
        wA = np.concatenate([wA, [w_hi]])
    for i in range(6):
        for j in range(6):
            col = np.concatenate([[A_lo[i, j]], coeffs.A[:, i, j]])
            if coeffs.Ainf is not None:
                col = np.concatenate([col, [coeffs.Ainf[i, j]]])
            A[:, i, j] = np.interp(w, wA, col)
            B[:, i, j] = np.interp(
                w, np.concatenate([[0.0], wB]),
                np.concatenate([[0.0], coeffs.B[:, i, j]]),
            )
    X = np.zeros((nw, 6), complex)
    if coeffs.X is not None:
        hs = np.asarray(coeffs.headings, float)
        order = np.argsort(hs)
        hs_s = hs[order]
        if len(hs_s) == 1 or beta <= hs_s[0]:
            Xh = coeffs.X[:, order[0], :]
        elif beta >= hs_s[-1]:
            Xh = coeffs.X[:, order[-1], :]
        else:
            j = int(np.searchsorted(hs_s, beta))
            t = (beta - hs_s[j - 1]) / (hs_s[j] - hs_s[j - 1])
            Xh = ((1.0 - t) * coeffs.X[:, order[j - 1], :]
                  + t * coeffs.X[:, order[j], :])
        for i in range(6):
            X[:, i] = np.interp(w, wB, Xh[:, i].real) + 1j * np.interp(
                w, wB, Xh[:, i].imag
            )
    for name, arr in (("added mass", A), ("damping", B), ("excitation", X)):
        if np.isnan(arr).any():
            raise Exception(
                f"NaN values detected in BEM {name} coefficients. "
                f"Check the input data."
            )
    return A, B, X
