"""raft_tpu — a TPU-native (JAX/XLA) frequency-domain dynamics framework for
floating offshore wind turbines, providing the capabilities of NREL's RAFT
(reference: /root/reference) re-designed TPU-first.

Design notes
------------
The reference is a single-threaded NumPy code whose hot loops (frequencies,
member strip nodes, load cases, sweep designs) are Python ``for`` loops
(reference raft/raft_model.py:585, raft/raft_fowt.py:503).  Here the whole
case-dynamics pipeline is a single jitted XLA graph: strip-theory integrals
are einsums over a padded node axis, the drag-linearization fixed point is a
``lax.while_loop`` with per-case convergence freezing, and the per-frequency
6x6 complex solves run as batched 12x12 real block systems solved by a
vectorized Gauss-Jordan over ``[case, freq]`` (raft_tpu/dynamics.py).
Design sweeps shard over devices with ``jax.sharding``/``shard_map``.

Unlike the reference, the external native solvers (MoorPy quasi-static
mooring, CCBlade Fortran BEM aero, HAMS Fortran potential flow) are
reimplemented natively in JAX (``raft_tpu.mooring``, ``raft_tpu.aero``,
``raft_tpu.bem``), with derivatives coming from autodiff instead of hand
coded adjoints / finite differences.
"""

import os as _os

# Host-mesh CPU parallelism: RAFT_TPU_HOST_DEVICES=N splits the XLA:CPU
# host platform into N virtual devices so embarrassingly-parallel f64 CPU
# islands (the rotor second pass, Rotor.run_bem_batch) shard across host
# cores with shard_map/NamedSharding instead of running one vmapped
# executable on a single XLA:CPU device.  The flag must reach XLA before
# the backend initializes, which means before the first `import jax` in
# the process — importing raft_tpu first is sufficient; a process that
# already initialized JAX keeps its existing device count (documented in
# docs/performance.md "heterogeneous overlap").
_hd = _os.environ.get("RAFT_TPU_HOST_DEVICES", "")
if _hd.strip().isdigit() and int(_hd) > 1:
    _flags = _os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        _os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={int(_hd)}"
        ).strip()

from jax import config as _jax_config

# Float64 is the framework default: the reference physics is float64 NumPy and
# several statics quantities (e.g. hydrostatic C44 ~ -5e9 from cancellation)
# need the headroom.  Hot-path dtypes are still selectable per-Model
# (precision='float32' keeps the TPU MXU path fast; the 6x6 solves stay c128).
if not _os.environ.get("RAFT_TPU_NO_X64"):
    _jax_config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: TPU compiles of the case pipeline and the
# BEM solver run tens of seconds to minutes; caching them on disk makes every
# process after the first start warm (verified to work under the axon TPU
# plugin).  Opt out with RAFT_TPU_NO_COMPILE_CACHE=1 or override the location
# with RAFT_TPU_CACHE_DIR; an explicit user/env JAX cache config wins.
if not _os.environ.get("RAFT_TPU_NO_COMPILE_CACHE"):
    if _jax_config.jax_compilation_cache_dir is None and not _os.environ.get(
        "JAX_COMPILATION_CACHE_DIR"
    ):
        # one cache dir per platform config: CPU executables AOT-compiled
        # in a TPU-plugin process can carry machine features the plain
        # CPU-only process doesn't accept (observed SIGILL warnings).
        # Only a programmatic jax.config platform selection is trusted —
        # the axon TPU plugin in this image ignores the JAX_PLATFORMS env
        # var, so an env-only "cpu" process may still initialize the TPU
        # backend and must not share the true-CPU cache dir.
        _plat = (
            getattr(_jax_config, "jax_platforms", None) or "default"
        ).replace(",", "-")
        _cache = _os.environ.get("RAFT_TPU_CACHE_DIR") or _os.path.expanduser(
            f"~/.cache/raft_tpu_xla_{_plat}"
        )
        try:
            _os.makedirs(_cache, exist_ok=True)
            _jax_config.update("jax_compilation_cache_dir", _cache)
            _jax_config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            _jax_config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        except OSError:  # read-only home: run without the on-disk cache
            pass

from raft_tpu.model import Model, run_raft  # noqa: E402,F401

__version__ = "0.1.0"
