"""End-to-end differentiable design parameterization: theta -> response
metrics as ONE jax-traceable function, so ``jax.jacfwd`` delivers exact
design gradients through the whole frequency-domain pipeline — geometry,
statics, strip-theory hydro, mooring equilibrium (implicit, via the
catenary ``custom_root``s and the equilibrium Newton), the aero-servo
rotor evaluation (including the second-order terms through the BEM
inflow-angle ``custom_root``), and the drag-linearization fixed point.

This is the capability the reference system cannot offer: RAFT's own
OpenMDAO component declares no partials, so WEIS finite-differences
around it (reference raft/omdao_raft.py — no declare_partials anywhere);
MoorPy finite-differences its stiffnesses internally and CCBlade's
hand-coded derivatives stop at the rotor boundary.  Here the same design
scalars that drive the fused sweep (draft, ballast density, column
diameter, mooring line length) flow through a traced twin of the
preprocessing pipeline and every response metric comes back with exact
forward-mode derivatives, validated against central differences in
``tests/test_parametric.py``.

Architecture — the "frozen-topology traced twin"
------------------------------------------------
Host-side preprocessing (``geometry.py``, ``statics.py``) is branchy
NumPy: strip counts from ``ceil``, waterplane-crossing detection,
cap-position cases.  All of those branches depend only on the design
*topology*, which a smooth parameter perturbation does not change.  So
each traced function takes the concrete base-design ``Member`` (the
"template") for every branch decision and strip count, and carries the
arithmetic with traced values.  At ``theta = 1`` the traced twin
reproduces the NumPy pipeline to roundoff (asserted in the tests); away
from it, it is the smooth branch-fixed extension whose derivative is the
true pipeline derivative wherever the true pipeline is differentiable.

Parameters (all multiplicative scales, theta0 = ones(4)):
  0 ``draft``       submerged endpoint depths of platform members
                    (z < 0 scaled, like sweep_fused.scale_draft)
  1 ``ballast``     ballast fill density of platform members
  2 ``col_diam``    diameters of *circular* platform members (columns),
                    including cap hole diameters; shell thickness fixed
  3 ``line_length`` unstretched mooring line length

Metrics returned by the response function (scalars):
  ``pitch_max_deg``     max over cases of mean + 3 sigma platform pitch
  ``offset_max``        max over cases of hypot(surge, sway) mean + 3 sigma
                        (with the reference's sway-from-heave-std quirk)
  ``rao_pitch_peak``    peak pitch RAO [deg/m] over the frequency band of
                        a unit-amplitude wave case appended to the case
                        list (zeta = 1, no wind)
  ``moor_util``         max line tension / breaking load
  ``Mbase_DEL``         Dirlik damage-equivalent tower-base moment range
                        (Wohler m = 4), max over wind cases
"""

import dataclasses
import math

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.geometry import HydroNodes, process_members
from raft_tpu.hydro import added_mass_morison
from raft_tpu.io.schema import cases_as_dicts
from raft_tpu.model import Model, make_case_dynamics
from raft_tpu.mooring import case_mooring, parse_mooring
from raft_tpu.utils.frames import (
    transform_force,
    translate_matrix_3to6,
    translate_matrix_6to6,
)

PARAM_NAMES = ("draft", "ballast", "col_diam", "line_length")

METRIC_NAMES = (
    "pitch_max_deg", "offset_max", "rao_pitch_peak", "moor_util",
    "Mbase_DEL", "Mbase_max", "mass", "displacement",
)


def apply_design_scales(design, theta):
    """Dict-level twin of the traced parameterization: the SAME design
    the traced pipeline models at parameter vector ``theta``, produced by
    mutating a deep copy of the design dict (used by the OpenMDAO scale
    inputs and by finite-difference validation, so the traced derivative
    and the plain-model FD are derivatives of the same function)."""
    import copy

    def scaled(v, s):
        """Scalar/list/array-robust multiplicative scale."""
        if v is None:
            return v
        if np.isscalar(v):
            return float(v) * s
        return (np.asarray(v, float) * s).tolist()

    s_draft, s_ball, s_diam, s_line = (float(t) for t in np.asarray(theta))
    d = copy.deepcopy(design)
    for mem in d["platform"]["members"]:
        for key in ("rA", "rB"):
            v = [float(x) for x in np.asarray(mem[key]).reshape(-1)]
            if v[2] < 0.0:
                v[2] = v[2] * s_draft
            mem[key] = v
        if mem.get("rho_fill") is not None:
            mem["rho_fill"] = scaled(mem["rho_fill"], s_ball)
        if str(mem["shape"])[0].lower() == "c":
            mem["d"] = scaled(mem["d"], s_diam)
            if mem.get("cap_d_in") is not None:
                mem["cap_d_in"] = scaled(mem["cap_d_in"], s_diam)
    for ln in d["mooring"]["lines"]:
        ln["length"] = float(ln["length"]) * s_line
    return d


# =====================================================================
# traced frustum helpers (branch decisions passed in from the template)
# =====================================================================

def _vcv_circ_t(dA, dB, H, degenerate):
    if degenerate:
        return jnp.zeros(()), jnp.zeros(())
    A1 = jnp.pi / 4 * dA**2
    A2 = jnp.pi / 4 * dB**2
    Am = jnp.pi / 4 * dA * dB
    V = (A1 + A2 + Am) * H / 3
    hc = (A1 + 2 * Am + 3 * A2) / (A1 + Am + A2) * H / 4
    return V, hc


def _vcv_rect_t(slA, slB, H, degenerate):
    if degenerate:
        return jnp.zeros(()), jnp.zeros(())
    A1 = slA[0] * slA[1]
    A2 = slB[0] * slB[1]
    Am = jnp.sqrt(A1 * A2)
    denom = A1 + Am + A2
    V = denom * H / 3
    hc = (A1 + 2 * Am + 3 * A2) / denom * H / 4
    return V, hc


def _moi_circ_t(dA, dB, H, p, zero_h, uniform):
    """(I_rad about end, I_ax) of a circular frustum — traced twin of
    statics._moi_circ with the H == 0 / dA == dB branches decided from the
    template (``zero_h``, ``uniform``)."""
    if zero_h:
        return jnp.zeros(()), jnp.zeros(())
    r1, r2 = dA / 2, dB / 2
    if uniform:
        I_rad = (1 / 12) * (p * H * jnp.pi * r1**2) * (3 * r1**2 + 4 * H**2)
        I_ax = 0.5 * p * jnp.pi * H * r1**4
    else:
        ratio = (r2**5 - r1**5) / (r2 - r1)
        I_rad = (1 / 20) * p * jnp.pi * H * ratio + (1 / 30) * p * jnp.pi * \
            H**3 * (r1**2 + 3 * r1 * r2 + 6 * r2**2)
        I_ax = (1 / 10) * p * jnp.pi * H * ratio
    return I_rad, I_ax


def _moi_rect_t(slA, slB, H, p, zero_h):
    if zero_h:
        z = jnp.zeros(())
        return z, z, z
    La, Wa = slA[0], slA[1]
    Lb, Wb = slB[0], slB[1]
    dL, dW = Lb - La, Wb - Wa

    def poly_int(c):
        return sum(ck / (k + 1) for k, ck in enumerate(c))

    l3 = [La**3, 3 * La**2 * dL, 3 * La * dL**2, dL**3]
    w3 = [Wa**3, 3 * Wa**2 * dW, 3 * Wa * dW**2, dW**3]
    x2 = p * H / 12 * poly_int([
        l3[0] * Wa, l3[0] * dW + l3[1] * Wa, l3[1] * dW + l3[2] * Wa,
        l3[2] * dW + l3[3] * Wa, l3[3] * dW,
    ])
    y2 = p * H / 12 * poly_int([
        w3[0] * La, w3[0] * dL + w3[1] * La, w3[1] * dL + w3[2] * La,
        w3[2] * dL + w3[3] * La, w3[3] * dL,
    ])
    z2 = p * H**3 * poly_int(
        [0.0, 0.0, La * Wa, La * dW + Wa * dL, dL * dW])
    return y2 + z2, x2 + z2, x2 + y2


def _translate_force_3to6_t(F, r):
    return jnp.concatenate([F, jnp.cross(r, F)])


# =====================================================================
# traced member construction
# =====================================================================

def _lateral_norm_zero(tpl):
    """True when the template member is exactly vertical (its axis has no
    lateral component) — the traced orientation then uses the constant
    template rotation, avoiding the 0/0 arctan2/sqrt derivative at the
    pole (a vertical member stays vertical under every parameter here)."""
    rAB = tpl.rB - tpl.rA
    return float(rAB[0] ** 2 + rAB[1] ** 2) == 0.0


def _traced_orientation(tpl, rA, rB):
    """q, p1, p2, R traced from the member axis (twin of
    geometry._calc_orientation; Z1Y2Z3 Euler with constant twist)."""
    rAB = rB - rA
    l = jnp.linalg.norm(rAB)
    q = rAB / l
    if _lateral_norm_zero(tpl):
        # direction exactly constant under the parameterization
        return (jnp.asarray(tpl.q), jnp.asarray(tpl.p1),
                jnp.asarray(tpl.p2), jnp.asarray(tpl.R), l)
    beta = np.arctan2(tpl.q[1], tpl.q[0])     # xy-direction is constant
    s1, c1 = np.sin(beta), np.cos(beta)
    phi = jnp.arctan2(jnp.sqrt(q[0] ** 2 + q[1] ** 2), q[2])
    s2, c2 = jnp.sin(phi), jnp.cos(phi)
    s3, c3 = np.sin(np.deg2rad(tpl.gamma)), np.cos(np.deg2rad(tpl.gamma))
    R = jnp.stack([
        jnp.stack([c1 * c2 * c3 - s1 * s3, -c3 * s1 - c1 * c2 * s3,
                   c1 * s2]),
        jnp.stack([c1 * s3 + c2 * c3 * s1, c1 * c3 - c2 * s1 * s3,
                   s1 * s2]),
        jnp.stack([-c3 * s2, s2 * s3 + jnp.zeros(()), c2]),
    ])
    p1 = R @ jnp.array([1.0, 0.0, 0.0])
    p2 = jnp.cross(q, p1)
    return q, p1, p2, R, l


def _segment_strip_counts(tpl):
    """Strips the template discretization assigned to each positive-length
    station segment: count the positive-length strips whose station falls
    inside the segment (exact — geometry._discretize places them at
    midpoints strictly inside)."""
    counts = []
    for i in range(1, len(tpl.stations)):
        a, b = tpl.stations[i - 1], tpl.stations[i]
        if b > a:
            counts.append(int(np.sum(
                (tpl.dls > 0) & (tpl.ls > a) & (tpl.ls < b))))
        else:
            counts.append(0)
    return counts


def _discretize_t(tpl, tm):
    """Traced strip discretization: twin of geometry._discretize with the
    per-segment strip counts and branch structure from the template (the
    counts come from a ceil(), frozen at the base design's values)."""
    dorsl = [tm["dorsl"][i] for i in range(len(tpl.stations))]
    stations = tm["stations"]
    n = len(tpl.stations)

    ls = [jnp.zeros(())]
    dls = [jnp.zeros(())]
    ds = [0.5 * dorsl[0]]
    drs = [0.5 * dorsl[0]]

    tpl_cnt = _segment_strip_counts(tpl)

    for i in range(1, n):
        lstrip_t = tpl.stations[i] - tpl.stations[i - 1]
        lstrip = stations[i] - stations[i - 1]
        if lstrip_t > 0.0:
            ns_seg = tpl_cnt[i - 1]
            dlstrip = lstrip / ns_seg
            m = 0.5 * (dorsl[i] - dorsl[i - 1]) / lstrip
            ls += [stations[i - 1] + dlstrip * (0.5 + j)
                   for j in range(ns_seg)]
            dls += [dlstrip] * ns_seg
            ds += [dorsl[i - 1] + dlstrip * 2 * m * (0.5 + j)
                   for j in range(ns_seg)]
            drs += [dlstrip * m] * ns_seg
        elif lstrip_t == 0.0:
            ls += [stations[i - 1]]
            dls += [jnp.zeros(())]
            ds += [0.5 * (dorsl[i - 1] + dorsl[i])]
            drs += [0.5 * (dorsl[i] - dorsl[i - 1])]
        # end-B plate strip, appended per segment (reference quirk kept,
        # see geometry._discretize docstring)
        ls += [stations[-1]]
        dls += [jnp.zeros(())]
        ds += [0.5 * dorsl[-1]]
        drs += [-0.5 * dorsl[-1]]

    return (jnp.stack(ls), jnp.stack(dls), jnp.stack(ds), jnp.stack(drs))


def make_traced_members(templates, theta):
    """Traced member bundles from the concrete templates at parameter
    vector ``theta`` (see module docstring for the parameterization).
    Returns a list of dicts, one per member, carrying traced arrays plus
    the template for branch decisions."""
    s_draft, s_ball, s_diam = theta[0], theta[1], theta[2]
    out = []
    for tpl in templates:
        platform = tpl.type > 1
        if platform:
            zA = jnp.where(tpl.rA[2] < 0, tpl.rA[2] * s_draft,
                           tpl.rA[2])
            zB = jnp.where(tpl.rB[2] < 0, tpl.rB[2] * s_draft,
                           tpl.rB[2])
            rA = jnp.asarray(tpl.rA).at[2].set(zA)
            rB = jnp.asarray(tpl.rB).at[2].set(zB)
        else:
            rA = jnp.asarray(tpl.rA)
            rB = jnp.asarray(tpl.rB)
        q, p1, p2, R, l = _traced_orientation(tpl, rA, rB)
        stations = jnp.asarray(tpl.stations) * (l / tpl.l)
        if tpl.circular:
            scale = s_diam if platform else 1.0
            dorsl = jnp.asarray(tpl.d) * scale
            cap_d_in = (jnp.asarray(tpl.cap_stations * 0.0)
                        if len(tpl.cap_stations) == 0
                        else jnp.asarray(tpl.cap_d_in) * scale)
        else:
            dorsl = jnp.asarray(tpl.sl)
            cap_d_in = jnp.asarray(np.atleast_2d(tpl.cap_d_in)) \
                if len(tpl.cap_stations) else jnp.zeros((0, 2))
        rho_fill = jnp.asarray(tpl.rho_fill) * (s_ball if platform else 1.0)

        tm = dict(
            tpl=tpl,
            rA=rA, rB=rB, l=l, q=q, p1=p1, p2=p2, R=R,
            stations=stations,
            dorsl=dorsl,
            t=jnp.asarray(tpl.t),
            l_fill=jnp.asarray(tpl.l_fill),
            rho_fill=rho_fill,
            cap_stations=jnp.asarray(tpl.cap_stations) * (l / tpl.l),
            cap_t=jnp.asarray(tpl.cap_t),
            cap_d_in=cap_d_in,
        )
        tm["ls"], tm["dls"], tm["ds"], tm["drs"] = _discretize_t(tpl, tm)
        tm["r"] = rA[None, :] + (tm["ls"][:, None] / l) * (rB - rA)[None, :]
        out.append(tm)
    return out


# =====================================================================
# traced inertia / hydrostatics / statics aggregation
# =====================================================================

def member_inertia_t(tm):
    """Traced twin of statics.member_inertia (same math, branch decisions
    from the template)."""
    tpl = tm["tpl"]
    n = len(tpl.stations)
    mass_center = jnp.zeros(3)
    M_struc = jnp.zeros((6, 6))

    for i in range(1, n):
        rA = tm["rA"] + tm["q"] * tm["stations"][i - 1]
        l_t = float(tpl.stations[i] - tpl.stations[i - 1])
        if l_t == 0.0:
            continue
        l = tm["stations"][i] - tm["stations"][i - 1]

        l_fill = (tm["l_fill"] if tm["l_fill"].ndim == 0
                  else tm["l_fill"][i - 1])
        rho_fill = (tm["rho_fill"] if tm["rho_fill"].ndim == 0
                    else tm["rho_fill"][i - 1])
        rho_shell = tpl.rho_shell

        if tpl.circular:
            dA, dB = tm["dorsl"][i - 1], tm["dorsl"][i]
            dA_t, dB_t = tpl.d[i - 1], tpl.d[i]
            dAi = dA - 2 * tm["t"][i - 1]
            dBi = dB - 2 * tm["t"][i]
            dAi_t = tpl.d[i - 1] - 2 * tpl.t[i - 1]
            dBi_t = tpl.d[i] - 2 * tpl.t[i]
            V_o, hco = _vcv_circ_t(dA, dB, l, dA_t == 0 and dB_t == 0)
            V_i, hci = _vcv_circ_t(dAi, dBi, l, dAi_t == 0 and dBi_t == 0)
            v_shell = V_o - V_i
            m_shell = v_shell * rho_shell
            hc_shell = (hco * V_o - hci * V_i) / (V_o - V_i)
            dBi_fill = (dBi - dAi) * (l_fill / l) + dAi
            lf_t = float(tpl.l_fill if np.isscalar(tpl.l_fill)
                         else tpl.l_fill[i - 1])
            dBi_fill_t = (dBi_t - dAi_t) * (lf_t / l_t) + dAi_t
            v_fill, hc_fill = _vcv_circ_t(
                dAi, dBi_fill, l_fill, dAi_t == 0 and dBi_fill_t == 0)
            m_fill = v_fill * rho_fill
            mass = m_shell + m_fill
            hc = (hc_fill * m_fill + hc_shell * m_shell) / mass
            center = rA + tm["q"] * hc

            Iro, Iao = _moi_circ_t(dA, dB, l, rho_shell, l_t == 0,
                                   dA_t == dB_t)
            Iri, Iai = _moi_circ_t(dAi, dBi, l, rho_shell, l_t == 0,
                                   dAi_t == dBi_t)
            Irf, Iaf = _moi_circ_t(dAi, dBi_fill, l_fill, rho_fill,
                                   lf_t == 0, dAi_t == dBi_fill_t)
            I_rad = (Iro - Iri) + Irf - mass * hc**2
            I_ax = (Iao - Iai) + Iaf
            Ixx = Iyy = I_rad
            Izz = I_ax
        else:
            slA, slB = tm["dorsl"][i - 1], tm["dorsl"][i]
            slA_t, slB_t = tpl.sl[i - 1], tpl.sl[i]
            slAi = slA - 2 * tm["t"][i - 1]
            slBi = slB - 2 * tm["t"][i]
            slAi_t = tpl.sl[i - 1] - 2 * tpl.t[i - 1]
            slBi_t = tpl.sl[i] - 2 * tpl.t[i]

            def deg_rect(a_t, b_t):
                A1, A2 = a_t[0] * a_t[1], b_t[0] * b_t[1]
                return (A1 + A2 + np.sqrt(max(A1 * A2, 0.0))) == 0

            V_o, hco = _vcv_rect_t(slA, slB, l, deg_rect(slA_t, slB_t))
            V_i, hci = _vcv_rect_t(slAi, slBi, l, deg_rect(slAi_t, slBi_t))
            v_shell = V_o - V_i
            m_shell = v_shell * rho_shell
            hc_shell = (hco * V_o - hci * V_i) / (V_o - V_i)
            slBi_fill = (slBi - slAi) * (l_fill / l) + slAi
            lf_t = (tpl.l_fill if np.isscalar(tpl.l_fill)
                    else tpl.l_fill[i - 1])
            v_fill, hc_fill = _vcv_rect_t(
                slAi, slBi_fill, l_fill, lf_t == 0)
            m_fill = v_fill * rho_fill
            mass = m_shell + m_fill
            hc = (hc_fill * m_fill + hc_shell * m_shell) / mass
            center = rA + tm["q"] * hc

            Ixo, Iyo, Izo = _moi_rect_t(slA, slB, l, rho_shell, l_t == 0)
            Ixi, Iyi, Izi = _moi_rect_t(slAi, slBi, l, rho_shell, l_t == 0)
            Ixf, Iyf, Izf = _moi_rect_t(slAi, slBi_fill, l_fill, rho_fill,
                                        lf_t == 0)
            Ixx = (Ixo - Ixi) + Ixf - mass * hc**2
            Iyy = (Iyo - Iyi) + Iyf - mass * hc**2
            Izz = (Izo - Izi) + Izf

        mass_center = mass_center + mass * center
        Mmat = jnp.diag(jnp.stack([mass, mass, mass,
                                   jnp.zeros(()), jnp.zeros(()),
                                   jnp.zeros(())]))
        I = jnp.diag(jnp.stack([Ixx, Iyy, Izz]))
        Mmat = Mmat.at[3:, 3:].set(tm["R"] @ I @ tm["R"].T)
        M_struc = M_struc + translate_matrix_6to6(Mmat, center)

    # ----- end caps / bulkheads -----
    for i in range(len(tpl.cap_stations)):
        L_t = float(tpl.cap_stations[i])
        L = tm["cap_stations"][i]
        h = tm["cap_t"][i]
        h_t = float(tpl.cap_t[i])
        rho_cap = tpl.rho_shell
        st_t = tpl.stations
        st = tm["stations"]

        if tpl.circular:
            d_hole = tm["cap_d_in"][i]
            d_in = tm["dorsl"] - 2 * tm["t"]
            d_in_t = tpl.d - 2 * tpl.t
            if L_t == st_t[0]:
                dA = d_in[0]
                dB = jnp.interp(L + h, st, d_in)
                dAi = d_hole
                dBi = dB * (dAi / dA)
                dA_t, dB_t = d_in_t[0], np.interp(L_t + h_t, st_t, d_in_t)
                dAi_t = tpl.cap_d_in[i]
                dBi_t = dB_t * (dAi_t / dA_t)
            elif L_t == st_t[-1]:
                dA = jnp.interp(L - h, st, d_in)
                dB = d_in[-1]
                dBi = d_hole
                dAi = dA * (dBi / dB)
                dA_t, dB_t = np.interp(L_t - h_t, st_t, d_in_t), d_in_t[-1]
                dBi_t = tpl.cap_d_in[i]
                dAi_t = dA_t * (dBi_t / dB_t)
            elif (i < len(tpl.cap_stations) - 1
                    and L_t == tpl.cap_stations[i + 1]):
                dA = jnp.interp(L - h, st, d_in)
                dB = d_in[i]
                dBi = d_hole
                dAi = dA * (dBi / dB)
                dA_t = np.interp(L_t - h_t, st_t, d_in_t)
                dB_t = d_in_t[i]
                dBi_t = tpl.cap_d_in[i]
                dAi_t = dA_t * (dBi_t / dB_t)
            elif i > 0 and L_t == tpl.cap_stations[i - 1]:
                dA = d_in[i]
                dB = jnp.interp(L + h, st, d_in)
                dAi = d_hole
                dBi = dB * (dAi / dA)
                dA_t = d_in_t[i]
                dB_t = np.interp(L_t + h_t, st_t, d_in_t)
                dAi_t = tpl.cap_d_in[i]
                dBi_t = dB_t * (dAi_t / dA_t)
            else:
                dA = jnp.interp(L - h / 2, st, d_in)
                dB = jnp.interp(L + h / 2, st, d_in)
                dM = jnp.interp(L, st, d_in)
                dMi = d_hole
                dAi = dA * (dMi / dM)
                dBi = dB * (dMi / dM)
                dA_t = np.interp(L_t - h_t / 2, st_t, d_in_t)
                dB_t = np.interp(L_t + h_t / 2, st_t, d_in_t)
                dM_t = np.interp(L_t, st_t, d_in_t)
                dAi_t = dA_t * (tpl.cap_d_in[i] / dM_t)
                dBi_t = dB_t * (tpl.cap_d_in[i] / dM_t)

            V_o, hco = _vcv_circ_t(dA, dB, h, dA_t == 0 and dB_t == 0)
            V_i, hci = _vcv_circ_t(dAi, dBi, h, dAi_t == 0 and dBi_t == 0)
            v_cap = V_o - V_i
            m_cap = v_cap * rho_cap
            hc_cap = (hco * V_o - hci * V_i) / (V_o - V_i)
            Iro, Iao = _moi_circ_t(dA, dB, h, rho_cap, h_t == 0,
                                   dA_t == dB_t)
            Iri, Iai = _moi_circ_t(dAi, dBi, h, rho_cap, h_t == 0,
                                   dAi_t == dBi_t)
            I_rad = (Iro - Iri) - m_cap * hc_cap**2
            Ixx = Iyy = I_rad
            Izz = Iao - Iai
        else:
            raise NotImplementedError(
                "traced rectangular caps not supported (no reference "
                "design uses them; reference raft/raft_member.py:570 "
                "cannot execute this path either)"
            )

        pos_cap = tm["rA"] + tm["q"] * L
        if L_t == st_t[0]:
            center_cap = pos_cap + tm["q"] * hc_cap
        elif L_t == st_t[-1]:
            center_cap = pos_cap - tm["q"] * (h - hc_cap)
        else:
            center_cap = pos_cap - tm["q"] * (h / 2 - hc_cap)

        mass_center = mass_center + m_cap * center_cap
        Mmat = jnp.diag(jnp.stack([m_cap, m_cap, m_cap, jnp.zeros(()),
                                   jnp.zeros(()), jnp.zeros(())]))
        I = jnp.diag(jnp.stack([Ixx, Iyy, Izz]))
        Mmat = Mmat.at[3:, 3:].set(tm["R"] @ I @ tm["R"].T)
        M_struc = M_struc + translate_matrix_6to6(Mmat, center_cap)

    mass = M_struc[0, 0]
    center = mass_center / mass
    return M_struc, mass, center


def member_hydrostatics_t(tm, rho, g):
    """Traced twin of statics.member_hydrostatics (crossing/submerged
    branch per segment decided from the template)."""
    tpl = tm["tpl"]
    Fvec = jnp.zeros(6)
    Cmat = jnp.zeros((6, 6))
    V_UW = jnp.zeros(())
    r_centerV = jnp.zeros(3)
    AWP = IWP = xWP = yWP = jnp.zeros(())

    n = len(tpl.stations)
    for i in range(1, n):
        rA = tm["rA"] + tm["q"] * tm["stations"][i - 1]
        rB = tm["rA"] + tm["q"] * tm["stations"][i]
        zA_t = tpl.rA[2] + tpl.q[2] * tpl.stations[i - 1]
        zB_t = tpl.rA[2] + tpl.q[2] * tpl.stations[i]

        if zA_t * zB_t <= 0 and not (zA_t <= 0 and zB_t <= 0):
            # waterplane-crossing segment
            beta = np.arctan2(tpl.q[1], tpl.q[0])
            if _lateral_norm_zero(tpl):
                phi = jnp.zeros(())
            else:
                phi = jnp.arctan2(
                    jnp.sqrt(tm["q"][0] ** 2 + tm["q"][1] ** 2),
                    tm["q"][2])
            cosPhi, sinPhi = jnp.cos(phi), jnp.sin(phi)
            tanPhi = jnp.tan(phi)

            def intrp(x, xA, xB, yA, yB):
                return yA + (x - xA) * (yB - yA) / (xB - xA)

            xWP = intrp(0.0, rA[2], rB[2], rA[0], rB[0])
            yWP = intrp(0.0, rA[2], rB[2], rA[1], rB[1])
            if tpl.circular:
                # reference endpoint-order quirk kept (raft_member.py:697)
                dWP = intrp(0.0, rA[2], rB[2], tm["dorsl"][i],
                            tm["dorsl"][i - 1])
                AWP = (jnp.pi / 4) * dWP**2
                IWP = (jnp.pi / 64) * dWP**4
                IxWP = IyWP = IWP
            else:
                slWP = intrp(0.0, rA[2], rB[2], tm["dorsl"][i],
                             tm["dorsl"][i - 1])
                dWP = jnp.sqrt(4 * slWP[0] * slWP[1] / jnp.pi)
                AWP = slWP[0] * slWP[1]
                IxWP = (1 / 12) * slWP[0] * slWP[1] ** 3
                IyWP = (1 / 12) * slWP[0] ** 3 * slWP[0]  # quirk kept
                I = jnp.diag(jnp.stack([IxWP, IyWP, jnp.zeros(())]))
                I_rot = tm["R"] @ I @ tm["R"].T
                IxWP = I_rot[0, 0]
                IyWP = I_rot[1, 1]
                IWP = IxWP

            LWP = jnp.abs(rA[2]) / cosPhi
            if tpl.circular:
                V_UWi, hc = _vcv_circ_t(tm["dorsl"][i - 1], dWP, LWP,
                                        False)
            else:
                V_UWi, hc = _vcv_rect_t(tm["dorsl"][i - 1], slWP, LWP,
                                        False)
            r_center = rA + tm["q"] * hc

            dPhi_dThx = -np.sin(beta)
            dPhi_dThy = np.cos(beta)
            dFz_dz = -rho * g * AWP / cosPhi

            Fz = rho * g * V_UWi
            M = (
                -rho * g * jnp.pi
                * (dWP**2 / 32 * (2.0 + tanPhi**2)
                   + 0.5 * (rA[2] / cosPhi) ** 2) * sinPhi
            )
            Fvec = Fvec.at[2].add(Fz)
            Fvec = Fvec.at[3].add(M * dPhi_dThx + Fz * rA[1])
            Fvec = Fvec.at[4].add(M * dPhi_dThy - Fz * rA[0])

            Cmat = Cmat.at[2, 2].add(-dFz_dz)
            Cmat = Cmat.at[2, 3].add(rho * g * (-AWP * yWP))
            Cmat = Cmat.at[2, 4].add(rho * g * (AWP * xWP))
            Cmat = Cmat.at[3, 2].add(rho * g * (-AWP * yWP))
            Cmat = Cmat.at[3, 3].add(rho * g * (IxWP + AWP * yWP**2))
            Cmat = Cmat.at[3, 4].add(rho * g * (AWP * xWP * yWP))
            Cmat = Cmat.at[4, 2].add(rho * g * (AWP * xWP))
            Cmat = Cmat.at[4, 3].add(rho * g * (AWP * xWP * yWP))
            Cmat = Cmat.at[4, 4].add(rho * g * (IyWP + AWP * xWP**2))
            Cmat = Cmat.at[3, 3].add(rho * g * V_UWi * r_center[2])
            Cmat = Cmat.at[4, 4].add(rho * g * V_UWi * r_center[2])

            V_UW = V_UW + V_UWi
            r_centerV = r_centerV + r_center * V_UWi

        elif zA_t <= 0 and zB_t <= 0:
            l = tm["stations"][i] - tm["stations"][i - 1]
            if tpl.circular:
                V_UWi, hc = _vcv_circ_t(tm["dorsl"][i - 1], tm["dorsl"][i],
                                        l, False)
            else:
                V_UWi, hc = _vcv_rect_t(tm["dorsl"][i - 1], tm["dorsl"][i],
                                        l, False)
            r_center = rA + tm["q"] * hc
            Fvec = Fvec + _translate_force_3to6_t(
                jnp.stack([jnp.zeros(()), jnp.zeros(()),
                           rho * g * V_UWi]), r_center)
            Cmat = Cmat.at[3, 3].add(rho * g * V_UWi * r_center[2])
            Cmat = Cmat.at[4, 4].add(rho * g * V_UWi * r_center[2])
            V_UW = V_UW + V_UWi
            r_centerV = r_centerV + r_center * V_UWi
        # else fully above water: nothing

    return Fvec, Cmat, V_UW, r_centerV, AWP, IWP, xWP, yWP


def compute_statics_t(tms, turbine, rho_water, g, turbine_t=None):
    """Traced twin of statics.compute_statics returning the subset the
    dynamics/mooring consume: M_struc, C_struc, C_hydro, mass, rCG_TOT,
    V, AWP, zMeta.

    ``turbine_t`` optionally supplies the RNA lumped properties as a
    traced 5-tuple (mRNA, IxRNA, IrRNA, xCG_RNA, hHub) — the batched
    design-prep path (raft_tpu/batched_prep.py) traces them per lane;
    when None (default) the constants come from the ``turbine`` dict
    exactly as before."""
    M_struc = jnp.zeros((6, 6))
    C_hydro = jnp.zeros((6, 6))
    Sum_M_center = jnp.zeros(3)
    VTOT = jnp.zeros(())
    AWP_TOT = jnp.zeros(())
    IWPx_TOT = jnp.zeros(())
    Sum_V_rCB = jnp.zeros(3)

    for tm in tms:
        Mm, mass, center = member_inertia_t(tm)
        M_struc = M_struc + Mm
        Sum_M_center = Sum_M_center + center * mass

        Fvec, Cmat, V_UW, r_centerV, AWP, IWP, xWP, yWP = \
            member_hydrostatics_t(tm, rho_water, g)
        C_hydro = C_hydro + Cmat
        VTOT = VTOT + V_UW
        AWP_TOT = AWP_TOT + AWP
        IWPx_TOT = IWPx_TOT + IWP + AWP * yWP**2
        Sum_V_rCB = Sum_V_rCB + r_centerV

    if turbine_t is not None:
        mRNA, IxRNA, IrRNA, xCG_RNA, hHub = (
            jnp.asarray(v) for v in turbine_t)
    else:
        mRNA = float(turbine["mRNA"])
        IxRNA = float(turbine["IxRNA"])
        IrRNA = float(turbine["IrRNA"])
        xCG_RNA = float(turbine["xCG_RNA"])
        hHub = float(turbine["hHub"])
    Mmat = jnp.diag(jnp.stack(
        [jnp.asarray(v) for v in
         (mRNA, mRNA, mRNA, IxRNA, IrRNA, IrRNA)]))
    center = jnp.stack([jnp.asarray(v) for v in (xCG_RNA, 0.0, hHub)])
    M_struc = M_struc + translate_matrix_6to6(Mmat, center)
    Sum_M_center = Sum_M_center + center * mRNA

    mTOT = M_struc[0, 0]
    rCG_TOT = Sum_M_center / mTOT
    rCB_TOT = Sum_V_rCB / VTOT
    zMeta = rCB_TOT[2] + IWPx_TOT / VTOT

    C_struc = jnp.zeros((6, 6))
    C_struc = C_struc.at[3, 3].set(-mTOT * g * rCG_TOT[2])
    C_struc = C_struc.at[4, 4].set(-mTOT * g * rCG_TOT[2])

    return dict(M_struc=M_struc, C_struc=C_struc, C_hydro=C_hydro,
                mass=mTOT, rCG=rCG_TOT, V=VTOT, AWP=AWP_TOT, zMeta=zMeta)


# =====================================================================
# traced node packing
# =====================================================================

def pack_nodes_t(tms):
    """Traced twin of geometry.pack_nodes: the same per-node static
    quantities, vectorized per member and concatenated; waterline-clip and
    submergence decisions follow the traced node z (value-only masks over
    the template-fixed node set, so shapes stay frozen)."""
    fields = {f.name: [] for f in dataclasses.fields(HydroNodes)}

    for tm in tms:
        tpl = tm["tpl"]
        ns = tpl.ns
        dl = tm["dls"]
        z = tm["r"][:, 2]

        fields["r"].append(tm["r"])
        fields["q"].append(jnp.broadcast_to(tm["q"], (ns, 3)))
        for key, v in (("qMat", tm["q"]), ("p1Mat", tm["p1"]),
                       ("p2Mat", tm["p2"])):
            fields[key].append(jnp.broadcast_to(
                v[:, None] * v[None, :], (ns, 3, 3)))

        if tpl.circular:
            d = tm["ds"]
            dr = tm["drs"]
            v = 0.25 * jnp.pi * d**2 * dl
            ve = jnp.pi / 12.0 * jnp.abs((d + dr) ** 3 - (d - dr) ** 3)
            ae = jnp.pi * d * dr
            aq = jnp.pi * d * dl
            ap1 = d * dl
            ap2 = d * dl
            ae_abs = jnp.abs(ae)
        else:
            d0, d1 = tm["ds"][:, 0], tm["ds"][:, 1]
            dr0, dr1 = tm["drs"][:, 0], tm["drs"][:, 1]
            v = d0 * d1 * dl
            dmean = jnp.mean(tm["ds"] + tm["drs"], axis=1)
            dmean2 = jnp.mean(tm["ds"] - tm["drs"], axis=1)
            ve = jnp.pi / 12.0 * (dmean**3 - dmean2**3)
            ae = (d0 + dr0) * (d1 + dr1) - (d0 - dr0) * (d1 - dr1)
            aq = 2 * (d0 + d0) * dl   # reference quirk kept
            ap1 = d0 * dl
            ap2 = d1 * dl
            ae_abs = jnp.abs(ae)

        # waterline clip mask from the traced geometry, matching
        # geometry.pack_nodes exactly: the scaled z decides which strip
        # straddles the waterline, not the template z (a draft scale
        # moves the z=0 crossing between strips; freezing the mask at
        # the template was the pinned draft-axis twin divergence).
        # Shape-safe: a where() over the same fixed node set.
        clip = (z < 0) & (z + 0.5 * dl > 0) & (dl > 0)
        v = jnp.where(clip,
                      v * (0.5 * dl - z) / jnp.where(dl == 0, 1.0, dl), v)
        fields["v_side"].append(v)
        fields["v_end"].append(ve)
        fields["a_end"].append(ae)
        fields["a_q"].append(aq)
        fields["a_p1"].append(ap1)
        fields["a_p2"].append(ap2)
        fields["a_end_abs"].append(ae_abs)

        st = tm["stations"]
        ls = tm["ls"]
        for key, coef in (("Ca_p1", tpl.Ca_p1), ("Ca_p2", tpl.Ca_p2),
                          ("Ca_End", tpl.Ca_End), ("Cd_q", tpl.Cd_q),
                          ("Cd_p1", tpl.Cd_p1), ("Cd_p2", tpl.Cd_p2),
                          ("Cd_End", tpl.Cd_End)):
            fields[key].append(jnp.interp(ls, st, jnp.asarray(coef)))

        sub = z < 0
        fields["submerged"].append(sub)
        fields["strip_mask"].append(sub & (not tpl.potMod))

    return HydroNodes(**{
        k: jnp.concatenate(vs) for k, vs in fields.items()
    })


# =====================================================================
# traced servo transfer terms + Dirlik DEL
# =====================================================================

def _servo_terms_t(w, J, kp_beta, ki_beta, kp_tau, ki_tau, k_float, Ng,
                   I_drivetrain, Zhub):
    """jnp twin of aero.servo_transfer_terms for one operating point.
    J : [10, 3] SI derivative matrix.  Returns (C, c_exc, a, b) [nw]."""
    dT_dU, dT_dOm, dT_dPi = J[0, 0], J[0, 1], J[0, 2]
    dQ_dU, dQ_dOm, dQ_dPi = J[1, 0], J[1, 1], J[1, 2]
    D = (
        I_drivetrain * w**2
        + (dQ_dOm + kp_beta * dQ_dPi - Ng * kp_tau) * 1j * w
        + ki_beta * dQ_dPi
        - Ng * ki_tau
    )
    C = 1j * w * (dQ_dU - k_float * dQ_dPi / Zhub) / D
    H_QT = ((dT_dOm + kp_beta * dT_dPi) * 1j * w + ki_beta * dT_dPi) / D
    c_exc = dT_dU - H_QT * dQ_dU
    resp = dT_dU - k_float * dT_dPi - H_QT * (dQ_dU - k_float * dQ_dPi)
    b_aero = jnp.real(resp)
    a_aero = jnp.real(resp / (1j * w))
    return C, c_exc, a_aero, b_aero


def dirlik_del_t(S, w, m_wohler, f_ref=1.0):
    """jnp twin of fatigue.dirlik_del (same closed form, jnp clips)."""
    m0 = jnp.trapezoid(S, w)
    m1 = jnp.trapezoid(w * S, w)
    m2 = jnp.trapezoid(w**2 * S, w)
    m4 = jnp.trapezoid(w**4 * S, w)
    nu_p = jnp.sqrt(m4 / m2) / (2.0 * jnp.pi)
    xm = (m1 / m0) * jnp.sqrt(m2 / m4)
    a2 = jnp.clip(m2 / jnp.sqrt(m0 * m4), None, 1.0 - 1e-12)
    D1 = jnp.clip(2.0 * (xm - a2 * a2) / (1.0 + a2 * a2), 1e-12,
                  1.0 - 1e-12)
    R = jnp.clip((a2 - xm - D1 * D1) / (1.0 - a2 - D1 + D1 * D1), 1e-12,
                 1.0 - 1e-12)
    D2 = (1.0 - a2 - D1 + D1 * D1) / (1.0 - R)
    D3 = 1.0 - D1 - D2
    Q = jnp.clip(1.25 * (a2 - D3 - D2 * R) / D1, 1e-12, None)
    m_ = float(m_wohler)
    ESm = (2.0 * jnp.sqrt(m0)) ** m_ * (
        D1 * Q**m_ * math.gamma(1.0 + m_)
        + math.sqrt(2.0) ** m_ * math.gamma(1.0 + m_ / 2.0)
        * (D2 * R**m_ + D3)
    )
    return (nu_p / f_ref * ESm) ** (1.0 / m_)


# =====================================================================
# the response function builder
# =====================================================================

def build_design_response(base_design, metrics=METRIC_NAMES,
                          m_wohler=4.0, dynamics_factory=None,
                          mooring_fn=None):
    """Build the differentiable design-response function.

    Returns (f, theta0) where ``f(theta) -> dict`` of scalar metrics is a
    pure traceable function of the 4-parameter vector (see PARAM_NAMES)
    and ``theta0 = ones(4)`` reproduces the base design.  ``jax.jit(f)``
    and ``jax.jacfwd(f)`` both work; all math is f64 (run on CPU).

    ``dynamics_factory`` / ``mooring_fn`` are signature-compatible
    replacements for :func:`raft_tpu.model.make_case_dynamics` and
    :func:`raft_tpu.mooring.case_mooring`: the reverse-mode path
    (raft_tpu/grad/response.py) injects implicit-adjoint variants here
    so ``jax.grad(f)`` works end-to-end; the defaults keep this builder
    forward-mode-only (``jacfwd``) with bit-identical values.
    """
    model0 = Model(base_design, precision="float64", device="cpu")
    templates = process_members(base_design)
    turbine = base_design["turbine"]
    rho, g = model0.rho_water, model0.g
    w, k = model0.w, np.asarray(model0.k)
    nw = model0.nw
    dw = float(w[1] - w[0])

    cases = cases_as_dicts(base_design)
    spec, height, period, beta, wind = model0._case_arrays(cases)
    zeta = model0._zeta(spec, height, period)              # [nc, nw]
    # appended unit-amplitude wave-only case for the RAO metric
    zeta_all = np.concatenate([zeta, np.ones((1, nw))])
    beta_all = np.concatenate([beta, [0.0]])
    wind_all = np.concatenate([wind, [0.0]])
    nc = len(zeta_all)

    # Scope of the traced twin (what the declared-exact OM partials are
    # derivatives OF): Morison-only hydrodynamics (no native-BEM
    # coefficients), no ballast trim, and — enforced right below —
    # simple non-bridled moorings.  omdao._check_derivative_options
    # refuses the run_native_BEM / trim_ballast modeling options when
    # 'derivatives' is on for exactly this reason.
    ms = parse_mooring(base_design["mooring"], rho_water=rho, g=g)
    if ms.bridles is not None:
        raise NotImplementedError(
            "parametric design gradients support simple (non-bridled) "
            "moorings")
    mbl = min(
        float(lt.get("breaking_load", np.inf))
        for lt in base_design["mooring"]["line_types"]
    )

    # first-pass mean rotor loads at zero platform pitch (theta-independent)
    F_prp = np.asarray(model0.aero_case_means(cases, wind))      # [nc0, 6]
    F_prp = np.concatenate([F_prp, np.zeros((1, 6))])            # [nc, 6]

    rotor = model0.rotor
    aero_on = (rotor is not None and model0.aeroServoMod > 0
               and bool(np.any(wind_all > 0)))
    widx = [i for i in range(nc) if wind_all[i] > 0.0] if aero_on else []
    # operating-schedule constants per wind case
    if aero_on:
        Om_case = np.interp(wind_all, rotor.Uhub, rotor.Omega_rpm) \
            * np.pi / 30.0
        bpitch_case = np.deg2rad(
            np.interp(wind_all, rotor.Uhub, rotor.pitch_deg))
        yaw_case = np.array([
            np.deg2rad(float(cases[i].get("yaw_misalign", 0.0)))
            if i < len(cases) else 0.0 for i in range(nc)
        ])
        gains = rotor.case_gains(wind_all)                      # 4 x [nc]

    if dynamics_factory is None:
        dynamics_factory = make_case_dynamics
    if mooring_fn is None:
        mooring_fn = case_mooring
    one_case = dynamics_factory(
        w, k, model0.depth, rho, g, model0.XiStart, model0.nIter,
        np.float64, np.complex128,
    )
    E00 = np.zeros((1, 3, 3))
    E00[0, 0, 0] = 1.0
    P_hub = jnp.asarray(np.asarray(
        translate_matrix_3to6(E00, np.array([0.0, 0.0, model0.hHub])))[0])

    # tower-base constants (theta-independent: tower + RNA only)
    from raft_tpu.statics import compute_statics as _compute_statics_np
    st0 = _compute_statics_np(templates, turbine, rho, g)
    m_turbine = st0.mtower + model0.mRNA
    zCG_turbine = (st0.rCG_tow[2] * st0.mtower
                   + model0.hHub * model0.mRNA) / m_turbine
    zBase = templates[-1].rA[2]
    hArm = zCG_turbine - zBase
    from raft_tpu.statics import member_inertia as _member_inertia_np
    M_tower = _member_inertia_np(templates[-1])[0]
    ICG_turbine = (
        np.asarray(translate_matrix_6to6(
            M_tower, np.array([0.0, 0.0, -zCG_turbine])))[4, 4]
        + model0.mRNA * (model0.hHub - zCG_turbine) ** 2 + model0.IrRNA
    )

    moor_const = tuple(
        np.asarray(a, np.float64)
        for a in (ms.anchors, ms.rFair, ms.L, ms.EA, ms.w, ms.Wp, ms.cb)
    )
    w_j = jnp.asarray(w)
    zeta_j = jnp.asarray(zeta_all)
    beta_j = jnp.asarray(beta_all)

    def rotor_terms(i, ptfm_pitch):
        """Traced rotor loads/derivatives + servo terms for wind case i at
        platform pitch ``ptfm_pitch`` (the second-pass evaluation).
        Returns (F_aero0_prp[6], a_w[nw], b_w[nw], C[nw], c_exc)."""
        U = float(wind_all[i])
        Om = float(Om_case[i])
        bp = float(bpitch_case[i])
        tilt0 = float(np.deg2rad(rotor.shaft_tilt))
        yaw = float(yaw_case[i])
        geom0 = dict(rotor.geom)

        from raft_tpu.aero import rotor_evaluate

        def loads(x):
            # x = [U, Omega, blade pitch, tilt]
            gd = dict(geom0)
            gd["tilt"] = x[3]
            gd["yaw"] = yaw
            out = rotor_evaluate(x[0], x[1], x[2], gd, rotor.polars,
                                 rotor.env)
            return jnp.stack([out["T"], out["Q"], out["P"], out["CP"],
                              out["CT"], out["CQ"], out["Y"], out["Z"],
                              out["My"], out["Mz"]])

        x = jnp.stack([jnp.asarray(U), jnp.asarray(Om), jnp.asarray(bp),
                       tilt0 + ptfm_pitch])
        vals = loads(x)
        J4 = jax.jacfwd(loads)(x)            # [10, 4]
        J = J4[:, :3]
        # hub loads with the reference ordering quirk [T, Y, Z, My, Q, Mz]
        F_hub = jnp.stack([vals[0], vals[6], vals[7], vals[8], vals[1],
                           vals[9]])
        F0 = transform_force(
            F_hub, offset=jnp.asarray([0.0, 0.0, model0.hHub]))
        if model0.aeroServoMod == 1:
            b_w = jnp.broadcast_to(J[0, 0], (nw,))
            a_w = jnp.zeros(nw)
            C = jnp.zeros(nw, jnp.complex128)
            c_exc = jnp.zeros(())
        else:
            kp_beta, ki_beta, kp_tau, ki_tau = (float(gg[i])
                                                for gg in gains)
            C, c_exc, a_w, b_w = _servo_terms_t(
                w_j, J, kp_beta, ki_beta, kp_tau, ki_tau,
                rotor.k_float, rotor.Ng, rotor.I_drivetrain, rotor.Zhub)
        return F0, a_w, b_w, C, c_exc

    def f(theta):
        theta = jnp.asarray(theta, jnp.float64)
        tms = make_traced_members(templates, theta)
        stat = compute_statics_t(tms, turbine, rho, g)
        nodes = pack_nodes_t(tms)
        A_mor = added_mass_morison(nodes, rho)

        arrs = list(jnp.asarray(a) for a in moor_const)
        arrs[2] = arrs[2] * theta[3]                    # line length
        rM = jnp.stack([jnp.zeros(()), jnp.zeros(()), stat["zMeta"]])

        def moor_one(f6):
            return mooring_fn(
                f6, stat["mass"], stat["V"], stat["rCG"], rM,
                stat["AWP"], *arrs, bridles=None, rho=rho, g=g,
                yawstiff=model0.yawstiff,
            )
        r6, C_moor, F_moor, T_moor, J_moor, _resid = jax.vmap(moor_one)(
            jnp.asarray(F_prp))

        # second-pass aero at each wind case's mean platform pitch
        a_hub = [jnp.zeros(nw)] * nc
        b_hub = [jnp.zeros(nw)] * nc
        F_aero2 = [jnp.zeros(6)] * nc
        for i in widx:
            F0_i, a_w, b_w, _C, _ce = rotor_terms(i, r6[i, 4])
            a_hub[i] = a_w
            b_hub[i] = b_w
            F_aero2[i] = F0_i
        a_hub = jnp.stack(a_hub)
        b_hub = jnp.stack(b_hub)
        F_aero2 = jnp.stack(F_aero2)

        M0 = stat["M_struc"] + A_mor
        C_lin = (stat["C_struc"] + stat["C_hydro"])[None] + C_moor
        Fz = jnp.zeros((nw, 6))

        def dyn_one(z, b, C, a1, b1):
            M_lin = M0[None] + a1[:, None, None] * P_hub
            B_lin = b1[:, None, None] * P_hub
            return one_case(nodes, z, b, C, M_lin, B_lin, Fz, Fz)

        xr, xi, _rep = jax.vmap(dyn_one)(
            zeta_j, beta_j, C_lin, a_hub, b_hub)   # [nc, 6, nw]
        Xi2 = xr**2 + xi**2
        std = jnp.sqrt(jnp.sum(Xi2, axis=-1) * dw)              # [nc, 6]

        out = {}
        # case aggregates over the design's OWN cases only ([:nc0] — the
        # appended unit-spectrum case exists solely for the RAO metric),
        # matching the omdao aggregates (omdao.py:728-741)
        nc0 = nc - 1
        pitch_max = jnp.rad2deg(r6[:nc0, 4] + 3.0 * std[:nc0, 4])
        out["pitch_max_deg"] = jnp.max(pitch_max)
        surge_max = r6[:nc0, 0] + 3.0 * std[:nc0, 0]
        sway_max = r6[:nc0, 1] + 3.0 * std[:nc0, 2]     # reference quirk
        out["offset_max"] = jnp.max(jnp.hypot(surge_max, sway_max))
        # RAO of the appended unit case: |Xi_pitch| in deg/m
        out["rao_pitch_peak"] = jnp.rad2deg(
            jnp.max(jnp.sqrt(Xi2[-1, 4, :])))
        out["moor_util"] = jnp.max(T_moor[:nc0]) / mbl
        out["mass"] = stat["mass"]
        out["displacement"] = rho * stat["V"]

        # tower-base moment: dynamic spectrum (DEL + 3-sigma max) per
        # case, aggregated like the omdao max_tower_base / the fatigue
        # channel (model.py:755-792, fatigue.py)
        dels, maxes = [], []
        for i in range(nc0):
            Xi_c = xr[i] + 1j * xi[i]
            aCG = -(w_j**2) * (Xi_c[0] + zCG_turbine * Xi_c[4])
            M_I = -m_turbine * aCG * hArm - ICG_turbine * (
                -(w_j**2) * Xi_c[4])
            M_w = m_turbine * g * hArm * Xi_c[4]
            M_X = (
                -(-(w_j**2) * a_hub[i] + 1j * w_j * b_hub[i])
                * (model0.hHub - zBase) ** 2 * Xi_c[4]
            )
            S_m = jnp.abs(M_I + M_w + M_X) ** 2
            dels.append(dirlik_del_t(S_m, w_j, m_wohler))
            M_avg = m_turbine * g * hArm * jnp.sin(r6[i, 4]) + \
                transform_force(
                    F_aero2[i],
                    offset=jnp.asarray([0.0, 0.0, -hArm]))[4]
            M_std = jnp.sqrt(jnp.sum(S_m) * dw)
            maxes.append(M_avg + 3.0 * M_std)
        out["Mbase_DEL"] = jnp.max(jnp.stack(dels))
        out["Mbase_max"] = jnp.max(jnp.stack(maxes))
        return {k_: out[k_] for k_ in metrics}

    return f, jnp.ones(4)


def design_gradients(base_design, theta=None, metrics=METRIC_NAMES):
    """Convenience: metrics and their exact forward-mode jacobian at
    ``theta`` (default: the base design).  Returns (values dict,
    jacobian dict mapping metric -> {param: d metric / d scale})."""
    f, theta0 = build_design_response(base_design, metrics=metrics)
    if theta is not None:
        theta0 = jnp.asarray(theta, jnp.float64)
    # CPU-committed: the pipeline is f64 (statics cancellations), which
    # the TPU backend does not provide — placement follows the operand
    theta0 = jax.device_put(theta0, jax.devices("cpu")[0])
    vals = jax.jit(f)(theta0)
    jac = jax.jit(jax.jacfwd(f))(theta0)
    return (
        {k: float(v) for k, v in vals.items()},
        {k: {p: float(jac[k][i]) for i, p in enumerate(PARAM_NAMES)}
         for k in vals},
    )
