"""Axisymmetric panel mesher for potential-flow (BEM) members.

Meshes each ``potMod`` member into quadrilateral/triangular surface panels for
the native radiation/diffraction solver (raft_tpu/bem_solver.py) and for
HAMS/WAMIT interop (.pnl / .gdf writers).  Capability-equivalent to the
reference mesher (reference raft/member2pnl.py:73-275): adaptive subdivision
of the member generator curve by panel-size targets, azimuthal refinement in
powers of two with 2:1 transition rings, end-cap fill, member pose rotation,
and waterplane clipping — but restructured: panels are generated as vectorized
rings per profile segment, node dedup is O(n) hashing (the reference is O(n²)
list scanning), and panel geometry (centroids/areas/normals) is computed for
direct consumption by the BEM solver rather than only file output.

A C++ core for the data-dependent adaptive loops lives in
raft_tpu/native/mesher.cpp (SURVEY.md §2.3: the one XLA-hostile host-side
component); this module transparently uses it when the shared library is
available and falls back to the pure-Python implementation below.
"""

import os

import numpy as np


# ---------------------------------------------------------------- profile ---

def profile_points(stations, radii, dz_max=0.0, da_max=0.0, end_a=True,
                   end_b=True):
    """Discretize the member generator curve (radius vs axial coordinate).

    Subdivision rule (reference member2pnl.py:115-165): vertical segments are
    split by ``dz_max``; horizontal (flat) segments by ``0.6*da_max``; sloped
    segments by a slope-angle-weighted blend of the two.  End caps are filled
    with concentric rings down to r=0.

    Returns (r, z) profile arrays ordered from end A to end B.
    """
    stations = np.asarray(stations, float)
    radii = np.asarray(radii, float)
    if dz_max <= 0.0:
        dz_max = float(stations[-1]) / 20.0
    if da_max <= 0.0:
        da_max = float(np.max(radii)) / 8.0

    r_rp = [float(radii[0])]
    z_rp = [float(stations[0])]
    for i in range(1, len(radii)):
        dr = float(radii[i] - radii[i - 1])
        dz = float(stations[i] - stations[i - 1])
        hyp = np.hypot(dr, dz)
        if hyp == 0.0:
            continue
        if dr == 0.0:
            target = dz_max
        elif dz == 0.0:
            target = 0.6 * da_max
        else:
            # blend by the segment's inclination angle
            a_r = np.arctan(abs(dr / dz)) * 2.0 / np.pi
            a_z = np.arctan(abs(dz / dr)) * 2.0 / np.pi
            target = a_r * 0.6 * da_max + a_z * dz_max
        n = max(1, int(np.ceil(hyp / target)))
        for j in range(1, n + 1):
            frac = j / n
            r_rp.append(float(radii[i - 1]) + frac * dr)
            z_rp.append(float(stations[i - 1]) + frac * dz)

    # end-cap rings: concentric circles shrinking to the axis
    if end_b and radii[-1] > 0.0:
        n = max(1, int(np.ceil(radii[-1] / (0.6 * da_max))))
        for j in range(1, n + 1):
            r_rp.append(float(radii[-1]) * (1.0 - j / n))
            z_rp.append(float(stations[-1]))
    if end_a and radii[0] > 0.0:
        n = max(1, int(np.ceil(radii[0] / (0.6 * da_max))))
        head_r = [float(radii[0]) * (1.0 - j / n) for j in range(n, 0, -1)]
        head_z = [float(stations[0])] * n
        r_rp = head_r + r_rp
        z_rp = head_z + z_rp
    return np.array(r_rp), np.array(z_rp)


def _ring_quads(r1, z1, r2, z2, naz):
    """One ring of naz quads between profile points (r1,z1)-(r2,z2),
    vectorized over azimuth.  Winding matches the reference's so that panel
    normals point out of the body (reference member2pnl.py:233-241)."""
    th = np.linspace(0.0, 2.0 * np.pi, naz + 1)
    c, s = np.cos(th), np.sin(th)
    quads = np.empty((naz, 4, 3))
    quads[:, 0, 0] = r1 * c[1:]
    quads[:, 0, 1] = r1 * s[1:]
    quads[:, 0, 2] = z1
    quads[:, 1, 0] = r2 * c[1:]
    quads[:, 1, 1] = r2 * s[1:]
    quads[:, 1, 2] = z2
    quads[:, 2, 0] = r2 * c[:-1]
    quads[:, 2, 1] = r2 * s[:-1]
    quads[:, 2, 2] = z2
    quads[:, 3, 0] = r1 * c[:-1]
    quads[:, 3, 1] = r1 * s[:-1]
    quads[:, 3, 2] = z1
    return quads


def _transition_ring(r1, z1, r2, z2, naz, refine_bottom):
    """2:1 transition ring: naz/2 coarse cells each split into two panels.

    ``refine_bottom``: the (r2,z2) edge is the finer one (reference's
    'increase azimuthal discretization' branch, member2pnl.py:194-210);
    otherwise the (r1,z1) edge is finer (member2pnl.py:213-229).
    """
    panels = []
    for ia in range(1, naz // 2 + 1):
        th1 = (ia - 1.0) * 2.0 * np.pi / naz * 2.0
        th2 = (ia - 0.5) * 2.0 * np.pi / naz * 2.0
        th3 = (ia - 0.0) * 2.0 * np.pi / naz * 2.0
        c1_, s1_ = np.cos(th1), np.sin(th1)
        c2_, s2_ = np.cos(th2), np.sin(th2)
        c3_, s3_ = np.cos(th3), np.sin(th3)
        if refine_bottom:
            mid = ((r1 * c1_ + r1 * c3_) / 2.0, (r1 * s1_ + r1 * s3_) / 2.0)
            panels.append([[mid[0], mid[1], z1],
                           [r2 * c2_, r2 * s2_, z2],
                           [r2 * c1_, r2 * s1_, z2],
                           [r1 * c1_, r1 * s1_, z1]])
            panels.append([[r1 * c3_, r1 * s3_, z1],
                           [r2 * c3_, r2 * s3_, z2],
                           [r2 * c2_, r2 * s2_, z2],
                           [mid[0], mid[1], z1]])
        else:
            mid = ((r2 * c1_ + r2 * c3_) / 2.0, (r2 * s1_ + r2 * s3_) / 2.0)
            panels.append([[r1 * c2_, r1 * s2_, z1],
                           [mid[0], mid[1], z2],
                           [r2 * c1_, r2 * s1_, z2],
                           [r1 * c1_, r1 * s1_, z1]])
            panels.append([[r1 * c3_, r1 * s3_, z1],
                           [r2 * c3_, r2 * s3_, z2],
                           [mid[0], mid[1], z2],
                           [r1 * c2_, r1 * s2_, z1]])
    return np.array(panels)


def revolve_profile(r_rp, z_rp, da_max):
    """Revolve the profile into panels with adaptive azimuthal refinement.

    The azimuth count follows the reference's hysteresis state machine
    (member2pnl.py:188-191): starting from 8, double while both edge widths
    are >= da_max/2, halve while both are < da_max/2; mixed edges emit a 2:1
    transition ring.  Returns [npan, 4, 3] panel vertices (local frame).
    """
    panels = []
    naz = 8
    for i in range(len(z_rp) - 1):
        r1, z1 = r_rp[i], z_rp[i]
        r2, z2 = r_rp[i + 1], z_rp[i + 1]
        while (r1 * 2 * np.pi / naz >= da_max / 2
               and r2 * 2 * np.pi / naz >= da_max / 2):
            naz *= 2
        while (naz > 2 and r1 * 2 * np.pi / naz < da_max / 2
               and r2 * 2 * np.pi / naz < da_max / 2):
            naz //= 2
        w1 = r1 * 2 * np.pi / naz
        w2 = r2 * 2 * np.pi / naz
        if w1 < da_max / 2 <= w2:
            panels.append(_transition_ring(r1, z1, r2, z2, naz,
                                           refine_bottom=True))
        elif w2 < da_max / 2 <= w1:
            panels.append(_transition_ring(r1, z1, r2, z2, naz,
                                           refine_bottom=False))
        else:
            panels.append(_ring_quads(r1, z1, r2, z2, naz))
    return np.concatenate(panels, axis=0) if panels else np.zeros((0, 4, 3))


def member_pose_matrix(rA, rB, gamma=0.0):
    """Z1Y2Z3 member pose rotation (reference member2pnl.py:245-260)."""
    rAB = np.asarray(rB, float) - np.asarray(rA, float)
    beta = np.arctan2(rAB[1], rAB[0])
    phi = np.arctan2(np.hypot(rAB[0], rAB[1]), rAB[2])
    s1, c1 = np.sin(beta), np.cos(beta)
    s2, c2 = np.sin(phi), np.cos(phi)
    s3, c3 = np.sin(np.deg2rad(gamma)), np.cos(np.deg2rad(gamma))
    return np.array([
        [c1 * c2 * c3 - s1 * s3, -c3 * s1 - c1 * c2 * s3, c1 * s2],
        [c1 * s3 + c2 * c3 * s1, c1 * c3 - c2 * s1 * s3, s1 * s2],
        [-c3 * s2, s2 * s3, c2],
    ])


def waterline_station(stations, vals, rA, rB):
    """Insert an interpolated profile station EXACTLY where the member
    axis crosses the free surface (z = 0), so revolved rings align with
    the waterline on every refinement.

    Without it, the clip leaves a sliver row whose height is the accident
    of where the dz_max grid lands relative to z = 0 — measured on the
    VolturnUS full hull as a ±2.4% surge/heave added-mass scatter between
    refinements while pitch/roll converged cleanly (docs/parity.md study;
    VERDICT r4 #3).  With an aligned ring the sub-surface row heights are
    draft/n for every n and the scatter collapses to ordinary p≈2 mesh
    convergence.

    Returns (stations, vals) unchanged when the axis does not cross, or
    with one inserted row (``vals`` interpolated per column) when it does.
    """
    rA = np.asarray(rA, float)
    rB = np.asarray(rB, float)
    stations = np.asarray(stations, float)
    vals = np.asarray(vals, float)
    dzg = rB[2] - rA[2]
    if dzg == 0.0:
        return stations, vals
    t = -rA[2] / dzg                      # axis fraction where z = 0
    if not 0.0 < t < 1.0:
        return stations, vals
    span = stations[-1] - stations[0]
    s_wl = stations[0] + t * span
    if np.min(np.abs(stations - s_wl)) < 1e-9 * max(abs(span), 1.0):
        return stations, vals
    i = int(np.searchsorted(stations, s_wl))
    v_wl = vals[i - 1] + (vals[i] - vals[i - 1]) * (
        (s_wl - stations[i - 1]) / (stations[i] - stations[i - 1]))
    return (np.insert(stations, i, s_wl),
            np.insert(vals, i, v_wl, axis=0))


def _graded_waterline_stations(stations, vals, rA, rB, dz_max):
    """Waterline-aligned AND surface-graded profile stations.

    Inserts a station exactly at the z = 0 crossing (see
    :func:`waterline_station`) and replaces the uniform subdivision of
    the submerged segment adjacent to it with sine-clustered stations —
    spacing shrinks quadratically toward the free surface (finest row
    ~ L*(pi/2n)^2/2 where n = ceil(L/dz_max)), where the velocity
    potential varies fastest.  Both effects remove the
    refinement-to-refinement layout accidents of clip-based waterline
    handling: every mesh in a refinement sequence has the same smooth
    row-height profile, just scaled (VERDICT r4 #3; the unaligned clip
    left a sliver row whose height was the accident of where the dz grid
    landed, measured as a ±2.4% surge/heave scatter on the VolturnUS
    hull while pitch/roll converged cleanly).
    """
    st, vv = waterline_station(stations, vals, rA, rB)
    if len(st) == len(np.asarray(stations)):          # no crossing
        return st, vv
    rA = np.asarray(rA, float)
    rB = np.asarray(rB, float)
    # index of the inserted waterline station
    span = st[-1] - st[0]
    t = -rA[2] / (rB[2] - rA[2])
    s_wl = st[0] + t * span
    i = int(np.argmin(np.abs(st - s_wl)))
    # submerged side: stations where global z < 0, i.e. toward rA if
    # rA[2] < 0 else toward rB
    below_first = rA[2] < 0.0
    j = i - 1 if below_first else i + 1
    if j < 0 or j >= len(st):
        return st, vv
    s_edge = st[j]
    L = abs(s_wl - s_edge)
    if dz_max <= 0.0:
        dz_max = span / 20.0
    n = max(1, int(np.ceil(L / dz_max)))
    if n < 2:
        return st, vv
    # stations spanning (s_wl, s_edge) clustered quadratically at s_wl
    k = np.arange(1, n)
    s_new = np.sort(
        s_wl + (s_edge - s_wl) * (1.0 - np.cos(k * np.pi / (2 * n))))
    lo, hi = (j, i) if below_first else (i, j)
    f = (s_new - st[lo]) / (st[hi] - st[lo])
    if vv.ndim == 2:
        v_new = vv[lo][None, :] + (vv[hi] - vv[lo])[None, :] * f[:, None]
    else:
        v_new = vv[lo] + (vv[hi] - vv[lo]) * f
    return np.insert(st, lo + 1, s_new), np.insert(vv, lo + 1, v_new,
                                                   axis=0)


def mesh_member(stations, diameters, rA, rB, dz_max=0.0, da_max=0.0,
                align_waterline=True):
    """Mesh one axisymmetric member: profile → revolve → pose transform.

    ``stations`` are axial coordinates from end A; ``rA``/``rB`` global end
    positions.  Returns [npan, 4, 3] global-frame panel vertices (unclipped).
    ``align_waterline`` inserts a profile ring exactly at z = 0 (see
    :func:`waterline_station`; the reference mesher has no equivalent and
    relies on the clip, reference member2pnl.py:23-30).
    """
    rA = np.asarray(rA, float)
    rB = np.asarray(rB, float)
    stations = np.asarray(stations, float)
    diameters = np.asarray(diameters, float)
    if align_waterline:
        stations, diameters = _graded_waterline_stations(
            stations, diameters, rA, rB, dz_max)
    radii = 0.5 * diameters
    # profile z measured from end A along the member axis
    r_rp, z_rp = profile_points(stations - stations[0], radii, dz_max, da_max)
    panels = _native_or_python_revolve(r_rp, z_rp, da_max)
    R = member_pose_matrix(rA, rB)
    return panels @ R.T + rA[None, None, :]


def clip_waterplane(panels, z_max=0.0):
    """Drop panels fully above the waterline and clamp remaining vertices to
    the free surface (reference member2pnl.py:23-30).  Panels squashed to
    zero area by the clamp are also dropped."""
    if len(panels) == 0:
        return panels
    keep = ~np.all(panels[:, :, 2] > z_max, axis=1)
    out = panels[keep].copy()
    out[:, :, 2] = np.minimum(out[:, :, 2], z_max)
    areas = panel_geometry(out)[2]
    return out[areas > 1e-10]


def dedupe_nodes(panels, decimals=6):
    """Merge coincident vertices: returns (nodes [N,3], conn [npan,4] int).

    Panels with a repeated vertex (clip-degenerate quads) become triangles:
    the repeated index appears once and the 4th entry is -1
    (the reference detects these the same way, member2pnl.py:49-56).
    """
    nodes = []
    index = {}
    conn = np.full((len(panels), 4), -1, dtype=int)
    for ip, quad in enumerate(panels):
        ids = []
        for v in quad:
            key = tuple(np.round(v, decimals) + 0.0)
            j = index.get(key)
            if j is None:
                j = len(nodes)
                index[key] = j
                nodes.append(v)
            if j not in ids:
                ids.append(j)
        conn[ip, : len(ids)] = ids
    return np.array(nodes), conn


def panel_geometry(panels):
    """Centroids, normals, areas of quad/tri panels [npan,4,3].

    Each quad is split into two triangles; the panel normal is the
    area-weighted triangle normal (robust for clip-degenerate quads), the
    centroid the area-weighted triangle centroid.  Returns
    (centroids [n,3], normals [n,3], areas [n]).
    """
    p = np.asarray(panels, float)
    a, b, c, d = p[:, 0], p[:, 1], p[:, 2], p[:, 3]
    n1 = 0.5 * np.cross(b - a, c - a)
    n2 = 0.5 * np.cross(c - a, d - a)
    c1 = (a + b + c) / 3.0
    c2 = (a + c + d) / 3.0
    A1 = np.linalg.norm(n1, axis=1)
    A2 = np.linalg.norm(n2, axis=1)
    areas = A1 + A2
    nvec = n1 + n2
    norm = np.linalg.norm(nvec, axis=1)
    normals = nvec / np.where(norm > 0, norm, 1.0)[:, None]
    w = np.where(areas > 0, areas, 1.0)
    centroids = (c1 * A1[:, None] + c2 * A2[:, None]) / w[:, None]
    return centroids, normals, areas


def mesh_volume(panels):
    """Signed enclosed volume by the divergence theorem (positive when panel
    normals point out of the body) — used to sanity-check orientation."""
    cen, nrm, areas = panel_geometry(panels)
    return float(np.sum(areas * np.einsum("ij,ij->i", cen, nrm)) / 3.0)


# -------------------------------------------------------------- file I/O ----

def write_pnl(path, nodes, conn):
    """Write a HAMS-format HullMesh .pnl file (reference member2pnl.py:279-307
    format: header, 1-based node table, panel connectivity)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write("    --------------Hull Mesh File---------------\n\n")
        f.write("    # Number of Panels, Nodes, X-Symmetry and Y-Symmetry\n")
        f.write(f"         {len(conn)}         {len(nodes)}         0         0\n\n")
        f.write("    #Start Definition of Node Coordinates     "
                "! node_number   x   y   z\n")
        for i, nd in enumerate(nodes):
            f.write(f"{i+1:>5}{nd[0]:18.6f}{nd[1]:18.6f}{nd[2]:18.6f}\n")
        f.write("   #End Definition of Node Coordinates\n\n")
        f.write("   #Start Definition of Node Relations   ! panel_number  "
                "number_of_vertices   Vertex1_ID   Vertex2_ID   Vertex3_ID   "
                "(Vertex4_ID)\n")
        for i, row in enumerate(conn):
            ids = [int(j) + 1 for j in row if j >= 0]
            f.write("".join(f"{v:>8}" for v in [i + 1, len(ids)] + ids) + "\n")
        f.write("   #End Definition of Node Relations\n\n")
        f.write("    --------------End Hull Mesh File---------------\n")


def read_pnl(path):
    """Read a HAMS .pnl file back into (nodes [N,3], conn [npan,4])."""
    nodes, conn = [], []
    section = None
    with open(path) as f:
        for line in f:
            s = line.strip()
            if s.startswith("#Start Definition of Node Coordinates"):
                section = "nodes"
                continue
            if s.startswith("#Start Definition of Node Relations"):
                section = "panels"
                continue
            if s.startswith("#End"):
                section = None
                continue
            if not s or s.startswith("-") or s.startswith("#"):
                continue
            parts = s.split()
            if section == "nodes" and len(parts) >= 4:
                nodes.append([float(parts[1]), float(parts[2]), float(parts[3])])
            elif section == "panels" and len(parts) >= 5:
                nv = int(parts[1])
                ids = [int(p) - 1 for p in parts[2:2 + nv]]
                conn.append(ids + [-1] * (4 - nv))
    return np.array(nodes), np.array(conn, dtype=int)


def conn_to_panels(nodes, conn):
    """Expand (nodes, conn) back to [npan,4,3] vertex panels (triangles
    repeat their last vertex, making a degenerate quad)."""
    out = np.empty((len(conn), 4, 3))
    for i, row in enumerate(conn):
        ids = [j for j in row if j >= 0]
        while len(ids) < 4:
            ids.append(ids[-1])
        out[i] = nodes[ids]
    return out


def write_gdf(path, panels, ulen=1.0, g=9.8):
    """Write panels to a WAMIT .gdf file (reference member2pnl.py:501-529)."""
    verts = np.asarray(panels, float).reshape(-1, 3)
    with open(path, "w") as f:
        f.write("gdf mesh written by raft_tpu\n")
        f.write(f"{ulen}   {g}\n")
        f.write("0, 0\n")
        f.write(f"{len(verts) // 4}\n")
        for v in verts:
            f.write(f"{v[0]:>12.6f} {v[1]:>12.6f} {v[2]:>12.6f}\n")


def read_gdf(path):
    """Read a WAMIT .gdf file into [npan,4,3] panels."""
    with open(path) as f:
        lines = f.readlines()
    npan = int(lines[3].split()[0])
    vals = []
    for line in lines[4:]:
        parts = line.split()
        if len(parts) >= 3:
            vals.append([float(parts[0]), float(parts[1]), float(parts[2])])
    verts = np.array(vals[: npan * 4])
    return verts.reshape(npan, 4, 3)


def _grid_quads(P00, P10, P01, P11, n_u, n_v):
    """Panel a bilinear patch defined by its 4 corners into n_u x n_v quads.
    Winding (u x v right-handed) chosen by the caller via corner order."""
    u = np.linspace(0.0, 1.0, n_u + 1)
    v = np.linspace(0.0, 1.0, n_v + 1)
    U, V = np.meshgrid(u, v, indexing="ij")
    pts = ((1 - U)[:, :, None] * (1 - V)[:, :, None] * P00
           + U[:, :, None] * (1 - V)[:, :, None] * P10
           + (1 - U)[:, :, None] * V[:, :, None] * P01
           + U[:, :, None] * V[:, :, None] * P11)
    quads = np.empty((n_u, n_v, 4, 3))
    quads[:, :, 0] = pts[:-1, :-1]
    quads[:, :, 1] = pts[1:, :-1]
    quads[:, :, 2] = pts[1:, 1:]
    quads[:, :, 3] = pts[:-1, 1:]
    return quads.reshape(-1, 4, 3)


def mesh_rect_member(stations, side_lengths, rA, rB, dz_max=0.0, da_max=0.0,
                     gamma=0.0, align_waterline=True):
    """Mesh a rectangular member as a (tapered) box: four side faces plus end
    caps.  ``side_lengths`` is [n,2] per station.  This extends the reference
    mesher, which only handles axisymmetric members (member2pnl.py:73).
    Returns [npan,4,3] global-frame panels with outward normals."""
    stations = np.asarray(stations, float) - float(np.asarray(stations)[0])
    sl = np.asarray(side_lengths, float).reshape(len(stations), 2)
    if align_waterline:
        stations, sl = _graded_waterline_stations(
            stations, sl, rA, rB, dz_max)
        sl = sl.reshape(len(stations), 2)
    if dz_max <= 0.0:
        dz_max = float(stations[-1]) / 20.0
    if da_max <= 0.0:
        da_max = float(np.max(sl)) / 8.0

    # subdivide the axial profile (same rule as circular: straight segments
    # split by dz_max)
    zs = [0.0]
    sls = [sl[0]]
    for i in range(1, len(stations)):
        dz = stations[i] - stations[i - 1]
        if dz <= 0.0:
            continue
        n = max(1, int(np.ceil(dz / dz_max)))
        for j in range(1, n + 1):
            f = j / n
            zs.append(stations[i - 1] + f * dz)
            sls.append(sl[i - 1] + f * (sl[i] - sl[i - 1]))
    zs = np.array(zs)
    sls = np.array(sls)

    def corners(i):
        a, b = 0.5 * sls[i]
        z = zs[i]
        return np.array([[+a, +b, z], [-a, +b, z], [-a, -b, z], [+a, -b, z]])

    chunks = []
    n_a = max(1, int(np.ceil(float(np.max(sls[:, 0])) / da_max)))
    n_b = max(1, int(np.ceil(float(np.max(sls[:, 1])) / da_max)))
    # edges 0/2 run corner->corner along the x side (length sl[:,0]),
    # edges 1/3 along the y side (length sl[:,1])
    n_per = [n_a, n_b, n_a, n_b]  # panels along each perimeter edge
    for i in range(len(zs) - 1):
        c1 = corners(i)
        c2 = corners(i + 1)
        for e in range(4):
            j = (e + 1) % 4
            # outward-facing side patch between axial rings i and i+1
            chunks.append(_grid_quads(c1[e], c1[j], c2[e], c2[j],
                                      n_per[e], 1))
    # end caps (normals along -z at end A, +z at end B in local frame)
    cA = corners(0)  # u: c0->c3 runs along the y side, v along the x side
    chunks.append(_grid_quads(cA[0], cA[3], cA[1], cA[2], n_b, n_a))
    cB = corners(len(zs) - 1)
    chunks.append(_grid_quads(cB[0], cB[1], cB[3], cB[2], n_a, n_b))

    panels = np.concatenate(chunks, axis=0)
    R = member_pose_matrix(rA, rB, gamma=gamma)
    panels = panels @ R.T + np.asarray(rA, float)[None, None, :]
    # ensure outward orientation (flip all if the enclosed volume is negative)
    if mesh_volume(panels) < 0:
        panels = panels[:, ::-1, :]
    return panels


# -------------------------------------------------- platform-level helper ---

def mesh_platform(members, dz_max=0.0, da_max=0.0, clip=True):
    """Mesh every potential-flow member of a platform into one panel set.

    ``members`` is the processed Member list (raft_tpu.geometry); only members
    with ``potMod=True`` are meshed (reference raft_fowt.py:349-357).  Returns
    [npan,4,3] waterplane-clipped panels for the wetted hull.
    """
    chunks = []
    for mem in members:
        if not getattr(mem, "potMod", False):
            continue
        if mem.circular:
            chunks.append(
                mesh_member(mem.stations, mem.d, mem.rA, mem.rB, dz_max, da_max)
            )
        else:
            # rectangular members: box mesh (beyond the reference mesher,
            # which is axisymmetric-only, member2pnl.py:73)
            chunks.append(
                mesh_rect_member(mem.stations, mem.sl, mem.rA, mem.rB,
                                 dz_max, da_max, gamma=mem.gamma)
            )
    if not chunks:
        return np.zeros((0, 4, 3))
    panels = np.concatenate(chunks, axis=0)
    return clip_waterplane(panels) if clip else panels


# ------------------------------------------------------------ native core ---

_native = None
_native_tried = False


def _load_native():
    """Load the C++ mesher core (raft_tpu/native/libraft_mesher.so) lazily;
    build it with `make -C raft_tpu/native` if missing.  Returns None when
    unavailable — callers fall back to the Python implementation."""
    global _native, _native_tried
    if _native_tried:
        return _native
    _native_tried = True
    try:
        import ctypes

        here = os.path.dirname(os.path.abspath(__file__))
        lib_path = os.path.join(here, "native", "libraft_mesher.so")
        if not os.path.exists(lib_path):
            return None
        lib = ctypes.CDLL(lib_path)
        lib.raft_revolve_profile.restype = ctypes.c_int
        lib.raft_revolve_profile.argtypes = [
            ctypes.POINTER(ctypes.c_double),  # r profile
            ctypes.POINTER(ctypes.c_double),  # z profile
            ctypes.c_int,                     # n profile points
            ctypes.c_double,                  # da_max
            ctypes.POINTER(ctypes.c_double),  # out vertices (cap*12)
            ctypes.c_int,                     # capacity (panels)
        ]
        _native = lib
    except OSError:
        _native = None
    return _native


def _native_or_python_revolve(r_rp, z_rp, da_max):
    lib = _load_native()
    if lib is None:
        return revolve_profile(r_rp, z_rp, da_max)
    import ctypes

    r = np.ascontiguousarray(r_rp, dtype=np.float64)
    z = np.ascontiguousarray(z_rp, dtype=np.float64)
    cap = 65536
    out = np.empty((cap, 4, 3), dtype=np.float64)
    n = lib.raft_revolve_profile(
        r.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        z.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(r), float(da_max),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), cap,
    )
    if n < 0:  # capacity exceeded — fall back
        return revolve_profile(r_rp, z_rp, da_max)
    return out[:n]


def lid_panels_from_mesh(panels, nr=2, z_tol=1e-6):
    """Interior free-surface ("lid") panels for irregular-frequency removal:
    extract the waterline loop(s) of a clipped hull mesh and fill each with
    ``nr`` concentric rings of quads collapsing to the loop centroid.

    This is the geometric half of the extended-boundary-condition method
    (the reference's external solver exposes it as HAMS
    If_remove_irr_freq, consumed at reference raft/raft_fowt.py:381): the
    interior waterplane is panelled AT z = 0 and joins the body surface as
    a rigid extension (v_n = 0), displacing the interior-problem
    eigenfrequencies out of the wave band.  Works for any surface-piercing
    waterline whose loop is star-shaped about its centroid (circular and
    rectangular columns included).

    Keep ``nr`` SMALL: the lid only needs to represent the interior
    waterplane approximately, and refining it degrades the source-system
    conditioning through near-singular lid<->waterline-panel interactions
    (measured on the truncated cylinder: nr=2 biases the valid band
    <= 0.3%, nr=8 up to 4%).

    Returns [nlid, 4, 3] panels lying exactly at z = 0 (normals +z).
    """
    p = np.asarray(panels, float)
    # collect panel edges with both endpoints on the waterplane
    edges = {}
    for quad in p:
        for k in range(4):
            a, b = quad[k], quad[(k + 1) % 4]
            if abs(a[2]) < z_tol and abs(b[2]) < z_tol:
                ka = (round(a[0], 6), round(a[1], 6))
                kb = (round(b[0], 6), round(b[1], 6))
                if ka != kb:
                    edges.setdefault(ka, []).append(kb)
    loops = []
    visited = set()
    for start in list(edges):
        if start in visited:
            continue
        loop = [start]
        visited.add(start)
        cur = start
        while True:
            nxts = [v for v in edges.get(cur, []) if v not in visited]
            if not nxts:
                break
            cur = nxts[0]
            visited.add(cur)
            loop.append(cur)
        if len(loop) >= 3:
            loops.append(np.array(loop, float))
    out = []
    for loop in loops:
        c = loop.mean(axis=0)
        ts = np.linspace(1.0, 0.0, nr + 1)
        nv = len(loop)
        for k in range(nr):
            P1 = c + ts[k] * (loop - c)          # outer ring [nv, 2]
            P2 = c + ts[k + 1] * (loop - c)      # inner ring
            for i in range(nv):
                j = (i + 1) % nv
                quad = np.zeros((4, 3))
                # wind so the +z normal comes out of panel_geometry for a
                # counter-clockwise waterline loop; orientation is fixed
                # below regardless of loop direction
                quad[0, :2] = P1[i]
                quad[1, :2] = P1[j]
                quad[2, :2] = P2[j]
                quad[3, :2] = P2[i]
                out.append(quad)
    if not out:
        return np.zeros((0, 4, 3))
    lids = np.asarray(out)
    # enforce +z normals panel-by-panel (loop direction may be either way)
    _, nrm, _ = panel_geometry(lids)
    flip = nrm[:, 2] < 0.0
    lids[flip] = lids[flip, ::-1]
    return lids
