"""Hand-written Pallas TPU kernels for the solve core.

Two hot spots get hand-pipelined kernels (the recipe of the
high-resolution-imaging-on-TPUs line of work, arXiv:1912.08063: keep the
working set in VMEM, feed the MXU from explicit tiles, avoid the
gather/scatter lowerings XLA picks for generic linear algebra):

 - :func:`gauss_solve_pallas` — the batched augmented Gauss-Jordan
   behind the 12x12 real-block complex 6x6 solve
   (:func:`raft_tpu.dynamics.gauss_solve`).  One kernel invocation runs
   the full n-step elimination on a [tile, n, n+1] batch block resident
   in VMEM, so the per-step argmax/swap/eliminate round trips to HBM
   that the XLA lowering pays (n dispatch boundaries per solve) collapse
   into a single fused loop.  Pivot selection, row swap, and pivot-row
   extraction are mask/one-hot reductions (no gathers — 1-D gathers are
   the slowest path on the TPU vector unit and ``jnp.take_along_axis``
   is unsupported in Pallas TPU lowering).
 - :func:`gj_stage_pallas` — the blocked banded Gauss-Jordan stage of
   the BEM solve (:func:`raft_tpu.bem_solver._gj_stage`).  The full
   [2N, 2N] operator exceeds VMEM for every mesh the blocked path
   exists for, so the stage stays a JAX-level ``fori_loop`` over pivot
   blocks and the three dense pieces inside each step become kernels:
   in-VMEM pivot-tile inversion (:func:`tile_inv_pallas` — a whole
   [block, 2*block] augmented elimination per call; ``jnp.linalg.inv``
   has no Pallas equivalent), and VMEM-tiled matmul / matmul-subtract
   updates (:func:`mm_pallas` / :func:`mm_sub_pallas`) for the row
   scaling and the rank-``block`` elimination update.
 - :func:`fused_block_fn` — the fused per-iteration fixed-point
   megakernel behind the convergence-aware engine's ``fused`` mode
   (raft_tpu/waterfall.py).  One grid step owns one (design x case)
   lane and runs a whole K-iteration waterfall block on-chip: drag
   linearization, damping update, impedance assembly, the batched
   [nw] 12x12 real-block complex solve, the under-relaxed update, and
   the convergence/NaN-quarantine flags — with the iterate XiLast
   resident in VMEM across all K iterations, so the per-iteration HBM
   round trips of the XLA scan (every einsum materializes [N, 3, nw]
   intermediates to HBM between dispatch boundaries) collapse into one
   fused loop.  Complex arithmetic is carried as explicit re/im pairs
   (TPU Pallas has no complex dtype).  Numerics are tolerance-level,
   not bitwise, against the XLA phase programs (reduction orders
   differ); the finalize phase always runs the XLA recovery ladder.

Dispatch contract (the safety half of the ISSUE):

 - everything here sits behind ``RAFT_TPU_PALLAS`` (default OFF).  With
   the flag unset, the callers' existing XLA paths run untouched —
   bit-for-bit, including the health ladder's tiers, which NEVER route
   through these kernels regardless of the flag (tier selection must
   not change arithmetic under recovery);
 - off-TPU the kernels run in interpret mode (``interpret=True``), so
   the CPU tier-1 suite executes the exact kernel bodies and
   parity-tests them against the XLA reference implementations
   (tests/test_kernels.py; enforced for every future kernel module by
   tests/test_pallas_parity_registered.py).

Numerics: :func:`gauss_solve_pallas` mirrors ``_gj_step``'s partial
pivoting step for step, so it agrees with the reference to roundoff
(one-hot masked reductions replace gathers; adding exact zeros changes
no values, but reduction order inside XLA vs the kernel may differ by
ulps).  :func:`tile_inv_pallas` is a Gauss-Jordan inverse with partial
pivoting — a *different* (and more pivot-robust) algorithm than the
LAPACK/XLA LU inverse it replaces, so stage parity is tolerance-level,
not bitwise; the acceptance gate is the solver-level relative-residual
check in the parity tests.
"""

import os
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    HAVE_PALLAS = True
except ImportError:  # pragma: no cover - pallas ships with jax>=0.4
    pl = None
    HAVE_PALLAS = False

_TRUTHY = ("1", "true", "on", "yes")


def pallas_enabled():
    """Whether ``RAFT_TPU_PALLAS`` routes the solve core through the
    hand-written kernels.  Default off: the generic XLA paths are the
    production fallback and stay bit-for-bit unchanged."""
    return HAVE_PALLAS and os.environ.get(
        "RAFT_TPU_PALLAS", ""
    ).strip().lower() in _TRUTHY


def _interpret():
    """Interpret mode off-TPU: the kernels execute as reference Python/
    XLA on CPU so tier-1 parity tests run the real kernel bodies."""
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------- batched GJ

def _gj_elim_body(M, i):
    """One masked Gauss-Jordan elimination step on the augmented batch
    ``M [TB, n, m]`` — the kernel-side mirror of
    :func:`raft_tpu.dynamics._gj_step`, with every gather replaced by a
    one-hot masked reduction (TPU vector units hate gathers; summing a
    single-nonzero mask product is exact)."""
    TB, n, m = M.shape
    ridx = jax.lax.broadcasted_iota(jnp.int32, (TB, n), 1)
    cmask = jax.lax.broadcasted_iota(jnp.int32, (TB, n, m), 2) == i
    col = jnp.sum(jnp.where(cmask, M, 0.0), axis=-1)        # M[:, :, i]
    colmag = jnp.where(ridx < i, -jnp.inf, jnp.abs(col))
    p = jnp.argmax(colmag, axis=-1)                          # pivot row
    is_p = ridx == p[:, None]
    is_i = ridx == i
    rp = jnp.sum(jnp.where(is_p[:, :, None], M, 0.0), axis=1)  # [TB, m]
    ri = jnp.sum(jnp.where(is_i[:, :, None], M, 0.0), axis=1)
    M = jnp.where(is_i[:, :, None], rp[:, None, :],
                  jnp.where(is_p[:, :, None], ri[:, None, :], M))
    pmask = jax.lax.broadcasted_iota(jnp.int32, (TB, m), 1) == i
    piv = jnp.sum(jnp.where(pmask, rp, 0.0), axis=-1)        # rp[i]
    row = rp / piv[:, None]
    fac = jnp.sum(jnp.where(cmask, M, 0.0), axis=-1)         # col i, swapped
    return jnp.where(is_i[:, :, None], row[:, None, :],
                     M - fac[:, :, None] * row[:, None, :])


def _gj_solve_kernel(m_ref, out_ref):
    M = m_ref[...]
    n = M.shape[1]
    out_ref[...] = jax.lax.fori_loop(
        0, n, lambda i, M: _gj_elim_body(M, i), M
    )


@lru_cache(maxsize=32)
def _gj_solve_call(nblocks, tb, n, m, dtype_name, interpret):
    dtype = np.dtype(dtype_name)
    fn = pl.pallas_call(
        _gj_solve_kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((tb, n, m), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((tb, n, m), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks * tb, n, m), dtype),
        interpret=interpret,
    )
    return jax.jit(fn)


def gauss_solve_pallas(A, b, batch_tile=512):
    """Drop-in for :func:`raft_tpu.dynamics.gauss_solve` through the
    Pallas batched elimination kernel.

    A : [..., n, n]; b : [..., n, nrhs] -> x : [..., n, nrhs].  Leading
    batch axes are flattened into VMEM-resident tiles of ``batch_tile``
    systems; the tail tile is padded with identity systems (solved and
    discarded — always-finite work, zero effect on real lanes).
    """
    n = A.shape[-1]
    nrhs = b.shape[-1]
    m = n + nrhs
    batch_shape = A.shape[:-2]
    B = int(np.prod(batch_shape, dtype=np.int64)) if batch_shape else 1
    M = jnp.concatenate([A, b], axis=-1).reshape((B, n, m))
    tb = min(B, int(batch_tile))
    pad = (-B) % tb
    if pad:
        fill = jnp.concatenate(
            [jnp.eye(n, dtype=M.dtype), jnp.zeros((n, nrhs), M.dtype)],
            axis=-1,
        )
        M = jnp.concatenate(
            [M, jnp.broadcast_to(fill, (pad, n, m))], axis=0
        )
    out = _gj_solve_call(
        (B + pad) // tb, tb, n, m, M.dtype.name, _interpret()
    )(M)
    x = out[:B, :, n:]
    return x.reshape(batch_shape + (n, nrhs))


# ------------------------------------------------------------ blocked stage

def _tile_inv_kernel(a_ref, out_ref):
    """In-VMEM Gauss-Jordan inversion of one pivot tile: the [n, 2n]
    augmented elimination runs entirely on-chip (n=512 f32: 2 MB)."""
    A = a_ref[...]
    n = A.shape[-1]
    ri = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    ci = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    eye = (ri == ci).astype(A.dtype)
    M = jnp.concatenate([A, eye], axis=-1)[None]       # [1, n, 2n]
    M = jax.lax.fori_loop(0, n, lambda i, M: _gj_elim_body(M, i), M)
    out_ref[...] = M[0, :, n:]


@lru_cache(maxsize=32)
def _tile_inv_call(n, dtype_name, interpret):
    dtype = np.dtype(dtype_name)
    fn = pl.pallas_call(
        _tile_inv_kernel,
        out_shape=jax.ShapeDtypeStruct((n, n), dtype),
        interpret=interpret,
    )
    return jax.jit(fn)


def tile_inv_pallas(A):
    """Invert a square tile in VMEM (replaces ``jnp.linalg.inv`` on the
    pivot blocks of the blocked Gauss-Jordan)."""
    n = A.shape[-1]
    return _tile_inv_call(n, A.dtype.name, _interpret())(A)


def _mm_kernel(l_ref, r_ref, o_ref):
    o_ref[...] = jnp.dot(l_ref[...], r_ref[...],
                         preferred_element_type=o_ref.dtype)


def _mm_sub_kernel(x_ref, l_ref, r_ref, o_ref):
    o_ref[...] = x_ref[...] - jnp.dot(l_ref[...], r_ref[...],
                                      preferred_element_type=o_ref.dtype)


def _tile(dim, cap=256):
    """Largest power-of-two tile <= cap that divides ``dim`` (whole dim
    if none does — small right-hand-side column counts stay one tile)."""
    for t in (256, 128, 64, 32, 16, 8):
        if t <= cap and dim % t == 0:
            return t
    return dim


@lru_cache(maxsize=64)
def _mm_call(nr, K, nc, tm, tn, dtype_name, interpret, sub):
    dtype = np.dtype(dtype_name)
    ospec = pl.BlockSpec((tm, tn), lambda i, j: (i, j))
    lspec = pl.BlockSpec((tm, K), lambda i, j: (i, 0))
    rspec = pl.BlockSpec((K, tn), lambda i, j: (0, j))
    kernel = _mm_sub_kernel if sub else _mm_kernel
    in_specs = [ospec, lspec, rspec] if sub else [lspec, rspec]
    fn = pl.pallas_call(
        kernel,
        grid=(nr // tm, nc // tn),
        in_specs=in_specs,
        out_specs=ospec,
        out_shape=jax.ShapeDtypeStruct((nr, nc), dtype),
        interpret=interpret,
    )
    return jax.jit(fn)


def mm_pallas(L, R):
    """``L @ R`` with VMEM-tiled operand blocks (full-K tiles: the
    blocked stage's K is the pivot block size, <= 512)."""
    nr, K = L.shape
    nc = R.shape[-1]
    tm, tn = _tile(nr), _tile(nc)
    return _mm_call(nr, K, nc, tm, tn, L.dtype.name, _interpret(),
                    False)(L, R)


def mm_sub_pallas(X, L, R):
    """``X - L @ R`` fused in one pass over X's tiles (the elimination
    update — saves materializing the [n, n] product in HBM)."""
    nr, K = L.shape
    nc = R.shape[-1]
    tm, tn = _tile(nr), _tile(nc)
    return _mm_call(nr, K, nc, tm, tn, X.dtype.name, _interpret(),
                    True)(X, L, R)


# ------------------------------------------------ fused fixed-point block

def _fused_fp_kernel(dw, rho, tol, relax, w_old, nIter, K):
    """Kernel body factory for one waterfall block: K gated fixed-point
    iterations of ONE (design x case) lane, entirely in VMEM.

    The per-iteration math mirrors ``fixed_point_phases``'s ``body``
    (raft_tpu/dynamics.py) composed with :func:`linearized_drag` and
    ``assemble_impedance``, in split re/im real arithmetic; the gating
    mirrors the waterfall's ``where(cond, body(s), s)`` trips, so a
    converged/frozen lane's state rides through unchanged bit-for-bit
    (the body IS computed — branchless, like the XLA select — and
    discarded).  The scalars are baked in as compile-time constants;
    the frequency grid rides in as a lane-shared input (Pallas forbids
    captured array constants), its block mapped to (0,) for every grid
    step.
    """
    from raft_tpu.utils.frames import translate_matrix_3to6

    c_drag = float(np.sqrt(8.0 / np.pi) * 0.5 * rho)
    nIter = int(nIter)

    def kernel(w_ref, r_ref, q_ref, p1sq_ref, p2sq_ref, qmat_ref, p1mat_ref,
               p2mat_ref, aq_ref, ap1_ref, ap2_ref, aend_ref,
               cdq_ref, cdp1_ref, cdp2_ref, cdend_ref, sub_ref,
               ure_ref, uim_ref, c_ref, m_ref, b_ref, flr_ref, fli_ref,
               it_ref, xnr_ref, xni_ref, xpr_ref, xpi_ref,
               xfr_ref, xfi_ref, dn_ref, fz_ref,
               oit_ref, oxnr_ref, oxni_ref, oxpr_ref, oxpi_ref,
               oxfr_ref, oxfi_ref, odn_ref, ofz_ref):
        r = r_ref[0]                                   # [N, 3]
        q = q_ref[0]
        p1_sq = p1sq_ref[0]
        p2_sq = p2sq_ref[0]
        qMat = qmat_ref[0]                             # [N, 3, 3]
        p1Mat = p1mat_ref[0]
        p2Mat = p2mat_ref[0]
        a_q, a_p1, a_p2 = aq_ref[0], ap1_ref[0], ap2_ref[0]
        a_end_abs = aend_ref[0]
        Cd_q, Cd_p1, Cd_p2 = cdq_ref[0], cdp1_ref[0], cdp2_ref[0]
        Cd_End = cdend_ref[0]
        m3 = (sub_ref[0] > 0)[:, None, None]           # [N, 1, 1]
        ur, ui = ure_ref[0], uim_ref[0]                # [N, 3, W]
        C = c_ref[0]                                   # [6, 6]
        M, B = m_ref[0], b_ref[0]                      # [W, 6, 6]
        Flr, Fli = flr_ref[0], fli_ref[0]              # [W, 6]
        dt = ur.dtype
        w_arr = w_ref[...]                             # [W]
        w2 = (w_arr * w_arr)[:, None, None]

        def fp_step(XLr, XLi):
            # --- drag linearization at the point XL [6, W] (split re/im
            # mirror of hydro.linearized_drag; i*w*dr -> (-w di, w dr))
            def cross_rth(th):                         # [3, W] -> [N, 3, W]
                return jnp.stack(
                    [th[2][None, :] * (-r[:, 1][:, None])
                     + th[1][None, :] * r[:, 2][:, None],
                     th[2][None, :] * r[:, 0][:, None]
                     - th[0][None, :] * r[:, 2][:, None],
                     -th[1][None, :] * r[:, 0][:, None]
                     + th[0][None, :] * r[:, 1][:, None]],
                    axis=1)

            drr = XLr[None, :3, :] + cross_rth(XLr[3:, :])
            dri = XLi[None, :3, :] + cross_rth(XLi[3:, :])
            vrr = jnp.where(m3, ur - (-w_arr * dri), 0.0)
            vri = jnp.where(m3, ui - (w_arr * drr), 0.0)
            cq_r = vrr * q[:, :, None]
            cq_i = vri * q[:, :, None]
            vRMS_q = jnp.sqrt(
                jnp.sum(cq_r * cq_r + cq_i * cq_i, axis=(1, 2)) * dw)
            abs2 = vrr * vrr + vri * vri
            vRMS_p1 = jnp.sqrt(
                jnp.sum(abs2 * p1_sq[:, :, None], axis=(1, 2)) * dw)
            vRMS_p2 = jnp.sqrt(
                jnp.sum(abs2 * p2_sq[:, :, None], axis=(1, 2)) * dw)
            Bq = c_drag * vRMS_q * a_q * Cd_q
            Bp1 = c_drag * vRMS_p1 * a_p1 * Cd_p1
            Bp2 = c_drag * vRMS_p2 * a_p2 * Cd_p2
            Bend = c_drag * vRMS_q * a_end_abs * Cd_End
            Bmat = ((Bq + Bend)[:, None, None] * qMat
                    + Bp1[:, None, None] * p1Mat
                    + Bp2[:, None, None] * p2Mat)
            B_drag = jnp.sum(
                jnp.where(m3, translate_matrix_3to6(Bmat, r), 0.0), axis=0)
            f3r = jnp.einsum("nij,njw->niw", Bmat, ur)
            f3i = jnp.einsum("nij,njw->niw", Bmat, ui)

            def sum_force(f3):
                f3 = jnp.where(m3, f3, 0.0)
                fw = jnp.moveaxis(f3, -1, 1)           # [N, W, 3]
                mom = jnp.cross(r[:, None, :], fw)
                return jnp.concatenate(
                    [jnp.sum(fw, axis=0), jnp.sum(mom, axis=0)], axis=-1)

            # --- impedance + excitation, then the [W] batch of complex
            # 6x6 solves as augmented 12x13 eliminations in one loop
            Zr = -w2 * M + C
            Zi = w_arr[:, None, None] * (B + B_drag[None])
            FR = sum_force(f3r) + Flr
            FI = sum_force(f3i) + Fli
            A = jnp.concatenate(
                [jnp.concatenate([Zr, -Zi], axis=-1),
                 jnp.concatenate([Zi, Zr], axis=-1)], axis=-2)
            rhs = jnp.concatenate([FR, FI], axis=-1)[..., None]
            Maug = jnp.concatenate([A, rhs], axis=-1)  # [W, 12, 13]
            Maug = jax.lax.fori_loop(
                0, 12, lambda i, Mx: _gj_elim_body(Mx, i), Maug)
            x = Maug[:, :, 12]                         # [W, 12]
            return x[:, :6].T, x[:, 6:].T              # [6, W] re, im

        def trip(_, carry):
            it, xnr, xni, xpr, xpi, xfr, xfi, dn, fz = carry
            run = (it < nIter + 1) & (dn == 0)
            Xr, Xj = fp_step(xnr, xni)
            finite = jnp.all(jnp.isfinite(Xr)) & jnp.all(jnp.isfinite(Xj))
            num = jnp.sqrt((Xr - xnr) ** 2 + (Xj - xni) ** 2)
            den = jnp.sqrt(Xr * Xr + Xj * Xj) + dt.type(tol)
            conv = jnp.all(num / den < tol)            # NaN compares False
            newdone = conv | ~finite
            new = (it + 1,
                   jnp.where(newdone, xnr, w_old * xnr + relax * Xr),
                   jnp.where(newdone, xni, w_old * xni + relax * Xj),
                   xnr, xni,
                   jnp.where(finite, Xr, xfr),
                   jnp.where(finite, Xj, xfi),
                   jnp.where(newdone, 1, dn).astype(dn.dtype),
                   jnp.where(finite, fz, 1).astype(fz.dtype))
            return tuple(
                jnp.where(run, n, o) for n, o in zip(new, carry))

        carry = (it_ref[0], xnr_ref[0], xni_ref[0], xpr_ref[0],
                 xpi_ref[0], xfr_ref[0], xfi_ref[0], dn_ref[0], fz_ref[0])
        carry = jax.lax.fori_loop(0, K, trip, carry)
        oit_ref[0] = carry[0]
        oxnr_ref[0] = carry[1]
        oxni_ref[0] = carry[2]
        oxpr_ref[0] = carry[3]
        oxpi_ref[0] = carry[4]
        oxfr_ref[0] = carry[5]
        oxfi_ref[0] = carry[6]
        odn_ref[0] = carry[7]
        ofz_ref[0] = carry[8]

    return kernel


def _lane_spec(a):
    """One-lane BlockSpec for a [L, ...] operand: grid step l owns row l."""
    rest = tuple(a.shape[1:])
    nr = len(rest)
    return pl.BlockSpec((1,) + rest, lambda l, _n=nr: (l,) + (0,) * _n)


@lru_cache(maxsize=16)
def fused_block_fn(physics, relax, block):
    """The ``fused`` engine's block program: same signature as the
    waterfall's XLA block (``(nodes, u, C, M, B, Fr, Fi, state) ->
    state``, all leading [L]) with the K gated fixed-point trips running
    inside ONE Pallas megakernel, one lane per grid step.

    physics : raft_tpu.serve.buckets.SlotPhysics
    relax / block : under-relaxation weight and iterations per block

    Complex operands/state are split into re/im pairs at the kernel
    boundary and re-married after (TPU Pallas has no complex dtype); the
    per-lane flags come back as int32 and are cast to the XLA state's
    bool/int dtypes, so the host-side waterfall driver and the XLA
    finalize consume the kernel's state unchanged.  Off-TPU the kernel
    runs in interpret mode — tier-1 parity-tests the exact kernel body
    against the XLA phase programs (tolerance-level: reduction orders
    differ inside the kernel).
    """
    w = np.frombuffer(physics.w_bytes, np.float64, count=physics.nw)
    dtype = np.dtype(physics.dtype_name)
    dw = float(w[1] - w[0])
    relax = float(relax)
    w_old = round(1.0 - relax, 12)
    kernel = _fused_fp_kernel(dw, physics.rho, 0.01,
                              relax, w_old, physics.nIter, int(block))
    w_in = jnp.asarray(w.astype(dtype))
    w_spec = pl.BlockSpec((physics.nw,), lambda l: (0,))

    def block_fn(nodes, u, C, M, B, Fr, Fi, state):
        i0, xn, xp, xf, dn, fz = state
        L = u.shape[0]
        if nodes.r.ndim == 2:
            # lane-shared node bundle (waterfall shared_nodes mode):
            # the kernel grid owns one lane per step, so broadcast
            nodes = jax.tree.map(
                lambda a: jnp.broadcast_to(
                    jnp.asarray(a)[None], (L,) + a.shape), nodes)
        re, im = jnp.real, jnp.imag
        p1_sq = jnp.diagonal(nodes.p1Mat, axis1=-2, axis2=-1)
        p2_sq = jnp.diagonal(nodes.p2Mat, axis1=-2, axis2=-1)
        ins = (nodes.r, nodes.q, p1_sq, p2_sq,
               nodes.qMat, nodes.p1Mat, nodes.p2Mat,
               nodes.a_q, nodes.a_p1, nodes.a_p2, nodes.a_end_abs,
               nodes.Cd_q, nodes.Cd_p1, nodes.Cd_p2, nodes.Cd_End,
               nodes.submerged.astype(jnp.int32),
               re(u), im(u), C, M, B, Fr, Fi,
               i0.astype(jnp.int32), re(xn), im(xn), re(xp), im(xp),
               re(xf), im(xf),
               dn.astype(jnp.int32), fz.astype(jnp.int32))
        sd = jax.ShapeDtypeStruct
        xs = tuple(xn.shape)                           # (L, 6, W)
        out_shape = [sd((L,), np.int32)] + [sd(xs, dtype)] * 6 + [
            sd((L,), np.int32), sd((L,), np.int32)]
        outs = pl.pallas_call(
            kernel,
            grid=(L,),
            in_specs=[w_spec] + [_lane_spec(a) for a in ins],
            out_specs=[_lane_spec(s) for s in out_shape],
            out_shape=out_shape,
            interpret=_interpret(),
        )(w_in, *ins)
        oi, oxnr, oxni, oxpr, oxpi, oxfr, oxfi, odn, ofz = outs
        mk = lambda a, b: jax.lax.complex(               # noqa: E731
            a, b).astype(xn.dtype)
        return (oi.astype(i0.dtype), mk(oxnr, oxni), mk(oxpr, oxpi),
                mk(oxfr, oxfi), odn.astype(dn.dtype), ofz.astype(fz.dtype))

    return jax.jit(block_fn)


def gj_stage_pallas(A, b, kb0, nblk, block=512):
    """Pallas-composed mirror of :func:`raft_tpu.bem_solver._gj_stage`:
    same JAX-level ``fori_loop`` over pivot blocks (``kb0``/``nblk`` stay
    traced so one executable serves every streamed stage), with the
    pivot-tile inverse and the dense updates in kernels.  Same
    no-inter-block-pivoting contract as the XLA path."""
    n = A.shape[0]
    m = b.shape[1]
    assert n % block == 0, (n, block)
    rowidx = jnp.arange(n)

    def step(kb, carry):
        A, b = carry
        k0 = kb * block
        D = jax.lax.dynamic_slice(A, (k0, 0), (block, n))
        Db = jax.lax.dynamic_slice(b, (k0, 0), (block, m))
        Dinv = tile_inv_pallas(
            jax.lax.dynamic_slice(A, (k0, k0), (block, block))
        )
        Arow = mm_pallas(Dinv, D)                           # [block, n]
        brow = mm_pallas(Dinv, Db)                          # [block, m]
        C = jax.lax.dynamic_slice(A, (0, k0), (n, block))   # [n, block]
        mask = ((rowidx >= k0) & (rowidx < k0 + block))[:, None]
        C = jnp.where(mask, 0.0, C)
        A = mm_sub_pallas(A, C, Arow)
        b = mm_sub_pallas(b, C, brow)
        A = jax.lax.dynamic_update_slice(A, Arow, (k0, 0))
        b = jax.lax.dynamic_update_slice(b, brow, (k0, 0))
        return A, b

    return jax.lax.fori_loop(kb0, kb0 + nblk, step, (A, b))
