"""Hand-written Pallas TPU kernels for the solve core.

Two hot spots get hand-pipelined kernels (the recipe of the
high-resolution-imaging-on-TPUs line of work, arXiv:1912.08063: keep the
working set in VMEM, feed the MXU from explicit tiles, avoid the
gather/scatter lowerings XLA picks for generic linear algebra):

 - :func:`gauss_solve_pallas` — the batched augmented Gauss-Jordan
   behind the 12x12 real-block complex 6x6 solve
   (:func:`raft_tpu.dynamics.gauss_solve`).  One kernel invocation runs
   the full n-step elimination on a [tile, n, n+1] batch block resident
   in VMEM, so the per-step argmax/swap/eliminate round trips to HBM
   that the XLA lowering pays (n dispatch boundaries per solve) collapse
   into a single fused loop.  Pivot selection, row swap, and pivot-row
   extraction are mask/one-hot reductions (no gathers — 1-D gathers are
   the slowest path on the TPU vector unit and ``jnp.take_along_axis``
   is unsupported in Pallas TPU lowering).
 - :func:`gj_stage_pallas` — the blocked banded Gauss-Jordan stage of
   the BEM solve (:func:`raft_tpu.bem_solver._gj_stage`).  The full
   [2N, 2N] operator exceeds VMEM for every mesh the blocked path
   exists for, so the stage stays a JAX-level ``fori_loop`` over pivot
   blocks and the three dense pieces inside each step become kernels:
   in-VMEM pivot-tile inversion (:func:`tile_inv_pallas` — a whole
   [block, 2*block] augmented elimination per call; ``jnp.linalg.inv``
   has no Pallas equivalent), and VMEM-tiled matmul / matmul-subtract
   updates (:func:`mm_pallas` / :func:`mm_sub_pallas`) for the row
   scaling and the rank-``block`` elimination update.

Dispatch contract (the safety half of the ISSUE):

 - everything here sits behind ``RAFT_TPU_PALLAS`` (default OFF).  With
   the flag unset, the callers' existing XLA paths run untouched —
   bit-for-bit, including the health ladder's tiers, which NEVER route
   through these kernels regardless of the flag (tier selection must
   not change arithmetic under recovery);
 - off-TPU the kernels run in interpret mode (``interpret=True``), so
   the CPU tier-1 suite executes the exact kernel bodies and
   parity-tests them against the XLA reference implementations
   (tests/test_kernels.py; enforced for every future kernel module by
   tests/test_pallas_parity_registered.py).

Numerics: :func:`gauss_solve_pallas` mirrors ``_gj_step``'s partial
pivoting step for step, so it agrees with the reference to roundoff
(one-hot masked reductions replace gathers; adding exact zeros changes
no values, but reduction order inside XLA vs the kernel may differ by
ulps).  :func:`tile_inv_pallas` is a Gauss-Jordan inverse with partial
pivoting — a *different* (and more pivot-robust) algorithm than the
LAPACK/XLA LU inverse it replaces, so stage parity is tolerance-level,
not bitwise; the acceptance gate is the solver-level relative-residual
check in the parity tests.
"""

import os
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    HAVE_PALLAS = True
except ImportError:  # pragma: no cover - pallas ships with jax>=0.4
    pl = None
    HAVE_PALLAS = False

_TRUTHY = ("1", "true", "on", "yes")


def pallas_enabled():
    """Whether ``RAFT_TPU_PALLAS`` routes the solve core through the
    hand-written kernels.  Default off: the generic XLA paths are the
    production fallback and stay bit-for-bit unchanged."""
    return HAVE_PALLAS and os.environ.get(
        "RAFT_TPU_PALLAS", ""
    ).strip().lower() in _TRUTHY


def _interpret():
    """Interpret mode off-TPU: the kernels execute as reference Python/
    XLA on CPU so tier-1 parity tests run the real kernel bodies."""
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------- batched GJ

def _gj_elim_body(M, i):
    """One masked Gauss-Jordan elimination step on the augmented batch
    ``M [TB, n, m]`` — the kernel-side mirror of
    :func:`raft_tpu.dynamics._gj_step`, with every gather replaced by a
    one-hot masked reduction (TPU vector units hate gathers; summing a
    single-nonzero mask product is exact)."""
    TB, n, m = M.shape
    ridx = jax.lax.broadcasted_iota(jnp.int32, (TB, n), 1)
    cmask = jax.lax.broadcasted_iota(jnp.int32, (TB, n, m), 2) == i
    col = jnp.sum(jnp.where(cmask, M, 0.0), axis=-1)        # M[:, :, i]
    colmag = jnp.where(ridx < i, -jnp.inf, jnp.abs(col))
    p = jnp.argmax(colmag, axis=-1)                          # pivot row
    is_p = ridx == p[:, None]
    is_i = ridx == i
    rp = jnp.sum(jnp.where(is_p[:, :, None], M, 0.0), axis=1)  # [TB, m]
    ri = jnp.sum(jnp.where(is_i[:, :, None], M, 0.0), axis=1)
    M = jnp.where(is_i[:, :, None], rp[:, None, :],
                  jnp.where(is_p[:, :, None], ri[:, None, :], M))
    pmask = jax.lax.broadcasted_iota(jnp.int32, (TB, m), 1) == i
    piv = jnp.sum(jnp.where(pmask, rp, 0.0), axis=-1)        # rp[i]
    row = rp / piv[:, None]
    fac = jnp.sum(jnp.where(cmask, M, 0.0), axis=-1)         # col i, swapped
    return jnp.where(is_i[:, :, None], row[:, None, :],
                     M - fac[:, :, None] * row[:, None, :])


def _gj_solve_kernel(m_ref, out_ref):
    M = m_ref[...]
    n = M.shape[1]
    out_ref[...] = jax.lax.fori_loop(
        0, n, lambda i, M: _gj_elim_body(M, i), M
    )


@lru_cache(maxsize=32)
def _gj_solve_call(nblocks, tb, n, m, dtype_name, interpret):
    dtype = np.dtype(dtype_name)
    fn = pl.pallas_call(
        _gj_solve_kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((tb, n, m), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((tb, n, m), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks * tb, n, m), dtype),
        interpret=interpret,
    )
    return jax.jit(fn)


def gauss_solve_pallas(A, b, batch_tile=512):
    """Drop-in for :func:`raft_tpu.dynamics.gauss_solve` through the
    Pallas batched elimination kernel.

    A : [..., n, n]; b : [..., n, nrhs] -> x : [..., n, nrhs].  Leading
    batch axes are flattened into VMEM-resident tiles of ``batch_tile``
    systems; the tail tile is padded with identity systems (solved and
    discarded — always-finite work, zero effect on real lanes).
    """
    n = A.shape[-1]
    nrhs = b.shape[-1]
    m = n + nrhs
    batch_shape = A.shape[:-2]
    B = int(np.prod(batch_shape, dtype=np.int64)) if batch_shape else 1
    M = jnp.concatenate([A, b], axis=-1).reshape((B, n, m))
    tb = min(B, int(batch_tile))
    pad = (-B) % tb
    if pad:
        fill = jnp.concatenate(
            [jnp.eye(n, dtype=M.dtype), jnp.zeros((n, nrhs), M.dtype)],
            axis=-1,
        )
        M = jnp.concatenate(
            [M, jnp.broadcast_to(fill, (pad, n, m))], axis=0
        )
    out = _gj_solve_call(
        (B + pad) // tb, tb, n, m, M.dtype.name, _interpret()
    )(M)
    x = out[:B, :, n:]
    return x.reshape(batch_shape + (n, nrhs))


# ------------------------------------------------------------ blocked stage

def _tile_inv_kernel(a_ref, out_ref):
    """In-VMEM Gauss-Jordan inversion of one pivot tile: the [n, 2n]
    augmented elimination runs entirely on-chip (n=512 f32: 2 MB)."""
    A = a_ref[...]
    n = A.shape[-1]
    ri = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    ci = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    eye = (ri == ci).astype(A.dtype)
    M = jnp.concatenate([A, eye], axis=-1)[None]       # [1, n, 2n]
    M = jax.lax.fori_loop(0, n, lambda i, M: _gj_elim_body(M, i), M)
    out_ref[...] = M[0, :, n:]


@lru_cache(maxsize=32)
def _tile_inv_call(n, dtype_name, interpret):
    dtype = np.dtype(dtype_name)
    fn = pl.pallas_call(
        _tile_inv_kernel,
        out_shape=jax.ShapeDtypeStruct((n, n), dtype),
        interpret=interpret,
    )
    return jax.jit(fn)


def tile_inv_pallas(A):
    """Invert a square tile in VMEM (replaces ``jnp.linalg.inv`` on the
    pivot blocks of the blocked Gauss-Jordan)."""
    n = A.shape[-1]
    return _tile_inv_call(n, A.dtype.name, _interpret())(A)


def _mm_kernel(l_ref, r_ref, o_ref):
    o_ref[...] = jnp.dot(l_ref[...], r_ref[...],
                         preferred_element_type=o_ref.dtype)


def _mm_sub_kernel(x_ref, l_ref, r_ref, o_ref):
    o_ref[...] = x_ref[...] - jnp.dot(l_ref[...], r_ref[...],
                                      preferred_element_type=o_ref.dtype)


def _tile(dim, cap=256):
    """Largest power-of-two tile <= cap that divides ``dim`` (whole dim
    if none does — small right-hand-side column counts stay one tile)."""
    for t in (256, 128, 64, 32, 16, 8):
        if t <= cap and dim % t == 0:
            return t
    return dim


@lru_cache(maxsize=64)
def _mm_call(nr, K, nc, tm, tn, dtype_name, interpret, sub):
    dtype = np.dtype(dtype_name)
    ospec = pl.BlockSpec((tm, tn), lambda i, j: (i, j))
    lspec = pl.BlockSpec((tm, K), lambda i, j: (i, 0))
    rspec = pl.BlockSpec((K, tn), lambda i, j: (0, j))
    kernel = _mm_sub_kernel if sub else _mm_kernel
    in_specs = [ospec, lspec, rspec] if sub else [lspec, rspec]
    fn = pl.pallas_call(
        kernel,
        grid=(nr // tm, nc // tn),
        in_specs=in_specs,
        out_specs=ospec,
        out_shape=jax.ShapeDtypeStruct((nr, nc), dtype),
        interpret=interpret,
    )
    return jax.jit(fn)


def mm_pallas(L, R):
    """``L @ R`` with VMEM-tiled operand blocks (full-K tiles: the
    blocked stage's K is the pivot block size, <= 512)."""
    nr, K = L.shape
    nc = R.shape[-1]
    tm, tn = _tile(nr), _tile(nc)
    return _mm_call(nr, K, nc, tm, tn, L.dtype.name, _interpret(),
                    False)(L, R)


def mm_sub_pallas(X, L, R):
    """``X - L @ R`` fused in one pass over X's tiles (the elimination
    update — saves materializing the [n, n] product in HBM)."""
    nr, K = L.shape
    nc = R.shape[-1]
    tm, tn = _tile(nr), _tile(nc)
    return _mm_call(nr, K, nc, tm, tn, X.dtype.name, _interpret(),
                    True)(X, L, R)


def gj_stage_pallas(A, b, kb0, nblk, block=512):
    """Pallas-composed mirror of :func:`raft_tpu.bem_solver._gj_stage`:
    same JAX-level ``fori_loop`` over pivot blocks (``kb0``/``nblk`` stay
    traced so one executable serves every streamed stage), with the
    pivot-tile inverse and the dense updates in kernels.  Same
    no-inter-block-pivoting contract as the XLA path."""
    n = A.shape[0]
    m = b.shape[1]
    assert n % block == 0, (n, block)
    rowidx = jnp.arange(n)

    def step(kb, carry):
        A, b = carry
        k0 = kb * block
        D = jax.lax.dynamic_slice(A, (k0, 0), (block, n))
        Db = jax.lax.dynamic_slice(b, (k0, 0), (block, m))
        Dinv = tile_inv_pallas(
            jax.lax.dynamic_slice(A, (k0, k0), (block, block))
        )
        Arow = mm_pallas(Dinv, D)                           # [block, n]
        brow = mm_pallas(Dinv, Db)                          # [block, m]
        C = jax.lax.dynamic_slice(A, (0, k0), (n, block))   # [n, block]
        mask = ((rowidx >= k0) & (rowidx < k0 + block))[:, None]
        C = jnp.where(mask, 0.0, C)
        A = mm_sub_pallas(A, C, Arow)
        b = mm_sub_pallas(b, C, brow)
        A = jax.lax.dynamic_update_slice(A, Arow, (k0, 0))
        b = jax.lax.dynamic_update_slice(b, brow, (k0, 0))
        return A, b

    return jax.lax.fori_loop(kb0, kb0 + nblk, step, (A, b))
