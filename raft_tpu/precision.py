"""Gated mixed-precision policy: bf16 operands, f32 accumulation.

The strip-theory assembly (hydro einsums over the node axis) and the
impedance assembly are the arithmetic bulk of every fixed-point
iteration, and none of it needs full working precision to drive a fixed
point whose stopping test is 1% — the accuracy of the RETURNED
amplitudes comes from the final conditioned re-solve.  With
``RAFT_TPU_MIXED_PRECISION=1`` the assembly operands are rounded to
bfloat16 and the contractions accumulate in float32 (the classic MXU
recipe: bf16 multiplicands, f32 accumulator), which on TPU doubles the
MXU issue rate and halves assembly HBM traffic.

The flag defaults OFF, and off means *off*: every call site branches to
the exact pre-existing expression, so the default path stays
bit-for-bit identical (tier-1 asserts this by construction — the whole
suite runs with the flag unset).

Safety net (see :func:`raft_tpu.dynamics.solve_dynamics`): the final
re-solve always computes a full-precision assembly alongside the
mixed-precision one, and any frequency lane whose mixed-precision
solve left the health ladder's baseline tier — or whose condition
estimate exceeds the f32 ladder threshold — takes the full-precision
answer.  Degraded lanes therefore fall back to f32 (the full working
dtype) automatically; healthy lanes keep the fast-path result, gated
by the ``rao_linf_err <= 1e-4`` acceptance test in bench.
"""

import os

import jax.numpy as jnp

_TRUTHY = ("1", "true", "on", "yes")


def mixed_precision_enabled():
    """Whether ``RAFT_TPU_MIXED_PRECISION`` requests the bf16/f32 path.

    Read at trace time: jitted callers bake the answer into the
    executable, so flipping the flag mid-process needs a fresh trace
    (the same contract as every other RAFT_TPU_* flag).
    """
    return os.environ.get(
        "RAFT_TPU_MIXED_PRECISION", ""
    ).strip().lower() in _TRUTHY


def mp_round(x):
    """Round a real array's values through bfloat16 (operand rounding of
    the bf16-multiplicand / f32-accumulator recipe) while keeping the
    caller's dtype, so downstream promotion rules are unchanged."""
    return x.astype(jnp.bfloat16).astype(x.dtype)


def mp_matmul(einsum_str, A, X):
    """``jnp.einsum`` contraction with bf16 operands and f32
    accumulation.  ``A`` real, ``X`` real or complex (complex operands
    are contracted as separate real/imaginary bf16 passes — bf16 has no
    complex dtype)."""
    Ab = A.astype(jnp.bfloat16)
    if jnp.iscomplexobj(X):
        xr = jnp.einsum(einsum_str, Ab, jnp.real(X).astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32)
        xi = jnp.einsum(einsum_str, Ab, jnp.imag(X).astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32)
        return (xr + 1j * xi).astype(X.dtype)
    out = jnp.einsum(einsum_str, Ab, X.astype(jnp.bfloat16),
                     preferred_element_type=jnp.float32)
    return out.astype(X.dtype)


def mp_masked_sum(A, mask, axis=0):
    """Masked reduction with bf16 operands and f32 accumulation, result
    cast back to the operand dtype (the strip-theory 3->6 matrix sums)."""
    Ab = jnp.where(mask, A, 0.0).astype(jnp.bfloat16)
    return jnp.sum(Ab, axis=axis, dtype=jnp.float32).astype(A.dtype)
