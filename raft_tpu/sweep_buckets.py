"""Sweep-through-buckets: sweep dynamics dispatch on the serving layer's
canonical fixed-shape bucket executables.

The sweep drivers historically traced their own [group, draft, ballast,
case]-shaped pipelines — program shapes the serving subsystem never
compiles, so a fresh process pays the full trace+compile wall
(BENCH_FULL.json: 389 s) even with a fully warmed serve cache on disk.
This module re-routes the sweep's per-(design, case) dynamics lanes
through the SAME canonical slot executables the serving engine runs
(raft_tpu/serve/buckets.py): lanes are flattened, bucketized with
``choose_bucket`` (same node quantum and slot ladder as serving), and
dispatched slab-by-slab through ``slot_pipeline``.  Every bucket a sweep
touches is recorded in the serve warm-up manifest, so
``raft_tpu.serve.cache.warmup()`` in a fresh process pre-compiles (or
persistent-cache-loads) exactly the executables the next sweep needs —
the fixed-shape program-reuse discipline of TPU CFD frameworks
(arXiv:2108.11076) applied to the design sweep.  On multi-device
backends the slabs dispatch through the engine's lane-sharded
fixed-block executables (serve.buckets.sharded_slot_pipeline), so the
sweep weak-scales over the same 1-D ``('lane',)`` mesh the server uses.

Routing is opt-in: ``RAFT_TPU_SWEEP_BUCKETS=1`` (or the drivers'
``via_buckets=True``).  Off (the default), the drivers' fused pipelines
run bit-for-bit unchanged.

Bit-identity contract (inherited from the bucket layer, see
buckets.py's module docstring): within one bucket executable a lane's
result depends only on that lane's inputs, so a design's bucket-routed
sweep results are ``np.array_equal`` to the same design swept in any
other batch composition of the same bucket — and to the serve engine's
answer for the same case inputs.  Results vs the legacy fused pipeline
agree to solver tolerance (different executables re-associate
reductions by ulps; the fixed point's 1% stop can amplify that to
~1e-4), which is why the routing is a dispatch choice, not a silent
default.

The bounded non-convergence retry intentionally stays on the legacy
pipeline: retries re-solve with a different (nIter, relax) physics that
is NOT a canonical serving configuration, and polluting the manifest
with retry-only executables would defeat the warm-start story.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.serve.buckets import (
    SlotPhysics,
    choose_bucket,
    lane_block,
    serve_lane_devices,
    sharded_slot_pipeline,
    slot_pipeline,
)
from raft_tpu.utils.profiling import logger

_TRUTHY = ("1", "true", "on", "yes")


def sweep_buckets_enabled(explicit=None):
    """Whether sweep dynamics routes through serve buckets: the driver's
    explicit ``via_buckets`` argument wins; ``None`` defers to the
    ``RAFT_TPU_SWEEP_BUCKETS`` env flag (default off)."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get(
        "RAFT_TPU_SWEEP_BUCKETS", ""
    ).strip().lower() in _TRUTHY


def chunk_designs(n_designs, n_cases=None, chunk=None, rung=None):
    """Split a sweep's design indices into megabatch-sized chunks for the
    serve tier's continuous batcher (engine.submit_sweep).

    ``chunk`` (explicit designs-per-chunk) wins; else the
    ``RAFT_TPU_SERVE_SWEEP_CHUNK`` env knob (0 = auto); the auto rule
    sizes a chunk so its flattened (design x case) lanes fill ``rung``
    lanes (default: the top waterfall rung, waterfall.LANE_LADDER[-1])
    — one chunk = one slab-resident fixed-shape program, the preemption
    granularity.  A preemption-enabled engine passes a smaller ``rung``
    so block walls (the interactive wait at a yield) stay short; chunk
    size never changes bits (per-lane identity across rungs is the
    waterfall ladder's contract).

    Returns a list of contiguous design-index lists covering
    ``range(n_designs)``."""
    from raft_tpu.waterfall import LANE_LADDER

    n_designs = int(n_designs)
    if n_designs <= 0:
        return []
    if chunk is None:
        try:
            chunk = int(os.environ.get("RAFT_TPU_SERVE_SWEEP_CHUNK", 0))
        except ValueError:
            chunk = 0
    chunk = int(chunk)
    if chunk <= 0:
        nc = max(int(n_cases), 1) if n_cases else 1
        chunk = max(1, int(rung or LANE_LADDER[-1]) // nc)
    return [list(range(s, min(s + chunk, n_designs)))
            for s in range(0, n_designs, chunk)]


def _record_bucket(physics, spec):
    """Record a dispatched bucket in the serve warm-up manifest (and
    drop the persistent-cache size/time thresholds so its executable
    lands on disk) — this is what makes the NEXT process's sweep start
    warm.  Manifest trouble degrades to a log line, never a failed
    sweep."""
    try:
        from raft_tpu.serve.cache import WarmupManifest, persist_all_compiles

        persist_all_compiles()
        WarmupManifest().record(physics, spec)
    except OSError as e:
        logger.warning(
            "sweep bucket manifest record failed (%s); the sweep still "
            "runs, the next process just starts cold", e)


def _pad_node_axis(nodes_stacked, n_nodes):
    """Zero-pad every leaf's node axis (axis 1 of [nd, N, ...]) to the
    bucket's quantized node count — the same inert-padding contract as
    serve.buckets.pad_nodes."""
    N = nodes_stacked.r.shape[1]
    if N == n_nodes:
        return nodes_stacked
    if N > n_nodes:
        raise ValueError(
            f"stacked designs have {N} strip nodes > bucket "
            f"n_nodes={n_nodes}")

    def pad(a):
        widths = [(0, 0)] * a.ndim
        widths[1] = (0, n_nodes - N)
        return jnp.pad(a, widths)

    return jax.tree.map(pad, nodes_stacked)


def dispatch_lanes(physics, spec, n_lanes, slab_args, checkable=False,
                   record=True, devices=None):
    """Run ``n_lanes`` flattened (design x case) lanes through the
    canonical slot executable of ``spec``, ``spec.n_slots`` lanes per
    dispatch (all dispatches issued async, results concatenated on
    device).

    slab_args(idx) -> (nodes_slab, args_slab): the [len(idx)] operand
    gather for the given lane indices (``idx`` is tail-padded with lane
    0 — replicated-first-lane padding, same contract as
    serve.buckets.pack_slots; padded results are trimmed here).

    devices : lane-mesh devices for the multi-chip sharded executables
        (default: ``serve_lane_devices()`` — every device on accelerator
        backends, legacy single-device on CPU).  On the sharded path each
        slab is one ``len(devices) * lane_block()`` super-block laid
        across the 1-D ``('lane',)`` mesh, the SAME fixed-block program
        family the serving engine dispatches — so 256-design sweeps
        weak-scale over the mesh and share the engine's warm executables.

    Returns ``(xr [n_lanes, 6, nw], xi, report)`` device arrays.
    """
    if devices is None:
        devices = serve_lane_devices()
    if devices:
        fn, lane_sharding = sharded_slot_pipeline(
            physics, devices, checkable)
        chunk = len(devices) * lane_block()
        put = lambda a: jax.device_put(a, lane_sharding)  # noqa: E731
    else:
        fn = slot_pipeline(physics, checkable)
        chunk = spec.n_slots
        put = None
    if record:
        _record_bucket(physics, spec)
    outs = []
    for s0 in range(0, n_lanes, chunk):
        idx = np.arange(s0, min(s0 + chunk, n_lanes))
        if len(idx) < chunk:
            idx = np.concatenate(
                [idx, np.zeros(chunk - len(idx), idx.dtype)])
        nodes_slab, args_slab = slab_args(idx)
        if put is not None:
            nodes_slab = jax.tree.map(put, nodes_slab)
            args_slab = tuple(put(a) for a in args_slab)
        outs.append(fn(nodes_slab, *args_slab))       # async dispatch
    if len(outs) == 1:
        xr, xi, rep = outs[0]
        take = lambda a: a[:n_lanes]  # noqa: E731
    else:
        xr = jnp.concatenate([o[0] for o in outs])
        xi = jnp.concatenate([o[1] for o in outs])
        rep = jax.tree.map(
            lambda *leaves: jnp.concatenate(leaves),
            *[o[2] for o in outs])
        take = lambda a: a[:n_lanes]  # noqa: E731
    return take(xr), take(xi), jax.tree.map(take, rep)


def fused_bucket_pipeline(model0, return_xi):
    """Bucket-routed drop-in for ``sweep_fused._dynamics_pipeline``'s
    executable: same call signature ``(nodes_g, zeta, beta, C_g, M0_g,
    a_g, b_g)`` (leading group axes [G, gd(, nB)]), same output tuple
    ``(std, report[, xr, xi])`` — shaped flat [nd_flat * nc, ...] along
    the leading axis, which ``_unpack_dyn``'s reshape consumes
    identically (lane order is design-major, case-minor, exactly the
    row-major order of the grouped axes).

    The rank-1 hub added-mass/damping profiles are materialized per
    slab (``M_lin = M0 + a(w) * P_hub``, elementwise identical to the
    fused pipeline's in-graph expression) because the canonical slot
    executable takes full [nw, 6, 6] matrices per lane — that is the
    price of sharing ONE program with the serving engine instead of
    compiling a sweep-shaped program family.
    """
    from raft_tpu.utils.frames import translate_matrix_3to6

    physics = SlotPhysics.from_model(model0)
    dtype = np.dtype(physics.dtype_name).type
    w = np.frombuffer(physics.w_bytes, np.float64, count=physics.nw)
    dw = dtype(w[1] - w[0])
    nw = physics.nw
    E00 = np.zeros((1, 3, 3))
    E00[0, 0, 0] = 1.0
    P_hub = jnp.asarray(
        np.asarray(
            translate_matrix_3to6(E00, np.array([0.0, 0.0,
                                                 float(model0.hHub)]))
        )[0],
        dtype,
    )

    def pipeline(nodes_g, zeta, beta, C_g, M0_g, a_g, b_g):
        lead = C_g.shape[:-3]          # (G, gd, nB) or (G, gd)
        ncc = C_g.shape[-3]
        n_designs = int(np.prod(lead[:2], dtype=np.int64))  # nodes axis
        n_rows = int(np.prod(lead, dtype=np.int64))         # C/a/b rows
        L = n_rows * ncc
        nB = n_rows // n_designs
        nodes_flat = jax.tree.map(
            lambda a: a.reshape((n_designs,) + a.shape[2:]), nodes_g)
        spec = choose_bucket(nw, nodes_flat.r.shape[1], ncc)
        nodes_flat = _pad_node_axis(nodes_flat, spec.n_nodes)
        C_flat = C_g.reshape((n_rows, ncc, 6, 6))
        M0_flat = M0_g.reshape((n_rows, 6, 6))
        a_flat = a_g.reshape((n_rows, ncc, nw))
        b_flat = b_g.reshape((n_rows, ncc, nw))

        def slab_args(idx):
            ri = jnp.asarray(idx // ncc)                 # design-row idx
            ci = jnp.asarray(idx % ncc)                  # case idx
            di = ri // nB                                # node-bundle idx
            nodes_s = jax.tree.map(
                lambda a: jnp.take(a, di, axis=0), nodes_flat)
            M0_s = jnp.take(M0_flat, ri, axis=0)         # [S, 6, 6]
            a_s = a_flat[ri, ci]                         # [S, nw]
            b_s = b_flat[ri, ci]
            M_lin = M0_s[:, None] + a_s[:, :, None, None] * P_hub
            B_lin = b_s[:, :, None, None] * P_hub
            Fz = jnp.zeros((len(idx), nw, 6), dtype)
            args = (jnp.take(zeta, ci, axis=0),
                    jnp.take(beta, ci, axis=0),
                    C_flat[ri, ci], M_lin, B_lin, Fz, Fz)
            return nodes_s, args

        xr, xi, rep = dispatch_lanes(physics, spec, L, slab_args)
        std = jnp.sqrt(jnp.sum(xr * xr + xi * xi, axis=-1) * dw)
        if return_xi:
            return std, rep, xr, xi
        return std, rep

    return pipeline


def grouped_sweep_pipeline(model0, checkable=False):
    """Bucket-routed drop-in for ``sweep._sweep_pipeline``'s [design,
    case] executable: call signature ``(nodes_b, zeta, beta, C, M, B,
    Fr, Fi)`` with leading [nd] (nodes) / [nd, nc] (args) axes, output
    ``(xr [nd, nc, 6, nw], xi, report)`` exactly like the vmapped
    pipeline — but through the serving buckets, one slab of canonical
    lanes at a time.

    ``model0`` may be a full ``Model`` or a batched-prep
    ``PreppedDesign`` (raft_tpu/batched_prep.py): both expose the
    ``SlotPhysics.from_model`` attribute surface, which is all this
    pipeline reads."""
    physics = SlotPhysics.from_model(model0)

    def pipeline(nodes_b, *args_b):
        nd, nc = args_b[0].shape[:2]
        L = nd * nc
        spec = choose_bucket(physics.nw, nodes_b.r.shape[1], nc)
        nodes_p = _pad_node_axis(nodes_b, spec.n_nodes)
        flat = tuple(
            jnp.reshape(a, (L,) + tuple(a.shape[2:])) for a in args_b)

        def slab_args(idx):
            di = jnp.asarray(idx // nc)
            li = jnp.asarray(idx)
            nodes_s = jax.tree.map(
                lambda a: jnp.take(a, di, axis=0), nodes_p)
            return nodes_s, tuple(jnp.take(a, li, axis=0) for a in flat)

        xr, xi, rep = dispatch_lanes(physics, spec, L, slab_args,
                                     checkable=checkable)
        shape = lambda a: a.reshape((nd, nc) + a.shape[1:])  # noqa: E731
        return shape(xr), shape(xi), jax.tree.map(shape, rep)

    return pipeline
