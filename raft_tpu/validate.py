"""Input validation and device-side numeric checking.

The reference's equivalents are scattered inline guards (SURVEY.md §5):
NaN checks on BEM output (reference raft/raft_fowt.py:409-420), matrix
diagonal viability (raft_model.py:419-426), station-count checks
(raft_member.py:58-59), YAML shape validation in getFromDict
(helpers.py:456-516).  Here they are one subsystem:

 - ``validate_design(design)``: host-side structural validation of the
   design dict, returning a list of problem strings (raise_on_error=True
   turns them into one ValueError);
 - ``checked_pipeline(model)``: the case pipeline wrapped in
   ``jax.experimental.checkify`` float checks, so device-side NaN/Inf in
   the solve surfaces as a Python error with a location instead of
   silently propagating into the response statistics;
 - the ``RAFT_TPU_DEBUG_NANS=1`` environment switch (re-exported from
   raft_tpu.health): enables ``jax_debug_nans`` and makes the Model build
   the scan-based checkable fixed point, so a production run can be
   re-launched in NaN-hunting mode without a code change.
"""

import numpy as np

from raft_tpu.health import (            # noqa: F401  (re-exported API)
    DEBUG_NANS_ENV,
    apply_debug_nans,
    debug_nans_requested,
)


def _numeric(problems, label, value, cast=float):
    """Cast a design value, recording (instead of raising) on failure."""
    try:
        return cast(value)
    except (TypeError, ValueError):
        problems.append(f"{label}: not numeric: {value!r}")
        return None


def _check_member(mem, i, problems):
    name = mem.get("name", f"member {i}")
    try:
        stations = np.atleast_1d(np.asarray(mem.get("stations", []), float))
    except (TypeError, ValueError):
        problems.append(f"{name}: stations are not numeric")
        return
    if stations.size < 2:
        problems.append(f"{name}: needs >= 2 stations, got {stations.size}")
        return
    if not (np.diff(stations) >= 0).all():
        problems.append(f"{name}: stations must be non-decreasing")
    n = stations.size
    shape = str(mem.get("shape", "circ"))
    if shape.startswith("circ") and np.ndim(mem.get("d", 0.0)) == 1 \
            and len(np.atleast_1d(mem["d"])) not in (1, n):
        problems.append(
            f"{name}: {len(np.atleast_1d(mem['d']))} diameters for "
            f"{n} stations"
        )
    t = mem.get("t", None)
    if t is not None and np.ndim(t) == 1 and len(t) not in (1, n):
        problems.append(f"{name}: {len(t)} thicknesses for {n} stations")
    for key in ("l_fill", "rho_fill"):
        v = mem.get(key)
        if v is not None and np.ndim(v) == 1 and len(v) not in (1, n - 1):
            problems.append(
                f"{name}: {key} has {len(v)} entries for {n - 1} sections"
            )
    caps = mem.get("cap_stations")
    if caps is not None:
        for key in ("cap_t", "cap_d_in"):
            v = np.atleast_1d(mem.get(key, []))
            if len(v) not in (1, len(np.atleast_1d(caps))):
                problems.append(
                    f"{name}: {key} length does not match cap_stations"
                )


def validate_design(design, raise_on_error=True):
    """Structural validation of a design dict before Model construction."""
    problems = []
    for key in ("site", "turbine", "platform", "mooring"):
        if key not in design or design[key] is None:
            problems.append(f"missing top-level section '{key}'")
    site = design.get("site") or {}
    if "water_depth" not in site:
        problems.append("site.water_depth is required")
    else:
        depth = _numeric(problems, "site.water_depth", site["water_depth"])
        if depth is not None and depth <= 0:
            problems.append("site.water_depth must be positive")

    platform = design.get("platform") or {}
    members = platform.get("members") or []
    if not members:
        problems.append("platform.members is empty")
    for i, mem in enumerate(members):
        _check_member(mem, i, problems)
    turbine = design.get("turbine")
    if turbine is not None and not isinstance(turbine, dict):
        problems.append("turbine must be a mapping")
    elif isinstance(turbine, dict):  # present (even empty) -> needs tower
        if not turbine.get("tower"):
            problems.append("turbine.tower is required")
        else:
            _check_member(turbine["tower"], "tower", problems)

    cases = design.get("cases")
    if cases:
        keys = cases.get("keys", [])
        for j, row in enumerate(cases.get("data", [])):
            if len(row) != len(keys):
                problems.append(
                    f"cases.data row {j} has {len(row)} entries for "
                    f"{len(keys)} keys"
                )
            else:
                from raft_tpu.model import _SPECTRUM_CODES

                case = dict(zip(keys, row))
                spec = str(case.get("wave_spectrum", "unit"))
                if spec not in _SPECTRUM_CODES:
                    problems.append(
                        f"cases.data row {j}: unknown wave_spectrum '{spec}'"
                    )
                period = _numeric(
                    problems, f"cases.data row {j} wave_period",
                    case.get("wave_period", 1.0),
                )
                if period is not None and period <= 0:
                    problems.append(
                        f"cases.data row {j}: wave_period must be positive"
                    )

    mooring = design.get("mooring") or {}
    point_names = {p.get("name") for p in mooring.get("points", [])}
    for ln in mooring.get("lines", []):
        for end in ("endA", "endB"):
            if ln.get(end) not in point_names:
                problems.append(
                    f"mooring line {ln.get('name')}: {end} "
                    f"'{ln.get(end)}' is not a defined point"
                )

    if problems and raise_on_error:
        raise ValueError(
            "design validation failed:\n  - " + "\n  - ".join(problems)
        )
    return problems


def checked_pipeline(model):
    """The model's case pipeline wrapped in checkify float checks: calling
    the returned function raises on any device-side NaN/Inf with the
    failing operation's location (the TPU-native version of the
    reference's post-hoc NaN guards, raft/raft_fowt.py:409-420)."""
    import jax
    from jax.experimental import checkify

    # checkify cannot wrap a vmapped while_loop: the Model builds its
    # pipeline as vmap-of-checkify-of-(scan-based fixed point) when asked
    jitted = jax.jit(model.case_pipeline_fn(
        checkable=True,
        wrap=lambda f: checkify.checkify(f, errors=checkify.float_checks),
    ))

    def run(*args):
        err, out = jitted(*args)
        checkify.check_error(err)
        return out

    return run


def full_hull_convergence(design_path, backend="tpu", sizes=(2.0, 1.5),
                          nw=8, w_lo=0.25, w_hi=0.9, n_devices=None):
    """Two-mesh potential-flow convergence study of a full hull — the
    flagship VolturnUS-S verification anchor (no published IEA-15MW
    potential-flow tables ship with the reference mirror, so the solve is
    anchored by refinement; study recorded in docs/parity.md).  Shared by
    tests/test_reference_designs.py::test_volturnus_full_hull_mesh_convergence
    and bench.py's ``bem_conv_*`` block so the two cannot drift apart.

    Returns (sols, rel_A, rel_X) — the two solve dicts keyed
    "fine"/"xfine", the per-DOF max relative A-diagonal difference [6],
    and the max relative |X| difference for surge/heave/pitch [3]
    (measured where |X| carries ≥ 5% of its band maximum, so the
    near-zero crossings of the excitation do not inflate the ratio).
    """
    import numpy as np

    from raft_tpu.bem_solver import solve_bem
    from raft_tpu.io.schema import load_design
    from raft_tpu.mesh import mesh_platform
    from raft_tpu.model import Model

    d = load_design(design_path)
    d["turbine"]["aeroServoMod"] = 0
    d["platform"]["potModMaster"] = 2
    m = Model(d)
    mem = [mm for mm in m.members if mm.potMod]
    w = np.linspace(w_lo, w_hi, nw)
    sols = {}
    for tag, sz in zip(("fine", "xfine"), sizes):
        panels = mesh_platform(mem, dz_max=sz, da_max=sz)
        sols[tag] = solve_bem(panels, w, rho=m.rho_water, g=m.g,
                              backend=backend, depth=m.depth,
                              n_devices=n_devices)
    Af, Ax = sols["fine"]["A"], sols["xfine"]["A"]
    rel_A = [
        float(np.max(np.abs(Af[:, i, i] - Ax[:, i, i])
                     / np.abs(Ax[:, i, i])))
        for i in range(6)
    ]
    Xf = np.abs(sols["fine"]["X"][:, 0, :])     # beta = 0 heading
    Xx = np.abs(sols["xfine"]["X"][:, 0, :])
    rel_X = []
    for i in (0, 2, 4):                          # surge, heave, pitch
        sig = Xx[:, i] >= 0.05 * Xx[:, i].max()
        rel_X.append(float(np.max(
            np.abs(Xf[sig, i] - Xx[sig, i]) / Xx[sig, i])))
    return sols, rel_A, rel_X
