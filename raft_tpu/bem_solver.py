"""Native first-order radiation/diffraction panel solver (HAMS equivalent).

Replaces the reference's external Fortran BEM solver HAMS (subprocess at
reference raft/raft_fowt.py:367-395) with a device-portable
source-distribution panel method — one jitted XLA graph that runs on the
TPU when requested (``solve_bem(backend='tpu')`` / ``Model(device=...)``,
validated against the CPU results to ~1e-5) with a CPU default tuned for
one-shot meshes (the graph specializes on the mesh shape; see solve_bem):

  * constant-strength source panels on the wetted hull (meshed by
    raft_tpu/mesh.py),
  * free-surface Green function G = 1/r + 1/r' + Gw with the wave term Gw
    evaluated gather-free on TPU (exact Struve/Bessel oscillatory part +
    per-region Chebyshev remainders, greens.eval_F_F1_cheb; row-blocked
    assembly feeds the basis contractions to the MXU) and from
    precomputed regularized tables on CPU (raft_tpu/greens.py),
  * body boundary condition  sigma/2 + K sigma = v_n  solved on-device as
    the equivalent real 2N x 2N block system (the dense complex LU has no
    TPU lowering; real f32 LU does), lax.map'd over frequency — the
    per-frequency N^2 influence assembly is pure table-lookup + elementwise
    math, both MXU/VPU-friendly with static shapes; complex values never
    cross the host-device boundary (re/im split),
  * added mass A(w), radiation damping B(w) about the PRP from the radiation
    potentials, and wave excitation X(w, beta) from the diffraction solve
    (Haskind available as a cross-check in tests),
  * multi-device: per-frequency problems are independent (the WAMIT/HAMS
    per-omega formulation), so with >1 local device the [nw] frequency
    batch (or the flattened frequency x heading batch when nw alone
    would underfill) lays across a 1-D device mesh with an explicit
    NamedSharding — the same pattern sweep.py uses for the design axis —
    with automatic single-device fallback (see solve_bem / _run_sharded).

Time convention matches the reference (e^{+i w t}; impedance
Z = -w^2 M + i w B + C, reference raft/raft_model.py:585-590), so the wave
term uses the conjugate (outgoing H0^(2)) branch of the tabulated kernel.

Irregular frequencies are removed by the extended-boundary-condition
method (HAMS If_remove_irr_freq equivalent): the interior waterplane is
panelled AT z = 0 (mesh.lid_panels_from_mesh) and joins the system as a
rigid extension with the doubled-jump lid diagonal (LID_JUMP).  A
DISPLACED rigid lid (z = -0.4/-0.2 below the surface) was prototyped in
round 2 and rejected for placement-sensitive 1-10% errors; the z = 0 lid
works because the TPU kernel is exact at b -> 0 (the closed forms in
raft_tpu/greens.py).  The CPU path's bilinear table clamps lid-row
arguments to its b = -1e-5 log-grid floor, which carries up to ~1e-2
kernel error for close low-frequency pairs; the truncated-cylinder test
bounds the resulting valid-band bias at ~0.5-1.2% on CPU (vs ~0.3% on
TPU) — still an order of magnitude below the irregular-frequency glitch
it removes, but the TPU backend is the precision path for lidded solves.
Validated on the truncated-cylinder scan through the first glitches
(nu*a ~ 2.40 heave, 3.83 surge): both removed.  The mesh-resolution
frequency cap (max_resolved_omega) remains purely a panels-per-
wavelength limit, decoupled from the irregular band.
Finite water depth (the depth HAMS receives in its control file, reference
raft/raft_fowt.py:367-381) is handled as deep water + John's finite-depth
difference: a seabed-image Rankine term plus an exponentially-decaying
pole-subtracted quadrature correction to the wave term
(greens.finite_depth_correction) and the cosh-profile incident wave.
"""

from dataclasses import dataclass

import numpy as np

from raft_tpu import greens

_G_GAUSS = np.array([-1.0 / np.sqrt(3.0), 1.0 / np.sqrt(3.0)])


@dataclass
class PanelArrays:
    """Static panel geometry staged for device assembly."""

    cen: np.ndarray    # [N,3] collocation points (centroids)
    nrm: np.ndarray    # [N,3] outward normals (into fluid)
    area: np.ndarray   # [N]
    qpts: np.ndarray   # [N,Q,3] source-panel quadrature points
    qwts: np.ndarray   # [N,Q] quadrature weights (sum = area)

    @property
    def n(self):
        return len(self.area)


def panel_arrays(panels, quad="gauss"):
    """Build PanelArrays from [npan,4,3] vertex panels with 2x2 Gauss
    quadrature on the bilinear patch (exact for planar quads; robust for the
    clip-degenerate triangles).

    quad="centroid" builds single-point (centroid x area) quadrature.
    solve_bem uses it only for the smooth per-frequency wave term (the
    near-singular Rankine assembly always keeps the 2x2 Gauss points):
    ~2.4x faster assembly for design-loop preview solves at some accuracy
    cost (measured <= ~5% max added-mass error on the OC4 semi vs MARIN
    data before the Rankine part was exempted).
    """
    from raft_tpu.mesh import panel_geometry

    p = np.asarray(panels, float)
    cen, nrm, area = panel_geometry(p)
    if quad == "centroid":
        return PanelArrays(cen=cen, nrm=nrm, area=area,
                           qpts=cen[:, None, :], qwts=area[:, None])
    if quad != "gauss":
        raise ValueError(f"unknown quad {quad!r} (use 'gauss' or 'centroid')")
    a, b, c, d = p[:, 0], p[:, 1], p[:, 2], p[:, 3]
    qpts = np.empty((len(p), 4, 3))
    qwts = np.empty((len(p), 4))
    k = 0
    for u in _G_GAUSS:
        for v in _G_GAUSS:
            Nu = np.array([(1 - u) * (1 - v), (1 + u) * (1 - v),
                           (1 + u) * (1 + v), (1 - u) * (1 + v)]) / 4.0
            pt = (Nu[0, None] * a.T + Nu[1, None] * b.T
                  + Nu[2, None] * c.T + Nu[3, None] * d.T).T
            # Jacobian of the bilinear map at (u, v)
            dPu = ((-(1 - v)) * a + (1 - v) * b + (1 + v) * c
                   - (1 + v) * d) / 4.0
            dPv = ((-(1 - u)) * a - (1 + u) * b + (1 + u) * c
                   + (1 - u) * d) / 4.0
            J = np.linalg.norm(np.cross(dPu, dPv), axis=1)
            qpts[:, k] = pt
            qwts[:, k] = J  # Gauss weight 1x1 per point in 2x2 rule
            k += 1
    # normalize so weights sum exactly to the panel area
    scale = area / np.maximum(qwts.sum(axis=1), 1e-30)
    qwts *= scale[:, None]
    return PanelArrays(cen=cen, nrm=nrm, area=area, qpts=qpts, qwts=qwts)


def _concat_panel_arrays(pa, pb):
    """Concatenate two PanelArrays along the panel axis."""
    return PanelArrays(
        cen=np.concatenate([pa.cen, pb.cen]),
        nrm=np.concatenate([pa.nrm, pb.nrm]),
        area=np.concatenate([pa.area, pb.area]),
        qpts=np.concatenate([pa.qpts, pb.qpts]),
        qwts=np.concatenate([pa.qwts, pb.qwts]),
    )


def pad_panel_arrays(pa, multiple=256):
    """Pad a PanelArrays to the next multiple of ``multiple`` with exactly
    inert dummy entries: zero area, zero quadrature weight, zero normal,
    collocation/quadrature points parked far from the hull at mid-draft.

    Zero normals null the dummy rows' influence integrals and radiation /
    diffraction right-hand sides (their equations reduce to
    -sigma/2 = 0), zero weights null their columns, and zero areas null
    their contribution to every output integral — so padding changes the
    coefficients only through floating-point summation of explicit zeros.

    Two purposes on the TPU backend: mesh-size bucketing (compiled
    executables are reused across designs whose meshes land in the same
    bucket — the reference regenerates HAMS runs per design with no such
    reuse, reference raft/raft_fowt.py:318-423) and the 512-row block
    multiple the large-N blocked solve requires."""
    n = pa.n
    nb = -(-n // multiple) * multiple
    if nb == n:
        return pa
    pad = nb - n
    span = float(np.max(np.abs(pa.cen[:, :2]))) if n else 1.0
    z_mid = min(-1.0, 0.5 * float(np.min(pa.cen[:, 2])))
    far = np.array([50.0 * max(span, 1.0), 0.0, z_mid])
    Q = pa.qpts.shape[1]
    return PanelArrays(
        cen=np.concatenate([pa.cen, np.tile(far, (pad, 1))]),
        nrm=np.concatenate([pa.nrm, np.zeros((pad, 3))]),
        area=np.concatenate([pa.area, np.zeros(pad)]),
        qpts=np.concatenate([pa.qpts, np.tile(far, (pad, Q, 1))]),
        qwts=np.concatenate([pa.qwts, np.zeros((pad, Q))]),
    )


def _rankine(pa, dtype=np.float64, depth=np.inf, lid_mask=None):
    """Frequency-independent Rankine + image influence matrices (host, once).

    S0[i,j] = int_j (1/r + 1/r') dS,   K0[i,j] = int_j d/dn_i (1/r + 1/r') dS

    Off-diagonal by source-panel quadrature; the self 1/r potential uses the
    equivalent-disc closed form int 1/r dS = 2 sqrt(pi A), and the flat-panel
    self normal-gradient principal value is zero (the 1/2 jump term appears
    explicitly in the boundary condition).

    At finite ``depth`` the seabed image 1/r2 (source mirrored across
    z = -h) joins the static part — John's finite-depth Green function is
    G = 1/r + 1/r2 + wave integral (the wave-term difference evaluated by
    greens.finite_depth_correction cancels it again as nu*h grows).
    """
    x = pa.cen.astype(dtype)
    n = pa.nrm.astype(dtype)
    y = pa.qpts.astype(dtype)
    w = pa.qwts.astype(dtype)
    N = pa.n

    # row-chunked assembly: the [chunk,N,Q,3] pairwise temp stays bounded
    # (~0.8 GB at f64) however large the mesh gets
    Q = y.shape[1]
    chunk = max(1, int(3.2e7 // max(N * Q, 1)))

    def img(yq):
        S = np.empty((N, N), dtype)
        K = np.empty((N, N), dtype)
        for i0 in range(0, N, chunk):
            i1 = min(i0 + chunk, N)
            dxi = x[i0:i1, None, None, :] - yq[None, :, :, :]  # [c,N,Q,3]
            ri = np.maximum(np.sqrt(np.sum(dxi * dxi, axis=-1)), 1e-9)
            S[i0:i1] = np.sum(w[None] / ri, axis=-1)
            K[i0:i1] = -np.sum(
                w[None] * np.einsum("ijqk,ik->ijq", dxi, n[i0:i1]) / ri**3,
                axis=-1,
            )
        return S, K

    S_r, K_r = img(y)
    yi = y.copy()
    yi[:, :, 2] *= -1.0                                   # free-surface image
    S_i, K_i = img(yi)

    idx = np.arange(N)
    S_r[idx, idx] = 2.0 * np.sqrt(np.pi * pa.area)
    K_r[idx, idx] = 0.0
    if lid_mask is not None and np.any(lid_mask):
        # the free-surface image of a z=0 lid panel IS the panel: its
        # image-self entry takes the same closed-form potential and the
        # flat-panel zero PV (the generic quadrature would integrate its
        # own clamped near-singularity instead)
        li = np.where(lid_mask)[0]
        S_i[li, li] = 2.0 * np.sqrt(np.pi * pa.area[li])
        K_i[li, li] = 0.0
    S0, K0 = S_r + S_i, K_r + K_i
    if np.isfinite(depth):
        yb = y.copy()
        yb[:, :, 2] = -2.0 * depth - yb[:, :, 2]          # seabed image
        S_b, K_b = img(yb)
        S0 += S_b
        K0 += K_b
    return S0, K0


def _radiation_normals(pa):
    """v[k, i]: normal velocity on panel i for unit velocity in DOF k about
    the PRP (origin): n for surge/sway/heave, (r x n) for roll/pitch/yaw."""
    rxn = np.cross(pa.cen, pa.nrm)
    return np.concatenate([pa.nrm.T, rxn.T], axis=0)  # [6, N]


def _gj_stage(A, b, kb0, nblk, block=512):
    """Run ``nblk`` consecutive elimination steps (starting at block row
    ``kb0``) of the blocked Gauss-Jordan on the in-progress system
    ``(A, b)``.  ``kb0``/``nblk`` may be traced scalars, so ONE compiled
    executable serves every stage of a staged (multi-dispatch)
    elimination — the streamed path's solve-stage banding.

    With ``RAFT_TPU_PALLAS`` set (default off) the stage routes through
    the hand-written Pallas kernels (raft_tpu/pallas_kernels.py:
    in-VMEM pivot-tile inversion + tiled matmul-subtract updates);
    otherwise this generic XLA body runs bit-for-bit unchanged."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.pallas_kernels import gj_stage_pallas, pallas_enabled

    if pallas_enabled():
        return gj_stage_pallas(A, b, kb0, nblk, block=block)

    n = A.shape[0]
    m = b.shape[1]
    assert n % block == 0, (n, block)
    rowidx = jnp.arange(n)

    def step(kb, carry):
        A, b = carry
        k0 = kb * block
        D = jax.lax.dynamic_slice(A, (k0, 0), (block, n))
        Db = jax.lax.dynamic_slice(b, (k0, 0), (block, m))
        Dinv = jnp.linalg.inv(
            jax.lax.dynamic_slice(A, (k0, k0), (block, block))
        )
        Arow = Dinv @ D                                     # [block, n]
        brow = Dinv @ Db                                    # [block, m]
        C = jax.lax.dynamic_slice(A, (0, k0), (n, block))   # [n, block]
        mask = ((rowidx >= k0) & (rowidx < k0 + block))[:, None]
        C = jnp.where(mask, 0.0, C)
        A = A - C @ Arow
        b = b - C @ brow
        A = jax.lax.dynamic_update_slice(A, Arow, (k0, 0))
        b = jax.lax.dynamic_update_slice(b, brow, (k0, 0))
        return A, b

    return jax.lax.fori_loop(kb0, kb0 + nblk, step, (A, b))


def _blocked_gj(A, b, block=512):
    """Solve ``A x = b`` for a well-conditioned dense real system by
    blocked Gauss-Jordan elimination: per-step pivot-block inversion
    (jnp.linalg.inv on [block, block] tiles) + full-matrix matmul updates
    (the step body lives in :func:`_gj_stage` so the streamed path can
    split the same elimination across watchdog-sized dispatches).

    Every O(n^3) flop is an MXU matmul and no LU custom call ever exceeds
    ``block`` rows — this is what lets the TPU backend solve past the
    LuDecompositionBlock scoped-VMEM ceiling (observed on v5e: clean
    compile failure at 16k rows, runtime worker crash at 5800 rows; the
    reference's external solver HAMS runs arbitrary mesh sizes,
    reference raft/raft_fowt.py:391).

    No inter-block pivoting (rows pivot only inside each tile's LU): valid
    because the BEM boundary operator -1/2 I + K/4pi is a compact
    perturbation of -1/2 I, so every leading Schur complement stays
    uniformly invertible at practical mesh densities (validated against
    the complex-LU CPU path in tests/test_bem_solver.py).

    A : [n, n] with n a multiple of ``block``; b : [n, m].  Returns x.
    """
    _, x = _gj_stage(A, b, 0, A.shape[0] // block, block=block)
    return x


def _wave_rows(nu, k0, xc, nc_, y, w_q, tables, depth, kmax_geom, finite):
    """Wave-term influence rows for a collocation chunk: [RB,3] collocation
    points/normals against the full quadrature set -> (Sw, Kw) [RB,N] c64.
    Shared by the in-graph assembly (_solve_all) and the streamed
    large-mesh band assembly (_solve_streamed)."""
    import jax.numpy as jnp

    cheb = isinstance(tables, dict)
    Rh = jnp.sqrt((xc[:, None, None, 0] - y[None, :, :, 0]) ** 2
                  + (xc[:, None, None, 1] - y[None, :, :, 1]) ** 2)
    zz = xc[:, None, None, 2] + y[None, :, :, 2]
    ex = (xc[:, None, None, 0] - y[None, :, :, 0]) / jnp.maximum(Rh, 1e-9)
    ey = (xc[:, None, None, 1] - y[None, :, :, 1]) / jnp.maximum(Rh, 1e-9)
    if cheb:
        Gw, dGw_dR, dGw_dz = greens.wave_term_cheb(nu, Rh, zz, tables)
    else:
        Gw, dGw_dR, dGw_dz = greens.wave_term(nu, Rh, zz, *tables)
    if finite:
        # finite-depth wave-term difference (John's G minus the deep
        # tabulated part; the seabed-image Rankine term is already in
        # S0/K0 from _rankine)
        dGc, dRc, dzc = greens.finite_depth_correction(
            nu, k0, depth,
            Rh, xc[:, None, None, 2], y[None, :, :, 2], kmax_geom,
        )
        Gw = Gw + dGc
        dGw_dR = dGw_dR + dRc
        dGw_dz = dGw_dz + dzc
    # e^{+iwt} convention: conjugate branch (outgoing waves)
    Gw = jnp.conj(Gw)
    dGw_dR = jnp.conj(dGw_dR)
    dGw_dz = jnp.conj(dGw_dz)
    Sw = jnp.sum(w_q[None] * Gw, axis=-1)
    Kw = jnp.sum(
        w_q[None] * (dGw_dR * (ex * nc_[:, None, None, 0]
                               + ey * nc_[:, None, None, 1])
                     + dGw_dz * nc_[:, None, None, 2]),
        axis=-1,
    )
    return Sw, Kw


def _solve_all(omegas, betas, x, nrm, area, y, w_q, S0, K0, vmodes, jump,
               tables, g, rho, real_block, depth, kmax_geom, finite):
    """Device solve over all frequencies (jit target; see solve_bem).

    All inputs/outputs are real f32 (complex never crosses the host-device
    boundary — TPU constraint); complex64 exists only inside the graph.
    With ``real_block`` the per-frequency dense complex system is solved
    as the equivalent real 2N x 2N block system
    [[Kr, -Ki], [Ki, Kr]] [sr; si] = [br; bi] (the dense complex LU has
    no TPU lowering; real f32 LU does); backends with a complex LU (CPU)
    use the plain c64 solve at half the flops/memory.  Frequencies are
    processed by lax.map so one influence assembly is live at a time.

    ``tables`` selects the wave-term kernel: a dict of Chebyshev patch
    coefficients (greens.load_cheb_tables) runs the gather-free evaluation
    — the TPU path, where table gathers dominate assembly time — and the
    assembly is row-blocked (lax.map over collocation chunks) so the
    Chebyshev basis matmuls stay in modest [E, deg] blocks; a (F, F1)
    tuple (greens.load_tables) runs the bilinear-lookup kernel in one
    whole-matrix sweep — the CPU path, where gathers are cheap.

    ``betas`` [nbeta] is shared by every frequency; a 2-D ``betas``
    [nw, nbeta] maps a heading row alongside each frequency — the
    flattened frequency x heading layout the multi-device sharding uses
    when nw alone would underfill the mesh (see solve_bem).
    """
    import jax
    import jax.numpy as jnp

    c = jnp.complex64
    N = x.shape[0]
    cheb = isinstance(tables, dict)
    # row-block size: TPU meshes are padded to multiples of 256; CPU (and
    # odd sizes) assemble in one sweep like before
    RB = 32 if (cheb and N % 32 == 0) else N
    nblk = N // RB

    # `finite` is the only static piece of the depth handling — depth and
    # kmax_geom stay traced operands so a draft/depth sweep at a fixed
    # mesh shape reuses one compiled executable
    def one_omega(omega, bet):
        nu = omega * omega / g
        k0 = greens.dispersion_k0(nu, depth) if finite else nu

        def assemble(xc, nc_):
            return _wave_rows(nu, k0, xc, nc_, y, w_q, tables, depth,
                              kmax_geom, finite)

        if nblk == 1:
            Sw, Kw = assemble(x, nrm)
        else:
            Sw, Kw = jax.lax.map(
                lambda args: assemble(*args),
                (x.reshape(nblk, RB, 3), nrm.reshape(nblk, RB, 3)),
            )
            Sw = Sw.reshape(N, N)
            Kw = Kw.reshape(N, N)

        S = S0.astype(c) + Sw
        K = K0.astype(c) + Kw
        return _post_assembly(omega, nu, k0, S, K, bet, x, nrm, area,
                              vmodes, jump, g, rho, real_block, depth,
                              finite)

    # TPU f32 matmuls default to bf16 passes; the influence sums and the
    # block solve need the full f32 path
    with jax.default_matmul_precision("highest"):
        if betas.ndim == 2:
            return jax.lax.map(lambda ob: one_omega(*ob), (omegas, betas))
        return jax.lax.map(lambda om: one_omega(om, betas), omegas)


def _incident_wave(omega, nu, k0, betas, x, nrm, g, depth, finite):
    """Incident-wave potential phiI [nb, N] and its normal derivative
    dphiIdn [nb, N] at the collocation points; finite depth uses the
    cosh-profile incident wave at wavenumber k0 (written in decaying
    exponentials; reduces to e^{nu z} as k0 h -> inf)."""
    import jax.numpy as jnp

    cosb = jnp.cos(betas)[:, None]
    sinb = jnp.sin(betas)[:, None]
    kx = x[None, :, 0] * cosb + x[None, :, 1] * sinb          # [nb,N]
    if finite:
        Eh = jnp.exp(-2.0 * k0 * depth)
        e2z = jnp.exp(-2.0 * k0 * (x[None, :, 2] + depth))
        amp = jnp.exp(k0 * x[None, :, 2]) / (1.0 + Eh)
        phiI = ((1j * g / omega) * amp * (1.0 + e2z)
                * jnp.exp(-1j * k0 * kx))
        phiIz = ((1j * g / omega) * k0 * amp * (1.0 - e2z)
                 * jnp.exp(-1j * k0 * kx))
    else:
        phiI = ((1j * g / omega) * jnp.exp(nu * x[None, :, 2])
                * jnp.exp(-1j * nu * kx))
        phiIz = nu * phiI
    dphiIdn = (-1j * k0 * cosb * phiI * nrm[None, :, 0]
               - 1j * k0 * sinb * phiI * nrm[None, :, 1]
               + phiIz * nrm[None, :, 2])
    return phiI, dphiIdn


def _real_block_system(lhs, rhs):
    """The equivalent real 2N x 2N block system of the dense complex
    system lhs sigma = rhs: [[Kr, -Ki], [Ki, Kr]] [sr; si] = [br; bi]
    (the dense complex LU has no TPU lowering; real f32 LU does)."""
    import jax.numpy as jnp

    Ar, Ai = jnp.real(lhs), jnp.imag(lhs)
    A2 = jnp.concatenate(
        [jnp.concatenate([Ar, -Ai], axis=1),
         jnp.concatenate([Ai, Ar], axis=1)], axis=0,
    )                                                          # [2N,2N]
    b2 = jnp.concatenate([jnp.real(rhs), jnp.imag(rhs)], axis=1).T
    return A2, b2


def _integrate_outputs(omega, sigma, S, phiI, area, vmodes, rho):
    """Pressure-integral tail shared by every solve path: source strengths
    sigma [6+nb, N] -> (A, B, Xr, Xi) f32 for one frequency."""
    import jax.numpy as jnp

    f = jnp.float32
    phi = sigma @ (S.T / (4 * jnp.pi))                         # [6+nb,N]

    # radiation coefficients: rho int phi_k n_i dS = -A_ik + i B_ik / w
    P = rho * (phi[:6] * area[None]) @ vmodes.T                # [6k,6i]
    A = -jnp.real(P).T
    B = omega * jnp.imag(P).T

    # excitation per unit amplitude: F_i = i w rho int (phiI+phiS) n_i dS
    phiT = phi[6:] + phiI
    X = 1j * omega * rho * (phiT * area[None]) @ vmodes.T
    return A.astype(f), B.astype(f), jnp.real(X).astype(f), \
        jnp.imag(X).astype(f)


def _post_assembly(omega, nu, k0, S, K, betas, x, nrm, area, vmodes, jump,
                   g, rho, real_block, depth, finite):
    """From assembled influence matrices to (A, B, Xr, Xi) for one
    frequency (the solve + pressure-integral tail of _solve_all's
    one_omega; shared with the streamed large-mesh path)."""
    import jax.numpy as jnp

    c = jnp.complex64
    N = x.shape[0]
    # exterior (fluid-side) limit of the single-layer normal derivative:
    # dphi/dn = jump*sigma + K' sigma with jump = -1/2 on body rows
    # (pulsating-sphere eigenvalue check K'[1] = -1/2 fixes the sign;
    # see tests/test_bem_solver.py) and LID_JUMP on interior
    # free-surface rows (their coincident image doubles the layer)
    lhs = K / (4 * jnp.pi) + jnp.diag(jump).astype(c)

    # radiation RHS (unit velocity) + diffraction RHS per heading
    phiI, dphiIdn = _incident_wave(omega, nu, k0, betas, x, nrm, g,
                                   depth, finite)
    rhs = jnp.concatenate([vmodes.astype(c), -dphiIdn], axis=0)  # [6+nb,N]
    if real_block:
        A2, b2 = _real_block_system(lhs, rhs)
        if N > 1024 and (2 * N) % 512 == 0:
            # past the TPU LU custom call's comfort zone: blocked
            # Gauss-Jordan, all matmuls (padding in solve_bem
            # guarantees the 512-row block multiple)
            sol = _blocked_gj(A2, b2, block=512)               # [2N,6+nb]
        else:
            sol = jnp.linalg.solve(A2, b2)                     # [2N,6+nb]
        sigma = (sol[:N] + 1j * sol[N:]).T                     # [6+nb,N]
    else:
        sigma = jnp.linalg.solve(lhs, rhs.T).T                 # [6+nb,N]
    return _integrate_outputs(omega, sigma, S, phiI, area, vmodes, rho)


# jitted streamed-path executables cached at module level, keyed on
# (D, rows, N, finite) plus the physics scalars baked into the closures —
# mirroring _solve_all_jit, so repeat streamed solves of the same mesh
# shape reuse warm programs instead of rebuilding fresh jax.jit wrappers
# (and recompiling) every call (ADVICE r5)
_stream_fn_cache = {}


def _streamed_fns(D, rows, N, finite, g, rho, rb=32):
    """The four jitted stages of the streamed out-of-core path for one
    (band count, band rows, mesh size, depth regime) configuration:

      band(omega, xb, nb_, y, w_q, tables, depth, kmax) -> 4 x [rows, N]
          wave-term influence rows of one collocation band (f32 re/im;
          complex never crosses the host-device boundary),
      system(omega, betas, x, nrm, S0, K0, vmodes, jump, depth, *bands)
          -> (A2, b2, Sf_r, Sf_i, phiI_r, phiI_i): concatenates the
          bands (donated — XLA may alias their memory straight into the
          full matrices) and assembles the real 2N x 2N block system,
      stage(A2, b2, kb0, nblk): ``nblk`` blocked Gauss-Jordan steps
          (traced bounds — one executable serves every stage; A2/b2
          donated so the elimination ping-pongs two HBM buffers),
      finish(omega, sol, Sf_r, Sf_i, phiI_r, phiI_i, area, vmodes)
          -> (A, B, Xr, Xi): source strengths to coefficients.
    """
    import jax
    import jax.numpy as jnp

    key = (D, rows, N, finite, float(g), float(rho), rb)
    hit = _stream_fn_cache.get(key)
    if hit is not None:
        return hit

    def band(omega, xb, nb_, y, w_q, tables, depth, kmax_geom):
        nu = omega * omega / g
        k0 = greens.dispersion_k0(nu, depth) if finite else nu
        nbd = xb.shape[0]
        nblk = nbd // rb

        def rows_fn(args):
            return _wave_rows(nu, k0, args[0], args[1], y, w_q, tables,
                              depth, kmax_geom, finite)

        with jax.default_matmul_precision("highest"):
            Sw, Kw = jax.lax.map(
                rows_fn,
                (xb.reshape(nblk, rb, 3), nb_.reshape(nblk, rb, 3)))
        Nf = y.shape[0]
        Sw = Sw.reshape(nbd, Nf)
        Kw = Kw.reshape(nbd, Nf)
        return (jnp.real(Sw), jnp.imag(Sw), jnp.real(Kw), jnp.imag(Kw))

    def system(omega, betas, x, nrm, S0, K0, vmodes, jump, depth, *bands):
        Sr = jnp.concatenate(bands[:D])
        Si = jnp.concatenate(bands[D:2 * D])
        Kr = jnp.concatenate(bands[2 * D:3 * D])
        Ki = jnp.concatenate(bands[3 * D:])
        c = jnp.complex64
        S = S0.astype(c) + (Sr + 1j * Si)
        K = K0.astype(c) + (Kr + 1j * Ki)
        nu = omega * omega / g
        k0 = greens.dispersion_k0(nu, depth) if finite else nu
        lhs = K / (4 * jnp.pi) + jnp.diag(jump).astype(c)
        phiI, dphiIdn = _incident_wave(omega, nu, k0, betas, x, nrm, g,
                                       depth, finite)
        rhs = jnp.concatenate([vmodes.astype(c), -dphiIdn], axis=0)
        with jax.default_matmul_precision("highest"):
            A2, b2 = _real_block_system(lhs, rhs)
        return (A2, b2, jnp.real(S), jnp.imag(S),
                jnp.real(phiI), jnp.imag(phiI))

    def stage(A2, b2, kb0, nblk):
        with jax.default_matmul_precision("highest"):
            return _gj_stage(A2, b2, kb0, nblk, block=512)

    def finish(omega, sol, Sf_r, Sf_i, phiI_r, phiI_i, area, vmodes):
        Nn = Sf_r.shape[0]
        sigma = (sol[:Nn] + 1j * sol[Nn:]).T               # [6+nb,N]
        S = Sf_r + 1j * Sf_i
        phiI = phiI_r + 1j * phiI_i
        with jax.default_matmul_precision("highest"):
            return _integrate_outputs(omega, sigma, S, phiI, area, vmodes,
                                      rho)

    hit = (
        jax.jit(band),
        jax.jit(system, donate_argnums=tuple(range(9, 9 + 4 * D))),
        jax.jit(stage, donate_argnums=(0, 1)),
        jax.jit(finish),
    )
    _stream_fn_cache[key] = hit
    return hit


_solve_all_jit = None

# frequency-independent Rankine matrices keyed by (mesh bytes, depth) —
# raw bytes, not hash(), so distinct meshes can never collide; FIFO bound
# by total byte budget (each entry is two [N,N] f32 matrices)
_rankine_cache = {}
_RANKINE_CACHE_BYTES = 256 * 1024 * 1024

# The TPU LU custom-call has a scoped-VMEM ceiling (observed on v5e:
# clean compile failure at 2N=16k rows, runtime worker crash at 2N=5800,
# i.e. ~2900 panels); above 1024 panels the solve switches to the blocked
# Gauss-Jordan (_blocked_gj), which has no such ceiling.  The limits now:
#  * HBM: the assembly is row-blocked (RB=32 chunks), so the live set is
#    the [N,N] matrices — S0/K0 (f32) + S/K/lhs (c64) + the 2Nx2N real
#    block system and its Gauss-Jordan double buffer, ~6 GB at N=8960
#    against v5e's 16 GB — HBM would cap N around ~12k;
#  * the axon tunnel's per-dispatch execution watchdog (~60-70 s) binds
#    FIRST: one frequency costs ~(N/4864)^2 * 11 s on-device, so ~10k
#    panels (~50 s/frequency) is the single-dispatch ceiling in this
#    harness.  solve_bem already chunks multi-frequency requests to stay
#    under it.
# Above the limit solve_bem switches to the STREAMED out-of-core path
# (_run_streamed): the per-frequency assembly is split into row bands,
# each its own dispatch (device arrays persist in HBM between
# dispatches), followed by a system-assembly dispatch, >= 2 staged
# blocked-Gauss-Jordan solve dispatches (the O((2N)^3) elimination is
# ~6 s/frequency at the 16k-panel ceiling and grows cubically — it gets
# banded like the assembly), and a pressure-integral dispatch — removing
# the dispatch-time ceiling so mesh size is bounded by HBM (~16k panels
# on 16 GB), like HAMS is bounded by host memory.
TPU_PANEL_LIMIT = 10240


# jitted multi-device (shard_map) solve executables keyed on the device
# set + physics statics; jit's own cache handles array shapes
_sharded_fn_cache = {}


def _sharded_solve_fn(mesh, g, rho, real_block, finite, betas_mapped):
    """Jitted shard_map wrapper of _solve_all laying the frequency batch
    across ``mesh``'s 'freq' axis — the same NamedSharding pattern that
    shards the design axis in sweep.py.  Per-frequency solves are
    independent (WAMIT/HAMS-style per-omega problems), so each device
    runs its frequency shard's lax.map with zero communication.

    ``betas_mapped`` selects the flattened frequency x heading layout:
    betas then carries a per-frequency heading row [n, 1] sharded
    alongside omegas instead of a replicated [nbeta] vector."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    key = (tuple(mesh.devices.flat), float(g), float(rho),
           bool(real_block), bool(finite), bool(betas_mapped))
    hit = _sharded_fn_cache.get(key)
    if hit is not None:
        return hit

    def body(om, bet, x, nrm, area, y, wq, S0, K0, vmodes, jump, tables,
             depth, kmax):
        return _solve_all(om, bet, x, nrm, area, y, wq, S0, K0, vmodes,
                          jump, tables, g, rho, real_block, depth, kmax,
                          finite)

    spec_b = P("freq") if betas_mapped else P()
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P("freq"), spec_b) + (P(),) * 12,
        out_specs=P("freq"),
    )
    hit = jax.jit(fn)
    _sharded_fn_cache[key] = hit
    return hit


def _run_sharded(omegas, betas, static_pre, mesh, mode, n, report_cost):
    """Multi-device execution of the batched solve: frequencies (or, in
    'freqbeta' mode, flattened frequency x heading pairs) are laid across
    the device mesh, repeat-padded to fill every shard, and dispatched in
    watchdog-sized chunks exactly like the single-device path — each
    dispatch now solves n_devices shards concurrently.

    Returns (A, B, Xr, Xi, flops) host arrays in the caller's layout
    (A/B [nw,6,6]; Xr/Xi [nw, nbeta, 6]); flops is None unless
    ``report_cost``."""
    import jax

    from raft_tpu.utils.placement import batch_sharding

    (betas_d, x_d, nrm_d, area_d, y_d, wq_d, S0_d, K0_d, vmodes_d,
     jump_d, tables_d, g, rho, real_block, depth_d, kmax_d,
     finite) = static_pre

    n_dev = int(mesh.devices.size)
    sh = batch_sharding(mesh, "freq")
    omegas = np.atleast_1d(np.asarray(omegas, float))
    nw = len(omegas)
    betas_mapped = mode == "freqbeta"
    if betas_mapped:
        # underfilled frequency axis: solve (omega, heading) pairs, one
        # heading per lane (the radiation part is recomputed per lane —
        # the utilization trade this mode exists for)
        nb = len(betas)
        items_om = np.repeat(omegas, nb)
        items_bet = np.tile(np.asarray(betas, float), nw)[:, None]
    else:
        items_om = omegas
        items_bet = None
    n_items = len(items_om)

    fn = _sharded_solve_fn(mesh, g, rho, real_block, finite, betas_mapped)

    # per-DEVICE dispatch budget: each dispatch runs chunk_dev
    # frequencies per device concurrently, so the wall-clock per dispatch
    # is chunk_dev * per_freq_s regardless of n_dev
    chunk_dev = int(np.ceil(n_items / n_dev))
    if real_block:
        per_freq_s = max((n / 4864.0) ** 2 * 11.0, 1e-3)
        if chunk_dev * per_freq_s > 45.0:
            chunk_dev = max(1, int(45.0 / per_freq_s))
    chunk_total = chunk_dev * n_dev

    parts = []
    last_args = None
    for i in range(0, n_items, chunk_total):
        om = items_om[i:i + chunk_total]
        bet = items_bet[i:i + chunk_total] if betas_mapped else None
        if len(om) < chunk_total:      # repeat-pad: same compiled shape
            padn = chunk_total - len(om)
            om = np.concatenate([om, np.repeat(om[-1:], padn)])
            if betas_mapped:
                bet = np.concatenate(
                    [bet, np.repeat(bet[-1:], padn, axis=0)])
        om_d = jax.device_put(np.asarray(om, np.float32), sh)
        bet_d = (jax.device_put(np.asarray(bet, np.float32), sh)
                 if betas_mapped else betas_d)
        last_args = (om_d, bet_d, x_d, nrm_d, area_d, y_d, wq_d, S0_d,
                     K0_d, vmodes_d, jump_d, tables_d, depth_d, kmax_d)
        parts.append(fn(*last_args))
    A, B, Xr, Xi = (
        np.concatenate([np.asarray(p[j]) for p in parts])[:n_items]
        for j in range(4)
    )
    if betas_mapped:
        nb = len(betas)
        A = A[::nb]                     # radiation: one copy per omega
        B = B[::nb]
        Xr = Xr[:, 0, :].reshape(nw, nb, 6)
        Xi = Xi[:, 0, :].reshape(nw, nb, 6)

    flops = None
    if report_cost:
        from raft_tpu.utils.profiling import compiled_flops

        flops = compiled_flops(fn, last_args) * (n_items / chunk_total)
    return A, B, Xr, Xi, flops


# lid-row jump coefficient of the extended integral equation: the
# free-surface image of a z=0 panel coincides with the panel (doubling
# the effective layer) and the collocation limit approaches from the
# interior side, flipping the sign relative to a body row's -1/2.
# Selected from the +-1/2, +-1 candidates by the truncated-cylinder
# irregular-frequency scan (tests/test_bem_solver.py): +1 removes the
# nu*a ~ 2.40/3.83 glitches (J0/J1 zeros) cleanly and leaves the valid
# band within ~0.3% of the lid-free solve; -1/2 and -1 made the
# irregular behavior worse.
LID_JUMP = 1.0


def solve_bem(panels, omegas, betas=(0.0,), rho=1025.0, g=9.81,
              quad="gauss", backend=None, depth=np.inf, lid_panels=None,
              report_cost=False, n_devices=None):
    """Radiation + diffraction solve over frequencies.

    panels : [npan,4,3] wetted-hull panels (outward normals)
    lid_panels : optional [nlid,4,3] interior free-surface panels at z=0
        (mesh.lid_panels_from_mesh) — the extended-boundary-condition
        irregular-frequency removal: lids join the system as rigid
        extensions (zero radiation normal velocity, diffraction forced
        like body panels) but are excluded from the pressure-force
        integrals, displacing the interior-problem eigenfrequencies out
        of the wave band (HAMS If_remove_irr_freq equivalent, reference
        raft/raft_fowt.py:381).
    omegas : [nw] rad/s;  betas : wave headings [rad]
    depth : water depth [m] (np.inf = deep water).  Finite depth adds the
        seabed-image Rankine term, the John wave-term correction
        (greens.finite_depth_correction), and the cosh-profile incident
        wave; it requires the hull to float clear of the seabed.
    backend : 'tpu' | 'cpu' | None — device the batched solve runs on.
        None = CPU: the solve specializes on the mesh shape, and a TPU
        compile of the [N,N,Q] assembly graph takes minutes per shape
        (vs seconds on CPU) — worth paying only when the same mesh is
        re-solved (persistent compilation cache makes later processes
        warm; a warm TPU solve measures ~1.3-4.6x faster than CPU).
        Meshes above TPU_PANEL_LIMIT panels fall back to CPU.
    n_devices : int | None — cap on the local devices the frequency batch
        is sharded over (None = all local devices of the backend; 1
        forces the single-device path).  With >1 devices and enough
        frequencies to fill them, the [nw] batch is laid across a 1-D
        'freq' mesh with an explicit NamedSharding (the sweep.py
        pattern); when nw alone would underfill the mesh but nw * nbeta
        fills it, the flattened frequency x heading batch is sharded
        instead.  Falls back to the single-device path automatically
        when neither fills the mesh, when only one device exists, or on
        the streamed out-of-core path.
    Returns dict with A [nw,6,6], B [nw,6,6] and X [nw, nbeta, 6] complex
    (excitation per unit wave amplitude, e^{+iwt} convention, PRP-referenced).
    """
    import jax

    global _solve_all_jit

    pa = panel_arrays(panels)        # 2x2 Gauss for the singular Rankine part
    n_body = pa.n
    has_lid = lid_panels is not None and len(lid_panels) > 0
    if has_lid:
        pa = _concat_panel_arrays(pa, panel_arrays(lid_panels))
    n_real = pa.n
    depth = float(depth)
    # keel depth from panel VERTICES — centroids sit up to half a panel
    # above the keel, which would under-estimate the decay-rate cutoff
    # and let a near-bottom hull slip past the clearance guard
    draft = float(-np.min(np.asarray(panels, float)[:, :, 2]))
    if np.isfinite(depth):
        if depth <= draft * 1.02:
            raise ValueError(
                f"solve_bem: water depth {depth} m does not clear the hull "
                f"draft {draft} m (bottom-sitting structures are out of "
                "scope for the finite-depth wave correction)"
            )
        kmax_geom = 15.0 / (depth - draft)
    else:
        kmax_geom = 0.0
    streamed = bool(backend == "tpu" and pa.n > TPU_PANEL_LIMIT)
    if streamed:
        from raft_tpu.utils.profiling import logger

        logger.info(
            "solve_bem: %d panels exceeds the single-dispatch ceiling "
            "(%d); using the streamed out-of-core path (multi-dispatch "
            "band assembly + staged solve dispatches per frequency)",
            pa.n, TPU_PANEL_LIMIT,
        )
    backend = backend or "cpu"
    # the TPU LU lowering is real-only; CPU (and GPU) have complex LU,
    # which halves the solve flops and peak memory
    real_block = backend == "tpu"
    if real_block:
        # bucket the mesh size (compile reuse across designs) and give the
        # blocked large-N solve its 512-row block multiple
        pa = pad_panel_arrays(pa)
    # lid rows: everything past the body panels, up to the bucket padding
    # (dummy pad entries keep the body jump; their rows are inert anyway)
    lid_mask = np.zeros(pa.n, bool)
    lid_mask[n_body:n_real] = True
    jump = np.where(lid_mask, LID_JUMP, -0.5)
    # the frequency-independent Rankine assembly is ~0.6-0.8 s of host
    # time per call at ~850 panels; repeated solves of the same mesh
    # (preview + final, preprocess_hams after run_bem, benchmarks) reuse it
    key = (
        np.asarray(panels, float).tobytes(), depth, pa.n,
        np.asarray(lid_panels, float).tobytes() if has_lid else b"",
    )
    cached = _rankine_cache.get(key)
    if cached is None:
        S0f, K0f = _rankine(pa, depth=depth, lid_mask=lid_mask)
        # cache in f32 — the solver consumes f32 anyway, and it doubles
        # how many meshes fit the byte budget
        cached = (S0f.astype(np.float32), K0f.astype(np.float32))
        new_bytes = cached[0].nbytes + cached[1].nbytes
        if new_bytes <= _RANKINE_CACHE_BYTES:  # else: too big, don't evict
            held = sum(v[0].nbytes + v[1].nbytes
                       for v in _rankine_cache.values())
            while _rankine_cache and held + new_bytes > _RANKINE_CACHE_BYTES:
                old = _rankine_cache.pop(next(iter(_rankine_cache)))
                held -= old[0].nbytes + old[1].nbytes
            _rankine_cache[key] = cached
    S0, K0 = cached
    # the per-frequency wave term is smooth: "centroid" swaps only its
    # quadrature for a ~2.4x faster assembly loop
    if quad == "gauss":
        pa_wave = pa
    else:
        pa_wave = panel_arrays(panels, quad=quad)
        if has_lid:
            pa_wave = _concat_panel_arrays(
                pa_wave, panel_arrays(lid_panels, quad=quad))
        if real_block:
            pa_wave = pad_panel_arrays(pa_wave)
    # TPU: gather-free Chebyshev wave-term kernel; CPU: bilinear tables
    if real_block:
        tables = greens.load_cheb_tables()
    else:
        tables = tuple(greens.load_tables())
    vmodes = _radiation_normals(pa)                     # [6, N]
    # lids are rigid extensions: zero radiation normal velocity AND zero
    # weight in the pressure-force integrals (both flow through vmodes)
    vmodes[:, lid_mask] = 0.0

    if _solve_all_jit is None:
        _solve_all_jit = jax.jit(
            _solve_all, static_argnums=(12, 13, 14, 17)
        )

    from raft_tpu.utils.placement import (
        backend_devices,
        backend_sharding,
        batch_mesh,
        replicated_sharding,
    )

    # device-mesh policy: shard the frequency batch when >1 local device
    # of the backend exists and the batch fills the mesh; otherwise the
    # single-device path, unchanged.  The defensive try keeps the
    # "TPU-form solve on a CPU-only host" route (tests monkeypatch
    # backend_sharding) working: no devices found -> no sharding.
    try:
        devs = backend_devices(backend)
    except RuntimeError:
        devs = []
    n_dev = len(devs) if n_devices is None else max(
        1, min(int(n_devices), len(devs)))
    nw_req = len(np.atleast_1d(np.asarray(omegas, float)))
    nb_req = len(np.atleast_1d(np.asarray(betas, float)))
    shard_mode = None
    if not streamed and n_dev > 1:
        if nw_req >= n_dev:
            shard_mode = "freq"
        elif nb_req > 1 and nw_req * nb_req >= n_dev:
            shard_mode = "freqbeta"

    if shard_mode:
        dev_mesh = batch_mesh(axis="freq", devices=devs[:n_dev])
        rep = replicated_sharding(dev_mesh)
        put = lambda a: jax.device_put(    # noqa: E731
            np.asarray(a, np.float32), rep)
    else:
        put = lambda a: jax.device_put(    # noqa: E731
            np.asarray(a, np.float32), backend_sharding(backend))
    tables = jax.tree.map(put, tables)

    # frequency-independent arrays transfer ONCE (S0/K0 alone are ~94 MB
    # each at N=4858 — re-putting them per chunk would multiply tunnel
    # traffic by the chunk count)
    static_pre = (
        put(betas), put(pa.cen), put(pa.nrm), put(pa.area),
        put(pa_wave.qpts), put(pa_wave.qwts), put(S0), put(K0),
        put(vmodes), put(jump), tables, float(g), float(rho), real_block,
        put(depth if np.isfinite(depth) else 0.0), put(kmax_geom),
        bool(np.isfinite(depth)),
    )

    def call_args(om):
        return (put(om),) + static_pre

    if streamed:
        A, B, Xr, Xi, ndisp = _run_streamed(
            omegas, static_pre, put, pa.n)
        out = {
            "w": np.asarray(omegas, float),
            "A": np.asarray(A, np.float64),
            "B": np.asarray(B, np.float64),
            "X": np.asarray(Xr, np.float64) + 1j * np.asarray(
                Xi, np.float64),
            "betas": np.asarray(betas, float),
            "npanels": n_real,
            "npanels_solved": pa.n,
            "streamed": True,
            "stream_bands": ndisp["bands"],
            "stream_solve_dispatches": ndisp["solve_stages"],
        }
        return out

    if shard_mode:
        A, B, Xr, Xi, flops = _run_sharded(
            omegas, np.atleast_1d(np.asarray(betas, float)), static_pre,
            dev_mesh, shard_mode, pa.n, report_cost)
        out = {
            "w": np.asarray(omegas, float),
            "A": np.asarray(A, np.float64),
            "B": np.asarray(B, np.float64),
            "X": np.asarray(Xr, np.float64) + 1j * np.asarray(
                Xi, np.float64),
            "betas": np.asarray(betas, float),
            "npanels": n_real,
            "npanels_solved": pa.n,
            "sharded": shard_mode,
            "n_devices": n_dev,
        }
        if flops is not None:
            out["flops"] = flops
        return out

    # Large TPU meshes: keep each dispatch under the tunnel worker's
    # execution watchdog.  At N=4864 one frequency runs ~10.6 s hot
    # on-device; an 8-frequency lax.map in a single dispatch (~85 s)
    # reproducibly crashes the axon worker where 6 survives, with ample
    # HBM headroom — the wall is dispatch TIME, not memory.  Host-side
    # frequency chunks reuse ONE compiled executable (the last chunk is
    # padded by repeating its final frequency so every dispatch keeps the
    # same shape) at ~0.1 s dispatch overhead per chunk — negligible
    # against the ~10 s/frequency compute.
    # gate on ESTIMATED TOTAL DISPATCH TIME, not mesh size alone: many
    # frequencies on a moderate mesh run over the watchdog just as surely
    # as few frequencies on a huge one
    chunk = len(omegas)
    if real_block:
        per_freq_s = max((pa.n / 4864.0) ** 2 * 11.0, 1e-3)
        if len(omegas) * per_freq_s > 45.0:
            chunk = max(1, min(len(omegas), int(45.0 / per_freq_s)))
    if chunk >= len(omegas):
        A, B, Xr, Xi = _solve_all_jit(*call_args(omegas))
    else:
        nw_all = len(omegas)
        parts = []
        for i in range(0, nw_all, chunk):
            om = omegas[i:i + chunk]
            if len(om) < chunk:        # repeat-pad: same compiled shape
                om = np.concatenate([om, np.full(chunk - len(om), om[-1])])
            parts.append(_solve_all_jit(*call_args(om)))
        A, B, Xr, Xi = (
            np.concatenate([np.asarray(p[j]) for p in parts])[:nw_all]
            for j in range(4)
        )
    out = {
        "w": np.asarray(omegas, float),
        "A": np.asarray(A, np.float64),
        "B": np.asarray(B, np.float64),
        "X": np.asarray(Xr, np.float64) + 1j * np.asarray(Xi, np.float64),
        "betas": np.asarray(betas, float),
        "npanels": n_real,
        "npanels_solved": pa.n,   # incl. inert bucket padding on TPU
    }
    if report_cost:
        from raft_tpu.utils.profiling import compiled_flops

        # lower the shape that actually executed (the per-chunk shape when
        # chunking; flops scale linearly in frequencies either way)
        nrep = min(chunk, len(omegas))
        out["flops"] = compiled_flops(
            _solve_all_jit, call_args(omegas[:nrep])
        ) * (len(omegas) / nrep)
    return out


# per-dispatch time budget for one streamed assembly band (under the
# ~60-70 s tunnel watchdog with margin); tests shrink it to force
# multi-band execution on small meshes
STREAM_BAND_BUDGET_S = 28.0


# measured blocked-Gauss-Jordan throughput used to budget the staged
# solve dispatches (v5e: >= 12 TFLOP/s of f32 matmul at 2N = 6656)
_GJ_FLOPS_PER_S = 12e12


def _run_streamed(omegas, static_pre, put, n, band_budget_s=None):
    """Out-of-core execution for meshes past the single-dispatch ceiling
    (VERDICT r4 #8): per frequency, the wave-term influence assembly is
    split into D row bands, each assembled in its OWN dispatch (device
    arrays persist in HBM between dispatches, so nothing crosses the
    tunnel), then the solve runs as one system-assembly dispatch plus
    the blocked Gauss-Jordan elimination split into >= 2 row-band stage
    dispatches (the 2(2N)^3-flop elimination grows past the watchdog
    well before the ~16k-panel HBM ceiling; each stage runs a bounded
    slice of block steps through ONE compiled executable with traced
    bounds), and a final pressure-integral dispatch.  Each dispatch
    stays under the tunnel watchdog; HAMS-style arbitrary mesh sizes are
    then bounded by HBM (~16k panels on 16 GB), not dispatch time.

    Returns (A, B, Xr, Xi, ndisp) with ndisp the per-frequency dispatch
    counts {"bands": D, "solve_stages": S}."""
    import jax

    (betas_d, x_d, nrm_d, area_d, y_d, wq_d, S0_d, K0_d, vmodes_d,
     jump_d, tables_d, g, rho, _real_block, depth_d, kmax_d,
     finite) = static_pre

    if band_budget_s is None:
        band_budget_s = STREAM_BAND_BUDGET_S
    per_freq_s = (n / 4864.0) ** 2 * 11.0
    units = n // 256
    D = min(units, max(1, int(np.ceil(per_freq_s / band_budget_s))))
    while units % D:                  # bands must tile the padded mesh
        D += 1
    rows = n // D

    # solve-stage banding: the elimination has (2N)/512 block steps;
    # group them into >= 2 dispatches sized by the same per-dispatch
    # budget as the assembly bands
    nblk_total = (2 * n) // 512
    t_gj = 2.0 * (2.0 * n) ** 3 / _GJ_FLOPS_PER_S
    n_stages = min(nblk_total,
                   max(2, int(np.ceil(t_gj / band_budget_s))))
    steps = [nblk_total // n_stages + (1 if s < nblk_total % n_stages
                                       else 0) for s in range(n_stages)]

    band_fn, system_fn, stage_fn, finish_fn = _streamed_fns(
        D, rows, n, finite, g, rho)

    A, B, Xr, Xi = [], [], [], []
    for om in np.atleast_1d(np.asarray(omegas, float)):
        om_d = put(om)
        bands = []
        for b in range(D):
            sl = slice(b * rows, (b + 1) * rows)
            parts = band_fn(om_d, x_d[sl], nrm_d[sl], y_d, wq_d,
                            tables_d, depth_d, kmax_d)
            # block per band: one watchdog window per dispatch
            jax.block_until_ready(parts)
            bands.append(parts)
        flat = [p[j] for j in range(4) for p in bands]
        A2, b2, Sf_r, Sf_i, phiI_r, phiI_i = system_fn(
            om_d, betas_d, x_d, nrm_d, S0_d, K0_d, vmodes_d, jump_d,
            depth_d, *flat)
        kb0 = 0
        for ns in steps:
            # python-int bounds trace as scalars of one consistent dtype
            # (jit caches on dtype/shape, so every stage length reuses
            # the first compiled executable per distinct length)
            A2, b2 = stage_fn(A2, b2, np.int64(kb0), np.int64(ns))
            jax.block_until_ready(b2)
            kb0 += ns
        res = finish_fn(om_d, b2, Sf_r, Sf_i, phiI_r, phiI_i, area_d,
                        vmodes_d)
        jax.block_until_ready(res)
        A.append(np.asarray(res[0]))
        B.append(np.asarray(res[1]))
        Xr.append(np.asarray(res[2]))
        Xi.append(np.asarray(res[3]))
    ndisp = {"bands": D, "solve_stages": n_stages}
    return (np.stack(A), np.stack(B), np.stack(Xr), np.stack(Xi), ndisp)


def max_resolved_omega(panel_size, g=9.81, panels_per_wavelength=7.0):
    """Highest frequency the mesh resolves: wave length 2 pi g / w^2 must
    span >= panels_per_wavelength panels (validated against the OC3/WAMIT
    comparison in tests: accuracy collapses once nu * panel_size ~ 1)."""
    return float(np.sqrt(2.0 * np.pi * g / (panels_per_wavelength * panel_size)))


def coeffs_from_members(members, omegas, headings_deg=(0.0,), rho=1025.0,
                        g=9.81, dz_max=0.0, da_max=0.0, panels=None,
                        quad="gauss", backend=None, depth=np.inf,
                        irr_removal=True, n_devices=None):
    """Mesh all potMod members, run the native solver, return a HydroCoeffs
    set (same container the WAMIT-file import path produces, so the Model
    pipeline is agnostic to where coefficients came from).

    A pre-built panel array can be passed to skip the meshing step.

    irr_removal : generate interior free-surface lids from the mesh's
        waterline loops and solve the extended system (irregular-frequency
        removal, on by default — the HAMS If_remove_irr_freq equivalent).

    Frequencies above what the mesh resolves are clamped to the solve cap
    and back-filled with the cap value for A (B, X decay there anyway) —
    mirroring the reference's interp-with-clamp semantics
    (reference raft/raft_fowt.py:398-401).
    """
    from raft_tpu.bem import HydroCoeffs
    from raft_tpu.mesh import (
        lid_panels_from_mesh,
        mesh_platform,
        panel_geometry,
    )

    omegas = np.sort(np.asarray(omegas, float))
    if panels is None:
        panels = mesh_platform(members, dz_max=dz_max, da_max=da_max)
    if len(panels) == 0:
        raise ValueError("no potMod members to mesh for the BEM solve")
    lids = lid_panels_from_mesh(panels) if irr_removal else None
    size = float(np.sqrt(np.median(panel_geometry(panels)[2])))
    w_cap = max_resolved_omega(size, g=g)
    w_solve = np.unique(np.minimum(omegas, w_cap))
    betas = np.deg2rad(np.asarray(headings_deg, float))
    out = solve_bem(panels, w_solve, betas=betas, rho=rho, g=g, quad=quad,
                    backend=backend, depth=depth, lid_panels=lids,
                    n_devices=n_devices)
    return HydroCoeffs(
        w=out["w"], A=out["A"], B=out["B"],
        headings=np.asarray(headings_deg, float), X=out["X"],
        solver_info={
            k: out[k] for k in (
                "npanels", "npanels_solved", "sharded", "n_devices",
                "streamed", "stream_bands", "stream_solve_dispatches",
            ) if k in out
        },
    )
