"""HTTP/1.1 JSON transport over the serve engine — stdlib only.

``serve_http(backend)`` wraps anything with the engine's front surface
(``submit``/``probe``/``snapshot``/``shutdown`` — both ``Engine`` and
``router.Router`` qualify) in a threaded ``http.server`` front end:

* ``POST /v1/solve`` — body is a wire request document
  (serve/wire.py).  The response is chunked NDJSON: an ``accepted``
  line with the assigned rid as soon as admission control takes the
  request, then exactly one terminal result line (the engine's
  exactly-once terminal-status guarantee, PR 5).  The HTTP status is
  committed at the accepted chunk (200); the terminal status rides in
  the body.  ``?stream=0`` buffers instead and maps the terminal
  status to an HTTP code (wire.HTTP_STATUS).
* ``POST /v1/sweep`` — body is a sweep request document
  (``wire.parse_sweep_request``).  Always streamed NDJSON: ``accepted``,
  one ``sweep_chunk`` line per finished chunk (PR 2 checkpoint schema),
  then exactly one terminal ``sweep_result`` line (see ``_post_sweep``).
  A router backend may answer either route straight from its own
  result-cache tier (PR 18): the wire shape is unchanged — a solo hit
  is a normal terminal line with ``replica`` absent, a fully-cached
  sweep streams chunk lines with ``mode: "cached"`` — so clients never
  see where the bits came from, only that they are the exact bits a
  forwarded solve would have produced.
* ``POST /v1/grad`` — body is a grad request document
  (``wire.parse_grad_request``: design + objective spec,
  docs/differentiation.md).  Always a single buffered JSON
  ``grad_result`` document — the payload is a handful of f64 scalars,
  so there is nothing to stream; the terminal status maps to an HTTP
  code exactly like a ``?stream=0`` solve.
* ``GET /healthz`` — liveness: 200 whenever the process can answer.
* ``GET /readyz`` — readiness from ``backend.probe()`` (the cheap
  lock-free gauge): 503 while draining, stopped, or shedding
  (queue above high-water), or when every circuit breaker is open.
* ``GET /statz`` — full ``snapshot()`` as JSON.

Drain (``HttpTransport.drain``) reuses the engine's terminal-status
guarantee for the SIGTERM story: stop admitting (503), shut the
backend down — which resolves every in-flight handle with a terminal
status and thereby unblocks every handler thread mid-wait — then wait
for the active handlers to flush their terminal chunk before closing
the listener socket.  Every accepted rid gets its terminal line before
its socket closes (pinned by the router SIGTERM subprocess test).

Fault injection: the ``conn_drop`` chaos fault (chaos.py) closes the
client connection after the accepted chunk and before the terminal
line — the client must surface ``ConnectionDropped`` while the engine
handle still resolves internally.

No fixed ports anywhere: ``port=0`` binds an OS-assigned port which is
read back from the listening socket (``HttpTransport.port``); the repo
lint tests/test_no_fixed_ports.py keeps it that way.
"""

import http.client
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl

from raft_tpu.chaos import get_injector
from raft_tpu.resilience import TransientError
from raft_tpu.serve import wire
from raft_tpu.utils.profiling import logger

# Upper bound on one handler's wait for a terminal result.  The engine
# resolves every handle eventually (terminal-status guarantee), but a
# handler thread must not hold a socket forever if a solve outlives any
# sane client; past this the transport emits a terminal "failed" line
# itself (the late engine resolution is then counted by the engine as a
# late_resolution, not lost).
DEFAULT_RESULT_WAIT_S = 600.0

MAX_BODY_BYTES = 64 * 1024 * 1024


class ConnectionDropped(TransientError):
    """The server closed the stream before the terminal result line —
    retry-eligible (the solve is pure; re-submitting cannot double
    apply)."""


class WireChecksumError(ConnectionDropped):
    """A response payload failed its embedded checksum (serve/wire.py)
    — in-flight corruption.  Subclasses ConnectionDropped so the router
    retries the request instead of ever decoding the wrong bits."""


def _flip_first_leaf(value):
    """First numeric leaf of a nested list/dict flipped to a different
    value; everything else untouched (copy-on-write along the path)."""
    if isinstance(value, list) and value:
        return [_flip_first_leaf(value[0])] + value[1:]
    if isinstance(value, dict) and value:
        key = next(iter(value))
        return {**value, key: _flip_first_leaf(value[key])}
    if isinstance(value, (int, float)):
        return -float(value) - 1.0
    return value


def _corrupt_payload(doc):
    """The wire_corrupt chaos mutation: one payload value of a decoded
    response flipped.  Deliberately a STILL-VALID-JSON corruption — the
    only kind a payload checksum is needed for; garbage that breaks the
    JSON parse already fails loudly as ConnectionDropped."""
    out = dict(doc)
    for key in ("Xi_re", "Xi_r", "std", "gradient", "value", "theta"):
        if key in out:
            out[key] = _flip_first_leaf(out[key])
            return out
    return out


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "raft-tpu-serve"

    def log_message(self, fmt, *args):  # stdout belongs to the CLI lines
        logger.debug("http: " + fmt % args)

    # -- plumbing ---------------------------------------------------

    @property
    def transport(self):
        return self.server.transport

    def _send_json(self, code, doc):
        payload = (wire.dumps(doc) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _send_text(self, code, text,
                   content_type="text/plain; version=0.0.4"):
        payload = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _chunk(self, doc):
        data = (wire.dumps(doc) + "\n").encode()
        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    def _end_chunks(self):
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()

    # -- routes -----------------------------------------------------

    def do_GET(self):
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            return self._send_json(200, {"status": "alive",
                                         "uptime_s": round(
                                             self.transport.uptime_s, 3)})
        if path == "/readyz":
            ready, probe = self.transport.readiness()
            return self._send_json(200 if ready else 503, probe)
        if path == "/statz":
            doc = self.transport.backend.snapshot()
            registry = getattr(self.transport.backend, "metrics", None)
            if registry is not None:
                doc = dict(doc)
                doc["metrics"] = registry.to_doc()
            return self._send_json(200, doc)
        if path == "/metricz":
            # Prometheus text exposition (docs/observability.md)
            registry = getattr(self.transport.backend, "metrics", None)
            if registry is None:
                return self._send_json(
                    404, {"error": "backend has no metrics registry"})
            return self._send_text(200, registry.render_prometheus())
        if path == "/tracez":
            ring = getattr(self.transport.backend, "trace_ring", None)
            if ring is None:
                return self._send_json(
                    404, {"error": "backend has no trace ring"})
            params = dict(parse_qsl(query))
            try:
                limit = int(params["limit"]) if "limit" in params \
                    else None
            except ValueError:
                return self._send_json(
                    400, {"error": f"bad limit {params['limit']!r}"})
            spans = ring.spans(limit=limit,
                               trace_id=params.get("trace_id"))
            doc = {"spans": spans, "n_spans": len(spans)}
            doc.update(ring.snapshot())
            return self._send_json(200, doc)
        if path == "/versionz":
            # the attach handshake surface (Router.attach_remote): the
            # FULL flag surface of serve/cache.py — code_version sha,
            # jax version, x64, env knobs, device topology — so a peer
            # can apply the stale-flag discipline to a live replica
            # before routing any work to it (docs/serving.md)
            from raft_tpu.serve.cache import (ENV_FLAG_SURFACE,
                                              current_flags)
            return self._send_json(200, {
                "wire_version": wire.WIRE_VERSION,
                "flags": current_flags(),
                "env_flag_surface": dict(ENV_FLAG_SURFACE),
                "uptime_s": round(self.transport.uptime_s, 3)})
        return self._send_json(404, {"error": f"no route {path}"})

    def do_POST(self):
        path, _, query = self.path.partition("?")
        if path == "/v1/sweep":
            return self._post_sweep()
        if path == "/v1/grad":
            return self._post_grad()
        if path == "/profilez":
            return self._post_profilez()
        if path == "/v1/cache/preload":
            return self._post_cache_preload()
        if path != "/v1/solve":
            return self._send_json(404, {"error": f"no route {path}"})
        if self.transport.draining:
            return self._send_json(503, {"error": "draining"})
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > MAX_BODY_BYTES:
                return self._send_json(413, {"error": "body too large"})
            doc = json.loads(self.rfile.read(length))
            design, cases, deadline_s, want_xi = wire.parse_request(doc)
            if isinstance(design, str):
                from raft_tpu.io.schema import load_design
                design = load_design(design)
        except wire.WireError as e:
            return self._send_json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — bad body, keep serving
            return self._send_json(
                400, {"error": f"{type(e).__name__}: {e}"})

        stream = "stream=0" not in query
        try:
            handle = self.transport.backend.submit(
                design, cases=cases, deadline_s=deadline_s,
                trace=wire.parse_trace(doc))
        except RuntimeError as e:           # backend already stopped
            return self._send_json(503, {"error": str(e)})

        self.transport.note_accept(handle.rid)
        try:
            if stream:
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                self._chunk({"event": "accepted", "rid": handle.rid})
            inj = get_injector()
            if inj is not None and inj.should("conn_drop",
                                              handle.rid) is not None:
                # chaos: drop the client mid-stream.  The engine handle
                # is deliberately left to resolve on its own.
                logger.warning("chaos conn_drop: closing rid=%d stream",
                               handle.rid)
                self.close_connection = True
                self.connection.close()
                return
            doc = self.transport.wait_terminal(handle)
            if stream:
                self._chunk(doc)
                self._end_chunks()
            else:
                self._send_json(wire.HTTP_STATUS.get(doc["status"], 500),
                                doc)
        except (BrokenPipeError, ConnectionResetError):
            # client went away mid-wait; the engine still resolves the
            # handle (terminal-status guarantee is server-side).
            self.close_connection = True

    def _post_profilez(self):
        """``POST /profilez`` — arm a one-shot profiler capture around
        the backend's next dispatch window (docs/observability.md).
        Body is optional JSON ``{"log_dir": ...}``; with no body the
        backend falls back to ``RAFT_TPU_PROFILE_DIR``."""
        backend = self.transport.backend
        capture = getattr(backend, "capture_profile", None)
        if capture is None:
            return self._send_json(
                404, {"error": "backend has no profiler hook"})
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > MAX_BODY_BYTES:
                return self._send_json(413, {"error": "body too large"})
            body = json.loads(self.rfile.read(length)) if length else {}
        except Exception as e:  # noqa: BLE001 — bad body, keep serving
            return self._send_json(
                400, {"error": f"{type(e).__name__}: {e}"})
        doc = capture(log_dir=body.get("log_dir"))
        code = 200 if doc.get("armed", True) else 409
        return self._send_json(code, doc)

    def _post_cache_preload(self):
        """``POST /v1/cache/preload`` — one chunk of a shared-nothing
        warm transfer (docs/serving.md): a checksummed result-cache
        entry's raw npz bytes, the warm-handoff manifest, or the
        warm-up bucket manifest.  Delegates to ``backend.preload_wire``
        (the Engine); a torn or corrupt chunk is refused-and-deleted
        per the result_cache convention, never served."""
        if self.transport.draining:
            return self._send_json(503, {"error": "draining"})
        preload = getattr(self.transport.backend, "preload_wire", None)
        if preload is None:
            return self._send_json(
                404, {"error": "backend has no wire-preload surface"})
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > MAX_BODY_BYTES:
                return self._send_json(413, {"error": "body too large"})
            body = json.loads(self.rfile.read(length)) if length else {}
        except Exception as e:  # noqa: BLE001 — bad body, keep serving
            return self._send_json(
                400, {"error": f"{type(e).__name__}: {e}"})
        try:
            doc = preload(body)
        except ValueError as e:
            return self._send_json(400, {"error": str(e)})
        code = 200 if not doc.get("error") else 409
        return self._send_json(code, doc)

    def _post_grad(self):
        """``POST /v1/grad`` — evaluate one objective + exact adjoint
        gradient (engine.submit_grad).  Buffered single JSON document:
        the answer is a handful of f64 scalars whose json repr
        round-trips bit-exactly, so the served bits equal the in-process
        ``design_value_and_grad`` answer (pinned in tests/test_grad.py).
        """
        if self.transport.draining:
            return self._send_json(503, {"error": "draining"})
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > MAX_BODY_BYTES:
                return self._send_json(413, {"error": "body too large"})
            doc = json.loads(self.rfile.read(length))
            design, objective = wire.parse_grad_request(doc)
            if isinstance(design, str):
                from raft_tpu.io.schema import load_design
                design = load_design(design)
        except wire.WireError as e:
            return self._send_json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — bad body, keep serving
            return self._send_json(
                400, {"error": f"{type(e).__name__}: {e}"})
        try:
            handle = self.transport.backend.submit_grad(
                design, objective, trace=wire.parse_trace(doc))
        except RuntimeError as e:           # backend already stopped
            return self._send_json(503, {"error": str(e)})
        except ValueError as e:             # objective refused upstream
            return self._send_json(400, {"error": str(e)})
        self.transport.note_accept(handle.rid)
        with self.transport._lock:
            self.transport._active += 1
        try:
            wait = self.transport.result_wait_s
            try:
                res = handle.result(timeout=wait)
                out = wire.grad_result_doc(res)
            except TimeoutError:
                out = {"event": "grad_result", "rid": handle.rid,
                       "status": "failed",
                       "error": f"transport result wait exceeded "
                                f"{wait:.0f}s"}
            self._send_json(wire.HTTP_STATUS.get(out["status"], 500),
                            out)
        except (BrokenPipeError, ConnectionResetError):
            # client went away mid-wait; the engine still resolves the
            # handle (terminal-status guarantee is server-side).
            self.close_connection = True
        finally:
            with self.transport._idle:
                self.transport._active -= 1
                self.transport._idle.notify_all()

    def _post_sweep(self):
        """``POST /v1/sweep`` — always streamed NDJSON: an ``accepted``
        line (rid, n_designs, n_chunks) as soon as admission takes the
        sweep, one ``sweep_chunk`` line per chunk as the continuous
        batcher finishes it (the PR 2 checkpoint schema slices), then
        exactly one terminal ``sweep_result`` line — WITHOUT the
        aggregate arrays (the chunks carried them;
        wire.sweep_result_from_doc reassembles client-side)."""
        if self.transport.draining:
            return self._send_json(503, {"error": "draining"})
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > MAX_BODY_BYTES:
                return self._send_json(413, {"error": "body too large"})
            doc = json.loads(self.rfile.read(length))
            designs, cases, chunk = wire.parse_sweep_request(doc)
            if any(isinstance(d, str) for d in designs):
                from raft_tpu.io.schema import load_design
                designs = [load_design(d) if isinstance(d, str) else d
                           for d in designs]
        except wire.WireError as e:
            return self._send_json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — bad body, keep serving
            return self._send_json(
                400, {"error": f"{type(e).__name__}: {e}"})
        try:
            handle = self.transport.backend.submit_sweep(
                designs, cases=cases, chunk=chunk,
                trace=wire.parse_trace(doc))
        except (RuntimeError, ValueError) as e:   # stopped / empty sweep
            return self._send_json(503, {"error": str(e)})
        self.transport.note_accept(handle.rid)
        with self.transport._lock:
            self.transport._active += 1
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self._chunk({"event": "accepted", "rid": handle.rid,
                         "n_designs": handle.n_designs,
                         "n_chunks": handle.n_chunks})
            wait = self.transport.result_wait_s
            try:
                for ch in handle.chunks(timeout=wait):
                    self._chunk(wire.sweep_chunk_doc(ch))
                res = handle.result(timeout=wait)
                self._chunk(wire.sweep_result_doc(res))
            except (queue.Empty, TimeoutError):
                self._chunk({"event": "sweep_result", "rid": handle.rid,
                             "status": "failed",
                             "error": f"transport result wait exceeded "
                                      f"{wait:.0f}s"})
            self._end_chunks()
        except (BrokenPipeError, ConnectionResetError):
            # client went away mid-stream; the engine still resolves the
            # handle (terminal-status guarantee is server-side).
            self.close_connection = True
        finally:
            with self.transport._idle:
                self.transport._active -= 1
                self.transport._idle.notify_all()


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class HttpTransport:
    """Owns the listener socket + serve thread; see module docstring."""

    def __init__(self, backend, host="127.0.0.1", port=0,
                 result_wait_s=DEFAULT_RESULT_WAIT_S):
        self.backend = backend
        self.result_wait_s = result_wait_s
        self.draining = False
        self._t0 = time.monotonic()
        self._active = 0                  # solve handlers mid-request
        self._accepted = 0
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._server = _Server((host, port), _Handler)
        self._server.transport = self
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="raft-http",
            daemon=True)
        self._thread.start()
        logger.info("http transport listening on %s:%d", self.host,
                    self.port)

    @property
    def uptime_s(self):
        return time.monotonic() - self._t0

    def note_accept(self, rid):
        with self._lock:
            self._accepted += 1

    def readiness(self):
        probe = dict(self.backend.probe())
        probe["draining"] = self.draining
        probe["accepted"] = self._accepted
        breakers = probe.get("breaker_states") or {}
        all_open = bool(breakers) and probe.get("breakers_open", 0) >= len(
            breakers)
        ready = (probe.get("accepting", False) and not self.draining
                 and not all_open)
        probe["ready"] = ready
        return ready, probe

    def wait_terminal(self, handle):
        """Block a handler thread for the terminal result document."""
        with self._lock:
            self._active += 1
        try:
            try:
                res = handle.result(timeout=self.result_wait_s)
            except TimeoutError:
                return {"event": "result", "rid": handle.rid,
                        "status": "failed",
                        "error": f"transport result wait exceeded "
                                 f"{self.result_wait_s:.0f}s"}
            return wire.result_doc(res, include_xi=True)
        finally:
            with self._idle:
                self._active -= 1
                self._idle.notify_all()

    def drain(self, drain_queue=False, timeout=30.0):
        """Graceful shutdown: refuse new work, resolve ALL in-flight
        requests to terminal lines, then close the listener."""
        self.draining = True
        # Resolves every outstanding handle (terminal-status guarantee),
        # which unblocks every handler sitting in wait_terminal().
        self.backend.shutdown(wait=True, drain=drain_queue,
                              timeout=timeout)
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._active and time.monotonic() < deadline:
                self._idle.wait(0.1)
            leftover = self._active
        if leftover:  # pragma: no cover — handlers always unblock above
            logger.warning("drain: %d handler(s) still active at close",
                           leftover)
        self.close()
        return {"accepted": self._accepted, "active_at_close": leftover}

    def close(self):
        self._server.shutdown()
        self._server.server_close()


def serve_http(backend, host="127.0.0.1", port=0, **kw):
    """Start an HTTP front end on ``backend``; returns the transport
    (read ``.port`` back — port 0 requests an OS-assigned one)."""
    return HttpTransport(backend, host=host, port=port, **kw)


class WireClient:
    """Minimal stdlib HTTP client for the wire protocol (used by the
    router's forwarding tier, the tests and the bench).

    ``solve`` returns the terminal result document; any transport-level
    failure (refused connection, dropped stream, premature EOF) raises
    ``ConnectionDropped`` — a TransientError, so the router's retry
    policy may re-attempt on another replica."""

    def __init__(self, host, port, timeout=DEFAULT_RESULT_WAIT_S):
        self.host, self.port, self.timeout = host, port, timeout

    def _conn(self, timeout=None):
        return http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.timeout if timeout is None else timeout)

    def _chaos_partition(self):
        """net_partition chaos hook: drop this endpoint's /v1/* POST
        traffic (GET health probes still answer) — the gray failure a
        partitioned host produces.  Injected at the wire client because
        the chaos env is deliberately stripped from replica processes
        (spawn_replica); ``@PORT`` in the spec targets one endpoint."""
        inj = get_injector()
        if inj is not None and inj.should("net_partition",
                                          self.port) is not None:
            raise ConnectionDropped(
                f"chaos net_partition: {self.host}:{self.port} dropped "
                f"the /v1/* request (health probes still answer)")

    def _verify(self, doc):
        """Refuse a response document whose embedded payload checksum
        does not match its payload (wire.checksum_mismatch): raises
        WireChecksumError — a ConnectionDropped — so the caller retries
        elsewhere instead of decoding corrupted Xi bits.  The
        wire_corrupt chaos mutation lands here, BEFORE verification,
        so the test proves detection rather than assuming it."""
        inj = get_injector()
        if inj is not None and inj.should("wire_corrupt",
                                          self.port) is not None:
            logger.warning(
                "chaos wire_corrupt: flipping payload bits of %s "
                "rid=%s from %s:%d", doc.get("event"), doc.get("rid"),
                self.host, self.port)
            doc = _corrupt_payload(doc)
        reason = wire.checksum_mismatch(doc)
        if reason:
            raise WireChecksumError(f"{self.host}:{self.port}: {reason}")
        return doc

    def get(self, path, timeout=10.0):
        """GET a JSON endpoint -> (status_code, doc)."""
        conn = self._conn(timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read())
        finally:
            conn.close()

    def get_text(self, path, timeout=10.0):
        """GET a text endpoint (``/metricz``) -> (status_code, str)."""
        conn = self._conn(timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read().decode()
        finally:
            conn.close()

    def post_json(self, path, doc, timeout=30.0):
        """POST a small JSON document (``/profilez``,
        ``/v1/cache/preload``) -> response doc."""
        self._chaos_partition()
        body = wire.dumps(doc or {}).encode()
        conn = self._conn(timeout)
        try:
            try:
                conn.request("POST", path, body=body, headers={
                    "Content-Type": "application/json"})
                resp = conn.getresponse()
                return json.loads(resp.read())
            except (ConnectionError, http.client.HTTPException,
                    TimeoutError, OSError, ValueError) as e:
                raise ConnectionDropped(
                    f"{self.host}:{self.port}: "
                    f"{type(e).__name__}: {e}") from e
        finally:
            conn.close()

    def solve(self, doc, on_sent=None, slow_s=None):
        """POST a request document, stream the response, return the
        terminal result document.  ``on_sent`` fires after the request
        bytes are on the wire (the replica_kill chaos hook point).
        ``slow_s`` is the replica_slow chaos hook point: stall that many
        seconds after the request is on the wire, then give up on the
        reply exactly as a socket timeout would — the raised
        ``ConnectionDropped`` sends the router to the next ring replica
        (the solve is pure, so the abandoned replica's late answer is
        simply discarded)."""
        self._chaos_partition()
        body = wire.dumps(doc).encode()
        conn = self._conn()
        try:
            try:
                conn.request("POST", "/v1/solve", body=body, headers={
                    "Content-Type": "application/json"})
                if on_sent is not None:
                    on_sent()
                if slow_s is not None:
                    time.sleep(float(slow_s))
                    raise ConnectionDropped(
                        f"chaos replica_slow: gave up on "
                        f"{self.host}:{self.port} after {slow_s:.3f}s")
                resp = conn.getresponse()
                if resp.status != 200:
                    err = {}
                    try:
                        err = json.loads(resp.read())
                    except (ValueError, OSError,
                            http.client.HTTPException):
                        err = {"error": f"HTTP {resp.status} "
                                        f"(unparseable error body)"}
                    if resp.status == 503:
                        # refused before admission — the drain gate, or
                        # submit() raising on an engine that finished
                        # shutting down between the gate check and the
                        # admission call (the retirement-window race).
                        # Either way the request was never served, so it
                        # is safe to re-attempt elsewhere.
                        raise ConnectionDropped(
                            f"{self.host}:{self.port} is draining; "
                            f"request refused before admission "
                            f"({err.get('error', 'unavailable')})")
                    return {"event": "result", "rid": err.get("rid", -1),
                            "status": err.get("status", "failed"),
                            "http_status": resp.status,
                            "error": err.get("error",
                                             f"HTTP {resp.status}")}
                terminal = None
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    event = json.loads(line)
                    if event.get("event") == "result":
                        terminal = event
                if terminal is None:
                    raise ConnectionDropped(
                        f"stream from {self.host}:{self.port} ended "
                        f"before a terminal result line")
                return self._verify(terminal)
            except (ConnectionError, http.client.HTTPException,
                    TimeoutError, OSError) as e:
                raise ConnectionDropped(
                    f"{self.host}:{self.port}: "
                    f"{type(e).__name__}: {e}") from e
        finally:
            conn.close()

    def grad(self, doc, timeout=None):
        """POST a grad request document to ``/v1/grad``; returns the
        terminal ``grad_result`` document.  A 503 raises
        ``ConnectionDropped`` — the drain gate / retirement-window rule
        of ``solve()``: the request was refused before admission or the
        replica resolved it with ``status="shutdown"`` while retiring,
        and either way the evaluation is pure, so re-attempting on
        another replica cannot double apply."""
        self._chaos_partition()
        body = wire.dumps(doc).encode()
        conn = self._conn(timeout)
        try:
            try:
                conn.request("POST", "/v1/grad", body=body, headers={
                    "Content-Type": "application/json"})
                resp = conn.getresponse()
                raw = resp.read()
                try:
                    out = json.loads(raw)
                except ValueError:
                    out = {}
                if resp.status == 503:
                    raise ConnectionDropped(
                        f"{self.host}:{self.port} is draining; grad "
                        f"request not served "
                        f"({out.get('error', 'unavailable')})")
                if out.get("event") == "grad_result":
                    return self._verify(out)
                return {"event": "grad_result",
                        "rid": out.get("rid", -1),
                        "status": out.get("status", "failed"),
                        "http_status": resp.status,
                        "error": out.get("error",
                                         f"HTTP {resp.status}")}
            except (ConnectionError, http.client.HTTPException,
                    TimeoutError, OSError) as e:
                raise ConnectionDropped(
                    f"{self.host}:{self.port}: "
                    f"{type(e).__name__}: {e}") from e
        finally:
            conn.close()

    def sweep(self, doc, on_chunk=None, on_sent=None):
        """POST a sweep request document to ``/v1/sweep`` and stream the
        response.  Returns ``(terminal_doc, chunk_docs)`` — the raw
        terminal ``sweep_result`` line plus the decoded numpy-backed
        chunk docs (wire.sweep_chunk_from_doc), ready for
        ``wire.sweep_result_from_doc(terminal, chunks=chunk_docs)``.
        ``on_chunk`` fires per decoded chunk (streaming consumers /
        router progress forwarding); transport-level failures raise
        ``ConnectionDropped``."""
        self._chaos_partition()
        body = wire.dumps(doc).encode()
        conn = self._conn()
        try:
            try:
                conn.request("POST", "/v1/sweep", body=body, headers={
                    "Content-Type": "application/json"})
                if on_sent is not None:
                    on_sent()
                resp = conn.getresponse()
                if resp.status != 200:
                    err = {}
                    try:
                        err = json.loads(resp.read())
                    except (ValueError, OSError,
                            http.client.HTTPException):
                        err = {"error": f"HTTP {resp.status} "
                                        f"(unparseable error body)"}
                    if resp.status == 503:
                        # same retirement-window rule as solve(): a 503
                        # is always refused-before-admission, retryable
                        raise ConnectionDropped(
                            f"{self.host}:{self.port} is draining; "
                            f"sweep refused before admission "
                            f"({err.get('error', 'unavailable')})")
                    return ({"event": "sweep_result",
                             "rid": err.get("rid", -1),
                             "status": err.get("status", "failed"),
                             "http_status": resp.status,
                             "error": err.get("error",
                                              f"HTTP {resp.status}")},
                            [])
                terminal, chunks = None, []
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    event = json.loads(line)
                    kind = event.get("event")
                    if kind == "sweep_chunk":
                        ch = wire.sweep_chunk_from_doc(
                            self._verify(event))
                        chunks.append(ch)
                        if on_chunk is not None:
                            on_chunk(ch)
                    elif kind == "sweep_result":
                        terminal = event
                if terminal is None:
                    raise ConnectionDropped(
                        f"sweep stream from {self.host}:{self.port} "
                        f"ended before a terminal sweep_result line")
                return terminal, chunks
            except (ConnectionError, http.client.HTTPException,
                    TimeoutError, OSError) as e:
                raise ConnectionDropped(
                    f"{self.host}:{self.port}: "
                    f"{type(e).__name__}: {e}") from e
        finally:
            conn.close()
